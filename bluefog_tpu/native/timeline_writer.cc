// Copyright 2026. Licensed under the Apache License, Version 2.0.
//
// Chrome-trace timeline writer: the native component of the tracing
// subsystem. TPU-native counterpart of the reference's C++ timeline
// (reference common/timeline.cc: a dedicated writer thread draining a
// lock-free SPSC queue of records, timeline.h:46-76). Host-side phases
// (enqueue, dispatch, synchronize, python-level activities) are recorded
// from Python through the extern "C" API below and serialized off-thread
// so tracing never blocks the dispatch path; device-side phases come from
// jax.profiler and are merged by the Python layer.
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread \
//            -o libbluefog_timeline.so timeline_writer.cc
// Loaded from Python via ctypes (bluefog_tpu/timeline.py).

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>

namespace {

struct Record {
  long long ts_us;
  int pid;        // worker rank (chrome "process")
  long long tid;  // lane within the worker
  char ph;        // 'B' begin, 'E' end, 'X' complete, 'i' instant,
                  // 'C' counter
  long long dur_us;
  double value = 0.0;  // counter value ('C' records only)
  std::string name;
  std::string cat;
};

class TimelineWriter {
 public:
  // Static destruction must not leave a joinable thread behind (that is
  // std::terminate); Stop() is idempotent, so a forgotten
  // timeline_shutdown() degrades to a flush-at-exit instead of an abort.
  ~TimelineWriter() { Stop(); }

  bool Start(const char* path) {
    std::lock_guard<std::mutex> lk(control_mu_);
    if (file_ != nullptr) return false;
    file_ = std::fopen(path, "w");
    if (file_ == nullptr) return false;
    std::fputs("[\n", file_);
    first_ = true;
    stop_ = false;
    thread_ = std::thread(&TimelineWriter::Loop, this);
    return true;
  }

  void Add(Record&& r) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (file_ == nullptr) return;
      queue_.emplace_back(std::move(r));
    }
    cv_.notify_one();
  }

  void Stop() {
    std::lock_guard<std::mutex> lk(control_mu_);
    if (file_ == nullptr) return;
    {
      std::lock_guard<std::mutex> qlk(mu_);
      stop_ = true;
    }
    cv_.notify_one();
    thread_.join();
    std::fputs("\n]\n", file_);
    std::fclose(file_);
    file_ = nullptr;
  }

 private:
  void Loop() {
    std::deque<Record> batch;
    for (;;) {
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !queue_.empty(); });
        std::swap(batch, queue_);
        if (batch.empty() && stop_) return;
      }
      for (const Record& r : batch) Emit(r);
      std::fflush(file_);
      batch.clear();
    }
  }

  void Emit(const Record& r) {
    if (!first_) std::fputs(",\n", file_);
    first_ = false;
    // chrome://tracing JSON-array format
    std::fprintf(file_,
                 "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"%c\", "
                 "\"ts\": %lld, \"pid\": %d, \"tid\": %lld",
                 Escape(r.name).c_str(), Escape(r.cat).c_str(), r.ph,
                 r.ts_us, r.pid, r.tid);
    if (r.ph == 'X') std::fprintf(file_, ", \"dur\": %lld", r.dur_us);
    if (r.ph == 'i') std::fputs(", \"s\": \"p\"", file_);
    // counter events carry their series value in args (chrome renders
    // them as stacked area tracks)
    if (r.ph == 'C') std::fprintf(file_, ", \"args\": {\"value\": %g}", r.value);
    std::fputs("}", file_);
  }

  static std::string Escape(const std::string& s) {
    std::string out;
    out.reserve(s.size());
    for (char c : s) {
      if (c == '"' || c == '\\') out.push_back('\\');
      if (static_cast<unsigned char>(c) >= 0x20) out.push_back(c);
    }
    return out;
  }

  FILE* file_ = nullptr;
  bool first_ = true;
  bool stop_ = false;
  std::deque<Record> queue_;
  std::mutex mu_;
  std::mutex control_mu_;
  std::condition_variable cv_;
  std::thread thread_;
};

TimelineWriter g_writer;

long long NowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

}  // namespace

extern "C" {

int bf_timeline_start(const char* path) { return g_writer.Start(path) ? 1 : 0; }

void bf_timeline_stop() { g_writer.Stop(); }

// ph: 'B' begin / 'E' end / 'i' instant; ts measured here so callers need
// no clock plumbing.
void bf_timeline_record(const char* name, const char* category, char ph,
                        int pid, long long tid) {
  Record r;
  r.ts_us = NowUs();
  r.pid = pid;
  r.tid = tid;
  r.ph = ph;
  r.dur_us = 0;
  r.name = name == nullptr ? "" : name;
  r.cat = category == nullptr ? "" : category;
  g_writer.Add(std::move(r));
}

// Complete event with explicit duration (for phases timed in Python).
void bf_timeline_record_complete(const char* name, const char* category,
                                 int pid, long long tid, long long ts_us,
                                 long long dur_us) {
  Record r;
  r.ts_us = ts_us;
  r.pid = pid;
  r.tid = tid;
  r.ph = 'X';
  r.dur_us = dur_us;
  r.name = name == nullptr ? "" : name;
  r.cat = category == nullptr ? "" : category;
  g_writer.Add(std::move(r));
}

// Counter event (ph 'C'): a named scalar sampled now — the timeline
// exporter of the metrics registry (bluefog_tpu/metrics.py).
void bf_timeline_record_counter(const char* name, const char* category,
                                int pid, long long tid, double value) {
  Record r;
  r.ts_us = NowUs();
  r.pid = pid;
  r.tid = tid;
  r.ph = 'C';
  r.dur_us = 0;
  r.value = value;
  r.name = name == nullptr ? "" : name;
  r.cat = category == nullptr ? "" : category;
  g_writer.Add(std::move(r));
}

long long bf_timeline_now_us() { return NowUs(); }

}  // extern "C"
