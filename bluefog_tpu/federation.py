# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Hierarchical multi-pod federation: one gossip fabric across ICI and DCN.

A single pod is one uniform fabric — every plan, repair, and spectral
score in this repo assumed that until now. This module makes the fabric
TWO-LEVEL, the way multi-pod TPU deployments actually look:

- **Intra-pod (ICI)**: each pod keeps its existing gossip graph (exp2 /
  ring), compiled by the CommPlan compiler against the ICI-class
  calibrated alpha-beta, dispatched at full rate every communicating
  step.
- **Inter-pod (DCN)**: one designated **gateway** rank per pod (the
  lowest live rank — deterministic, re-elected on membership change)
  gossips with the other pods' gateways over the data-center network,
  every ``BLUEFOG_DCN_PERIOD`` communicating steps, on an aggressive
  quantized wire (``BLUEFOG_DCN_WIRE``, default int4). The inter leg is
  compiled against its OWN calibrated alpha-beta
  (``compiler.calibrate(link_class="dcn")`` / per-class pins).

The composed two-level mixing matrix is scored end-to-end by the sparse
spectral engine (:mod:`bluefog_tpu.topology.spectral`): a period-``T``
window is the matrix product of ``T`` intra-pod combines and one
gateway combine, and its per-step consensus decay rate is
``slem ** (1/T)`` — so the DCN period is *chosen* from a target
consensus rate (:func:`choose_dcn_period`), never guessed.

Pod partitioning rides the serpentine placement contract
(:mod:`bluefog_tpu.topology.placement`): pods are CONTIGUOUS virtual
rank ranges, which the serpentine walk maps to physically compact
regions, and a declared ``BLUEFOG_TORUS_DIMS`` fabric is cross-checked
so a pod boundary that slices through a torus plane warns at parse.

Nothing here activates unless ``BLUEFOG_PODS`` is set: the flat fabric
dispatches the bitwise-identical pre-federation program under the same
cache keys (pinned by tests/test_federation.py).

Environment:

- ``BLUEFOG_PODS``: the pod spec — a pod count (``"2"``), a
  ``pods x ranks`` shape (``"2x8"``), or explicit inclusive rank ranges
  (``"0-7,8-15"``). Must partition ``0..N-1`` contiguously.
- ``BLUEFOG_DCN_PERIOD``: inter-pod gossip period in communicating
  steps (default 8).
- ``BLUEFOG_DCN_WIRE``: wire tier of the DCN leg — ``int4`` (default),
  ``int8``, ``bf16``, or ``exact``. Error-feedback tiers
  (``int4_ef``/``int8_ef``) fall back to their memoryless base with a
  one-shot warning: CHOCO residual state staled across a ``T``-step DCN
  period integrates against stale iterates and is not convergent-safe.
"""

import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.logging_util import warn_once

__all__ = [
    "PODS_ENV",
    "DCN_PERIOD_ENV",
    "DCN_WIRE_ENV",
    "DEFAULT_DCN_PERIOD",
    "DEFAULT_DCN_WIRE",
    "PodLayout",
    "parse_pods",
    "enabled",
    "layout_from_env",
    "dcn_period",
    "dcn_wire",
    "elect_gateways",
    "intra_edges",
    "inter_edges",
    "federated_union_edges",
    "composed_rate",
    "choose_dcn_period",
    "simulate_consensus",
    "intra_plan",
    "inter_plan",
    "wire_summary",
    "Fabric",
    "get_fabric",
    "clear_fabric_cache",
    "FederatedFleet",
]

PODS_ENV = "BLUEFOG_PODS"
DCN_PERIOD_ENV = "BLUEFOG_DCN_PERIOD"
DCN_WIRE_ENV = "BLUEFOG_DCN_WIRE"

DEFAULT_DCN_PERIOD = 8
DEFAULT_DCN_WIRE = "int4"

# Memoryless tiers the DCN leg may ride (None = exact f32). The _ef
# tiers are deliberately absent — see dcn_wire().
_DCN_WIRES = (None, "int8", "bf16", "int4")


def enabled() -> bool:
    """True when a pod spec is declared — the single activation gate.
    Everything in this module is inert without it."""
    return bool(os.environ.get(PODS_ENV, "").strip())


# -- pod layout ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PodLayout:
    """A partition of ranks ``0..size-1`` into contiguous pods.

    ``bounds[p] = (lo, hi)`` half-open: pod ``p`` owns ranks ``lo..hi-1``.
    Contiguity is a contract, not a convenience: the serpentine device
    order (:mod:`bluefog_tpu.topology.placement`) lays consecutive
    virtual ranks onto physically adjacent chips, so a contiguous rank
    range IS a physically compact region — the thing a "pod" means.
    """

    size: int
    bounds: Tuple[Tuple[int, int], ...]
    spec: str = ""

    @property
    def n_pods(self) -> int:
        return len(self.bounds)

    def ranks(self, pod: int) -> range:
        lo, hi = self.bounds[pod]
        return range(lo, hi)

    def pod_of(self, rank: int) -> int:
        for p, (lo, hi) in enumerate(self.bounds):
            if lo <= rank < hi:
                return p
        raise ValueError(f"rank {rank} outside the {self.size}-rank layout")

    def gateways(
        self, live: Optional[Sequence[int]] = None
    ) -> Tuple[Optional[int], ...]:
        """The designated gateway per pod: the LOWEST live rank (None
        for a fully dead pod). Deterministic in the live set, so every
        survivor elects the same gateways without coordination."""
        if live is None:
            return tuple(lo for lo, _hi in self.bounds)
        live_set = set(int(r) for r in live)
        out: List[Optional[int]] = []
        for lo, hi in self.bounds:
            g = next((r for r in range(lo, hi) if r in live_set), None)
            out.append(g)
        return tuple(out)

    def to_json(self) -> dict:
        return {
            "size": self.size,
            "n_pods": self.n_pods,
            "bounds": [list(b) for b in self.bounds],
            "spec": self.spec,
        }


def parse_pods(spec: str, size: int) -> PodLayout:
    """Parse a ``BLUEFOG_PODS`` spec into a validated :class:`PodLayout`.

    Three forms: a pod count (``"2"`` — equal split, size must divide),
    a ``pods x ranks`` shape (``"2x8"`` — product must equal ``size``),
    or explicit inclusive rank ranges (``"0-7,8-15"`` — must partition
    ``0..size-1`` contiguously, in order). A declared torus fabric
    (``BLUEFOG_TORUS_DIMS``) is cross-checked: pod boundaries that slice
    through an inner torus plane warn once (the pods are still usable,
    but the serpentine-compactness argument weakens)."""
    from bluefog_tpu.topology import placement

    spec = str(spec).strip()
    size = int(size)
    if size < 2:
        raise ValueError(f"{PODS_ENV} needs at least 2 ranks, got {size}")
    if not spec:
        raise ValueError(f"empty {PODS_ENV} spec")

    bounds: List[Tuple[int, int]] = []
    if "-" in spec:
        cursor = 0
        for part in spec.split(","):
            part = part.strip()
            try:
                lo_s, hi_s = part.split("-")
                lo, hi = int(lo_s), int(hi_s) + 1
            except ValueError:
                raise ValueError(
                    f"{PODS_ENV} range {part!r} is not 'lo-hi'"
                ) from None
            if lo != cursor:
                raise ValueError(
                    f"{PODS_ENV} ranges must partition 0..{size - 1} "
                    f"contiguously in order; pod {len(bounds)} starts at "
                    f"{lo}, expected {cursor}"
                )
            if hi <= lo:
                raise ValueError(f"{PODS_ENV} range {part!r} is empty")
            bounds.append((lo, hi))
            cursor = hi
        if cursor != size:
            raise ValueError(
                f"{PODS_ENV} ranges cover 0..{cursor - 1} but the world "
                f"has {size} ranks"
            )
    else:
        try:
            dims = tuple(
                int(d) for d in spec.replace("x", ",").split(",")
                if d.strip()
            )
        except ValueError:
            raise ValueError(
                f"{PODS_ENV}={spec!r} is not a pod count, 'PxR' shape, "
                "or 'lo-hi,...' range list"
            ) from None
        if len(dims) == 1:
            n_pods = dims[0]
            if n_pods < 2 or size % n_pods != 0:
                raise ValueError(
                    f"{PODS_ENV}={spec!r}: {size} ranks do not split "
                    f"into {n_pods} equal pods (need >= 2 pods and an "
                    "even division)"
                )
            per = size // n_pods
        elif len(dims) == 2:
            n_pods, per = dims
            if n_pods < 2 or per < 1 or n_pods * per != size:
                raise ValueError(
                    f"{PODS_ENV}={spec!r}: {n_pods} pods x {per} ranks "
                    f"!= {size} world ranks"
                )
        else:
            raise ValueError(
                f"{PODS_ENV}={spec!r} has {len(dims)} dims; expected a "
                "pod count or 'pods x ranks'"
            )
        bounds = [(p * per, (p + 1) * per) for p in range(n_pods)]

    if len(bounds) < 2:
        raise ValueError(
            f"{PODS_ENV}={spec!r} declares one pod; federation needs >= 2"
        )

    torus = placement.declared_torus_dims(size)
    if torus is not None and len(torus) > 1:
        inner = 1
        for d in torus[1:]:
            inner *= d
        if any((hi - lo) % inner != 0 for lo, hi in bounds):
            warn_once(
                f"pods-torus-misaligned-{size}",
                "%s=%r pod boundaries do not align to whole %s-rank "
                "planes of the declared torus %s; pods remain usable "
                "but are not physically compact regions",
                PODS_ENV, spec, inner, "x".join(str(d) for d in torus),
            )
    return PodLayout(size=size, bounds=tuple(bounds), spec=spec)


def layout_from_env(size: int) -> Optional[PodLayout]:
    """The env-declared layout for ``size`` ranks, or None when
    ``BLUEFOG_PODS`` is unset. Raises on a malformed spec — a declared
    federation that cannot be honored must not silently run flat."""
    spec = os.environ.get(PODS_ENV, "").strip()
    if not spec:
        return None
    return parse_pods(spec, size)


def dcn_period() -> int:
    raw = os.environ.get(DCN_PERIOD_ENV, "").strip()
    if not raw:
        return DEFAULT_DCN_PERIOD
    try:
        period = int(raw)
    except ValueError:
        raise ValueError(
            f"{DCN_PERIOD_ENV} must be a positive int, got {raw!r}"
        ) from None
    if period < 1:
        raise ValueError(
            f"{DCN_PERIOD_ENV} must be a positive int, got {raw!r}"
        )
    return period


def dcn_wire() -> Optional[str]:
    """The DCN leg's wire tier. Error-feedback tiers degrade to their
    memoryless base with a one-shot warning: CHOCO residuals integrated
    once per ``T``-step period would correct against ``T``-step-stale
    iterates — a bias, not an error feedback."""
    raw = os.environ.get(DCN_WIRE_ENV, "").strip().lower()
    if not raw:
        return DEFAULT_DCN_WIRE
    if raw in ("exact", "none", "f32", "fp32"):
        return None
    if raw in ("int8_ef", "int4_ef"):
        base = raw[:-3]
        warn_once(
            "dcn-wire-ef",
            "%s=%r: error-feedback wires are not supported on the "
            "periodic DCN leg (residual state would stale across the "
            "period); using the memoryless %r tier",
            DCN_WIRE_ENV, raw, base,
        )
        return base
    if raw not in ("int8", "bf16", "int4"):
        raise ValueError(
            f"{DCN_WIRE_ENV} must be one of int4/int8/bf16/exact "
            f"(got {raw!r})"
        )
    return raw


def elect_gateways(
    layout: PodLayout, live: Optional[Sequence[int]] = None
) -> Tuple[Optional[int], ...]:
    """Module-level alias of :meth:`PodLayout.gateways` (the elastic
    layer's entry point at repair time)."""
    return layout.gateways(live)


# -- two-level edge builders --------------------------------------------------


def intra_edges(
    layout: PodLayout, kind: str = "exp2"
) -> Dict[Tuple[int, int], float]:
    """The block-diagonal intra-pod combine: each pod's base topology
    (:func:`bluefog_tpu.fleetsim.base_edges` — self loops included,
    receiver-normalized), remapped to global ranks."""
    from bluefog_tpu import fleetsim

    out: Dict[Tuple[int, int], float] = {}
    for p in range(layout.n_pods):
        lo, hi = layout.bounds[p]
        for (a, b), w in fleetsim.base_edges(hi - lo, kind).items():
            out[(lo + a, lo + b)] = w
    return out


def inter_edges(
    layout: PodLayout,
    gateways: Optional[Sequence[Optional[int]]] = None,
) -> Dict[Tuple[int, int], float]:
    """The gateway combine: a ring over the (live) gateways,
    receiver-normalized, identity everywhere else. Applied AFTER the
    intra combine on a DCN step, so each gateway's payload already
    carries its pod's mixed value."""
    from bluefog_tpu import fleetsim

    if gateways is None:
        gateways = layout.gateways()
    gws = [g for g in gateways if g is not None]
    out: Dict[Tuple[int, int], float] = {
        (r, r): 1.0 for r in range(layout.size) if r not in set(gws)
    }
    if len(gws) <= 1:
        # zero or one pod left: the inter leg is the identity
        for g in gws:
            out[(g, g)] = 1.0
        return out
    ring = fleetsim.ring_edges(len(gws))
    for (a, b), w in ring.items():
        out[(gws[a], gws[b])] = w
    return out


def federated_union_edges(
    layout: PodLayout,
    kind: str = "exp2",
    gateways: Optional[Sequence[Optional[int]]] = None,
) -> Dict[Tuple[int, int], float]:
    """The UNION graph (intra edges + cross-pod gateway edges) for
    consumers that hold one combine matrix — the fleet simulator's
    repair algebra. Off-diagonal gateway edges are added at the intra
    self-weight scale; a receiver-normalizing policy owns the final
    column sums."""
    if gateways is None:
        gateways = layout.gateways()
    out = dict(intra_edges(layout, kind))
    gws = [g for g in gateways if g is not None]
    for k in range(len(gws)):
        for d in (-1, 1):
            src, dst = gws[k], gws[(k + d) % len(gws)]
            if src != dst:
                out[(src, dst)] = out.get((src, dst), 0.0) + 0.5
    return out


# -- spectral scoring / the period chooser ------------------------------------


def composed_rate(
    layout: PodLayout, period: int, kind: str = "exp2",
    gateways: Optional[Sequence[Optional[int]]] = None,
) -> Tuple[float, dict]:
    """Per-communicating-step consensus decay rate of the two-level
    fabric at DCN period ``T``: the sparse spectral engine scores the
    ``T``-step window product (``T`` intra combines, one gateway
    combine) and the per-step rate is ``slem ** (1/T)`` — the window
    spans ``T`` gossip steps however many matrices compose it. The
    ``N x N`` product is never formed (period composes as mat-vecs)."""
    from bluefog_tpu.topology import spectral

    period = int(period)
    if period < 1:
        raise ValueError(f"period must be >= 1, got {period}")
    n = layout.size
    w_ici = (n, intra_edges(layout, kind))
    w_dcn = (n, inter_edges(layout, gateways))
    mats = [w_ici] * period + [w_dcn]
    _rate, info = spectral.decay_info(mats)
    rate = float(info["slem"]) ** (1.0 / period)
    info = dict(info)
    info["dcn_period"] = period
    info["rate_per_comm_step"] = rate
    return rate, info


def choose_dcn_period(
    layout: PodLayout,
    target_rate: float,
    kind: str = "exp2",
    max_period: int = 64,
) -> dict:
    """Choose the DCN period FROM a target per-step consensus rate.

    Scans ``T = 1..max_period`` (each window scored end-to-end by
    :func:`composed_rate`) and returns the LARGEST period whose
    composed per-step rate still meets ``target_rate`` — the least DCN
    traffic that keeps the promised contraction. When even ``T = 1``
    misses the target (the pod graph itself is the bottleneck) the
    result is ``period = 1`` with ``met = False`` disclosed.

    Returns ``{"period", "predicted_rate", "target_rate", "met",
    "table"}`` where ``table`` discloses every scored candidate."""
    target_rate = float(target_rate)
    best: Optional[Tuple[int, float]] = None
    table: List[dict] = []
    for period in range(1, int(max_period) + 1):
        rate, info = composed_rate(layout, period, kind)
        table.append({
            "period": period,
            "rate": round(rate, 8),
            "slem": round(float(info["slem"]), 8),
            "engine": info.get("engine"),
        })
        if rate <= target_rate:
            best = (period, rate)
        elif best is not None:
            # rate degrades monotonically past the knee; once the
            # target is lost after having been met, longer periods
            # cannot recover it
            break
    if best is None:
        rate1 = table[0]["rate"]
        return {
            "period": 1,
            "predicted_rate": rate1,
            "target_rate": target_rate,
            "met": False,
            "table": table,
        }
    return {
        "period": best[0],
        "predicted_rate": best[1],
        "target_rate": target_rate,
        "met": True,
        "table": table,
    }


def simulate_consensus(
    edges_sequence: Sequence[Tuple[int, Dict[Tuple[int, int], float]]],
    steps: int,
    seed: int = 0,
    comm_steps_per_cycle: Optional[int] = None,
) -> float:
    """MEASURED per-communicating-step consensus decay over a periodic
    matrix sequence: gossip a random mean-zero vector for ``steps``
    cycles of the sequence and fit the geometric rate of its deviation
    norm. The empirical check the spectral prediction is matched
    against in evidence (predictions are promises; this is the run).

    ``comm_steps_per_cycle`` is how many COMMUNICATING STEPS one pass
    of the sequence represents (default: one per matrix). A federated
    period-``T`` window lists ``T + 1`` matrices but spans ``T`` steps
    — the DCN combine rides the last step's dispatch — so pass ``T`` to
    make the measured rate comparable with :func:`composed_rate`."""
    n = edges_sequence[0][0]
    cycle = (
        len(edges_sequence) if comm_steps_per_cycle is None
        else int(comm_steps_per_cycle)
    )
    rng = np.random.RandomState(seed)
    x = rng.randn(n)
    x -= x.mean()
    d0 = float(np.linalg.norm(x))
    if d0 == 0.0:
        return 0.0
    mats = []
    for size, edges in edges_sequence:
        w = np.zeros((size, size))
        for (i, j), v in edges.items():
            w[i, j] = v
        mats.append(w)
    comm_steps = 0
    for _ in range(int(steps)):
        for w in mats:
            x = w.T @ x
        comm_steps += cycle
        x -= x.mean()
    d1 = float(np.linalg.norm(x))
    if d1 <= 0.0 or comm_steps == 0:
        return 0.0
    return (d1 / d0) ** (1.0 / comm_steps)


# -- CommPlan lowering --------------------------------------------------------


def _matrix_from_edges(
    n: int, edges: Dict[Tuple[int, int], float]
) -> np.ndarray:
    w = np.zeros((n, n))
    for (i, j), v in edges.items():
        w[i, j] = v
    return w


def intra_plan(layout: PodLayout, kind: str = "exp2", method: str = "auto"):
    """The ICI leg as a :class:`~bluefog_tpu.collective.plan.CommPlan`,
    compiled against the ICI-class calibration (the default class — the
    flat fabric's exact compile path)."""
    from bluefog_tpu.collective import plan as plan_mod

    w = _matrix_from_edges(layout.size, intra_edges(layout, kind))
    return plan_mod.plan_from_matrix(w, method=method)


def inter_plan(
    layout: PodLayout,
    live: Optional[Sequence[int]] = None,
    method: str = "auto",
):
    """The DCN leg as a :class:`~bluefog_tpu.collective.plan.CommPlan`
    over the CURRENT gateways, compiled against the DCN-class
    calibration (``link_class="dcn"``)."""
    from bluefog_tpu.collective import plan as plan_mod

    w = _matrix_from_edges(
        layout.size, inter_edges(layout, layout.gateways(live))
    )
    return plan_mod.plan_from_matrix(w, method=method, link_class="dcn")


def wire_summary(
    layout: PodLayout,
    n_elems: int,
    itemsize: int = 4,
    ici_wire: Optional[str] = None,
    dcn_wire_tier: Optional[str] = None,
    period: Optional[int] = None,
    kind: str = "exp2",
) -> dict:
    """Per-leg wire accounting for one communicating step: ICI bytes at
    full rate, DCN bytes amortized over the period, and the flat
    baseline — the per-step DCN bytes a FLAT fabric of the same base
    topology would push through cross-pod links (every cross-pod edge
    rides DCN every step at the gossip wire). The ``>= 8x`` DCN-cut
    evidence claim (FEDERATE_EVIDENCE.json) is this ratio."""
    from bluefog_tpu import fleetsim, metrics

    period = dcn_period() if period is None else int(period)
    if dcn_wire_tier is None:
        dcn_wire_tier = dcn_wire()
    intra = intra_plan(layout, kind)
    by_item = {int(itemsize): int(n_elems)}
    ici_bytes = metrics.wire_bytes_per_step(
        by_item, len(intra.rounds), ici_wire
    )
    # DCN legs are counted per-EDGE (fleet totals): only the gateway
    # pairs put bytes on DCN, so the per-worker round convention the
    # ICI counter uses would overcount every silent rank
    inter_e = inter_edges(layout)
    n_inter_edges = sum(1 for (i, j) in inter_e if i != j)
    per_edge_dcn = metrics.wire_bytes_per_step(by_item, 1, dcn_wire_tier)
    dcn_event_bytes = n_inter_edges * per_edge_dcn
    # flat baseline: the same base topology spanning all pods; its
    # cross-pod edges would each carry one payload per step on DCN
    flat = fleetsim.base_edges(layout.size, kind)
    per_edge = metrics.wire_bytes_per_step(by_item, 1, ici_wire)
    cross = sum(
        1 for (i, j) in flat
        if i != j and layout.pod_of(i) != layout.pod_of(j)
    )
    flat_dcn_bytes = cross * per_edge
    fed_dcn_bytes = dcn_event_bytes / max(period, 1)
    return {
        "ici_wire_bytes_per_step": int(ici_bytes),
        "dcn_wire_bytes_per_event": int(dcn_event_bytes),
        "dcn_wire_bytes_per_step": fed_dcn_bytes,
        "dcn_period": period,
        "dcn_wire": dcn_wire_tier or "exact",
        "flat_cross_pod_edges": cross,
        "flat_dcn_bytes_per_step": int(flat_dcn_bytes),
        "dcn_cut_ratio": (
            round(flat_dcn_bytes / fed_dcn_bytes, 4)
            if fed_dcn_bytes > 0 else float("inf")
        ),
    }


# -- the active fabric (optimizer dispatch surface) ---------------------------


@dataclasses.dataclass(frozen=True)
class Fabric:
    """The resolved two-level fabric one optimizer dispatches against:
    the layout, the per-leg plans, the DCN period and wire. Built once
    per (env signature, size) and cached — the dispatch gate reads it
    every communicating step."""

    layout: PodLayout
    period: int
    wire: Optional[str]
    intra: object  # CommPlan
    inter: object  # CommPlan
    kind: str = "exp2"

    def dcn_step(self, comm_count: int) -> bool:
        """Whether communicating step ``comm_count`` carries the DCN
        leg (every ``period``-th step, starting at the first)."""
        return int(comm_count) % self.period == 0

    def to_json(self) -> dict:
        try:
            rate = float(
                composed_rate(self.layout, self.period, self.kind)[0]
            )
        except Exception:
            rate = None
        return {
            "layout": self.layout.to_json(),
            "gateways": [
                g for g in self.layout.gateways() if g is not None
            ],
            "dcn_period": self.period,
            "dcn_wire": self.wire or "exact",
            "intra_rounds": len(self.intra.rounds),
            "inter_rounds": len(self.inter.rounds),
            "kind": self.kind,
            "predicted_rate": rate,
        }


_FABRIC_CACHE: Dict[tuple, Fabric] = {}


def _env_signature(size: int) -> tuple:
    return (
        int(size),
        os.environ.get(PODS_ENV, "").strip(),
        os.environ.get(DCN_PERIOD_ENV, "").strip(),
        os.environ.get(DCN_WIRE_ENV, "").strip().lower(),
    )


def get_fabric(size: int, kind: str = "exp2") -> Optional[Fabric]:
    """The active fabric for ``size`` ranks, or None when federation is
    off. Cached on the full env signature, so flipping any knob
    rebuilds (and the optimizer's cache keys change with the plans)."""
    if not enabled():
        return None
    sig = _env_signature(size) + (kind,)
    fab = _FABRIC_CACHE.get(sig)
    if fab is None:
        from bluefog_tpu import metrics

        layout = parse_pods(os.environ[PODS_ENV], size)
        fab = Fabric(
            layout=layout,
            period=dcn_period(),
            wire=dcn_wire(),
            intra=intra_plan(layout, kind),
            inter=inter_plan(layout),
            kind=kind,
        )
        _FABRIC_CACHE[sig] = fab
        metrics.gauge("bluefog.federation.pods").set(layout.n_pods)
        metrics.gauge("bluefog.federation.dcn_period").set(fab.period)
    return fab


def clear_fabric_cache() -> None:
    _FABRIC_CACHE.clear()


# -- whole-pod elastic semantics (the fleet-scale exercise) -------------------


class FederatedFleet:
    """A federated :class:`~bluefog_tpu.fleetsim.VirtualFleet`: the
    union graph (intra blocks + gateway ring) under the same repair
    algebra, with GATEWAY RE-ELECTION folded into the repair event.

    Whole-pod loss is ONE repair event: the fault plan delivers every
    kill at the same step, detection batches them, and the single
    ``_repair`` pass prunes the pod, re-elects gateways among the
    survivors, rewires the inter-pod ring, and bumps the topology
    version ONCE — the plan cache can never serve a stale gateway.
    Exercised at O(pods x chips) by ``BENCH_MODE=federate``."""

    def __init__(self, layout: PodLayout, kind: str = "exp2",
                 policy: str = "receiver", plan=None,
                 audit_edges: bool = True, seed: int = 0):
        from bluefog_tpu import fleetsim

        self.layout = layout
        self.kind = kind
        self._gateways = [g for g in layout.gateways() if g is not None]
        fleet = fleetsim.VirtualFleet(
            layout.size, topology=kind, policy=policy, plan=plan,
            audit_edges=audit_edges, seed=seed,
            edges=federated_union_edges(layout, kind),
        )
        fleet.pod_layout = layout
        # fold gateway re-election into the fleet's repair event: the
        # hook runs inside the timed, single-version-bump repair pass
        fleet.repair_hook = self._on_repair
        self.fleet = fleet

    def _on_repair(self, newly_dead: List[int], step: int) -> dict:
        """Runs inside ``VirtualFleet._repair`` after the prune: re-elect
        gateways over the survivors and rewire the inter-pod ring in
        place (normalizer caches of touched ranks invalidated — the
        same lazy-repair discipline as the prune itself)."""
        topo = self.fleet.topo
        live = [r for r in range(self.layout.size) if topo.live[r]]
        new_gws = [
            g for g in self.layout.gateways(live) if g is not None
        ]
        old_gws = self._gateways
        if new_gws == old_gws:
            return {"gateways": list(old_gws), "gateway_change": False}
        # drop every cross-pod edge of the OLD ring...
        for k in range(len(old_gws)):
            for d in (-1, 1):
                src = old_gws[k]
                dst = old_gws[(k + d) % len(old_gws)]
                if src == dst:
                    continue
                topo.base_out[src].pop(dst, None)
                topo.base_in[dst].pop(src, None)
                topo._touch_neighborhood(src)
                topo._touch_neighborhood(dst)
        # ...and wire the NEW ring between the re-elected gateways
        if len(new_gws) > 1:
            for k in range(len(new_gws)):
                for d in (-1, 1):
                    src = new_gws[k]
                    dst = new_gws[(k + d) % len(new_gws)]
                    if src == dst:
                        continue
                    topo.base_out[src][dst] = 0.5
                    topo.base_in[dst][src] = 0.5
                    topo._touch_neighborhood(src)
                    topo._touch_neighborhood(dst)
        topo._avg = None
        self._gateways = new_gws
        return {"gateways": list(new_gws), "gateway_change": True}

    # thin delegation — the fleet keeps its own clock and records
    def tick(self) -> dict:
        return self.fleet.tick()

    def run(self, steps: int) -> None:
        self.fleet.run(steps)

    def summary(self) -> dict:
        out = self.fleet.summary()
        out["federation"] = {
            "n_pods": self.layout.n_pods,
            "gateways": list(self._gateways),
            "dcn_period": dcn_period() if enabled() else None,
        }
        return out
