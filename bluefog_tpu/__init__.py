# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""bluefog_tpu: a TPU-native decentralized (gossip) training framework.

Capability parity with BlueFog (reference at /root/reference) re-designed
for JAX/XLA SPMD over TPU meshes: neighbor collectives are ``ppermute``
schedules over ICI, window-style asynchronous algorithms are buffered
step-synchronous neighbor state, and the optimizer wrappers drive
pjit-compiled train steps.

The user-facing facade mirrors ``bluefog.torch`` lifted to the
single-controller model — distributed values are stacked "worker arrays"
with one leading slot per worker::

    import bluefog_tpu as bf
    bf.init()                                 # mesh + default Exp graph
    x = bf.worker_values(lambda rank: ...)    # stacked [size, ...] array
    y = bf.neighbor_allreduce(x)              # weighted gossip step
    h = bf.neighbor_allreduce_nonblocking(x)  # async-dispatch handle
    y = bf.synchronize(h)

See :mod:`bluefog_tpu.context` for the documented API departures from the
reference's per-process model.
"""

import jax as _jax

from bluefog_tpu import compat as _compat  # install jax API shims first
from bluefog_tpu.version import __version__
from bluefog_tpu import topology
from bluefog_tpu import topology as topology_util  # reference-style alias
from bluefog_tpu import collective
from bluefog_tpu.context import (
    BluefogContext,
    get_context,
    init,
    is_initialized,
    shutdown,
)
from bluefog_tpu.windows import (
    win_create,
    win_free,
    win_update,
    win_update_then_collect,
    win_put,
    win_put_nonblocking,
    win_get,
    win_get_nonblocking,
    win_accumulate,
    win_accumulate_nonblocking,
    win_wait,
    win_poll,
    win_mutex,
    win_read,
    get_win_version,
    get_win_age,
    get_current_created_window_names,
    turn_on_win_ops_with_associated_p,
    turn_off_win_ops_with_associated_p,
    win_associated_p,
)
from bluefog_tpu.optimizers import (
    CommunicationType,
    DistributedGradientAllreduceOptimizer,
    DistributedAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
    DistributedHierarchicalNeighborAllreduceOptimizer,
    DistributedAdaptThenCombineOptimizer,
    DistributedAdaptWithCombineOptimizer,
    DistributedWinPutOptimizer,
    DistributedPullGetOptimizer,
    DistributedPushSumOptimizer,
)
from bluefog_tpu.utility import (
    broadcast_parameters,
    broadcast_optimizer_state,
    allreduce_parameters,
)
from bluefog_tpu import async_gossip
from bluefog_tpu.async_gossip import make_async_train_step
from bluefog_tpu import checkpoint
from bluefog_tpu import elastic
from bluefog_tpu import ops
from bluefog_tpu.timeline import (
    timeline_init,
    timeline_shutdown,
    timeline_enabled,
    timeline_start_activity,
    timeline_end_activity,
    timeline_context,
)
from bluefog_tpu.logging_util import logger, set_log_level
from bluefog_tpu import flight
from bluefog_tpu.flight import dump as flight_dump
from bluefog_tpu import attribution
from bluefog_tpu import attribution as doctor  # bf.doctor facade
from bluefog_tpu import autotune
from bluefog_tpu import health
from bluefog_tpu import memory
from bluefog_tpu import fleetsim
from bluefog_tpu import federation
from bluefog_tpu import sharding
from bluefog_tpu import slo
from bluefog_tpu import staleness
from bluefog_tpu import metrics
from bluefog_tpu.metrics import (
    metrics_export,
    snapshot as metrics_snapshot,
)
from bluefog_tpu.timeline import (
    timeline_record_counter,
    timeline_record_instant,
)
from bluefog_tpu.watchdog import set_stall_timeout
from bluefog_tpu.watchdog import suspend, resume
from bluefog_tpu.collective.ops import (
    worker_values,
    allreduce,
    allreduce_nonblocking,
    allgather,
    allgather_nonblocking,
    broadcast,
    broadcast_nonblocking,
    neighbor_allreduce,
    neighbor_allreduce_nonblocking,
    neighbor_allgather,
    neighbor_allgather_nonblocking,
    hierarchical_neighbor_allreduce,
    hierarchical_neighbor_allreduce_nonblocking,
    pair_gossip,
    pair_gossip_nonblocking,
    poll,
    synchronize,
    wait,
    barrier,
)


# -- fused train step (overlap layer) ----------------------------------------


def make_train_step(optimizer, loss_fn, has_aux: bool = False,
                    delayed: bool = False):
    """Compile ``loss_fn`` + backward + inner update + gossip into ONE
    program so XLA can overlap the ppermute rounds with compute.

    Free-function facade over ``optimizer.make_train_step`` for any of the
    gossip-family distributed optimizers::

        opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
        train_step = bf.make_train_step(opt, loss_fn)
        params, opt_state, loss = train_step(params, opt_state, batch)

    ``delayed=True`` mixes each step against the previous step's payload
    (one-step-stale gossip), removing communication from the critical path
    entirely; see :meth:`bluefog_tpu.optimizers._GossipOptimizer.make_train_step`
    and docs/performance.md for semantics and the staleness caveat.
    """
    return optimizer.make_train_step(
        loss_fn, has_aux=has_aux, delayed=delayed
    )


# -- size / rank queries (reference basics.py:112-201) -----------------------


def size() -> int:
    """Number of workers (= mesh devices; the reference's MPI world size)."""
    return get_context().size


def local_size() -> int:
    """Workers per machine (reference local communicator size)."""
    return get_context().local_size


def machine_size() -> int:
    """Number of machines in the hierarchical split."""
    return get_context().machine_size


def rank() -> int:
    """Controller process index. 0 under single-controller; equals the
    reference's rank only in the shared one-process-per-host regime. Worker
    identity lives in the mesh axis, not the process — see
    :mod:`bluefog_tpu.context`."""
    return _jax.process_index()


def local_rank() -> int:
    """Process-local analogue of :func:`rank` (0 on a single controller)."""
    return 0


def machine_rank(worker_rank: int) -> int:
    """Machine index of a worker rank (reference basics.py:180-188)."""
    return worker_rank // get_context().local_size


def is_homogeneous() -> bool:
    """All machines have the same worker count — always true here because
    the machines×local split is a mesh reshape (reference basics.py:190-201
    discovers this over MPI)."""
    return True


# -- topology management -----------------------------------------------------


def set_topology(topology_graph=None, is_weighted: bool = False) -> bool:
    """Install a new virtual topology (reference basics.py:311-419). With
    ``None`` restores the default ExponentialGraph."""
    ctx = get_context()
    if topology_graph is None:
        topology_graph = topology.ExponentialGraph(ctx.size)
    return ctx.set_topology(topology_graph, is_weighted)


def load_topology():
    """The active topology digraph (reference basics.py:292-309)."""
    return get_context().load_topology()


def is_topo_weighted() -> bool:
    return get_context().is_topo_weighted()


def set_machine_topology(topology_graph, is_weighted: bool = False) -> bool:
    """Install the machine-level topology for hierarchical ops
    (reference basics.py:267-309)."""
    return get_context().set_machine_topology(topology_graph, is_weighted)


def load_machine_topology():
    return get_context().load_machine_topology()


def is_machine_topo_weighted() -> bool:
    return get_context().is_machine_topo_weighted()


def in_neighbor_ranks(rank: int = None):
    """In-neighbors of ``rank``; all ranks' lists when ``rank`` is None
    (single-controller lift of reference basics.py:203-233)."""
    return get_context().in_neighbor_ranks(rank)


def out_neighbor_ranks(rank: int = None):
    return get_context().out_neighbor_ranks(rank)


def in_neighbor_machine_ranks(machine_rank: int = None):
    return get_context().in_neighbor_machine_ranks(machine_rank)


def out_neighbor_machine_ranks(machine_rank: int = None):
    return get_context().out_neighbor_machine_ranks(machine_rank)


__all__ = [
    "__version__",
    "topology",
    "topology_util",
    "collective",
    "BluefogContext",
    "init",
    "shutdown",
    "is_initialized",
    "get_context",
    "size",
    "local_size",
    "machine_size",
    "rank",
    "local_rank",
    "machine_rank",
    "is_homogeneous",
    "set_topology",
    "load_topology",
    "is_topo_weighted",
    "set_machine_topology",
    "load_machine_topology",
    "is_machine_topo_weighted",
    "in_neighbor_ranks",
    "out_neighbor_ranks",
    "in_neighbor_machine_ranks",
    "out_neighbor_machine_ranks",
    "worker_values",
    "allreduce",
    "allreduce_nonblocking",
    "allgather",
    "allgather_nonblocking",
    "broadcast",
    "broadcast_nonblocking",
    "neighbor_allreduce",
    "neighbor_allreduce_nonblocking",
    "neighbor_allgather",
    "neighbor_allgather_nonblocking",
    "hierarchical_neighbor_allreduce",
    "hierarchical_neighbor_allreduce_nonblocking",
    "pair_gossip",
    "pair_gossip_nonblocking",
    "poll",
    "synchronize",
    "wait",
    "barrier",
    "win_create",
    "win_free",
    "win_update",
    "win_update_then_collect",
    "win_put",
    "win_put_nonblocking",
    "win_get",
    "win_get_nonblocking",
    "win_accumulate",
    "win_accumulate_nonblocking",
    "win_wait",
    "win_poll",
    "win_mutex",
    "win_read",
    "get_win_version",
    "get_win_age",
    "get_current_created_window_names",
    "turn_on_win_ops_with_associated_p",
    "turn_off_win_ops_with_associated_p",
    "win_associated_p",
    "make_train_step",
    "async_gossip",
    "make_async_train_step",
    "CommunicationType",
    "DistributedGradientAllreduceOptimizer",
    "DistributedAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
    "DistributedHierarchicalNeighborAllreduceOptimizer",
    "DistributedAdaptThenCombineOptimizer",
    "DistributedAdaptWithCombineOptimizer",
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
    "broadcast_parameters",
    "broadcast_optimizer_state",
    "allreduce_parameters",
    "timeline_init",
    "timeline_shutdown",
    "timeline_enabled",
    "timeline_start_activity",
    "timeline_end_activity",
    "timeline_record_instant",
    "timeline_record_counter",
    "timeline_context",
    "elastic",
    "flight",
    "flight_dump",
    "attribution",
    "doctor",
    "autotune",
    "health",
    "sharding",
    "memory",
    "fleetsim",
    "federation",
    "slo",
    "staleness",
    "metrics",
    "metrics_snapshot",
    "metrics_export",
    "logger",
    "set_log_level",
    "set_stall_timeout",
    "suspend",
    "resume",
]
