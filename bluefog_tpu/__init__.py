# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""bluefog_tpu: a TPU-native decentralized (gossip) training framework.

Capability parity with BlueFog (reference at /root/reference) re-designed for
JAX/XLA SPMD over TPU meshes: neighbor collectives are ``ppermute`` schedules
over ICI, window-style asynchronous algorithms are buffered step-synchronous
neighbor state, and the optimizer wrappers drive pjit-compiled train steps.

The user-facing facade mirrors ``bluefog.torch``::

    import bluefog_tpu as bf
    bf.init()
    x = bf.worker_values(lambda rank: ...)   # stacked [size, ...] array
    y = bf.neighbor_allreduce(x)
"""

from bluefog_tpu.version import __version__
from bluefog_tpu import topology
from bluefog_tpu import topology as topology_util  # reference-style alias
