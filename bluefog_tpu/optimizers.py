# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Decentralized optimizer layer: every reference factory, optax-composed.

The reference wraps ``torch.optim`` objects and splices communication into
module forward/backward hooks so it overlaps compute
(``torch/optimizers.py:166-1554``); the combine order distinguishes the
families — CTA (combine-then-adapt: gossip the weights, then take the
local optimizer step) vs ATC (adapt-then-combine: step first, gossip the
result). On TPU there are two execution shapes. ``opt.step(params, state,
grads)`` compiles the update + gossip into one jitted shard_map program —
but the caller's forward/backward is a SEPARATE program, and XLA cannot
overlap collectives with compute across a program boundary: every ppermute
round in ``step`` sits fully exposed on the critical path between the two
dispatches. ``opt.make_train_step(loss_fn)`` removes that boundary — it
fuses forward, backward, inner update, and the gossip combine into ONE
program, the only place XLA's latency-hiding scheduler can actually run
the ppermute rounds concurrently with backward/update compute (see
``docs/performance.md`` "Overlapping communication with compute").
The reference's hand-rolled inner sgd/adam/rmsprop/
adagrad/adadelta re-implementations (optimizers.py:564-842) collapse into
"pass any optax transformation".

Factory parity map (reference torch/optimizers.py line refs):

- DistributedGradientAllreduceOptimizer (:1376) — psum-mean the gradients.
- DistributedAllreduceOptimizer        (:1301) — CTA, global allreduce.
- DistributedNeighborAllreduceOptimizer(:1326) — CTA, neighbor gossip.
- DistributedHierarchicalNeighborAllreduceOptimizer (:1352) — CTA,
  machine-level gossip.
- DistributedAdaptThenCombineOptimizer (:1426) — ATC, comm type selectable.
- DistributedAdaptWithCombineOptimizer (:1497) — CTA, comm type selectable.
- DistributedWinPutOptimizer   (:1271) — diffusion via win_put.
- DistributedPullGetOptimizer  (:1225) — diffusion via win_get.
- DistributedPushSumOptimizer  (:1180) — directed-graph push-sum via
  win_accumulate + associated-p correction.

Dynamic topology follows the reference idiom: assign
``opt.self_weight / opt.src_weights / opt.dst_weights`` (or a precompiled
``opt.schedule``) between steps; the compiled-step cache is keyed by the
resolved plan, so periodic schedules never retrace.

Distributed state model: parameters, optimizer state, and gradients are
worker-stacked pytrees (leading axis = worker), the same convention as
:mod:`bluefog_tpu.collective.ops`.
"""

import enum
import itertools
import time
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax

from bluefog_tpu import attribution
from bluefog_tpu import autotune as autotune_mod
from bluefog_tpu import context as ctx_mod
from bluefog_tpu import flight
from bluefog_tpu import sharding
from bluefog_tpu import health as health_mod
from bluefog_tpu import memory as memory_mod
from bluefog_tpu import metrics as metrics_mod
from bluefog_tpu import slo as slo_mod
from bluefog_tpu import staleness as staleness_mod
from bluefog_tpu import timeline as tl
from bluefog_tpu import windows as win_mod
from bluefog_tpu.collective import compiler, inner, ops as col_ops
from bluefog_tpu.collective.plan import SchedulePlan, plan_from_topology
from bluefog_tpu.logging_util import warn_once
from jax.sharding import PartitionSpec as P

__all__ = [
    "CommunicationType",
    "DistributedGradientAllreduceOptimizer",
    "DistributedAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
    "DistributedHierarchicalNeighborAllreduceOptimizer",
    "DistributedAdaptThenCombineOptimizer",
    "DistributedAdaptWithCombineOptimizer",
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
]


class CommunicationType(enum.Enum):
    """Reference ``CommunicationType`` (torch/optimizers.py:28-32)."""

    neighbor_allreduce = "neighbor.allreduce"
    hierarchical_neighbor_allreduce = "hierarchical.neighbor.allreduce"
    allreduce = "allreduce"
    empty = "empty"


def _tree_block(tree):
    return jax.tree_util.tree_map(lambda t: t[0], tree)


def _dtype_groups(leaves):
    """Deterministic (dtype-sorted) same-dtype leaf groups:
    [(dtype_str, [leaf_idx...])]."""
    groups: dict = {}
    for i, l in enumerate(leaves):
        groups.setdefault(str(jnp.result_type(l)), []).append(i)
    return sorted(groups.items())


def _bucketed_flat_gossip(flat, gossip_fn, step, wops, cap_bytes):
    """Gossip a flat payload in size-capped buckets (Horovod-style).

    Each bucket issues its own plan rounds, so independent buckets'
    ppermutes can pipeline — bucket k+1's combine arithmetic overlaps
    bucket k's transfer — instead of the whole model serializing behind
    one monolithic payload. Slicing a flat vector never reorders
    elements, and the combine is elementwise per element, so bucketed
    output is bitwise-identical to the monolithic combine (quantized
    wires included: bounds snap to the 512-element scale chunk)."""
    bounds = inner.bucket_bounds(flat.size, flat.dtype.itemsize, cap_bytes)
    if len(bounds) == 1:
        return gossip_fn(flat, step, wops)
    return jnp.concatenate(
        [gossip_fn(flat[a:b], step, wops) for a, b in bounds]
    )


def _packed_gossip(tree, gossip_fn, step, wops, cap_bytes=0):
    """Apply a gossip combine to a whole pytree, packed per dtype group
    and split into size-capped wire buckets.

    XLA does not combine per-leaf collective-permutes (a 6-leaf ATC step
    over a 3-round plan compiles to 18 of them — verified by
    tests/test_fusion.py), so a model-sized tree would pay
    O(leaves x rounds) message latencies. Packing every same-dtype leaf
    into one flat vector before the combine is the TPU-native analogue of
    the reference's tensor-fusion buffer (``tensor_queue.h:75-124``, 8 MiB
    threshold, ``global_state.h:91``): the many-leaf gossip becomes a
    single ppermute payload per round, at the price of one concat/split
    (a fused HBM copy) per step. Grouping by dtype keeps the wire policy
    intact — bf16 leaves gossip in bf16, never promoted by packing.

    ``cap_bytes`` > 0 re-splits each packed payload into independent
    buckets (:func:`bluefog_tpu.collective.inner.bucket_bounds`) so the
    scheduler can pipeline them; 0 keeps one payload per dtype group.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = [None] * len(leaves)
    for _dt, idxs in _dtype_groups(leaves):
        if len(idxs) == 1:
            i = idxs[0]
            l = leaves[i]
            bounds = inner.bucket_bounds(l.size, l.dtype.itemsize, cap_bytes)
            if len(bounds) == 1:
                out[i] = gossip_fn(l, step, wops)
            else:
                res = _bucketed_flat_gossip(
                    l.reshape(-1), gossip_fn, step, wops, cap_bytes
                )
                out[i] = res.reshape(l.shape)
            continue
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
        res = _bucketed_flat_gossip(flat, gossip_fn, step, wops, cap_bytes)
        off = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = res[off:off + n].reshape(leaves[i].shape)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _packed_gossip_ef(tree, ef_blocks, ef_combine, cap_bytes=0):
    """Like :func:`_packed_gossip` but with sender error-feedback state:
    one f32 residual vector per dtype group, threaded through the combine
    (``ef_combine(flat, e) -> (y, e_new)``). Returns (tree', ef').

    Bucketing slices the residual state with the payload (the state is
    positional over the same flat vector), so each bucket carries its own
    error feedback and the reassembled state layout is unchanged."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = [None] * len(leaves)
    ef_out = []
    for gi, (_dt, idxs) in enumerate(_dtype_groups(leaves)):
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
        bounds = inner.bucket_bounds(
            flat.size, flat.dtype.itemsize, cap_bytes
        )
        e_self, e_recv = ef_blocks[gi]
        if len(bounds) == 1:
            y, e_new = ef_combine(flat, (e_self, e_recv))
        else:
            ys, e_selfs, e_recvs = [], [], []
            for a, b in bounds:
                yb, (es, er) = ef_combine(
                    flat[a:b], (e_self[a:b], e_recv[:, a:b])
                )
                ys.append(yb)
                e_selfs.append(es)
                e_recvs.append(er)
            y = jnp.concatenate(ys)
            e_new = (
                jnp.concatenate(e_selfs),
                jnp.concatenate(e_recvs, axis=1),
            )
        ef_out.append(e_new)
        off = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = y[off:off + n].reshape(leaves[i].shape)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out), tuple(ef_out)


def _shard_check_groups(tree, layout, what):
    """The packed dtype groups of ``tree`` must be exactly the groups
    the shard layout was built for — a silent mismatch would slice the
    wrong coordinates."""
    leaves = jax.tree_util.tree_leaves(tree)
    got = tuple(
        (dt, sum(int(np.prod(leaves[i].shape)) for i in idxs))
        for dt, idxs in _dtype_groups(leaves)
    )
    want = tuple((g.dtype, g.elems) for g in layout.groups)
    if got != want:
        raise ValueError(
            f"BLUEFOG_SHARD: the {what} tree packs into dtype groups "
            f"{got} but the shard layout was built for {want}; "
            "gradients must share the parameter tree's dtypes (re-init "
            "the optimizer state after changing parameter avals)"
        )


def _shard_own_slices(tree, layout, axis):
    """Each rank's owned 512-aligned slot of every packed dtype group
    (traced): pack -> pad to the layout grid -> dynamic-slice at this
    rank's owner index. Dead ranks slice slot 0 — they compute an
    unused duplicate whose output the gather never selects."""
    packs = _pack_groups(tree)
    lidx = jnp.asarray(layout.live_index())
    i = lidx[jax.lax.axis_index(axis)]
    out = []
    for gi, gsh in enumerate(layout.groups):
        f = jnp.pad(packs[gi], (0, gsh.padded - packs[gi].shape[0]))
        out.append(
            jax.lax.dynamic_slice_in_dim(f, i * gsh.slot, gsh.slot)
        )
    return tuple(out)


def _sharded_inner_update(tx, layout, p, s, g, own_g=None):
    """The ZeRO-1 weight update (arxiv 2004.13336), valid exactly when
    the update inputs are rank-invariant (the gradient-allreduce
    family): each rank updates only its owned slot of the packed
    parameter vector with its 1/N optax-state shard (optionally against
    an fp32 master slice), then one ``all_gather`` redistributes the
    updated slices and the full tree is repacked. Runs inside the
    shard_map block on UNSTACKED trees; ``s`` is a
    :class:`bluefog_tpu.sharding.ShardedOptState`. Returns ``(p, s)``.

    ``own_g`` short-circuits the gradient slicing for the ZeRO-2 form:
    the reduce-scatter already delivered each rank its owned slot of
    the fleet-mean gradient, so the full-width gradient is never
    materialized here (:func:`_scatter_own_grads`).
    """
    _shard_check_groups(p, layout, "parameter")
    if own_g is None:
        _shard_check_groups(g, layout, "gradient")
        own_g = _shard_own_slices(g, layout, ctx_mod.WORKER_AXIS)
    own_p = _shard_own_slices(p, layout, ctx_mod.WORKER_AXIS)
    if layout.master:
        # fp32 master slices carry the reference values; the update
        # runs in fp32 and the wire ships the narrowed result
        own_g = tuple(x.astype(jnp.float32) for x in own_g)
        updates, inner_s = tx.update(own_g, s.inner, s.master)
        masters = optax.apply_updates(s.master, updates)
        new_own = tuple(
            m.astype(o.dtype) for m, o in zip(masters, own_p)
        )
        s_out = sharding.ShardedOptState(inner_s, tuple(masters))
    else:
        updates, inner_s = tx.update(own_g, s.inner, own_p)
        new_own = optax.apply_updates(own_p, updates)
        s_out = sharding.ShardedOptState(inner_s, ())
    live_rows = jnp.asarray(np.asarray(layout.live, np.int32))
    full = []
    for gi, gsh in enumerate(layout.groups):
        gathered = jax.lax.all_gather(
            new_own[gi], ctx_mod.WORKER_AXIS
        )  # [size, slot]
        full.append(
            jnp.take(gathered, live_rows, axis=0).reshape(-1)[:gsh.elems]
        )
    return _unpack_groups(p, tuple(full)), s_out


def _scatter_own_grads(g, layout, wire, chunks, ef_blocks):
    """The ZeRO-2 gradient leg (arxiv 2004.13336's full
    weight-update-sharding form): ring reduce-scatter every packed
    dtype group so each rank receives ONLY its owned 512-aligned slot
    of the fleet-mean gradient — the full-width allreduce output is
    never materialized. The scatter speaks the same wire tiers as the
    gossip path (``wire``); the ``*_ef`` tiers hold their CHOCO
    residual per-slot in ``ef_blocks`` (one ``[padded]`` f32 per
    group). Reduction order is fixed inside
    :func:`bluefog_tpu.collective.inner.reduce_scatter` (own row
    first, then ring rounds in order), which is what keeps the
    sharded==replicated trajectory pins inside their envelopes.
    Returns ``(own_g, ef_blocks')`` — ``ef_blocks'`` is ``()`` for the
    residual-free tiers."""
    _shard_check_groups(g, layout, "gradient")
    packs = _pack_groups(g)
    live_index = tuple(int(v) for v in layout.live_index())
    live_set = set(layout.live)
    live_mask = tuple(
        1.0 if r in live_set else 0.0 for r in range(layout.size)
    )
    own, ef_out = [], []
    for gi, gsh in enumerate(layout.groups):
        f = jnp.pad(packs[gi], (0, gsh.padded - packs[gi].shape[0]))
        k = chunks[gi] if gi < len(chunks) else 1
        if wire in ("int8_ef", "int4_ef"):
            y, e_new = inner.reduce_scatter(
                f, ctx_mod.WORKER_AXIS, live_index, gsh.slot,
                average=True, wire=wire, chunks=k,
                ef=ef_blocks[gi], live_mask=live_mask,
            )
            ef_out.append(e_new)
        else:
            y = inner.reduce_scatter(
                f, ctx_mod.WORKER_AXIS, live_index, gsh.slot,
                average=True, wire=wire, chunks=k,
            )
        own.append(y)
    return tuple(own), tuple(ef_out)


def _combine_update(order, tx, gossip_fn, wops, step, cap_bytes,
                    ef, ef_state, p, s, g, wire=None, with_metrics=False,
                    shard=None, scatter_wire=None, scatter_chunks=()):
    """The gossip+inner-update core shared by :meth:`_GossipOptimizer.step`
    and the fused builder (:meth:`_GossipOptimizer.make_train_step`).

    One implementation, two callers, so the fused train step is
    bitwise-identical math to the legacy two-program path by construction
    (pinned by tests/test_overlap.py). Runs inside a shard_map block on
    UNSTACKED (per-worker) trees; returns ``(p, s, ef_state', mvec)``.

    ``with_metrics=True`` additionally computes the gossip-health metric
    row (:func:`bluefog_tpu.metrics.build_probe_payload`) from the
    combine's own intermediates — purely extra *outputs*, never touching
    the values that feed ``p``/``s``, so metrics on/off stays
    bitwise-identical for the training state (tests/test_metrics.py);
    ``mvec`` is None when off. ``wire`` names the quantized wire in use
    so the metric row can include its quantization error.
    """
    mvec = None
    allreduce_fn = lambda t, _s, _w: inner.allreduce(
        t, ctx_mod.WORKER_AXIS, average=True
    )

    def probe(tree, ef_st, comb_fn):
        """The metrics SUB-GOSSIP: slice a 512-aligned prefix of the
        packed combine INPUT (touching inputs is free) and run the SAME
        wire on just that subsample — the combine is elementwise (and
        chunk-local for the quantized wires, with 512-aligned prefixes
        preserving chunk boundaries), so the tiny combine's output is
        bitwise the restriction of the full combine. The BIG combine's
        outputs are never consumed: any metric path touching them
        (tree-domain or packed, sliced or reduced) was measured to
        derail the CPU backend's schedule by ~a third of a step."""
        pairs = []
        for gi, (sub, scale) in enumerate(
            _packed_prefix(tree, metrics_mod.sample_elems_cap())
        ):
            if ef:
                e_self, e_recv = ef_st[gi]
                k = sub.shape[0]
                # restriction of the CHOCO combine: state slices are
                # INPUT values; the probe's updated copies are exported
                # for the residual metric and then discarded
                y_sub, (es_new, _er_new) = comb_fn(
                    sub, (e_self[:k], e_recv[:, :k]), wops
                )
                pairs.append((sub, y_sub, scale, es_new))
            else:
                y_sub = comb_fn(sub, step, wops)
                pairs.append((sub, y_sub, scale, None))
        g_subs = (
            _packed_prefix(g, metrics_mod.sample_elems_cap())
            if g is not None else ()
        )
        return metrics_mod.build_probe_payload(pairs, g_subs, wire=wire)

    if order == "grad":
        # order='grad' only exists with allreduce communication
        # (DistributedGradientAllreduceOptimizer); the "iterate" on the
        # wire IS the local gradient: disagreement = ||g_avg - g_local||
        if with_metrics:
            mvec = probe(g, ef_state, allreduce_fn)
        if shard is not None and shard.grads:
            # BLUEFOG_SHARD_GRADS=1 (ZeRO-2): lower the gradient
            # allreduce to reduce-scatter(own slot) — each rank
            # receives only the 1/N slot its update consumes, and the
            # ef_state slot carries the scatter wire's per-slot
            # residuals (not the gossip CHOCO copies)
            own_g, ef_state = _scatter_own_grads(
                g, shard, scatter_wire, scatter_chunks, ef_state
            )
            p, s = _sharded_inner_update(
                tx, shard, p, s, g, own_g=own_g
            )
            return p, s, ef_state, mvec
        g = _packed_gossip(g, allreduce_fn, step, wops, cap_bytes)

    if shard is not None:
        # BLUEFOG_SHARD=1: the allreduce above made the gradient
        # rank-invariant, so the replicated inner update is redundant —
        # run the ZeRO-1 sharded form instead (1/N state, owned-slot
        # update, all-gather redistribution). `s` is a ShardedOptState.
        p, s = _sharded_inner_update(tx, shard, p, s, g)
        return p, s, ef_state, mvec

    def communicate(tree, ef_st):
        nonlocal mvec
        if with_metrics and order in ("cta", "atc"):
            mvec = probe(tree, ef_st, gossip_fn)
        if ef:
            return _packed_gossip_ef(
                tree,
                ef_st,
                lambda flat, e: gossip_fn(flat, e, wops),
                cap_bytes,
            )
        return _packed_gossip(tree, gossip_fn, step, wops, cap_bytes), ef_st

    if order == "cta":
        p, ef_state = communicate(p, ef_state)
    updates, s = tx.update(g, s, p)
    p = optax.apply_updates(p, updates)
    if order == "atc":
        p, ef_state = communicate(p, ef_state)
    return p, s, ef_state, mvec


def _pack_groups(tree):
    """Per-dtype-group flat packed payloads of an UNSTACKED tree, in
    :func:`_dtype_groups` order — the wire layout `_packed_gossip` uses."""
    leaves = jax.tree_util.tree_leaves(tree)
    return tuple(
        jnp.concatenate([leaves[i].reshape(-1) for i in idxs])
        if len(idxs) > 1
        else leaves[idxs[0]].reshape(-1)
        for _dt, idxs in _dtype_groups(leaves)
    )


def _packed_prefix(tree, cap):
    """``[(sub_flat, scale)]`` per dtype group: a 512-aligned prefix of
    the group's PACKED flat, built directly from whole input leaves
    plus at most one partial leaf slice — so only O(cap) elements are
    ever concatenated and only INPUT values are consumed. ``scale``
    (= group elems / covered elems) restores whole-group squared-sum
    estimates on the host; 1.0 (exact) when the group fits the cap.
    The 512 alignment matches the quantization chunk, keeping the
    metrics sub-gossip's chunk scales bit-identical to the full wire's
    for the covered region (:mod:`bluefog_tpu.metrics`)."""
    leaves = jax.tree_util.tree_leaves(tree)
    out = []
    for _dt, idxs in _dtype_groups(leaves):
        total = sum(int(leaves[i].size) for i in idxs)
        keep = min(total, max(512, cap - cap % 512))
        parts = []
        got = 0
        for i in idxs:
            if got >= keep:
                break
            n = int(leaves[i].size)
            take = min(n, keep - got)
            flat = leaves[i].reshape(-1)
            parts.append(flat if take == n else flat[:take])
            got += take
        sub = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        out.append((sub, total / keep))
    return out


def _unpack_groups(tree, groups):
    """Scatter per-dtype-group flat packed values back onto a tree's
    leaves; the inverse of :func:`_pack_groups`."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    out = list(leaves)
    for gi, (_dt, idxs) in enumerate(_dtype_groups(leaves)):
        y = groups[gi]
        off = 0
        for i in idxs:
            n = leaves[i].size
            out[i] = y[off:off + n].reshape(
                leaves[i].shape
            ).astype(leaves[i].dtype)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)


def _tree_restack(tree):
    return jax.tree_util.tree_map(lambda t: jnp.expand_dims(t, 0), tree)


def _aval_key(tree):
    return tuple(
        (tuple(l.shape), str(l.dtype))
        for l in jax.tree_util.tree_leaves(tree)
    ) + (str(jax.tree_util.tree_structure(tree)),)


def _timed_dispatch(name, fn, *args):
    """ENQUEUE-span dispatch, the analogue of the reference's optimizer
    timeline hooks (torch/optimizers.py:112-165); same plumbing as the
    eager facade's `_compiled` wrapper (collective/ops.py). The memory
    observatory's ``dispatch`` phase watermark brackets the same span
    (on the first call of a fresh program the lazy jit compile lands
    inside this bracket too — the ``compile`` phase the watermark
    decomposition reports is exactly that first-dispatch growth). With
    both the timeline and the observatory off — the common case — the
    fast path is two reads and a direct call."""
    if memory_mod.active() is None:
        if not tl.timeline_enabled():
            return fn(*args)
        t0 = tl.timeline_now_us()
        out = fn(*args)
        tl.timeline_record_complete(name, "ENQUEUE", t0,
                                    tl.timeline_now_us() - t0)
        return out
    with memory_mod.phase_scope("dispatch"):
        if not tl.timeline_enabled():
            return fn(*args)
        t0 = tl.timeline_now_us()
        out = fn(*args)
        tl.timeline_record_complete(name, "ENQUEUE", t0,
                                    tl.timeline_now_us() - t0)
        return out


_opt_uid = itertools.count()


class _GossipOptimizer:
    """Shared engine for the allreduce/neighbor/hierarchical families.

    ``order``: 'cta' gossips the parameters before the inner update,
    'atc' after, 'grad' gossips the *gradients* (allreduce-mean) instead.
    """

    def __init__(self, base_optimizer, communication_type, order: str,
                 num_steps_per_communication: int = 1):
        # Unique id for compiled-step cache keys: id(self.tx) is unsafe
        # (CPython reuses addresses after GC).
        self._uid = next(_opt_uid)
        if not isinstance(communication_type, CommunicationType):
            raise TypeError(
                "communication_type must be a CommunicationType, got "
                f"{communication_type!r}"
            )
        assert not (
            order == "grad"
            and communication_type != CommunicationType.allreduce
        ), "gradient gossip is only defined for allreduce communication"
        self._tx_version = 0
        self._tx = base_optimizer
        self.communication_type = communication_type
        self.order = order
        # Dynamic-topology knobs, reference README.rst:108-123.
        self.self_weight = None
        self.src_weights = None
        self.dst_weights = None
        self.enable_topo_check = True
        # Quantized gossip wire: 'bf16' (2x fewer bytes), 'int8' (4x),
        # 'int4' (8x, block-scaled nibbles), or the error-feedback tiers
        # 'int8_ef'/'int4_ef' (CHOCO memory removes the quantization
        # noise floor; see inner.weighted_combine_quantized*).
        # Static-plan path only.
        self.compression = None
        self.schedule: Optional[SchedulePlan] = None
        # Hierarchical knobs (reference mpi_ops.py:648-821).
        self.neighbor_machine_weights = None
        self.send_neighbor_machines = None
        # Communicate every K-th step() call (reference
        # torch/optimizers.py:321): intermediate calls run the inner
        # update purely locally (cta/atc) or accumulate gradients with no
        # update at all (grad order — classic gradient accumulation).
        self.num_steps_per_communication = num_steps_per_communication
        self._step_count = 0
        self._comm_count = 0  # schedule index: advances per communication
        self._grad_accum = None  # grad-order local accumulator (sum)
        # Device-tier metrics: the 1-in-BLUEFOG_METRICS_INTERVAL sampled
        # step additionally OUTPUTS a pytree of tiny subsample slices
        # (metrics.build_probe_payload); the host folds the previous
        # sample's payload — by then long copied back — into the
        # registry at each new sample.
        self._pending_drain = None  # (wire, payload) copying to host
        self._metrics_hooked = False
        self._acct_cache: dict = {}  # per-program wire-byte accounting
        # The CommPlan behind the most recent gossip resolution (None
        # for allreduce/empty/hierarchical): the attribution doctor's
        # per-round probes measure exactly this plan's rounds.
        self._last_plan = None
        # BLUEFOG_SHARD=1 weight-update sharding (docs/sharding.md):
        # the active ShardLayout (None = replicated state) and the
        # membership-change re-shard count.
        self._shard_layout = None
        self._shard_reshards = 0

    @property
    def tx(self):
        """The inner optax transformation. Reassigning it retraces the
        compiled step (the old compiled program would silently keep the
        stale update rule otherwise); in-place mutation is not detectable —
        always rebind, as with any jitted closure."""
        return self._tx

    @tx.setter
    def tx(self, value):
        if value is not self._tx:
            self._tx = value
            self._tx_version += 1

    # -- state ---------------------------------------------------------------

    def init(self, params):
        """Per-worker inner-optimizer state, worker-stacked. Under
        ``BLUEFOG_SHARD=1`` (gradient-allreduce family) the state is a
        worker-stacked :class:`bluefog_tpu.sharding.ShardedOptState`:
        each rank's 1/N bucket-aligned optax shard plus the optional
        fp32 master slices (``BLUEFOG_SHARD_MASTER``)."""
        ctx = ctx_mod.get_context()
        if self._shard_active():
            return self._shard_init(ctx, params)
        key = ("opt_init", self._uid, self._tx_version) + _aval_key(params)
        fn = ctx.op_cache.get(key)
        if fn is None:
            spec = P(ctx_mod.WORKER_AXIS)

            def body(p):
                return _tree_restack(self.tx.init(_tree_block(p)))

            fn = jax.jit(
                jax.shard_map(
                    body, mesh=ctx.mesh, in_specs=spec, out_specs=spec
                )
            )
            ctx.op_cache[key] = fn
        return fn(params)

    # -- weight-update sharding (BLUEFOG_SHARD, docs/sharding.md) ------------

    def _shard_active(self) -> bool:
        """Sharding applies where it is trajectory-exact: the family
        whose post-communication update inputs are rank-invariant
        (order='grad', the arxiv 2004.13336 setting). Every other
        family holds genuinely per-rank state — already 1/N of the
        fleet total, nothing redundant to shard — so the knob warns
        once and the replicated path runs verbatim (bitwise, pinned in
        tests/test_sharding.py)."""
        if not sharding.enabled():
            return False
        if self.order == "grad" and self.schedule is None:
            return True
        warn_once(
            f"shard-family:{self.order}:{self.communication_type.value}",
            "BLUEFOG_SHARD=1 ignored for the %s/%s family: its optax "
            "state integrates each rank's own gradient stream (per-rank "
            "by construction, no cross-rank redundancy), so a "
            "coordinate-partitioned update would change the algorithm. "
            "Running the replicated path verbatim; weight-update "
            "sharding applies to the gradient-allreduce family "
            "(docs/sharding.md).",
            self.order, self.communication_type.value,
        )
        return False

    def _shard_groups(self, params):
        """``[(dtype, elems)]`` of the worker-stacked parameter tree in
        packed-wire order — the grain the shard layout is built on."""
        leaves = jax.tree_util.tree_leaves(params)
        return tuple(
            (dt, sum(int(np.prod(leaves[i].shape[1:])) for i in idxs))
            for dt, idxs in _dtype_groups(leaves)
        )

    def _ensure_shard_layout(self, ctx, params):
        """Resolve the current shard layout; returns ``(layout,
        changed)`` where ``changed`` means the stored layout no longer
        matches the live set / parameter avals (the caller must
        re-shard any existing state)."""
        token = ctx.live_token()
        groups = self._shard_groups(params)
        master = sharding.master_enabled()
        grads = sharding.grads_enabled()
        lay = self._shard_layout
        if (
            lay is not None
            and lay.token == token
            and lay.master == master
            and tuple((g.dtype, g.elems) for g in lay.groups) == groups
        ):
            if lay.grads != grads:
                # a pure BLUEFOG_SHARD_GRADS flip swaps the gradient
                # leg (allreduce <-> reduce-scatter), not the state
                # layout: rebuild so the layout signature (and thus
                # the compiled-step cache key) changes, but do NOT
                # report a membership change — the slot map is
                # identical and there is nothing to re-shard
                lay = sharding.build_layout(
                    groups, lay.live, ctx.size, master=master,
                    token=token, grads=grads,
                )
                self._shard_layout = lay
            return lay, False
        live = token[1] if token is not None else tuple(range(ctx.size))
        new = sharding.build_layout(
            groups, live, ctx.size, master=master, token=token,
            grads=grads,
        )
        changed = lay is not None
        self._shard_layout = new
        return new, changed

    def _shard_check_elementwise(self, ctx):
        """Refuse inner transformations with cross-coordinate coupling
        (global-norm clipping, LARS/LAMB trust ratios): their update of
        a slot depends on coordinates the slot's owner never sees, so
        sharding would silently train a different trajectory — the one
        failure mode docs/sharding.md promises cannot happen.

        Detection is behavioral, not by type: update a small vector
        twice with identical values in the probe region and different
        values outside it. An elementwise transform yields bit-equal
        probe-region updates; a coupled one almost surely differs."""
        key = ("shard_elementwise", self._uid, self._tx_version)
        ok = ctx.op_cache.get(key)
        if ok is None:
            d = 2 * sharding.ALIGN_ELEMS
            half = d // 2
            rng = np.random.RandomState(0)
            p = rng.randn(d).astype(np.float32)
            g1 = rng.randn(d).astype(np.float32)
            g2 = g1.copy()
            g2[half:] = rng.randn(half).astype(np.float32)
            s0 = self._tx.init(p)
            u1, _ = self._tx.update(g1, s0, p)
            u2, _ = self._tx.update(g2, self._tx.init(p), p)
            ok = bool(
                np.array_equal(
                    np.asarray(u1)[:half], np.asarray(u2)[:half]
                )
            )
            ctx.op_cache[key] = ok
        if not ok:
            raise ValueError(
                "BLUEFOG_SHARD=1 requires an ELEMENTWISE inner "
                "transformation: this optimizer's update of a "
                "coordinate depends on other coordinates (global-norm "
                "clipping, LARS/LAMB-style trust ratios, ...), so a "
                "1/N-slot update would silently diverge from the "
                "replicated trajectory. Use an elementwise transform "
                "(adam, sgd, rmsprop, adagrad, per-element clipping) "
                "or run with BLUEFOG_SHARD=0 (docs/sharding.md)"
            )

    def _shard_init(self, ctx, params):
        self._shard_check_elementwise(ctx)
        layout, _ = self._ensure_shard_layout(ctx, params)
        key = (
            "opt_shard_init", self._uid, self._tx_version,
        ) + layout.sig() + _aval_key(params)
        fn = ctx.op_cache.get(key)
        if fn is None:
            tx = self._tx

            def body(p_b):
                p = _tree_block(p_b)
                own = _shard_own_slices(p, layout, ctx_mod.WORKER_AXIS)
                master = (
                    tuple(x.astype(jnp.float32) for x in own)
                    if layout.master else ()
                )
                return _tree_restack(
                    sharding.ShardedOptState(tx.init(own), master)
                )

            spec = P(ctx_mod.WORKER_AXIS)
            fn = jax.jit(
                jax.shard_map(
                    body, mesh=ctx.mesh, in_specs=spec, out_specs=spec
                )
            )
            ctx.op_cache[key] = fn
        state = fn(params)
        self._register_shard(layout, state)
        return state

    def _register_shard(self, layout, state) -> None:
        from bluefog_tpu import scaling

        sharding.register_active(
            layout, reshards=self._shard_reshards,
            measured_state_bytes=scaling.optimizer_state_bytes(
                state=state, world=layout.size
            ),
        )

    @staticmethod
    def _shard_slot_group(arr_shape, layout):
        """The group index a worker-stacked state leaf of ``arr_shape``
        belongs to, or None for non-slot (scalar/replicated) leaves.
        Slot lengths are unique per layout (sharding.build_layout), so
        the trailing dimension is an unambiguous discriminator."""
        if len(arr_shape) != 2 or arr_shape[0] != layout.size:
            return None
        for gi, g in enumerate(layout.groups):
            if arr_shape[1] == g.slot:
                return gi
        return None

    def _reshard_state(self, ctx, old, new, opt_state):
        """Host-side membership-change re-shard: reconstruct each
        per-coordinate state group from its old owners' rows (the
        worker-stacked simulation holds every row; a real fleet would
        source a lost shard from the gather-on-save checkpoint — see
        docs/sharding.md) and re-slice it under the new owner map.
        Non-slot leaves (step counts) are replicated and carried over.
        """
        from jax.sharding import NamedSharding

        leaves, treedef = jax.tree_util.tree_flatten(opt_state)
        nd_sharding = NamedSharding(ctx.mesh, P(ctx_mod.WORKER_AXIS))
        out = []
        for leaf in leaves:
            gi = self._shard_slot_group(tuple(leaf.shape), old)
            if gi is None:
                out.append(leaf)
                continue
            full = sharding.gather_rows(np.asarray(leaf), old, gi)
            out.append(jax.device_put(
                sharding.slice_rows(full, new, gi), nd_sharding
            ))
        self._shard_reshards += 1
        metrics_mod.counter("bluefog.shard.reshards").inc()
        flight.record(
            "shard_reshard", live=len(new.live), was=len(old.live),
        )
        return jax.tree_util.tree_unflatten(treedef, out)

    def _shard_prepare(self, ctx, params, opt_state):
        """Per-dispatch shard prologue: resolve the layout against the
        current live set and re-shard the state on a membership change
        — the compiled-step cache key carries the layout signature, so
        a stale layout can never dispatch."""
        if not isinstance(opt_state, sharding.ShardedOptState):
            raise ValueError(
                "BLUEFOG_SHARD=1 but the optimizer state is not sharded "
                "(was it created with BLUEFOG_SHARD=0, or restored from "
                "a replicated checkpoint?); re-run init(params) or "
                "restore a gather-on-save sharded checkpoint"
            )
        # re-checked per tx_version: rebinding opt.tx after init must
        # not smuggle a coupled transform past the init-time probe
        self._shard_check_elementwise(ctx)
        old = self._shard_layout
        layout, changed = self._ensure_shard_layout(ctx, params)
        if changed:
            if old.master != layout.master:
                # a reshard can re-lay slot leaves but cannot invent
                # (or drop) the fp32 master slices mid-run; without
                # this the mismatch surfaces as an opaque pytree error
                # deep inside the jitted trace
                raise ValueError(
                    "BLUEFOG_SHARD_MASTER changed mid-run (was "
                    f"{int(old.master)}, now {int(layout.master)}); "
                    "the master slices are part of the optimizer "
                    "state — re-run init(params) (or restore a "
                    "checkpoint saved under the same master mode)"
                )
            opt_state = self._reshard_state(ctx, old, layout, opt_state)
            self._register_shard(layout, opt_state)
        return layout, opt_state

    def _scatter_active(self) -> bool:
        """ZeRO-2 (``BLUEFOG_SHARD_GRADS=1``) on top of an active shard
        family: the gradient leg lowers to reduce-scatter, so the
        gossip-path error-feedback state (full-width CHOCO copies) must
        not engage — the scatter leg holds its own per-slot residuals
        (:meth:`_ensure_scatter_ef`)."""
        return self._shard_active() and sharding.grads_enabled()

    def _scatter_chunks(self, ctx, layout):
        """Per-group transfer chunk counts for the reduce-scatter leg,
        chosen by the same calibrated alpha-beta model as the gossip
        plans — priced on the per-round SLOT payload (the scatter ships
        one slot per round, and a quantized wire ships fewer bytes per
        element than the storage dtype, cf. :meth:`_plan_chunks`)."""
        from bluefog_tpu import scaling

        out = []
        for g in layout.groups:
            itemsize = np.dtype(g.dtype).itemsize
            payload = (
                scaling.wire_payload_bytes(
                    g.slot, itemsize, self.compression
                )
                if self.compression is not None
                else g.slot * itemsize
            )
            out.append(compiler.reduce_scatter_chunks(
                ctx.size, payload, n_elems=g.slot
            ))
        return tuple(out)

    def _ensure_scatter_ef(self, ctx, layout, spec):
        """Per-group per-slot CHOCO residuals for the ZeRO-2 scatter
        wire's ``*_ef`` tiers: worker-stacked ``[size, padded]`` f32,
        rebuilt (zeroed) whenever the layout signature or the wire tier
        changes — a re-shard moves slot ownership, so stale residuals
        would integrate against the wrong coordinates, while zeroed
        ones merely re-transmit full magnitude for a few steps (same
        reset discipline as :meth:`_ensure_ef_state`)."""
        from jax.sharding import NamedSharding

        sig = (layout.sig(), self.compression)
        if getattr(self, "_scatter_ef_sig", None) == sig:
            return
        nd = NamedSharding(ctx.mesh, spec)
        self._scatter_ef = tuple(
            jax.device_put(
                np.zeros((ctx.size, g.padded), np.float32), nd
            )
            for g in layout.groups
        )
        self._scatter_ef_sig = sig

    def _scatter_prologue(self, ctx, shard_l, spec):
        """The ZeRO-2 dispatch prologue shared by :meth:`step` and the
        fused builder: resolve the scatter wire/chunks, materialize the
        per-slot EF residuals when the tier needs them, and build the
        cache-key appendix that keeps wire/chunk/kernel flips from
        aliasing compiled programs. Returns ``(scatter_key,
        scatter_wire, scatter_chunks, scatter_ef)`` — all empty/None
        when the layout does not shard gradients."""
        if shard_l is None or not shard_l.grads:
            return (), None, (), False
        scatter_wire = self.compression
        scatter_chunks = self._scatter_chunks(ctx, shard_l)
        scatter_ef = scatter_wire in ("int8_ef", "int4_ef")
        if scatter_ef:
            self._ensure_scatter_ef(ctx, shard_l, spec)
        # kernel token only for the kernel-gated tiers: the EF scatter
        # quantizes through the composite pair unconditionally (see
        # inner.reduce_scatter), so a kernel flip cannot change it
        scatter_key = (
            "scatter", scatter_wire or "fp32", scatter_chunks,
        ) + (
            inner._kernels.cache_token(scatter_wire)
            if scatter_wire in ("int8", "int4") else ()
        )
        return scatter_key, scatter_wire, scatter_chunks, scatter_ef

    # -- gossip resolution ---------------------------------------------------

    def _wire_payload(self, params):
        """``(payload_bytes, n_elems)`` of the largest wire bucket this
        dispatch ships — the payload the compiler's chunk chooser prices
        (PR-2 buckets are the chunking grain: each bucket is split into
        the chosen chunk count inside the combine)."""
        leaves = jax.tree_util.tree_leaves(params)
        cap = inner.bucket_bytes_cap()
        best = None
        for dt, idxs in _dtype_groups(leaves):
            n = sum(int(np.prod(leaves[i].shape[1:])) for i in idxs)
            if n == 0:
                continue
            itemsize = np.dtype(dt).itemsize
            bounds = inner.bucket_bounds(n, itemsize, cap)
            elems = max(b - a for a, b in bounds)
            if best is None or elems * itemsize > best[0]:
                best = (elems * itemsize, elems)
        return best

    def _plan_chunks(self, plan, payload) -> int:
        """The (rounds, chunks, route) Pareto chooser for one static-plan
        dispatch; 1 when no payload is known (keying callers that never
        dispatch, e.g. structural tests). A quantized wire ships fewer
        bytes per element than the bucket's storage dtype — the chooser
        prices the wire payload (scale sidecar included), not the
        uncompressed input."""
        from bluefog_tpu import scaling

        if payload is None:
            return 1
        payload_bytes, n_elems = payload
        if self.compression is not None:
            payload_bytes = scaling.wire_payload_bytes(
                n_elems, payload_bytes // max(n_elems, 1), self.compression
            )
        compiled = plan.compile_info
        return compiler.choose_chunks(
            compiled if compiled is not None else len(plan.rounds),
            payload_bytes,
            n_elems=n_elems,
            method=col_ops._plan_method(),
        )

    def _gossip_key_and_fn(self, ctx, payload=None):
        """Resolve the communication into (cache-key piece, block fn,
        weight operands).

        The block fn signature is ``fn(t, step, wops)``. Weight *values*
        for plan-based gossip ride in ``wops`` as replicated device
        operands, so the reference's per-iteration weight-reassignment
        idiom (README.rst:108-123) reuses ONE compiled program per edge
        structure instead of compiling per weight vector.

        ``payload`` is ``(bytes, elems)`` of the largest wire bucket
        (:meth:`_wire_payload`); the static-plan neighbor_allreduce
        paths feed it to the chunk chooser, and the chosen chunk count
        plus the plan's route family join the cache-key piece — a
        chunk/route change compiles its own program.
        """
        comm = self.communication_type
        self._last_plan = None
        if self.schedule is not None and comm not in (
            CommunicationType.neighbor_allreduce,
            CommunicationType.hierarchical_neighbor_allreduce,
        ):
            raise ValueError(
                "opt.schedule (a SchedulePlan) only applies to "
                "neighbor_allreduce or hierarchical communication; "
                f"this optimizer uses {comm.value!r}"
            )
        if comm == CommunicationType.empty:
            return ("empty",), (lambda t, step, wops: t), ()
        if comm == CommunicationType.allreduce:
            return (
                ("allreduce",),
                lambda t, step, wops: inner.allreduce(
                    t, ctx_mod.WORKER_AXIS, average=True
                ),
                (),
            )
        if comm == CommunicationType.neighbor_allreduce:
            if self.schedule is not None:
                sched = self.schedule
                if sched.size != ctx.size:
                    raise ValueError(
                        f"opt.schedule is sized for {sched.size} workers "
                        f"but the mesh has {ctx.size}"
                    )
                for p in sched.plans:
                    # deduped: the whole period lands in the postmortem
                    # side table once, however many steps dispatch
                    flight.note_plan(p, ctx.topo_version, ctx.live_token())
                # the doctor probes whichever plan THIS step dispatches
                self._last_plan = sched.plans[
                    self._comm_count % sched.period
                ]
                return (
                    (sched,),
                    lambda t, step, wops: inner.neighbor_allreduce_step(
                        t, step, sched, ctx_mod.WORKER_AXIS
                    ),
                    (),
                )
            if (
                self.self_weight is None
                and self.src_weights is None
                and self.dst_weights is None
            ):
                from bluefog_tpu import federation

                fed = (
                    federation.get_fabric(ctx.size)
                    if federation.enabled() else None
                )
                if fed is not None:
                    return self._federated_key_and_fn(ctx, fed, payload)
            plan = col_ops._resolve_plan(
                ctx,
                self.self_weight,
                self.src_weights,
                self.dst_weights,
                self.enable_topo_check,
            )
            self._last_plan = plan
            perms = plan.perms
            info = plan.compile_info
            inject = info.inject if info is not None else None
            chunks = self._plan_chunks(plan, payload)
            self_w, recv_w = plan.weight_operands()
            if self.compression is not None:
                inner._check_combine_normalized(
                    plan, f"compression={self.compression!r}"
                )
                # keyed on the edge STRUCTURE with weights as operands —
                # per-step varying weights reuse one compiled program,
                # same guarantee as the exact path
                wire = self.compression
                if wire in ("int8_ef", "int4_ef"):
                    if inject is not None:
                        raise ValueError(
                            f"compression={wire!r} cannot ride a "
                            "short-cut (relay) plan: the CHOCO copies "
                            "integrate a fixed per-round source, which "
                            "relay rounds do not have. Unset "
                            "BLUEFOG_PLAN_METHOD=shortcut or use a "
                            "memoryless wire (None/'int8'/'bf16'/"
                            "'int4')."
                        )
                    ef_wire = "int4" if wire == "int4_ef" else "int8"
                    return (
                        # the kernel token rides at the END of every
                        # quantized gossip key (flows into the opt_step
                        # key via tuple(gossip_key); _metrics_wire
                        # parses wire positionally at [1], so appending
                        # is the only safe spot)
                        ("na_q_ef", ef_wire, perms, chunks)
                        + inner._kernels.cache_token(ef_wire),
                        lambda flat, e, wops: (
                            inner.weighted_combine_quantized_ef_operands(
                                flat, e, perms, wops[0],
                                ctx_mod.WORKER_AXIS, chunks=chunks,
                                wire=ef_wire,
                            )
                        ),
                        (jnp.asarray(recv_w),),
                    )
                return (
                    ("na_q", wire, perms, chunks, inject)
                    + inner._kernels.cache_token(wire),
                    lambda t, step, wops: (
                        inner.weighted_combine_quantized_operands(
                            t, perms, wops[0], ctx_mod.WORKER_AXIS,
                            wire=wire, chunks=chunks, inject=inject,
                        )
                    ),
                    (jnp.asarray(recv_w),),
                )
            return (
                ("na", perms, chunks, inject),
                lambda t, step, wops: inner.weighted_combine_operands(
                    t, perms, wops[0], wops[1], ctx_mod.WORKER_AXIS,
                    chunks=chunks, inject=inject,
                ),
                (jnp.asarray(self_w), jnp.asarray(recv_w)),
            )
        raise AssertionError(comm)

    def _federated_key_and_fn(self, ctx, fed, payload):
        """Two-level federated dispatch (docs/federation.md): every
        communicating step runs the intra-pod combine over ICI at full
        rate; every ``fed.period``-th communication appends the
        designated-gateway inter-pod combine on the aggressive DCN wire
        in the SAME compiled body, so XLA overlaps the slow cross-pod
        rounds with the tail of the intra-pod ones.

        Key shapes (the ``"fed"`` tag is what keeps the flat path
        bitwise-untouched — a flat run never produces one):

        - ICI-only step: ``("fed", "ici", wire, perms, chunks, inject)``
        - DCN step: ``("fed", "dcn", wire, perms, chunks, inject,
          dcn_wire, inter_perms, inter_chunks, inter_inject)``

        kernel cache tokens ride at the END (same contract as the flat
        quantized keys). ``wire`` is the intra-pod tier
        (``self.compression``); error-feedback tiers degrade to their
        memoryless base because the CHOCO residual recursion assumes
        the same combine every communicating step, which the periodic
        DCN leg breaks.
        """
        from bluefog_tpu import scaling

        intra = fed.intra
        inter = fed.inter
        self._last_plan = intra
        flight.note_plan(intra, ctx.topo_version, ctx.live_token())
        axis = ctx_mod.WORKER_AXIS
        perms = intra.perms
        info = intra.compile_info
        inject = info.inject if info is not None else None
        chunks = self._plan_chunks(intra, payload)
        self_w, recv_w = intra.weight_operands()
        wire = self.compression
        if wire in ("int8_ef", "int4_ef"):
            warn_once(
                "fed-ef-wire",
                "compression=%r under bf.federation falls back to the "
                "memoryless %r wire: error-feedback residuals would go "
                "stale across the BLUEFOG_DCN_PERIOD gap",
                wire, wire[:-3],
            )
            wire = wire[:-3]
        if wire is not None:
            inner._check_combine_normalized(
                intra, f"compression={wire!r}"
            )
        if not fed.dcn_step(self._comm_count):
            if wire is not None:
                return (
                    ("fed", "ici", wire, perms, chunks, inject)
                    + inner._kernels.cache_token(wire),
                    lambda t, step, wops: (
                        inner.weighted_combine_quantized_operands(
                            t, perms, wops[0], axis,
                            wire=wire, chunks=chunks, inject=inject,
                        )
                    ),
                    (jnp.asarray(recv_w),),
                )
            return (
                ("fed", "ici", None, perms, chunks, inject),
                lambda t, step, wops: inner.weighted_combine_operands(
                    t, perms, wops[0], wops[1], axis,
                    chunks=chunks, inject=inject,
                ),
                (jnp.asarray(self_w), jnp.asarray(recv_w)),
            )
        # DCN step: the gateway leg composes AFTER the intra leg inside
        # one fn, giving the x -> W_dcn^T (W_ici^T x) composed step the
        # spectral scorer priced (federation.composed_rate)
        flight.note_plan(inter, ctx.topo_version, ctx.live_token())
        inter_perms = inter.perms
        inter_info = inter.compile_info
        inter_inject = (
            inter_info.inject if inter_info is not None else None
        )
        inter_self, inter_recv = inter.weight_operands()
        dcn_wire = fed.wire
        inter_chunks = 1
        if payload is not None and inter_info is not None:
            payload_bytes, n_elems = payload
            if dcn_wire is not None:
                payload_bytes = scaling.wire_payload_bytes(
                    n_elems, payload_bytes // max(n_elems, 1), dcn_wire
                )
            inter_chunks = compiler.choose_chunks(
                inter_info, payload_bytes, n_elems=n_elems,
                method=col_ops._plan_method(),
            )
        if dcn_wire is not None:
            inner._check_combine_normalized(
                inter, f"BLUEFOG_DCN_WIRE={dcn_wire!r}"
            )
        key = (
            ("fed", "dcn", wire, perms, chunks, inject,
             dcn_wire, inter_perms, inter_chunks, inter_inject)
            + (inner._kernels.cache_token(wire)
               if wire is not None else ())
            + (inner._kernels.cache_token(dcn_wire)
               if dcn_wire is not None else ())
        )
        if wire is not None:
            n_intra = 1
            intra_ops = (jnp.asarray(recv_w),)

            def intra_leg(t, wops):
                return inner.weighted_combine_quantized_operands(
                    t, perms, wops[0], axis,
                    wire=wire, chunks=chunks, inject=inject,
                )
        else:
            n_intra = 2
            intra_ops = (jnp.asarray(self_w), jnp.asarray(recv_w))

            def intra_leg(t, wops):
                return inner.weighted_combine_operands(
                    t, perms, wops[0], wops[1], axis,
                    chunks=chunks, inject=inject,
                )
        if dcn_wire is not None:
            def fed_fn(t, step, wops):
                return inner.weighted_combine_quantized_operands(
                    intra_leg(t, wops), inter_perms, wops[n_intra],
                    axis, wire=dcn_wire, chunks=inter_chunks,
                    inject=inter_inject,
                )

            wops = intra_ops + (jnp.asarray(inter_recv),)
        else:
            def fed_fn(t, step, wops):
                return inner.weighted_combine_operands(
                    intra_leg(t, wops), inter_perms, wops[n_intra],
                    wops[n_intra + 1], axis, chunks=inter_chunks,
                    inject=inter_inject,
                )

            wops = intra_ops + (
                jnp.asarray(inter_self), jnp.asarray(inter_recv),
            )
        return key, fed_fn, wops

    def _self_weight_fn(self, ctx):
        """Per-rank SELF weight of the active combine, as a traced
        ``fn(step, wops) -> scalar``, for the delayed (one-step-stale) mix.

        The stale combine is ``y = C(buf) + s * (x - buf)``: wire payloads
        come from the stale buffer (so the ppermutes depend on nothing the
        current step computes), but the receiver swaps the stale SELF
        contribution ``s * buf`` for the fresh ``s * x``. That
        "self-fresh, neighbors-stale" recursion is the AD-PSGD-family
        stale-mixing form, stable for every row-stochastic nonnegative
        weight matrix (each root t of ``t^2 - s t - (lam - s)`` has
        ``|t| <= 1`` because Gershgorin puts ``|lam - s| <= 1 - s``),
        where the naive ``y = x + C(buf) - buf`` delta recursion diverges
        whenever the mixing matrix has eigenvalues left of ``Re = 0``.
        """
        comm = self.communication_type
        if comm == CommunicationType.empty:
            return lambda step, wops: jnp.float32(1.0)
        if comm == CommunicationType.allreduce:
            inv_n = 1.0 / ctx.size
            return lambda step, wops: jnp.float32(inv_n)
        if self.schedule is not None:
            sched = self.schedule
            sw = jnp.asarray(
                np.stack([p.self_weights for p in sched.plans]),
                jnp.float32,
            )

            def from_schedule(step, wops):
                idx = jax.lax.axis_index(ctx_mod.WORKER_AXIS)
                return sw[step % sched.period, idx]

            return from_schedule
        compression = self.compression
        if compression in ("int8_ef", "int4_ef"):
            from bluefog_tpu import federation

            if (
                self.self_weight is None
                and self.src_weights is None
                and self.dst_weights is None
                and federation.enabled()
                and federation.get_fabric(ctx.size) is not None
            ):
                # federated EF fallback: the dispatch degraded to the
                # memoryless base tier, whose wops carry only recv_w
                compression = compression[:-3]
        if compression in ("int8", "bf16", "int4"):
            # quantized path carries only recv_w (wops[0], [rounds, size]);
            # the plan is validated normalized, so s = 1 - sum_r recv_w
            def from_recv(step, wops):
                idx = jax.lax.axis_index(ctx_mod.WORKER_AXIS)
                return 1.0 - wops[0][:, idx].astype(jnp.float32).sum()

            return from_recv

        def from_operands(step, wops):  # exact path: wops = (self_w, recv_w)
            idx = jax.lax.axis_index(ctx_mod.WORKER_AXIS)
            return wops[0][idx].astype(jnp.float32)

        return from_operands

    def _validate_compression(self):
        """Central knob validation for BOTH the flat and hierarchical
        paths: a silently-ignored or trace-time-erroring knob would make
        the user believe wire bytes dropped when nothing changed."""
        if self.compression is None:
            return
        comm = self.communication_type
        if self.compression not in (
            "int8", "bf16", "int8_ef", "int4", "int4_ef",
        ):
            raise ValueError(
                "compression must be None, 'int8', 'bf16', 'int4', "
                f"'int8_ef', or 'int4_ef', got {self.compression!r}"
            )
        if (
            comm == CommunicationType.allreduce
            and self.order == "grad"
            and self.schedule is None
            and sharding.enabled()
            and sharding.grads_enabled()
        ):
            # ZeRO-2 scatter wire: every tier rides the reduce-scatter
            # gradient leg (the *_ef residuals are held per-slot inside
            # the scatter, not as gossip CHOCO copies)
            return
        if self.compression in ("int8_ef", "int4_ef") and (
            comm != CommunicationType.neighbor_allreduce
            or self.schedule is not None
        ):
            raise ValueError(
                f"compression={self.compression!r} (error feedback "
                "carries per-worker state) is only supported on the "
                "static-plan neighbor_allreduce path"
            )
        if comm not in (
            CommunicationType.neighbor_allreduce,
            CommunicationType.hierarchical_neighbor_allreduce,
        ) or self.schedule is not None:
            raise ValueError(
                f"compression={self.compression!r} is only supported "
                "on the static-plan neighbor_allreduce and "
                "hierarchical paths (not schedules, allreduce, or "
                "empty communication)"
            )

    def _hier_key_and_fn(self, ctx):
        """Hierarchical communication: static machine plan (operand
        weights) or a dynamic machine-level SchedulePlan (the reference's
        GetExp2DynamicSendRecvMachineRanks training pattern,
        examples/pytorch_benchmark.py:182-202)."""
        # machine-mesh rounds are not probeable on the worker mesh: the
        # doctor keeps step-level attribution, skips per-round profiling
        self._last_plan = None
        if self.schedule is not None:
            sched = self.schedule
            if sched.size != ctx.machine_size:
                raise ValueError(
                    "hierarchical opt.schedule must be machine-level: "
                    f"sized {sched.size}, but there are {ctx.machine_size} "
                    "machines"
                )
            return (
                ("hier_sched", sched),
                lambda t, step, wops: inner.hierarchical_neighbor_allreduce_step(
                    t, step, sched, ctx_mod.MACHINE_AXIS, ctx_mod.LOCAL_AXIS
                ),
                (),
            )
        mplan = self._machine_plan(ctx)
        perms = mplan.perms
        self_w, recv_w = mplan.weight_operands()
        if self.compression is not None:
            # compress the MACHINE-level (DCN) leg — the transfer that
            # actually scales with pod count; the intra-host psum stays
            # exact on ICI
            inner._check_combine_normalized(
                mplan, f"compression={self.compression!r}"
            )
            wire = self.compression
            return (
                ("hier_q", wire, perms)
                + inner._kernels.cache_token(wire),
                lambda t, step, wops: (
                    inner.hierarchical_neighbor_allreduce_quantized(
                        t, perms, wops[0],
                        ctx_mod.MACHINE_AXIS, ctx_mod.LOCAL_AXIS,
                        wire=wire,
                    )
                ),
                (jnp.asarray(recv_w),),
            )
        return (
            ("hier", perms),
            lambda t, step, wops: inner.hierarchical_neighbor_allreduce_operands(
                t, perms, wops[0], wops[1],
                ctx_mod.MACHINE_AXIS, ctx_mod.LOCAL_AXIS
            ),
            (jnp.asarray(self_w), jnp.asarray(recv_w)),
        )

    def _machine_plan(self, ctx):
        if self.neighbor_machine_weights is not None:
            from bluefog_tpu.collective.plan import plan_from_weights

            mplan = plan_from_weights(
                ctx.machine_size,
                self.self_weight if self.self_weight is not None else 0.5,
                self.neighbor_machine_weights,
                self.send_neighbor_machines,
                enable_topo_check=self.enable_topo_check
                and self.send_neighbor_machines is not None,
            )
            flight.note_plan(
                mplan, ctx.machine_topo_version, kind="machine"
            )
            return mplan
        mtopo = ctx.load_machine_topology()
        assert mtopo is not None, (
            "hierarchical optimizer needs bf.set_machine_topology() or "
            "explicit neighbor_machine_weights"
        )
        key = ("opt_machine_plan", ctx.machine_topo_version,
               ctx.is_machine_topo_weighted())
        plan = ctx.op_cache.get(key)
        if plan is None:
            plan = plan_from_topology(
                mtopo, weighted=ctx.is_machine_topo_weighted()
            )
            ctx.op_cache[key] = plan
            flight.note_plan(
                plan, ctx.machine_topo_version, kind="machine"
            )
        return plan

    # -- error-feedback state (compression='int8_ef') ------------------------

    def _ensure_ef_state(self, ctx, params, spec, perms):
        """Per-dtype-group CHOCO copies (x_hat_self, x_hat_recv),
        worker-stacked f32; rebuilt (zeroed) whenever the parameter avals
        OR the communication structure OR the EF wire tier change —
        x_hat_recv[r] integrates round-r's fixed source, so a new edge
        set invalidates every copy (stale copies would break the
        bit-identical-replica invariant; zeroed copies merely
        re-transmit full magnitude a few rounds), and copies integrated
        under one quantizer must not seed the other tier's recursion."""
        from jax.sharding import NamedSharding

        leaves = jax.tree_util.tree_leaves(params)
        sig = (
            tuple(
                (dt, sum(int(np.prod(leaves[i].shape[1:])) for i in idxs))
                for dt, idxs in _dtype_groups(leaves)
            ),
            perms,
            self.compression,
        )
        if getattr(self, "_ef_sig", None) == sig:
            return
        n_rounds = len(perms)
        sharding = NamedSharding(ctx.mesh, spec)
        self._ef = tuple(
            (
                jax.device_put(
                    np.zeros((ctx.size, d), np.float32), sharding
                ),
                jax.device_put(
                    np.zeros((ctx.size, n_rounds, d), np.float32), sharding
                ),
            )
            for _dt, d in sig[0]
        )
        self._ef_sig = sig

    # -- the step ------------------------------------------------------------

    def _comm_now(self) -> bool:
        """Communicate on the K-th call (reference torch/optimizers.py:321);
        validates the K knob on every dispatch."""
        k = int(self.num_steps_per_communication)
        if k < 1:
            raise ValueError(
                "num_steps_per_communication must be a positive int, got "
                f"{self.num_steps_per_communication!r}"
            )
        return self._step_count % k == k - 1

    def _resolve_dispatch(self, ctx, params, comm_now):
        """The dispatch prologue shared by :meth:`step` and the fused
        builder: mesh/spec selection, gossip resolution, error-feedback
        state. One implementation so a new communication type or
        validation rule cannot reach one path and skip the other.
        Returns ``(hier, mesh, spec, gossip_key, gossip_fn, wops, ef,
        cap_bytes)``."""
        self._validate_compression()
        hier = (
            self.communication_type
            == CommunicationType.hierarchical_neighbor_allreduce
        )
        if hier:
            mesh = ctx.machine_mesh
            spec = P((ctx_mod.MACHINE_AXIS, ctx_mod.LOCAL_AXIS))
        else:
            mesh = ctx.mesh
            spec = P(ctx_mod.WORKER_AXIS)
        if not comm_now:
            # between-communication cta/atc call: the SAME fused body, with
            # the identity combine — a purely local inner update
            gossip_key, gossip_fn, wops = (
                ("local",), (lambda t, step, wops: t), ()
            )
        elif hier:
            gossip_key, gossip_fn, wops = self._hier_key_and_fn(ctx)
        else:
            gossip_key, gossip_fn, wops = self._gossip_key_and_fn(
                ctx, self._wire_payload(params)
            )
        ef = comm_now and not hier and self.compression in (
            "int8_ef", "int4_ef",
        ) and not self._scatter_active() and gossip_key[0] != "fed"
        if ef:
            self._ensure_ef_state(ctx, params, spec, gossip_key[2])
        return (
            hier, mesh, spec, gossip_key, gossip_fn, wops, ef,
            inner.bucket_bytes_cap(),
        )

    # -- device-tier metrics plumbing ----------------------------------------

    def _metrics_wire(self, comm_now, hier, gossip_key=None):
        """The quantized-wire name for this dispatch's metric row, or
        None. Hierarchical compression quantizes the machine-level
        local_sum (not the packed tree payload the metric helper sees),
        so its quantization error is not computed — the flat-path wires
        are the ones with a well-defined per-worker payload here."""
        if not comm_now or hier or self.schedule is not None:
            return None
        if gossip_key is not None and gossip_key[0] == "fed":
            # federated dispatch: the key carries the EFFECTIVE intra
            # wire (EF tiers degrade to their memoryless base there)
            return gossip_key[2]
        if self.compression in (
            "int8", "bf16", "int8_ef", "int4", "int4_ef",
        ):
            if (
                self.compression.endswith("_ef")
                and self._scatter_active()
            ):
                # ZeRO-2 scatter EF: the residual lives per-slot inside
                # the scatter (no probe-side CHOCO slice), so the metric
                # row replays the base tier's quantization error
                return self.compression[:-3]
            return self.compression
        return None

    @staticmethod
    def _fold_pending(pending, export):
        wire, payload = pending
        payload = jax.tree_util.tree_map(np.asarray, payload)
        metrics_mod.fold_device_payload(payload, wire=wire, export=export)

    def _drain_after_sample(self, wire, payload):
        """After a sampled dispatch, stash its subsample payload and
        START the device->host copy (``copy_to_host_async``); the
        registry fold happens at the NEXT sample (or at an explicit
        :func:`bluefog_tpu.metrics.flush`), by which point the copy has
        long completed. A synchronous ``np.asarray`` here would block
        the host mid-loop and forfeit a dispatch-pipeline's worth of
        overlap per drain."""
        if not self._metrics_hooked:
            # flush hook: bf.metrics_export()/shutdown fold the pending
            # payload so exports never miss the tail of a run
            metrics_mod.register_flush_hook(self)
            self._metrics_hooked = True
        if self._pending_drain is not None:
            self._fold_pending(self._pending_drain, export=True)
        for leaf in jax.tree_util.tree_leaves(payload):
            try:
                leaf.copy_to_host_async()
            except AttributeError:  # non-jax.Array stand-ins in tests
                pass
        self._pending_drain = (wire, payload)

    def _flush_metrics(self):
        """Fold the pending payload into the registry now (no exporter
        side effects — the caller, :func:`bluefog_tpu.metrics.flush`,
        owns what happens next)."""
        if self._pending_drain is not None:
            self._fold_pending(self._pending_drain, export=False)
            self._pending_drain = None

    def _record_comm_accounting(self, key, gossip_key, params, ctx,
                                shard=None):
        """Host-tier per-dispatch accounting: ppermute rounds and wire
        bytes for this communicating step (static per compiled program,
        so the numbers are computed once per cache key). TopoOpt-style
        per-edge traffic planning starts from exactly this counter.
        An active shard layout adds its all-gather redistribution bytes
        and publishes the ``bluefog.shard.*`` gauges."""
        acct = self._acct_cache.get(key)
        if acct is None:
            tag = gossip_key[0]
            wire = None
            rounds = 0
            ici_bytes = dcn_bytes = 0
            # gossip_key layouts: ("na", perms, chunks, inject),
            # ("na_q", wire, perms, chunks, inject),
            # ("na_q_ef", wire, perms, chunks), ("hier", perms),
            # ("hier_q", wire, perms) — perms sits at [1] except the
            # wire-tagged quantized keys where it sits at [2];
            # ("fed", leg, wire, perms, chunks, inject[, dcn_wire,
            # inter_perms, inter_chunks, inter_inject]) carries the
            # intra perms at [3] and (dcn leg) inter perms at [7]
            if tag in ("na", "hier"):
                rounds = len(gossip_key[1])
            elif tag in ("na_q", "na_q_ef", "hier_q"):
                wire = gossip_key[1]
                if tag == "na_q_ef":
                    # the key carries the inner quantizer name; the
                    # accounting tier is the _ef wire (same bytes)
                    wire = f"{wire}_ef"
                rounds = len(gossip_key[2])
            elif isinstance(tag, SchedulePlan):
                rounds = max(len(p.rounds) for p in tag.plans)
            elif tag == "hier_sched":
                rounds = max(len(p.rounds) for p in gossip_key[1].plans)
            elif tag == "allreduce":
                rounds = 1
            leaves = jax.tree_util.tree_leaves(params)
            by_item: dict = {}
            for l in leaves:
                n = int(np.prod(l.shape[1:])) if l.ndim > 1 else 1
                item = np.dtype(l.dtype).itemsize
                by_item[item] = by_item.get(item, 0) + n
            scatter_bytes = 0
            if tag == "allreduce":
                if shard is not None and shard.grads:
                    # ZeRO-2: the gradient leg is a reduce-scatter of
                    # owned slots (optionally quantized) — price what
                    # actually ships, not the allreduce formula the
                    # replicated family would have used
                    from bluefog_tpu import scaling

                    scatter_bytes = scaling.reduce_scatter_bytes(
                        tuple(
                            (g.slot, np.dtype(g.dtype).itemsize)
                            for g in shard.groups
                        ),
                        shard.size, wire=self.compression,
                    )
                    wire_bytes = scatter_bytes
                    rounds = shard.size - 1
                else:
                    # ring allreduce ships ~2 (n-1)/n payloads per worker
                    payload = sum(i * n for i, n in by_item.items())
                    wire_bytes = int(
                        2 * (ctx.size - 1) / max(ctx.size, 1) * payload
                    )
            elif tag == "fed":
                # per-leg accounting: the ICI leg ships the intra-pod
                # rounds on the optimizer's wire, the DCN leg (when this
                # key is a DCN step) the gateway rounds on the fabric's
                # aggressive tier
                ici_bytes = metrics_mod.wire_bytes_per_step(
                    by_item, len(gossip_key[3]), gossip_key[2]
                )
                rounds = len(gossip_key[3])
                if gossip_key[1] == "dcn":
                    dcn_bytes = metrics_mod.wire_bytes_per_step(
                        by_item, len(gossip_key[7]), gossip_key[6]
                    )
                    rounds += len(gossip_key[7])
                wire_bytes = ici_bytes + dcn_bytes
            else:
                wire_bytes = metrics_mod.wire_bytes_per_step(
                    by_item, rounds, wire
                )
            if shard is not None:
                # the sharded step ships the updated slices back over
                # the fabric: price the all-gather with the gossip wire
                wire_bytes += sharding.gather_wire_bytes(shard)
            acct = (rounds, wire_bytes, scatter_bytes, ici_bytes,
                    dcn_bytes)
            self._acct_cache[key] = acct
        rounds, wire_bytes, scatter_bytes, ici_bytes, dcn_bytes = acct
        metrics_mod.gauge("bluefog.gossip.rounds").set(rounds)
        metrics_mod.counter("bluefog.wire_bytes").inc(wire_bytes)
        metrics_mod.counter("bluefog.comm_steps").inc()
        if ici_bytes or dcn_bytes:
            metrics_mod.counter(
                "bluefog.federation.ici_wire_bytes"
            ).inc(ici_bytes)
            metrics_mod.counter(
                "bluefog.federation.dcn_wire_bytes"
            ).inc(dcn_bytes)
        if shard is not None:
            metrics_mod.gauge("bluefog.shard.enabled").set(1)
            metrics_mod.gauge("bluefog.shard.state_bytes").set(
                sharding.state_bytes(shard)
            )
            metrics_mod.gauge("bluefog.shard.ratio").set(
                sharding.state_bytes(shard)
                / max(sharding.state_bytes(shard, sharded=False), 1)
            )
            metrics_mod.counter("bluefog.shard.gather_bytes").inc(
                sharding.gather_wire_bytes(shard)
            )
            metrics_mod.gauge("bluefog.shard.grads").set(
                1 if shard.grads else 0
            )
            if shard.grads:
                metrics_mod.counter("bluefog.shard.scatter_bytes").inc(
                    scatter_bytes
                )
                metrics_mod.gauge("bluefog.shard.grad_bytes").set(
                    sharding.grad_bytes(shard)
                )

    def step(self, params, opt_state, grads):
        """One decentralized optimization step; returns (params, opt_state).

        The whole step is one compiled SPMD program (reference splits it
        across hooks + synchronize + inner step, optimizers.py:362-482).
        """
        ctx = ctx_mod.get_context()
        comm_now = self._comm_now()
        if not comm_now and self.order == "grad":
            # between communications, gradient order accumulates and leaves
            # params/state untouched (reference _DistributedOptimizer's
            # reduce-delay accumulation, optimizers.py:347,443)
            self._step_count += 1
            self._grad_accum = (
                grads if self._grad_accum is None
                else self._tree_add(ctx, self._grad_accum, grads)
            )
            return params, opt_state
        (
            hier, mesh, spec, gossip_key, gossip_fn, wops, ef, cap_bytes,
        ) = self._resolve_dispatch(ctx, params, comm_now)
        shard_l = None
        if comm_now and self._shard_active():
            shard_l, opt_state = self._shard_prepare(ctx, params, opt_state)
        (
            scatter_key, scatter_wire, scatter_chunks, scatter_ef,
        ) = self._scatter_prologue(ctx, shard_l, spec)
        met_enabled = metrics_mod.enabled() and comm_now
        # Two-program sampling: only the 1-in-interval sampled step pays
        # the metric computation — every other step dispatches a program
        # whose cache key EQUALS the metrics-off key, so 9 of 10 steps
        # are the metrics-off program by construction (the design that
        # keeps BENCH_MODE=metrics under its 2% bound; an in-graph
        # lax.cond was measured to drag every step).
        met = met_enabled and (
            self._comm_count % metrics_mod.metrics_interval() == 0
        )
        wire_now = self._metrics_wire(comm_now, hier, gossip_key)
        key = (
            "opt_step", self.order, self.communication_type, self._uid,
            self._tx_version, ef, cap_bytes, met,
        ) + tuple(gossip_key) + (
            # BLUEFOG_SHARD=0 leaves the key verbatim (bitwise shard-off
            # pin); an active layout keys on its full signature so a
            # membership change can never dispatch a stale owner map
            shard_l.sig() if shard_l is not None else ()
        ) + scatter_key + _aval_key(params)
        fn = ctx.op_cache.get(key)
        if fn is None:
            metrics_mod.counter("bluefog.recompiles").inc()
            flight.record("compile", name="opt_step")
            order = self.order
            tx = self._tx

            def body(params_b, state_b, grads_b, step, wops, ef_b):
                p = _tree_block(params_b)
                s = _tree_block(state_b)
                g = _tree_block(grads_b)
                step = step[0]
                # unstack whichever EF state rides this program: the
                # gossip CHOCO pairs or the ZeRO-2 per-slot residuals
                ef_in = jax.tree_util.tree_map(lambda a: a[0], ef_b)
                p, s, ef_out, mvec = _combine_update(
                    order, tx, gossip_fn, wops, step, cap_bytes,
                    ef, ef_in, p, s, g, wire=wire_now, with_metrics=met,
                    shard=shard_l, scatter_wire=scatter_wire,
                    scatter_chunks=scatter_chunks,
                )
                ef_out = jax.tree_util.tree_map(
                    lambda a: jnp.expand_dims(a, 0), ef_out
                )
                met_out = (
                    (_tree_restack(mvec),) if met else ()
                )
                return _tree_restack(p), _tree_restack(s), ef_out, met_out

            # "compile" phase watermark: the wrapper build is traced
            # here; the XLA compile itself lands in the first
            # dispatch's bracket (jit is lazy) — both attributed
            with memory_mod.phase_scope("compile"):
                fn = jax.jit(
                    jax.shard_map(
                        body,
                        mesh=mesh,
                        in_specs=(spec, spec, spec, P(), P(), spec),
                        out_specs=(spec, spec, spec, spec),
                    )
                )
            ctx.op_cache[key] = fn
        if comm_now and self.order == "grad" and self._grad_accum is not None:
            grads = self._tree_add(ctx, self._grad_accum, grads)
            self._grad_accum = None
        # dynamic schedules advance per COMMUNICATION, not per call, so a
        # K>1 optimizer still walks every topology in the schedule
        step_idx = jnp.asarray([self._comm_count], jnp.int32)
        flight.record("step_begin", step=self._step_count, comm=comm_now)
        self._step_count += 1
        if comm_now:
            self._comm_count += 1
        if scatter_ef:
            ef_in = self._scatter_ef
        else:
            ef_in = self._ef if ef else ()
        if met_enabled:
            self._record_comm_accounting(
                key, gossip_key, params, ctx, shard=shard_l
            )
        doc_t0 = attribution.dispatch_timer(comm_now)
        params_out, opt_state, ef_out, met_out = _timed_dispatch(
            "optimizer_step", fn, params, opt_state, grads, step_idx, wops,
            ef_in,
        )
        flight.record("step_dispatched", step=self._step_count - 1)
        if comm_now:
            # attribution doctor (BLUEFOG_DOCTOR): purely host-side
            # observation — the dispatched program above is untouched
            attribution.observe_step(
                ctx, step=self._step_count - 1, outputs=params_out,
                plan=self._last_plan, params=params,
                wire=self.compression,
                dispatch_s=(
                    time.perf_counter() - doc_t0
                    if doc_t0 is not None else None
                ),
            )
            # fleet health plane (BLUEFOG_HEALTH): same discipline —
            # host arithmetic + its own tiny lane dispatches only
            health_mod.observe_step(
                ctx, step=self._step_count - 1, plan=self._last_plan,
            )
            # staleness observatory (BLUEFOG_STALENESS): the two-program
            # path always gossips the fresh iterate — delivered age 0,
            # the lane's per-sample self-check
            staleness_mod.observe_step(
                ctx, step=self._step_count - 1, plan=self._last_plan,
                payload_age=0, surface="sync",
            )
            # autotune controller (BLUEFOG_AUTOTUNE): host-side
            # decision logic only; a migration it makes lands as a
            # topology-version bump this step path re-resolves next
            # dispatch, exactly like an elastic repair
            autotune_mod.observe_step(
                ctx, step=self._step_count - 1, optimizer=self,
                plan=self._last_plan,
            )
            # memory observatory (BLUEFOG_MEMORY): host-side census of
            # the buffers THIS dispatch left live — the program above
            # is untouched (same cache key, bitwise pin)
            memory_mod.observe_step(
                ctx, step=self._step_count - 1, optimizer=self,
                params=params_out, opt_state=opt_state, grads=grads,
            )
            # SLO engine (BLUEFOG_SLO): evaluates LAST so its sampled
            # pass reads the gauges the tiers above just refreshed;
            # its canary probe dispatches in its own op-cache family —
            # the training program above is untouched (same cache
            # key, bitwise pin)
            slo_mod.observe_step(
                ctx, step=self._step_count - 1, plan=self._last_plan,
                wire=self.compression,
            )
        if ef:
            self._ef = ef_out
        elif scatter_ef:
            self._scatter_ef = ef_out
        if met:
            self._drain_after_sample(wire_now, met_out[0])
        return params_out, opt_state

    # -- the fused train step (overlap layer) --------------------------------

    def _ensure_delay_state(self, ctx, mesh, params, spec, struct_key):
        """Double buffer for ``delayed=True``: one worker-stacked flat
        payload per dtype group, holding the PREVIOUS step's gossip input
        (pre-update params for CTA, post-update for ATC). Seeded from the
        current params — step 0's combine is then exactly the fresh
        combine, and staleness starts at step 1. Rebuilt whenever the
        parameter avals or the communication structure change (a stale
        buffer under a new edge set would mix against the wrong sources,
        same invalidation rule as the error-feedback copies)."""
        from jax.sharding import NamedSharding

        leaves = jax.tree_util.tree_leaves(params)
        sig = (
            tuple(
                (dt, sum(int(np.prod(leaves[i].shape[1:])) for i in idxs))
                for dt, idxs in _dtype_groups(leaves)
            ),
            struct_key,
        )
        if getattr(self, "_delay_sig", None) == sig:
            return
        sharding = NamedSharding(mesh, spec)
        size = ctx.size
        bufs = []
        for _dt, idxs in _dtype_groups(leaves):
            flat = jnp.concatenate(
                [jnp.reshape(leaves[i], (size, -1)) for i in idxs], axis=1
            )
            bufs.append(jax.device_put(flat, sharding))
        self._delay_buf = tuple(bufs)
        self._delay_sig = sig
        # provenance: a (re)seeded buffer holds the CURRENT params, so
        # the next combine's payload age is 0 — the staleness
        # observatory reads the age-0 transient at every topology swap
        # / elastic repair, then the steady-state age-1 again
        self._delay_birth_comm = self._comm_count

    def make_train_step(self, loss_fn, has_aux: bool = False,
                        delayed: bool = False):
        """Build the fused train step: forward, backward, inner optax
        update, and the gossip combine in ONE compiled shard_map program.

        ``loss_fn(params, *batch) -> loss`` (or ``(loss, aux)`` with
        ``has_aux=True``) is evaluated per worker on UNSTACKED trees; the
        returned callable takes worker-stacked operands::

            train_step = opt.make_train_step(loss_fn)
            params, opt_state, loss = train_step(params, opt_state, *batch)

        Why this exists: ``opt.step`` is its own program, so the caller's
        backward pass and the gossip collective live in different XLA
        programs and can never overlap — every ppermute round is exposed
        on the step critical path. Inside one program, XLA's
        latency-hiding scheduler hoists each round's ppermute start above
        independent backward/update compute and sinks the wait below it,
        hiding the transfer (the in-XLA analogue of the reference's
        backward-hook overlap, torch/optimizers.py:166-1554, and of the
        fused weight-update design in "Automatic Cross-Replica Sharding
        of Weight Update in Data-Parallel Training"). The math is the
        shared :func:`_combine_update` core, so fused and two-program
        paths are bitwise-identical (tests/test_overlap.py).

        ``delayed=True`` (ATC/CTA only) takes communication off the
        critical path entirely: the combine at step k mixes the payload
        double-buffered from step k-1, so the ppermutes depend ONLY on a
        carried buffer — zero data dependency on this step's
        forward/backward — and the scheduler can run them concurrently
        with the whole step. The cost is one-step-stale mixing, a
        known-convergent decentralized-SGD variant (the same staleness
        family as asynchronous gossip; consensus and convergence are
        preserved, constants degrade slightly — see docs/performance.md
        for the caveat). ``compression='int8_ef'`` is refused with
        ``delayed=True``: the error-feedback copies integrate the payload
        round by round, and a one-step-stale payload would desynchronize
        sender and receiver copies, breaking the bit-identical-replica
        invariant that scheme relies on.
        """
        if self.order not in ("cta", "atc", "grad"):
            raise AssertionError(self.order)
        if delayed and self.order == "grad":
            raise ValueError(
                "delayed=True applies to the weight-gossip families "
                "(CTA/ATC); gradient allreduce has no stale-mix variant"
            )
        value_and_grad = jax.value_and_grad(loss_fn, has_aux=has_aux)
        # Per-builder cache-key component: two builders over the same
        # optimizer may close over different loss functions.
        fused_uid = next(_opt_uid)

        def train_step(params, opt_state, *batch):
            ctx = ctx_mod.get_context()
            if delayed and self.compression in ("int8_ef", "int4_ef"):
                raise ValueError(
                    f"compression={self.compression!r} cannot carry "
                    "error feedback across a one-step delay (the CHOCO "
                    "copies would integrate a stale payload and "
                    "desynchronize); use delayed=False or a memoryless "
                    "wire (None/'int8'/'bf16'/'int4')"
                )
            comm_now = self._comm_now()
            (
                hier, mesh, spec, gossip_key, gossip_fn, wops, ef,
                cap_bytes,
            ) = self._resolve_dispatch(ctx, params, comm_now)
            shard_l = None
            if comm_now and self._shard_active():
                shard_l, opt_state = self._shard_prepare(
                    ctx, params, opt_state
                )
            (
                scatter_key, scatter_wire, scatter_chunks, scatter_ef,
            ) = self._scatter_prologue(ctx, shard_l, spec)
            if delayed and hier:
                raise ValueError(
                    "delayed=True is not supported for hierarchical "
                    "communication (the intra-machine psum leg has no "
                    "stale-mix form); use flat neighbor_allreduce or "
                    "delayed=False"
                )
            delay_now = delayed and comm_now
            self_weight_fn = (
                self._self_weight_fn(ctx) if delay_now else None
            )
            if delay_now:
                self._ensure_delay_state(ctx, mesh, params, spec, gossip_key)
            accum = (
                self._grad_accum
                if comm_now and self.order == "grad" else None
            )
            met_enabled = metrics_mod.enabled() and comm_now
            # two-program sampling, same rationale as in step(): only
            # the 1-in-interval sampled dispatch compiles/pays for the
            # metric outputs; the rest share the metrics-off program
            met = met_enabled and (
                self._comm_count % metrics_mod.metrics_interval() == 0
            )
            wire_now = self._metrics_wire(comm_now, hier, gossip_key)
            key = (
                "opt_fused_step", fused_uid, self.order,
                self.communication_type, self._uid, self._tx_version, ef,
                delay_now, cap_bytes, accum is not None, met,
            ) + tuple(gossip_key) + (
                # same shard-key discipline as step(): absent when off
                # (bitwise pin), full layout signature when on
                shard_l.sig() if shard_l is not None else ()
            ) + scatter_key + _aval_key((params, opt_state, batch))
            fn = ctx.op_cache.get(key)
            if fn is None:
                metrics_mod.counter("bluefog.recompiles").inc()
                flight.record("compile", name="opt_fused_step")
                order = self.order
                tx = self._tx
                has_accum = accum is not None

                def body(params_b, state_b, step, wops, ef_b, buf_b,
                         accum_b, *batch_b):
                    p = _tree_block(params_b)
                    s = _tree_block(state_b)
                    bat = tuple(_tree_block(b) for b in batch_b)
                    step = step[0]
                    if delay_now:
                        # The stale combine's wire legs FIRST, on the
                        # carried buffers: these ppermutes depend on
                        # nothing this step computes, so the scheduler is
                        # free to run them under the forward/backward
                        # below. Only the cheap elementwise self-swap
                        # (see _self_weight_fn) touches fresh values.
                        bufs = tuple(b[0] for b in buf_b)
                        combined = tuple(
                            _bucketed_flat_gossip(
                                b, gossip_fn, step, wops, cap_bytes
                            )
                            for b in bufs
                        )
                        sw = self_weight_fn(step, wops)

                        def stale_mix(tree):
                            fresh = _pack_groups(tree)
                            return _unpack_groups(tree, tuple(
                                c + sw.astype(c.dtype)
                                * (x.astype(c.dtype) - b.astype(c.dtype))
                                for c, x, b in zip(combined, fresh, bufs)
                            ))

                        def delayed_probe(tree, grads):
                            """Metrics sub-gossip for the stale mix
                            (same rationale as _combine_update's probe:
                            never consume the big combine's outputs):
                            re-run the mix on a 512-aligned prefix of
                            the carried buffer + fresh packs — bitwise
                            the restriction of the full stale combine."""
                            cap = metrics_mod.sample_elems_cap()
                            pairs = []
                            for gi, (f_sub, scale) in enumerate(
                                _packed_prefix(tree, cap)
                            ):
                                k = f_sub.shape[0]
                                b_sub = bufs[gi][:k]
                                c_sub = _bucketed_flat_gossip(
                                    b_sub, gossip_fn, step, wops,
                                    cap_bytes,
                                )
                                y_sub = c_sub + sw.astype(c_sub.dtype) * (
                                    f_sub.astype(c_sub.dtype)
                                    - b_sub.astype(c_sub.dtype)
                                )
                                pairs.append((f_sub, y_sub, scale, None))
                            return metrics_mod.build_probe_payload(
                                pairs,
                                _packed_prefix(grads, cap),
                                wire=None,
                            )
                    if has_aux:
                        (loss, aux), grads = value_and_grad(p, *bat)
                    else:
                        loss, grads = value_and_grad(p, *bat)
                        aux = ()
                    if order == "grad" and not comm_now:
                        # accumulation call: params/state untouched, the
                        # gradient comes OUT to the host-side accumulator
                        return (
                            _tree_restack(p), _tree_restack(s),
                            jnp.reshape(loss, (1,)),
                            _tree_restack(aux) if has_aux else (),
                            (), _tree_restack(grads), (),
                        )
                    if has_accum:
                        grads = jax.tree_util.tree_map(
                            jnp.add, _tree_block(accum_b), grads
                        )
                    mvec = None
                    if delay_now:
                        if order == "cta":
                            new_buf = _pack_groups(p)
                            if met:
                                # delayed mix: delta measured against
                                # the FRESH iterate (wire/EF metrics
                                # have no stale-payload form, see
                                # docs/metrics.md)
                                mvec = delayed_probe(p, grads)
                            p = stale_mix(p)
                            updates, s = tx.update(grads, s, p)
                            p = optax.apply_updates(p, updates)
                        else:  # atc
                            updates, s = tx.update(grads, s, p)
                            p = optax.apply_updates(p, updates)
                            new_buf = _pack_groups(p)
                            if met:
                                mvec = delayed_probe(p, grads)
                            p = stale_mix(p)
                        buf_out = tuple(
                            jnp.expand_dims(b, 0) for b in new_buf
                        )
                        ef_out = ()
                    else:
                        # unstack whichever EF state rides this
                        # program: gossip CHOCO pairs or the ZeRO-2
                        # per-slot scatter residuals
                        ef_in = jax.tree_util.tree_map(
                            lambda a: a[0], ef_b
                        )
                        p, s, ef_out, mvec = _combine_update(
                            order, tx, gossip_fn, wops, step, cap_bytes,
                            ef, ef_in, p, s, grads,
                            wire=wire_now, with_metrics=met,
                            shard=shard_l, scatter_wire=scatter_wire,
                            scatter_chunks=scatter_chunks,
                        )
                        ef_out = jax.tree_util.tree_map(
                            lambda a: jnp.expand_dims(a, 0), ef_out
                        )
                        buf_out = ()
                    met_out = (
                        (_tree_restack(mvec),) if met else ()
                    )
                    return (
                        _tree_restack(p), _tree_restack(s),
                        jnp.reshape(loss, (1,)),
                        _tree_restack(aux) if has_aux else (),
                        ef_out, buf_out, met_out,
                    )

                n_batch = len(batch)
                fn = jax.jit(
                    jax.shard_map(
                        body,
                        mesh=mesh,
                        in_specs=(spec, spec, P(), P(), spec, spec, spec)
                        + (spec,) * n_batch,
                        out_specs=(
                            spec, spec, spec, spec, spec, spec, spec,
                        ),
                    )
                )
                ctx.op_cache[key] = fn
            step_idx = jnp.asarray([self._comm_count], jnp.int32)
            flight.record(
                "step_begin", step=self._step_count, comm=comm_now,
                fused=True,
            )
            # the comm index THIS dispatch runs at, and the age of the
            # payload its combine consumes: 0 on the fresh path, comm
            # steps since the delay buffer was written on the delayed
            # path (1 in steady state, 0 right after a reseed)
            cur_comm = self._comm_count
            payload_age = (
                cur_comm - self._delay_birth_comm if delay_now else 0
            )
            self._step_count += 1
            if comm_now:
                self._comm_count += 1
            if scatter_ef:
                ef_in = self._scatter_ef
            else:
                ef_in = self._ef if ef else ()
            buf_in = self._delay_buf if delay_now else ()
            accum_in = accum if accum is not None else ()
            if met_enabled:
                self._record_comm_accounting(
                    key, gossip_key, params, ctx, shard=shard_l
                )
            # single source of truth for debug/evidence lowering
            # (lower_last_fused_hlo): the compiled fn plus exactly the
            # operand structure this dispatch used — as avals, not live
            # arrays, so the hook never pins a superseded model-sized
            # buffer generation in device memory
            self._last_fused = (fn,) + tuple(
                jax.tree_util.tree_map(
                    lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), op
                )
                for op in (wops, ef_in, buf_in, accum_in)
            )
            doc_t0 = attribution.dispatch_timer(comm_now)
            if self.order == "grad" and not comm_now:
                params_o, state_o, loss, aux, _ef_o, grads_o, _met_o = (
                    _timed_dispatch(
                        "fused_train_step", fn, params, opt_state,
                        step_idx, wops, ef_in, buf_in, accum_in,
                        *batch,
                    )
                )
                self._grad_accum = (
                    grads_o if self._grad_accum is None
                    else self._tree_add(ctx, self._grad_accum, grads_o)
                )
            else:
                params_o, state_o, loss, aux, ef_o, buf_o, met_o = (
                    _timed_dispatch(
                        "fused_train_step", fn, params, opt_state,
                        step_idx, wops, ef_in, buf_in, accum_in,
                        *batch,
                    )
                )
                if ef:
                    self._ef = ef_o
                elif scatter_ef:
                    self._scatter_ef = ef_o
                if delay_now:
                    self._delay_buf = buf_o
                if comm_now and self.order == "grad":
                    self._grad_accum = None
                if met:
                    # the delayed probe measures the stale mix without a
                    # wire payload (no quant/EF slots) — see delayed_probe
                    self._drain_after_sample(
                        None if delay_now else wire_now, met_o[0]
                    )
            flight.record("step_dispatched", step=self._step_count - 1)
            if comm_now:
                # attribution doctor: host-side only, program untouched
                attribution.observe_step(
                    ctx, step=self._step_count - 1, outputs=loss,
                    plan=self._last_plan, params=params,
                    wire=self.compression,
                    dispatch_s=(
                        time.perf_counter() - doc_t0
                        if doc_t0 is not None else None
                    ),
                )
                # fleet health plane: same host-side-only discipline
                health_mod.observe_step(
                    ctx, step=self._step_count - 1,
                    plan=self._last_plan,
                )
                # staleness observatory: stamp the payload's REAL birth
                # (the delayed path gossips the double-buffered
                # previous iterate) and fold the delivered ages
                staleness_mod.observe_step(
                    ctx, step=self._step_count - 1,
                    plan=self._last_plan, payload_age=payload_age,
                    surface="delayed" if delay_now else "sync",
                )
                # autotune controller: host-side decision logic only —
                # a migration lands as a topology-version bump the
                # fused path re-resolves next dispatch
                autotune_mod.observe_step(
                    ctx, step=self._step_count - 1, optimizer=self,
                    plan=self._last_plan,
                )
                # memory observatory: census of this dispatch's live
                # buffers (params + optax state + EF/delay copies),
                # host-side only
                memory_mod.observe_step(
                    ctx, step=self._step_count - 1, optimizer=self,
                    params=params_o, opt_state=state_o,
                )
                # SLO engine: last, same discipline as the two-program
                # path — reads the tiers above, canary in its own
                # op-cache family, training program untouched
                slo_mod.observe_step(
                    ctx, step=self._step_count - 1,
                    plan=self._last_plan, wire=self.compression,
                )
                if delay_now:
                    # the dispatch above refilled the double buffer
                    # with this step's payload
                    self._delay_birth_comm = cur_comm
            if has_aux:
                return params_o, state_o, (loss, aux)
            return params_o, state_o, loss

        return train_step

    def make_async_train_step(self, loss_fn, has_aux: bool = False,
                              **kwargs):
        """Build the fully *asynchronous* train step: per-rank-cadence
        push-sum gossip where no rank ever waits on a peer
        (:func:`bluefog_tpu.async_gossip.make_async_train_step` — this
        optimizer contributes its inner optax transformation and its
        ``compression`` knob as the default wire tier). With
        ``BLUEFOG_ASYNC=0`` this IS :meth:`make_train_step` — the
        synchronous path, bitwise identical. See docs/async.md."""
        from bluefog_tpu import async_gossip

        return async_gossip.make_async_train_step(
            self, loss_fn, has_aux=has_aux, **kwargs
        )

    def lower_last_fused_hlo(self, params, opt_state, *batch) -> str:
        """Optimized HLO text of the most recently dispatched fused train
        step, lowered against the given operands (only their avals
        matter; the recorded dispatch operands are kept as
        ShapeDtypeStructs). Evidence/debug hook for
        ``BENCH_MODE=overlap`` and ``tests/test_overlap.py`` — it owns
        the compiled fn's operand structure so callers never have to
        poke cache-key internals."""
        fn, wops, ef_in, buf_in, accum_in = self._last_fused
        step_idx = jnp.asarray([0], jnp.int32)
        return (
            fn.lower(
                params, opt_state, step_idx, wops, ef_in, buf_in,
                accum_in, *batch,
            )
            .compile()
            .as_text()
        )

    def _tree_add(self, ctx, a, b):
        # keyed by avals only: identical tree-adds from different
        # optimizer instances share one compiled program
        key = ("opt_tree_add",) + _aval_key(a)
        fn = ctx.op_cache.get(key)
        if fn is None:
            fn = jax.jit(
                lambda x, y: jax.tree_util.tree_map(jnp.add, x, y)
            )
            ctx.op_cache[key] = fn
        return fn(a, b)

def DistributedGradientAllreduceOptimizer(base_optimizer,
                                          num_steps_per_communication=1):
    """Synchronous gradient averaging, Horovod-style
    (reference optimizers.py:166-295, factory :1376)."""
    return _GossipOptimizer(
        base_optimizer, CommunicationType.allreduce, order="grad",
        num_steps_per_communication=num_steps_per_communication,
    )


def DistributedAllreduceOptimizer(base_optimizer,
                                  num_steps_per_communication=1):
    """CTA with global weight averaging (reference :1301)."""
    return _GossipOptimizer(
        base_optimizer, CommunicationType.allreduce, order="cta",
        num_steps_per_communication=num_steps_per_communication,
    )


def DistributedNeighborAllreduceOptimizer(base_optimizer,
                                          num_steps_per_communication=1):
    """CTA with neighbor weight gossip — the flagship decentralized
    optimizer (reference :1326; algebra comment :311-318)."""
    return _GossipOptimizer(
        base_optimizer, CommunicationType.neighbor_allreduce, order="cta",
        num_steps_per_communication=num_steps_per_communication,
    )


def DistributedHierarchicalNeighborAllreduceOptimizer(
    base_optimizer, num_steps_per_communication=1
):
    """CTA with intra-machine average + machine-level gossip
    (reference :1352)."""
    return _GossipOptimizer(
        base_optimizer,
        CommunicationType.hierarchical_neighbor_allreduce,
        order="cta",
        num_steps_per_communication=num_steps_per_communication,
    )


def DistributedAdaptThenCombineOptimizer(
    base_optimizer,
    communication_type: CommunicationType = CommunicationType.neighbor_allreduce,
    num_steps_per_communication=1,
):
    """ATC: local optax step first, then gossip the updated weights
    (reference :485-842, factory :1426 — its hand-written inner sgd/adam/
    rmsprop/adagrad/adadelta steps are any optax transformation here)."""
    return _GossipOptimizer(
        base_optimizer, communication_type, order="atc",
        num_steps_per_communication=num_steps_per_communication,
    )


def DistributedAdaptWithCombineOptimizer(
    base_optimizer,
    communication_type: CommunicationType = CommunicationType.neighbor_allreduce,
    num_steps_per_communication=1,
):
    """CTA with selectable communication (reference :1497)."""
    return _GossipOptimizer(
        base_optimizer, communication_type, order="cta",
        num_steps_per_communication=num_steps_per_communication,
    )


# -- window-based (asynchronous-algorithm) optimizers ------------------------


class _WindowOptimizer:
    """Shared engine for the win_put / pull-get / push-sum families.

    All pytree leaves are packed into ONE flat combo-vector window (shape
    ``[size, D]``), and the whole step — inner optax update, window
    exchange, combine — is ONE jitted shard_map program regardless of leaf
    count. This is the TPU answer to the reference's fusion buffer
    (``tensor_queue.h:75-124``): where the reference memcpys many small
    tensors into one MPI message, the packed lane makes the many-leaf
    window traffic a single ppermute payload, and O(1) host dispatches per
    step. Execution is step-synchronous (the buffered redesign, see
    :mod:`bluefog_tpu.windows`), preserving the reference algorithms'
    update maps (optimizers.py:844-1177) though not their wall-clock
    asynchrony (push-sum differs in iterate bookkeeping: see
    :func:`DistributedPushSumOptimizer`).
    """

    def __init__(self, base_optimizer, mode: str, window_prefix=None,
                 num_steps_per_communication: int = 1):
        self._uid = next(_opt_uid)  # compiled-step cache key component
        self._tx_version = 0
        self._tx = base_optimizer
        self.mode = mode  # 'put' | 'get' | 'push_sum'
        self.self_weight = None
        self.dst_weights = None
        self.src_weights = None
        self.force_barrier = False  # parity knob; barrier is implicit
        # Exchange every K-th step() call; intermediate calls update the
        # window value locally (reference optimizers.py:846,865-866).
        self.num_steps_per_communication = num_steps_per_communication
        self._step_count = 0
        if window_prefix is None:
            window_prefix = f"_wopt{self._uid}"
        self.prefix = window_prefix
        self._name = None  # the single combo window
        self._treedef = None
        self._leaf_shapes = None
        self._leaf_dtypes = None
        self._offsets = None
        self._pack_dtype = None
        self._enabled_p = False
        self._default_dst = None
        self._default_sw = None
        self._default_topo_v = None

    @property
    def tx(self):
        """Inner optax transformation; reassignment retraces the compiled
        step (see :class:`_GossipOptimizer`.tx)."""
        return self._tx

    @tx.setter
    def tx(self, value):
        if value is not self._tx:
            self._tx = value
            self._tx_version += 1

    # -- pack / unpack --------------------------------------------------------

    def _pack(self, leaves, size):
        return jnp.concatenate(
            [
                jnp.reshape(l, (size, -1)).astype(self._pack_dtype)
                for l in leaves
            ],
            axis=1,
        )

    def _unpack_block(self, flat):
        """[D] combo vector -> list of per-worker leaf blocks (traced)."""
        out = []
        for (start, end), shape, dtype in zip(
            self._offsets, self._leaf_shapes, self._leaf_dtypes
        ):
            out.append(flat[start:end].reshape(shape).astype(dtype))
        return out

    def init(self, params):
        """Create the combo-vector parameter window and inner state."""
        ctx = ctx_mod.get_context()
        leaves, treedef = jax.tree_util.tree_flatten(params)
        for i, l in enumerate(leaves):
            if l.ndim < 1 or l.shape[0] != ctx.size:
                raise ValueError(
                    f"window-optimizer parameter leaf {i} must be "
                    f"worker-stacked [size={ctx.size}, ...]; got shape "
                    f"{tuple(l.shape)}"
                )
            if not jnp.issubdtype(l.dtype, jnp.inexact):
                raise TypeError(
                    f"window-optimizer parameter leaf {i} has dtype "
                    f"{l.dtype}: all leaves share ONE packed float combo "
                    "window, and integer leaves would round-trip through "
                    "float on every step (silent truncation). Keep integer "
                    "state out of the optimized parameter tree."
                )
        self._treedef = treedef
        self._leaf_shapes = [tuple(l.shape[1:]) for l in leaves]
        self._leaf_dtypes = [l.dtype for l in leaves]
        self._pack_dtype = jnp.result_type(*leaves)
        sizes = [int(np.prod(s)) if s else 1 for s in self._leaf_shapes]
        bounds = np.concatenate([[0], np.cumsum(sizes)])
        self._offsets = [
            (int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])
        ]
        self._name = f"{self.prefix}.combo"
        packed = self._pack(leaves, ctx.size)
        created = win_mod.win_create(
            packed, self._name, zero_init=self.mode == "push_sum"
        )
        assert created, f"window {self._name} already exists"
        if self.mode == "push_sum":
            # refcounted: freeing one push-sum optimizer must not disable
            # the p lane under another live one; the hold is tagged with
            # the context generation so free() after shutdown/re-init
            # cannot touch a newer context's count
            self._p_ctx_uid = win_mod._acquire_associated_p()
            self._enabled_p = True
        gopt = _GossipOptimizer(
            self.tx, CommunicationType.empty, order="atc"
        )
        return gopt.init(params)

    def free(self):
        if self._name is not None:
            win_mod.win_free(self._name)
        self._name = None
        if self._enabled_p:
            win_mod._release_associated_p(self._p_ctx_uid)
            self._enabled_p = False

    def params(self):
        """Current parameter estimate held by the window."""
        ctx = ctx_mod.get_context()
        value = win_mod.win_read(self._name)
        if self.mode == "push_sum":
            p = win_mod.win_associated_p(self._name)
            value = value / jnp.asarray(p)[:, None].astype(value.dtype)
        leaves = [
            value[:, start:end]
            .reshape((ctx.size,) + shape)
            .astype(dtype)
            for (start, end), shape, dtype in zip(
                self._offsets, self._leaf_shapes, self._leaf_dtypes
            )
        ]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # -- per-mode exchange/combine configuration ------------------------------

    def _exchange_config(self, ctx, win):
        """Resolve (mode, w_edges, self_vec) for this step."""
        outs = ctx.out_neighbor_ranks()
        size = ctx.size
        if self.mode == "push_sum":
            # x and the p lane share weights: column-stochastic split over
            # self + out-neighbors (reference optimizers.py:1026-1177).
            # Defaults are cached per topology version: rebuilding dicts
            # per step is host noise.
            if self._default_topo_v != ctx.topo_version:
                self._default_dst = None
                self._default_sw = None
                self._default_topo_v = ctx.topo_version
            if self.dst_weights is not None:
                dst = self.dst_weights
            else:
                if self._default_dst is None:
                    self._default_dst = [
                        {d: 1.0 / (len(outs[r]) + 1) for d in outs[r]}
                        for r in range(size)
                    ]
                dst = self._default_dst
            sw = self.self_weight
            if sw is None:
                if self._default_sw is None:
                    self._default_sw = [
                        1.0 / (len(outs[r]) + 1) for r in range(size)
                    ]
                sw = self._default_sw
            w, participating = win_mod._per_rank_edges(
                ctx, dst, win.out_neighbors, "dst_weights"
            )
            self_vec = win_mod._self_weight_vec(ctx, sw, participating)
            return "acc", w, self_vec
        if self.mode == "put":
            w, participating = win_mod._per_rank_edges(
                ctx, self.dst_weights, win.out_neighbors, "dst_weights"
            )
            self_vec = win_mod._self_weight_vec(
                ctx, self.self_weight, participating
            )
            return "put", w, self_vec
        # 'get': receiver-keyed spec, transposed to sender-keyed edges;
        # value is never self-rescaled by a get (see win_get_nonblocking).
        w_recv, participating = win_mod._per_rank_edges(
            ctx, self.src_weights, win.in_neighbors, "src_weights"
        )
        self_vec = win_mod._self_weight_vec(
            ctx, None, np.zeros_like(participating)
        )
        return "get", w_recv.T, self_vec

    def _update_config(self, ctx, win):
        """Combine weights after the exchange: push-sum collects (sum +
        reset), put/get use the window-update default (topology weights or
        uniform), matching the unfused op sequence."""
        if self.mode == "push_sum":
            ones = [{s: 1.0 for s in srcs} for srcs in win.in_neighbors]
            self_vec, w_recv, participating = win_mod._update_weights(
                ctx, win, 1.0, ones
            )
            return self_vec, w_recv, participating, True
        self_vec, w_recv, participating = win_mod._update_weights(
            ctx, win, None, None
        )
        return self_vec, w_recv, participating, False

    def _local_step(self, ctx, win, axis, opt_state, grads):
        """A between-communication call under num_steps_per_communication:
        the inner update adapts the raw window value; no exchange, no
        combine, buffers/versions/p untouched (reference
        _DistributedWinOptimizer's delay gate, optimizers.py:866,1000)."""
        key = (
            "wopt_local_step", self._uid, self._tx_version,
        ) + _aval_key((opt_state, grads))
        fn = ctx.op_cache.get(key)
        if fn is None:
            push_sum = self.mode == "push_sum"
            tx = self._tx

            def body(value, p, s_b, g_b):
                v, pv = value[0], p[0]
                s = _tree_block(s_b)
                g = _tree_block(g_b)
                cur = jax.tree_util.tree_unflatten(
                    self._treedef, self._unpack_block(v)
                )
                updates, s = tx.update(g, s, cur)
                cur = optax.apply_updates(cur, updates)
                xb = jnp.concatenate(
                    [
                        jnp.reshape(l, (-1,)).astype(self._pack_dtype)
                        for l in jax.tree_util.tree_leaves(cur)
                    ]
                )
                est = xb / pv.astype(xb.dtype) if push_sum else xb
                out = jax.tree_util.tree_unflatten(
                    self._treedef, self._unpack_block(est)
                )
                return (
                    jnp.expand_dims(xb, 0),
                    _tree_restack(out), _tree_restack(s),
                )

            spec = P(axis)
            fn = jax.jit(
                jax.shard_map(
                    body, mesh=ctx.mesh,
                    in_specs=(spec, spec, spec, spec),
                    out_specs=(spec, spec, spec),
                )
            )
            ctx.op_cache[key] = fn
        win.value, params_out, opt_state = _timed_dispatch(
            "window_optimizer_step_local", fn,
            win.value, win.p, opt_state, grads,
        )
        # a local adapt ages the neighbor buffers by one local step
        win_mod._note_local_step(win)
        return params_out, opt_state

    # -- the fused step -------------------------------------------------------

    def step(self, opt_state, grads):
        """One window-optimizer step from gradients evaluated at
        ``self.params()``; returns (new_params_estimate, opt_state).

        ONE compiled program: unpack -> optax update -> pack -> window
        exchange (ppermute rounds) -> combine -> repack params estimate.
        """
        assert self._name is not None, "call init(params) first"
        ctx = ctx_mod.get_context()
        win = win_mod._get_win(ctx, self._name)
        axis = ctx_mod.WORKER_AXIS
        update_p = win_mod._p_enabled()
        k = int(self.num_steps_per_communication)
        if k < 1:
            raise ValueError(
                "num_steps_per_communication must be a positive int, got "
                f"{self.num_steps_per_communication!r}"
            )
        comm_now = self._step_count % k == k - 1
        self._step_count += 1
        if not comm_now:  # between exchanges: pure local adapt
            return self._local_step(ctx, win, axis, opt_state, grads)

        # Weight *content* never enters the cache key: the compiled program
        # is keyed on the communication structure and takes the resolved
        # weight vectors as replicated operands, so per-step varying
        # weights (randomized gossip, time-varying push-sum) and in-place
        # mutation of the weight knobs are both safe and compile-free.
        # The price is O(size^2) numpy work per step — deliberately paid:
        # an identity-keyed fast path would reintroduce the stale-mutation
        # hazard this design removes. Measured (pinned by
        # tests/test_windows.py::test_host_weight_resolution_cost):
        # ~0.6 ms/step at 256 workers, ~3.5 ms at 1024, default specs.
        ex_mode, w_edges, ex_self = self._exchange_config(ctx, win)
        perms, slot_table = win_mod._lowered_exchange(ctx, win, w_edges)
        up_self, up_w, up_part, reset = self._update_config(ctx, win)
        slot_w = win_mod._slot_weights(win, up_w, ctx.size)
        wire = win_mod.window_wire()

        key = (
            "wopt_fused_step", self._uid, self._tx_version, ex_mode, perms,
            tuple(map(tuple, slot_table)), reset, update_p, wire,
        ) + _aval_key((opt_state, grads))
        fn = ctx.op_cache.get(key)
        if fn is None:
            slots_const = np.asarray(slot_table, np.int32)
            push_sum = self.mode == "push_sum"
            tx = self._tx
            # locals, not the _Window: a closure over `win` would pin its
            # device arrays in op_cache past opt.free()
            max_deg = win.max_deg
            win_shape = win.shape

            def body(value, buffers, versions, p, p_buffers, s_b, g_b, wops):
                (
                    ex_recv_w, ex_self_w, ex_sent_w,
                    up_self_w, up_slot_w, up_part_arr,
                ) = wops
                v, bufs, vers = value[0], buffers[0], versions[0]
                pv, pbufs = p[0], p_buffers[0]
                s = _tree_block(s_b)
                g = _tree_block(g_b)
                # inner update on the window's current (raw) iterate
                cur = jax.tree_util.tree_unflatten(
                    self._treedef, self._unpack_block(v)
                )
                updates, s = tx.update(g, s, cur)
                cur = optax.apply_updates(cur, updates)
                xb = jnp.concatenate(
                    [
                        jnp.reshape(l, (-1,)).astype(self._pack_dtype)
                        for l in jax.tree_util.tree_leaves(cur)
                    ]
                )
                # adopt the adapted x, then exchange + combine
                v, bufs, vers, pv, pbufs = win_mod._exchange_core(
                    axis, ex_mode, perms, slots_const, update_p,
                    max_deg, win_shape,
                    xb, bufs, vers, pv, pbufs, xb, ex_recv_w, ex_self_w,
                    wire=wire, sent_w=ex_sent_w,
                )
                v, bufs, vers, pv, pbufs = win_mod._update_core(
                    axis, reset, update_p, max_deg,
                    v, bufs, vers, pv, pbufs,
                    up_self_w, up_slot_w, up_part_arr,
                )
                est = v / pv.astype(v.dtype) if push_sum else v
                out_leaves = self._unpack_block(est)
                params_out = jax.tree_util.tree_unflatten(
                    self._treedef, out_leaves
                )
                expand = lambda t: jnp.expand_dims(t, 0)
                return (
                    expand(v), expand(bufs), expand(vers),
                    expand(pv), expand(pbufs),
                    _tree_restack(params_out), _tree_restack(s),
                )

            spec = P(axis)
            fn = jax.jit(
                jax.shard_map(
                    body, mesh=ctx.mesh,
                    in_specs=(spec,) * 7 + (P(),), out_specs=(spec,) * 7,
                )
            )
            ctx.op_cache[key] = fn
        wops = (
            jnp.asarray(win_mod._round_weights(perms, w_edges)),
            jnp.asarray(np.asarray(ex_self, np.float64)),
            jnp.asarray(np.asarray(w_edges.sum(axis=1), np.float64)),
            jnp.asarray(np.asarray(up_self, np.float64)),
            jnp.asarray(np.asarray(slot_w, np.float64)),
            jnp.asarray(up_part, bool),
        )
        (
            win.value, win.buffers, win.versions, win.p, win.p_buffers,
            params_out, opt_state,
        ) = _timed_dispatch(
            "window_optimizer_step", fn,
            win.value, win.buffers, win.versions, win.p, win.p_buffers,
            opt_state, grads, wops,
        )
        # age lane: ONE dispatched program = one local step (exchange +
        # combine fused), so the update note applies collect semantics
        # without a second clock tick
        win_mod._note_exchange_age(win, slot_table, ex_mode)
        win_mod._note_update_age(win, up_part, reset, tick=False)
        staleness_mod.observe_window(
            ctx, win, step=self._step_count - 1
        )
        return params_out, opt_state


def DistributedWinPutOptimizer(base_optimizer, window_prefix=None,
                               num_steps_per_communication=1):
    """Diffusion by pushing updated weights into neighbor buffers
    (reference :1271, engine :844-1023)."""
    return _WindowOptimizer(
        base_optimizer, mode="put", window_prefix=window_prefix,
        num_steps_per_communication=num_steps_per_communication,
    )


def DistributedPullGetOptimizer(base_optimizer, window_prefix=None,
                                num_steps_per_communication=1):
    """Diffusion by pulling neighbors' current weights (reference :1225)."""
    return _WindowOptimizer(
        base_optimizer, mode="get", window_prefix=window_prefix,
        num_steps_per_communication=num_steps_per_communication,
    )


def DistributedPushSumOptimizer(base_optimizer, window_prefix=None,
                                num_steps_per_communication=1):
    """Push-sum (directed-graph) asynchronous SGD: sender-stochastic
    win_accumulate of (x, p) with the x/p correction (reference :1180,
    engine :1026-1177).

    Iterate bookkeeping departs deliberately from the reference: this is
    the textbook accumulated-p recursion (push raw x, never reset p),
    where the reference pushes the corrected iterate and resets its
    ps-weight to 1 every round. On weight-balanced digraphs (ring, Exp2 —
    every uniform-weight regular graph) the two recursions are provably
    identical step for step; on non-balanced digraphs they diverge at
    step 2, and the accumulated-p form is the one that preserves
    push-sum's exact-average guarantee. The committed numpy oracle for
    both recursions, the sequence-equality proof, and the divergence pin
    live in ``tests/test_pushsum_oracle.py``."""
    return _WindowOptimizer(
        base_optimizer, mode="push_sum", window_prefix=window_prefix,
        num_steps_per_communication=num_steps_per_communication,
    )
