# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Fleet health plane (``bf.health``): online mixing-rate observatory,
in-band push-sum fleet aggregation, and live ``/healthz`` serving.

The repo measures *wall-clock* health (:mod:`bluefog_tpu.metrics` counts
what moved, :mod:`bluefog_tpu.attribution` attributes where time went)
— but never checks the *algorithmic* contract the paper rests on: that
neighbor averaging over the active graph contracts the consensus error
at the rate the graph's spectral gap promises. This module closes that
gap and gives every rank a live fleet-wide view without a central
collector. Three parts:

**(a) Mixing observatory.** Host-side spectral analysis of the active
combine matrix, cached per ``(topo_version, live_token)``: SLEM of a
static :class:`~bluefog_tpu.collective.plan.CommPlan`'s weight matrix,
the period-product rate of a dynamic
:class:`~bluefog_tpu.collective.plan.SchedulePlan`, the post-repair
matrix after an elastic membership change (the repaired plan simply
arrives under a new topo_version) — all through
:func:`bluefog_tpu.topology.consensus_decay_rate`. The *predicted*
per-round decay is compared online against the *measured* decay fitted
over the sampled consensus-distance series (the PR-3 sub-gossip
``bluefog.gossip.disagreement`` gauge, or a directly fed series for the
eager path), yielding a **mixing-efficiency ratio**
(``ln(measured)/ln(predicted)``: 1.0 = the fabric delivers what the
spectrum promises, < 1 = it lags), a **time-to-consensus-ε projection**,
and a ``mixing_degraded`` advisory — routed through the PR-7 advisory
plumbing (``bluefog.doctor.*`` metrics, flight side table, timeline
instants) — when measured decay falls beyond the EWMA+MAD baseline of
its own efficiency history. Localization joins the detection with the
chaos layer's active degrade faults and the attribution doctor's
``degraded_link`` edges: the observatory proves the contract is broken,
the wire probes name the link.

**(b) In-band aggregation.** Each rank's scalar health summary
(step-time EWMA, consensus distance, wire bytes/step, advisory count,
live-set digest) is aggregated fleet-wide min/mean/max over the gossip
fabric itself: a tiny push-sum side lane (:func:`fleet_aggregate`) —
sum and weight lanes under a sender-mass-conserving row-normalized
push matrix derived from the active combine, min/max lanes via masked
neighbor-min gossip — compiled over the SAME ppermute fabric the
training gossip uses (no coordinator; a dead rank's mass simply drops
out of the repaired plan, so the estimate converges to the live-set
aggregate). The lane is a *separate* tiny dispatch on sampled steps
only: the training program is untouched, so unsampled steps dispatch
the bitwise-identical health-off program under the same cache key —
the PR-3/PR-7 sampling discipline, re-proven by ``BENCH_MODE=health``.

**(c) Serving surface.** ``BLUEFOG_HEALTH_PORT`` starts a per-rank
stdlib HTTP endpoint: ``/healthz`` (RAG verdict from advisory recency +
elastic liveness; 200 on ok/warn, 503 on critical — load-balancer
ready), ``/metrics`` (live Prometheus scrape via
:func:`bluefog_tpu.metrics.prom_lines`, complementing the textfile
exporter), ``/fleet`` (the in-band aggregate as JSON). A port conflict
logs a warning and serves nothing — never kills training.
``tools/fleet_report.py`` renders one fleet table from N ranks'
artifacts or live endpoints.

Env knobs: ``BLUEFOG_HEALTH=1`` enables the observatory (default off),
``BLUEFOG_HEALTH_INTERVAL`` (sampling period in communicating steps,
default 20), ``BLUEFOG_HEALTH_PORT`` (serve; 0/unset = off),
``BLUEFOG_HEALTH_ROUNDS`` (push-sum applications per sample; 0
disables the lane, unset = auto from the predicted rate),
``BLUEFOG_HEALTH_EPS`` (consensus target for the time-to-ε projection,
default 1e-6), ``BLUEFOG_HEALTH_FILE`` (JSONL samples + advisories).
See docs/health.md.
"""

import collections
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "HealthPlane",
    "HealthServer",
    "enabled",
    "health_interval",
    "health_port",
    "health_eps",
    "fit_decay_rate",
    "mixing_efficiency",
    "time_to_consensus_steps",
    "push_matrix",
    "fleet_aggregate_np",
    "fleet_aggregate",
    "healthz_verdict",
    "FLEET_FIELDS",
    "start",
    "stop",
    "activate",
    "active",
    "observe_step",
    "serve",
    "server",
    "dump",
    "on_init",
    "on_shutdown",
]

ENABLE_ENV = "BLUEFOG_HEALTH"
INTERVAL_ENV = "BLUEFOG_HEALTH_INTERVAL"
PORT_ENV = "BLUEFOG_HEALTH_PORT"
ROUNDS_ENV = "BLUEFOG_HEALTH_ROUNDS"
EPS_ENV = "BLUEFOG_HEALTH_EPS"
FILE_ENV = "BLUEFOG_HEALTH_FILE"

# mixing_degraded gate: efficiency this fraction below its EWMA
# baseline AND a -3 MAD z-score, for MIXING_STREAK consecutive samples
# (one bad fit on a noisy series is jitter, not degradation — the
# ambient_drift discipline applied to the algorithmic contract).
# Calibration note: fully dropping ONE directed edge of an 8-ring only
# costs ~24 % of the promised contraction (SLEM 0.805 -> 0.844), so a
# deeper gate would be blind to exactly the single-flaky-link failure
# this advisory exists for; the z-score + streak carry the
# false-positive burden.
MIXING_DEGRADED_FRAC = 0.10
MIXING_STREAK = 2
# decay-rate fit: least-squares over the last FIT_WINDOW sampled
# (step, distance) points of the CURRENT topology version; fewer than
# MIN_FIT_POINTS points (or distances at the fp noise floor) = no fit.
FIT_WINDOW = 8
MIN_FIT_POINTS = 4
DIST_FLOOR = 1e-12
# advisory recency window for the /healthz verdict, in samples — MUST
# exceed the mixing_degraded re-fire cooldown (FIT_WINDOW samples), or
# a persistently degraded fabric would flap ok/warn between re-fires
VERDICT_RECENT_SAMPLES = 20

# The per-rank health summary vector the push-sum lane aggregates.
FLEET_FIELDS = (
    "step_ms",             # step-time EWMA at this rank
    "consensus",           # per-worker consensus distance (PR-3 drain)
    "wire_bytes_per_step", # wire bytes per communicating step
    "advisories",          # advisories on record (health + doctor)
    "live_digest",         # digest of the believed live set
    "stale_age_max",       # worst delivered parameter age on record
    #                        (bluefog_tpu.staleness; 0 when the
    #                        observatory is off) — fleet-wide
    #                        min/mean/max age rides the same lane
    "mem_bytes_per_rank",  # measured per-chip memory footprint
    #                        (bluefog_tpu.memory census; 0 when the
    #                        observatory is off)
    "mem_headroom",        # budget minus footprint (0 when no
    #                        BLUEFOG_MEMORY_BUDGET is configured) —
    #                        the fleet min is the chip closest to OOM
    "slo_burn",            # worst fast-window SLO burn rate at this
    #                        rank (bluefog_tpu.slo; 0 when the engine
    #                        is off) — the fleet MAX is the rank
    #                        burning its error budget fastest
)


# Non-finite sanitizer for the HTTP endpoints (the JSONL exporters get
# it through logging_util.append_jsonl): a NaN step EWMA before warmup
# must reach the scraper as null, never as a bare NaN token.
from bluefog_tpu.logging_util import json_safe as _json_safe  # noqa: E402


def enabled() -> bool:
    """Observatory switch: ``BLUEFOG_HEALTH=1`` (default off). Like the
    metrics device tier and the doctor, the health plane is opt-in;
    the serving surface additionally needs ``BLUEFOG_HEALTH_PORT``."""
    return os.environ.get(ENABLE_ENV, "0").lower() in (
        "1", "true", "on", "yes",
    )


def health_interval() -> int:
    """Sampling period in communicating steps
    (``BLUEFOG_HEALTH_INTERVAL``, default 20 — twice the metrics drain
    period, so the consensus gauge has refreshed between health
    samples). A sample is host arithmetic plus one tiny push-sum lane
    dispatch; the default keeps the amortized cost under the 1 %
    acceptance bound re-measured by ``BENCH_MODE=health``."""
    from bluefog_tpu.logging_util import env_int

    return max(1, env_int(INTERVAL_ENV, 20))


def health_port() -> int:
    """``BLUEFOG_HEALTH_PORT`` (0/unset = no serving)."""
    from bluefog_tpu.logging_util import env_int

    return env_int(PORT_ENV, 0)


def health_eps() -> float:
    """Consensus target for the time-to-ε projection
    (``BLUEFOG_HEALTH_EPS``, default 1e-6)."""
    from bluefog_tpu.logging_util import env_float

    return env_float(EPS_ENV, 1e-6)


# -- measured-decay estimation ------------------------------------------------


def fit_decay_rate(
    points: Sequence[Tuple[float, float]]
) -> Optional[float]:
    """Per-step consensus decay rate fitted over sampled ``(comm_step,
    distance)`` points: ``exp`` of the least-squares slope of ``ln d``
    against the step index. Returns None with fewer than
    :data:`MIN_FIT_POINTS` usable points (distances at or under the fp
    noise floor are dropped — a series that has *reached* consensus
    carries no rate information). A returned rate >= 1 means the series
    is not decaying; callers map that to efficiency 0, not an error."""
    usable = [
        (float(s), math.log(float(d)))
        for s, d in points if d is not None and d > DIST_FLOOR
    ]
    if len(usable) < MIN_FIT_POINTS:
        return None
    xs = np.array([s for s, _ in usable])
    ys = np.array([y for _, y in usable])
    if float(xs.max() - xs.min()) <= 0:
        return None
    slope = float(np.polyfit(xs, ys, 1)[0])
    # guard against overflow on a wildly diverging series
    return float(math.exp(min(slope, 50.0)))


def mixing_efficiency(
    measured: Optional[float], predicted: Optional[float]
) -> Optional[float]:
    """``ln(measured) / ln(predicted)``: the fraction of the spectrally
    promised per-step contraction the fabric actually delivers. 1.0 =
    on contract, < 1 = lagging, 0 = not decaying at all; None when
    either rate is unavailable or the matrix promises nothing
    (predicted SLEM ~ 1: a disconnected or non-mixing graph)."""
    if measured is None or predicted is None:
        return None
    if predicted >= 1.0 - 1e-9 or predicted <= 0.0:
        return None
    if measured >= 1.0:
        return 0.0
    eff = math.log(max(measured, 1e-300)) / math.log(predicted)
    return float(eff)


def time_to_consensus_steps(
    distance: Optional[float], rate: Optional[float],
    eps: Optional[float] = None,
) -> Optional[float]:
    """Projected communicating steps until the consensus distance
    reaches ``eps`` at the given per-step decay rate (None when the
    series is not decaying or the distance is unknown; 0 when already
    there)."""
    eps = health_eps() if eps is None else float(eps)
    if distance is None or rate is None or not 0.0 < rate < 1.0:
        return None
    if distance <= eps:
        return 0.0
    return float(math.log(eps / distance) / math.log(rate))


# -- in-band push-sum aggregation ---------------------------------------------


def push_matrix(
    w: np.ndarray, dead: Sequence[int] = ()
) -> np.ndarray:
    """Sender-mass-conserving push matrix from a combine matrix ``W``:
    dead ranks' rows and columns are zeroed, then every live sender's
    row (self weight + out-edge weights) is normalized to sum 1 —
    column-stochastic in the (sender -> receiver) sense, so
    ``sum_j x'_j == sum_i x_i`` exactly and the push-sum ratio
    estimates the *live-set* mean. A live sender left with no mass
    (isolated by the pruning) keeps everything: ``P[i, i] = 1``."""
    w = np.asarray(w, np.float64).copy()
    dead = set(int(r) for r in dead)
    for r in dead:
        w[r, :] = 0.0
        w[:, r] = 0.0
    p = np.zeros_like(w)
    n = w.shape[0]
    for i in range(n):
        if i in dead:
            continue
        row = w[i]
        s = float(row.sum())
        if s <= 0.0:
            p[i, i] = 1.0
        else:
            p[i] = row / s
    return p


def _fleet_estimates(x, p, mn, mx, live) -> dict:
    """Fold lane outputs into the per-rank report: each live rank's
    mean estimate is ``x/p``; the published aggregate is the average of
    the live estimates with the worst-rank deviation disclosed as
    ``residual`` (push-sum converges geometrically — the residual IS
    the honesty metric for a finite-round lane)."""
    live = list(live)
    est = np.array([x[j] / max(p[j], 1e-12) for j in live])
    mean = est.mean(axis=0)
    denom = np.maximum(np.abs(mean), 1e-12)
    residual = float(
        np.max(np.abs(est - mean[None, :]) / denom[None, :])
    ) if len(live) else 0.0
    mn_f = np.min(np.stack([mn[j] for j in live]), axis=0)
    mx_f = np.max(np.stack([mx[j] for j in live]), axis=0)
    return {
        "mean": [float(v) for v in mean],
        "min": [float(v) for v in mn_f],
        "max": [float(v) for v in mx_f],
        "per_rank_mean": {int(j): [float(v) for v in est[k]]
                          for k, j in enumerate(live)},
        "residual": residual,
        "live": [int(j) for j in live],
    }


def fleet_aggregate_np(
    w: np.ndarray,
    values: np.ndarray,
    rounds: int,
    dead: Sequence[int] = (),
) -> dict:
    """Numpy reference of the device lane, same per-application
    semantics: ``rounds`` synchronous applications of (sum lanes
    ``x <- P^T x``, ``p <- P^T p``; min/max lanes one neighbor-min/max
    over the application-start snapshot). The oracle
    ``tests/test_health.py`` pins :func:`fleet_aggregate` against."""
    values = np.asarray(values, np.float64)
    n, k = values.shape
    dead = set(int(r) for r in dead)
    live = [j for j in range(n) if j not in dead]
    p_mat = push_matrix(w, dead)
    in_nbrs = [
        [i for i in range(n) if i != j and p_mat[i, j] > 0.0]
        for j in range(n)
    ]
    x = values.copy()
    p = np.ones(n)
    mn = values.copy()
    mx = values.copy()
    for r in dead:
        x[r] = 0.0
        p[r] = 0.0
        mn[r] = np.inf
        mx[r] = -np.inf
    for _ in range(rounds):
        x = p_mat.T @ x
        p = p_mat.T @ p
        mn0, mx0 = mn.copy(), mx.copy()
        for j in range(n):
            for i in in_nbrs[j]:
                mn[j] = np.minimum(mn[j], mn0[i])
                mx[j] = np.maximum(mx[j], mx0[i])
    return _fleet_estimates(x, p, mn, mx, live)


def _lane_operands(w: np.ndarray, dead: Sequence[int]):
    """Push plan + operands for the lane program — the ONE wire format
    both the one-shot (oracle-pinned) and streaming paths compile
    against: ``(perms, self_w, recv_w, destination mask)``."""
    from bluefog_tpu.collective import plan as plan_mod

    p_mat = push_matrix(w, dead)
    lane_plan = plan_mod.plan_from_matrix(p_mat)
    self_w, recv_w = lane_plan.weight_operands()
    dmask = (recv_w > 0.0).astype(np.float32)
    return lane_plan.perms, self_w, recv_w, dmask


def _seed_state(values32: np.ndarray, dead: Sequence[int],
                k: int) -> np.ndarray:
    """The lane buffer ``[x (k) | p (1) | min (k) | max (k)]`` seeded
    from per-rank values, dead ranks masked (zero mass, ±inf extrema)
    — shared by both lane paths so the oracle pin covers the streaming
    seed layout too."""
    size = values32.shape[0]
    st = np.zeros((size, 3 * k + 1), np.float32)
    st[:, :k] = values32
    st[:, k] = 1.0
    _reseed_minmax(st, values32, dead, k)
    for r in dead:
        st[r, : k + 1] = 0.0
    return st


def _reseed_minmax(st: np.ndarray, values32: np.ndarray,
                   dead: Sequence[int], k: int) -> None:
    """Reset the min/max lanes to current values (generation start)."""
    st[:, k + 1: 2 * k + 1] = values32
    st[:, 2 * k + 1:] = values32
    for r in dead:
        st[r, k + 1: 2 * k + 1] = np.inf
        st[r, 2 * k + 1:] = -np.inf


def _auto_rounds(size: int, predicted_rate: Optional[float]) -> int:
    """Push-sum applications per sample: enough that the mean estimate
    lands within ~1 % (``rho^R <= 0.01``) and the min/max gossip covers
    any strongly-connected diameter, clamped to a fixed budget."""
    env = os.environ.get(ROUNDS_ENV)
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    rho = predicted_rate if predicted_rate and 0 < predicted_rate < 1 \
        else 0.5
    need = math.log(0.01) / math.log(rho)
    return int(max(4, min(32, max(need, size))))


def _lane_program(ctx, perms, n_apps: int, k: int):
    """Compiled push-sum lane: ``n_apps`` applications of the plan's
    ppermute rounds on a ``[size, 3k+1]`` buffer (sum lanes x|p via the
    weighted combine with weights as operands, min/max lanes via masked
    neighbor gossip over the application-start snapshot). Cached in the
    context op cache under its own ``health_pushsum`` family — training
    cache keys are untouched, which is what keeps the health plane's
    bitwise no-op trivially true."""
    key = ("health_pushsum", perms, n_apps, k)
    fn = ctx.op_cache.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from bluefog_tpu import context as ctx_mod
        from bluefog_tpu.collective import inner

        axis = ctx_mod.WORKER_AXIS
        n_rounds = len(perms)

        def body(v, self_w, recv_w, dmask):
            x = v[:, : k + 1]           # sum lanes: k fields + mass p
            mn = v[:, k + 1: 2 * k + 1]
            mx = v[:, 2 * k + 1:]
            idx = lax.axis_index(axis)
            for _ in range(n_apps):
                x = inner.weighted_combine_operands(
                    x, perms, self_w[0], recv_w[0], axis
                )
                mn0, mx0 = mn, mx
                for r in range(n_rounds):
                    m = dmask[0][r, idx] > 0
                    rmn = lax.ppermute(mn0, axis, perms[r])
                    rmx = lax.ppermute(mx0, axis, perms[r])
                    mn = jnp.minimum(
                        mn, jnp.where(m, rmn, jnp.inf)
                    )
                    mx = jnp.maximum(
                        mx, jnp.where(m, rmx, -jnp.inf)
                    )
            return jnp.concatenate([x, mn, mx], axis=1)

        fn = jax.jit(
            jax.shard_map(
                body,
                mesh=ctx.mesh,
                in_specs=(P(ctx_mod.WORKER_AXIS), P(), P(), P()),
                out_specs=P(ctx_mod.WORKER_AXIS),
            )
        )
        ctx.op_cache[key] = fn
    return fn


def fleet_aggregate(
    ctx,
    values: np.ndarray,
    rounds: Optional[int] = None,
    w: Optional[np.ndarray] = None,
    dead: Sequence[int] = (),
    predicted_rate: Optional[float] = None,
) -> dict:
    """Aggregate a ``[size, K]`` per-rank summary fleet-wide min / mean
    / max over the gossip fabric itself — the in-band lane.

    ``w`` defaults to the active topology's combine matrix; ``dead``
    defaults to the elastic membership's dead set when a session is
    live. Oracle-pinned against :func:`fleet_aggregate_np`."""
    import jax

    values = np.asarray(values, np.float64)
    size, k = values.shape
    if w is None:
        from bluefog_tpu import topology as topo_mod

        w = topo_mod.mixing_matrix(ctx.load_topology())
    if not dead:
        membership = getattr(ctx, "elastic_membership", None)
        if membership is not None:
            dead = list(membership.dead_ranks())
    dead = [int(r) for r in dead]
    live = [j for j in range(size) if j not in dead]
    if rounds is None:
        rounds = _auto_rounds(len(live), predicted_rate)
    if rounds <= 0 or not live:
        return _fleet_estimates(
            values.copy(), np.ones(size),
            values.copy(), values.copy(), live or range(size),
        )
    perms, self_w, recv_w, dmask = _lane_operands(w, dead)
    seed = _seed_state(values.astype(np.float32), dead, k)
    fn = _lane_program(ctx, perms, int(rounds), k)
    out = np.asarray(jax.device_get(fn(
        seed,
        self_w[None, :],
        recv_w[None, :, :],
        dmask[None, :, :],
    )), np.float64)
    x = out[:, :k]
    p = out[:, k]
    mn = out[:, k + 1: 2 * k + 1]
    mx = out[:, 2 * k + 1:]
    rep = _fleet_estimates(x, p, mn, mx, live)
    rep["rounds"] = int(rounds)
    return rep


# -- the health plane session -------------------------------------------------


class HealthPlane:
    """One observatory session. Built by :func:`start` (or implicitly
    by ``bf.init()`` under ``BLUEFOG_HEALTH=1``); fed by the optimizer
    layer through :func:`observe_step` on every communicating step, or
    directly (``plane.observe(ctx, step=..., consensus=...)``) by the
    eager path."""

    def __init__(self, interval: Optional[int] = None,
                 eps: Optional[float] = None, history: int = 512):
        from bluefog_tpu import attribution

        self.interval = int(interval) if interval else health_interval()
        self.eps = float(eps) if eps is not None else health_eps()
        self._count = 0
        # guards the sample history against the serving thread:
        # list(deque) while the training thread appends (and maxlen
        # evicts) raises "deque mutated during iteration", turning
        # /fleet scrapes into spurious 500s exactly on sampled steps
        self._report_lock = threading.Lock()
        self.samples: collections.deque = collections.deque(
            maxlen=history
        )
        self.advisories: List[Any] = []
        # comm-step count at each emit, parallel to ``advisories``: the
        # /healthz recency window compares THIS clock, not the caller's
        # ``step`` (which counts non-communicating accumulation steps
        # too under K>1 gradient accumulation)
        self.advisory_marks: List[int] = []
        self._eff_tracker = attribution.BaselineTracker()
        self._mix_streak = 0
        self._mix_cooldown = 0
        self._oob_streak = 0
        # decay points of the CURRENT topology version only: a repair /
        # topology swap changes the predicted rate, and a fit across
        # the seam would blame the new graph for the old one's series
        self._decay_points: collections.deque = collections.deque(
            maxlen=FIT_WINDOW
        )
        self._decay_topo_v: Optional[int] = None
        self._spectral_cache: Dict[Any, Tuple[Optional[float], dict]] = {}
        self._last_sample_wall: Optional[float] = None
        self._last_sample_count = 0
        self._step_ewma_ms: Optional[float] = None
        self._last_wire_bytes: Optional[float] = None
        self._last_wire_steps = 0
        self._wire_per_step: float = 0.0
        self.fleet: Optional[dict] = None
        # streaming push-sum lane state (one application per sample;
        # the dispatched application is retrieved at the NEXT sample —
        # the metrics deferred-drain discipline, so the sampled step
        # never blocks on the device)
        self._lane_cache: Optional[tuple] = None
        self._lane_state = None
        self._lane_pending = None
        self._lane_prev = None
        self._lane_age = 0
        self._published_mm: Optional[tuple] = None

    # -- spectral side --------------------------------------------------------

    def predicted_rate(self, ctx, plan=None) -> Tuple[Optional[float], dict]:
        """Predicted per-round consensus decay of the ACTIVE combine,
        cached per ``(topo_version, live_token)``. Source of truth is
        the optimizer's dispatched plan when given (static CommPlan,
        dynamic SchedulePlan period product, post-repair plans — all
        carry their effective weight matrix); the declared topology
        otherwise."""
        from bluefog_tpu import topology as topo_mod
        from bluefog_tpu.collective.plan import CommPlan, SchedulePlan

        # the plan SOURCE is part of the key: a direct-fed observation
        # (plan=None, declared-topology SLEM) and an optimizer sample
        # (dynamic period product) under the same topo_version are
        # different predictions — the first caller must not freeze the
        # wrong one for the whole version
        source = (
            "schedule" if isinstance(plan, SchedulePlan)
            else "plan" if isinstance(plan, CommPlan)
            else "topology"
        )
        key = (ctx.topo_version, ctx.live_token(), source)
        hit = self._spectral_cache.get(key)
        if hit is not None:
            return hit
        kind = "topology"
        if isinstance(plan, SchedulePlan):
            mats = [p.weight_matrix() for p in plan.plans]
            rate, spec = topo_mod.consensus_decay_rate_info(mats)
            kind = f"schedule(period={len(mats)})"
            self_w = float(np.mean([np.mean(np.diag(m)) for m in mats]))
        elif isinstance(plan, CommPlan):
            w = plan.weight_matrix()
            rate, spec = topo_mod.consensus_decay_rate_info(w)
            kind = "plan"
            self_w = float(np.mean(np.diag(w)))
        else:
            w = topo_mod.mixing_matrix(ctx.load_topology())
            rate, spec = topo_mod.consensus_decay_rate_info(w)
            self_w = float(np.mean(np.diag(w)))
        # mean self weight of the active combine: the `s` of the
        # stale-mixing companion polynomial the age-discounted
        # prediction solves (bluefog_tpu.staleness.age_adjusted_rate).
        # `spectral` discloses how the number was obtained (dense oracle
        # vs deflated Arnoldi over edge lists) with its convergence
        # residual — the honesty field for fleet-scale predictions.
        meta = {"kind": kind, "slem": float(rate),
                "self_weight": self_w,
                "spectral": {
                    "engine": spec.get("engine"),
                    "matvecs": spec.get("matvecs", 0),
                    "residual": spec.get("residual", 0.0),
                    "converged": spec.get("converged", True),
                }}
        if rate >= 1.0 - 1e-9:
            # no contraction promised (disconnected / periodic):
            # publish "no prediction" rather than a vacuous 1.0
            out = (None, meta)
        else:
            out = (float(rate), meta)
        self._spectral_cache[key] = out
        return out

    # -- suspects join --------------------------------------------------------

    @staticmethod
    def _suspect_edges() -> List[Any]:
        """Edges/ranks to name in a ``mixing_degraded`` advisory: the
        shared fabric-health join (:func:`bluefog_tpu.attribution.
        suspect_join` — chaos degrade faults + recent
        ``degraded_link`` edges). The observatory detects the broken
        contract; the wire layers localize it."""
        from bluefog_tpu.attribution import suspect_join

        return suspect_join()

    # -- observation ----------------------------------------------------------

    def observe(self, ctx, *, step: int, plan=None,
                consensus: Optional[float] = None) -> Optional[dict]:
        """Called once per communicating step. Unsampled steps cost one
        compare + one increment; the sampled step runs the observatory
        pass, the push-sum lane, and the serving-state refresh."""
        sampled = self._count % self.interval == 0
        self._count += 1
        if not sampled:
            return None
        return self._sample(ctx, step=step, plan=plan,
                            consensus=consensus)

    def _read_consensus(self) -> Optional[float]:
        from bluefog_tpu import metrics as metrics_mod

        g = metrics_mod.peek("bluefog.gossip.disagreement")
        return float(g.value) if g is not None else None

    def _read_wire_rate(self, steps_elapsed: int) -> float:
        from bluefog_tpu import metrics as metrics_mod

        c = metrics_mod.peek("bluefog.wire_bytes")
        cur = float(c.value) if c is not None else None
        if cur is not None and self._last_wire_bytes is not None \
                and steps_elapsed > 0:
            self._wire_per_step = (
                (cur - self._last_wire_bytes) / steps_elapsed
            )
        self._last_wire_bytes = cur
        return self._wire_per_step

    def _doctor_advisory_count(self) -> int:
        try:
            from bluefog_tpu import attribution

            doc = attribution.active()
            return len(doc.advisories) if doc is not None else 0
        except Exception:
            return 0

    def _live_set(self, ctx) -> Tuple[List[int], List[int]]:
        membership = getattr(ctx, "elastic_membership", None)
        if membership is None:
            return list(range(ctx.size)), []
        return (list(membership.live_ranks()),
                list(membership.dead_ranks()))

    def _local_vector(self, ctx, consensus, live) -> np.ndarray:
        """[size, K] per-rank summary the lane aggregates. Per-worker
        consensus comes from the PR-3 drain's worker rows when the
        device tier is on; host-wide scalars (step EWMA, wire rate,
        advisory count, live digest) replicate across the ranks this
        controller owns — on a multi-controller fleet each process
        contributes its own."""
        from bluefog_tpu import metrics as metrics_mod

        size = ctx.size
        vec = np.zeros((size, len(FLEET_FIELDS)))
        vec[:, 0] = self._step_ewma_ms or 0.0
        rows = metrics_mod.last_worker_rows()
        per_worker = rows.get("bluefog.gossip.disagreement")
        if per_worker is not None and len(per_worker) == size:
            vec[:, 1] = np.asarray(per_worker)
        elif consensus is not None:
            vec[:, 1] = consensus
        vec[:, 2] = self._wire_per_step
        vec[:, 3] = len(self.advisories) + self._doctor_advisory_count()
        digest = float(
            sum((j + 1) * 31 ** i for i, j in enumerate(sorted(live)))
            % 1_000_003
        )
        vec[:, 4] = digest
        vec[:, 5] = self._staleness_age_max()
        mem_bytes, mem_headroom = self._memory_fields()
        vec[:, 6] = mem_bytes
        vec[:, 7] = mem_headroom
        vec[:, 8] = self._slo_burn()
        return vec

    @staticmethod
    def _memory_fields() -> Tuple[float, float]:
        """This controller's measured per-chip footprint and headroom
        ((0.0, 0.0) when the memory observatory is off) — aggregated
        fleet-wide min/mean/max over the push-sum lane: the fleet MIN
        headroom is the chip closest to OOM."""
        try:
            from bluefog_tpu import memory as mem_mod

            obs = mem_mod.active()
            if obs is None:
                return 0.0, 0.0
            return (float(obs.last_bytes_per_rank()),
                    float(obs.last_headroom()))
        except Exception:
            return 0.0, 0.0

    @staticmethod
    def _slo_burn() -> float:
        """Worst fast-window burn rate this controller's SLO engine
        reports (0.0 when the engine is off) — aggregated fleet-wide
        min/mean/max over the push-sum lane: the fleet MAX names the
        rank burning its error budget fastest."""
        try:
            from bluefog_tpu import slo as slo_mod

            return float(slo_mod.worst_burn())
        except Exception:
            return 0.0

    @staticmethod
    def _staleness_age_max() -> float:
        """Worst delivered parameter age this controller has measured
        (0.0 when the staleness observatory is off) — aggregated
        fleet-wide min/mean/max over the push-sum lane."""
        try:
            from bluefog_tpu import staleness as stal_mod

            obs = stal_mod.active()
            return float(obs.last_age_max()) if obs is not None else 0.0
        except Exception:
            return 0.0

    def _fleet_step(self, ctx, values: np.ndarray,
                    dead: Sequence[int],
                    predicted: Optional[float]) -> dict:
        """One STREAMING push-sum application — the sampled-step form
        of :func:`fleet_aggregate` whose cost fits the 1 % budget.

        The lane state persists on the host between samples; each
        sample injects the summary *delta* into the sum lanes
        (``sum(x)`` stays equal to the current fleet total, so ``x/p``
        tracks the live mean with geometric forgetting) and dispatches
        ONE application of the push plan — ~3 ppermutes instead of a
        full fresh convergence per sample — retrieved at the NEXT
        sample (deferred-drain discipline: a synchronous device_get
        here was measured riding the CPU collective rendezvous for
        whole milliseconds under load). Min/max gossip cannot
        forget, so those lanes run in *generations*: reseeded from
        current values every ``generation_len`` samples, with the last
        COMPLETED generation published (staleness <= 2 generations,
        ``warming`` flagged until the first completes). A topology or
        membership change rebuilds the plan and reseeds everything —
        a dead rank's mass drops out with its edges."""
        import jax

        from bluefog_tpu import topology as topo_mod

        size, k = values.shape
        dead = [int(r) for r in dead]
        live = [j for j in range(size) if j not in set(dead)]
        key = (ctx.topo_version, ctx.live_token(), k)
        if self._lane_cache is None or self._lane_cache[0] != key:
            w = topo_mod.mixing_matrix(ctx.load_topology())
            perms, self_w, recv_w, dmask = _lane_operands(w, dead)
            fn = _lane_program(ctx, perms, 1, k)
            self._lane_cache = (
                key, fn, self_w[None, :], recv_w[None, :, :],
                dmask[None, :, :],
            )
            self._lane_state = None
            self._lane_pending = None  # old plan's in-flight result
        _key, fn, self_w, recv_w, dmask = self._lane_cache
        if self._lane_pending is not None:
            # the PREVIOUS sample's application: dispatched a whole
            # sample interval ago, so this read is a completed-copy
            # pickup, not a sync barrier (np.array, not asarray — the
            # delta injection below writes in place)
            self._lane_state = np.array(
                jax.device_get(self._lane_pending), np.float32
            )
            self._lane_pending = None
        gen_len = _auto_rounds(len(live), predicted)
        st = self._lane_state
        values32 = values.astype(np.float32)
        if st is None:
            st = _seed_state(values32, dead, k)
            self._lane_prev = values.copy()
            self._lane_age = 0
            self._published_mm = None
        else:
            delta = (values - self._lane_prev).astype(np.float32)
            if dead:
                delta[dead] = 0.0
            st[:, :k] += delta
            self._lane_prev = values.copy()
            if self._lane_age >= gen_len:
                self._published_mm = (
                    st[:, k + 1: 2 * k + 1].copy(),
                    st[:, 2 * k + 1:].copy(),
                )
                _reseed_minmax(st, values32, dead, k)
                self._lane_age = 0
        # dispatch this sample's application WITHOUT waiting: the
        # result is picked up at the next sample (estimates below come
        # from the retrieved previous state + this sample's injection,
        # one application behind — a health view, not a barrier)
        self._lane_state = st
        pending = fn(st, self_w, recv_w, dmask)
        try:
            pending.copy_to_host_async()
        except AttributeError:
            pass
        self._lane_pending = pending
        self._lane_age += 1
        mm = (
            self._published_mm if self._published_mm is not None
            else (st[:, k + 1: 2 * k + 1], st[:, 2 * k + 1:])
        )
        rep = _fleet_estimates(
            st[:, :k].astype(np.float64),
            st[:, k].astype(np.float64),
            np.asarray(mm[0], np.float64),
            np.asarray(mm[1], np.float64),
            live,
        )
        rep["rounds"] = 1
        rep["generation_len"] = int(gen_len)
        rep["warming"] = self._published_mm is None
        return rep

    def _sample(self, ctx, *, step, plan, consensus) -> dict:
        from bluefog_tpu import metrics as metrics_mod

        t_now = time.perf_counter()
        steps_elapsed = self._count - self._last_sample_count
        step_s = None
        if self._last_sample_wall is not None and steps_elapsed > 0:
            step_s = (t_now - self._last_sample_wall) / steps_elapsed
        self._last_sample_wall = t_now
        self._last_sample_count = self._count
        if step_s is not None:
            ms = step_s * 1e3
            self._step_ewma_ms = ms if self._step_ewma_ms is None \
                else 0.8 * self._step_ewma_ms + 0.2 * ms

        if consensus is None:
            consensus = self._read_consensus()
        wire_rate = self._read_wire_rate(steps_elapsed)
        live, dead = self._live_set(ctx)

        sample: Dict[str, Any] = {
            "kind": "sample",
            "step": int(step),
            "comm_steps": self._count,
            "topo_version": int(ctx.topo_version),
        }
        if self._step_ewma_ms is not None:
            sample["step_ms_ewma"] = round(self._step_ewma_ms, 4)
        if consensus is not None:
            sample["consensus"] = float(consensus)
        if wire_rate:
            sample["wire_bytes_per_step"] = wire_rate
        if dead:
            sample["dead_ranks"] = dead

        # -- mixing observatory ----------------------------------------------
        predicted, spec_meta = self.predicted_rate(ctx, plan)
        sample["predicted_rate"] = predicted
        sample["spectral"] = spec_meta
        if ctx.topo_version != self._decay_topo_v:
            from bluefog_tpu import attribution

            self._decay_points.clear()
            self._decay_topo_v = ctx.topo_version
            # a new graph promises a new rate: the efficiency baseline
            # of the old one must not advise (or silence) this one
            self._eff_tracker = attribution.BaselineTracker()
            self._mix_streak = 0
            self._mix_cooldown = 0
            self._oob_streak = 0
        if consensus is not None:
            self._decay_points.append((self._count, consensus))
        measured = fit_decay_rate(self._decay_points)
        eff = mixing_efficiency(measured, predicted)
        if measured is not None:
            sample["measured_rate"] = round(measured, 6)
        if eff is not None:
            sample["mixing_efficiency"] = round(eff, 4)
        tte = time_to_consensus_steps(
            consensus,
            measured if measured is not None and measured < 1.0
            else predicted,
            self.eps,
        )
        if tte is not None:
            sample["time_to_eps_steps"] = round(tte, 1)
            sample["eps"] = self.eps

        # -- age-discounted effective mixing (bluefog_tpu.staleness) ---------
        # The spectral prediction assumes zero staleness; under
        # delayed=True or window-op exchanges it silently overstates
        # the promised contraction. When the staleness observatory is
        # measuring delivered age, correct the promise through the
        # stale-mixing companion polynomial — the corrected efficiency
        # is what the fabric can honestly be held to.
        eff_adj = None
        try:
            from bluefog_tpu import staleness as stal_mod

            obs = stal_mod.active()
            age = obs.last_age_mean() if obs is not None else None
        except Exception:
            age = None
        if age and predicted is not None:
            adj = stal_mod.age_adjusted_rate(
                predicted, age, spec_meta.get("self_weight", 0.5)
            )
            sample["age_mean"] = round(float(age), 4)
            if adj is not None and adj != predicted:
                sample["age_adjusted_rate"] = round(adj, 6)
                eff_adj = mixing_efficiency(measured, adj)
                if eff_adj is not None:
                    sample["mixing_efficiency_age_adjusted"] = round(
                        eff_adj, 4
                    )
                metrics_mod.gauge(
                    "bluefog.health.age_adjusted_rate"
                ).set(adj)

        found = []
        if eff is not None:
            tr = self._eff_tracker
            if tr.n < MIN_FIT_POINTS:
                # warmup: the first fits ride a transient (a short
                # window over the initial decay knee) — absorb them
                # unconditionally so a garbage first value can never
                # freeze the baseline
                tr.update(eff)
                base, degraded = tr.mean, False
                self._oob_streak = 0
            else:
                base = tr.mean
                floor = max(tr.mad, abs(base) * 0.01, 1e-12)
                z = (eff - base) / floor
                degraded = z < -3.0 and eff < base * (
                    1.0 - MIXING_DEGRADED_FRAC
                )
                if abs(z) <= 3.0:
                    # only IN-BAND samples teach the baseline: a slow
                    # efficiency ramp absorbed while "not yet degraded"
                    # inflates the MAD exactly as fast as the ramp
                    # diverges, so the z-gate would never trip — the
                    # baseline must stay the healthy reference until
                    # the series returns to band
                    tr.update(eff)
                    self._oob_streak = 0
                elif degraded:
                    self._oob_streak = 0
                else:
                    # out of band but NOT degraded (e.g. efficiency
                    # jumped ABOVE the band): a persistent shift is a
                    # new regime, not an anomaly — re-baseline after a
                    # full fit window of it
                    self._oob_streak += 1
                    if self._oob_streak >= FIT_WINDOW:
                        tr.update(eff)
                        self._oob_streak = 0
            self._mix_streak = self._mix_streak + 1 if degraded else 0
            if self._mix_cooldown > 0:
                self._mix_cooldown -= 1
            if self._mix_streak >= MIXING_STREAK and \
                    self._mix_cooldown == 0:
                from bluefog_tpu.attribution import Advisory

                found.append(Advisory(
                    kind="mixing_degraded", step=int(step),
                    detail={
                        "mixing_efficiency": round(eff, 4),
                        "baseline_efficiency": round(base, 4),
                        "predicted_rate": predicted,
                        "measured_rate": (
                            round(measured, 6)
                            if measured is not None else None
                        ),
                        "topo_version": int(ctx.topo_version),
                        "suspect_edges": self._suspect_edges(),
                    },
                ))
                self._mix_streak = 0
                # rate-limit a PERSISTENT condition: the counter and
                # /healthz stay raised; the flight ring need not fill
                self._mix_cooldown = FIT_WINDOW

        # -- in-band fleet aggregation ---------------------------------------
        try:
            vec = self._local_vector(ctx, consensus, live)
            fleet = self._fleet_step(ctx, vec, dead, predicted)
            fleet["fields"] = list(FLEET_FIELDS)
            self.fleet = fleet
            sample["fleet"] = {
                "mean": fleet["mean"], "min": fleet["min"],
                "max": fleet["max"], "residual": fleet["residual"],
                "rounds": fleet.get("rounds", 0),
                "live": fleet["live"],
            }
            if fleet.get("warming"):
                # min/max lanes publish their first completed
                # generation; until then the extrema cover only the
                # warmup snapshot and must say so
                sample["fleet"]["warming"] = True
            metrics_mod.gauge("bluefog.health.fleet_residual").set(
                fleet["residual"]
            )
        except Exception as e:  # the lane must never kill training
            sample["fleet_error"] = str(e)[:200]

        # -- emission ---------------------------------------------------------
        if eff is not None:
            metrics_mod.gauge("bluefog.health.mixing_efficiency").set(
                eff
            )
        if predicted is not None:
            metrics_mod.gauge("bluefog.health.predicted_rate").set(
                predicted
            )
        if measured is not None:
            metrics_mod.gauge("bluefog.health.measured_rate").set(
                measured
            )
        if eff_adj is not None:
            metrics_mod.gauge(
                "bluefog.health.mixing_efficiency_age_adjusted"
            ).set(eff_adj)
        if tte is not None:
            metrics_mod.gauge("bluefog.health.time_to_eps_steps").set(
                tte
            )
        metrics_mod.counter("bluefog.health.samples").inc()

        if found:
            sample["advisories"] = [a.to_json() for a in found]
        for adv in found:
            self._emit(adv)
        with self._report_lock:
            self.samples.append(sample)
        self._export_line(sample)
        return sample

    def _emit(self, adv) -> None:
        """One advisory, the PR-7 surfaces: ``bluefog.doctor.*``
        metrics, flight side table, timeline instant, health JSONL."""
        from bluefog_tpu import flight as flight_mod
        from bluefog_tpu import metrics as metrics_mod
        from bluefog_tpu import timeline as tl

        self.advisories.append(adv)
        self.advisory_marks.append(self._count)
        metrics_mod.counter(
            f"bluefog.doctor.advisory.{adv.kind}"
        ).inc()
        metrics_mod.gauge("bluefog.doctor.last_advisory_step").set(
            adv.step
        )
        flight_mod.note_advisory(kind=adv.kind, step=adv.step,
                                 **adv.detail)
        tl.timeline_record_advisory(adv.kind, adv.detail)
        self._export_line({
            "kind": "advisory", "advisory_kind": adv.kind,
            "step": adv.step, **adv.detail,
        })

    def _export_line(self, obj: dict) -> None:
        path = os.environ.get(FILE_ENV)
        if path:
            from bluefog_tpu.logging_util import append_jsonl

            append_jsonl(FILE_ENV, path, obj)

    # -- serving state / artifact ---------------------------------------------

    def _build_report(self) -> dict:
        with self._report_lock:
            samples = list(self.samples)
        return {
            "kind": "health_dump",
            "interval": self.interval,
            "comm_steps": self._count,
            "eps": self.eps,
            "last_sample": samples[-1] if samples else {},
            "samples": samples,
            "advisories": [a.to_json() for a in self.advisories],
            "fleet": self.fleet,
            "fields": list(FLEET_FIELDS),
        }

    def report(self) -> dict:
        """The health artifact ``tools/fleet_report.py`` and
        ``tools/doctor.py --health`` consume. Built on demand (the
        serving thread's clock, not the training loop's — copying the
        sample history every sample was measurable against the 1 %
        budget)."""
        rep = self._build_report()
        rep["healthz"] = healthz_verdict(self)
        # the autotune controller's decision summary rides the /fleet
        # surface: an operator reading the fleet table must see that a
        # rank's topology is being actively re-tuned (and how often it
        # rolled back) next to the health numbers that drove it
        try:
            from bluefog_tpu import autotune as autotune_mod

            tuner = autotune_mod.active()
            if tuner is not None:
                rep["autotune"] = tuner.summary()
        except Exception:
            pass
        # the asynchronous gossip engine's summary rides the same
        # surface: ticks vs local steps, staleness-gate activity, and
        # the cadence map an operator needs to read the age-adjusted
        # mixing score next to it (docs/async.md)
        try:
            from bluefog_tpu import async_gossip as async_mod

            engine = async_mod.active()
            if engine is not None:
                rep["async"] = engine.summary()
        except Exception:
            pass
        # the weight-update shard layout rides here too: an operator
        # sizing a fleet reads per-rank optimizer-state bytes (measured
        # + analytic 1/N model) next to the health numbers
        # (BLUEFOG_SHARD, docs/sharding.md)
        try:
            from bluefog_tpu import sharding as sharding_mod

            shard = sharding_mod.summary()
            if shard is not None:
                rep["shard"] = shard
        except Exception:
            pass
        # the federated fabric rides here too: an operator reading the
        # fleet table must see WHICH pod layout, gateway set, DCN
        # period/wire, and predicted composed consensus rate the gossip
        # they are looking at is actually running (BLUEFOG_PODS,
        # docs/federation.md)
        try:
            from bluefog_tpu import context as ctx_mod
            from bluefog_tpu import federation as fed_mod

            if fed_mod.enabled() and ctx_mod.is_initialized():
                fab = fed_mod.get_fabric(ctx_mod.get_context().size)
                if fab is not None:
                    rep["federation"] = fab.to_json()
        except Exception:
            pass
        # the memory observatory's summary rides the same surface: an
        # operator sizing a fleet reads per-chip footprint, headroom
        # against the budget, and the last ranked census next to the
        # health numbers (BLUEFOG_MEMORY, docs/memory.md)
        try:
            from bluefog_tpu import memory as mem_mod

            obs = mem_mod.active()
            if obs is not None:
                rep["memory"] = {
                    "bytes_per_rank": int(obs.last_bytes_per_rank()),
                    "headroom_bytes": (
                        int(obs.last_headroom()) if obs.budget else None
                    ),
                    "budget_bytes": obs.budget or None,
                    "peak_bytes_per_rank": int(obs._peak_bytes),
                    "oom_events": obs.oom_events,
                    "ranked_census": mem_mod.ranked_census(
                        obs.last_census
                    )[:4],
                }
        except Exception:
            pass
        # the SLO engine's budget summary rides the same surface: the
        # operator reading the fleet table needs "how much failure
        # budget is left and how fast is it burning" next to the raw
        # numbers that spend it (BLUEFOG_SLO, docs/slo.md); the full
        # artifact is served at /slo
        try:
            from bluefog_tpu import slo as slo_mod

            eng = slo_mod.active()
            if eng is not None:
                rep["slo"] = {
                    "worst_burn": eng.worst_burn(),
                    "exhausted": eng.exhausted_objectives(),
                    "alerts": len(eng.alerts),
                    "canary": (
                        eng.canary.last
                        if eng.canary is not None else None
                    ),
                }
        except Exception:
            pass
        return rep

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.report(), f)
        return path


# -- RAG verdict --------------------------------------------------------------


def healthz_verdict(plane: Optional["HealthPlane"] = None) -> dict:
    """The ``/healthz`` RAG verdict, computable without a live mesh:

    - **critical** — the elastic membership holds dead or suspect
      ranks (the run is mid-failure or down a worker), or an SLO
      error budget is exhausted (:mod:`bluefog_tpu.slo` — a spent
      budget is the contract-level outage even while every rank
      answers its heartbeat);
    - **warn** — any advisory (health or doctor) fired within the last
      :data:`VERDICT_RECENT_SAMPLES` health samples;
    - **ok** — otherwise.

    HTTP mapping: 200 for ok/warn (serving but flagged), 503 for
    critical — what a load balancer or k8s liveness probe expects."""
    plane = plane if plane is not None else _plane
    status = "ok"
    reasons: List[str] = []
    dead: List[int] = []
    suspects: List[int] = []
    try:
        from bluefog_tpu import context as ctx_mod

        ctx = ctx_mod.get_context() if ctx_mod.is_initialized() else None
    except Exception:
        ctx = None
    membership = getattr(ctx, "elastic_membership", None) if ctx else None
    if membership is not None:
        dead = [int(r) for r in membership.dead_ranks()]
        from bluefog_tpu.elastic.membership import RankState

        suspects = [
            int(r) for r in range(membership.world_size)
            if membership.state(r) == RankState.SUSPECT
        ] if hasattr(membership, "world_size") else []
        if dead:
            status = "critical"
            reasons.append(f"dead ranks: {dead}")
        if suspects:
            status = "critical"
            reasons.append(f"suspect ranks: {suspects}")
    exhausted: List[str] = []
    try:
        from bluefog_tpu import slo as slo_mod

        exhausted = slo_mod.exhausted_objectives()
    except Exception:
        pass
    if exhausted:
        status = "critical"
        reasons.append(f"slo budget exhausted: {exhausted}")
    recent: List[dict] = []
    if plane is not None:
        floor = plane._count - VERDICT_RECENT_SAMPLES * plane.interval
        recent = [
            a.to_json()
            for a, mark in zip(plane.advisories, plane.advisory_marks)
            if mark >= max(floor, 0)
        ]
    try:
        from bluefog_tpu import attribution

        doc = attribution.active()
        if doc is not None:
            # same window, the DOCTOR's own comm-step clock (its
            # advisory marks; advisory.step counts non-communicating
            # accumulation steps too and would stretch the window K×)
            floor = doc._count - VERDICT_RECENT_SAMPLES * doc.interval
            marks = getattr(doc, "advisory_marks", None)
            if marks is not None:
                recent += [
                    a.to_json()
                    for a, mark in zip(doc.advisories, marks)
                    if mark >= max(floor, 0)
                ]
            else:
                recent += [a.to_json() for a in doc.advisories[-3:]]
    except Exception:
        pass
    if recent and status == "ok":
        status = "warn"
        kinds = sorted({a.get("kind", "?") for a in recent})
        reasons.append(f"recent advisories: {kinds}")
    return {
        "status": status,
        "reasons": reasons,
        "dead_ranks": dead,
        "suspect_ranks": suspects,
        "slo_exhausted": exhausted,
        "recent_advisories": recent[-8:],
        "ts": time.time(),
    }


# -- serving surface ----------------------------------------------------------


class HealthServer:
    """Per-rank stdlib HTTP endpoint: ``/healthz`` (RAG verdict, 503 on
    critical), ``/metrics`` (live Prometheus scrape), ``/fleet`` (the
    in-band aggregate + local summary as JSON). Daemon-threaded; a bind
    failure is a logged no-op (:meth:`maybe_start`), never a training
    crash."""

    def __init__(self, httpd, thread):
        self._httpd = httpd
        self._thread = thread
        self.port = int(httpd.server_address[1])

    @classmethod
    def maybe_start(cls, port: Optional[int] = None,
                    host: str = "0.0.0.0") -> Optional["HealthServer"]:
        """Start serving on ``port`` (default ``BLUEFOG_HEALTH_PORT``;
        0 with an explicit call = OS-assigned). Returns None — with a
        warning, without raising — when the port is taken or the env
        asks for nothing."""
        from http.server import BaseHTTPRequestHandler, HTTPServer
        from socketserver import ThreadingMixIn

        from bluefog_tpu.logging_util import logger

        env_port = port is None
        if port is None:
            port = health_port()
            if port <= 0:
                return None

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr chatter per scrape
                pass

            def _send(self, code, body, ctype="application/json"):
                data = body.encode() if isinstance(body, str) else body
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                from bluefog_tpu import metrics as metrics_mod

                path = self.path.split("?")[0].rstrip("/") or "/healthz"
                try:
                    if path == "/healthz":
                        v = healthz_verdict()
                        code = 503 if v["status"] == "critical" else 200
                        # strict JSON: a NaN gauge must never reach the
                        # scraper as a bare NaN token (allow_nan=False
                        # is the regression tripwire — _json_safe
                        # already replaced every non-finite value)
                        self._send(code, json.dumps(
                            _json_safe(v), allow_nan=False
                        ))
                    elif path == "/metrics":
                        self._send(
                            200,
                            "\n".join(metrics_mod.prom_lines()) + "\n",
                            ctype="text/plain; version=0.0.4",
                        )
                    elif path == "/fleet":
                        plane = active()
                        body = (
                            plane.report() if plane is not None
                            else {"kind": "health_dump",
                                  "healthz": healthz_verdict(None),
                                  "fleet": None, "samples": []}
                        )
                        self._send(200, json.dumps(
                            _json_safe(body), allow_nan=False
                        ))
                    elif path == "/slo":
                        from bluefog_tpu import slo as slo_mod

                        eng = slo_mod.active()
                        body = (
                            eng.report() if eng is not None
                            else {"kind": "slo_dump",
                                  "objectives": [], "alerts": [],
                                  "canary": None}
                        )
                        self._send(200, json.dumps(
                            _json_safe(body), allow_nan=False
                        ))
                    else:
                        self._send(404, json.dumps(
                            {"error": f"unknown path {path!r}",
                             "paths": ["/healthz", "/metrics",
                                       "/fleet", "/slo"]}
                        ))
                except Exception as e:  # a scrape bug must not hang curl
                    try:
                        self._send(500, json.dumps(
                            {"error": str(e)[:200]}
                        ))
                    except Exception:
                        pass

        class _Server(ThreadingMixIn, HTTPServer):
            daemon_threads = True
            # fast rebinds between tests/restarts; a REAL port conflict
            # (another process listening) still raises EADDRINUSE
            allow_reuse_address = True

        try:
            httpd = _Server((host, int(port)), _Handler)
        except OSError as e:
            logger.warning(
                "health endpoint disabled: cannot bind %s:%s (%s)%s",
                host, port, e,
                " — set BLUEFOG_HEALTH_PORT to a free port" if env_port
                else "",
            )
            return None
        thread = threading.Thread(
            target=httpd.serve_forever, name="bf-healthz", daemon=True
        )
        thread.start()
        return cls(httpd, thread)

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass


# -- module-level session -----------------------------------------------------

_plane: Optional[HealthPlane] = None
_server: Optional[HealthServer] = None


def start(interval: Optional[int] = None, **kwargs) -> HealthPlane:
    """Open a health-plane session (replacing any active one)."""
    global _plane
    _plane = HealthPlane(interval=interval, **kwargs)
    return _plane


def stop() -> None:
    global _plane
    _plane = None


def activate(plane: Optional[HealthPlane]) -> Optional[HealthPlane]:
    """Install (or clear, with None) a pre-built session WITHOUT
    resetting its baselines — the A/B rotation in ``BENCH_MODE=health``
    toggles one session on and off around individual steps."""
    global _plane
    _plane = plane
    return plane


def active() -> Optional[HealthPlane]:
    return _plane


def serve(port: Optional[int] = None) -> Optional[HealthServer]:
    """Start (or restart) the HTTP endpoint; None on bind failure."""
    global _server
    if _server is not None:
        _server.close()
    _server = HealthServer.maybe_start(port)
    return _server


def server() -> Optional[HealthServer]:
    return _server


def observe_step(ctx, *, step: int, plan=None,
                 consensus: Optional[float] = None) -> None:
    """Optimizer-layer hook, called after every communicating dispatch
    (next to :func:`bluefog_tpu.attribution.observe_step`). No-op (one
    attribute read) when no session is active."""
    plane = _plane
    if plane is None:
        return
    plane.observe(ctx, step=step, plan=plan, consensus=consensus)


def dump(path: str) -> Optional[str]:
    """Write the active session's health artifact (None when no
    session is active)."""
    plane = _plane
    if plane is None:
        return None
    return plane.dump(path)


def on_init(ctx) -> None:
    """``bf.init()`` hook: fresh session under ``BLUEFOG_HEALTH=1`` (a
    new mesh must not inherit a torn-down mesh's efficiency baseline),
    endpoint under ``BLUEFOG_HEALTH_PORT``."""
    if enabled():
        start()
    else:
        stop()
    global _server
    if _server is not None:
        _server.close()
        _server = None
    if health_port() > 0:
        serve()


def on_shutdown() -> None:
    """``bf.shutdown()`` hook: flush the JSONL tail, stop serving,
    drop the session."""
    global _server
    plane = _plane
    if plane is not None and plane.samples:
        plane._export_line({"kind": "session_end",
                            "comm_steps": plane._count})
    if _server is not None:
        _server.close()
        _server = None
    stop()
