# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Thousand-rank fleet simulator (``bf.fleetsim``).

Runs hundreds-to-thousands of *virtual* ranks in one process on the
elastic engine's fault-plan step clock — no device dispatch, but every
control-plane mechanism driven for real:

- **Virtual membership** is the real :class:`~bluefog_tpu.elastic.
  membership.Membership` state machine (epoch bumps, verdict history,
  flight records), plus an incrementally-maintained live-set token
  (O(1) per transition) standing in for the O(N) live tuple the
  device path hashes into its plan-cache keys.
- **Repair-weight algebra** reimplements the
  :func:`~bluefog_tpu.elastic.repair.repaired_matrix` policy contract
  over per-rank edge dicts: ``receiver`` and ``push_sum`` repairs touch
  only the killed ranks' neighborhoods (lazy per-receiver /
  per-sender renormalization — O(degree^2) per killed rank, sublinear
  in N), while ``average`` rebuilds its Metropolis–Hastings weights
  per event (the connectivity audit is O(edges); disclosed, and the
  reason the fleet-scale storm evidence runs the structure-preserving
  ``receiver`` policy). All three are oracle-tested against the dense
  ``repaired_matrix`` at small N.
- **Plan-cache keys** follow the exact dispatch discipline of
  :func:`bluefog_tpu.collective.ops` — ``("static_plan",
  topo_version, weighted, method, live_token)`` — with the elastic
  session's zero-stale-dispatch tripwire: every dispatch audits the
  fetched plan's compile-time edge snapshot against the current dead
  set (``audit_edges=False`` keeps the timed evidence path free of the
  O(edges) audit; tier-1 runs it at N=1024).
- **Advisory plumbing** files real :class:`~bluefog_tpu.attribution.
  Advisory` records (``fleet_churn`` on simultaneous-loss storms,
  ``fleet_partition`` when the survivor graph disconnects).
- **Fleet aggregation** runs the health plane's push-sum lanes
  (``x <- P^T x``, ``p <- P^T p``, min/max neighbor folds) as sparse
  scatter-adds over the live edge list, oracle-tested against
  :func:`bluefog_tpu.health.fleet_aggregate_np`.
- **Autotune decision latency**: :meth:`VirtualFleet.decision_probe`
  scores a candidate set (current / live ring / live Exp2) through the
  sparse spectral engine and reports the measured decision latency —
  the number the N=1024 acceptance bound pins.

Everything is deterministic on the step clock, so churn storms,
cascading repairs, and whole-region loss at N=1024 are plain tier-1
unit tests; ``BENCH_MODE=fleetscale`` commits the measured control-
plane scaling as ``FLEETSCALE_EVIDENCE.json``.
"""

import json
import math
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu import metrics as metrics_mod
from bluefog_tpu.logging_util import logger, warn_once

__all__ = [
    "VirtualFleet",
    "base_edges",
    "ring_edges",
    "exp2_edges",
    "storm_plan",
    "cascade_plan",
    "region_plan",
    "FLEETSIM_FILE_ENV",
]

FLEETSIM_FILE_ENV = "BLUEFOG_FLEETSIM_FILE"

# a simultaneous kill batch at least this large (and >= 2) files a
# fleet_churn advisory
_CHURN_FRACTION = 0.01


def _rank_salt(rank: int) -> int:
    """Per-rank 64-bit mixing salt for the incremental live-set hash:
    the XOR of live ranks' salts is order-independent and updates in
    O(1) per membership transition (the fleet-scale stand-in for
    hashing the O(N) live tuple into every plan-cache key)."""
    x = (rank + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9 & 0xFFFFFFFFFFFFFFFF
    x = (x ^ (x >> 27)) * 0x94D049BB133111EB & 0xFFFFFFFFFFFFFFFF
    return x ^ (x >> 31)


# -- sparse topology constructors ---------------------------------------------


def ring_edges(size: int) -> Dict[Tuple[int, int], float]:
    """Bidirectional ring combine weights as an edge dict — sparse twin
    of :func:`bluefog_tpu.topology.RingGraph` (connect_style=0)."""
    if size <= 0:
        raise ValueError(f"size must be positive, got {size}")
    if size == 1:
        return {(0, 0): 1.0}
    if size == 2:
        return {(0, 0): 0.5, (0, 1): 0.5, (1, 0): 0.5, (1, 1): 0.5}
    out: Dict[Tuple[int, int], float] = {}
    w = 1.0 / 3.0
    for i in range(size):
        out[(i, i)] = w
        out[(i, (i + 1) % size)] = w
        out[(i, (i - 1) % size)] = w
    return out


def exp2_edges(size: int) -> Dict[Tuple[int, int], float]:
    """Exponential-2 combine weights as an edge dict — sparse twin of
    :func:`bluefog_tpu.topology.ExponentialTwoGraph` (O(N log N)
    construction; the generator's dense N x N array never exists)."""
    if size <= 0:
        raise ValueError(f"size must be positive, got {size}")
    offsets = [0]
    d = 1
    while d < size:
        offsets.append(d)
        d *= 2
    w = 1.0 / len(offsets)
    out: Dict[Tuple[int, int], float] = {}
    for i in range(size):
        for d in offsets:
            out[(i, (i + d) % size)] = w
    return out


def base_edges(size: int, kind: str = "exp2",
               seed: int = 0) -> Dict[Tuple[int, int], float]:
    """Base-topology edge dict by name. ``ring`` and ``exp2`` build
    sparsely (the fleet-scale families); ``mesh`` / ``star`` / ``rrd``
    densify through the :mod:`bluefog_tpu.topology` generators and are
    intended for small-N oracle tests."""
    if kind == "ring":
        return ring_edges(size)
    if kind == "exp2":
        return exp2_edges(size)
    from bluefog_tpu import topology as topo_mod

    if kind == "mesh":
        g = topo_mod.MeshGrid2DGraph(size)
    elif kind == "star":
        g = topo_mod.StarGraph(size)
    elif kind == "rrd":
        g = topo_mod.RandomRegularDigraph(size, min(3, size - 1), seed=seed)
    else:
        raise ValueError(
            f"unknown fleet topology {kind!r} "
            "(ring / exp2 / mesh / star / rrd)"
        )
    return {
        (u, v): d.get("weight", 1.0)
        for u, v, d in g.edges(data=True)
        if d.get("weight", 1.0) != 0.0
    }


# -- fault-plan builders -------------------------------------------------------


def storm_plan(size: int, fraction: float, step: int, seed: int = 0):
    """A churn storm: ``fraction`` of the fleet killed simultaneously
    at ``step`` (the 10%-loss acceptance scenario). Deterministic in
    ``seed``."""
    from bluefog_tpu.elastic.faults import Fault, FaultPlan

    rng = np.random.RandomState(seed)
    k = max(1, int(round(size * fraction)))
    ranks = rng.choice(size, size=k, replace=False)
    return FaultPlan(
        [Fault(kind="kill", rank=int(r), step=step) for r in sorted(ranks)]
    )


def cascade_plan(size: int, count: int, start_step: int,
                 stride: int = 1, seed: int = 0):
    """A cascading failure: ``count`` kills spread ``stride`` steps
    apart — every kill lands on an already-repaired fleet, so each
    event re-runs the full detect/repair/recompile discipline."""
    from bluefog_tpu.elastic.faults import Fault, FaultPlan

    rng = np.random.RandomState(seed)
    ranks = rng.choice(size, size=min(count, size - 1), replace=False)
    return FaultPlan([
        Fault(kind="kill", rank=int(r), step=start_step + k * stride)
        for k, r in enumerate(sorted(ranks))
    ])


def region_plan(size: int, lo: int, hi: int, step: int):
    """Whole-region loss: every rank in ``[lo, hi)`` killed at once
    (a pod / availability-zone outage)."""
    from bluefog_tpu.elastic.faults import Fault, FaultPlan

    return FaultPlan([
        Fault(kind="kill", rank=r, step=step) for r in range(lo, hi)
    ])


def classify_loss(detected: Sequence[int], n: int,
                  layout=None) -> Dict[str, object]:
    """Classify one batched detection into a loss class for the repair
    event record (rendered as distinct classes by
    ``tools/fleetsim_report.py`` — a whole-pod outage must not read
    like scattered churn in the storm timeline).

    - ``pod_loss``: a declared pod layout
      (:class:`bluefog_tpu.federation.PodLayout`) and the detected set
      covers >= 1 whole pod — ``pods_lost`` lists them.
    - ``region_loss``: no pod knowledge, but the detected ranks form
      one contiguous block of at least ``max(4, 2%)`` of the fleet (a
      rack / availability-zone outage under serpentine placement).
    - ``storm``: simultaneous scattered loss at or above the churn
      advisory threshold.
    - ``churn``: everything smaller.
    """
    ranks = sorted(int(r) for r in set(detected))
    if not ranks:
        return {"loss_class": "none"}
    if layout is not None:
        covered = set(ranks)
        pods_lost = [
            p for p in range(layout.n_pods)
            if all(r in covered for r in layout.ranks(p))
        ]
        if pods_lost:
            return {"loss_class": "pod_loss", "pods_lost": pods_lost}
    block = (
        len(ranks) >= max(4, int(n * 0.02))
        and ranks[-1] - ranks[0] + 1 == len(ranks)
    )
    if block:
        return {
            "loss_class": "region_loss",
            "region": [ranks[0], ranks[-1]],
        }
    if len(ranks) >= max(2, int(n * _CHURN_FRACTION)):
        return {"loss_class": "storm"}
    return {"loss_class": "churn"}


# -- sparse repair-weight algebra ---------------------------------------------


class FleetTopology:
    """The live combine matrix held as per-rank edge dicts with the
    :func:`~bluefog_tpu.elastic.repair.repaired_matrix` policy contract
    applied lazily: ``receiver`` / ``push_sum`` normalizers are cached
    per rank and invalidated only in the killed ranks' neighborhoods
    (O(degree^2) per killed rank), ``average`` rebuilds its
    Metropolis–Hastings weights per event (O(edges) — the connectivity
    audit that unions in the survivor ring needs the whole graph)."""

    def __init__(self, n: int, edges: Dict[Tuple[int, int], float],
                 policy: str = "receiver"):
        from bluefog_tpu.elastic.repair import POLICIES

        if policy not in POLICIES:
            raise ValueError(
                f"policy must be one of {POLICIES}, got {policy!r}"
            )
        self.n = int(n)
        self.policy = policy
        self.base_out: List[Dict[int, float]] = [dict() for _ in range(n)]
        self.base_in: List[Dict[int, float]] = [dict() for _ in range(n)]
        self.base_self = np.zeros(n)
        for (i, j), w in edges.items():
            if w == 0.0:
                continue
            if i == j:
                self.base_self[i] = float(w)
            else:
                self.base_out[i][j] = float(w)
                self.base_in[j][i] = float(w)
        self.live = np.ones(n, dtype=bool)
        self.degraded: Dict[int, float] = {}
        # lazy normalizers: rank -> 1/sum (None = dirty). Start clean
        # with everything live.
        self._col: List[Optional[float]] = [None] * n
        self._row: List[Optional[float]] = [None] * n
        self._avg: Optional[List[Dict[int, float]]] = None
        self.partitioned = False

    # -- membership events ----------------------------------------------------

    def _touch_neighborhood(self, rank: int) -> int:
        """Invalidate the normalizer caches of every rank adjacent to
        ``rank`` — the only ranks whose repaired weights can change.
        Returns the number of touched ranks (the per-event cost the
        evidence measures)."""
        touched = 0
        for j in self.base_out[rank]:
            self._col[j] = None
            touched += 1
        for i in self.base_in[rank]:
            self._row[i] = None
            touched += 1
        self._col[rank] = None
        self._row[rank] = None
        return touched + 1

    def kill(self, ranks: Sequence[int]) -> int:
        touched = 0
        for r in ranks:
            r = int(r)
            if self.live[r]:
                self.live[r] = False
                self.degraded.pop(r, None)
                touched += self._touch_neighborhood(r)
        self._avg = None
        return touched

    def revive(self, rank: int) -> int:
        rank = int(rank)
        if not self.live[rank]:
            self.live[rank] = True
            self._avg = None
            return self._touch_neighborhood(rank)
        return 0

    def degrade(self, rank: int, factor: float) -> int:
        rank = int(rank)
        if not self.live[rank]:
            return 0
        self.degraded[rank] = float(factor)
        self._avg = None
        return self._touch_neighborhood(rank)

    # -- policy weights -------------------------------------------------------

    def _dfac(self, sender: int, receiver: int) -> float:
        """Degrade discount of edge ``(sender, receiver)``: a degraded
        rank's outgoing edges (self loop excluded) carry its factor —
        the `repaired_matrix` pre-normalization scaling."""
        if sender == receiver:
            return 1.0
        return self.degraded.get(sender, 1.0)

    def _col_scale(self, j: int) -> float:
        s = self._col[j]
        if s is None:
            tot = self.base_self[j]
            for i, w in self.base_in[j].items():
                if self.live[i]:
                    tot += w * self._dfac(i, j)
            s = (1.0 / tot) if tot > 0.0 else 0.0
            self._col[j] = s
        return s

    def _row_scale(self, i: int) -> float:
        s = self._row[i]
        if s is None:
            tot = self.base_self[i]
            for j, w in self.base_out[i].items():
                if self.live[j]:
                    tot += w * self._dfac(i, j)
            s = (1.0 / tot) if tot > 0.0 else 0.0
            self._row[i] = s
        return s

    def _average_weights(self) -> List[Dict[int, float]]:
        """Per-rank ``{dst: w}`` out-edge weights (self loop included as
        ``{rank: w}``) under the ``average`` policy: symmetrized
        surviving edge set, survivor-ring union when disconnected,
        Metropolis–Hastings weights, symmetric degrade reabsorbed into
        both diagonals — the dense `repaired_matrix` recipe verbatim,
        rebuilt per membership event."""
        if self._avg is not None:
            return self._avg
        n = self.n
        live = [r for r in range(n) if self.live[r]]
        adj: List[set] = [set() for _ in range(n)]
        for i in live:
            for j in self.base_out[i]:
                if i != j and self.live[j]:
                    adj[i].add(j)
                    adj[j].add(i)
        # survivor connectivity audit (BFS over the symmetrized live
        # graph); disconnected -> union in the survivor ring
        self.partitioned = False
        if len(live) > 1:
            seen = {live[0]}
            stack = [live[0]]
            while stack:
                u = stack.pop()
                for v in adj[u]:
                    if v not in seen:
                        seen.add(v)
                        stack.append(v)
            if len(seen) != len(live):
                self.partitioned = True
                for k, i in enumerate(live):
                    j = live[(k + 1) % len(live)]
                    if i != j:
                        adj[i].add(j)
                        adj[j].add(i)
        deg = {i: len(adj[i]) for i in live}
        out: List[Dict[int, float]] = [dict() for _ in range(n)]
        for i in live:
            row_sum = 0.0
            row = out[i]
            for j in adj[i]:
                w = 1.0 / (1.0 + max(deg[i], deg[j]))
                w *= self._dfac(i, j) * self._dfac(j, i)
                row[j] = w
                row_sum += w
            row[i] = 1.0 - row_sum
        for d in range(n):
            if not self.live[d]:
                out[d] = {d: 1.0}
        self._avg = out
        return out

    def send_weights(self, i: int) -> Dict[int, float]:
        """Effective out-edge weights of live rank ``i`` (self loop
        included) under the active policy — the operand a dispatch
        round actually ships."""
        if not self.live[i]:
            return {i: 1.0}
        if self.policy == "average":
            return dict(self._average_weights()[i])
        out: Dict[int, float] = {}
        if self.policy == "receiver":
            s = self._col_scale(i)
            out[i] = self.base_self[i] * s if s > 0.0 else 1.0
            for j, w in self.base_out[i].items():
                if self.live[j]:
                    sj = self._col_scale(j)
                    if sj > 0.0:
                        out[j] = w * self._dfac(i, j) * sj
            return out
        # push_sum: sender-normalized
        s = self._row_scale(i)
        if s <= 0.0:
            return {i: 1.0}
        out[i] = self.base_self[i] * s
        for j, w in self.base_out[i].items():
            if self.live[j]:
                out[j] = w * self._dfac(i, j) * s
        return out

    def recv_weights(self, j: int) -> Tuple[float, Dict[int, float]]:
        """(self_weight, {in_neighbor: weight}) of live rank ``j`` —
        the :func:`bluefog_tpu.topology.GetRecvWeights` view of the
        repaired matrix, O(degree)."""
        if not self.live[j]:
            return 1.0, {}
        if self.policy == "average":
            # the average adjacency is symmetric (incl. any ring-union
            # edges), so j's in-neighbors are exactly the keys of its
            # own out row
            row_all = self._average_weights()
            self_w = row_all[j].get(j, 0.0)
            nbrs = {
                i: row_all[i][j]
                for i in row_all[j]
                if i != j
            }
            return self_w, nbrs
        if self.policy == "receiver":
            s = self._col_scale(j)
            if s <= 0.0:
                return 1.0, {}
            nbrs = {
                i: w * self._dfac(i, j) * s
                for i, w in self.base_in[j].items()
                if self.live[i]
            }
            return self.base_self[j] * s, nbrs
        # push_sum
        nbrs = {}
        for i, w in self.base_in[j].items():
            if self.live[i]:
                si = self._row_scale(i)
                if si > 0.0:
                    nbrs[i] = w * self._dfac(i, j) * si
        return self.base_self[j] * self._row_scale(j), nbrs

    # -- whole-matrix views (tests / verdicts) --------------------------------

    def live_ranks(self) -> List[int]:
        return [r for r in range(self.n) if self.live[r]]

    def edges_dict(self) -> Dict[Tuple[int, int], float]:
        """Full repaired edge dict (dead ranks isolated at self weight
        1) — O(edges); the oracle-test and verdict view, not the
        per-event path."""
        out: Dict[Tuple[int, int], float] = {}
        if self.policy == "average":
            rows = self._average_weights()
            for i in range(self.n):
                for j, w in rows[i].items():
                    if w != 0.0:
                        out[(i, j)] = w
            return out
        for i in range(self.n):
            for j, w in self.send_weights(i).items():
                if w != 0.0:
                    out[(i, j)] = w
        return out

    def to_dense(self) -> np.ndarray:
        w = np.zeros((self.n, self.n))
        for (i, j), v in self.edges_dict().items():
            w[i, j] = v
        return w

    def decay_info(self) -> Tuple[Optional[float], dict]:
        """Post-repair verdict: predicted per-step consensus decay on
        the live submatrix through the spectral engine (sparse above
        ``BLUEFOG_SPECTRAL_DENSE_MAX``). ``None`` = no contraction
        promised."""
        from bluefog_tpu import topology as topo_mod

        live = self.live_ranks()
        n_sub, sub = topo_mod.live_submatrix_edges(self.edges_dict(), live)
        rate, spec = topo_mod.second_largest_eigenvalue_modulus_info(
            (n_sub, sub)
        )
        if rate >= 1.0 - 1e-9:
            return None, spec
        return float(rate), spec


# -- the simulator -------------------------------------------------------------


class VirtualFleet:
    """N virtual ranks on the fault-plan step clock. One
    :meth:`tick` = one communicating step: due faults apply through the
    real :class:`Membership` state machine, detection + repair run
    *before* the dispatch (the elastic engine's synchronous
    discipline), and the dispatch fetches its plan under the real
    cache-key shape with the zero-stale tripwire."""

    def __init__(self, n: int, topology: str = "exp2",
                 policy: str = "receiver", plan=None,
                 method: str = "neighbor_allreduce",
                 audit_edges: bool = True, seed: int = 0,
                 edges: Optional[Dict[Tuple[int, int], float]] = None):
        from bluefog_tpu.elastic.faults import FaultPlan
        from bluefog_tpu.elastic.membership import Membership

        self.n = int(n)
        self.topology = topology
        self.topo = FleetTopology(
            n,
            edges if edges is not None else base_edges(n, topology, seed),
            policy,
        )
        # pod layout (bluefog_tpu.federation.PodLayout) for loss-class
        # annotation on repair events; federated fleets install a
        # repair_hook that runs INSIDE the timed repair pass (gateway
        # re-election) so membership + rewiring stay one event
        self.pod_layout = None
        self.repair_hook = None
        if os.environ.get("BLUEFOG_PODS", "").strip():
            try:
                from bluefog_tpu import federation

                self.pod_layout = federation.layout_from_env(self.n)
            except ValueError:
                warn_once(
                    "fleetsim-pods",
                    "BLUEFOG_PODS does not parse for a %d-rank fleet; "
                    "repair events stay unclassified", self.n,
                )
        self.membership = Membership(n)
        self.fault_plan = plan if plan is not None else FaultPlan()
        self.fault_plan.validate(n)
        self.method = method
        self.audit_edges = bool(audit_edges)
        self.step = 0
        self.topo_version = 0
        self.events: List[dict] = []
        self.advisories: List[object] = []
        self.repairs = 0
        self.stale_dispatches = 0
        self.cache_hits = 0
        self.cache_misses = 0
        self.plan_cache: Dict[tuple, dict] = {}
        self.last_event_ms: Optional[float] = None
        self.last_decision_ms: Optional[float] = None
        self._live_hash = 0
        self._live_count = self.n
        for r in range(self.n):
            self._live_hash ^= _rank_salt(r)
        self._dead_seen: set = set()
        self._degrade_dirty = False
        self._file = os.environ.get(FLEETSIM_FILE_ENV)
        self._file_ok = True
        metrics_mod.gauge("bluefog.fleetsim.ranks").set(self.n)
        metrics_mod.gauge("bluefog.fleetsim.live").set(self.n)

    # -- plan-cache key discipline --------------------------------------------

    def live_token(self) -> tuple:
        """The plan-cache live token, maintained incrementally: the
        membership epoch plus an order-independent XOR hash of the live
        set (O(1) per transition vs the O(N) live tuple the device
        path hashes — same discipline: any membership change changes
        the token)."""
        return (self.membership.epoch, self._live_hash, self._live_count)

    def _cache_key(self) -> tuple:
        # the `ops._static_plan` key shape: fleet topologies are always
        # weighted
        return ("static_plan", self.topo_version, True, self.method,
                self.live_token())

    def _compile_plan(self) -> dict:
        plan = {
            "topo_version": self.topo_version,
            "token": self.live_token(),
        }
        if self.audit_edges:
            # compile-time edge snapshot (O(edges)) for the per-dispatch
            # stale audit — the tier-1 path; the timed evidence path
            # disables it and audits version/token only
            edges = []
            for i in self.topo.live_ranks():
                for j in self.topo.send_weights(i):
                    if i != j:
                        edges.append((i, j))
            plan["edges"] = edges
        return plan

    # -- event application ----------------------------------------------------

    def _record(self, row: dict) -> None:
        self.events.append(row)
        if self._file and self._file_ok:
            try:
                with open(self._file, "a") as fh:
                    fh.write(json.dumps(row) + "\n")
            except OSError:
                self._file_ok = False
                warn_once(
                    "fleetsim-file",
                    "fleetsim JSONL path %s is not writable; fleet "
                    "events stay in memory only", self._file,
                )

    def _advise(self, kind: str, step: int, detail: dict) -> None:
        from bluefog_tpu.attribution import Advisory

        adv = Advisory(kind=kind, step=step, detail=detail)
        self.advisories.append(adv)
        metrics_mod.counter("bluefog.fleetsim.advisories").inc()
        self._record({"metric": "fleetsim_advisory", **adv.to_json()})

    def _repair(self, newly_dead: List[int], step: int) -> float:
        """Synchronous repair: prune + renormalize the killed ranks'
        neighborhoods, bump the topology version (old plan-cache keys
        can never match again), file the advisory, and record the
        event. Returns the measured event cost in ms."""
        t0 = time.perf_counter()
        touched = self.topo.kill(newly_dead)
        for r, f in self.membership.degraded().items():
            if self.topo.degraded.get(r) != f:
                touched += self.topo.degrade(r, f)
        hook_detail = None
        if self.repair_hook is not None:
            # federated fleets re-elect gateways and rewire the
            # inter-pod ring HERE — inside the timed window, before the
            # single version bump, so the whole transition is one event
            hook_detail = self.repair_hook(newly_dead, step)
        self.topo_version += 1
        self.repairs += 1
        self._degrade_dirty = False
        ms = (time.perf_counter() - t0) * 1e3
        self.last_event_ms = ms
        metrics_mod.counter("bluefog.fleetsim.repairs").inc()
        metrics_mod.gauge("bluefog.fleetsim.live").set(self._live_count)
        metrics_mod.gauge("bluefog.fleetsim.epoch").set(
            self.membership.epoch
        )
        metrics_mod.histogram("bluefog.fleetsim.event_ms").observe(ms)
        row = {
            "metric": "fleetsim_repair",
            "step": int(step),
            "detected": [int(r) for r in newly_dead],
            "dead": self.n - self._live_count,
            "live": self._live_count,
            "epoch": int(self.membership.epoch),
            "topo_version": int(self.topo_version),
            "policy": self.topo.policy,
            "touched_ranks": int(touched),
            "event_ms": round(ms, 6),
        }
        row.update(classify_loss(newly_dead, self.n, self.pod_layout))
        if hook_detail:
            row.update(hook_detail)
        self._record(row)
        if len(newly_dead) >= max(2, int(self.n * _CHURN_FRACTION)):
            self._advise("fleet_churn", step, {
                "killed": len(newly_dead),
                "live": self._live_count,
                "epoch": int(self.membership.epoch),
                "event_ms": round(ms, 6),
            })
        if self.topo.partitioned:
            self._advise("fleet_partition", step, {
                "live": self._live_count,
                "note": "survivor graph disconnected; ring unioned in",
            })
        return ms

    def kill(self, rank: int, step: Optional[int] = None) -> bool:
        """Out-of-plan kill (storm drivers call this directly)."""
        rank = int(rank)
        if not self.membership.mark_dead(rank, step=step):
            return False
        self._live_hash ^= _rank_salt(rank)
        self._live_count -= 1
        self._dead_seen.add(rank)
        metrics_mod.counter("bluefog.fleetsim.events").inc()
        return True

    def rejoin(self, rank: int) -> bool:
        """Re-admit a dead rank and repair — the elastic rejoin path on
        the virtual fleet."""
        rank = int(rank)
        if not self.membership.revive(rank, step=self.step):
            return False
        self._live_hash ^= _rank_salt(rank)
        self._live_count += 1
        self._dead_seen.discard(rank)
        self.topo.revive(rank)
        metrics_mod.counter("bluefog.fleetsim.events").inc()
        self.topo_version += 1
        self.repairs += 1
        self._record({
            "metric": "fleetsim_rejoin",
            "step": int(self.step),
            "rank": rank,
            "live": self._live_count,
            "epoch": int(self.membership.epoch),
            "topo_version": int(self.topo_version),
        })
        return True

    def tick(self) -> dict:
        """One communicating step on the fault-plan clock: apply due
        faults, repair before dispatch, dispatch under the cache-key
        discipline. Returns the step summary row."""
        step = self.step
        newly: List[int] = []
        for f in self.fault_plan.due(step):
            if f.kind == "kill":
                if self.kill(f.rank, step=step):
                    newly.append(f.rank)
            elif f.kind == "degrade":
                if self.membership.mark_degraded(f.rank, f.factor,
                                                 step=step):
                    self._degrade_dirty = True
                    metrics_mod.counter("bluefog.fleetsim.events").inc()
            else:
                # stall/slow/oom have no membership consequence here;
                # they are suspects for the advisory join
                self._advise("fleet_suspect", step, {
                    "rank": int(f.rank), "kind": f.kind,
                })
        if newly or self._degrade_dirty:
            self._repair(newly, step)
        row = self.dispatch()
        self.step += 1
        return row

    def run(self, steps: int) -> None:
        for _ in range(int(steps)):
            self.tick()

    def dispatch(self) -> dict:
        """One virtual dispatch: fetch the plan under the real cache
        key; audit it against the dead set (the zero-stale tripwire —
        any fetched plan carrying an edge into a dead rank is a stale
        dispatch, and the counter must stay 0)."""
        key = self._cache_key()
        plan = self.plan_cache.get(key)
        if plan is None:
            plan = self._compile_plan()
            self.plan_cache[key] = plan
            self.cache_misses += 1
        else:
            self.cache_hits += 1
        stale = (
            plan["topo_version"] != self.topo_version
            or plan["token"] != self.live_token()
        )
        if not stale and self.audit_edges:
            for (i, j) in plan.get("edges", ()):
                if not (self.topo.live[i] and self.topo.live[j]):
                    stale = True
                    break
        if stale:
            self.stale_dispatches += 1
            metrics_mod.counter(
                "bluefog.fleetsim.stale_dispatches"
            ).inc()
            logger.warning(
                "fleetsim stale dispatch at step %d (topo v%d)",
                self.step, self.topo_version,
            )
        return {
            "step": int(self.step),
            "live": self._live_count,
            "epoch": int(self.membership.epoch),
            "topo_version": int(self.topo_version),
            "stale": bool(stale),
        }

    # -- fleet aggregation (push-sum lanes, sparse) ---------------------------

    def aggregate(self, values: np.ndarray, rounds: int) -> dict:
        """The health plane's in-band push-sum aggregate over the
        virtual fleet: ``rounds`` applications of ``x <- P^T x``,
        ``p <- P^T p`` plus min/max neighbor folds, as sparse
        scatter-adds over the live edge list. Same per-application
        semantics as :func:`bluefog_tpu.health.fleet_aggregate_np`
        (the small-N oracle); same report shape."""
        from bluefog_tpu.health import _fleet_estimates

        values = np.asarray(values, np.float64)
        n, _k = values.shape
        assert n == self.n, f"values rows {n} != fleet size {self.n}"
        live = self.topo.live_ranks()
        dead = [r for r in range(self.n) if not self.topo.live[r]]
        # push matrix: each live sender's current row (self + live out
        # edges) normalized to sum 1 — assembled as COO over the live
        # edge list
        rows: List[int] = []
        cols: List[int] = []
        vals: List[float] = []
        for i in live:
            sw = self.topo.send_weights(i)
            tot = sum(sw.values())
            if tot <= 0.0:
                rows.append(i)
                cols.append(i)
                vals.append(1.0)
                continue
            for j, w in sw.items():
                if w != 0.0:
                    rows.append(i)
                    cols.append(j)
                    vals.append(w / tot)
        rows_a = np.asarray(rows, np.intp)
        cols_a = np.asarray(cols, np.intp)
        vals_a = np.asarray(vals, np.float64)
        off = rows_a != cols_a
        x = values.copy()
        p = np.ones(self.n)
        mn = values.copy()
        mx = values.copy()
        for r in dead:
            x[r] = 0.0
            p[r] = 0.0
            mn[r] = np.inf
            mx[r] = -np.inf
        for _ in range(int(rounds)):
            x2 = np.zeros_like(x)
            np.add.at(x2, cols_a, vals_a[:, None] * x[rows_a])
            p2 = np.zeros_like(p)
            np.add.at(p2, cols_a, vals_a * p[rows_a])
            mn0, mx0 = mn.copy(), mx.copy()
            np.minimum.at(mn, cols_a[off], mn0[rows_a[off]])
            np.maximum.at(mx, cols_a[off], mx0[rows_a[off]])
            x, p = x2, p2
        return _fleet_estimates(x, p, mn, mx, live)

    # -- autotune decision latency --------------------------------------------

    def decision_probe(self,
                       factors: Optional[Dict[Tuple[int, int], float]]
                       = None) -> dict:
        """One controller decision at fleet scale: score the candidate
        set (incumbent / live ring / live Exp2) through the sparse
        spectral engine and pick the best predicted rate, measuring the
        decision latency — the N=1024 acceptance bound. Wire pricing is
        the per-step round count proxy (max live out-degree); the
        spectral term is the real engine with its convergence
        disclosure."""
        from bluefog_tpu import topology as topo_mod

        t0 = time.perf_counter()
        live = self.topo.live_ranks()
        sub_n, current = topo_mod.live_submatrix_edges(
            self.topo.edges_dict(), live
        )
        cands = {
            "current": current,
            "ring": ring_edges(sub_n),
            "exp2": exp2_edges(sub_n),
        }
        if factors:
            for edges in cands.values():
                for (s, d), f in factors.items():
                    w = edges.get((s, d))
                    if w is None or s == d:
                        continue
                    lost = (1.0 - min(max(float(f), 0.0), 1.0)) * w
                    edges[(s, d)] = w - lost
                    edges[(d, d)] = edges.get((d, d), 0.0) + lost
        scored = {}
        for name, edges in cands.items():
            rate, spec = topo_mod.consensus_decay_rate_info((sub_n, edges))
            out_deg: Dict[int, int] = {}
            for (i, j) in edges:
                if i != j:
                    out_deg[i] = out_deg.get(i, 0) + 1
            rounds = max(out_deg.values()) if out_deg else 0
            scored[name] = {
                "rate": float(rate),
                "rounds": int(rounds),
                "steps_to_eps": (
                    float(math.log(1e-6) / math.log(rate))
                    if 0.0 < rate < 1.0 - 1e-12 else None
                ),
                "spectral": {
                    "engine": spec.get("engine"),
                    "matvecs": spec.get("matvecs", 0),
                    "residual": spec.get("residual", 0.0),
                    "converged": spec.get("converged", True),
                },
            }
        def _objective(s):
            if s["steps_to_eps"] is None:
                return float("inf")
            return s["steps_to_eps"] * max(s["rounds"], 1)
        chosen = min(scored, key=lambda k: _objective(scored[k]))
        ms = (time.perf_counter() - t0) * 1e3
        self.last_decision_ms = ms
        metrics_mod.histogram("bluefog.fleetsim.decision_ms").observe(ms)
        row = {
            "metric": "fleetsim_decision",
            "step": int(self.step),
            "n_live": sub_n,
            "chosen": chosen,
            "decision_ms": round(ms, 3),
            "candidates": scored,
        }
        self._record(row)
        return row

    # -- summary ---------------------------------------------------------------

    def summary(self) -> dict:
        """The storm-timeline summary the report tool renders."""
        worst = None
        for e in self.events:
            if e.get("metric") == "fleetsim_repair":
                if worst is None or e["event_ms"] > worst["event_ms"]:
                    worst = e
        return {
            "n": self.n,
            "topology": self.topology,
            "policy": self.topo.policy,
            "steps": int(self.step),
            "live": self._live_count,
            "dead": self.n - self._live_count,
            "epoch": int(self.membership.epoch),
            "topo_version": int(self.topo_version),
            "repairs": int(self.repairs),
            "stale_dispatches": int(self.stale_dispatches),
            "cache_hits": int(self.cache_hits),
            "cache_misses": int(self.cache_misses),
            "advisories": [
                a.to_json() for a in self.advisories
            ],
            "worst_event_ms": (
                worst["event_ms"] if worst is not None else None
            ),
            "last_decision_ms": self.last_decision_ms,
        }
