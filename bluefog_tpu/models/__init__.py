# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Benchmark / example model zoo.

The reference treats models as externals (torchvision ResNet50 in
``examples/pytorch_benchmark.py``, a small conv/MLP net in
``examples/pytorch_mnist.py``); the TPU rebuild ships its own flax
implementations so the BASELINE configs are reproducible without torch.
"""

from bluefog_tpu.models.resnet import (
    ResNet, ResNet18, ResNet34, ResNet50, ResNet101, ResNet152,
)
from bluefog_tpu.models.mlp import MLP, MnistCNN
from bluefog_tpu.models.transformer import TransformerLM

__all__ = [
    "ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101", "ResNet152",
    "MLP", "MnistCNN", "TransformerLM",
]
