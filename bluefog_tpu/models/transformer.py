# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Decoder-only transformer LM with pluggable sequence-parallel attention.

The reference has no transformer (its examples are ResNet/MNIST-scale,
data-parallel only); this model exists so the framework's long-context
layer (:mod:`bluefog_tpu.ops.attention`) can be exercised end-to-end: the
attention implementation is injected, so the SAME module runs dense on
one device or ring/Ulysses sequence-parallel inside ``shard_map`` —
weights are identical either way, which is what the equivalence tests
rely on.
"""

from typing import Any, Callable, Optional

import jax.numpy as jnp
import flax.linen as nn

from bluefog_tpu.ops.attention import reference_attention  # noqa: F401 (re-export)
from bluefog_tpu.ops.flash import flash_attention

__all__ = ["TransformerLM"]


class Block(nn.Module):
    dim: int
    heads: int
    attend: Callable
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        split = lambda t: t.reshape(
            t.shape[0], t.shape[1], self.heads, self.dim // self.heads
        )
        att = self.attend(split(q), split(k), split(v))
        att = att.reshape(x.shape[0], x.shape[1], self.dim)
        x = x + nn.Dense(self.dim, use_bias=False, dtype=self.dtype)(att)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(4 * self.dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        return x + nn.Dense(self.dim, dtype=self.dtype)(h)


class TransformerLM(nn.Module):
    """Tiny causal LM. ``attend(q, k, v)`` defaults to dense causal
    attention; pass a sequence-parallel block function (closed over the
    mesh axis) to shard the sequence. Positions are GLOBAL: pass
    ``pos_offset`` = this worker's first token index so sequence-sharded
    workers embed their true positions.

    Caveat: the out-of-range check below only fires for *static* int
    offsets. A traced offset (e.g. computed from ``lax.axis_index`` inside
    ``shard_map``) that pushes positions past ``max_len`` silently clamps
    the position gather — ensure ``n_shards * block_len <= max_len`` at
    call-site when the offset is traced."""

    vocab: int = 64
    dim: int = 32
    heads: int = 4
    layers: int = 2
    max_len: int = 4096
    dtype: Any = jnp.float32
    attend: Optional[Callable] = None
    # rematerialize each block in the backward pass: activation memory
    # drops from O(layers * T * dim) to O(T * dim), buying ~2x longer
    # single-chip context (e.g. 32k on a 16 GB v5e at dim 1024 / 12
    # layers) for ~1.3x backward FLOPs
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, pos_offset=0):
        # default attention: Pallas flash kernels on TPU (fwd + custom-VJP
        # bwd; measured 2.6-14.6x fwd / 3.2-5.2x fwd+bwd over the dense XLA path at T>=4096 — see
        # docs/performance.md), dense XLA elsewhere (flash_attention falls
        # back by itself)
        attend = self.attend or (
            lambda q, k, v: flash_attention(q, k, v, causal=True)
        )
        x = nn.Embed(self.vocab, self.dim, dtype=self.dtype)(tokens)
        pos_table = self.param(
            "pos", nn.initializers.normal(0.02), (self.max_len, self.dim)
        )
        if isinstance(pos_offset, int):
            # static offsets are checkable at trace time; the gather below
            # would silently CLAMP out-of-range positions otherwise
            if tokens.shape[1] + pos_offset > self.max_len:
                raise ValueError(
                    f"sequence of {tokens.shape[1]} tokens at offset "
                    f"{pos_offset} exceeds max_len={self.max_len}"
                )
        elif tokens.shape[1] > self.max_len:
            raise ValueError(
                f"block of {tokens.shape[1]} tokens exceeds "
                f"max_len={self.max_len}"
            )
        pos = (
            jnp.arange(tokens.shape[1]) + pos_offset
        )  # global positions under sequence sharding
        x = x + pos_table[pos][None].astype(self.dtype)
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.layers):
            # explicit names: nn.remat would otherwise rename modules to
            # CheckpointBlock_i, making params/checkpoints incompatible
            # across a remat toggle
            x = block_cls(
                dim=self.dim, heads=self.heads, attend=attend,
                dtype=self.dtype, name=f"Block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        return nn.Dense(self.vocab, dtype=jnp.float32)(x)
