# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Small models for optimizer tests and the MNIST example.

Counterpart of the reference test/example nets (``examples/pytorch_mnist.py``
Net: two convs + two dense; ``test/torch_optimizer_test.py`` uses small
MLPs to assert loss decrease per optimizer family).
"""

from typing import Sequence

import jax.numpy as jnp
import flax.linen as nn

__all__ = ["MLP", "MnistCNN"]


class MLP(nn.Module):
    """Plain MLP used by optimizer convergence tests."""

    features: Sequence[int] = (64, 32, 10)

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for f in self.features[:-1]:
            x = nn.relu(nn.Dense(f)(x))
        return nn.Dense(self.features[-1])(x)


class MnistCNN(nn.Module):
    """Conv net mirroring the reference MNIST example topology."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        if x.ndim == 3:
            x = x[..., None]
        x = nn.relu(nn.Conv(32, (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3))(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128)(x))
        return nn.Dense(self.num_classes)(x)
