# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""ResNet in flax for the BASELINE benchmark configs.

The reference benchmarks torchvision's ResNet50
(``examples/pytorch_benchmark.py:60-75``); this is a TPU-first flax
implementation: NHWC layout (TPU conv native), bfloat16 compute with
float32 batch-norm statistics and parameters, and everything shaped so XLA
tiles the convolutions onto the MXU.
"""

import functools
from typing import Any, Callable, Sequence, Tuple

import jax.numpy as jnp
import flax.linen as nn

__all__ = ["ResNet", "ResNet18", "ResNet34", "ResNet50", "ResNet101", "ResNet152"]

ModuleDef = Any


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    """3x3 -> 3x3 residual block (ResNet-18/34)."""

    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """NHWC ResNet; ``dtype=bfloat16`` keeps matmuls on the MXU while batch
    statistics accumulate in float32."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=nn.relu,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = functools.partial(
    ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock
)
ResNet101 = functools.partial(
    ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock
)
ResNet152 = functools.partial(
    ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock
)
