# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Flight recorder: an always-on, bounded-memory black box for gossip runs.

The reference's rank-0 coordinator could at least *name* the stuck
tensors when a run hung (its 60-s message-table scan,
``common/operations.cc:388-433``). The single-controller SPMD port has no
negotiation table to scan — when a rank dies mid-combine the elastic
layer repairs the graph, but the evidence of what happened in the
seconds *before* is gone, and the per-rank Chrome traces are disjoint
files with unaligned clocks. This module is the black box: a fixed-size
ring of structured events fed by the runtime itself (the
PyTorch-NCCL-flight-recorder shape, adapted to gossip), dumped to JSON
when something goes wrong and fused across ranks by
``tools/trace_merge.py``.

Design constraints, in order:

1. **~Zero hot-path cost.** One :func:`record` call is a monotonic-clock
   read plus one slot assignment into a preallocated list. There is no
   lock on the write path: each call takes a unique sequence number from
   an ``itertools.count`` (atomic under the GIL) and writes its own slot
   ``seq % capacity`` — concurrent writers (the training loop, the
   watchdog thread) never share a slot, and readers sort the snapshot by
   sequence. ``BENCH_MODE=flight`` re-checks the <=1 % per-step bound
   and the bitwise on/off trajectory pin every round.
2. **Bounded memory.** ``BLUEFOG_FLIGHT_CAPACITY`` slots (default 8192);
   old events are overwritten, never accumulated. Side tables that the
   postmortem needs regardless of ring age (the compiled CommPlan
   structures, the session clock handshake) are kept separately, bounded.
3. **Always on.** Enabled by default (``BLUEFOG_FLIGHT=0`` disables);
   recording never touches device values, so the training trajectory is
   bitwise-identical with the recorder on or off.

What gets recorded (event ``kind`` -> payload):

- ``session_start`` / ``session_end`` — clock handshake (unix ns,
  monotonic us, timeline us) + mesh shape + process index; the
  cross-rank alignment anchor ``tools/trace_merge.py`` uses.
- ``plan_compile`` — every CommPlan the compiler lowers (topology
  version, round count, live token); full round/edge structure is
  retained in a bounded side table for the postmortem.
- ``compile`` — XLA program (re)builds, by cache-key family.
- ``step_begin`` / ``step_dispatched`` — optimizer step boundaries with
  the communicating flag; the merge tool turns these into per-rank step
  spans and computes per-step critical paths over the plan's rounds.
- ``sync_begin`` / ``sync_ready`` — host blocking points (the moments a
  hang becomes observable).
- ``window_op`` — one-sided window traffic (put/get/accumulate/update).
- ``membership`` / ``fault`` / ``repair`` — elastic verdicts with
  epoch, reason, and the topology version the verdict was filed under.
- ``stall`` — watchdog deadline hits.
- ``advisory`` — observability diagnoses (:mod:`bluefog_tpu.
  attribution` degraded_link / straggler / recompile_storm /
  consensus_stall / ambient_drift, :mod:`bluefog_tpu.health`
  mixing_degraded, :mod:`bluefog_tpu.staleness` staleness_breach),
  with their evidence, kept eviction-proof in a side table like
  faults.
- ``staleness`` — per-sample delivered-age summaries from the
  staleness observatory's lineage lane (surface, mean/max age, lane
  self-check), so a postmortem can see whether data was going stale
  in the steps before a hang.
- ``memory`` — per-sample live-buffer totals and headroom from the
  memory observatory (:mod:`bluefog_tpu.memory`), so a postmortem can
  see the footprint trending toward the budget in the steps before an
  OOM.
- ``oom`` — a device allocation failure (real ``RESOURCE_EXHAUSTED``
  caught by the memory observatory's crash hooks, or the injected
  ``oom`` chaos fault); the ranked buffer census rides the advisory
  side table so it survives ring eviction.
- ``crash`` / ``sigterm`` — the run's last words.

Dump triggers: a watchdog stall, an elastic SUSPECT/DEAD verdict, an
unhandled exception, SIGTERM, or an explicit ``bf.flight_dump()``. The
automatic triggers write only when ``BLUEFOG_FLIGHT_DIR`` is configured
(set it, or launch with ``bfrun-tpu --flight-dir``); the dump file
``flight_<process_index>.json`` is rewritten in place, so the latest
dump always carries the fullest event window. See docs/flight.md.
"""

import itertools
import json
import os
import signal
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from bluefog_tpu import timeline as tl
from bluefog_tpu import watchdog
from bluefog_tpu.logging_util import logger

__all__ = [
    "FlightRecorder",
    "enabled",
    "record",
    "events",
    "note_plan",
    "note_fault",
    "note_advisory",
    "note_decision",
    "dump",
    "maybe_dump",
    "dump_dir",
    "reconfigure",
    "on_init",
    "on_shutdown",
    "DUMP_VERSION",
]

ENABLE_ENV = "BLUEFOG_FLIGHT"
CAPACITY_ENV = "BLUEFOG_FLIGHT_CAPACITY"
DIR_ENV = "BLUEFOG_FLIGHT_DIR"

DUMP_VERSION = 1

# How many compiled CommPlan structures the side table retains (newest
# kept). The postmortem needs the plan that was ACTIVE at the fault, and
# an elastic run compiles one plan per membership epoch; dynamic
# schedules add one entry per period step. 32 covers any plausible
# window between failure and dump.
_MAX_PLANS = 32


def _now_us() -> int:
    return time.monotonic_ns() // 1000


class FlightRecorder:
    """Fixed-capacity event ring. See the module docstring for the
    lock-free-ish write protocol."""

    def __init__(self, capacity: int):
        assert capacity > 0
        self.capacity = int(capacity)
        self._buf: List[Optional[Tuple]] = [None] * self.capacity
        self._seq = itertools.count()

    def record(self, kind: str, data: Optional[dict] = None) -> int:
        seq = next(self._seq)  # GIL-atomic: unique slot per writer
        self._buf[seq % self.capacity] = (seq, _now_us(), kind, data)
        return seq

    def events(self) -> List[dict]:
        """Snapshot of the ring as dicts, oldest first. Taken without a
        lock: a slot overwritten mid-snapshot just reflects the newer
        event (the ring's contract is "the last N events", not a
        consistent cut)."""
        snap = [e for e in list(self._buf) if e is not None]
        snap.sort(key=lambda e: e[0])
        return [
            {"seq": s, "t_us": t, "kind": k, **({"data": d} if d else {})}
            for s, t, k, d in snap
        ]

    def __len__(self) -> int:
        return sum(1 for e in self._buf if e is not None)


# -- module state -------------------------------------------------------------

_enabled_cache: Optional[bool] = None
_recorder: Optional[FlightRecorder] = None
_plans: List[dict] = []  # bounded side table of compiled plan structures
_faults: List[dict] = []  # bounded side table of fault verdicts: the
# postmortem's fault -> plan linkage must survive ring eviction on long
# runs, exactly like the plan structures themselves
_advisories: List[dict] = []  # bounded side table of doctor advisories
# (bluefog_tpu.attribution): a postmortem that cannot see "degraded_link
# fired 40 minutes ago" mis-tells the story, so advisory history gets
# the same eviction-proof treatment as faults
_decisions: List[dict] = []  # bounded side table of autotune decisions
# (bluefog_tpu.autotune): a postmortem of a run whose topology the
# controller changed mid-flight must carry WHY — the swap/rollback
# history survives ring eviction exactly like the advisories that
# triggered it
_slo: List[dict] = []  # bounded side table of SLO budget snapshots
# (bluefog_tpu.slo): a crash dump must carry the burn-rate and
# error-budget state that preceded it — "we died while paging on a
# burned budget" vs "we died green" is the first postmortem question
# — so the sampled snapshots survive ring eviction like the rest
_plans_lock = threading.Lock()
_hooks_installed = False
_prev_excepthook = None
_prev_sigterm = None
_dump_lock = threading.Lock()
# every dump reason this session, oldest first: the canonical dump file
# is rewritten in place, so a later explicit dump must not erase the
# fact that a verdict/stall trigger fired earlier (bounded)
_dump_history: List[str] = []


def enabled() -> bool:
    """Recorder switch, default ON (``BLUEFOG_FLIGHT=0`` disables). The
    value is cached for the hot path; :func:`reconfigure` (called by
    ``bf.init()``) re-reads the environment."""
    global _enabled_cache
    if _enabled_cache is None:
        _enabled_cache = os.environ.get(ENABLE_ENV, "1").lower() not in (
            "0", "false", "off", "no",
        )
    return _enabled_cache


def capacity() -> int:
    from bluefog_tpu.logging_util import env_int

    return max(256, env_int(CAPACITY_ENV, 8192))


def dump_dir() -> Optional[str]:
    """Directory the automatic triggers dump into (``BLUEFOG_FLIGHT_DIR``
    / ``bfrun-tpu --flight-dir``), or None when unset (automatic dumps
    disabled; explicit :func:`dump` still works)."""
    return os.environ.get(DIR_ENV) or None


def _rec() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        _recorder = FlightRecorder(capacity())
    return _recorder


def reconfigure() -> None:
    """Re-read the env knobs and start a fresh ring (one flight per
    session: ``bf.init()`` calls this so a dump never mixes events from
    a torn-down mesh with the new one)."""
    global _enabled_cache, _recorder
    _enabled_cache = None
    _recorder = None
    with _plans_lock:
        _plans.clear()
        _faults.clear()
        _advisories.clear()
        _decisions.clear()
        _slo.clear()
    del _dump_history[:]


def record(kind: str, **data) -> int:
    """Append one structured event to the ring; returns its sequence
    number (-1 when the recorder is disabled). ``data`` values must be
    JSON-serializable — they go into the dump verbatim."""
    if not enabled():
        return -1
    return _rec().record(kind, data or None)


def events() -> List[dict]:
    if _recorder is None:
        return []
    return _recorder.events()


def note_plan(plan, topo_version: int, live_token=None,
              kind: str = "worker") -> None:
    """Retain a compiled CommPlan's structure in the bounded side table
    (and drop a ``plan_compile`` ring event). The postmortem resolves
    "which edge/round was rank j waiting on" from exactly this record,
    so it must survive ring eviction. ``kind`` distinguishes worker-rank
    plans from hierarchical *machine*-graph plans — their version
    counters are independent and their node ids mean different things,
    so the postmortem must never match a fault against the wrong kind."""
    if not enabled():
        return
    entry = {
        "kind": kind,
        "topo_version": int(topo_version),
        "n_rounds": len(plan.rounds),
        "rounds": [
            [[int(s), int(d)] for s, d in rnd.perm] for rnd in plan.rounds
        ],
        "live": (
            None if live_token is None
            else {"epoch": live_token[0], "ranks": list(live_token[1])}
        ),
    }
    with _plans_lock:
        if entry in _plans:
            # dynamic-weight plans are rebuilt per dispatch (no cache in
            # front of them): retain the structure once, and don't spam
            # the ring with a plan_compile event per step
            return
        _plans.append(entry)
        del _plans[:-_MAX_PLANS]
    record(
        "plan_compile", topo_version=entry["topo_version"],
        n_rounds=entry["n_rounds"],
        live_epoch=None if live_token is None else live_token[0],
    )


def note_fault(**data) -> None:
    """Record a fault verdict in BOTH the ring and a bounded side table:
    the postmortem resolves the fault's topology version against the
    plan side table, and that linkage must not depend on the fault event
    still being in the (evicted-on-overflow) ring when the dump fires."""
    if not enabled():
        return
    with _plans_lock:
        _faults.append(dict(data))
        del _faults[:-64]
    record("fault", **data)


def note_advisory(**data) -> None:
    """Record a doctor advisory (:mod:`bluefog_tpu.attribution`) in BOTH
    the ring and a bounded side table, mirroring :func:`note_fault`: the
    triage report (``tools/doctor.py``) joins advisories against dump
    reasons and fault verdicts, and that history must survive ring
    eviction."""
    if not enabled():
        return
    with _plans_lock:
        _advisories.append(dict(data))
        del _advisories[:-64]
    # the ring event's own kind is "advisory"; the diagnosis kind rides
    # as advisory_kind (same convention as note_fault's fault_kind)
    record("advisory", **{
        ("advisory_kind" if k == "kind" else k): v
        for k, v in data.items()
    })


def note_decision(**data) -> None:
    """Record an autotune controller decision
    (:mod:`bluefog_tpu.autotune`) in BOTH the ring and a bounded side
    table, mirroring :func:`note_advisory`: the postmortem of a run
    whose topology was swapped mid-flight must name the decision that
    swapped it — and that record must survive ring eviction on a long
    run."""
    if not enabled():
        return
    with _plans_lock:
        _decisions.append(dict(data))
        del _decisions[:-64]
    record("autotune", **data)


def note_slo(**data) -> None:
    """Record an SLO budget snapshot (:mod:`bluefog_tpu.slo`) in BOTH
    the ring and a bounded side table, mirroring
    :func:`note_decision`: the postmortem must read the worst burn
    rate and exhausted-objective set leading into a crash even after
    the ring evicts the samples."""
    if not enabled():
        return
    with _plans_lock:
        _slo.append(dict(data))
        del _slo[:-64]
    record("slo", **data)


def _clock_triple() -> dict:
    """The cross-rank alignment anchor: the same instant on all three
    clocks this process emits timestamps in — wall (shared across
    hosts), monotonic (flight events), timeline (Chrome-trace ts)."""
    return {
        "unix_ns": time.time_ns(),
        "mono_us": _now_us(),
        "timeline_us": (
            tl.timeline_now_us() if tl.timeline_enabled() else None
        ),
    }


def _owned_ranks(ctx) -> List[int]:
    """Mesh slots this controller process is responsible for (all of
    them on a single controller; the local devices' positions on a
    multi-host pod)."""
    try:
        import jax

        proc = jax.process_index()
        if jax.process_count() > 1:
            return [
                i for i, d in enumerate(ctx.devices)
                if getattr(d, "process_index", proc) == proc
            ]
    except Exception:
        pass
    return list(range(ctx.size))


def _build_dump(reason: str) -> dict:
    from bluefog_tpu import context as ctx_mod
    from bluefog_tpu import metrics as metrics_mod

    out: Dict[str, Any] = {
        "version": DUMP_VERSION,
        "reason": reason,
        "process_index": tl.process_file_index(),
        "clock": _clock_triple(),
    }
    ctx = ctx_mod._context  # do not raise if uninitialized: a crash dump
    # must succeed even before/after init
    if ctx is not None:
        out["world"] = {
            "size": ctx.size,
            "machine_size": ctx.machine_size,
            "local_size": ctx.local_size,
            "topo_version": ctx.topo_version,
            "ranks": _owned_ranks(ctx),
        }
        m = ctx.elastic_membership
        if m is not None:
            out["membership"] = {
                "epoch": m.epoch,
                "live": list(m.live_ranks()),
                "dead": list(m.dead_ranks()),
                "history": [
                    list(h) for h in m.history[-64:]
                ],
            }
    try:
        from bluefog_tpu import elastic as elastic_mod

        session = elastic_mod.active_session()
        if session is not None:
            out["faults"] = [
                {
                    "kind": f.kind, "rank": f.rank, "step": f.step,
                    "seconds": f.seconds, "factor": f.factor,
                }
                for f in session.plan.faults
            ]
    except Exception:  # a broken elastic import must not lose the dump
        pass
    with _plans_lock:
        out["comm_plans"] = list(_plans)
        out["fault_events"] = list(_faults)
        out["advisories"] = list(_advisories)
        out["autotune_decisions"] = list(_decisions)
        out["slo_snapshots"] = list(_slo)
    try:
        out["metrics"] = metrics_mod.snapshot()
    except Exception:
        out["metrics"] = {}
    out["events"] = events()
    return out


def dump(path: Optional[str] = None, reason: str = "explicit") -> str:
    """Write the flight dump as JSON and return the path written.

    ``path`` defaults to ``<BLUEFOG_FLIGHT_DIR or .>/flight_<process
    index>.json``. The write is atomic (tmp + rename): a dump raced by a
    crashing process must never leave a half-written JSON — the file
    exists precisely to be read after something went wrong."""
    if path is None:
        base = dump_dir() or "."
        os.makedirs(base, exist_ok=True)
        path = os.path.join(
            base, f"flight_{tl.process_file_index()}.json"
        )
    with _dump_lock:
        _dump_history.append(reason)
        del _dump_history[:-32]
        payload = _build_dump(reason)
        payload["dump_history"] = list(_dump_history)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    return path


def maybe_dump(reason: str) -> Optional[str]:
    """Automatic-trigger dump: writes ``flight_<proc>.json`` into
    ``BLUEFOG_FLIGHT_DIR`` when that is configured, else does nothing
    (an unconfigured training run must not litter its cwd). Never
    raises — a failing dump must not take down the run it is trying to
    explain."""
    if not enabled() or dump_dir() is None:
        return None
    try:
        return dump(reason=reason)
    except Exception:
        logger.exception("flight dump (%s) failed", reason)
        return None


# -- automatic triggers -------------------------------------------------------


def _on_stall(name: str, waited: float) -> None:
    """Watchdog subscriber: a blocking wait outlived its deadline — the
    exact moment a hang becomes observable, so the black box goes to
    disk now, while the evidence is fresh."""
    record("stall", name=name, waited_s=round(float(waited), 3))
    maybe_dump(f"stall:{name}")


def _excepthook(exc_type, exc, tb):
    try:
        record(
            "crash", type=exc_type.__name__, message=str(exc)[:300]
        )
        maybe_dump(f"exception:{exc_type.__name__}")
    except Exception:
        pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def _sigterm_handler(signum, frame):
    try:
        record("sigterm")
        maybe_dump("sigterm")
    except Exception:
        pass
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
        return
    # default/ignored disposition: restore it and re-deliver so the
    # process still dies with the expected SIGTERM status
    signal.signal(signal.SIGTERM, prev if prev is not None
                  else signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def _install_crash_hooks() -> None:
    global _hooks_installed, _prev_excepthook, _prev_sigterm
    if _hooks_installed:
        return
    _prev_excepthook = sys.excepthook
    sys.excepthook = _excepthook
    try:
        _prev_sigterm = signal.signal(signal.SIGTERM, _sigterm_handler)
    except (ValueError, OSError):  # not the main thread / exotic platform
        _prev_sigterm = None
    _hooks_installed = True


def _uninstall_crash_hooks() -> None:
    global _hooks_installed, _prev_excepthook, _prev_sigterm
    if not _hooks_installed:
        return
    if sys.excepthook is _excepthook:
        sys.excepthook = _prev_excepthook or sys.__excepthook__
    try:
        if signal.getsignal(signal.SIGTERM) is _sigterm_handler:
            signal.signal(
                signal.SIGTERM,
                _prev_sigterm if _prev_sigterm is not None
                else signal.SIG_DFL,
            )
    except (ValueError, OSError):
        pass
    _hooks_installed = False
    _prev_excepthook = None
    _prev_sigterm = None


# -- session lifecycle (called by bluefog_tpu.context) ------------------------


def on_init(ctx) -> None:
    """Open the black box for a fresh session: new ring, clock
    handshake event, watchdog subscription, and (when a dump directory
    is configured) the crash hooks."""
    reconfigure()
    if not enabled():
        return
    record(
        "session_start",
        **_clock_triple(),
        process_index=tl.process_file_index(),
        size=ctx.size,
        machine_size=ctx.machine_size,
        pid=os.getpid(),
    )
    watchdog.add_stall_handler(_on_stall)  # idempotent (same fn object)
    if dump_dir() is not None:
        _install_crash_hooks()


def on_shutdown() -> None:
    record("session_end", **_clock_triple())
    watchdog.remove_stall_handler(_on_stall)
    _uninstall_crash_hooks()
