# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Memory observatory (``bf.memory``): live HBM/host accounting,
analytic-vs-measured reconciliation, and OOM forensics — the eighth
observability tier.

Seven tiers measure time, wire bytes, mixing, staleness, health and
topology decisions; none measures the one resource that actually kills
large runs: device memory. The weight-update sharding PR shipped an
*analytic* memory model (:func:`bluefog_tpu.scaling.
optimizer_state_bytes`, arxiv 2004.13336) with no measured counterpart
to reconcile it against, the kernel-fusion roadmap item (EQuARX, arxiv
2506.17615) needs a measured baseline of the full-width temporaries the
quantize→pack→ppermute→unpack chain materializes today, and an OOM
produces a bare XLA ``RESOURCE_EXHAUSTED`` with no flight dump, no
buffer census, and no advisory — the only failure mode the black box
does not record. This module closes all three gaps.

**Sampling discipline (PR-3).** 1-in-``BLUEFOG_MEMORY_INTERVAL``
communicating steps take a sample; the observatory is purely host-side
(``jax.live_arrays()`` census + device memory stats + RSS reads), so
unsampled steps — and sampled ones — dispatch the bitwise-identical
observatory-off training program under the SAME cache key
(structural + bitwise pinned by ``BENCH_MODE=memory``).

**Per sample:**

- **live-buffer census** — every live jax array classified by owner
  (``params``, ``opt_state`` incl. sharded slots, ``residuals`` =
  CHOCO/EF copies, ``delay`` buffers, ``windows``, ``other``) from the
  trees the optimizer layer hands the hook plus the window registry.
  The census total and per-category bytes land as
  ``bluefog.memory.*`` gauges and in the ``BLUEFOG_MEMORY_FILE``
  JSONL.
- **analytic-vs-measured reconciliation** — the measured per-rank
  optimizer-state bytes (census) against the analytic
  :func:`~bluefog_tpu.scaling.optimizer_state_bytes` model for the
  active shard configuration. A residual past
  ``BLUEFOG_MEMORY_DRIFT_TOL`` (default 10 %) for
  :data:`DRIFT_STREAK` consecutive samples — a leak, a stale buffer
  generation, an unaccounted slot — fires a ``memory_drift`` advisory
  through the PR-7 plumbing (doctor counter, flight side table,
  timeline instant, JSONL).
- **watermark + headroom** — a peak-bytes watermark (census total,
  plus per-phase peaks from :func:`phase_scope` around compile /
  dispatch / checkpoint-save), tracked EWMA+MAD
  (:class:`~bluefog_tpu.attribution.BaselineTracker`). With a
  per-chip budget (``BLUEFOG_MEMORY_BUDGET`` bytes), measured
  headroom below the predicted next-step watermark fires a
  ``memory_pressure`` advisory whose detail carries a
  **shard-recommendation hint**: when the optimizer state dominates
  and ``BLUEFOG_SHARD`` is off, the advisory says so (the 1/N shard
  is the one knob that buys back that category). Autotune
  :class:`~bluefog_tpu.autotune.DecisionRecord` entries carry a
  ``memory_pressure`` flag so the audit trail shows which topology
  decisions were taken under memory pressure.

**OOM forensics.** Crash hooks installed beside the PR-5 SIGTERM hooks:
an uncaught ``MemoryError`` or an XLA error whose message carries
``RESOURCE_EXHAUSTED`` records an ``oom`` flight event, files an
eviction-proof ``oom`` advisory whose detail is the **ranked buffer
census** (largest owner category first, from the last sample — the
allocation that failed is precisely the moment a fresh census cannot
run), and dumps the flight ring. A new ``oom`` chaos fault kind
(:mod:`bluefog_tpu.elastic.faults`) simulates the failure
deterministically so the postmortem is a tier-1 unit test:
``inject("oom", rank=r, step=s)`` runs the same forensics path and
raises :class:`SimulatedResourceExhausted`. ``tools/memory_report.py``
reconstructs the postmortem — who was the biggest owner when the chip
ran out — from the committed dump/JSONL artifacts alone.

**Fleet.** Each rank's census total and headroom ride the health
plane's push-sum lane (two ``FLEET_FIELDS`` slots), ``/fleet`` carries
a ``memory`` block, and ``tools/fleet_report.py`` renders the
columns.

Env knobs: ``BLUEFOG_MEMORY=1`` (default off),
``BLUEFOG_MEMORY_INTERVAL`` (default 20 communicating steps),
``BLUEFOG_MEMORY_BUDGET`` (per-chip bytes; 0/unset = no budget, no
pressure gate), ``BLUEFOG_MEMORY_DRIFT_TOL`` (relative reconciliation
tolerance, default 0.10), ``BLUEFOG_MEMORY_FILE`` (JSONL samples +
advisories). See docs/memory.md.
"""

import collections
import contextlib
import json
import os
import sys
from typing import Any, Dict, List, Optional

__all__ = [
    "MemoryObservatory",
    "SimulatedResourceExhausted",
    "CATEGORIES",
    "enabled",
    "memory_interval",
    "memory_budget",
    "drift_tolerance",
    "device_bytes_in_use",
    "host_peak_rss_bytes",
    "census",
    "ranked_census",
    "phase_scope",
    "start",
    "stop",
    "activate",
    "active",
    "observe_step",
    "on_oom",
    "dump",
    "on_init",
    "on_shutdown",
]

ENABLE_ENV = "BLUEFOG_MEMORY"
INTERVAL_ENV = "BLUEFOG_MEMORY_INTERVAL"
BUDGET_ENV = "BLUEFOG_MEMORY_BUDGET"
DRIFT_TOL_ENV = "BLUEFOG_MEMORY_DRIFT_TOL"
FILE_ENV = "BLUEFOG_MEMORY_FILE"

# Owner categories of the live-buffer census, in ranking-tiebreak
# order. "grads" covers the gradient buffers the optimizer layer holds
# across a dispatch (the input gradient tree plus the K>1 accumulator
# — full-width replicated, or the 1/N scattered slots under
# BLUEFOG_SHARD_GRADS=1, so the ZeRO-2 memory claim is visible in the
# census), "residuals" the CHOCO error-feedback copies (gossip pairs
# and the per-slot scatter residuals), "delay" the delayed-combine
# double buffers, "windows" every win_create buffer (value + neighbor
# slots + p lanes), "wire_temp" is reserved for the XLA temporary
# accounting (BENCH_MODE=memory reads it from the compiled program,
# not from live arrays), "other" is everything unattributed — batches,
# user state, framework internals.
CATEGORIES = (
    "params", "opt_state", "grads", "residuals", "delay", "windows",
    "wire_temp", "other",
)

# memory_drift gate: the relative analytic-vs-measured residual must
# exceed the tolerance for this many CONSECUTIVE samples before the
# advisory fires — one sample mid-rebuild (old and new buffer
# generations briefly coexist) is churn, not a leak.
DRIFT_STREAK = 2
# memory_pressure / memory_drift re-fire mute, in samples (the
# staleness-breach cooldown discipline): a persistently tight chip
# keeps its counter raised without flooding the flight ring.
ADVISORY_COOLDOWN = 8
# predicted next-step watermark = EWMA mean + this many MADs (the
# advisory-gate z the doctor's trackers use throughout).
WATERMARK_MADS = 3.0


class SimulatedResourceExhausted(MemoryError):
    """The chaos layer's deterministic stand-in for an XLA
    ``RESOURCE_EXHAUSTED`` allocation failure (the ``oom`` fault
    kind). A ``MemoryError`` subclass whose message carries the XLA
    casing, so every detection path — the crash hooks' type check and
    their message scan — sees exactly what a real OOM produces."""

    def __init__(self, detail: str = ""):
        super().__init__(
            "RESOURCE_EXHAUSTED: simulated allocation failure"
            + (f" ({detail})" if detail else "")
        )


def enabled() -> bool:
    """Observatory switch: ``BLUEFOG_MEMORY=1`` (default off) — opt-in
    like the metrics device tier, the doctor, and the staleness
    observatory. The OOM crash hooks are independent of this knob:
    they install whenever the flight recorder has a dump directory
    configured (``BLUEFOG_FLIGHT_DIR``), the same condition as the
    PR-5 crash hooks they stand beside — forensics follow the black
    box's configuration, not the sampling tier's."""
    return os.environ.get(ENABLE_ENV, "0").lower() in (
        "1", "true", "on", "yes",
    )


def memory_interval() -> int:
    """Sampling period in communicating steps
    (``BLUEFOG_MEMORY_INTERVAL``, default 20). A sample is one
    ``jax.live_arrays()`` walk plus O(leaves) id lookups — host-only —
    so the default keeps the amortized cost under the 1 % acceptance
    bound re-measured by ``BENCH_MODE=memory``."""
    from bluefog_tpu.logging_util import env_int

    return max(1, env_int(INTERVAL_ENV, 20))


def memory_budget() -> int:
    """Per-chip memory budget in bytes (``BLUEFOG_MEMORY_BUDGET``; 0 /
    unset disables the headroom gate). On a real TPU this is the HBM
    capacity minus the reserve the serving stack needs; on the CPU CI
    mesh it is whatever the test simulates."""
    from bluefog_tpu.logging_util import env_int

    return max(0, env_int(BUDGET_ENV, 0))


def drift_tolerance() -> float:
    """Relative analytic-vs-measured reconciliation tolerance
    (``BLUEFOG_MEMORY_DRIFT_TOL``, default 0.10). The analytic model
    prices the slot layout exactly, so a persistent residual past this
    is a real unaccounted buffer, not rounding."""
    from bluefog_tpu.logging_util import env_float

    tol = env_float(DRIFT_TOL_ENV, 0.10)
    return tol if tol > 0 else 0.10


# -- measurement primitives ---------------------------------------------------


def device_bytes_in_use(ctx=None) -> Optional[int]:
    """``bytes_in_use`` summed over the context's devices via the
    runtime's ``memory_stats()`` (real HBM numbers on TPU). None where
    the backend exposes no stats — the CPU CI mesh — in which case the
    census total is the measured stand-in and the artifact says so."""
    try:
        import jax

        devices = ctx.devices if ctx is not None else jax.devices()
        total = 0
        seen = False
        for d in devices:
            stats = getattr(d, "memory_stats", lambda: None)()
            if stats and "bytes_in_use" in stats:
                total += int(stats["bytes_in_use"])
                seen = True
        return total if seen else None
    except Exception:
        return None


def host_peak_rss_bytes() -> int:
    """Peak resident set size of this controller process in bytes
    (Linux ``ru_maxrss`` is KiB; 0 where unavailable)."""
    try:
        import resource

        return int(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        ) * 1024
    except Exception:
        return 0


def census(owners: Dict[str, Any]) -> Dict[str, Dict[str, int]]:
    """Classify every live jax array by owner category.

    ``owners`` maps category name -> pytree of arrays (the optimizer
    layer passes the CURRENT params / optax state / EF copies / delay
    buffers; the window registry is folded in by the observatory).
    Identity is ``id()`` membership — jax arrays are replaced
    functionally every step, so the map is built fresh per sample from
    the trees that are live *now*, never registered and left to go
    stale. Everything unmatched is ``other``. Returns
    ``{category: {"bytes": B, "arrays": N}}`` with every category
    present (zeros included) so artifact rows are schema-stable."""
    import jax

    id2cat: Dict[int, str] = {}
    for cat, tree in owners.items():
        for leaf in jax.tree_util.tree_leaves(tree):
            id2cat[id(leaf)] = cat
    out = {c: {"bytes": 0, "arrays": 0} for c in CATEGORIES}
    for arr in jax.live_arrays():
        cat = id2cat.get(id(arr), "other")
        rec = out.setdefault(cat, {"bytes": 0, "arrays": 0})
        try:
            nbytes = int(arr.nbytes)
        except Exception:
            continue
        rec["bytes"] += nbytes
        rec["arrays"] += 1
    return out


def ranked_census(c: Optional[Dict[str, Dict[str, int]]] = None
                  ) -> List[dict]:
    """The census as a ranked list, largest owner first — the form the
    OOM postmortem names its suspect in. With no argument, uses the
    active observatory's last census (the fresh-census fallback exists
    because the crash hook may fire before the first sample)."""
    if c is None:
        obs = _observatory
        c = obs.last_census if obs is not None else None
        if c is None:
            try:
                c = census({})
            except Exception:
                c = {}
    rows = [
        {"category": cat, "bytes": rec["bytes"],
         "arrays": rec["arrays"]}
        for cat, rec in c.items() if rec["arrays"] or rec["bytes"]
    ]
    rows.sort(key=lambda r: (-r["bytes"], r["category"]))
    return rows


# -- phase watermarks ---------------------------------------------------------


@contextlib.contextmanager
def phase_scope(name: str):
    """Bracket one step phase (``compile`` / ``dispatch`` /
    ``checkpoint_save``) so the observatory can decompose the peak
    watermark by phase. No-op — one global read — when no observatory
    session is active; never touches device values, so the bitwise
    pin holds trivially."""
    obs = _observatory
    if obs is None:
        yield
        return
    rss0 = host_peak_rss_bytes()
    try:
        yield
    finally:
        obs._note_phase(name, rss0)


# -- the observatory session --------------------------------------------------


class MemoryObservatory:
    """One memory session. Built by :func:`start` (or implicitly by
    ``bf.init()`` under ``BLUEFOG_MEMORY=1``); fed by the optimizer
    layer through :func:`observe_step` after every communicating
    dispatch."""

    def __init__(self, interval: Optional[int] = None,
                 budget: Optional[int] = None,
                 drift_tol: Optional[float] = None,
                 history: int = 512):
        from bluefog_tpu.attribution import BaselineTracker

        self.interval = int(interval) if interval else memory_interval()
        self.budget = int(budget) if budget is not None else memory_budget()
        self.drift_tol = (
            float(drift_tol) if drift_tol else drift_tolerance()
        )
        self._count = 0  # communicating steps observed
        self.samples: collections.deque = collections.deque(
            maxlen=history
        )
        self.advisories: List[Any] = []
        self.last_census: Optional[Dict[str, Dict[str, int]]] = None
        self._peak_tracker = BaselineTracker()
        self._drift_streak = 0
        self._mutes: Dict[str, int] = {}  # advisory kind -> cooldown
        self.phase_peaks: Dict[str, Dict[str, float]] = {}
        self._peak_bytes = 0.0
        self._last_total = 0.0
        self._last_per_rank = 0.0
        self._last_headroom: Optional[float] = None
        self._analytic_cache: Optional[tuple] = None
        self._last_step = 0
        self.oom_events = 0

    # -- fleet-facing state ---------------------------------------------------

    def last_bytes_per_rank(self) -> float:
        """Census total divided by the mesh size at the latest sample
        (0.0 before the first) — the per-chip usage estimate the fleet
        lane aggregates. On a single-controller virtual mesh the
        worker-stacked arrays hold every rank's slice in one host
        process, so total/size is exactly the per-chip share."""
        return self._last_per_rank

    def last_headroom(self) -> float:
        """Budget minus per-rank usage at the latest sample (0.0 when
        no budget is configured — the lane aggregates a number, and an
        unbudgeted rank must not read as infinitely roomy)."""
        h = self._last_headroom
        return float(h) if h is not None else 0.0

    # -- phase watermarks -----------------------------------------------------

    def _note_phase(self, name: str, rss0: int) -> None:
        from bluefog_tpu import metrics as metrics_mod

        rss1 = host_peak_rss_bytes()
        rec = self.phase_peaks.setdefault(
            name, {"peak_rss_bytes": 0.0, "rss_growth_bytes": 0.0,
                   "count": 0}
        )
        rec["peak_rss_bytes"] = max(rec["peak_rss_bytes"], float(rss1))
        rec["rss_growth_bytes"] += float(max(rss1 - rss0, 0))
        rec["count"] += 1
        metrics_mod.gauge(
            f"bluefog.memory.phase_peak_bytes.{name}"
        ).set(rec["peak_rss_bytes"])

    # -- advisory gating ------------------------------------------------------

    def _tick_mutes(self) -> None:
        """Advance the re-fire cooldowns by one SAMPLE — called once
        per sample, not per gate check, so a mute expires after
        :data:`ADVISORY_COOLDOWN` samples of wall progress regardless
        of whether anything fired in between (a stale mute must never
        swallow a new episode hours later), and one kind's gate never
        drains another kind's cooldown."""
        for k in list(self._mutes):
            self._mutes[k] -= 1
            if self._mutes[k] <= 0:
                del self._mutes[k]

    def _unmuted(self, kind: str) -> bool:
        if kind in self._mutes:
            return False
        self._mutes[kind] = ADVISORY_COOLDOWN
        return True

    def pressure_active(self) -> bool:
        """True while a ``memory_pressure`` advisory is inside its
        re-fire cooldown — the precise form of "an un-cooled-down
        pressure advisory on record" the autotune decision flag
        documents."""
        return "memory_pressure" in self._mutes

    # -- observation ----------------------------------------------------------

    def observe(self, ctx, *, step: int, optimizer=None, params=None,
                opt_state=None, grads=None) -> Optional[dict]:
        """Called once per communicating step. Unsampled steps cost one
        compare + one increment; the sampled step walks the live-array
        census and reconciles it against the analytic models."""
        sampled = self._count % self.interval == 0
        self._count += 1
        # TRAINING-step clock for the OOM record: every other advisory
        # carries the training step, and a postmortem join that mixes
        # clocks mis-orders the OOM against the pressure warnings that
        # preceded it
        self._last_step = int(step)
        if not sampled:
            return None
        return self._sample(
            ctx, step=step, optimizer=optimizer, params=params,
            opt_state=opt_state, grads=grads,
        )

    def _owner_trees(self, ctx, optimizer, params, opt_state,
                     grads=None) -> Dict:
        owners: Dict[str, Any] = {}
        if params is not None:
            owners["params"] = params
        if opt_state is not None:
            owners["opt_state"] = opt_state
        grad_trees = []
        if grads is not None:
            grad_trees.append(grads)
        if optimizer is not None:
            ef = getattr(optimizer, "_ef", None)
            scatter_ef = getattr(optimizer, "_scatter_ef", None)
            if ef or scatter_ef:
                owners["residuals"] = (ef or (), scatter_ef or ())
            buf = getattr(optimizer, "_delay_buf", None)
            if buf:
                owners["delay"] = buf
            accum = getattr(optimizer, "_grad_accum", None)
            if accum is not None:
                grad_trees.append(accum)
        if grad_trees:
            owners["grads"] = grad_trees
        wins = getattr(ctx, "windows", None)
        if wins:
            owners["windows"] = [
                (w.value, w.buffers, w.versions, w.p, w.p_buffers)
                for w in wins.values()
            ]
        return owners

    def _analytic_state_bytes(self, ctx, optimizer, params,
                              opt_state) -> Optional[int]:
        """The analytic per-rank optimizer-state model for the ACTIVE
        shard configuration (None when there is nothing to price).
        Cached on (param avals, shard signature, tx version): the
        model only moves when one of those does, and re-running
        ``jax.eval_shape`` per sample would spend the overhead budget
        on re-deriving a constant."""
        if optimizer is None or params is None:
            return None
        try:
            import jax

            from bluefog_tpu import scaling

            shard_l = getattr(optimizer, "_shard_layout", None)
            key = (
                tuple(
                    (tuple(l.shape), str(l.dtype))
                    for l in jax.tree_util.tree_leaves(params)
                ),
                shard_l.sig() if shard_l is not None else None,
                getattr(optimizer, "_tx_version", None),
            )
            cached = self._analytic_cache
            if cached is not None and cached[0] == key:
                return cached[1]
            val = scaling.optimizer_state_bytes(
                params, optimizer, shard=shard_l is not None,
            )
            self._analytic_cache = (key, val)
            return val
        except Exception:
            return None

    def _sample(self, ctx, *, step, optimizer, params,
                opt_state, grads=None) -> dict:
        from bluefog_tpu import flight as flight_mod
        from bluefog_tpu import metrics as metrics_mod

        self._tick_mutes()
        owners = self._owner_trees(
            ctx, optimizer, params, opt_state, grads=grads
        )
        c = census(owners)
        self.last_census = c
        total = float(sum(rec["bytes"] for rec in c.values()))
        size = max(int(getattr(ctx, "size", 1)), 1)
        per_rank = total / size
        dev_bytes = device_bytes_in_use(ctx)
        measured_per_rank = (
            dev_bytes / size if dev_bytes is not None else per_rank
        )
        self._last_total = total
        self._last_per_rank = measured_per_rank
        self._peak_bytes = max(self._peak_bytes, measured_per_rank)

        # registry gauges
        metrics_mod.counter("bluefog.memory.samples").inc()
        metrics_mod.gauge("bluefog.memory.live_bytes").set(total)
        for cat in CATEGORIES:
            if cat == "wire_temp":
                # reserved for the compiled-program scratch accounting
                # (BENCH_MODE=memory reads it from memory_analysis());
                # the live-array census can never populate it, and a
                # permanently-zero gauge is registry noise
                continue
            metrics_mod.gauge(
                f"bluefog.memory.live_bytes.{cat}"
            ).set(c.get(cat, {}).get("bytes", 0))
        metrics_mod.gauge("bluefog.memory.peak_bytes").set(
            self._peak_bytes
        )
        metrics_mod.gauge("bluefog.memory.host_rss_bytes").set(
            host_peak_rss_bytes()
        )

        # analytic-vs-measured optimizer-state reconciliation
        measured_state = c.get("opt_state", {}).get("bytes", 0) / size
        analytic_state = self._analytic_state_bytes(
            ctx, optimizer, params, opt_state
        )
        rel_err = None
        if analytic_state:
            rel_err = abs(measured_state - analytic_state) / analytic_state
            metrics_mod.gauge("bluefog.memory.drift_bytes").set(
                measured_state - analytic_state
            )

        sample: Dict[str, Any] = {
            "kind": "sample",
            "step": int(step),
            "comm_steps": self._count,
            "live_bytes_total": int(total),
            "live_bytes_per_rank": int(per_rank),
            "device_bytes_in_use": dev_bytes,
            "measured_source": (
                "device_memory_stats" if dev_bytes is not None
                else "live_array_census"
            ),
            "host_peak_rss_bytes": host_peak_rss_bytes(),
            "census": {
                cat: dict(rec) for cat, rec in c.items()
                if rec["arrays"] or rec["bytes"]
            },
            "peak_bytes_per_rank": int(self._peak_bytes),
        }
        if analytic_state is not None:
            sample["measured_state_bytes"] = int(measured_state)
            sample["analytic_state_bytes"] = int(analytic_state)
            sample["reconcile_rel_err"] = (
                round(rel_err, 6) if rel_err is not None else None
            )

        # drift gate: persistent residual -> memory_drift
        if rel_err is not None and rel_err > self.drift_tol:
            self._drift_streak += 1
        else:
            self._drift_streak = 0
        if self._drift_streak >= DRIFT_STREAK and self._unmuted(
            "memory_drift"
        ):
            self._advise(
                "memory_drift", step,
                {
                    "measured_state_bytes": int(measured_state),
                    "analytic_state_bytes": int(analytic_state),
                    "rel_err": round(rel_err, 6),
                    "tolerance": self.drift_tol,
                    "streak": self._drift_streak,
                    "census": ranked_census(c)[:4],
                },
                sample,
            )

        # headroom gate: budget-aware pressure tracking
        z = self._peak_tracker.update(measured_per_rank)
        if self.budget:
            headroom = float(self.budget) - measured_per_rank
            self._last_headroom = headroom
            metrics_mod.gauge("bluefog.memory.headroom_bytes").set(
                headroom
            )
            tr = self._peak_tracker
            predicted_next = float(tr.mean or measured_per_rank) + \
                WATERMARK_MADS * float(tr.mad)
            predicted_next = max(predicted_next, 0.0)
            sample["headroom_bytes"] = int(headroom)
            sample["predicted_next_watermark"] = int(predicted_next)
            # the gate: no headroom left, or the predicted next-step
            # watermark (EWMA + 3 MAD of the measured per-rank peak)
            # already exceeds the budget — measured headroom below the
            # next step's watermark, in the ISSUE's phrasing
            pressed = headroom <= 0 or (
                float(self.budget) - predicted_next
            ) <= 0
            if pressed and self._unmuted("memory_pressure"):
                from bluefog_tpu import sharding

                shard_on = sharding.enabled()
                state_frac = (
                    measured_state / measured_per_rank
                    if measured_per_rank else 0.0
                )
                self._advise(
                    "memory_pressure", step,
                    {
                        "budget_bytes": self.budget,
                        "bytes_per_rank": int(measured_per_rank),
                        "headroom_bytes": int(headroom),
                        "predicted_next_watermark": int(predicted_next),
                        "z": round(float(z), 3),
                        "census": ranked_census(c)[:4],
                        # the shard-recommendation hint: the optimizer
                        # state is the one category BLUEFOG_SHARD=1
                        # shrinks to 1/N, so the advisory names the
                        # knob exactly when it would help
                        "shard_hint": bool(
                            not shard_on and state_frac >= 0.25
                        ),
                        "opt_state_fraction": round(state_frac, 4),
                        "shard_enabled": bool(shard_on),
                    },
                    sample,
                )
        if self.phase_peaks:
            sample["phase_peaks"] = {
                k: dict(v) for k, v in sorted(self.phase_peaks.items())
            }

        flight_mod.record(
            "memory", live_bytes=int(total),
            per_rank=int(measured_per_rank),
            headroom=sample.get("headroom_bytes"),
        )
        self.samples.append(sample)
        self._export_line(sample)
        return sample

    # -- OOM forensics --------------------------------------------------------

    def note_oom(self, reason: str, message: str = "") -> List[dict]:
        """The forensics core: ranked census + flight event +
        eviction-proof advisory + dump. Returns the ranked census (the
        postmortem's suspect list). Never raises — forensics must not
        take down the process it is explaining (any further than the
        OOM already has)."""
        from bluefog_tpu import flight as flight_mod
        from bluefog_tpu import metrics as metrics_mod
        from bluefog_tpu import timeline as tl

        self.oom_events += 1
        ranked = ranked_census(self.last_census)
        try:
            metrics_mod.counter("bluefog.memory.oom_events").inc()
            detail = {
                "reason": reason,
                "message": message[:300],
                "ranked_census": ranked,
                "top_category": (
                    ranked[0]["category"] if ranked else None
                ),
                "bytes_per_rank": int(self._last_per_rank),
                "budget_bytes": self.budget or None,
                "host_peak_rss_bytes": host_peak_rss_bytes(),
            }
            flight_mod.record("oom", reason=reason,
                              top_category=detail["top_category"])
            # the TRAINING-step clock, like every other advisory: the
            # postmortem joins the oom against the pressure warnings
            # by step, and mixed clocks would mis-order them
            flight_mod.note_advisory(kind="oom", step=self._last_step,
                                     **detail)
            tl.timeline_record_advisory("oom", {"reason": reason})
            self._export_line({
                "kind": "advisory", "advisory_kind": "oom",
                "step": self._last_step, **detail,
            })
            flight_mod.maybe_dump(f"oom:{reason}")
        except Exception:
            pass
        return ranked

    # -- emission -------------------------------------------------------------

    def _advise(self, kind: str, step: int, detail: dict,
                sample: dict) -> None:
        """One advisory, the PR-7 surfaces: ``bluefog.doctor.*``
        metrics, flight side table, timeline instant, memory JSONL."""
        from bluefog_tpu import flight as flight_mod
        from bluefog_tpu import metrics as metrics_mod
        from bluefog_tpu import timeline as tl
        from bluefog_tpu.attribution import Advisory

        adv = Advisory(kind=kind, step=int(step), detail=detail)
        self.advisories.append(adv)
        metrics_mod.counter(f"bluefog.doctor.advisory.{kind}").inc()
        metrics_mod.gauge("bluefog.doctor.last_advisory_step").set(
            adv.step
        )
        flight_mod.note_advisory(kind=kind, step=adv.step, **detail)
        tl.timeline_record_advisory(kind, detail)
        sample.setdefault("advisories", []).append(adv.to_json())
        self._export_line({
            "kind": "advisory", "advisory_kind": kind,
            "step": adv.step, **detail,
        })

    def _export_line(self, obj: dict) -> None:
        path = os.environ.get(FILE_ENV)
        if path:
            from bluefog_tpu.logging_util import append_jsonl

            append_jsonl(FILE_ENV, path, obj)

    # -- artifact -------------------------------------------------------------

    def report(self) -> dict:
        """The memory artifact ``tools/memory_report.py`` consumes."""
        return {
            "kind": "memory_dump",
            "interval": self.interval,
            "budget_bytes": self.budget or None,
            "drift_tol": self.drift_tol,
            "comm_steps": self._count,
            "samples": list(self.samples),
            "advisories": [a.to_json() for a in self.advisories],
            "phase_peaks": {
                k: dict(v) for k, v in sorted(self.phase_peaks.items())
            },
            "peak_bytes_per_rank": int(self._peak_bytes),
            "last_census_ranked": ranked_census(self.last_census),
            "oom_events": self.oom_events,
        }

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.report(), f)
        return path


# -- module-level session -----------------------------------------------------

_observatory: Optional[MemoryObservatory] = None


def start(interval: Optional[int] = None, **kwargs) -> MemoryObservatory:
    """Open a memory session (replacing any active one)."""
    global _observatory
    _observatory = MemoryObservatory(interval=interval, **kwargs)
    return _observatory


def stop() -> None:
    global _observatory
    _observatory = None


def activate(obs: Optional[MemoryObservatory]
             ) -> Optional[MemoryObservatory]:
    """Install (or clear, with None) a pre-built session WITHOUT
    resetting its state — the A/B rotation in ``BENCH_MODE=memory``
    toggles one session on and off around individual steps."""
    global _observatory
    _observatory = obs
    return obs


def active() -> Optional[MemoryObservatory]:
    return _observatory


def observe_step(ctx, *, step: int, optimizer=None, params=None,
                 opt_state=None, grads=None) -> None:
    """Optimizer-layer hook, called after every communicating dispatch
    (next to the doctor / health / staleness hooks). No-op (one
    attribute read) when no session is active."""
    obs = _observatory
    if obs is None:
        return
    obs.observe(ctx, step=step, optimizer=optimizer, params=params,
                opt_state=opt_state, grads=grads)


def on_oom(reason: str, message: str = "") -> List[dict]:
    """Run the OOM forensics path (ranked census + flight dump) —
    callable with or without an active session: a crash hook firing
    before ``BLUEFOG_MEMORY=1`` was ever read must still produce the
    dump with whatever census it can take."""
    obs = _observatory
    if obs is None:
        obs = MemoryObservatory()
    return obs.note_oom(reason, message)


def dump(path: str) -> Optional[str]:
    """Write the active session's memory artifact (None when no
    session is active)."""
    obs = _observatory
    if obs is None:
        return None
    return obs.dump(path)


# -- crash hooks --------------------------------------------------------------

_hook_installed = False
_prev_excepthook = None


def _is_oom(exc_type, exc) -> bool:
    """A real host ``MemoryError`` or an XLA allocation failure (the
    runtime raises ``XlaRuntimeError`` with ``RESOURCE_EXHAUSTED`` in
    the message — matching the message instead of importing the exact
    exception class keeps the hook alive across jaxlib renames)."""
    if isinstance(exc, MemoryError) or (
        exc_type is not None and issubclass(exc_type, MemoryError)
    ):
        return True
    return "RESOURCE_EXHAUSTED" in str(exc)


def _excepthook(exc_type, exc, tb):
    try:
        # an exception whose forensics already ran (the oom chaos
        # fault marks its raise) must not be counted twice
        if _is_oom(exc_type, exc) and not getattr(
            exc, "_bf_oom_forensics_done", False
        ):
            on_oom(f"exception:{exc_type.__name__}", str(exc))
    except Exception:
        pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def _install_oom_hooks() -> None:
    global _hook_installed, _prev_excepthook
    if _hook_installed:
        return
    _prev_excepthook = sys.excepthook
    sys.excepthook = _excepthook
    _hook_installed = True


def _uninstall_oom_hooks() -> None:
    global _hook_installed, _prev_excepthook
    if not _hook_installed:
        return
    if sys.excepthook is _excepthook:
        sys.excepthook = _prev_excepthook or sys.__excepthook__
    _hook_installed = False
    _prev_excepthook = None


# -- session lifecycle (called by bluefog_tpu.context) ------------------------


def on_init(ctx) -> None:
    """``bf.init()`` hook: fresh session under ``BLUEFOG_MEMORY=1`` (a
    new mesh must not inherit a torn-down mesh's census or watermark),
    and the OOM crash hooks — which install beside the flight
    recorder's (AFTER it, so this hook runs FIRST on an uncaught
    error: the ranked census lands in the advisory side table before
    the flight hook writes its own crash dump)."""
    if enabled():
        start()
    else:
        stop()
    from bluefog_tpu import flight as flight_mod

    if flight_mod.enabled() and flight_mod.dump_dir() is not None:
        _install_oom_hooks()


def on_shutdown() -> None:
    """``bf.shutdown()`` hook: flush the JSONL tail, drop the session,
    detach the crash hooks."""
    obs = _observatory
    if obs is not None and obs.samples:
        obs._export_line({"kind": "session_end",
                          "comm_steps": obs._count})
    _uninstall_oom_hooks()
    stop()
