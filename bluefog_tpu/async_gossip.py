# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Fully asynchronous gossip training: ``bf.make_async_train_step``.

The reference's headline robustness axis is its win_put/win_accumulate
push-sum *asynchronous* optimizers (torch/optimizers.py:166-1554): each
rank trains at its own cadence, pushes weighted parameter mass into
neighbor windows, and folds whatever mass has arrived — no rank ever
blocks on a peer, so a 10x-slow straggler costs only its own
throughput, not the fleet's. Synchronous gossip cannot reach that
scenario: one slow rank gates every neighbor's ppermute.

**Execution model.** Under single-controller SPMD there is no
per-process wall clock to decouple, so asynchrony is modeled the same
way the window subsystem models one-sided RMA (:mod:`bluefog_tpu.
windows`): the *algorithmic* contract is preserved while execution
stays step-synchronous. The engine runs on a virtual **tick** clock.
Each tick dispatches ONE compiled program over the whole mesh in which
only the ranks *due* this tick (their cadence divides the tick) take a
local step:

1. evaluate ``loss_fn`` and the inner optax update at the push-sum
   estimate ``z = x / p``, applying the update to the raw window mass
   ``x`` (the accumulated-p recursion of
   :func:`~bluefog_tpu.optimizers.DistributedPushSumOptimizer`);
2. ``win_accumulate`` the updated mass into every out-neighbor's
   buffer slot under column-stochastic weights (self keeps its share)
   — the wire optionally quantized (see *Wire tiers* below), with the
   sender absorbing the shipped quantization residual so **sender mass
   is conserved exactly under every tier** (the
   :func:`bluefog_tpu.windows._exchange_core` column-sum identity);
3. fold (``win_update``-style collect) every pending buffer slot the
   bounded-staleness gate admits, zeroing exactly the folded slots —
   un-folded mass stays pending, never discarded.

Ranks not due this tick pass every lane through bitwise-unchanged:
their edge weights are zero *operands* of the same compiled program,
so a cadence pattern never recompiles. Participation masks, fold
masks, and all weights ride as runtime operands; the program is keyed
only on the communication structure.

**Bounded staleness.** The gate thresholds the host-side window age
lane (:func:`bluefog_tpu.windows.get_win_age`) at
``BLUEFOG_ASYNC_MAX_AGE`` local window steps. When an in-edge's buffer
falls past the bound the rank does not stall; per
``BLUEFOG_ASYNC_STALE_POLICY`` it either

- ``drop`` (default): excludes the stale edge from this fold (the
  pending mass stays buffered for a later fold — push-sum mass
  conservation is never traded for freshness), or
- ``throttle``: skips its own local step this tick, letting the
  laggard catch up (the classic bounded-staleness barrier, minus the
  barrier).

Either way an ``async_staleness`` advisory naming the stale edges (and
thereby the slow rank) files through the PR-7 plumbing: a
``bluefog.doctor.advisory.async_staleness`` counter, the flight side
table, a timeline instant, and the engine's own record list.

**Wire tiers** (``BLUEFOG_ASYNC_WIRE`` or the ``wire=`` argument):
``fp32`` (exact, default), ``bf16``, ``int8``, ``int4``, plus the
aliases ``int8_ef``/``int4_ef`` — on the push-sum accumulate surface
the sender-side residual absorption *is* the error feedback: the
quantization residual of every shipped payload is folded back into
the sender's own mass and re-transmitted on its next push, so the
``_ef`` spellings map to the int8/int4 window wire and inherit the
exact mass-conservation identity (tests/test_pushsum_oracle.py pins
the drift at f32 rounding, not quantization precision).

**Composition with the stack.**

- *Elastic*: the engine registers as a ``mode='push_sum'`` optimizer
  with the active :class:`~bluefog_tpu.elastic.recovery.
  ElasticSession` — every tick runs ``before_dispatch`` (chaos replay,
  repair); a membership change or an edge set the create-time window
  cannot carry triggers a **re-window**: the current estimate
  ``x / p`` is preserved as the new window value with ``p`` reset to 1
  over the live set. The new ``slow`` fault kind
  (:mod:`bluefog_tpu.elastic.faults`) dilates a rank's cadence
  deterministically — the 10x-straggler chaos scenario as a tier-1
  unit test.
- *Staleness*: delivered buffer ages fold into the observatory every
  tick under surface ``"async"`` (:func:`bluefog_tpu.staleness.
  observe_window`), so ``bf.staleness`` reports the async lane's ages
  and the fleet plane aggregates them.
- *Health*: the health report/``/fleet`` surface carries the engine
  summary next to the autotune block, and the age-adjusted mixing
  score (:func:`bluefog_tpu.staleness.age_adjusted_rate`) consumes the
  async lane's measured ages through the observatory.
- *Watchdog*: every tick's dispatch is a registered host blocking
  point (``watchdog.watch("async_fold:<window>")``), so a hung
  neighbor-window wait files SUSPECT liveness verdicts through the
  existing ``add_stall_handler`` -> elastic recovery hook.
- *Autotune*: decision records carry ``async_mode`` so the audit trail
  distinguishes choices made for an asynchronous lane.

**Async off** (``BLUEFOG_ASYNC=0`` or ``enabled=False``):
:func:`make_async_train_step` returns the wrapped optimizer's own
``make_train_step`` callable — the current synchronous path, bitwise
identical by construction (pinned by tests/test_async.py and
``BENCH_MODE=async``).

Env knobs: ``BLUEFOG_ASYNC`` (default on — the builder is the opt-in),
``BLUEFOG_ASYNC_MAX_AGE`` (default 8 local window steps),
``BLUEFOG_ASYNC_STALE_POLICY`` (``drop``/``throttle``),
``BLUEFOG_ASYNC_WIRE`` (see above). See docs/async.md.
"""

import itertools
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "AsyncGossipEngine",
    "make_async_train_step",
    "async_enabled",
    "async_max_age",
    "async_stale_policy",
    "async_wire",
    "active",
    "on_init",
    "on_shutdown",
]

ENABLE_ENV = "BLUEFOG_ASYNC"
MAX_AGE_ENV = "BLUEFOG_ASYNC_MAX_AGE"
POLICY_ENV = "BLUEFOG_ASYNC_STALE_POLICY"
WIRE_ENV = "BLUEFOG_ASYNC_WIRE"

_POLICIES = ("drop", "throttle")

# advisory re-fire mute per stale edge, in ticks — the staleness
# observatory's cooldown discipline: a persistently stale edge keeps
# its counter raised without flooding the flight ring, while a
# different edge's first breach is never swallowed
BREACH_COOLDOWN = 8


def async_enabled() -> bool:
    """The kill switch (``BLUEFOG_ASYNC``, default on). Calling
    :func:`make_async_train_step` is the opt-in; the env var exists so
    a deployment can force the synchronous path without a code change
    — and so the bitwise async-off pin has a dispatchable form."""
    return os.environ.get(ENABLE_ENV, "1").lower() not in (
        "0", "false", "off", "no",
    )


def async_max_age() -> int:
    """Bounded-staleness threshold in local window steps
    (``BLUEFOG_ASYNC_MAX_AGE``, default 8): an in-neighbor buffer older
    than this trips the gate. Chosen above the delivered ages any
    healthy cadence spread produces but below the 10x-dilation chaos
    scenario, so the gate engages exactly when a genuine straggler
    appears."""
    from bluefog_tpu.logging_util import env_int

    return max(1, env_int(MAX_AGE_ENV, 8))


def async_stale_policy() -> str:
    """``BLUEFOG_ASYNC_STALE_POLICY``: ``drop`` (default — exclude the
    stale edge from the fold, mass stays pending) or ``throttle`` (the
    rank skips its own local step to let the laggard catch up)."""
    p = os.environ.get(POLICY_ENV, "drop").strip().lower()
    if p not in _POLICIES:
        raise ValueError(
            f"{POLICY_ENV} must be one of {_POLICIES}, got {p!r}"
        )
    return p


def async_wire(requested: Optional[str] = None) -> Optional[str]:
    """Resolve the async push wire tier to the underlying window wire:
    ``None``/``fp32`` (exact), ``bf16``, ``int8``, ``int4``; the
    ``int8_ef``/``int4_ef`` aliases map to ``int8``/``int4`` — on the
    push-sum accumulate surface the sender's exact residual absorption
    already recycles the quantization error (the error-feedback role),
    see the module docstring."""
    w = (requested if requested is not None
         else os.environ.get(WIRE_ENV, "")).strip().lower()
    if w in ("", "0", "off", "none", "fp32", "f32", "exact"):
        return None
    if w in ("int8_ef", "int4_ef"):
        return w[:4]
    if w in ("bf16", "int8", "int4"):
        return w
    raise ValueError(
        "async wire must be one of fp32/bf16/int8/int4/int8_ef/int4_ef "
        f"(or unset for exact), got {w!r}"
    )


_engine_uid = itertools.count()


class AsyncGossipEngine:
    """One asynchronous gossip lane over a combo push-sum window.

    Built by :func:`make_async_train_step`; drive it through the
    returned callable. ``mode = 'push_sum'`` is the registration
    contract with the elastic repair engine: a membership repair
    installs its renormalized sender-stochastic weights on
    ``self.dst_weights`` / ``self.self_weight`` exactly as it does for
    :class:`~bluefog_tpu.optimizers._WindowOptimizer`.
    """

    mode = "push_sum"  # elastic _policy_for / _install_topology contract

    def __init__(self, opt, loss_fn, has_aux: bool = False,
                 cadence: Optional[Dict[int, int]] = None,
                 max_age: Optional[int] = None,
                 policy: Optional[str] = None,
                 wire: Optional[str] = None):
        self._uid = next(_engine_uid)
        self.opt = opt
        self.loss_fn = loss_fn
        self.has_aux = bool(has_aux)
        self.cadence = {int(r): int(p) for r, p in (cadence or {}).items()}
        for r, p in self.cadence.items():
            if p < 1:
                raise ValueError(
                    f"cadence period for rank {r} must be >= 1, got {p}"
                )
        if max_age is None:
            self.max_age = async_max_age()
        else:
            self.max_age = int(max_age)
            if self.max_age < 1:
                raise ValueError(
                    f"max_age must be >= 1 local window steps, got "
                    f"{max_age!r}"
                )
        self.policy = policy if policy is not None else async_stale_policy()
        if self.policy not in _POLICIES:
            raise ValueError(
                f"policy must be one of {_POLICIES}, got {self.policy!r}"
            )
        # wire: explicit arg > env > the wrapped optimizer's compression
        if wire is None and not os.environ.get(WIRE_ENV, "").strip():
            wire = getattr(opt, "compression", None)
        self.wire = async_wire(wire)
        self.wire_name = (
            (wire or os.environ.get(WIRE_ENV, "") or "fp32")
            .strip().lower() or "fp32"
        )
        # elastic repair installs renormalized weights here (push_sum
        # policy, recovery._install_topology)
        self.self_weight = None
        self.dst_weights = None
        self._name = f"_async{self._uid}.combo"
        self._win_sig = None          # (aval sig, live_token) at creation
        self._win_slots: Optional[tuple] = None  # create-time in-neighbors
        self._treedef = None
        self._leaf_shapes = None
        self._leaf_dtypes = None
        self._offsets = None
        self._pack_dtype = None
        self._tick = 0
        self._local_steps = 0
        self._throttled = 0
        self._stale_drops = 0
        self._rewindows = 0
        self._default_dst = None
        self._default_sw = None
        self._default_topo_v = None
        self._breach_mutes: Dict[Tuple[int, int], int] = {}
        # bounded like every other side table in the stack (flight
        # ring, autotune decisions): a permanent straggler fires one
        # advisory per cooldown window forever
        import collections as _collections

        self.advisories: Any = _collections.deque(maxlen=256)
        self._advisory_total = 0

    # -- packing --------------------------------------------------------------

    def _prepare_layout(self, ctx, params):
        import jax
        import jax.numpy as jnp

        leaves, treedef = jax.tree_util.tree_flatten(params)
        for i, l in enumerate(leaves):
            if l.ndim < 1 or l.shape[0] != ctx.size:
                raise ValueError(
                    f"async parameter leaf {i} must be worker-stacked "
                    f"[size={ctx.size}, ...]; got shape {tuple(l.shape)}"
                )
            if not jnp.issubdtype(l.dtype, jnp.inexact):
                raise TypeError(
                    f"async parameter leaf {i} has dtype {l.dtype}: the "
                    "push-sum lane packs every leaf into one float combo "
                    "window (integer state would round-trip through float "
                    "each tick)"
                )
        self._treedef = treedef
        self._leaf_shapes = [tuple(l.shape[1:]) for l in leaves]
        self._leaf_dtypes = [l.dtype for l in leaves]
        self._pack_dtype = jnp.result_type(*leaves)
        sizes = [int(np.prod(s)) if s else 1 for s in self._leaf_shapes]
        bounds = np.concatenate([[0], np.cumsum(sizes)])
        self._offsets = [
            (int(a), int(b)) for a, b in zip(bounds[:-1], bounds[1:])
        ]

    def _pack(self, leaves, size):
        import jax.numpy as jnp

        return jnp.concatenate(
            [
                jnp.reshape(l, (size, -1)).astype(self._pack_dtype)
                for l in leaves
            ],
            axis=1,
        )

    def _unpack_block(self, flat):
        """[D] combo vector -> per-worker leaf blocks (traced)."""
        out = []
        for (start, end), shape, dtype in zip(
            self._offsets, self._leaf_shapes, self._leaf_dtypes
        ):
            out.append(flat[start:end].reshape(shape).astype(dtype))
        return out

    # -- window lifecycle -----------------------------------------------------

    def _aval_sig(self, params):
        import jax

        return tuple(
            (tuple(l.shape), str(l.dtype))
            for l in jax.tree_util.tree_leaves(params)
        )

    def _topology_fits_window(self, ctx) -> bool:
        """True when every current-topology in-edge has a create-time
        buffer slot — repairs only prune, so they fit; a rejoin or
        controller migration can add edges back and force a
        re-window."""
        if self._win_slots is None:
            return False
        for r, srcs in enumerate(ctx.in_neighbor_ranks()):
            if not set(srcs) <= set(self._win_slots[r]):
                return False
        return True

    def _ensure_window(self, ctx, params) -> None:
        from bluefog_tpu import metrics as metrics_mod
        from bluefog_tpu import windows as win_mod

        sig = (self._aval_sig(params), ctx.live_token())
        win = win_mod._windows(ctx).get(self._name)
        if (win is not None and self._win_sig == sig
                and self._topology_fits_window(ctx)):
            return
        import jax

        if win is None or self._win_sig is None or (
            self._win_sig[0] != sig[0]
        ):
            # first creation (or a parameter-shape change): seed the
            # window mass from the given params, p = 1
            self._prepare_layout(ctx, params)
            packed = self._pack(
                jax.tree_util.tree_flatten(params)[0], ctx.size
            )
        else:
            # re-window (membership change / edge superset): the
            # current estimate x/p becomes the new mass with p reset
            # to 1 — consensus state survives the seam, mass
            # accounting restarts over the live set
            packed = win.value / win.p[:, None].astype(win.value.dtype)
            self._rewindows += 1
            metrics_mod.counter("bluefog.async.rewindows").inc()
        win_mod.win_free(self._name)
        created = win_mod.win_create(packed, self._name, zero_init=True)
        assert created, f"window {self._name} already exists"
        self._win_sig = sig
        self._win_slots = win_mod._get_win(ctx, self._name).in_neighbors
        # weight defaults follow the topology the window was cut for
        self._default_topo_v = None

    def free(self) -> None:
        from bluefog_tpu import context as ctx_mod
        from bluefog_tpu import windows as win_mod

        if ctx_mod.is_initialized():
            win_mod.win_free(self._name)
        self._win_sig = None
        self._win_slots = None

    def params(self):
        """The current push-sum estimate ``x / p`` as the parameter
        pytree."""
        import jax

        from bluefog_tpu import context as ctx_mod
        from bluefog_tpu import windows as win_mod

        ctx = ctx_mod.get_context()
        win = win_mod._get_win(ctx, self._name)
        est = win.value / win.p[:, None].astype(win.value.dtype)
        leaves = [
            est[:, start:end].reshape((ctx.size,) + shape).astype(dtype)
            for (start, end), shape, dtype in zip(
                self._offsets, self._leaf_shapes, self._leaf_dtypes
            )
        ]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # -- cadence --------------------------------------------------------------

    def _periods(self, ctx, session) -> np.ndarray:
        """Per-rank local-step period on the tick clock: the explicit
        cadence times any active ``slow`` fault's compute dilation
        (deterministic chaos, :meth:`~bluefog_tpu.elastic.recovery.
        ElasticSession.simulated_compute_dilation`)."""
        periods = np.ones(ctx.size, np.int64)
        for r, p in self.cadence.items():
            if 0 <= r < ctx.size:
                periods[r] = p
        if session is not None:
            dil = session.simulated_compute_dilation()
            for r, f in dil.items():
                if 0 <= r < ctx.size:
                    periods[r] *= max(1, int(np.ceil(f)))
        return periods

    # -- the staleness gate ---------------------------------------------------

    def _slot_ages(self, win) -> np.ndarray:
        """[size, max_deg] local-step ages of each buffer slot, -1 where
        no slot exists (the host age lane, :mod:`bluefog_tpu.windows`)."""
        size = len(win.in_neighbors)
        max_deg = max(win.max_deg, 1)
        ages = np.full((size, max_deg), -1, np.int64)
        clock = int(win.clock)
        for r, srcs in enumerate(win.in_neighbors):
            for k in range(len(srcs)):
                ages[r, k] = clock - int(win.slot_written[r, k])
        return ages

    def _gate(self, ctx, win, participating, ages):
        """Apply the bounded-staleness policy. Returns
        ``(participating, fold_mask, breached_edges)`` — ``fold_mask``
        [size, max_deg] bool; breached edges are (src, dst) pairs past
        the bound this tick (pre-cooldown)."""
        size = ctx.size
        max_deg = max(win.max_deg, 1)
        slot_exists = np.zeros((size, max_deg), bool)
        stale = np.zeros((size, max_deg), bool)
        for r, srcs in enumerate(win.in_neighbors):
            for k, s in enumerate(srcs):
                slot_exists[r, k] = True
                if ages[r, k] > self.max_age:
                    stale[r, k] = True
        participating = participating.copy()
        # only edges the gate ACTS on this tick are advisory-worthy: a
        # stale slot whose receiver is not due folds nothing anyway, so
        # reporting action='dropped'/'throttled' for it would make the
        # advisory stream disagree with the drop/throttle counters
        breached: List[Tuple[int, int]] = [
            (int(s), int(r))
            for r, srcs in enumerate(win.in_neighbors)
            for k, s in enumerate(srcs)
            if stale[r, k] and participating[r]
        ]
        if self.policy == "throttle":
            # a rank whose in-edges fell behind sits this tick out
            throttle_rows = stale.any(axis=1) & participating
            self._throttled += int(throttle_rows.sum())
            if throttle_rows.any():
                from bluefog_tpu import metrics as metrics_mod

                metrics_mod.counter("bluefog.async.throttled").inc(
                    int(throttle_rows.sum())
                )
            participating &= ~throttle_rows
            fold_mask = slot_exists & participating[:, None]
        else:  # drop: fold everything fresh, keep stale mass pending
            fold_mask = slot_exists & participating[:, None] & ~stale
            drops = int((stale & participating[:, None]).sum())
            if drops:
                from bluefog_tpu import metrics as metrics_mod

                self._stale_drops += drops
                metrics_mod.counter("bluefog.async.stale_drops").inc(
                    drops
                )
        return participating, fold_mask, breached

    def _decay_mutes(self) -> None:
        """Advance the advisory re-fire mutes by one TICK — called every
        tick (not only on breach ticks), so the documented in-ticks
        cooldown expires on wall progress and a recovered edge's next
        genuine incident is never swallowed by a stale counter."""
        for k in list(self._breach_mutes):
            self._breach_mutes[k] -= 1
            if self._breach_mutes[k] <= 0:
                del self._breach_mutes[k]

    def _advise(self, ctx, ages_by_edge: Dict[Tuple[int, int], int],
                breached: List[Tuple[int, int]]) -> None:
        """File the ``async_staleness`` advisory for un-muted breached
        edges through the PR-7 plumbing, naming the stale edges (and
        thereby the slow source ranks)."""
        fresh = [e for e in breached if e not in self._breach_mutes]
        if not fresh:
            return
        for e in fresh:
            self._breach_mutes[e] = BREACH_COOLDOWN
        fresh.sort(key=lambda e: (-ages_by_edge.get(e, 0), e))
        from bluefog_tpu import flight as flight_mod
        from bluefog_tpu import metrics as metrics_mod
        from bluefog_tpu import timeline as tl
        from bluefog_tpu.attribution import Advisory

        adv = Advisory(
            kind="async_staleness", step=self._tick,
            detail={
                "edges": [[int(s), int(d)] for s, d in fresh[:8]],
                "ages": {
                    f"{s}->{d}": int(ages_by_edge.get((s, d), 0))
                    for s, d in fresh[:8]
                },
                "slow_ranks": sorted({int(s) for s, _d in fresh}),
                "bound": self.max_age,
                "policy": self.policy,
                "action": (
                    "dropped_from_fold" if self.policy == "drop"
                    else "throttled_receivers"
                ),
                "surface": "async",
                "topo_version": int(ctx.topo_version),
            },
        )
        self.advisories.append(adv)
        self._advisory_total += 1
        metrics_mod.counter(
            f"bluefog.doctor.advisory.{adv.kind}"
        ).inc()
        metrics_mod.gauge("bluefog.doctor.last_advisory_step").set(
            adv.step
        )
        flight_mod.note_advisory(kind=adv.kind, step=adv.step,
                                 **adv.detail)
        tl.timeline_record_advisory(adv.kind, adv.detail)

    # -- weights --------------------------------------------------------------

    def _exchange_weights(self, ctx, win):
        """(w_edges [size, size], self_vec [size]) — explicit (elastic-
        installed) weights or the uniform column-stochastic default
        over the CURRENT topology's out-neighbors, cached per topology
        version (the :class:`~bluefog_tpu.optimizers._WindowOptimizer`
        push-sum resolution)."""
        from bluefog_tpu import windows as win_mod

        size = ctx.size
        if self._default_topo_v != ctx.topo_version:
            self._default_dst = None
            self._default_sw = None
            self._default_topo_v = ctx.topo_version
        if self.dst_weights is None or self.self_weight is None:
            if self._default_dst is None:
                # cached per topology version: the O(N*E) neighbor walk
                # must not sit in the per-tick hot path
                outs = ctx.out_neighbor_ranks()
                self._default_dst = [
                    {d: 1.0 / (len(outs[r]) + 1) for d in outs[r]}
                    for r in range(size)
                ]
                self._default_sw = [
                    1.0 / (len(outs[r]) + 1) for r in range(size)
                ]
        dst = (
            self.dst_weights if self.dst_weights is not None
            else self._default_dst
        )
        sw = (
            self.self_weight if self.self_weight is not None
            else self._default_sw
        )
        w, participating = win_mod._per_rank_edges(
            ctx, dst, win.out_neighbors, "dst_weights"
        )
        self_vec = win_mod._self_weight_vec(ctx, sw, participating)
        return w, self_vec

    # -- the compiled tick ----------------------------------------------------

    def _tick_fn(self, ctx, win, perms, slot_table, n_batch, state_aval,
                 batch_aval):
        """One compiled program per communication structure: masked
        local update + masked push (``_exchange_core``, the single
        source of truth for the wire) + masked per-slot fold. All
        masks and weights are runtime operands — a new participation
        pattern or weight assignment never recompiles."""
        import jax
        import jax.numpy as jnp
        from jax import lax
        from jax.sharding import PartitionSpec as P

        from bluefog_tpu import context as ctx_mod
        from bluefog_tpu import metrics as metrics_mod
        from bluefog_tpu import flight as flight_mod
        from bluefog_tpu import windows as win_mod

        from bluefog_tpu.collective import kernels as wire_kernels

        key = (
            "async_tick", self._uid, getattr(self.opt, "_tx_version", 0),
            perms, tuple(map(tuple, slot_table)), self.wire,
            self.has_aux, n_batch, state_aval, batch_aval,
            win.shape, str(win.dtype),
        ) + wire_kernels.cache_token(self.wire)
        fn = ctx.op_cache.get(key)
        if fn is not None:
            return fn
        metrics_mod.counter("bluefog.recompiles").inc()
        flight_mod.record("compile", name="async_tick")

        import optax

        axis = ctx_mod.WORKER_AXIS
        slots_const = np.asarray(slot_table, np.int32)
        max_deg, shape = win.max_deg, win.shape
        # sender of each buffer slot, -1 where none: gates the version
        # lane so only writes from participating senders count as mass
        # arrivals (the structural slot table writes every round)
        sender_idx = np.full((len(win.in_neighbors), max(max_deg, 1)),
                             -1, np.int32)
        for r, srcs in enumerate(win.in_neighbors):
            for k, s in enumerate(srcs):
                sender_idx[r, k] = s
        sender_idx_const = jnp.asarray(sender_idx)
        tx = self.opt.tx
        wire = self.wire
        has_aux = self.has_aux
        value_and_grad = jax.value_and_grad(self.loss_fn, has_aux=has_aux)
        unpack = self._unpack_block
        treedef = self._treedef
        pack_dtype = self._pack_dtype

        def tree_block(tree):
            return jax.tree_util.tree_map(lambda t: t[0], tree)

        def restack(tree):
            return jax.tree_util.tree_map(
                lambda t: jnp.expand_dims(t, 0), tree
            )

        def body(value, buffers, versions, p, p_buffers, s_b, wops,
                 *batch_b):
            (recv_w, self_w, sent_w, part_arr, fold_w) = wops
            v, bufs, vers = value[0], buffers[0], versions[0]
            pv, pbufs = p[0], p_buffers[0]
            s = tree_block(s_b)
            bat = tuple(tree_block(b) for b in batch_b)
            idx = lax.axis_index(axis)
            part = part_arr[idx]

            # 1. local step at the push-sum estimate z = x/p, update
            #    applied to the RAW mass x (accumulated-p recursion)
            est = v / pv.astype(v.dtype)
            z_tree = jax.tree_util.tree_unflatten(treedef, unpack(est))
            if has_aux:
                (loss, aux), grads = value_and_grad(z_tree, *bat)
            else:
                loss, grads = value_and_grad(z_tree, *bat)
                aux = ()
            x_tree = jax.tree_util.tree_unflatten(treedef, unpack(v))
            updates, s_new = tx.update(grads, s, x_tree)
            x_new = optax.apply_updates(x_tree, updates)
            xb_new = jnp.concatenate(
                [
                    jnp.reshape(l, (-1,)).astype(pack_dtype)
                    for l in jax.tree_util.tree_leaves(x_new)
                ]
            )
            xb = jnp.where(part, xb_new, v)
            s_out = jax.tree_util.tree_map(
                lambda a, b: jnp.where(part, a, b), s_new, s
            )

            # 2. masked push: non-participating rows carry zero edge
            #    weight, self weight 1, sent mass 0 — bitwise identity
            #    on their lanes; the shared wire core conserves sender
            #    mass exactly under every tier
            v2, bufs2, vers2, pv2, pbufs2 = win_mod._exchange_core(
                axis, "acc", perms, slots_const, True, max_deg, shape,
                xb, bufs, vers, pv, pbufs, xb, recv_w, self_w,
                wire=wire, sent_w=sent_w,
            )
            # version lane: count only mass from participating senders
            srow = sender_idx_const[idx]                  # [max_deg]
            sgate = jnp.where(
                srow >= 0, part_arr[jnp.clip(srow, 0)], False
            )
            vers2 = vers + (vers2 - vers) * sgate.astype(vers.dtype)

            # 3. masked per-slot fold (push-sum collect): folded slots
            #    zero, un-folded mass stays pending
            kw = fold_w[idx]                              # [max_deg]
            v3 = v2 + jnp.tensordot(kw.astype(v2.dtype), bufs2,
                                    axes=(0, 0))
            keep = (1.0 - kw)
            bufs3 = bufs2 * keep[:, None].astype(bufs2.dtype)
            pv3 = pv2 + jnp.dot(kw.astype(pv2.dtype), pbufs2)
            pbufs3 = pbufs2 * keep.astype(pbufs2.dtype)
            vers3 = jnp.where(kw > 0, 0, vers2).astype(vers2.dtype)

            est_out = v3 / pv3.astype(v3.dtype)
            params_out = jax.tree_util.tree_unflatten(
                treedef, unpack(est_out)
            )
            expand = lambda t: jnp.expand_dims(t, 0)
            outs = (
                expand(v3), expand(bufs3), expand(vers3),
                expand(pv3), expand(pbufs3),
                restack(params_out), restack(s_out),
                jnp.reshape(loss, (1,)),
            )
            return outs + ((restack(aux),) if has_aux else ((),))

        spec = P(axis)
        fn = jax.jit(
            jax.shard_map(
                body, mesh=ctx.mesh,
                in_specs=(spec,) * 6 + (P(),) + (spec,) * n_batch,
                out_specs=(spec,) * 9,
            )
        )
        ctx.op_cache[key] = fn
        return fn

    # -- the tick -------------------------------------------------------------

    def step(self, params, opt_state, *batch):
        """One tick: ranks due on the tick clock take a local step and
        push; everyone folds what the staleness gate admits. Returns
        ``(params_estimate, opt_state, loss)`` (loss worker-stacked;
        ranks that sat out report their previous-estimate loss).

        ``params`` seeds the window on the first call (and after a
        parameter-shape change); afterwards the window is the source
        of truth — the returned estimate IS what the next call should
        be fed."""
        import jax.numpy as jnp

        from bluefog_tpu import context as ctx_mod
        from bluefog_tpu import elastic as elastic_mod
        from bluefog_tpu import flight as flight_mod
        from bluefog_tpu import metrics as metrics_mod
        from bluefog_tpu import staleness as staleness_mod
        from bluefog_tpu import watchdog
        from bluefog_tpu import windows as win_mod
        from bluefog_tpu.optimizers import _aval_key, _timed_dispatch

        ctx = ctx_mod.get_context()
        session = elastic_mod.active_session()
        if session is not None:
            # chaos replay + repair BEFORE the window/weight resolution:
            # a repair this tick must shape this tick's dispatch
            session.before_dispatch(self)
        self._ensure_window(ctx, params)
        win = win_mod._get_win(ctx, self._name)

        periods = self._periods(ctx, session)
        live = np.ones(ctx.size, bool)
        if session is not None:
            live[:] = False
            live[list(session.membership.live_ranks())] = True
        participating = live & (self._tick % periods == 0)

        ages = self._slot_ages(win)
        self._decay_mutes()
        participating, fold_mask, breached = self._gate(
            ctx, win, participating, ages
        )
        ages_by_edge = {
            (int(s), int(r)): int(ages[r, k])
            for r, srcs in enumerate(win.in_neighbors)
            for k, s in enumerate(srcs)
        }
        if breached:
            self._advise(ctx, ages_by_edge, breached)

        # age telemetry every tick (the gate computed it anyway)
        if ages_by_edge:
            vals = list(ages_by_edge.values())
            hist = metrics_mod.histogram("bluefog.async.age")
            for a in vals:
                hist.observe(a)
            metrics_mod.gauge("bluefog.async.age_max").set(
                float(max(vals))
            )

        w_edges, self_vec = self._exchange_weights(ctx, win)
        # masking rides in the OPERANDS: zero edge rows / self 1 /
        # sent 0 for ranks sitting this tick out — one compiled
        # program per structure, never per participation pattern
        w_masked = w_edges * participating[:, None]
        self_masked = np.where(participating, self_vec, 1.0)
        sent_masked = w_masked.sum(axis=1)

        perms, slot_table = win_mod._lowered_exchange(ctx, win, w_edges)
        n_batch = len(batch)
        fn = self._tick_fn(
            ctx, win, perms, slot_table, n_batch,
            _aval_key(opt_state), _aval_key(batch),
        )
        fold_f = np.zeros(
            (ctx.size, max(win.max_deg, 1)), np.float64
        )
        fold_f[fold_mask] = 1.0
        wops = (
            jnp.asarray(win_mod._round_weights(perms, w_masked)),
            jnp.asarray(np.asarray(self_masked, np.float64)),
            jnp.asarray(np.asarray(sent_masked, np.float64)),
            jnp.asarray(participating, bool),
            jnp.asarray(fold_f),
        )

        flight_mod.record(
            "async_tick", tick=self._tick,
            participants=int(participating.sum()),
        )
        # the tick's host blocking point: a hung neighbor-window wait
        # here is what the watchdog must see (SUSPECT verdicts flow
        # through the elastic stall handler)
        with watchdog.watch(f"async_fold:{self._name}"):
            outs = _timed_dispatch(
                "async_tick", fn,
                win.value, win.buffers, win.versions, win.p,
                win.p_buffers, opt_state, wops, *batch,
            )
        (win.value, win.buffers, win.versions, win.p, win.p_buffers,
         params_out, state_out, loss, aux) = outs

        # host age lane: one tick = one local window step; stamp only
        # the slots whose SENDER participated, then clear the folds
        written = np.zeros_like(fold_mask)
        for r, srcs in enumerate(win.in_neighbors):
            for k, s in enumerate(srcs):
                written[r, k] = participating[s]
        win_mod._note_async_tick(win, written, fold_mask)

        n_part = int(participating.sum())
        self._local_steps += n_part
        metrics_mod.counter("bluefog.async.ticks").inc()
        metrics_mod.counter("bluefog.async.local_steps").inc(n_part)
        metrics_mod.gauge("bluefog.async.participants").set(n_part)
        n_elems = int(np.prod(win.shape)) if win.shape else 1
        metrics_mod.counter("bluefog.async.wire_bytes").inc(
            metrics_mod.wire_bytes_per_step(
                {np.dtype(win.dtype).itemsize: n_elems}, len(perms),
                self.wire,
            )
        )
        # the staleness observatory folds the async lane's delivered
        # ages on its own per-window sampling clock
        staleness_mod.observe_window(
            ctx, win, step=self._tick, surface="async"
        )
        self._tick += 1
        if self.has_aux:
            return params_out, state_out, (loss, aux)
        return params_out, state_out, loss

    # -- observability --------------------------------------------------------

    def summary(self) -> dict:
        """The engine block the health report / ``/fleet`` surface
        attaches (next to the autotune summary)."""
        return {
            "ticks": self._tick,
            "local_steps": self._local_steps,
            "throttled": self._throttled,
            "stale_drops": self._stale_drops,
            "rewindows": self._rewindows,
            "advisories": self._advisory_total,
            "policy": self.policy,
            "wire": self.wire_name,
            "max_age": self.max_age,
            "cadence": {
                str(r): int(p) for r, p in sorted(self.cadence.items())
            },
        }


# -- module-level engine registry ---------------------------------------------

_active: Optional[AsyncGossipEngine] = None


def active() -> Optional[AsyncGossipEngine]:
    """The most recently built (still current) async engine, or None —
    what the health report and autotune decision records consult."""
    return _active


def on_init(ctx) -> None:
    """``bf.init()`` hook: a new mesh must not inherit a torn-down
    mesh's engine (its window died with the old context)."""
    global _active
    _active = None


def on_shutdown() -> None:
    global _active
    _active = None


def make_async_train_step(opt, loss_fn, has_aux: bool = False,
                          cadence: Optional[Dict[int, int]] = None,
                          max_age: Optional[int] = None,
                          policy: Optional[str] = None,
                          wire: Optional[str] = None,
                          enabled: Optional[bool] = None):
    """Build the fully asynchronous train step (``bf.
    make_async_train_step``): per-rank-cadence push-sum gossip where no
    rank ever waits on a peer.

    ``opt`` is any gossip-family distributed optimizer — its inner
    optax transformation drives the local updates, and its
    ``compression`` knob seeds the wire tier. With async OFF
    (``enabled=False`` or ``BLUEFOG_ASYNC=0``) this returns
    ``opt.make_train_step(loss_fn, has_aux=...)`` — the current
    synchronous path, bitwise identical by construction.

    With async ON the returned callable has the same signature
    (``step(params, opt_state, *batch) -> (params, opt_state, loss)``)
    but each call is one *tick*: ranks whose cadence divides the tick
    take a local step and push; everyone folds what the
    bounded-staleness gate admits. ``cadence`` maps rank -> period in
    ticks (default 1 everywhere); active ``slow`` chaos faults dilate
    it deterministically. See the module docstring and docs/async.md.
    """
    on = async_enabled() if enabled is None else bool(enabled)
    if not on:
        return opt.make_train_step(loss_fn, has_aux=has_aux)
    global _active
    engine = AsyncGossipEngine(
        opt, loss_fn, has_aux=has_aux, cadence=cadence,
        max_age=max_age, policy=policy, wire=wire,
    )
    _active = engine

    def train_step(params, opt_state, *batch):
        return engine.step(params, opt_state, *batch)

    train_step.engine = engine
    return train_step
