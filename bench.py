#!/usr/bin/env python
# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Benchmark driver: the full performance evidence set in one run.

Default (no BENCH_MODE): emits EVERY metric family — scaling accounting,
gossip overhead (with its regression assertion on TPU), flash-vs-dense
attention timings, transformer throughput — each in an isolated
subprocess, then the ResNet50 headline line LAST (so a tail-reading
driver still lands on the headline). Every line is standalone JSON.

Individual families via ``BENCH_MODE``:

- ``headline``: ResNet50 decentralized train step, mirroring the
  reference benchmark driver (``examples/pytorch_benchmark.py``: bs=64
  per worker, neighbor_allreduce optimizer). Baseline: BlueFog-NCCL
  ResNet50 at 4310.6 img/s total on 16 V100s (docs/performance.rst:16-24)
  = 269.4 img/s per accelerator; ``vs_baseline`` is imgs/sec-per-chip
  against that. ``mfu`` uses the 2*MAC FLOP convention. Best-of-N timed
  windows with the min/median spread disclosed.
- ``transformer``: TransformerLM (bf16, dim 1024 / 16 heads / 12 layers,
  T=4096) train-step tokens/sec + MFU over the Pallas flash kernels.
- ``flash``: flash-vs-dense attention fwd / fwd+bwd timings at
  T in {1k, 4k, 8k} (the measured basis for flash-by-default).
- ``gossip``: gossip-overhead bound with communication REALLY in the
  program; asserts the per-worker combine stays < 10 % of a bs=64 step
  on TPU (regression check).
- ``scaling``: static HLO comm accounting + weak-scaling harness
  (reference docs/performance.rst:26-53, README.rst:51-60).
- ``plan``: comm-plan compiler evidence — naive (offset-grouped) vs
  optimized (minimum-round edge coloring) round counts, verified from
  compiled HLO, plus measured gossip-step times for irregular
  topologies (star, mesh2d, sparse random digraph). See
  ``docs/plan_compiler.md``.
- ``overlap``: exposed-communication comparison for the fused train
  step (two-program baseline vs fused vs fused+buckets vs delayed),
  per-bucket schedule timeline, and the static HLO overlap scan
  (``tools/hlo_overlap_scan.py``). See docs/performance.md
  "Overlapping communication with compute".
- ``metrics``: telemetry-overhead evidence — the fused gossip step
  timed with the device metric tier off vs on (interval 10), the
  bitwise on/off state pin, and a drained-registry sample; asserts the
  <2 % overhead acceptance bound. See ``docs/metrics.md``.
- ``flight``: flight-recorder evidence — per-event ring-write cost x
  exact events/step over the differenced step time (<=1 % bound,
  asserted), bitwise on/off trajectory pin, and a fault-plan kill whose
  dumps are fused by ``tools/trace_merge.py`` (merged-trace round count
  vs the compiled CommPlan, hang postmortem naming the killed rank and
  the stalled edges/rounds). See ``docs/flight.md``.
- ``attribution``: step-time attribution doctor evidence
  (``bf.doctor``, docs/doctor.md) — measured overhead at the default
  sampling interval (<=1 % bound, asserted, A/A control disclosed),
  the structural pin that unsampled steps dispatch the doctor-off
  program under the same cache key, the bitwise on/off trajectory pin,
  a sample's compute/comm/host decomposition, and a fault-plan
  degraded-link scenario where the emitted advisory must name the
  injected edge. Committed as ATTRIBUTION_EVIDENCE.json.
- ``health``: fleet-health-plane evidence (``bf.health``,
  docs/health.md) — measured consensus decay vs the spectral (SLEM)
  prediction on ring and Exp2 through the real eager combine (with the
  Exp2-faster ordering asserted), the push-sum in-band aggregation
  lane vs its numpy oracle under a dead rank, the <=1 % overhead bound
  at the default sampling interval (A/A control, structural +
  bitwise pins), and a deterministic lossy-link chaos scenario whose
  ``mixing_degraded`` advisory must name the injected edge. Committed
  as HEALTH_EVIDENCE.json.
- ``slo``: fleet-SLO-engine evidence (``bf.slo``, docs/slo.md) — a
  hard fault paging within the documented ``page_sample_bound`` with
  a 600-sample clean A/A raising nothing, a slow error ramp caught by
  the slow burn window while the fast window AND the doctor's
  EWMA+MAD streak rule stay correctly silent, the 512-element
  known-signal canary bit-clean through the real quantized wire on a
  healthy fabric and naming exactly the chaos-degraded edge on a
  lossy one, the <=1 % overhead bound at the default sampling
  interval (A/A control, structural + bitwise pins), and the burn /
  error-budget arithmetic pinned exactly to a numpy oracle through an
  N=1024 fleetsim churn storm. Committed as SLO_EVIDENCE.json.
- ``staleness``: staleness-observatory evidence (``bf.staleness``,
  docs/staleness.md) — the lineage lane's synchronous-path age ≡ 0
  self-check with the sidecar priced by
  ``scaling.wire_payload_bytes``, the ``delayed=True`` steady-state
  age ≡ 1 invariant with the topology-swap age-0 transition, the
  age-discounted mixing correction measurably shrinking the health
  plane's predicted-vs-measured residual on a delayed run, the <=1 %
  overhead bound at the default sampling interval (A/A control,
  structural + bitwise pins), and a deterministic per-edge stall chaos
  scenario whose measured age spike and ``staleness_breach`` advisory
  must name the injected edge. Committed as STALENESS_EVIDENCE.json.
- ``autotune``: closed-loop topology-controller evidence
  (``bf.autotune``, docs/autotune.md) — an injected degraded link is
  detected through the real doctor advisory stream, routed around by a
  live migration through the elastic repair path (decision record
  naming the edge, measured wire cost + mixing efficiency recovering
  past gated thresholds), with the ≤1 % overhead bound at the default
  interval (A/A control, structural + bitwise pins), a dry-run pass
  recording full decision history with zero migrations, and the audit
  trail round-tripped through every surface (metrics, flight side
  table, JSONL, ``tools/autotune_report.py``). Committed as
  AUTOTUNE_EVIDENCE.json.
- ``async``: asynchronous-gossip evidence (``bf.make_async_train_step``,
  docs/async.md) — the straggler-immunity chaos scenario (one rank
  compute-dilated 10x via the ``slow`` fault: synchronous fleet
  throughput collapses to ~1/10 while the async lane's measured
  participation stays within ~1/N of nominal), convergence within
  tolerance of the synchronous baseline on the same problem, exact
  push-sum mass conservation under random per-rank cadences for the
  fp32/int8_ef/int4_ef wire tiers, the bounded-staleness gate engaging
  (age histogram + ``async_staleness`` advisory naming the slow rank),
  and the async-off dispatch pinned bitwise to the current synchronous
  optimizer path. Committed as ASYNC_EVIDENCE.json.
- ``quant``: quantized-wire evidence — every wire tier
  (fp32/bf16/int8/int8_ef/int4/int4_ef) on one pure-consensus problem,
  per-tier wire bytes with the block-scale sidecar priced in,
  consensus-distance curves, quant-error telemetry, and the push-sum
  mass-conservation check under ``BLUEFOG_WINDOW_WIRE=int4``; asserts
  the >=2x wire-reduction-vs-int8 claim at int8-or-better consensus
  quality. Committed as QUANT_EVIDENCE.json.
- ``fleetscale``: fleet-scale control-plane evidence (``bf.fleetsim``,
  docs/fleetsim.md) — the thousand-rank fleet simulator driving the
  real membership/repair/plan-cache machinery with no device dispatch:
  per-membership-event repair cost sublinear in N (growth exponent
  asserted < 1 over N in {128..1024}, dense baseline timed at small N
  and power-law-extrapolated with the model disclosed), a 10 %
  simultaneous rank-loss storm at N=1024 repaired with ZERO stale
  dispatches under full edge auditing, bounded controller decision
  latency at N=1024 through the sparse spectral engine, and the
  sparse-vs-dense SLEM agreement spot check at the routing boundary.
  Committed as FLEETSCALE_EVIDENCE.json.
- ``federate``: hierarchical multi-pod federation evidence
  (``bf.federation``, docs/federation.md) — the two-level ICI/DCN
  gossip fabric: the spectrally-chosen DCN period matching the
  measured composed consensus rate within a disclosed tolerance, the
  >= 8x cross-pod (DCN) wire-byte cut vs the strongest flat opponent
  at the matched measured rate, whole-pod loss repaired as ONE event
  with zero stale dispatches (gateway re-election included), and a
  live 2-pod dispatch whose per-leg
  ``bluefog.federation.{ici,dcn}_wire_bytes`` counters reconcile.
  Committed as FEDERATE_EVIDENCE.json.

Every run additionally emits an **ambient-drift anchor** line
(``{"metric": "ambient_anchor"}``: the fixed dense bf16 matmul TFLOP/s
of ``tools/perf_probe.py``, 8192^3 on TPU) and the ResNet50/transformer
headlines carry ``vs_anchor`` (throughput per ambient TFLOP/s), so a
cross-round headline delta is classifiable as ambient host drift vs a
real change — ``tools/bench_diff.py`` consumes the anchor to make that
call mechanically.

Timing windows that come out degenerate (a clamped ``diff <= 0`` in
``timed_differenced`` — an ambient stall ate the differenced half) are
retried and excluded; a cell whose every window stayed degenerate is
published with ``"degenerate": true`` instead of a silent 0.0, and is
excluded from the flash regression assertion.
"""

import json
import os
import sys
import time

# Peak dense bf16 FLOP/s by TPU generation (public spec sheets); used only
# to report MFU. Unknown kinds fall back to 0 => mfu omitted.
_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# 2*MAC FLOPs: ResNet50 forward at 224x224 is ~4.1 GMACs = 8.2 GFLOP/img;
# backward ~= 2x forward.
_FLOPS_PER_IMG_FWD_BWD = 3 * 8.2e9


def _provenance() -> dict:
    """Round-over-round bench deltas are only attributable when every
    evidence artifact records WHAT produced it: jax/jaxlib versions,
    platform, CPU model, timing method, and the git SHA. Emitted as a
    standalone ``{"metric": "provenance"}`` line by every BENCH_MODE, so
    committed ``BENCH_*``/``*_EVIDENCE`` files carry it."""
    import platform as _platform
    import subprocess

    import jax
    import jaxlib

    cpu_model = ""
    try:
        fields = {}
        with open("/proc/cpuinfo") as f:
            for line in f:
                if ":" in line:
                    k, v = line.split(":", 1)
                    fields.setdefault(k.strip(), v.strip())
                if line.strip() == "":
                    break  # first processor block is enough
        cpu_model = fields.get("model name", "")
        if cpu_model in ("", "unknown"):
            # virtualized hosts often blank the model name; the numeric
            # family/model ids still identify the microarchitecture
            cpu_model = " ".join(
                filter(None, (
                    fields.get("vendor_id", ""),
                    f"family={fields.get('cpu family', '?')}",
                    f"model={fields.get('model', '?')}",
                ))
            )
    except OSError:
        cpu_model = _platform.processor() or _platform.machine()
    try:
        sha = subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "unknown"
    except (OSError, subprocess.SubprocessError):
        # TimeoutExpired included: a hung git (stale lock, slow NFS)
        # must degrade to sha="unknown", not kill the whole bench
        sha = "unknown"
    return {
        "metric": "provenance",
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "python": sys.version.split()[0],
        # requested platform only — resolving the actual backend here
        # would initialize it before the mode's own device setup
        "jax_platforms_env": os.environ.get("JAX_PLATFORMS", ""),
        "platform_node": _platform.platform(),
        "cpu_model": cpu_model,
        "timing_method": (
            "time.perf_counter, timed_differenced windows "
            "(bluefog_tpu/timing.py); best-of-N with spread disclosed"
        ),
        "git_sha": sha,
        "bench_mode": os.environ.get("BENCH_MODE", "all"),
        # host-memory context for every evidence artifact: the
        # process's peak RSS at emission time (Linux ru_maxrss is KiB).
        # Harness metadata like anchor_tflops — tools/bench_diff.py
        # must never treat its movement as a comparability break.
        "peak_rss_bytes": _peak_rss_bytes(),
        # per-link-class cost-model constants in force when this
        # artifact was produced (ici = intra-pod torus, dcn = the
        # cross-pod gateway leg): a plan-cost delta between rounds is
        # only attributable when the calibration that priced it is on
        # the record
        "calibration_link_classes": _calibration_classes(),
    }


def _calibration_classes() -> dict:
    try:
        from bluefog_tpu.collective import compiler as compiler_mod

        return {
            cls: compiler_mod.calibration(cls)
            for cls in compiler_mod.LINK_CLASSES
        }
    except Exception:  # provenance must never fail the bench
        return {}


def _peak_rss_bytes() -> int:
    """Peak resident set size of this process in bytes — the memory
    observatory's reader (one KiB→bytes conversion to keep correct;
    bluefog_tpu.memory is stdlib-only at import, and bench already
    imports the package for timing helpers)."""
    from bluefog_tpu.memory import host_peak_rss_bytes

    return host_peak_rss_bytes()


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for key, val in _PEAK_BF16.items():
        if kind.startswith(key):
            return val
    return 0.0


_ANCHOR_LINE = None


def _ambient_anchor() -> dict:
    """The ambient-drift anchor: a fixed dense bf16 matmul
    (``tools/perf_probe.py`` roofline probe — 8192^3 on TPU, a small
    CPU-sized square otherwise) timed in THIS process right where the
    evidence was measured. Same code, same shape, every round: when the
    anchor moves between rounds the host moved, and a headline delta of
    the same magnitude is ambient, not a regression (VERDICT Weak #1's
    unattributable 2798.8 -> 2510.5 drop is the wound this closes).
    Memoized so the headline's ``vs_anchor`` and the emitted anchor
    line are the same measurement."""
    global _ANCHOR_LINE
    if _ANCHOR_LINE is None:
        import jax

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tools.perf_probe import matmul_tflops

        on_tpu = jax.devices()[0].platform not in ("cpu",)
        n = int(
            os.environ.get("BENCH_ANCHOR_N", "8192" if on_tpu else "512")
        )
        _ANCHOR_LINE = {
            "metric": "ambient_anchor",
            "n": n,
            "dtype": "bfloat16",
            "tflops": round(
                matmul_tflops(n, iters=10 if on_tpu else 3, warmup=2), 4
            ),
            "device": jax.devices()[0].device_kind,
        }
    return _ANCHOR_LINE


def bench_row_problems(row: dict) -> list:
    """Physical-plausibility validator for one bench row: a published
    measurement must not claim a non-positive time, and a fwd+bwd cell
    can never undercut its own fwd. Returns the violations (empty =
    plausible). Rows already flagged ``degenerate`` are exempt — their
    values are disclosed as artifacts, not measurements. Wired into
    ``run_flash`` (reject + remeasure) and unit-tested so impossible
    rows cannot ship again (the r05 artifact committed a
    ``dense_fwdbwd_ms`` below ``dense_fwd_ms``)."""
    if row.get("degenerate"):
        return []
    problems = []
    times = {
        k: v for k, v in row.items()
        if k.endswith("_ms") and isinstance(v, (int, float))
        and not isinstance(v, bool)
    }
    for k, v in sorted(times.items()):
        if v <= 0:
            problems.append(f"{k}={v} is not a positive time")
    for k, v in sorted(times.items()):
        if "fwdbwd" not in k:
            continue
        fwd_key = k.replace("fwdbwd", "fwd")
        f = times.get(fwd_key)
        if f is not None and v < f:
            problems.append(
                f"{k}={v} < {fwd_key}={f}: fwd+bwd cannot be faster "
                "than its own forward"
            )
    return problems


# Tunnel-safe sync point (a plain np.asarray readback would cache on the
# array object and break the readback-latency correction — the round-3
# ~25% under-report) + the shared differenced-window timing harness.
from bluefog_tpu.timing import (  # noqa: E402
    settle as _settle,
    timed_differenced as _timed_differenced,
)


def run_headline() -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bluefog_tpu.models import ResNet50
    import bluefog_tpu.topology as topo
    from bluefog_tpu.collective import inner, plan as planlib

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    n = len(devices)

    # Per-worker batch: the BASELINE config is 64; CPU fallback stays tiny
    # so the driver always gets a line.
    batch = int(os.environ.get("BENCH_BATCH", "64" if on_tpu else "4"))
    image = int(os.environ.get("BENCH_IMAGE", "224" if on_tpu else "32"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3")))
    # >=1: the timing loop settles on the warmup's last loss
    warmup = max(
        1, int(os.environ.get("BENCH_WARMUP", "5" if on_tpu else "1"))
    )

    mesh = Mesh(np.array(devices), ("workers",))
    plan = planlib.plan_from_topology(
        topo.ExponentialTwoGraph(n) if n > 1 else topo.FullyConnectedGraph(1),
        weighted=True,
    )

    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    sample = jnp.ones((batch, image, image, 3), jnp.bfloat16)
    variables = model.init(rng, sample, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def stack(tree):
        return jax.tree_util.tree_map(
            lambda t: jnp.broadcast_to(t[None], (n,) + t.shape), tree
        )

    spec = P("workers")
    sharding = NamedSharding(mesh, spec)
    state = jax.device_put(
        (stack(params), stack(batch_stats), stack(opt_state)), sharding
    )

    def train_step(state, images, labels):
        params, batch_stats, opt_state = jax.tree_util.tree_map(
            lambda t: t[0], state
        )
        x, y = images[0], labels[0]

        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Adapt-then-combine gossip of the updated parameters (the
        # neighbor_allreduce optimizer's hot path).
        params = jax.tree_util.tree_map(
            lambda t: inner.neighbor_allreduce(t, plan, "workers"), params
        )
        expand = lambda tr: jax.tree_util.tree_map(
            lambda t: jnp.expand_dims(t, 0), tr
        )
        return expand((params, new_stats, opt_state)), loss.reshape(1)

    fn = jax.jit(
        jax.shard_map(
            train_step,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=(spec, spec),
        ),
        donate_argnums=(0,),
    )

    rng_np = np.random.RandomState(0)
    images = jax.device_put(
        rng_np.randn(n, batch, image, image, 3).astype(np.float32), sharding
    ).astype(jnp.bfloat16)
    labels = jax.device_put(
        rng_np.randint(0, 1000, size=(n, batch)).astype(np.int32), sharding
    )

    for _ in range(warmup):
        state, loss = fn(state, images, labels)
    _settle(loss)
    _settle(loss)  # warm any readback-path compile cache

    # Best-of-N timed windows (default 8 on TPU; each is cheap once
    # compiled): the chip is reached
    # through a shared tunnel, so a single window can absorb unrelated
    # stalls; the best window is the reproducible hardware number (each
    # window is still steps>=20 long).
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "8" if on_tpu else "1")))
    carry = [state]

    def _step():
        carry[0], loss = fn(carry[0], images, labels)
        return loss

    # per-call, sorted; degenerate (stall-clamped) windows are excluded,
    # so the disclosed count is the CLEAN sample size, not the request
    dts, degen = _timed_differenced(
        _step, steps, windows, with_degenerate=True
    )
    per_chip = batch / dts[0]
    baseline_per_accel = 4310.6 / 16.0  # docs/performance.rst:16-24
    anchor = _ambient_anchor()
    result = {
        "metric": "resnet50_bs%d_imgs_per_sec_per_chip" % batch,
        "value": round(per_chip, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / baseline_per_accel, 4),
        # throughput per ambient TFLOP/s: stable vs_anchor + moving
        # value across rounds = the host moved, not the code
        "vs_anchor": round(per_chip / max(anchor["tflops"], 1e-9), 3),
        "anchor_tflops": anchor["tflops"],
        # window spread: best-of-N filters shared-tunnel stalls; the
        # median and worst window are disclosed so the headline is not
        # mistaken for a guaranteed-reproducible number
        "windows": len(dts),
        "median": round(batch / dts[len(dts) // 2], 2),
        "min": round(batch / dts[-1], 2),
    }
    if degen:
        result["degenerate"] = True
    peak = _peak_flops(devices[0])
    if peak:
        # FLOPs/img scale ~quadratically with resolution (BENCH_IMAGE knob).
        flops_img = _FLOPS_PER_IMG_FWD_BWD * (image / 224.0) ** 2
        result["mfu"] = round(per_chip * flops_img / peak, 4)
        result["device"] = devices[0].device_kind
    print(json.dumps(result))
    return 0


def run_scaling() -> int:
    """Scaling-efficiency evidence: HLO comm accounting + weak scaling.

    Defaults to an 8-device virtual CPU mesh (the ambient TPU tunnel exposes
    one chip, and plain env vars are too late — the platform plugin pins
    JAX_PLATFORMS at interpreter startup, so this must go through
    ``jax.config`` before backend init). Set BENCH_SCALING_PLATFORM=native
    to run on the real devices of a multi-chip slice.
    """
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(int(os.environ.get("BENCH_SCALING_DEVICES", "8")))
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import bluefog_tpu.topology as topo
    from bluefog_tpu import scaling
    from bluefog_tpu.collective import plan as planlib

    n_dev = len(jax.devices())
    # Model size in ELEMENTS (ResNet50 has ~25.56M parameters); the f32 wire
    # payload is 4 bytes each.
    payload_elems = int(os.environ.get("BENCH_PAYLOAD_ELEMS", str(25_557_032)))
    payload_bytes = payload_elems * 4
    lines = []

    # Static comm accounting across mesh sizes (bounded by device count).
    ns = [n for n in (2, 4, 8, 16) if n <= n_dev]
    for n in ns:
        sched = planlib.schedule_from_dynamic(
            n,
            lambda r: topo.GetDynamicOnePeerSendRecvRanks(
                topo.ExponentialGraph(n), r
            ),
        )
        stats = scaling.gossip_comm_stats(
            sched.plans[0], payload_elems, jnp.float32
        )
        cp = stats.get("collective-permute", {"count": 0, "bytes": 0})
        ring = scaling.ring_allreduce_cost(n, payload_bytes)
        lines.append(
            {
                "metric": "one_peer_gossip_comm",
                "n_workers": n,
                "collective_permutes": cp["count"],
                "wire_bytes_per_worker": cp["bytes"],
                "ring_allreduce_wire_bytes": round(ring["wire_bytes"]),
                "ring_allreduce_hops": ring["latency_hops"],
            }
        )

    # Weak scaling: constant per-worker compute + one-peer gossip.
    def make_step(mesh):
        n = mesh.devices.size
        plan = (
            planlib.schedule_from_dynamic(
                n,
                lambda r: topo.GetDynamicOnePeerSendRecvRanks(
                    topo.ExponentialGraph(n), r
                ),
            ).plans[0]
            if n > 1
            else planlib.plan_from_topology(topo.FullyConnectedGraph(1))
        )
        spec = P("workers")

        def body(x, w):
            y = jnp.tanh(x @ w)
            return scaling.inner.neighbor_allreduce(y, plan, "workers")

        fn = jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=(spec, P()), out_specs=spec
            )
        )
        x = jax.device_put(
            np.ones((n, 64, 1024), np.float32), NamedSharding(mesh, spec)
        )
        w = jnp.ones((1024, 1024), jnp.float32)
        return fn, (x, w)

    ns_weak = [n for n in (1, 2, 4, 8) if n <= n_dev]
    virtual = os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native"
    for row in scaling.weak_scaling_times(make_step, ns_weak):
        lines.append(
            {
                "metric": "weak_scaling_gossip_step",
                "n_workers": row["n"],
                "ms_per_step": round(row["ms_per_step"], 3),
                "efficiency": round(row["efficiency"], 4),
                # virtual workers share one host's cores: these rows
                # validate the HARNESS (the step runs, efficiency is
                # computable), they are not a hardware scaling claim
                "harness_validation": virtual,
            }
        )

    for line in lines:
        print(json.dumps(line))
    return 0


def run_plan() -> int:
    """Plan-compiler evidence: for each topology, the naive
    (offset-grouped) vs optimized (cost-modeled minimum-round) lowering —
    round counts cross-checked against the compiled HLO's
    collective-permute count — plus measured gossip-step time for both
    plans. Circulant topologies (exp2, ring) must show identical rounds
    (the fast path is kept); the sparse random digraph is where the
    edge-coloring pass wins (König bound = max degree, vs O(N) offsets).

    Then the bandwidth-family evidence (ROADMAP item 2): a one-shot
    measured calibration of the alpha-beta constants
    (``{"metric": "plan_calibration"}``) followed by a payload-size
    sweep (``BENCH_PLAN_SWEEP_BYTES``, default 64 KiB -> 100 MiB) over
    the degree-3 random digraph, measuring the min-round coloring
    against chunked/pipelined and short-cut lowerings per payload —
    with an A/A re-measurement of the baseline as the noise floor —
    and reporting whether the calibrated ``auto`` chooser tracks the
    measured-fastest family (``{"metric": "plan_sweep"}`` lines;
    committed as PLAN_SWEEP_EVIDENCE.json). Degenerate timing windows
    are flagged per cell and excluded from the chooser comparison.
    ``BENCH_ASSERT=1`` additionally asserts the chooser tracks the
    measured winner (within the A/A floor) at both sweep extremes.

    Runs on a virtual CPU mesh by default (same contract as
    BENCH_MODE=scaling: backend init must be owned here); set
    BENCH_SCALING_PLATFORM=native for the real devices of a multi-chip
    slice.
    """
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_PLAN_DEVICES", "16"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import bluefog_tpu.topology as topo
    from bluefog_tpu import scaling
    from bluefog_tpu.collective import compiler, inner, plan as planlib

    n = min(
        len(jax.devices()), int(os.environ.get("BENCH_PLAN_WORKERS", "16"))
    )
    payload_elems = int(
        os.environ.get("BENCH_PLAN_PAYLOAD_ELEMS", str(1 << 16))
    )
    steps = max(1, int(os.environ.get("BENCH_STEPS", "5")))
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3")))

    topologies = {
        "exp2": topo.ExponentialTwoGraph(n),
        "ring": topo.RingGraph(n),
        "star": topo.StarGraph(n),
        "mesh2d": topo.MeshGrid2DGraph(n),
        "random_d3": topo.RandomRegularDigraph(n, min(3, n - 1), seed=1),
    }
    mesh = Mesh(np.array(jax.devices()[:n]), ("workers",))
    sharding = NamedSharding(mesh, P("workers"))
    x0 = jax.device_put(
        np.random.RandomState(0)
        .randn(n, payload_elems)
        .astype(np.float32),
        sharding,
    )

    def measure(plan, x=None, chunks=1, n_steps=None, n_windows=None):
        fn = jax.jit(
            jax.shard_map(
                lambda t: inner.neighbor_allreduce(
                    t, plan, "workers", chunks=chunks
                ),
                mesh=mesh, in_specs=P("workers"), out_specs=P("workers"),
            )
        )
        carry = [x0 if x is None else x]

        def _step():
            carry[0] = fn(carry[0])
            return carry[0][0, 0]  # scalar settle target

        dts, degen = _timed_differenced(
            _step, n_steps or steps, n_windows or windows,
            with_degenerate=True,
        )
        return dts[0], degen

    for name, g in topologies.items():
        optimized = planlib.plan_from_topology(g, weighted=True)
        naive = planlib.plan_from_topology(g, weighted=True, method="offset")
        stats = scaling.gossip_comm_stats(
            optimized, payload_elems, jnp.float32, include_plan=True
        )
        hlo_cp = stats.get("collective-permute", {"count": 0})["count"]
        summary = stats["plan"]
        t_opt, degen_opt = measure(optimized)
        if optimized.perms == naive.perms:
            # circulant fast path kept: the plans are byte-identical, so a
            # second measurement would only publish ambient noise as a
            # fake naive-vs-optimized delta
            t_naive, degen_naive = t_opt, degen_opt
        else:
            t_naive, degen_naive = measure(naive)
        line = {
            "metric": "plan_compiler",
            "topology": name,
            "n_workers": n,
            "payload_elems": payload_elems,
            "naive_rounds": len(naive.rounds),
            "optimized_rounds": len(optimized.rounds),
            "lower_bound": summary["lower_bound"],
            "decomposition": summary["decomposition"],
            "hlo_collective_permutes": hlo_cp,
            "predicted_cost_us": round(summary["predicted_cost_us"], 2),
            "naive_cost_us": round(summary["naive_cost_us"], 2),
            "naive_ms_per_step": round(t_naive * 1e3, 3),
            "optimized_ms_per_step": round(t_opt * 1e3, 3),
        }
        if degen_opt or degen_naive:
            line["degenerate"] = True
        assert len(optimized.rounds) <= len(naive.rounds), line
        assert hlo_cp == len(optimized.rounds), line
        print(json.dumps(line))

    # -- bandwidth family: measured calibration + payload-size sweep --------
    cal = compiler.calibrate(force=True)
    print(json.dumps({
        "metric": "plan_calibration",
        "alpha_us": round(cal["alpha_s"] * 1e6, 2),
        "beta_gbytes_per_s": round(cal["beta_bytes_per_s"] / 1e9, 4),
        "pipeline_eff": round(cal.get("pipeline_eff", 1.0), 4),
        "source": cal["source"],
        "probe_gain_2round_4chunk": round(
            cal.get("probe_gain_2round_4chunk", 0.0), 4
        ),
        "class_alpha_us": compiler.ROUND_ALPHA_S * 1e6,
        "class_beta_gbytes_per_s": compiler.ICI_LINK_BYTES_PER_S / 1e9,
    }))

    sweep_bytes = [
        int(v) for v in os.environ.get(
            "BENCH_PLAN_SWEEP_BYTES",
            "65536,1048576,16777216,104857600",
        ).split(",") if v.strip()
    ]
    sweep_steps = max(1, int(os.environ.get("BENCH_PLAN_SWEEP_STEPS", "3")))
    sweep_windows = max(
        1, int(os.environ.get("BENCH_PLAN_SWEEP_WINDOWS", "2"))
    )
    g = topologies["random_d3"]
    plan_color = planlib.plan_from_topology(g, weighted=True, method="coloring")
    plan_short = planlib.plan_from_topology(g, weighted=True, method="shortcut")
    rng = np.random.RandomState(1)
    sweep_results = []
    for payload_bytes in sweep_bytes:
        elems = max(512, payload_bytes // 4)
        x = jax.device_put(
            rng.randn(n, elems).astype(np.float32), sharding
        )
        auto_k = compiler.choose_chunks(
            plan_color.compile_info, payload_bytes, n_elems=elems,
        )
        # family grid: the latency-optimal point, the chunked/pipelined
        # point (the chooser's k, or a fixed k=8 so the family is still
        # measured when auto stays at 1), and the short-cut relay family
        chunk_k = auto_k if auto_k > 1 else 8
        cells = {}
        degen_cells = []
        for fam, plan, k in (
            ("coloring_k1", plan_color, 1),
            (f"chunked_k{chunk_k}", plan_color, chunk_k),
            (f"shortcut_k{chunk_k}", plan_short, chunk_k),
        ):
            t, degen = measure(
                plan, x=x, chunks=k, n_steps=sweep_steps,
                n_windows=sweep_windows,
            )
            cells[fam] = round(t * 1e3, 3)
            if degen:
                degen_cells.append(fam)
        # A/A floor: re-measure the baseline cell; the disclosed noise
        # any family-vs-family delta must clear to mean anything
        t_aa, degen_aa = measure(
            plan_color, x=x, chunks=1, n_steps=sweep_steps,
            n_windows=sweep_windows,
        )
        if degen_aa:
            degen_cells.append("aa_baseline")
        base = cells["coloring_k1"]
        aa_ms = round(t_aa * 1e3, 3)
        noise_pct = round(
            abs(aa_ms - base) / max(min(aa_ms, base), 1e-9) * 100.0, 2
        )
        auto_family = f"chunked_k{auto_k}" if auto_k > 1 else "coloring_k1"
        clean = {
            f: v for f, v in cells.items() if f not in degen_cells
        }
        measured_best = min(clean, key=clean.get) if clean else None
        # the verdict only means something when the auto family's own
        # cell survived the degenerate-window retries: a flagged cell is
        # EXCLUDED (tracks=None, "unknown"), never trusted either way
        auto_ms = clean.get(auto_family)
        tracks = (
            None
            if auto_ms is None or measured_best is None
            else auto_ms <= clean[measured_best] * (1.0 + noise_pct / 100.0)
        )
        line = {
            "metric": "plan_sweep",
            "topology": "random_d3",
            "n_workers": n,
            "payload_bytes": payload_bytes,
            "rounds": len(plan_color.rounds),
            "shortcut_rounds": len(plan_short.rounds),
            "cells_ms_per_step": cells,
            "aa_baseline_ms": aa_ms,
            "aa_noise_pct": noise_pct,
            "auto_choice": auto_family,
            "auto_chunks": auto_k,
            "predicted_auto_cost_us": round(
                scaling.pipelined_cost_s(
                    payload_bytes, auto_k,
                    plan_color.compile_info.congestion,
                ) * 1e6, 1,
            ),
            "measured_best": measured_best,
            "auto_tracks_best_within_noise": (
                None if tracks is None else bool(tracks)
            ),
        }
        if degen_cells:
            line["degenerate_cells"] = sorted(set(degen_cells))
        sweep_results.append(line)
        print(json.dumps(line))

    if os.environ.get("BENCH_ASSERT", "0") == "1" and len(sweep_results) >= 2:
        # acceptance: the calibrated chooser must track the measured
        # winner at both ends of the sweep (cells that stayed degenerate
        # after retries are excluded above rather than trusted: an end
        # whose verdict is None is unassertable, not a pass or a fail)
        for end in (sweep_results[0], sweep_results[-1]):
            assert end["auto_tracks_best_within_noise"] is not False, end
    return 0


def run_gossip_overhead() -> int:
    """Bound the gossip step's on-chip cost with communication REALLY in
    the program: 8 virtual workers share the one chip (vmapped replicas,
    bs/8 each), and the neighbor combine is the algebraically-identical
    einsum with the Exp2 weight matrix over the replica axis. The delta
    vs the combine-free step bounds the per-step gossip arithmetic +
    memory cost; the model-size HBM roundtrip gives the per-round wire
    floor a real ppermute pays on top (ICI transfer not measurable with
    one chip). Emits one JSON line per measurement."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    import networkx as nx

    from bluefog_tpu.models import ResNet50
    import bluefog_tpu.topology as topo

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    n_virt = int(os.environ.get("BENCH_GOSSIP_WORKERS", "8"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "2"))
    image = int(os.environ.get("BENCH_IMAGE", "224" if on_tpu else "32"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "10" if on_tpu else "2")))
    # >=1: the timing loop settles on the warmup's last loss
    warmup = max(
        1, int(os.environ.get("BENCH_WARMUP", "3" if on_tpu else "1"))
    )

    w = jnp.asarray(
        nx.to_numpy_array(topo.ExponentialTwoGraph(n_virt)), jnp.float32
    )
    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    sample = jnp.ones((batch, image, image, 3), jnp.bfloat16)
    variables = model.init(rng, sample, train=True)
    tx = optax.sgd(0.1, momentum=0.9)
    stack = lambda tree: jax.tree_util.tree_map(
        lambda t: jnp.broadcast_to(t[None], (n_virt,) + t.shape) + 0.0, tree
    )
    params = stack(variables["params"])
    batch_stats = stack(variables["batch_stats"])
    opt_state = jax.tree_util.tree_map(
        lambda t: t + 0.0, stack(tx.init(variables["params"]))
    )
    rng_np = np.random.RandomState(0)
    images = jnp.asarray(
        rng_np.randn(n_virt, batch, image, image, 3), jnp.bfloat16
    )
    labels = jnp.asarray(
        rng_np.randint(0, 1000, (n_virt, batch)), jnp.int32
    )

    def one_step(p, bs, s, x, y):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"],
            )
            return (
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean(),
                mutated["batch_stats"],
            )

        (loss, nbs), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), nbs, s, loss

    def make(gossip):
        def step(params, batch_stats, opt_state, images, labels):
            p, nbs, s, loss = jax.vmap(one_step)(
                params, batch_stats, opt_state, images, labels
            )
            if gossip:
                # y_j = sum_i W[i, j] x_i over the replica axis — the
                # exact neighbor_allreduce combine, on-chip
                p = jax.tree_util.tree_map(
                    lambda t: jnp.einsum(
                        "ij,i...->j...", w.astype(t.dtype), t
                    ),
                    p,
                )
            return p, nbs, s, loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def stepper(fn, carry):
        def _step():
            p, bs, s = carry[0]
            p, bs, s, loss = fn(p, bs, s, images, labels)
            carry[0] = (p, bs, s)
            return loss

        return _step

    copy = lambda tr: jax.tree_util.tree_map(lambda t: t + 0.0, tr)
    step_plain = stepper(
        make(False), [(copy(params), copy(batch_stats), copy(opt_state))]
    )
    step_gossip = stepper(make(True), [(params, batch_stats, opt_state)])
    for _ in range(warmup - 1):
        step_plain()
        step_gossip()
    # INTERLEAVED rounds: the overhead is a ratio of two measurements,
    # and ambient tunnel/host drift between two sequential measurement
    # phases (observed up to ~30% across minutes) would read as fake
    # overhead; alternating windows expose both variants to the same
    # ambient conditions
    dts_plain, dts_gossip = [], []
    for _ in range(3):
        dts_plain += _timed_differenced(step_plain, steps, windows=1)
        dts_gossip += _timed_differenced(step_gossip, steps, windows=1)
    dt_plain, dt_gossip = min(dts_plain), min(dts_gossip)

    # wire floor: one model-size HBM roundtrip (a ppermute's on-chip
    # cost). Sub-ms per iteration, so run many to dominate the readback
    # correction.
    flat = jnp.zeros((25_557_032,), jnp.float32)
    bump = jax.jit(lambda t: t + 1.0)
    copy_iters = 20 * steps
    for _ in range(warmup):
        flat = bump(flat)
    _settle(flat[:1])
    t0 = time.perf_counter()
    for _ in range(copy_iters):
        flat = bump(flat)
    _settle(flat[:1])
    t1 = time.perf_counter()
    _settle(flat[:1])
    dt_copy = max(t1 - t0 - (time.perf_counter() - t1), 1e-9) / copy_iters

    total = n_virt * batch
    overhead_pct = 100.0 * (dt_gossip - dt_plain) / dt_plain
    # The per-WORKER combine cost against the BASELINE-config (bs=64)
    # step is the deployment-relevant number: the raw ratio above divides
    # by this mode's deliberately small per-replica compute (bs=8 so 8
    # replicas fit one chip), which inflates it ~8x vs a real worker and
    # leaves it noise-dominated.
    combine_ms_per_worker = max(dt_gossip - dt_plain, 0.0) / n_virt * 1e3
    step_bs64_ms = dt_plain / n_virt * (64.0 / batch) * 1e3
    overhead_pct_bs64 = 100.0 * combine_ms_per_worker / step_bs64_ms
    for line in (
        {"metric": "gossip_step_no_comm", "workers_on_chip": n_virt,
         "imgs_per_sec": round(total / dt_plain, 1),
         "ms_per_step": round(dt_plain * 1e3, 2)},
        {"metric": "gossip_step_with_combine", "workers_on_chip": n_virt,
         "imgs_per_sec": round(total / dt_gossip, 1),
         "ms_per_step": round(dt_gossip * 1e3, 2),
         "gossip_overhead_pct": round(overhead_pct, 2),
         "combine_ms_per_worker": round(combine_ms_per_worker, 3),
         "overhead_pct_vs_bs64_step": round(overhead_pct_bs64, 2)},
        {"metric": "model_hbm_roundtrip", "ms": round(dt_copy * 1e3, 3)},
    ):
        print(json.dumps(line))
    if on_tpu and os.environ.get("BENCH_ASSERT", "1") != "0":
        # regression assertion (reference analogue:
        # scripts/pytorch_opt_linear_speedup_test.py asserts, not
        # narrates): the full-model combine must stay under 10% of a
        # baseline-config worker's step — loose enough to ride tunnel
        # noise, tight enough to catch a structural blowup (e.g. the
        # per-leaf combine regression _packed_gossip exists to prevent)
        assert overhead_pct_bs64 < 10.0, (
            f"per-worker gossip combine regressed to "
            f"{combine_ms_per_worker:.2f} ms = {overhead_pct_bs64:.2f}% "
            "of a bs=64 step (must stay < 10%)"
        )
    return 0


def run_overlap() -> int:
    """Exposed-communication comparison for the overlap layer
    (``opt.make_train_step``): two-program baseline vs fused vs
    fused+buckets vs delayed, plus the static HLO overlap scan.

    Each variant trains the same MLP regression step over an Exp2 gossip
    topology; ``exposed_comm_ms`` is the variant's step time minus the
    communication-free fused step (the compute floor), so it measures
    exactly the communication left on the critical path. The HLO scan
    (tools/hlo_overlap_scan.py) verifies the overlap claim statically:
    on TPU it counts async ``collective-permute-start``/``-done`` pairs
    with compute scheduled between them; on CPU (whose backend keeps
    collectives synchronous at the HLO level) it proves overlap
    *capability* by def-use independence instead. Runs on the ambient
    platform when it exposes >1 device (a real slice); otherwise on a
    virtual CPU mesh.
    """
    native = os.environ.get("BENCH_SCALING_PLATFORM", "")
    ambient = os.environ.get("JAX_PLATFORMS", "")
    use_native = native == "native" or (
        native == "" and ambient not in ("", "cpu")
    )
    if not use_native:
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_OVERLAP_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu.collective import inner as col_inner

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.hlo_overlap_scan import scan_overlap

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    n = min(len(devices), int(os.environ.get("BENCH_OVERLAP_WORKERS", "8")))
    if n < 2:
        # a 1-device native platform has no wire: nothing to overlap,
        # and every variant would time identically up to noise
        print(json.dumps({
            "metric": "overlap_skipped", "reason": "single device",
            "platform": devices[0].platform,
        }))
        return 0
    dim = int(os.environ.get("BENCH_OVERLAP_DIM", "2048" if on_tpu else "512"))
    layers = int(os.environ.get("BENCH_OVERLAP_LAYERS", "8" if on_tpu else "6"))
    batch = int(os.environ.get("BENCH_OVERLAP_BATCH", "128" if on_tpu else "32"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "10" if on_tpu else "5")))
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "5" if on_tpu else "3")))
    bucket_bytes = int(
        os.environ.get("BENCH_OVERLAP_BUCKET_BYTES", str(1 << 20))
    )

    bf.init(devices=devices[:n])
    bf.set_topology(topo.ExponentialTwoGraph(n))

    rng = np.random.RandomState(0)
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    x_np = rng.randn(n, batch, dim).astype(np.float32)
    y_np = rng.randn(n, batch, dim).astype(np.float32)

    def make_params():
        return {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }

    xs = bf.worker_values(lambda r: x_np[r])
    ys = bf.worker_values(lambda r: y_np[r])

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    n_elems = layers * dim * dim
    ctx = bf.get_context()

    def new_opt():
        return bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )

    def fused_stepper(opt, **kwargs):
        train_step = bf.make_train_step(opt, loss_fn, **kwargs)
        params = make_params()
        state = opt.init(params)
        carry = [(params, state)]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs, ys)
            carry[0] = (p, s)
            return loss

        return _step, train_step, carry

    def fused_hlo(opt, carry):
        """Optimized HLO of this variant's fused program."""
        p, s = carry[0]
        return opt.lower_last_fused_hlo(p, s, xs, ys)

    variants = ("no_comm", "two_program", "fused", "fused_buckets",
                "delayed")
    env_caps = {
        "two_program": "0",  # cap irrelevant: one payload, legacy path
        "fused": "0",
        "fused_buckets": str(bucket_bytes),
        "delayed": str(bucket_bytes),
        "no_comm": "0",
    }
    old_cap = os.environ.get("BLUEFOG_BUCKET_BYTES")
    # an ambient BLUEFOG_OVERLAP=0 would short-circuit bucket_bytes_cap()
    # and silently compile the bucketed variants monolithic — the
    # published evidence would describe programs that were never built
    old_overlap = os.environ.get("BLUEFOG_OVERLAP")
    os.environ["BLUEFOG_OVERLAP"] = "1"
    steppers = {}
    hlo_texts = {}

    # restore belongs in finally: bucket_bytes_cap() reads the env on
    # every optimizer dispatch, so an exception mid-bench (XLA OOM, a
    # degenerate-platform abort) must not leak the last variant's cap
    # into the caller's process
    try:
        for variant in variants:
            os.environ["BLUEFOG_BUCKET_BYTES"] = env_caps[variant]
            if variant == "two_program":
                # the pre-overlap reality: the caller's grad program and
                # the optimizer's gossip+update program are separate
                # dispatches — every ppermute round fully exposed
                # between them
                opt = new_opt()
                params = make_params()
                state = opt.init(params)
                spec = P("workers")

                def grad_body(p_b, x_b, y_b):
                    p = jax.tree_util.tree_map(lambda t: t[0], p_b)
                    g = jax.grad(loss_fn)(p, x_b[0], y_b[0])
                    return jax.tree_util.tree_map(
                        lambda t: jnp.expand_dims(t, 0), g
                    )

                grad_fn = jax.jit(
                    jax.shard_map(
                        grad_body, mesh=ctx.mesh,
                        in_specs=(spec, spec, spec), out_specs=spec,
                    )
                )
                carry = [(params, state)]

                def _step(carry=carry, grad_fn=grad_fn, opt=opt):
                    p, s = carry[0]
                    g = grad_fn(p, xs, ys)
                    p, s = opt.step(p, s, g)
                    carry[0] = (p, s)
                    return p["w0"][0, 0, 0]  # scalar settle target

                steppers[variant] = _step
            else:
                opt = new_opt()
                if variant == "no_comm":
                    opt.communication_type = bf.CommunicationType.empty
                kwargs = {"delayed": True} if variant == "delayed" else {}
                _step, train_step, carry = fused_stepper(opt, **kwargs)
                steppers[variant] = _step
                _step()  # compile now, under this variant's bucket cap
                if variant in ("fused", "fused_buckets", "delayed"):
                    hlo_texts[variant] = fused_hlo(opt, carry)

        # INTERLEAVED windows (same rationale as BENCH_MODE=gossip): the
        # comparison is a ratio of separately-timed variants, and
        # ambient drift between sequential phases would read as fake
        # overlap gains; round-robin windows expose every variant to the
        # same conditions.
        dts = {v: [] for v in variants}
        degens = {v: 0 for v in variants}  # stall-clamped window count
        for _ in range(windows):
            for variant in variants:
                os.environ["BLUEFOG_BUCKET_BYTES"] = env_caps[variant]
                ts_w, degen = _timed_differenced(
                    steppers[variant], steps, 1, with_degenerate=True
                )
                if degen:
                    degens[variant] += 1
                else:
                    dts[variant] += ts_w
    finally:
        if old_cap is None:
            os.environ.pop("BLUEFOG_BUCKET_BYTES", None)
        else:
            os.environ["BLUEFOG_BUCKET_BYTES"] = old_cap
        if old_overlap is None:
            os.environ.pop("BLUEFOG_OVERLAP", None)
        else:
            os.environ["BLUEFOG_OVERLAP"] = old_overlap
    results = {
        v: (min(dts[v]) if dts[v] else 0.0, not dts[v]) for v in variants
    }

    floor, floor_degen = results["no_comm"]
    for variant in ("two_program", "fused", "fused_buckets", "delayed"):
        dt, degen = results[variant]
        exposed = max(dt - floor, 0.0)
        line = {
            "metric": "overlap_step",
            "variant": variant,
            "n_workers": n,
            "payload_mb": round(n_elems * 4 / 1e6, 2),
            "ms_per_step": round(dt * 1e3, 3),
            "compute_floor_ms": round(floor * 1e3, 3),
            "exposed_comm_ms": round(exposed * 1e3, 3),
        }
        if floor > 0:
            line["gossip_overhead_pct"] = round(100.0 * exposed / floor, 2)
        if degens[variant]:
            # partial stalls: the published best-of excludes them, but
            # the sample size shrank — disclose, don't hide
            line["degenerate_windows"] = degens[variant]
            line["clean_windows"] = len(dts[variant])
        if degen or floor_degen:
            # every window clamped: the value is a floor artifact
            line["degenerate"] = True
        print(json.dumps(line))

    bounds = col_inner.bucket_bounds(n_elems, 4, bucket_bytes)
    print(json.dumps({
        "metric": "overlap_buckets",
        "bucket_bytes_cap": bucket_bytes,
        "n_buckets": len(bounds),
        "bucket_elems": [b - a for a, b in bounds[:16]],
    }))

    for variant, txt in hlo_texts.items():
        scan = scan_overlap(txt)
        print(json.dumps({
            "metric": "overlap_hlo",
            "variant": variant,
            "platform": devices[0].platform,
            **{k: v for k, v in scan.items() if k != "permutes"},
        }))
        if variant in ("fused_buckets", "delayed"):
            # schedule-order timeline: one event per bucket-round permute
            print(json.dumps({
                "metric": "overlap_bucket_timeline",
                "variant": variant,
                "events": [
                    {
                        "name": p["name"],
                        "kind": p["kind"],
                        "payload_bytes": p["payload_bytes"],
                        "start_pos": p["start_pos"],
                        "done_pos": p["done_pos"],
                        "overlapped_compute": p["compute_between"],
                        "independent_compute_ops":
                            p["independent_compute_ops"],
                    }
                    for p in scan["permutes"][:32]
                ],
            }))

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        degenerate = any(d for _t, d in results.values())
        if not degenerate:
            # the acceptance pair: fused+buckets must leave LESS
            # communication exposed than the two-program baseline
            two = results["two_program"][0] - floor
            fb = results["fused_buckets"][0] - floor
            assert fb < two, (
                f"fused+buckets exposed comm {fb*1e3:.3f} ms is not below "
                f"the two-program baseline {two*1e3:.3f} ms"
            )
        if on_tpu:
            scan = scan_overlap(hlo_texts["fused_buckets"])
            assert scan["overlapped_async_pairs"] >= 1, (
                "TPU fused program shows no async collective-permute "
                "pair overlapping compute: "
                f"{ {k: v for k, v in scan.items() if k != 'permutes'} }"
            )
    return 0


def run_metrics() -> int:
    """Metrics-overhead evidence: the same fused gossip train step timed
    with the telemetry device tier off vs on (``BLUEFOG_METRICS=1``,
    interval 10) on the 8-worker CPU mesh, plus the bitwise pin that
    enabling metrics does not move the training state, and a sample of
    the drained registry. The acceptance bound — <2 % step-time
    overhead — is asserted here so the committed METRICS_EVIDENCE.json
    is re-checked by every bench run.

    Measurement protocol — per-sample delta, analytically amortized.
    Direct wall-clock A/B at interval 10 cannot resolve <2 % on a
    shared host: the A/A (off vs off) control of both window-level and
    step-level paired protocols was measured swinging +-5 % run to run
    (ambient load states are autocorrelated at the seconds scale). The
    <2 % claim decomposes into two facts that ARE resolvable:

    1. Unsampled steps (interval-1 of every interval) dispatch the SAME
       compiled program as metrics-off — verified structurally here by
       toggling BLUEFOG_METRICS on the same optimizer and asserting no
       new op-cache entry appears. Zero overhead by construction.
    2. The sampled step's incremental cost (metric-instrumented program
       + drain swap) is measured directly by running the on-stepper at
       interval=1 — every step pays it — against the off-stepper in a
       step-level rotation (all orderings, position bias cancels).
       Resolving the PER-SAMPLE delta needs only ~20 % resolution for a
       2 % amortized bound, well above the noise floor; the published
       ``overhead_pct`` is that delta divided by the interval. An
       off/off A/A control runs the identical protocol and is published
       amortized the same way as the method's noise floor."""
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_METRICS_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import metrics as bf_metrics

    devices = jax.devices()
    n = min(len(devices), int(os.environ.get("BENCH_METRICS_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_METRICS_DIM", "512"))
    layers = int(os.environ.get("BENCH_METRICS_LAYERS", "12"))
    batch = int(os.environ.get("BENCH_METRICS_BATCH", "32"))
    interval = int(os.environ.get("BLUEFOG_METRICS_INTERVAL", "10"))
    samples = max(
        30, int(os.environ.get("BENCH_METRICS_SAMPLES", "150"))
    )

    bf.init(devices=devices[:n])
    bf.set_topology(topo.ExponentialTwoGraph(n))

    rng = np.random.RandomState(0)
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    xs = bf.worker_values(lambda r: rng.randn(batch, dim).astype(np.float32))
    ys = bf.worker_values(lambda r: rng.randn(batch, dim).astype(np.float32))

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    def make_stepper():
        opt = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )
        train_step = bf.make_train_step(opt, loss_fn)
        params = {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }
        carry = [(params, opt.init(params))]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs, ys)
            carry[0] = (p, s)
            return loss

        return _step, carry

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_METRICS", "BLUEFOG_METRICS_INTERVAL",
                  "BLUEFOG_METRICS_FILE", "BLUEFOG_METRICS_PROM")
    }
    # no exporter I/O inside the timed loop: the evidence bounds the
    # in-graph computation + the interval-amortized drain readback
    os.environ.pop("BLUEFOG_METRICS_FILE", None)
    os.environ.pop("BLUEFOG_METRICS_PROM", None)
    os.environ["BLUEFOG_METRICS_INTERVAL"] = str(interval)
    # "on" runs at interval=1 so EVERY timed step pays the sampled
    # program + drain; "off2" is the A/A control: a second metrics-off
    # stepper measured with the same protocol, so the published number
    # comes with the methodology's own noise floor next to it.
    env_cfg = {"off": ("0", None), "on": ("1", "1"), "off2": ("0", None)}

    def set_env(variant):
        met, iv = env_cfg[variant]
        os.environ["BLUEFOG_METRICS"] = met
        os.environ["BLUEFOG_METRICS_INTERVAL"] = iv or str(interval)

    try:
        import itertools
        import time as time_mod

        steppers = {}
        carries = {}
        for variant in ("off", "on", "off2"):
            set_env(variant)
            steppers[variant], carries[variant] = make_stepper()
            steppers[variant]()  # compile under this variant's config
            steppers[variant]()  # and the on-variant's drain path
            _settle(steppers[variant]())

        # structural fact 1: with metrics enabled, an off-boundary
        # (unsampled) dispatch reuses the metrics-off compiled program —
        # toggling the flag on the SAME stepper adds no op-cache entry
        ctx = bf.get_context()
        os.environ["BLUEFOG_METRICS"] = "0"
        steppers["off"]()
        n_cache = len(ctx.op_cache)
        # the off-stepper's comm count is already past 0, so with a huge
        # interval this enabled dispatch is off-boundary == unsampled
        os.environ["BLUEFOG_METRICS"] = "1"
        os.environ["BLUEFOG_METRICS_INTERVAL"] = "1000000000"
        steppers["off"]()
        unsampled_shared = len(ctx.op_cache) == n_cache
        set_env("off")

        orders = list(itertools.permutations(("off", "on", "off2")))
        times = {v: [] for v in steppers}
        for i in range(samples):
            for variant in orders[i % len(orders)]:
                set_env(variant)
                t0 = time_mod.perf_counter()
                _settle(steppers[variant]())
                times[variant].append(time_mod.perf_counter() - t0)

        pairs = list(zip(times["off"], times["on"]))
        control_pairs = list(zip(times["off"], times["off2"]))

        # bitwise pin, fresh state both ways, same step count, at the
        # published interval (so both sampled and unsampled dispatches
        # are exercised on the metrics-on side)
        state_bits = {}
        os.environ["BLUEFOG_METRICS_INTERVAL"] = str(interval)
        for variant in ("off", "on"):
            os.environ["BLUEFOG_METRICS"] = env_cfg[variant][0]
            _step, carry = make_stepper()
            for _ in range(12):
                _step()
            state_bits[variant] = jax.tree_util.tree_leaves(carry[0])
        bitwise = all(
            bool(np.array_equal(np.asarray(a), np.asarray(b)))
            for a, b in zip(state_bits["off"], state_bits["on"])
        )
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    def median(v):
        v = sorted(v)
        return v[len(v) // 2] if v else 0.0

    degenerate = not pairs
    base_s = median(times["off"])
    # per-SAMPLE incremental cost (ms): paired per-step deltas, median
    sample_extra_s = median([on - off for off, on in pairs])
    control_extra_s = median([o2 - off for off, o2 in control_pairs])
    # amortized: one sampled step per interval, the rest are the shared
    # metrics-off program (unsampled_shared above)
    overhead_pct = (
        100.0 * sample_extra_s / interval / base_s if base_s > 0 else 0.0
    )
    control_pct = (
        100.0 * control_extra_s / interval / base_s if base_s > 0 else 0.0
    )
    line = {
        "metric": "metrics_overhead",
        "n_workers": n,
        "payload_mb": round(layers * dim * dim * 4 / 1e6, 2),
        "interval": interval,
        "ms_per_step_off": round(base_s * 1e3, 3),
        "ms_sampled_step_extra": round(sample_extra_s * 1e3, 3),
        "unsampled_program_shared": unsampled_shared,
        "overhead_pct": round(overhead_pct, 3),
        # A/A control: what the same protocol+amortization reports for
        # two IDENTICAL metrics-off steppers — the honest noise floor
        "control_aa_pct": round(control_pct, 3),
        "bitwise_identical": bitwise,
        "samples": len(pairs),
    }
    if degenerate:
        line["degenerate"] = True
    print(json.dumps(line))

    bf_metrics.flush()  # fold any deferred drains before sampling
    snap = bf_metrics.snapshot()
    sample = {
        k: v.get("value")
        for k, v in snap.items()
        if k.startswith("bluefog.gossip.") or k in (
            "bluefog.wire_bytes", "bluefog.comm_steps",
            "bluefog.recompiles",
        )
    }
    print(json.dumps({"metric": "metrics_snapshot_sample", **sample}))

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert bitwise, (
            "enabling metrics changed the training state bitwise"
        )
        assert unsampled_shared, (
            "unsampled metrics-on dispatch did not reuse the "
            "metrics-off compiled program"
        )
        if not degenerate:
            assert overhead_pct < 2.0, (
                f"metrics overhead {overhead_pct:.2f}% exceeds the 2% "
                "acceptance bound at interval "
                f"{interval}"
            )
    return 0


def run_elastic() -> int:
    """Elastic-gossip evidence (``BENCH_MODE=elastic``): an 8-worker CPU
    mesh with a rank killed mid-training through the deterministic chaos
    layer. Emits steps-to-detect, steps-to-repair, the post-repair
    consensus distance against the numpy survivor-oracle, and the
    plan-cache live-set accounting proving no stale CommPlan dispatched
    after the membership change. ``BENCH_ASSERT=1`` (default) enforces
    the acceptance bounds. See docs/elastic.md."""
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_ELASTIC_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax
    import optax

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo

    devices = jax.devices()
    n = min(len(devices), int(os.environ.get("BENCH_ELASTIC_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_ELASTIC_DIM", "4096"))
    kill_step = int(os.environ.get("BENCH_ELASTIC_KILL_STEP", "5"))
    grad_steps = int(os.environ.get("BENCH_ELASTIC_GRAD_STEPS", "12"))
    steps = int(os.environ.get("BENCH_ELASTIC_STEPS", "48"))
    kill_rank = n // 2
    lr = np.float32(0.05)

    bf.init(devices=devices[:n])
    bf.set_topology(topo.ExponentialTwoGraph(n))
    ctx = bf.get_context()

    session = bf.elastic.start(policy="average")
    session.inject("kill", rank=kill_rank, step=kill_step)
    opt = bf.DistributedAdaptThenCombineOptimizer(optax.sgd(float(lr)))
    guard = bf.elastic.guard(opt)

    rng = np.random.RandomState(0)
    x0 = rng.randn(n, dim).astype(np.float32)
    grads = [
        rng.randn(n, dim).astype(np.float32) * 0.1 for _ in range(grad_steps)
    ]
    zeros = np.zeros((n, dim), np.float32)
    params = {"w": bf.worker_values(lambda r: x0[r])}
    state = opt.init(params)
    at_repair = None
    t0 = time.perf_counter()
    for t in range(steps):
        g = grads[t] if t < grad_steps else zeros
        if t == kill_step:
            at_repair = np.asarray(params["w"])
        params, state = guard.step(
            params, state, {"w": bf.worker_values(lambda r: g[r])}
        )
    wall_s = time.perf_counter() - t0

    rec = session.repairs[0]
    live = list(session.membership.live_ranks())
    final = np.asarray(params["w"])

    # survivor-consensus oracle: mean of survivors at repair plus the
    # post-repair gradient drift (the doubly stochastic repaired mix
    # preserves the survivor mean exactly)
    target = at_repair[live].mean(axis=0)
    for t in range(kill_step, grad_steps):
        target = target - lr * grads[t][live].mean(axis=0)
    consensus_dist = float(np.abs(final[live] - target).max())
    spread = float(np.abs(final[live] - final[live].mean(axis=0)).max())

    # live-set-aware plan cache: every static plan compiled after the
    # session opened carries a live token; repair added a new entry
    plan_keys = [
        k for k in ctx.op_cache if isinstance(k, tuple)
        and k and k[0] == "static_plan"
    ]
    tokened = [k for k in plan_keys if k[-1] is not None]

    detect = max(rec.steps_to_detect.values())
    lines = [
        {
            "metric": "elastic_repair",
            "workers": n,
            "kill_rank": kill_rank,
            "kill_step": kill_step,
            "repair_step": rec.step,
            "steps_to_detect": detect,
            "steps_to_repair": rec.steps_to_repair,
            "policy": rec.policy,
            "dead": list(rec.dead),
            "live_count": len(live),
            "topo_version_after": rec.topo_version,
            "wall_s_total": round(wall_s, 3),
        },
        {
            "metric": "elastic_consensus",
            "steps_after_repair": steps - kill_step,
            "post_repair_consensus_distance": consensus_dist,
            "survivor_spread": spread,
            "oracle": "numpy survivor mean + gradient drift",
        },
        {
            "metric": "elastic_plan_cache",
            "static_plan_cache_entries": len(plan_keys),
            "entries_with_live_token": len(tokened),
            "stale_commplan_dispatches": session.stale_dispatches,
        },
    ]
    for line in lines:
        print(json.dumps(line))
    bf.elastic.stop()

    if os.environ.get("BENCH_ASSERT", "1") == "1":
        assert detect <= 1, f"detection took {detect} steps"
        assert rec.steps_to_repair == 0, rec
        assert session.stale_dispatches == 0
        assert consensus_dist < 1e-3, consensus_dist
        assert tokened, "static-plan cache keys carry no live token"
    return 0


def run_flight() -> int:
    """Flight-recorder evidence (``BENCH_MODE=flight``): the black box
    must cost ~nothing and the postmortem must be right. Three claims,
    each measured the way it is resolvable (the direct-A/B noise-floor
    lesson of BENCH_MODE=metrics applies here too):

    1. **Overhead <= 1 % per step** (recorder is on by default). Primary
       measurement is analytic decomposition: the per-event ring-write
       cost (tight microbenchmark, best-of-windows) times the exact
       events-per-step count (read off the ring's sequence numbers)
       over the differenced-harness step time. A direct interleaved
       on/off A/B with an off/off A/A control is published next to it
       as the honest end-to-end cross-check (NOT asserted: its noise
       floor on a shared host exceeds the bound being claimed).
    2. **Bitwise-identical trajectory** recorder on vs off (recording
       never touches device values; pinned here every round).
    3. **Postmortem correctness**: a BLUEFOG_FAULT_PLAN-killed rank on
       the 8-worker mesh, dumps + timeline fused by
       ``tools/trace_merge.py`` — the merged Perfetto JSON must be
       valid, its per-step round count must match the independently
       compiled CommPlan, and the hang postmortem must name the killed
       rank and the exact edge/round each neighbor stalled on.
    """
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_FLIGHT_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import itertools
    import tempfile
    import time as time_mod

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import flight as bf_flight
    from bluefog_tpu.collective.plan import plan_from_topology

    devices = jax.devices()
    n = min(len(devices), int(os.environ.get("BENCH_FLIGHT_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_FLIGHT_DIM", "512"))
    layers = int(os.environ.get("BENCH_FLIGHT_LAYERS", "12"))
    batch = int(os.environ.get("BENCH_FLIGHT_BATCH", "32"))
    samples = max(24, int(os.environ.get("BENCH_FLIGHT_SAMPLES", "90")))
    kill_step = int(os.environ.get("BENCH_FLIGHT_KILL_STEP", "5"))
    pm_steps = int(os.environ.get("BENCH_FLIGHT_STEPS", "12"))

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_FLIGHT", "BLUEFOG_FLIGHT_DIR",
                  "BLUEFOG_TIMELINE")
    }
    os.environ.pop("BLUEFOG_FLIGHT_DIR", None)
    os.environ.pop("BLUEFOG_TIMELINE", None)

    bf.init(devices=devices[:n])
    bf.set_topology(topo.ExponentialTwoGraph(n))

    rng = np.random.RandomState(0)
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    xs = bf.worker_values(lambda r: rng.randn(batch, dim).astype(np.float32))
    ys = bf.worker_values(lambda r: rng.randn(batch, dim).astype(np.float32))

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    def make_stepper():
        opt = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )
        train_step = bf.make_train_step(opt, loss_fn)
        params = {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }
        carry = [(params, opt.init(params))]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs, ys)
            carry[0] = (p, s)
            return loss

        return _step, carry

    def set_flight(on: bool):
        os.environ["BLUEFOG_FLIGHT"] = "1" if on else "0"
        bf_flight.reconfigure()

    try:
        # -- claim 1a: per-event ring-write cost (microbenchmark) ------------
        set_flight(True)
        n_calls = 200_000
        per_event = []
        for _ in range(5):
            t0 = time_mod.perf_counter()
            for _i in range(n_calls):
                bf_flight.record("bench", step=1, comm=True)
            per_event.append((time_mod.perf_counter() - t0) / n_calls)
        per_event_s = min(per_event)

        # -- claim 1b: exact events-per-step, from ring sequence numbers -----
        set_flight(True)
        stepper, _carry = make_stepper()
        stepper()  # compile outside the counted window
        _settle(stepper())
        before = max(
            (e["seq"] for e in bf_flight.events()), default=0
        )
        count_steps = 10
        for _ in range(count_steps):
            stepper()
        _settle(stepper())
        after = max((e["seq"] for e in bf_flight.events()), default=0)
        events_per_step = (after - before) / (count_steps + 1)

        # -- claim 1c: step time (differenced harness), recorder ON ----------
        step_times = _timed_differenced(stepper, 10, 4)
        step_s = step_times[0]
        overhead_pct = (
            100.0 * events_per_step * per_event_s / step_s
            if step_s > 0 else 0.0
        )

        # -- cross-check: direct interleaved A/B + A/A control (disclosed) ---
        steppers = {}
        for variant in ("off", "on", "off2"):
            set_flight(variant == "on")
            steppers[variant], _ = make_stepper()
            steppers[variant]()
            _settle(steppers[variant]())
        orders = list(itertools.permutations(("off", "on", "off2")))
        times = {v: [] for v in steppers}
        for i in range(samples):
            for variant in orders[i % len(orders)]:
                set_flight(variant == "on")
                t0 = time_mod.perf_counter()
                _settle(steppers[variant]())
                times[variant].append(time_mod.perf_counter() - t0)

        def median(v):
            v = sorted(v)
            return v[len(v) // 2] if v else 0.0

        base_s = median(times["off"])
        direct_pct = (
            100.0 * median([b - a for a, b in zip(times["off"],
                                                  times["on"])]) / base_s
            if base_s > 0 else 0.0
        )
        control_pct = (
            100.0 * median([b - a for a, b in zip(times["off"],
                                                  times["off2"])]) / base_s
            if base_s > 0 else 0.0
        )

        # -- claim 2: bitwise trajectory pin, on vs off ----------------------
        state_bits = {}
        for variant in ("off", "on"):
            set_flight(variant == "on")
            _step, carry = make_stepper()
            for _ in range(12):
                _step()
            state_bits[variant] = jax.tree_util.tree_leaves(carry[0])
        bitwise = all(
            bool(np.array_equal(np.asarray(a), np.asarray(b)))
            for a, b in zip(state_bits["off"], state_bits["on"])
        )

        print(json.dumps({
            "metric": "flight_recorder_overhead",
            "n_workers": n,
            "payload_mb": round(layers * dim * dim * 4 / 1e6, 2),
            "per_event_us": round(per_event_s * 1e6, 3),
            "events_per_step": round(events_per_step, 2),
            "ms_per_step": round(step_s * 1e3, 3),
            "overhead_pct": round(overhead_pct, 4),
            "method": (
                "analytic: per-event ring-write cost x exact "
                "events/step over the differenced step time"
            ),
            "direct_ab_pct": round(direct_pct, 3),
            "control_aa_pct": round(control_pct, 3),
            "direct_ab_note": (
                "interleaved per-step median delta; disclosed as the "
                "end-to-end cross-check, not asserted (shared-host "
                "noise floor exceeds the 1% bound)"
            ),
            "bitwise_identical": bitwise,
            "samples": samples,
        }))

        # -- claim 3: kill -> dump -> merge -> postmortem --------------------
        bf.shutdown()
        dump_dir = tempfile.mkdtemp(prefix="bf_flight_")
        os.environ["BLUEFOG_FLIGHT_DIR"] = dump_dir
        os.environ["BLUEFOG_TIMELINE"] = os.path.join(dump_dir, "trace_")
        os.environ["BLUEFOG_FLIGHT"] = "1"
        bf.init(devices=devices[:n])
        bf.set_topology(topo.ExponentialTwoGraph(n))
        kill_rank = n // 2
        session = bf.elastic.start(policy="average")
        session.inject("kill", rank=kill_rank, step=kill_step)
        opt = bf.DistributedAdaptThenCombineOptimizer(optax.sgd(0.05))
        guard = bf.elastic.guard(opt)
        params = {"w": bf.worker_values(
            lambda r: rng.randn(dim).astype(np.float32)
        )}
        state = opt.init(params)
        for _t in range(pm_steps):
            params, state = guard.step(
                params, state,
                {"w": bf.worker_values(np.zeros(dim, np.float32))},
            )
        bf.flight_dump()
        bf.elastic.stop()
        bf.shutdown()  # closes the env-owned timeline -> valid JSON

        from tools.trace_merge import merge_and_analyze

        merged, report = merge_and_analyze(dump_dir)
        merged_valid = isinstance(
            json.loads(json.dumps(merged))["traceEvents"], list
        )
        # independent ground truth: compile the same topology again
        base_plan = plan_from_topology(topo.ExponentialTwoGraph(n))
        pre_kill = [
            s for s in report["per_step_rounds"] if s["step"] < kill_step
        ]
        rounds_match = bool(pre_kill) and all(
            s["rounds"] == len(base_plan.rounds) for s in pre_kill
        )
        pm = report["hang_postmortem"] or {}
        waiters = pm.get("waiters", [])
        rounds_by_edge = {}
        for ri, rnd in enumerate(base_plan.rounds):
            for s, d in rnd.perm:
                rounds_by_edge.setdefault((s, d), ri)
        expected_waiters = sorted(
            d for (s, d) in rounds_by_edge if s == kill_rank
        )
        postmortem_ok = (
            pm.get("dead_ranks") == [kill_rank]
            and sorted(w["rank"] for w in waiters) == expected_waiters
            and all(
                w["waiting_on"] == kill_rank
                and rounds_by_edge.get((kill_rank, w["rank"]))
                == w["round"]
                for w in waiters
            )
            # the DEAD verdict itself must have gone to disk (the
            # automatic trigger, not just the explicit end-of-run dump)
            and any(
                str(r).startswith("verdict:dead")
                for r in pm.get("dump_reasons", [])
            )
        )
        print(json.dumps({
            "metric": "flight_trace_merge",
            "n_workers": n,
            "merged_events": len(merged["traceEvents"]),
            "merged_valid_json": merged_valid,
            "plan_rounds_compiled": len(base_plan.rounds),
            "plan_rounds_reported": report["plan_rounds"],
            "per_step_rounds_match_plan": rounds_match,
            "steps_analyzed": len(report["steps"]),
        }))
        print(json.dumps({
            "metric": "flight_postmortem",
            "kill_rank": kill_rank,
            "kill_step": kill_step,
            "dead_ranks_reported": pm.get("dead_ranks"),
            "waiters": waiters,
            "expected_waiters": expected_waiters,
            "last_completed_step": pm.get("last_completed_step"),
            "dump_reasons": pm.get("dump_reasons"),
            "named_correctly": postmortem_ok,
        }))
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        bf_flight.reconfigure()

    if os.environ.get("BENCH_ASSERT", "1") == "1":
        assert bitwise, (
            "enabling the flight recorder changed the training state"
        )
        assert overhead_pct <= 1.0, (
            f"flight recorder overhead {overhead_pct:.3f}% exceeds the "
            "1% acceptance bound"
        )
        assert merged_valid and rounds_match, (
            "merged trace invalid or round counts diverge from the "
            "compiled CommPlan"
        )
        assert postmortem_ok, (
            f"postmortem failed to name the killed rank/edges: {pm}"
        )
    return 0


def run_attribution() -> int:
    """Attribution-doctor evidence (``BENCH_MODE=attribution``,
    committed as ATTRIBUTION_EVIDENCE.json). Four claims, measured the
    way each is resolvable (the BENCH_MODE=metrics noise-floor lessons
    apply unchanged):

    1. **Structural pin**: the doctor never changes the training
       program — enabling it adds no compiled-train-step cache entry
       (its probe programs live under their own ``doctor_probe`` keys),
       so every unsampled step dispatches the doctor-off program under
       the doctor-off cache key by construction.
    2. **Bitwise trajectory pin**: doctor on vs off, fresh state both
       ways, identical training state to the bit.
    3. **Overhead <= 1 % at the default interval**: the doctor's
       per-sample cost (settle + per-round probes + anchor) is measured
       directly by sampling EVERY step (interval 1) against a
       doctor-off stepper in a step-level rotation (all orderings), and
       amortized over the default interval; an off/off A/A control runs
       the identical protocol as the disclosed noise floor.
    4. **Degraded-link localization**: a fault-plan ``degrade`` on one
       directed edge (the PR-4 chaos layer's deterministic wire
       simulation); the doctor's per-round probes + per-edge drill-down
       must emit a ``degraded_link`` advisory naming exactly the
       injected edge — from timings alone.
    """
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_ATTR_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import itertools
    import time as time_mod

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import attribution
    from bluefog_tpu.collective import compiler

    devices = jax.devices()
    n = min(len(devices), int(os.environ.get("BENCH_ATTR_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_ATTR_DIM", "256"))
    layers = int(os.environ.get("BENCH_ATTR_LAYERS", "6"))
    batch = int(os.environ.get("BENCH_ATTR_BATCH", "16"))
    samples = max(18, int(os.environ.get("BENCH_ATTR_SAMPLES", "60")))

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_DOCTOR", "BLUEFOG_DOCTOR_INTERVAL",
                  "BLUEFOG_DOCTOR_FILE", "BLUEFOG_METRICS")
    }
    os.environ.pop("BLUEFOG_DOCTOR", None)
    # the evidence claims the DEFAULT interval: an ambient override
    # would silently re-scope the committed overhead amortization
    os.environ.pop("BLUEFOG_DOCTOR_INTERVAL", None)
    os.environ.pop("BLUEFOG_DOCTOR_FILE", None)
    os.environ.pop("BLUEFOG_METRICS", None)
    default_interval = attribution.doctor_interval()

    bf.init(devices=devices[:n])
    bf.set_topology(topo.ExponentialTwoGraph(n))
    # calibrate ONCE up front: the doctor's lazy first-sample probe
    # must not land inside a timed window
    compiler.calibrate()

    rng = np.random.RandomState(0)
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    xs = bf.worker_values(lambda r: rng.randn(batch, dim).astype(np.float32))
    ys = bf.worker_values(lambda r: rng.randn(batch, dim).astype(np.float32))

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    def make_stepper():
        opt = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )
        train_step = bf.make_train_step(opt, loss_fn)
        params = {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }
        carry = [(params, opt.init(params))]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs, ys)
            carry[0] = (p, s)
            return loss

        return _step, carry

    try:
        ctx = bf.get_context()

        # -- claim 1: structural — no train-step cache entry changes ---------
        attribution.stop()
        stepper, _carry = make_stepper()
        stepper()
        stepper()
        def train_keys():
            return {
                k for k in ctx.op_cache
                if isinstance(k, tuple) and k
                and k[0] in ("opt_step", "opt_fused_step")
            }
        keys_off = train_keys()
        doc = attribution.start(interval=1)
        stepper()
        stepper()
        keys_on = train_keys()
        probe_keys = [
            k for k in ctx.op_cache
            if isinstance(k, tuple) and k and k[0] == "doctor_probe"
        ]
        unsampled_shared = keys_on == keys_off
        attribution.stop()

        # -- claim 2: bitwise trajectory pin ---------------------------------
        state_bits = {}
        for variant in ("off", "on"):
            if variant == "on":
                attribution.start(interval=3)
            else:
                attribution.stop()
            _step, carry = make_stepper()
            for _ in range(12):
                _step()
            state_bits[variant] = jax.tree_util.tree_leaves(carry[0])
        attribution.stop()
        bitwise = all(
            bool(np.array_equal(np.asarray(a), np.asarray(b)))
            for a, b in zip(state_bits["off"], state_bits["on"])
        )

        # -- claim 3: overhead at the default interval -----------------------
        steppers = {}
        doc_on = attribution.StepDoctor(interval=1)
        for variant in ("off", "on", "off2"):
            attribution.activate(doc_on if variant == "on" else None)
            steppers[variant], _ = make_stepper()
            steppers[variant]()  # compile (+ probe compile for "on")
            _settle(steppers[variant]())
        orders = list(itertools.permutations(("off", "on", "off2")))
        times = {v: [] for v in steppers}
        for i in range(samples):
            for variant in orders[i % len(orders)]:
                attribution.activate(
                    doc_on if variant == "on" else None
                )
                t0 = time_mod.perf_counter()
                _settle(steppers[variant]())
                times[variant].append(time_mod.perf_counter() - t0)
        attribution.activate(None)

        def median(v):
            v = sorted(v)
            return v[len(v) // 2] if v else 0.0

        base_s = median(times["off"])
        sample_extra_s = median(
            [on - off for off, on in zip(times["off"], times["on"])]
        )
        control_extra_s = median(
            [o2 - off for off, o2 in zip(times["off"], times["off2"])]
        )
        overhead_pct = (
            100.0 * sample_extra_s / default_interval / base_s
            if base_s > 0 else 0.0
        )
        control_pct = (
            100.0 * control_extra_s / default_interval / base_s
            if base_s > 0 else 0.0
        )

        # one representative decomposition sample from the on-doctor
        decomp = {}
        for s in reversed(doc_on.samples):
            if "step_ms" in s and "comm_wire_ms" in s:
                decomp = {
                    "step_ms": s["step_ms"],
                    "comm_wire_ms": s["comm_wire_ms"],
                    "compute_ms": s.get("compute_ms"),
                    "dispatch_ms": s.get("dispatch_ms"),
                    "exposed_comm_frac": s.get("exposed_comm_frac"),
                    "rounds": len(s.get("rounds", [])),
                }
                break

        print(json.dumps({
            "metric": "attribution_overhead",
            "n_workers": n,
            "payload_mb": round(layers * dim * dim * 4 / 1e6, 2),
            "interval": default_interval,
            "ms_per_step_off": round(base_s * 1e3, 3),
            "ms_sampled_step_extra": round(sample_extra_s * 1e3, 3),
            "overhead_pct": round(overhead_pct, 3),
            "control_aa_pct": round(control_pct, 3),
            "unsampled_program_shared": unsampled_shared,
            "doctor_probe_programs": len(probe_keys),
            "bitwise_identical": bitwise,
            "samples": samples,
        }))
        print(json.dumps({
            "metric": "attribution_sample", **decomp,
        }))

        # -- claim 4: degraded-link localization -----------------------------
        bf.shutdown()
        bf.init(devices=devices[:n])
        bf.set_topology(topo.ExponentialTwoGraph(n))
        compiler.calibrate()
        # Exp2 edges are rank -> rank+2^k: degrade the single directed
        # edge (kill_src, kill_dst) and make the doctor find it
        kill_src = int(os.environ.get("BENCH_ATTR_DEGRADE_RANK", "2"))
        kill_dst = (kill_src + 4) % n
        session = bf.elastic.start(policy="average")
        session.inject(
            "degrade", rank=kill_src, step=0, factor=0.05, peer=kill_dst
        )
        doc = attribution.start(interval=2)
        opt = bf.DistributedAdaptThenCombineOptimizer(optax.sgd(0.05))
        guard = bf.elastic.guard(opt)
        params = {"w": bf.worker_values(
            lambda r: rng.randn(4096).astype(np.float32)
        )}
        state = opt.init(params)
        zeros = {"w": bf.worker_values(np.zeros(4096, np.float32))}
        for _t in range(6):
            params, state = guard.step(params, state, zeros)
        linked = [
            a.to_json() for a in doc.advisories
            if a.kind == "degraded_link"
        ]
        named = sorted({tuple(a["edge"]) for a in linked})
        named_correctly = (kill_src, kill_dst) in named
        print(json.dumps({
            "metric": "attribution_degraded_link",
            "injected_edge": [kill_src, kill_dst],
            "degrade_factor": 0.05,
            "advisories": linked[:4],
            "edges_named": [list(e) for e in named],
            "named_correctly": named_correctly,
        }))
        attribution.stop()
        bf.elastic.stop()
    finally:
        attribution.activate(None)
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert unsampled_shared, (
            "enabling the doctor changed the compiled train-step "
            "cache entries"
        )
        assert bitwise, (
            "enabling the doctor changed the training state bitwise"
        )
        assert overhead_pct <= 1.0, (
            f"doctor overhead {overhead_pct:.3f}% exceeds the 1% "
            f"acceptance bound at interval {default_interval}"
        )
        assert named_correctly, (
            f"degraded_link advisory failed to name the injected edge "
            f"({kill_src}, {kill_dst}): named {named}"
        )
    return 0


def run_health() -> int:
    """Fleet-health-plane evidence (``BENCH_MODE=health``, committed as
    HEALTH_EVIDENCE.json). Four claims, each measured the way it is
    resolvable (the metrics/attribution noise-floor lessons apply):

    1. **Decay tracks the spectrum**: a pure consensus problem is
       gossiped through the REAL eager combine on ring and Exp2; the
       observatory's fitted per-step decay must land within the
       disclosed tolerance of the SLEM prediction on both, and the
       Exp2-mixes-faster-than-ring ordering must hold (the paper's
       whole premise, now a machine-checked artifact).
    2. **Overhead <= 1 % at the default interval**: the health plane's
       per-sample cost (host fits + the push-sum lane dispatch) is
       measured by sampling EVERY step against a health-off stepper in
       a step-level rotation (all orderings) and amortized over the
       default interval; an off/off A/A control discloses the noise
       floor. Structural pin: enabling health adds no train-step cache
       entry (lane programs live under ``health_pushsum`` keys);
       bitwise pin: health on/off training state identical to the bit.
    3. **In-band aggregation is correct**: the device push-sum lane on
       a weighted digraph with one dead rank vs the numpy oracle.
    4. **Degraded-link chaos**: a lossy link (5 % delivery on one
       directed ring edge, replayed deterministically) measurably slows
       mixing below the spectral promise; ``mixing_degraded`` must fire
       and its suspect join must name the injected edge.
    """
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_HEALTH_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import itertools
    import time as time_mod

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import health
    from bluefog_tpu import metrics as bf_metrics

    devices = jax.devices()
    n = min(len(devices), int(os.environ.get("BENCH_HEALTH_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_HEALTH_DIM", "256"))
    layers = int(os.environ.get("BENCH_HEALTH_LAYERS", "6"))
    batch = int(os.environ.get("BENCH_HEALTH_BATCH", "16"))
    samples = max(18, int(os.environ.get("BENCH_HEALTH_SAMPLES", "60")))
    decay_steps = int(os.environ.get("BENCH_HEALTH_DECAY_STEPS", "40"))
    tolerance = 0.15  # |ln(measured)/ln(predicted) - 1| bound, disclosed

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_HEALTH", "BLUEFOG_HEALTH_INTERVAL",
                  "BLUEFOG_HEALTH_PORT", "BLUEFOG_HEALTH_FILE",
                  "BLUEFOG_HEALTH_ROUNDS", "BLUEFOG_METRICS",
                  "BLUEFOG_DOCTOR")
    }
    for k in old_env:
        os.environ.pop(k, None)
    default_interval = health.health_interval()

    bf.init(devices=devices[:n])
    ctx = bf.get_context()
    rng = np.random.RandomState(0)

    # -- claim 1: measured decay vs the spectral prediction ------------------
    decay_lines = {}
    for name, graph in (
        ("ring", topo.RingGraph(n)),
        ("exp2", topo.ExponentialTwoGraph(n)),
    ):
        bf.set_topology(graph)
        w = topo.mixing_matrix(graph)
        predicted = topo.consensus_decay_rate(w)
        plane = health.start(interval=1)
        x = bf.worker_values(
            lambda r: rng.randn(4096).astype(np.float32)
        )
        last = None
        d0 = None
        for t in range(decay_steps):
            x = bf.neighbor_allreduce(x)  # the real eager combine
            xs = np.asarray(x, np.float64)
            d = float(
                np.sqrt(((xs - xs.mean(0)) ** 2).sum(1)).mean()
            )
            d0 = d if d0 is None else d0
            if d < d0 * 1e-4:
                # the f32 combine's rounding floor is ~1e-6 of the
                # payload scale: feeding the plateau to the fit would
                # measure the noise floor, not the mixing rate
                break
            last = plane.observe(ctx, step=t, consensus=d)
        eff = last.get("mixing_efficiency")
        line = {
            "metric": "health_decay",
            "topology": name,
            "n_workers": n,
            "predicted_rate": round(predicted, 6),
            "measured_rate": last.get("measured_rate"),
            "mixing_efficiency": eff,
            "rate_ratio": eff,
            "tolerance": tolerance,
            "within_tolerance": (
                eff is not None and abs(eff - 1.0) <= tolerance
            ),
            "time_to_eps_steps": last.get("time_to_eps_steps"),
            "eps": last.get("eps"),
            "steps": decay_steps,
        }
        decay_lines[name] = line
        print(json.dumps(line))
        health.stop()
    exp2_faster = (
        decay_lines["exp2"]["measured_rate"] is not None
        and decay_lines["ring"]["measured_rate"] is not None
        and decay_lines["exp2"]["measured_rate"]
        < decay_lines["ring"]["measured_rate"]
    )
    print(json.dumps({
        "metric": "health_decay_ordering",
        "exp2_mixes_faster_than_ring": exp2_faster,
        "ring_measured": decay_lines["ring"]["measured_rate"],
        "exp2_measured": decay_lines["exp2"]["measured_rate"],
    }))

    # -- claim 3: in-band push-sum lane vs the numpy oracle ------------------
    bf.set_topology(topo.ExponentialTwoGraph(n))
    w = topo.mixing_matrix(bf.load_topology())
    vals = rng.rand(n, len(health.FLEET_FIELDS)) * 10.0
    dead = [n - 2] if n > 2 else []
    dev = health.fleet_aggregate(ctx, vals, rounds=12, w=w, dead=dead)
    ora = health.fleet_aggregate_np(w, vals, rounds=12, dead=dead)
    live = [j for j in range(n) if j not in dead]
    true_mean = vals[live].mean(axis=0)
    lane_err = float(np.max(np.abs(
        np.array(dev["mean"]) - np.array(ora["mean"])
    )))
    minmax_exact = bool(
        np.allclose(dev["min"], vals[live].min(axis=0))
        and np.allclose(dev["max"], vals[live].max(axis=0))
    )
    mean_err = float(np.max(np.abs(
        (np.array(dev["mean"]) - true_mean)
        / np.maximum(np.abs(true_mean), 1e-12)
    )))
    print(json.dumps({
        "metric": "health_fleet",
        "n_workers": n,
        "dead_ranks": dead,
        "rounds": 12,
        "lane_vs_oracle_max_err": lane_err,
        "minmax_exact_over_live": minmax_exact,
        "mean_rel_err_vs_true": round(mean_err, 6),
        "fleet_residual": dev["residual"],
    }))
    lane_ok = lane_err < 1e-3 and minmax_exact and mean_err < 0.05

    # -- claim 2: overhead / structural / bitwise pins -----------------------
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    xs_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )
    ys_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    def make_stepper():
        opt = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )
        train_step = bf.make_train_step(opt, loss_fn)
        params = {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }
        carry = [(params, opt.init(params))]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs_b, ys_b)
            carry[0] = (p, s)
            return loss

        return _step, carry

    # structural pin: enabling health adds no train-step cache entry
    health.stop()
    stepper, _carry = make_stepper()
    stepper()
    stepper()

    def train_keys():
        return {
            k for k in ctx.op_cache
            if isinstance(k, tuple) and k
            and k[0] in ("opt_step", "opt_fused_step")
        }

    keys_off = train_keys()
    health.start(interval=1)
    stepper()
    stepper()
    keys_on = train_keys()
    lane_keys = [
        k for k in ctx.op_cache
        if isinstance(k, tuple) and k and k[0] == "health_pushsum"
    ]
    unsampled_shared = keys_on == keys_off
    health.stop()

    # bitwise trajectory pin
    state_bits = {}
    for variant in ("off", "on"):
        if variant == "on":
            health.start(interval=3)
        else:
            health.stop()
        _step, carry = make_stepper()
        for _ in range(12):
            _step()
        state_bits[variant] = jax.tree_util.tree_leaves(carry[0])
    health.stop()
    bitwise = all(
        bool(np.array_equal(np.asarray(a), np.asarray(b)))
        for a, b in zip(state_bits["off"], state_bits["on"])
    )

    # overhead at the default interval, all-orderings rotation + A/A
    steppers = {}
    plane_on = health.HealthPlane(interval=1)
    for variant in ("off", "on", "off2"):
        health.activate(plane_on if variant == "on" else None)
        steppers[variant], _ = make_stepper()
        steppers[variant]()  # compile (+ lane compile for "on")
        _settle(steppers[variant]())
    orders = list(itertools.permutations(("off", "on", "off2")))
    times = {v: [] for v in steppers}
    for i in range(samples):
        for variant in orders[i % len(orders)]:
            health.activate(plane_on if variant == "on" else None)
            t0 = time_mod.perf_counter()
            _settle(steppers[variant]())
            times[variant].append(time_mod.perf_counter() - t0)
    health.activate(None)

    def median(v):
        v = sorted(v)
        return v[len(v) // 2] if v else 0.0

    base_s = median(times["off"])
    sample_extra_s = median(
        [on - off for off, on in zip(times["off"], times["on"])]
    )
    control_extra_s = median(
        [o2 - off for off, o2 in zip(times["off"], times["off2"])]
    )
    overhead_pct = (
        100.0 * sample_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    control_pct = (
        100.0 * control_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    print(json.dumps({
        "metric": "health_overhead",
        "n_workers": n,
        "payload_mb": round(layers * dim * dim * 4 / 1e6, 2),
        "interval": default_interval,
        "ms_per_step_off": round(base_s * 1e3, 3),
        "ms_sampled_step_extra": round(sample_extra_s * 1e3, 3),
        "overhead_pct": round(overhead_pct, 3),
        "control_aa_pct": round(control_pct, 3),
        "unsampled_program_shared": unsampled_shared,
        "health_lane_programs": len(lane_keys),
        "bitwise_identical": bitwise,
        "samples": samples,
    }))

    # -- claim 4: lossy link slows mixing; mixing_degraded names it ----------
    bf.shutdown()
    bf.init(devices=devices[:n])
    ctx = bf.get_context()
    ring = topo.RingGraph(n)
    bf.set_topology(ring)
    w = topo.mixing_matrix(ring)
    kill_src = int(os.environ.get("BENCH_HEALTH_DEGRADE_RANK", "2"))
    kill_dst = (kill_src + 1) % n
    factor = 0.05
    session = bf.elastic.start(policy="average")
    session.inject(
        "degrade", rank=kill_src, step=0, factor=factor, peer=kill_dst
    )
    plane = health.start(interval=1)
    x = rng.randn(n, 64)
    healthy_steps = 30
    for t in range(healthy_steps + 60):
        y = w.T @ x
        if t >= healthy_steps:
            # deterministic lossy-link replay: only `factor` of the
            # transfer on the injected edge arrives; the receiver keeps
            # its own value for the dropped fraction (the chaos-layer
            # model a real flaky ICI link reduces to)
            y[kill_dst] += (1.0 - factor) * w[kill_src, kill_dst] * (
                x[kill_dst] - x[kill_src]
            )
        x = y
        d = float(np.sqrt(((x - x.mean(0)) ** 2).sum(1)).mean())
        plane.observe(ctx, step=t, consensus=d)
    mix_advs = [
        a.to_json() for a in plane.advisories
        if a.kind == "mixing_degraded"
    ]
    named = sorted({
        tuple(e) for a in mix_advs
        for e in a.get("suspect_edges", []) if isinstance(e, list)
    })
    named_correctly = (kill_src, kill_dst) in named
    healthy_eff = None
    degraded_eff = None
    for s in plane.samples:
        if s.get("mixing_efficiency") is None:
            continue
        if s["step"] < healthy_steps:
            healthy_eff = s["mixing_efficiency"]
        else:
            degraded_eff = s["mixing_efficiency"]
    print(json.dumps({
        "metric": "health_mixing_degraded",
        "injected_edge": [kill_src, kill_dst],
        "degrade_factor": factor,
        "healthy_efficiency": healthy_eff,
        "degraded_efficiency": degraded_eff,
        "advisories": mix_advs[:3],
        "edges_named": [list(e) for e in named],
        "named_correctly": named_correctly,
    }))
    health.stop()
    bf.elastic.stop()

    bf_metrics.flush()
    for k, v in old_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        for name, line in decay_lines.items():
            assert line["within_tolerance"], (
                f"{name}: measured decay "
                f"{line['measured_rate']} outside the {tolerance} "
                f"tolerance of the spectral prediction "
                f"{line['predicted_rate']}"
            )
        assert exp2_faster, (
            "Exp2 did not measure faster mixing than ring: "
            f"{decay_lines}"
        )
        assert lane_ok, "push-sum lane diverged from the numpy oracle"
        assert unsampled_shared, (
            "enabling the health plane changed the compiled "
            "train-step cache entries"
        )
        assert bitwise, (
            "enabling the health plane changed the training state "
            "bitwise"
        )
        assert overhead_pct <= 1.0, (
            f"health overhead {overhead_pct:.3f}% exceeds the 1% "
            f"acceptance bound at interval {default_interval}"
        )
        assert named_correctly, (
            f"mixing_degraded failed to name the injected edge "
            f"({kill_src}, {kill_dst}): named {named}"
        )
    return 0


def run_slo() -> int:
    """Fleet-SLO-engine evidence (``BENCH_MODE=slo``, committed as
    SLO_EVIDENCE.json). Five claims, each measured the way it is
    resolvable (the metrics/health noise-floor lessons apply):

    1. **Pages within the documented bound, zero false alarms**: a
       hard fault (availability to zero) must raise ``slo_fast_burn``
       within ``page_sample_bound`` sampled evaluations of onset, and
       a 600-sample clean A/A series must raise nothing.
    2. **The slow window catches ramps the hygiene never trips on**: a
       slowly densifying error pattern (spacing 40 -> 8 samples over
       600) keeps the fast window silent AND never arms the doctor's
       EWMA+MAD two-streak rule on the rolling success fraction — the
       baseline adapts, by design — yet ``slo_slow_burn`` fires
       against the fixed target.
    3. **The canary flips on a lossy link and names the edge**: the
       512-element known-signal probe through the REAL quantized wire
       is bit-clean (vs the wire-exact numpy replay) on a healthy
       fabric and flags exactly the chaos-degraded edge when one is
       injected.
    4. **Overhead <= 1 % at the default interval**: sampled-step cost
       (resolver reads + canary dispatch) measured by an all-orderings
       step-level rotation with an off/off A/A noise-floor control.
       Structural pin: enabling SLO adds no train-step cache entry
       (canary programs live under ``slo_canary`` keys); bitwise pin:
       slo on/off training state identical to the bit.
    5. **Burn math matches the numpy oracle at fleet scale**: a 10 %
       churn storm on an N=1024 ``bf.fleetsim`` fleet drives a
       participation objective; the engine's fast/slow burn and budget
       accounting must match a from-scratch numpy recomputation
       exactly at EVERY step, and the storm must page within the
       documented bound.
    """
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_SLO_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import itertools
    import time as time_mod

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu import fleetsim
    from bluefog_tpu import slo
    from bluefog_tpu import metrics as bf_metrics
    from bluefog_tpu.attribution import BaselineTracker
    from bluefog_tpu.collective.plan import plan_from_topology

    devices = jax.devices()
    n = min(len(devices), int(os.environ.get("BENCH_SLO_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_SLO_DIM", "256"))
    layers = int(os.environ.get("BENCH_SLO_LAYERS", "6"))
    batch = int(os.environ.get("BENCH_SLO_BATCH", "16"))
    samples = max(18, int(os.environ.get("BENCH_SLO_SAMPLES", "60")))

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_SLO", "BLUEFOG_SLO_INTERVAL",
                  "BLUEFOG_SLO_FILE", "BLUEFOG_SLO_CANARY",
                  "BLUEFOG_METRICS", "BLUEFOG_HEALTH",
                  "BLUEFOG_DOCTOR")
    }
    for k in old_env:
        os.environ.pop(k, None)
    default_interval = slo.slo_interval()

    def probe_objective(**kw):
        base = dict(
            name="probe_avail", series="bench.synthetic", target=0.99,
            comparison="ge", window=240, budget_frac=0.05,
            fast_window=5, fast_burn=8.0, slow_window=60,
            slow_burn=2.0,
        )
        base.update(kw)
        return slo.Objective(**base)

    # -- claim 1: fault pages within the bound; A/A zero false alarms --------
    obj = probe_objective()
    bound = slo.page_sample_bound(
        obj.fast_window, obj.fast_burn, obj.budget_frac
    )
    eng = slo.SLOEngine(interval=1, objectives=[obj], canary=False)
    for t in range(obj.window):
        eng.observe(None, step=t, values={"probe_avail": 1.0})
    warmup_alerts = len(eng.alerts)
    onset = obj.window
    fired_at = None
    for t in range(onset, onset + 20):
        eng.observe(None, step=t, values={"probe_avail": 0.0})
        if any(a.kind == "slo_fast_burn" for a in eng.alerts):
            fired_at = t
            break
    samples_to_page = (
        fired_at - onset + 1 if fired_at is not None else None
    )
    eng_aa = slo.SLOEngine(
        interval=1, objectives=[probe_objective()], canary=False
    )
    aa_steps = 600
    for t in range(aa_steps):
        eng_aa.observe(None, step=t, values={"probe_avail": 1.0})
    print(json.dumps({
        "metric": "slo_page_bound",
        "fast_window": obj.fast_window,
        "fast_burn_threshold": obj.fast_burn,
        "budget_frac": obj.budget_frac,
        "page_sample_bound": bound,
        "samples_to_page": samples_to_page,
        "paged_within_bound": (
            samples_to_page is not None and samples_to_page <= bound
        ),
        "warmup_false_alarms": warmup_alerts,
        "aa_steps": aa_steps,
        "aa_false_alarms": len(eng_aa.alerts),
    }))
    page_ok = (
        samples_to_page is not None and samples_to_page <= bound
        and warmup_alerts == 0 and not eng_aa.alerts
    )

    # -- claim 2: slow ramp caught; EWMA+MAD hygiene correctly silent --------
    obj_b = probe_objective(name="ramp_avail")
    eng_b = slo.SLOEngine(interval=1, objectives=[obj_b], canary=False)
    tracker = BaselineTracker()
    rolling: list = []
    last_bad = None
    max_z = 0.0
    streak = 0
    hygiene_armed = False
    warmup_steps = 60  # clean preamble: the baseline the ramp erodes
    ramp_steps = 600
    bad_count = 0
    for t in range(warmup_steps + ramp_steps):
        # error spacing densifies 40 -> 8 samples: a ramp, not a step
        r = max(0, t - warmup_steps)
        spacing = max(8, int(round(40 - 32 * r / (ramp_steps - 1))))
        bad = t >= warmup_steps and (
            last_bad is None or (t - last_bad) >= spacing
        )
        if bad:
            last_bad = t
            bad_count += 1
        eng_b.observe(
            None, step=t, values={"ramp_avail": 0.0 if bad else 1.0}
        )
        # the doctor's view: rolling success fraction through the
        # EWMA+MAD baseline with the two-consecutive-outlier streak
        # rule every PR-9 detector uses — it adapts to the ramp
        rolling.append(0.0 if bad else 1.0)
        del rolling[:-60]
        z = tracker.update(sum(rolling) / len(rolling))
        max_z = max(max_z, abs(z))
        streak = streak + 1 if abs(z) >= 3.0 else 0
        hygiene_armed = hygiene_armed or streak >= 2
    ramp_kinds = sorted({a.kind for a in eng_b.alerts})
    slow_caught = (
        "slo_slow_burn" in ramp_kinds
        and "slo_fast_burn" not in ramp_kinds
        and not hygiene_armed
    )
    print(json.dumps({
        "metric": "slo_slow_ramp",
        "ramp_steps": ramp_steps,
        "bad_samples": bad_count,
        "alert_kinds": ramp_kinds,
        "fast_window_silent": "slo_fast_burn" not in ramp_kinds,
        "slow_window_fired": "slo_slow_burn" in ramp_kinds,
        "hygiene_max_abs_z": round(max_z, 3),
        "hygiene_streak_armed": hygiene_armed,
    }))

    # -- claim 3: canary flips on a lossy link and names the edge ------------
    bf.init(devices=devices[:n])
    ctx = bf.get_context()
    wire = os.environ.get("BENCH_SLO_WIRE", "int8")
    plan = plan_from_topology(ctx.load_topology())
    eng_c = slo.SLOEngine(interval=1, objectives=[], canary=True)
    clean = eng_c.canary.probe(ctx, plan, wire)
    kill_src = int(os.environ.get("BENCH_SLO_DEGRADE_RANK", "2"))
    kill_dst = int(os.environ.get("BENCH_SLO_DEGRADE_PEER", "3"))
    session = bf.elastic.start(policy="average")
    session.inject(
        "degrade", rank=kill_src, step=0, factor=0.05, peer=kill_dst
    )
    eng_c._canary_probe(ctx, plan, wire, step=0)
    lossy = eng_c.canary.last
    named = sorted({(e[0], e[1]) for e in lossy["edges"]})
    canary_advs = [
        a.to_json() for a in eng_c.alerts
        if a.kind == "slo_canary_failed"
    ]
    bf.elastic.stop()
    canary_ok = (
        clean["ok"] and not lossy["ok"]
        and named == [(kill_src, kill_dst)] and bool(canary_advs)
    )
    print(json.dumps({
        "metric": "slo_canary",
        "wire": wire,
        "probe_elems": slo.CANARY_ELEMS,
        "rounds": clean["rounds"],
        "tolerance": slo.CANARY_TOL,
        "clean_ok": clean["ok"],
        "clean_max_dev": clean["max_dev"],
        "injected_edge": [kill_src, kill_dst],
        "lossy_ok": lossy["ok"],
        "lossy_max_dev": lossy["max_dev"],
        "edges_named": [list(e) for e in named],
        "named_correctly": named == [(kill_src, kill_dst)],
        "advisory_fired": bool(canary_advs),
    }))

    # -- claim 4: overhead / structural / bitwise pins -----------------------
    rng = np.random.RandomState(0)
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    xs_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )
    ys_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    def make_stepper():
        opt = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )
        train_step = bf.make_train_step(opt, loss_fn)
        params = {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }
        carry = [(params, opt.init(params))]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs_b, ys_b)
            carry[0] = (p, s)
            return loss

        return _step, carry

    # structural pin: enabling slo adds no train-step cache entry
    slo.activate(None)
    stepper, _carry = make_stepper()
    stepper()
    stepper()

    def train_keys():
        return {
            k for k in ctx.op_cache
            if isinstance(k, tuple) and k
            and k[0] in ("opt_step", "opt_fused_step")
        }

    keys_off = train_keys()
    slo.activate(slo.SLOEngine(interval=1, canary=True))
    stepper()
    stepper()
    keys_on = train_keys()
    canary_keys = [
        k for k in ctx.op_cache
        if isinstance(k, tuple) and k and k[0] == "slo_canary"
    ]
    unsampled_shared = keys_on == keys_off
    slo.activate(None)

    # bitwise trajectory pin
    state_bits = {}
    for variant in ("off", "on"):
        slo.activate(
            slo.SLOEngine(interval=3, canary=True)
            if variant == "on" else None
        )
        _step, carry = make_stepper()
        for _ in range(12):
            _step()
        state_bits[variant] = jax.tree_util.tree_leaves(carry[0])
    slo.activate(None)
    bitwise = all(
        bool(np.array_equal(np.asarray(a), np.asarray(b)))
        for a, b in zip(state_bits["off"], state_bits["on"])
    )

    # overhead at the default interval, all-orderings rotation + A/A
    steppers = {}
    eng_on = slo.SLOEngine(interval=1, canary=True)
    for variant in ("off", "on", "off2"):
        slo.activate(eng_on if variant == "on" else None)
        steppers[variant], _ = make_stepper()
        steppers[variant]()  # compile (+ canary compile for "on")
        _settle(steppers[variant]())
    orders = list(itertools.permutations(("off", "on", "off2")))
    times = {v: [] for v in steppers}
    for i in range(samples):
        for variant in orders[i % len(orders)]:
            slo.activate(eng_on if variant == "on" else None)
            t0 = time_mod.perf_counter()
            _settle(steppers[variant]())
            times[variant].append(time_mod.perf_counter() - t0)
    slo.activate(None)

    def median(v):
        v = sorted(v)
        return v[len(v) // 2] if v else 0.0

    base_s = median(times["off"])
    sample_extra_s = median(
        [on - off for off, on in zip(times["off"], times["on"])]
    )
    control_extra_s = median(
        [o2 - off for off, o2 in zip(times["off"], times["off2"])]
    )
    overhead_pct = (
        100.0 * sample_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    control_pct = (
        100.0 * control_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    print(json.dumps({
        "metric": "slo_overhead",
        "n_workers": n,
        "payload_mb": round(layers * dim * dim * 4 / 1e6, 2),
        "interval": default_interval,
        "ms_per_step_off": round(base_s * 1e3, 3),
        "ms_sampled_step_extra": round(sample_extra_s * 1e3, 3),
        "overhead_pct": round(overhead_pct, 3),
        "control_aa_pct": round(control_pct, 3),
        "unsampled_program_shared": unsampled_shared,
        "canary_programs": len(canary_keys),
        "bitwise_identical": bitwise,
        "samples": samples,
    }))
    bf.shutdown()

    # -- claim 5: N=1024 churn storm burn math vs the numpy oracle -----------
    nfleet = int(os.environ.get("BENCH_SLO_FLEET", "1024"))
    storm_step = 10
    storm = fleetsim.storm_plan(nfleet, 0.10, step=storm_step, seed=7)
    vf = fleetsim.VirtualFleet(
        nfleet, topology="exp2", policy="receiver", plan=storm,
        audit_edges=False, seed=0,
    )
    obj_e = probe_objective(
        name="participation", target=0.95, window=60, slow_window=30,
    )
    eng_e = slo.SLOEngine(interval=1, objectives=[obj_e], canary=False)
    flags_hist: list = []
    max_burn_err = 0.0
    max_budget_err = 0.0
    ticks = 40
    for t in range(ticks):
        vf.tick()
        frac = vf._live_count / nfleet
        eng_e.observe(None, step=t, values={"participation": frac})
        flags_hist.append(0 if frac >= obj_e.target else 1)
        snap = eng_e._state["participation"].snapshot()
        # from-scratch numpy oracle of the engine's burn/budget math
        for w, key in ((obj_e.fast_window, "burn_fast"),
                       (obj_e.slow_window, "burn_slow")):
            if len(flags_hist) < w:
                assert snap[key] is None
                continue
            bad = float(np.sum(np.asarray(flags_hist[-w:])))
            oracle = (bad / w) / obj_e.budget_frac
            max_burn_err = max(max_burn_err, abs(snap[key] - oracle))
        wnd = np.asarray(flags_hist[-obj_e.window:], dtype=np.float64)
        total = obj_e.budget_frac * obj_e.window
        spent = float(wnd.sum())
        oracle_remaining = max(0.0, total - spent)
        max_budget_err = max(
            max_budget_err,
            abs(snap["budget"]["remaining"] - oracle_remaining),
        )
    storm_page = next(
        (a for a in eng_e.alerts if a.kind == "slo_fast_burn"), None
    )
    storm_bound = slo.page_sample_bound(
        obj_e.fast_window, obj_e.fast_burn, obj_e.budget_frac
    )
    storm_paged_within = (
        storm_page is not None
        and storm_page.step - storm_step + 1 <= storm_bound
    )
    print(json.dumps({
        "metric": "slo_fleet_storm",
        "fleet_n": nfleet,
        "storm_step": storm_step,
        "storm_fraction": 0.10,
        "live_after": vf._live_count,
        "ticks": ticks,
        "max_burn_err_vs_oracle": max_burn_err,
        "max_budget_err_vs_oracle": max_budget_err,
        "page_step": (
            storm_page.step if storm_page is not None else None
        ),
        "page_sample_bound": storm_bound,
        "paged_within_bound": storm_paged_within,
        "exhausted": eng_e.exhausted_objectives(),
    }))

    # the shipped catalog, for the record next to the claims
    print(json.dumps({
        "metric": "slo_catalog",
        "default_interval": default_interval,
        "objectives": [
            o.to_json() for o in slo.default_objectives()
        ],
    }))

    bf_metrics.flush()
    for k, v in old_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert page_ok, (
            f"fault did not page within {bound} samples clean of "
            f"false alarms: paged in {samples_to_page}, warmup "
            f"{warmup_alerts}, A/A {len(eng_aa.alerts)}"
        )
        assert slow_caught, (
            "slow ramp separation failed: kinds "
            f"{ramp_kinds}, hygiene_armed {hygiene_armed}"
        )
        assert canary_ok, (
            f"canary failed: clean {clean}, lossy edges {named} vs "
            f"({kill_src}, {kill_dst})"
        )
        assert unsampled_shared, (
            "enabling the SLO engine changed the compiled train-step "
            "cache entries"
        )
        assert canary_keys, (
            "canary probe compiled no slo_canary program"
        )
        assert bitwise, (
            "enabling the SLO engine changed the training state "
            "bitwise"
        )
        assert overhead_pct <= 1.0, (
            f"slo overhead {overhead_pct:.3f}% exceeds the 1% "
            f"acceptance bound at interval {default_interval}"
        )
        assert max_burn_err == 0.0 and max_budget_err == 0.0, (
            "engine burn/budget math diverged from the numpy oracle: "
            f"burn {max_burn_err}, budget {max_budget_err}"
        )
        assert storm_paged_within, (
            f"N={nfleet} storm did not page within {storm_bound} "
            f"samples: {storm_page}"
        )
    return 0


def run_staleness() -> int:
    """Staleness-observatory evidence (``BENCH_MODE=staleness``,
    committed as STALENESS_EVIDENCE.json). Five claims, each measured
    the way it is resolvable (the metrics/health noise-floor lessons
    apply):

    1. **Sync age ≡ 0 (lane self-check)**: the two-program optimizer
       gossips the fresh iterate; every sampled per-edge delivered age
       must be exactly 0 with the lane's own provenance check green —
       plus the sidecar-accounting pin (``scaling.wire_payload_bytes``
       with ``lineage=True`` prices exactly LINEAGE_TAG_BYTES more).
    2. **Delayed age ≡ 1 + transition**: the fused ``delayed=True``
       path measures age 0 on the reseed step, 1 in steady state, and
       an observable age-0 transition at a mid-run topology swap.
    3. **Age-discounted mixing shrinks the health residual**: on a
       pure-consensus ``delayed=True`` run the raw efficiency reads
       ~0.6-0.7 (the zero-staleness SLEM overstates the promise); the
       stale-mixing companion-polynomial correction must land the
       adjusted efficiency strictly closer to 1.0.
    4. **Overhead <= 1 % at the default interval**: sampled-step extra
       cost measured by an all-orderings off/on/off rotation,
       amortized over the default interval, A/A control disclosed;
       structural pin (no new train-step cache entries; the lane lives
       under ``staleness_lane`` keys) and bitwise on/off trajectory
       pin.
    5. **Per-edge stall chaos**: an injected ``stall`` with
       ``steps=``/``peer=`` must produce exactly the expected measured
       age ramp on the injected edge (and ONLY that edge), and the
       ``staleness_breach`` advisory must name it.
    """
    from bluefog_tpu.platforms import ensure_cpu_device_count

    ensure_cpu_device_count(
        int(os.environ.get("BENCH_STALENESS_DEVICES", "8"))
    )
    import itertools
    import time as time_mod

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    jax.config.update("jax_platforms", "cpu")

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import health, scaling, staleness
    from bluefog_tpu import metrics as bf_metrics

    devices = jax.devices()
    n = min(len(devices),
            int(os.environ.get("BENCH_STALENESS_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_STALENESS_DIM", "256"))
    layers = int(os.environ.get("BENCH_STALENESS_LAYERS", "6"))
    batch = int(os.environ.get("BENCH_STALENESS_BATCH", "16"))
    samples = max(18, int(os.environ.get("BENCH_STALENESS_SAMPLES",
                                         "60")))

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_STALENESS", "BLUEFOG_STALENESS_INTERVAL",
                  "BLUEFOG_STALENESS_BOUND", "BLUEFOG_STALENESS_FILE",
                  "BLUEFOG_METRICS", "BLUEFOG_HEALTH", "BLUEFOG_DOCTOR")
    }
    for k in old_env:
        os.environ.pop(k, None)
    default_interval = staleness.staleness_interval()

    bf.init(devices=devices[:n])
    ctx = bf.get_context()
    rng = np.random.RandomState(0)

    # -- claim 1: synchronous path age ≡ 0, sidecar priced --------------------
    bf.set_topology(topo.RingGraph(n))
    obs = staleness.start(interval=1)
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.01))
    params = {"w": bf.worker_values(
        lambda r: rng.randn(4096).astype(np.float32)
    )}
    state = opt.init(params)
    grads = {"w": bf.worker_values(
        lambda r: np.zeros(4096, np.float32)
    )}
    sync_steps = 12
    for _ in range(sync_steps):
        params, state = opt.step(params, state, grads)
    sync_samples = list(obs.samples)
    ages_all_zero = all(
        s["age_max"] == 0.0 and s["lane_ok"] for s in sync_samples
    )
    sidecar_delta = (
        scaling.wire_payload_bytes(4096, 4, None, lineage=True)
        - scaling.wire_payload_bytes(4096, 4, None)
    )
    lane_bytes = bf_metrics.peek("bluefog.staleness.wire_bytes")
    print(json.dumps({
        "metric": "staleness_sync",
        "n_workers": n,
        "steps": sync_steps,
        "edges_per_sample": sync_samples[0]["edges"],
        "ages_all_zero": ages_all_zero,
        "lane_selfcheck_ok": all(s["lane_ok"] for s in sync_samples),
        "lineage_tag_bytes": scaling.LINEAGE_TAG_BYTES,
        "sidecar_delta_bytes": sidecar_delta,
        "sidecar_priced_in_wire_payload_bytes": (
            sidecar_delta == scaling.LINEAGE_TAG_BYTES
        ),
        "lane_wire_bytes_total": (
            lane_bytes.value if lane_bytes is not None else 0
        ),
    }))
    staleness.stop()

    # -- claim 2: delayed ≡ 1 steady state + swap transition ------------------
    def consensus_loss(p, x):
        return ((p["w"] - x) ** 2).mean()

    opt_d = bf.DistributedAdaptThenCombineOptimizer(optax.sgd(0.0))
    ts = opt_d.make_train_step(consensus_loss, delayed=True)
    p_d = {"w": bf.worker_values(
        lambda r: np.random.RandomState(r).randn(2048)
        .astype(np.float32)
    )}
    s_d = opt_d.init(p_d)
    x_d = bf.worker_values(lambda r: np.zeros(2048, np.float32))
    obs = staleness.start(interval=1)
    pre_swap = 8
    for _ in range(pre_swap):
        p_d, s_d, _loss = ts(p_d, s_d, x_d)
    bf.set_topology(topo.ExponentialTwoGraph(n))
    for _ in range(6):
        p_d, s_d, _loss = ts(p_d, s_d, x_d)
    age_seq = [s["age_mean"] for s in obs.samples]
    steady_pre = age_seq[1:pre_swap]
    post = age_seq[pre_swap:]
    delayed_line = {
        "metric": "staleness_delayed",
        "n_workers": n,
        "age_sequence": age_seq,
        "seed_age_zero": age_seq[0] == 0.0,
        "steady_state_age_one": (
            bool(steady_pre) and all(a == 1.0 for a in steady_pre)
        ),
        "swap_transition_age_zero": bool(post) and post[0] == 0.0,
        "post_swap_steady_one": all(a == 1.0 for a in post[1:]),
    }
    print(json.dumps(delayed_line))
    staleness.stop()

    # -- claim 3: age-discounted mixing shrinks the health residual ----------
    bf.set_topology(topo.RingGraph(n))
    opt_r = bf.DistributedAdaptThenCombineOptimizer(optax.sgd(0.0))
    ts_r = opt_r.make_train_step(consensus_loss, delayed=True)
    p_r = {"w": bf.worker_values(
        lambda r: np.random.RandomState(100 + r).randn(2048)
        .astype(np.float32)
    )}
    s_r = opt_r.init(p_r)
    obs = staleness.start(interval=1)
    plane = health.HealthPlane(interval=1)  # driven directly, not installed
    last = None
    for t in range(40):
        p_r, s_r, _loss = ts_r(p_r, s_r, x_d)
        w = np.asarray(p_r["w"], np.float64)
        d = float(np.sqrt(((w - w.mean(0)) ** 2).sum(1)).mean())
        last = plane.observe(ctx, step=t, consensus=d)
    eff = last.get("mixing_efficiency")
    eff_adj = last.get("mixing_efficiency_age_adjusted")
    residual_raw = abs(eff - 1.0) if eff is not None else None
    residual_adj = abs(eff_adj - 1.0) if eff_adj is not None else None
    print(json.dumps({
        "metric": "staleness_residual",
        "n_workers": n,
        "predicted_rate": last.get("predicted_rate"),
        "age_adjusted_rate": last.get("age_adjusted_rate"),
        "measured_rate": last.get("measured_rate"),
        "age_mean": last.get("age_mean"),
        "mixing_efficiency": eff,
        "mixing_efficiency_age_adjusted": eff_adj,
        "residual_raw": residual_raw,
        "residual_age_adjusted": residual_adj,
        "residual_shrinks": (
            residual_raw is not None and residual_adj is not None
            and residual_adj < residual_raw
        ),
    }))
    staleness.stop()

    # -- claim 4: overhead / structural / bitwise pins -----------------------
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    xs_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )
    ys_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    def make_stepper():
        opt_s = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )
        train_step = bf.make_train_step(opt_s, loss_fn)
        params_s = {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }
        carry = [(params_s, opt_s.init(params_s))]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs_b, ys_b)
            carry[0] = (p, s)
            return loss

        return _step, carry

    # structural pin: enabling staleness adds no train-step cache entry
    staleness.stop()
    stepper, _carry = make_stepper()
    stepper()
    stepper()

    def train_keys():
        return {
            k for k in ctx.op_cache
            if isinstance(k, tuple) and k
            and k[0] in ("opt_step", "opt_fused_step")
        }

    keys_off = train_keys()
    staleness.start(interval=1)
    stepper()
    stepper()
    keys_on = train_keys()
    lane_keys = [
        k for k in ctx.op_cache
        if isinstance(k, tuple) and k and k[0] == "staleness_lane"
    ]
    unsampled_shared = keys_on == keys_off
    staleness.stop()

    # bitwise trajectory pin
    state_bits = {}
    for variant in ("off", "on"):
        if variant == "on":
            staleness.start(interval=3)
        else:
            staleness.stop()
        _step, carry = make_stepper()
        for _ in range(12):
            _step()
        state_bits[variant] = jax.tree_util.tree_leaves(carry[0])
    staleness.stop()
    bitwise = all(
        bool(np.array_equal(np.asarray(a), np.asarray(b)))
        for a, b in zip(state_bits["off"], state_bits["on"])
    )

    # overhead at the default interval, all-orderings rotation + A/A
    steppers = {}
    obs_on = staleness.StalenessObservatory(interval=1)
    for variant in ("off", "on", "off2"):
        staleness.activate(obs_on if variant == "on" else None)
        steppers[variant], _ = make_stepper()
        steppers[variant]()  # compile (+ lane compile for "on")
        _settle(steppers[variant]())
    orders = list(itertools.permutations(("off", "on", "off2")))
    times = {v: [] for v in steppers}
    for i in range(samples):
        for variant in orders[i % len(orders)]:
            staleness.activate(obs_on if variant == "on" else None)
            t0 = time_mod.perf_counter()
            _settle(steppers[variant]())
            times[variant].append(time_mod.perf_counter() - t0)
    staleness.activate(None)

    def median(v):
        v = sorted(v)
        return v[len(v) // 2] if v else 0.0

    base_s = median(times["off"])
    sample_extra_s = median(
        [on - off for off, on in zip(times["off"], times["on"])]
    )
    control_extra_s = median(
        [o2 - off for off, o2 in zip(times["off"], times["off2"])]
    )
    overhead_pct = (
        100.0 * sample_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    control_pct = (
        100.0 * control_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    print(json.dumps({
        "metric": "staleness_overhead",
        "n_workers": n,
        "payload_mb": round(layers * dim * dim * 4 / 1e6, 2),
        "interval": default_interval,
        "ms_per_step_off": round(base_s * 1e3, 3),
        "ms_sampled_step_extra": round(sample_extra_s * 1e3, 3),
        "overhead_pct": round(overhead_pct, 3),
        "control_aa_pct": round(control_pct, 3),
        "unsampled_program_shared": unsampled_shared,
        "staleness_lane_programs": len(lane_keys),
        "bitwise_identical": bitwise,
        "samples": samples,
    }))

    # -- claim 5: per-edge stall chaos → age spike + breach naming -----------
    bf.shutdown()
    bf.init(devices=devices[:n])
    ctx = bf.get_context()
    bf.set_topology(topo.RingGraph(n))
    stall_src = int(os.environ.get("BENCH_STALENESS_STALL_RANK", "2"))
    stall_dst = (stall_src + 1) % n
    hold_steps = 6
    stall_at = 4
    session = bf.elastic.start(policy="average")
    session.inject("stall", rank=stall_src, step=stall_at,
                   steps=hold_steps, peer=stall_dst)
    obs = staleness.start(interval=1)  # default bound 4 < spike of 6
    opt_c = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.01))
    guard = bf.elastic.guard(opt_c)
    p_c = {"w": bf.worker_values(
        lambda r: rng.randn(2048).astype(np.float32)
    )}
    s_c = opt_c.init(p_c)
    g_c = {"w": bf.worker_values(
        lambda r: np.zeros(2048, np.float32)
    )}
    for _ in range(stall_at + hold_steps + 4):
        p_c, s_c = guard.step(p_c, s_c, g_c)
    spike = [
        s["age_max"] for s in obs.samples
        if s.get("max_edge") == [stall_src, stall_dst]
    ]
    other_edges_clean = all(
        rec["max"] == 0.0
        for edge, rec in obs.report()["edge_ages"].items()
        if edge != f"{stall_src}->{stall_dst}"
    )
    breaches = [
        a.to_json() for a in obs.advisories
        if a.kind == "staleness_breach"
    ]
    named = sorted({
        tuple(e) for a in breaches for e in a.get("edges", [])
    })
    named_correctly = (
        named == [(stall_src, stall_dst)]
    )
    lane_ok_throughout = all(s["lane_ok"] for s in obs.samples)
    print(json.dumps({
        "metric": "staleness_chaos",
        "injected_edge": [stall_src, stall_dst],
        "hold_steps": hold_steps,
        "measured_spike_max": max(spike, default=0.0),
        "spike_matches_hold": max(spike, default=0.0) == hold_steps,
        "other_edges_age_zero": other_edges_clean,
        "bound": obs.bound,
        "breaches": breaches[:3],
        "edges_named": [list(e) for e in named],
        "named_correctly": named_correctly,
        "lane_selfcheck_ok": lane_ok_throughout,
    }))
    staleness.stop()
    bf.elastic.stop()

    bf_metrics.flush()
    for k, v in old_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert ages_all_zero, (
            "synchronous-path delivered age was not identically 0: "
            f"{sync_samples}"
        )
        assert sidecar_delta == scaling.LINEAGE_TAG_BYTES, (
            f"lineage sidecar mispriced: {sidecar_delta} != "
            f"{scaling.LINEAGE_TAG_BYTES}"
        )
        assert delayed_line["steady_state_age_one"], (
            f"delayed path steady-state age != 1: {age_seq}"
        )
        assert delayed_line["swap_transition_age_zero"], (
            f"topology-swap reseed transition not observed: {age_seq}"
        )
        assert residual_raw is not None and residual_adj is not None, (
            "health residual comparison incomplete: "
            f"raw={residual_raw} adj={residual_adj}"
        )
        assert residual_adj < residual_raw, (
            "age-discounted mixing did not shrink the residual: "
            f"raw={residual_raw} adj={residual_adj}"
        )
        assert unsampled_shared, (
            "enabling the staleness observatory changed the compiled "
            "train-step cache entries"
        )
        assert bitwise, (
            "enabling the staleness observatory changed the training "
            "state bitwise"
        )
        assert overhead_pct <= 1.0, (
            f"staleness overhead {overhead_pct:.3f}% exceeds the 1% "
            f"acceptance bound at interval {default_interval}"
        )
        assert max(spike, default=0.0) == hold_steps, (
            f"measured age spike {max(spike, default=0.0)} != injected "
            f"hold {hold_steps}"
        )
        assert other_edges_clean, "uninjected edges measured stale"
        assert named_correctly, (
            f"staleness_breach failed to name the injected edge "
            f"({stall_src}, {stall_dst}): named {named}"
        )
        assert lane_ok_throughout, "lane self-check failed under chaos"
    return 0


def run_autotune() -> int:
    """Closed-loop controller evidence (``BENCH_MODE=autotune``,
    committed as AUTOTUNE_EVIDENCE.json). Four claims, each measured
    the way it is resolvable (the metrics/health noise-floor lessons
    apply):

    1. **The loop closes on real telemetry** (``autotune_chaos``): a
       per-edge degrade fault slows the attribution doctor's probe
       dispatches deterministically; the ``degraded_link`` advisory
       names the edge from timings alone; the controller harvests it,
       searches, and migrates the LIVE guarded optimizer through the
       elastic repair path — the decision record names the edge in its
       trigger set, the installed matrix excludes (or down-weights)
       it, zero stale dispatches, and the doctor's own measured wire
       cost collapses back to the healthy level after the swap.
    2. **Mixing efficiency recovers** (``autotune_mixing_recovery``):
       the deterministic lossy-link consensus replay (the
       ``BENCH_MODE=health`` chaos model) degrades measured mixing
       below the spectral promise; ``mixing_degraded`` fires naming
       the edge; the controller routes around it and the measured
       efficiency (and the chaos-priced simulated step time, pinned
       calibration disclosed) recover past the gated thresholds. The
       same scenario re-run under ``dry_run`` records the full
       decision history with ZERO migrations (``autotune_dry_run``),
       and its audit trail round-trips through every surface —
       metrics, flight side table, JSONL,
       ``tools/autotune_report.py`` reconstruction, the health /fleet
       block (``autotune_audit``).
    3. **Overhead <= 1 % at the default interval**
       (``autotune_overhead``): controller-on (sampling every step,
       quiescent fabric) vs controller-off in a step-level all-
       orderings rotation, amortized over the default interval, with
       an off/off A/A control. Structural pin: enabling the
       controller adds no train-step cache entry; bitwise pin:
       controller-on/off training state identical to the bit (the
       controller never touches the dispatched program; only a
       migration bumps the topology version, and a quiescent fabric
       never migrates).
    """
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_AUTOTUNE_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import itertools
    import tempfile
    import time as time_mod

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import attribution
    from bluefog_tpu import autotune
    from bluefog_tpu import flight as flight_mod
    from bluefog_tpu import health
    from bluefog_tpu import metrics as bf_metrics
    from bluefog_tpu.collective import compiler

    devices = jax.devices()
    n = min(len(devices),
            int(os.environ.get("BENCH_AUTOTUNE_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_AUTOTUNE_DIM", "256"))
    layers = int(os.environ.get("BENCH_AUTOTUNE_LAYERS", "6"))
    batch = int(os.environ.get("BENCH_AUTOTUNE_BATCH", "16"))
    samples = max(18, int(os.environ.get("BENCH_AUTOTUNE_SAMPLES",
                                         "60")))

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_AUTOTUNE", "BLUEFOG_AUTOTUNE_INTERVAL",
                  "BLUEFOG_AUTOTUNE_FILE", "BLUEFOG_AUTOTUNE_DRY_RUN",
                  "BLUEFOG_AUTOTUNE_COOLDOWN", "BLUEFOG_AUTOTUNE_WIRE",
                  "BLUEFOG_DOCTOR", "BLUEFOG_HEALTH",
                  "BLUEFOG_METRICS")
    }
    for k in old_env:
        os.environ.pop(k, None)
    default_interval = autotune.autotune_interval()
    rng = np.random.RandomState(0)

    # -- claim 1: the loop closes on real doctor telemetry -------------------
    bf.init(devices=devices[:n])
    ctx = bf.get_context()
    bf.set_topology(topo.RingGraph(n))
    compiler.calibrate()
    kill_src = int(os.environ.get("BENCH_AUTOTUNE_DEGRADE_RANK", "2"))
    kill_dst = (kill_src + 1) % n
    factor = 0.05
    session = bf.elastic.start(policy="average")
    session.inject("degrade", rank=kill_src, step=0, factor=factor,
                   peer=kill_dst)
    # doctor at interval 1: every step probes, so an occasional
    # blame-free sample under ambient load cannot open a quiet gap
    # long enough to reset the controller's trigger streak
    doc = attribution.start(interval=1)
    # the controller is driven explicitly with a PINNED step clock for
    # its verification channel (an ambient-load spike on the shared
    # host would otherwise roll a good migration back — guardrail
    # working as designed, noise this evidence must not depend on);
    # the measured step-time recovery channel below is the doctor's
    # probe-measured wire cost, which IS wall clock
    tuner = autotune.TopologyAutotuner(interval=1, cooldown=8)
    opt = bf.DistributedAdaptThenCombineOptimizer(optax.sgd(0.05))
    guard = bf.elastic.guard(opt)
    params = {"w": bf.worker_values(
        lambda r: rng.randn(4096).astype(np.float32)
    )}
    state = opt.init(params)
    zeros = {"w": bf.worker_values(np.zeros(4096, np.float32))}
    w_before = topo.mixing_matrix(bf.load_topology()).copy()
    for _t in range(14):
        params, state = guard.step(params, state, zeros)
        tuner.observe(ctx, step=_t, optimizer=opt, step_s=0.01)
    named = sorted({
        tuple(a.detail["edge"]) for a in doc.advisories
        if a.kind == "degraded_link" and a.detail.get("edge")
    })
    detected = (kill_src, kill_dst) in named
    swap = next(
        (d for d in tuner.decisions if d.action == "swap"), None
    )
    trigger_names_edge = bool(swap) and any(
        t.get("edge") == [kill_src, kill_dst] for t in swap.triggers
    )
    w_after = topo.mixing_matrix(bf.load_topology())
    migrated_excludes = bool(
        w_after[kill_src, kill_dst] < w_before[kill_src, kill_dst]
    )
    wire_series = [
        s["comm_wire_ms"] for s in doc.samples
        if s.get("comm_wire_ms") is not None
    ]
    wire_degraded = max(wire_series[:2], default=0.0)
    wire_recovered = min(wire_series[-2:], default=0.0)
    wire_ratio = (
        wire_degraded / wire_recovered if wire_recovered > 0 else None
    )
    finite = bool(np.all(np.isfinite(np.asarray(params["w"]))))
    chaos_line = {
        "metric": "autotune_chaos",
        "n_workers": n,
        "injected_edge": [kill_src, kill_dst],
        "degrade_factor": factor,
        "detected_by_doctor": detected,
        "edges_named": [list(e) for e in named],
        "decision_action": swap.action if swap else None,
        "chosen": swap.chosen if swap else None,
        "trigger_names_edge": trigger_names_edge,
        "predicted_gain_frac": (
            swap.predicted.get("gain_frac") if swap else None
        ),
        "migrated_excludes_edge": migrated_excludes,
        "edge_weight_before": round(
            float(w_before[kill_src, kill_dst]), 6
        ),
        "edge_weight_after": round(
            float(w_after[kill_src, kill_dst]), 6
        ),
        "comm_wire_degraded_ms": round(wire_degraded, 4),
        "comm_wire_recovered_ms": round(wire_recovered, 4),
        "comm_wire_recovery_ratio": (
            round(wire_ratio, 2) if wire_ratio else None
        ),
        "stale_dispatches": session.stale_dispatches,
        "training_state_finite": finite,
    }
    print(json.dumps(chaos_line))
    autotune.stop()
    attribution.stop()
    bf.elastic.stop()
    bf.shutdown()

    # -- claim 2: mixing recovery + dry run + audit trail --------------------
    # Deterministic host replay of the lossy link (the BENCH_MODE=health
    # chaos model) with a PINNED calibration so the chaos-priced
    # simulated step times are identical run to run (disclosed: the
    # step-time channel here is the chaos pricing, not a wall clock —
    # claim 1 carries the measured-wall-clock recovery).
    compiler.set_calibration(1e-4, 1e9, source="pinned-sim")
    tmp_dir = tempfile.mkdtemp(prefix="bf_autotune_bench_")
    jsonl_path = os.path.join(tmp_dir, "autotune.jsonl")

    def run_sim(dry_run):
        bf.init(devices=devices[:n])
        ctx = bf.get_context()
        bf.set_topology(topo.RingGraph(n))
        session = bf.elastic.start(policy="average")
        healthy_steps = 30
        session.inject("degrade", rank=kill_src, step=healthy_steps,
                       factor=factor, peer=kill_dst)
        plane = health.start(interval=1)
        tuner = autotune.start(interval=1, cooldown=8,
                               dry_run=dry_run)
        v0 = ctx.topo_version
        x = rng.randn(n, 64)
        B = compiler.DEFAULT_PAYLOAD_BYTES
        last_v = ctx.topo_version
        sim_ms = []
        for t in range(130):
            session.before_dispatch(None)
            if ctx.topo_version != last_v:
                last_v = ctx.topo_version
                x = rng.randn(n, 64)  # fresh signal for the new
                # graph's decay fit (the old series hit the fp floor)
            w = topo.mixing_matrix(bf.load_topology())
            y = w.T @ x
            for key, f in session.simulated_wire_factors().items():
                if isinstance(key, tuple):
                    s, d = key
                    if w[s, d] != 0.0:
                        y[d] += (1.0 - f) * w[s, d] * (x[d] - x[s])
            x = y
            dist = float(np.sqrt(((x - x.mean(0)) ** 2).sum(1)).mean())
            plane.observe(ctx, step=t, consensus=dist)
            pen = sum(
                compiler.degraded_round_penalty_s(B, f)
                for key, f in
                session.simulated_wire_factors().items()
                if isinstance(key, tuple)
                and w[key[0], key[1]] != 0.0
            )
            sim_ms.append((0.010 + pen) * 1e3)
            tuner.observe(ctx, step=t, step_s=0.010 + pen)
        return ctx, plane, tuner, sim_ms, v0

    os.environ["BLUEFOG_AUTOTUNE_FILE"] = jsonl_path
    ctx, plane, tuner, sim_ms, _v0 = run_sim(dry_run=False)
    mix_advs = [
        a for a in plane.advisories if a.kind == "mixing_degraded"
    ]
    adv_named = sorted({
        tuple(e) for a in mix_advs
        for e in a.detail.get("suspect_edges", [])
        if isinstance(e, list)
    })
    swap2 = next(
        (d for d in tuner.decisions if d.action == "swap"), None
    )
    eff_degraded = (
        mix_advs[0].detail.get("mixing_efficiency") if mix_advs
        else None
    )
    eff_baseline = (
        mix_advs[0].detail.get("baseline_efficiency") if mix_advs
        else None
    )
    rec_effs = [
        s["mixing_efficiency"] for s in plane.samples
        if s.get("mixing_efficiency") is not None
        and swap2 is not None and s["step"] > swap2.step + 5
    ]
    eff_recovered = rec_effs[-1] if rec_effs else None
    w_final = topo.mixing_matrix(bf.load_topology())
    step_degraded_ms = max(sim_ms)
    step_recovered_ms = sim_ms[-1]
    recovery_line = {
        "metric": "autotune_mixing_recovery",
        "n_workers": n,
        "injected_edge": [kill_src, kill_dst],
        "degrade_factor": factor,
        "advisory_fired": bool(mix_advs),
        "advisory_names_edge": (kill_src, kill_dst) in adv_named,
        "decision_action": swap2.action if swap2 else None,
        "chosen": swap2.chosen if swap2 else None,
        "efficiency_baseline": eff_baseline,
        "efficiency_degraded": eff_degraded,
        "efficiency_recovered": eff_recovered,
        "sim_step_degraded_ms": round(step_degraded_ms, 3),
        "sim_step_recovered_ms": round(step_recovered_ms, 3),
        "recovered_step_ratio": round(
            step_degraded_ms / max(step_recovered_ms, 1e-9), 2
        ),
        "migrated_excludes_edge": bool(
            w_final[kill_src, kill_dst] == 0.0
        ),
        "calibration": "pinned (alpha=1e-4s, beta=1e9B/s) — the "
                       "simulated step-time channel is the chaos "
                       "pricing, disclosed",
    }
    print(json.dumps(recovery_line))

    # audit trail: every surface carries the decision
    snap = bf_metrics.snapshot()
    dump = flight_mod._build_dump("bench")
    from tools.autotune_report import build_report

    dump_path = os.path.join(tmp_dir, "autotune_dump.json")
    tuner.dump(dump_path)
    recon_dump = build_report([dump_path])
    recon_jsonl = build_report([jsonl_path])
    fleet_block = plane.report().get("autotune") or {}
    audit_line = {
        "metric": "autotune_audit",
        "decisions": len(tuner.decisions),
        "metrics_decisions": snap.get(
            "bluefog.autotune.decisions", {}
        ).get("value"),
        "flight_side_table_has_swap": any(
            d.get("action") == "swap"
            for d in dump.get("autotune_decisions", [])
        ),
        "jsonl_reconstruction_matches": (
            recon_jsonl["decisions"] == len(tuner.decisions)
        ),
        "dump_reconstruction_matches": (
            recon_dump["decisions"] == len(tuner.decisions)
        ),
        "report_joins_verification": any(
            h.get("verification") is not None
            for h in recon_dump["history"]
            if h.get("action") == "swap"
        ),
        "fleet_block": fleet_block,
    }
    print(json.dumps(audit_line))
    os.environ.pop("BLUEFOG_AUTOTUNE_FILE", None)
    autotune.stop()
    health.stop()
    bf.elastic.stop()
    bf.shutdown()

    # dry run: same condition, full history, zero migrations
    ctx, plane, tuner_dry, _sim, v0 = run_sim(dry_run=True)
    v_end = ctx.topo_version
    dry_line = {
        "metric": "autotune_dry_run",
        "decisions": len(tuner_dry.decisions),
        "actions": sorted({
            d.action for d in tuner_dry.decisions
        }),
        "swaps": tuner_dry.swaps,
        "migrations_zero": bool(
            tuner_dry.swaps == 0 and v_end == v0
        ),
        "topo_version_end": v_end,
        "candidates_recorded": bool(
            tuner_dry.decisions
            and tuner_dry.decisions[0].candidates
        ),
    }
    print(json.dumps(dry_line))
    autotune.stop()
    health.stop()
    bf.elastic.stop()
    bf.shutdown()
    compiler.clear_calibration()

    # -- claim 3: overhead / structural / bitwise pins -----------------------
    bf.init(devices=devices[:n])
    ctx = bf.get_context()
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    xs_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )
    ys_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    def make_stepper():
        opt = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )
        train_step = bf.make_train_step(opt, loss_fn)
        params = {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }
        carry = [(params, opt.init(params))]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs_b, ys_b)
            carry[0] = (p, s)
            return loss

        return _step, carry

    # structural pin: enabling the controller adds no cache entry at all
    autotune.stop()
    stepper, _carry = make_stepper()
    stepper()
    stepper()
    keys_off = set(ctx.op_cache)
    autotune.start(interval=1)
    stepper()
    stepper()
    keys_on = set(ctx.op_cache)
    unsampled_shared = keys_on == keys_off
    autotune.stop()

    # bitwise trajectory pin
    state_bits = {}
    for variant in ("off", "on"):
        if variant == "on":
            autotune.start(interval=3)
        else:
            autotune.stop()
        _step, carry = make_stepper()
        for _ in range(12):
            _step()
        state_bits[variant] = jax.tree_util.tree_leaves(carry[0])
    autotune.stop()
    bitwise = all(
        bool(np.array_equal(np.asarray(a), np.asarray(b)))
        for a, b in zip(state_bits["off"], state_bits["on"])
    )

    # overhead at the default interval, all-orderings rotation + A/A
    steppers = {}
    tuner_on = autotune.TopologyAutotuner(interval=1)
    for variant in ("off", "on", "off2"):
        autotune.activate(tuner_on if variant == "on" else None)
        steppers[variant], _ = make_stepper()
        steppers[variant]()
        _settle(steppers[variant]())
    orders = list(itertools.permutations(("off", "on", "off2")))
    times = {v: [] for v in steppers}
    for i in range(samples):
        for variant in orders[i % len(orders)]:
            autotune.activate(tuner_on if variant == "on" else None)
            t0 = time_mod.perf_counter()
            _settle(steppers[variant]())
            times[variant].append(time_mod.perf_counter() - t0)
    autotune.activate(None)

    def median(v):
        v = sorted(v)
        return v[len(v) // 2] if v else 0.0

    base_s = median(times["off"])
    sample_extra_s = median(
        [on - off for off, on in zip(times["off"], times["on"])]
    )
    control_extra_s = median(
        [o2 - off for off, o2 in zip(times["off"], times["off2"])]
    )
    overhead_pct = (
        100.0 * sample_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    control_pct = (
        100.0 * control_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    print(json.dumps({
        "metric": "autotune_overhead",
        "n_workers": n,
        "payload_mb": round(layers * dim * dim * 4 / 1e6, 2),
        "interval": default_interval,
        "ms_per_step_off": round(base_s * 1e3, 3),
        "ms_sampled_step_extra": round(sample_extra_s * 1e3, 3),
        "overhead_pct": round(overhead_pct, 3),
        "control_aa_pct": round(control_pct, 3),
        "unsampled_program_shared": unsampled_shared,
        "bitwise_identical": bitwise,
        "samples": samples,
    }))
    bf.shutdown()

    bf_metrics.flush()
    for k, v in old_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert detected, (
            f"doctor failed to name the injected edge "
            f"({kill_src}, {kill_dst}): named {named}"
        )
        assert swap is not None and trigger_names_edge, (
            f"no swap decision naming the injected edge: {chaos_line}"
        )
        assert migrated_excludes, (
            "migrated topology kept the blamed edge at full weight"
        )
        assert wire_ratio is not None and wire_ratio >= 2.0, (
            f"measured wire cost did not recover: {chaos_line}"
        )
        assert chaos_line["stale_dispatches"] == 0
        assert finite, "training state went non-finite across the swap"
        assert recovery_line["advisory_fired"] and \
            recovery_line["advisory_names_edge"], recovery_line
        assert recovery_line["migrated_excludes_edge"], recovery_line
        assert eff_recovered is not None and eff_recovered >= 0.9, (
            f"mixing efficiency did not recover: {recovery_line}"
        )
        assert recovery_line["recovered_step_ratio"] >= 2.0, (
            recovery_line
        )
        assert dry_line["migrations_zero"] and \
            dry_line["decisions"] >= 1, dry_line
        assert dry_line["actions"] == ["dry_run_swap"], dry_line
        assert audit_line["flight_side_table_has_swap"], audit_line
        assert audit_line["jsonl_reconstruction_matches"], audit_line
        assert audit_line["dump_reconstruction_matches"], audit_line
        assert audit_line["report_joins_verification"], audit_line
        assert unsampled_shared, (
            "enabling the controller changed the compiled cache entries"
        )
        assert bitwise, (
            "enabling the controller changed the training state bitwise"
        )
        assert overhead_pct <= 1.0, (
            f"autotune overhead {overhead_pct:.3f}% exceeds the 1% "
            f"acceptance bound at interval {default_interval}"
        )
    return 0


def run_async() -> int:
    """Asynchronous-gossip evidence (``BENCH_MODE=async``, committed as
    ASYNC_EVIDENCE.json): the straggler-immunity scenario synchronous
    gossip cannot reach, plus the correctness pins that make the async
    lane trustworthy. Five claims:

    1. **Straggler immunity** — one rank compute-dilated 10x (the
       ``slow`` chaos fault). Synchronous gossip's fleet throughput is
       gated by the slowest rank: every step costs
       ``max_r(dilation_r)`` local-step times, so the fleet runs at
       ~1/10 nominal. The async engine's measured participation ratio
       (real engine counters over the replayed cadence) stays within
       ~1/N of nominal: the slow rank costs only its own share. The
       tick clock is the virtual time base (a virtual CPU mesh has no
       physically slow chip — the dilation is the deterministic chaos
       replay, disclosed), while per-dispatch wall costs of both modes
       are measured for comparability.
    2. **Convergence** — the same quadratic consensus problem driven
       to convergence by both modes under the straggler; the async
       distance-to-optimum must land within tolerance of sync's.
    3. **Mass conservation** — random per-rank cadences x
       {fp32, int8_ef, int4_ef} wire tiers at lr=0: total push-sum x
       mass (window + pending buffers) and p mass pinned to f32
       rounding per tier (the sender absorbs its shipped quantization
       residual — exact by construction, not to quantization
       precision).
    4. **Bounded-staleness gate** — the 10x rank trips the
       ``BLUEFOG_ASYNC_MAX_AGE`` gate: delivered-age histogram, the
       ``async_staleness`` advisory naming the slow rank, and fresh
       edges staying at age <= cadence spread.
    5. **Async-off dispatch** — ``BLUEFOG_ASYNC=0`` returns the
       synchronous optimizer path, pinned bitwise over a multi-step
       trajectory.

    ``BENCH_ASSERT=1`` (default) enforces all bounds. See
    docs/async.md."""
    from bluefog_tpu.platforms import ensure_cpu_device_count

    ensure_cpu_device_count(
        int(os.environ.get("BENCH_ASYNC_DEVICES", "8"))
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import collections

    import numpy as np
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import windows as win_mod

    devices = jax.devices()
    n = min(len(devices), int(os.environ.get("BENCH_ASYNC_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_ASYNC_DIM", "4096"))
    dilation = float(os.environ.get("BENCH_ASYNC_DILATION", "10"))
    slow_rank = n - 2
    lr = 0.05
    rng = np.random.RandomState(0)
    z0 = rng.randn(n, dim).astype(np.float32)
    targets = z0 + rng.randn(n, dim).astype(np.float32)
    opt_point = targets.mean(axis=0)

    def loss_fn(p, target):
        return 0.5 * jnp.mean((p["w"] - target) ** 2)

    def median_ms(fn, reps=20):
        ts = []
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            ts.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(ts))

    lines = []

    # -- 1 + 2: straggler immunity + convergence ------------------------------
    # synchronous baseline (no chaos needed for the math: the collapse
    # is structural — each step is gated by the slowest participant)
    bf.init(devices=devices[:n])
    bf.set_topology(topo.RingGraph(n, connect_style=1))
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(lr))
    params = {"w": jnp.asarray(z0)}
    state = opt.init(params)
    sync_step = opt.make_train_step(loss_fn)
    batch = jnp.asarray(targets)
    params, state, _ = sync_step(params, state, batch)  # compile
    sync_steps = int(os.environ.get("BENCH_ASYNC_STEPS", "120"))
    t_sync_ms = median_ms(
        lambda: jax.block_until_ready(
            sync_step(params, state, batch)[0]["w"]
        )
    )
    for _ in range(sync_steps):
        params, state, _ = sync_step(params, state, batch)
    dist_sync = float(
        np.abs(np.asarray(params["w"]) - opt_point).max()
    )
    bf.shutdown()

    # asynchronous run under the 10x straggler
    bf.init(devices=devices[:n])
    bf.set_topology(topo.RingGraph(n, connect_style=1))
    session = bf.elastic.start(policy="push_sum")
    session.inject("slow", rank=slow_rank, step=0, factor=dilation)
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(lr))
    params = {"w": jnp.asarray(z0)}
    state = opt.init(params)
    async_step = bf.make_async_train_step(opt, loss_fn, max_age=4)
    eng = async_step.engine
    params, state, _ = async_step(params, state, batch)  # compile
    t_tick_ms = median_ms(
        lambda: jax.block_until_ready(
            async_step(params, state, batch)[0]["w"]
        )
    )
    ages_hist: collections.Counter = collections.Counter()
    # ages of edges NOT sourced at the dilated rank, tracked separately:
    # the "fresh edges stay within the bound" claim must be a real
    # measurement over the healthy edges, not a tautology over ages
    # already filtered to <= max_age
    healthy_hist: collections.Counter = collections.Counter()
    ticks = int(os.environ.get("BENCH_ASYNC_TICKS", "240"))
    while eng._tick < ticks:
        params, state, _ = async_step(params, state, batch)
        win = win_mod._get_win(bf.get_context(), eng._name)
        for r, srcs in enumerate(win.in_neighbors):
            for k, s in enumerate(srcs):
                a = int(win.clock - win.slot_written[r, k])
                ages_hist[a] += 1
                if s != slow_rank:
                    healthy_hist[a] += 1
    dist_async = float(
        np.abs(np.asarray(params["w"]) - opt_point).max()
    )
    # fleet throughput on the shared virtual time base (the tick = one
    # undilated local-step time): sync's per-step cost is gated by the
    # slowest rank; async's measured participation is the engine's own
    # counter over the deterministic cadence replay
    participation = eng._local_steps / (eng._tick * n)
    fleet_ratio_async = participation
    fleet_ratio_sync = 1.0 / max(dilation, 1.0)
    gate_advisory = eng.advisories[0] if eng.advisories else None
    lines.append({
        "metric": "async_straggler",
        "workers": n,
        "dim": dim,
        "slow_rank": slow_rank,
        "dilation": dilation,
        "ticks": eng._tick,
        "local_steps": eng._local_steps,
        "fleet_ratio_async": round(fleet_ratio_async, 4),
        "fleet_ratio_sync": round(fleet_ratio_sync, 4),
        "within_1_over_n": bool(
            fleet_ratio_async >= 1.0 - 1.5 / n
        ),
        "sync_collapse": bool(
            fleet_ratio_sync <= 1.5 / dilation
        ),
        "measured_sync_step_ms": round(t_sync_ms, 3),
        "measured_async_tick_ms": round(t_tick_ms, 3),
        "dilation_model": (
            "simulated: deterministic slow-fault cadence replay on the "
            "tick clock (virtual CPU mesh has no physically slow "
            "chip); per-dispatch wall costs measured above"
        ),
    })
    lines.append({
        "metric": "async_convergence",
        "steps_sync": sync_steps,
        "ticks_async": eng._tick,
        "dist_to_opt_sync": dist_sync,
        "dist_to_opt_async": dist_async,
        "tolerance_factor": 3.0,
        "within_tolerance": bool(
            dist_async <= 3.0 * dist_sync + 1e-3
        ),
    })
    # -- 4: the bounded-staleness gate ---------------------------------------
    # worst age over ALL edges not sourced at the slow rank — a real
    # measurement of "healthy edges never trip the gate"
    fresh_max = max(healthy_hist, default=0)
    lines.append({
        "metric": "async_staleness_gate",
        "max_age": eng.max_age,
        "policy": eng.policy,
        "age_hist": {
            str(a): int(c) for a, c in sorted(ages_hist.items())
        },
        "age_max": int(max(ages_hist)),
        "stale_drops": eng._stale_drops,
        "gate_engaged": bool(eng._stale_drops > 0),
        "advisory_present": gate_advisory is not None,
        "advisory_names_slow_rank": bool(
            gate_advisory is not None
            and slow_rank in gate_advisory.detail["slow_ranks"]
        ),
        "advisory_edges": (
            gate_advisory.detail["edges"] if gate_advisory else []
        ),
        "fresh_edges_within_bound": int(fresh_max),
    })
    gate = lines[-1]
    straggler = lines[0]
    conv = lines[1]
    bf.elastic.stop()
    bf.shutdown()

    # -- 3: mass conservation per wire tier ----------------------------------
    tiers = {}
    for tier in ("fp32", "int8_ef", "int4_ef"):
        bf.init(devices=devices[:n])
        bf.set_topology(topo.RingGraph(n, connect_style=1))
        trng = np.random.RandomState(5)
        cadence = {
            r: int(p) for r, p in enumerate(trng.randint(1, 5, n))
        }
        opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
        params = {"w": jnp.asarray(z0)}
        state = opt.init(params)
        step = bf.make_async_train_step(
            opt, loss_fn, cadence=cadence, wire=tier, max_age=10 ** 6
        )
        mass0 = float(np.sum(z0, dtype=np.float64))
        scale = float(np.abs(z0).sum())
        drift = p_drift = 0.0
        for _ in range(15):
            params, state, _ = step(params, state, batch)
            win = win_mod._get_win(bf.get_context(), step.engine._name)
            total = float(
                np.sum(np.asarray(win.value), dtype=np.float64)
            ) + float(np.sum(np.asarray(win.buffers), dtype=np.float64))
            ptotal = float(
                np.sum(np.asarray(win.p), dtype=np.float64)
            ) + float(
                np.sum(np.asarray(win.p_buffers), dtype=np.float64)
            )
            drift = max(drift, abs(total - mass0))
            p_drift = max(p_drift, abs(ptotal - n))
        tiers[tier] = {
            "mass_drift": drift,
            "p_drift": p_drift,
            "bound": 1e-5 * scale,
            "conserved": bool(
                drift < 1e-5 * scale and p_drift < 1e-5
            ),
        }
        bf.shutdown()
    lines.append({
        "metric": "async_mass",
        "dim": dim,
        "ticks": 15,
        "cadences": "random in [1, 4]",
        "tiers": tiers,
        "mass_drift_max": max(t["mass_drift"] for t in tiers.values()),
        "conserved_all_tiers": all(
            t["conserved"] for t in tiers.values()
        ),
    })
    mass = lines[-1]

    # -- 5: async-off dispatch is the synchronous path, bitwise --------------
    bf.init(devices=devices[:n])
    bf.set_topology(topo.RingGraph(n, connect_style=1))
    opt_a = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(lr))
    pa = {"w": jnp.asarray(z0)}
    sa = opt_a.init(pa)
    off_step = bf.make_async_train_step(opt_a, loss_fn, enabled=False)
    opt_b = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(lr))
    pb = {"w": jnp.asarray(z0)}
    sb = opt_b.init(pb)
    ref_step = opt_b.make_train_step(loss_fn)
    bitwise = True
    for _ in range(10):
        pa, sa, la = off_step(pa, sa, batch)
        pb, sb, lb = ref_step(pb, sb, batch)
        bitwise = bitwise and np.array_equal(
            np.asarray(pa["w"]), np.asarray(pb["w"])
        ) and np.array_equal(np.asarray(la), np.asarray(lb))
    lines.append({
        "metric": "async_off_bitwise",
        "steps": 10,
        "bitwise_identical": bool(bitwise),
        "dispatch_path_shared": not hasattr(off_step, "engine"),
    })
    off = lines[-1]
    bf.shutdown()

    for line in lines:
        print(json.dumps(line), flush=True)

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert straggler["within_1_over_n"], straggler
        assert straggler["sync_collapse"], straggler
        assert conv["within_tolerance"], conv
        assert mass["conserved_all_tiers"], mass
        assert gate["gate_engaged"], gate
        assert gate["advisory_names_slow_rank"], gate
        assert gate["age_max"] > gate["max_age"], gate
        assert off["bitwise_identical"], off
        assert off["dispatch_path_shared"], off
    return 0


def run_transformer() -> int:
    """TransformerLM train-step throughput: tokens/sec + MFU at long
    sequence over the Pallas flash kernels (fwd + custom-VJP bwd).

    The reference has no transformer or long-context tier (SURVEY §5);
    this number backs the beyond-reference attention stack with the same
    measured-claims discipline as the headline
    (reference docs/performance.rst:16-24)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from bluefog_tpu.models.transformer import TransformerLM

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    seq = int(os.environ.get("BENCH_SEQ", "4096" if on_tpu else "128"))
    batch = int(os.environ.get("BENCH_TLM_BATCH", "2" if on_tpu else "1"))
    dim = int(os.environ.get("BENCH_TLM_DIM", "1024" if on_tpu else "64"))
    heads = int(os.environ.get("BENCH_TLM_HEADS", "16" if on_tpu else "4"))
    layers = int(os.environ.get("BENCH_TLM_LAYERS", "12" if on_tpu else "2"))
    vocab = int(os.environ.get("BENCH_TLM_VOCAB", "16384" if on_tpu else "256"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "10" if on_tpu else "2")))
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "8" if on_tpu else "1")))

    remat = os.environ.get("BENCH_TLM_REMAT", "0") == "1"
    model = TransformerLM(
        vocab=vocab, dim=dim, heads=heads, layers=layers, max_len=seq,
        dtype=jnp.bfloat16, remat=remat,
    )
    rng_np = np.random.RandomState(0)
    tokens = jnp.asarray(
        rng_np.randint(0, vocab, (batch, seq)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    tx = optax.sgd(0.01, momentum=0.9)
    opt_state = tx.init(params)
    n_params = sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params)
    )

    @jax.jit
    def train_step(params, opt_state, tokens):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tokens[:, 1:]
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, new_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_state, loss

    carry = (params, opt_state)

    def step(tokens):
        nonlocal carry
        p, s, loss = train_step(carry[0], carry[1], tokens)
        carry = (p, s)
        return loss  # scalar: safe to settle through the tunnel

    dt = _timed_differenced(lambda: step(tokens), steps, windows)[0]
    tok_per_sec = batch * seq / dt
    # fwd FLOPs/token = 2*P (params matmuls) + 2*T*dim*L (causal QK^T+PV
    # at average context T/2, both 2*MAC); fwd+bwd = 3x fwd
    flops_token = 3 * (2 * n_params + 2 * seq * dim * layers)
    anchor = _ambient_anchor()
    result = {
        "metric": "transformer_lm_tokens_per_sec",
        "value": round(tok_per_sec, 1),
        "unit": "tokens/sec/chip",
        "vs_anchor": round(tok_per_sec / max(anchor["tflops"], 1e-9), 2),
        "anchor_tflops": anchor["tflops"],
        "seq_len": seq,
        "params_m": round(n_params / 1e6, 1),
        "dim": dim, "heads": heads, "layers": layers, "batch": batch,
        "attention": "pallas_flash", "remat": remat,
    }
    peak = _peak_flops(jax.devices()[0])
    if peak:
        result["mfu"] = round(tok_per_sec * flops_token / peak, 4)
        result["device"] = jax.devices()[0].device_kind
    print(json.dumps(result))
    return 0


def run_flash() -> int:
    """Flash-vs-dense attention timings: the measured basis for the
    flash-by-default decision (VERDICT r04 item 1). Emits one line per
    (shape, direction) with the speedup; on TPU asserts flash wins at
    long sequence so a kernel regression fails the bench."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from bluefog_tpu.ops.attention import reference_attention
    from bluefog_tpu.ops.flash import flash_attention

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "3" if on_tpu else "1")))
    seqs = [
        int(s) for s in os.environ.get(
            "BENCH_FLASH_SEQS", "1024,4096,8192" if on_tpu else "256"
        ).split(",")
    ]
    speedups = {}
    for h, d in ((16, 64), (8, 128)):
        for t in seqs:
            rng = np.random.RandomState(0)
            q, k, v = (
                jnp.asarray(rng.randn(1, t, h, d), jnp.bfloat16)
                for _ in range(3)
            )

            def mk(fn):
                # both timed programs return a SCALAR so the settle point
                # is a fixed cheap readback (settling a [T,H,D] output
                # through the tunnel would swamp the measurement)
                fwd = jax.jit(
                    lambda q, k, v: fn(q, k, v, causal=True)
                    .astype(jnp.float32).mean()
                )

                def loss(q, k, v):
                    return fn(q, k, v, causal=True).astype(
                        jnp.float32
                    ).mean()

                bwd = jax.jit(
                    lambda q, k, v: sum(
                        g.astype(jnp.float32).sum()
                        for g in jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
                    )
                )
                return fwd, bwd

            f_fwd, f_bwd = mk(flash_attention)
            r_fwd, r_bwd = mk(reference_attention)

            def measure(fn, cost_mult):
                # steps sized from the analytic FLOP count to ~1 s of
                # compute per window half (sub-second windows are pure
                # tunnel-RTT noise)
                flops = 2.0 * t * t * h * d * 1 * cost_mult  # causal ~half
                # floored: a sub-ms shape's per-call time is dominated
                # by dispatch (~50 us), not FLOPs — an unfloored
                # estimate requests absurd step counts and the window
                # measures dispatch noise, the r05 impossible-row root
                est = max(flops / 2.0e13, 5e-5)
                steps = max(8, min(4096, int(1.0 / est)))
                dts, degen = _timed_differenced(
                    lambda: fn(q, k, v), steps, windows,
                    with_degenerate=True,
                )
                return dts[0], degen

            def one_cell():
                (tf, d1), (tr, d2) = measure(f_fwd, 1), measure(r_fwd, 2)
                (tfb, d3), (trb, d4) = measure(f_bwd, 3), measure(r_bwd, 6)
                degenerate = d1 or d2 or d3 or d4
                cell = {
                    "metric": "flash_attention_vs_dense",
                    "seq_len": t, "heads": h, "head_dim": d,
                    "causal": True,
                    "flash_fwd_ms": round(tf * 1e3, 3),
                    "dense_fwd_ms": round(tr * 1e3, 3),
                    "fwd_speedup": round(tr / tf, 2),
                    "flash_fwdbwd_ms": round(tfb * 1e3, 3),
                    "dense_fwdbwd_ms": round(trb * 1e3, 3),
                    "fwdbwd_speedup": round(trb / tfb, 2),
                }
                if degenerate:
                    # every timing window stayed clamped even after
                    # retries: disclose instead of publishing a fake
                    # ~0 ms cell (and keep the cell out of the
                    # regression assertion below)
                    cell["degenerate"] = True
                return cell, degenerate, (tr / tf, trb / tfb)

            cell, degenerate, sp = one_cell()
            problems = bench_row_problems(cell)
            if problems:
                # an impossible row never ships as a measurement: one
                # full remeasure (transient stalls are the usual cause),
                # then reject the cell with its violations disclosed
                cell, degenerate, sp = one_cell()
                problems = bench_row_problems(cell)
                if problems:
                    cell["degenerate"] = True
                    cell["rejected"] = problems
                    degenerate = True
            if not degenerate:
                speedups[(h, d, t)] = sp
            print(json.dumps(cell))
    if on_tpu and os.environ.get("BENCH_ASSERT", "1") != "0":
        # stall-robust regression check: a single tunnel stall can distort
        # one cell, so require every long config to win in at least one
        # direction and at least one to win decisively in both (degenerate
        # cells never reach `speedups`)
        long_wins = [
            s for (h, d, t), s in speedups.items() if t >= 4096
        ]
        if long_wins:  # no long configs measured != a kernel regression
            assert all(
                max(fwd, bwd) > 1.0 for fwd, bwd in long_wins
            ) and any(
                fwd > 1.5 and bwd > 1.5 for fwd, bwd in long_wins
            ), f"flash lost to dense at long sequence: {speedups}"
    return 0


def run_quant() -> int:
    """Quantized-wire evidence (``BENCH_MODE=quant``, committed as
    QUANT_EVIDENCE.json): the full wire-tier family —
    fp32/bf16/int8/int8_ef/int4/int4_ef — run on the same pure-consensus
    problem (zero gradients isolate the wire's noise from optimizer
    bias), with per-tier wire bytes (scale sidecar priced in), the
    consensus-distance curve, and the metrics tier's quant-error
    telemetry. The headline claim this artifact gates (``BENCH_ASSERT``,
    default on): the int4 tiers ship >= 2x fewer wire bytes than int8,
    and ``int4_ef`` reaches consensus quality no worse than int8's
    (within the disclosed multi-seed A/A spread — error feedback erases
    the coarser quantizer's floor, so it typically lands ORDERS below).
    ``quant_kernel`` rows compare the fused wire kernels
    (``BLUEFOG_WIRE_KERNELS``) against the composite path — measured
    XLA scratch, step time, bitwise output equality — and gate the
    fused scratch BELOW the fp32 row for int8 AND int4 (the full-width
    temporary never materializes; docs/performance.md).
    A push-sum window run under ``BLUEFOG_WINDOW_WIRE=int4`` closes the
    artifact with the sender-mass-conservation check (drift bounded by
    f32 rounding, not quantization: the sender absorbs the residual of
    the mass it ships — docs/windows.md)."""
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_QUANT_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import metrics as bf_metrics
    from bluefog_tpu import scaling
    from bluefog_tpu import windows as win_mod
    from bluefog_tpu.collective.plan import plan_from_topology

    n = min(len(jax.devices()),
            int(os.environ.get("BENCH_QUANT_WORKERS", "8")))
    dim = int(os.environ.get("BENCH_QUANT_DIM", "4096"))
    steps = int(os.environ.get("BENCH_QUANT_STEPS", "200"))
    seeds = max(2, int(os.environ.get("BENCH_QUANT_SEEDS", "3")))
    curve_every = max(1, steps // 20)

    plan = plan_from_topology(topo.ExponentialTwoGraph(n), weighted=True)
    tiers = (None, "bf16", "int8", "int8_ef", "int4", "int4_ef")

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_METRICS", "BLUEFOG_METRICS_INTERVAL",
                  "BLUEFOG_METRICS_FILE", "BLUEFOG_METRICS_PROM",
                  "BLUEFOG_WINDOW_WIRE")
    }
    os.environ.pop("BLUEFOG_METRICS_FILE", None)
    os.environ.pop("BLUEFOG_METRICS_PROM", None)
    os.environ["BLUEFOG_METRICS"] = "1"
    os.environ["BLUEFOG_METRICS_INTERVAL"] = "1"

    def consensus_dist(w):
        return float(
            np.sqrt(((w - w.mean(0)) ** 2).sum(1)).mean()
        )

    finals = {}
    try:
        bf.init(devices=jax.devices()[:n])
        bf.set_topology(topo.ExponentialTwoGraph(n))
        for wire in tiers:
            name = wire or "fp32"
            curves = []
            quant_err = None
            for seed in range(seeds):
                bf_metrics.reset()
                c = (
                    np.random.RandomState(100 + seed)
                    .randn(n, dim).astype(np.float32) * 5.0
                )
                opt = bf.DistributedNeighborAllreduceOptimizer(
                    optax.sgd(0.0)
                )
                opt.compression = wire
                params = {"w": bf.worker_values(lambda r: c[r])}
                state = opt.init(params)
                zero = {"w": jnp.zeros((n, dim), jnp.float32)}
                curve = []
                for step in range(steps):
                    params, state = opt.step(params, state, zero)
                    if step == 0 and seed == 0 and wire not in (
                        None, "bf16",
                    ):
                        # first-step quant error: the EF tiers drive
                        # theirs to exactly 0 at consensus, so the
                        # meaningful sample is the full-magnitude one
                        bf_metrics.flush()
                        g = bf_metrics.snapshot().get(
                            "bluefog.gossip.quant_err"
                        )
                        quant_err = g["value"] if g else None
                    if step % curve_every == 0 or step == steps - 1:
                        curve.append(
                            round(consensus_dist(
                                np.asarray(params["w"])
                            ), 8)
                        )
                curves.append(curve)
            finals[name] = [cv[-1] for cv in curves]
            summary = scaling.plan_comm_summary(
                plan, dim * 4, wire=wire
            )
            line = {
                "metric": "quant_tier",
                "wire": name,
                "n_workers": n,
                "dim": dim,
                "steps": steps,
                "rounds": summary["rounds"],
                "wire_bytes_per_step": plan.wire_bytes(dim, 4, wire=wire),
                "effective_compression_ratio": summary[
                    "effective_compression_ratio"
                ],
                "final_consensus_median": float(
                    np.median(finals[name])
                ),
                "final_consensus_seeds": finals[name],
                "consensus_curve": curves[0],
            }
            if quant_err is not None:
                line["quant_err_rms"] = round(float(quant_err), 8)
            print(json.dumps(line), flush=True)
        bf.shutdown()

        # the disclosed A/A floor: the reference tier's own multi-seed
        # spread of final consensus distance (different random problems,
        # same config) — the resolution limit of "equal quality"
        int8_f = np.asarray(finals["int8"], np.float64)
        aa_noise_pct = float(
            100.0 * (int8_f.max() - int8_f.min())
            / max(int8_f.min(), 1e-30)
        )
        b_int8 = plan.wire_bytes(dim, 4, wire="int8")
        b_int4 = plan.wire_bytes(dim, 4, wire="int4")
        b_int4ef = plan.wire_bytes(dim, 4, wire="int4_ef")
        ratio = b_int8 / b_int4
        int8_med = float(np.median(finals["int8"]))
        int4ef_med = float(np.median(finals["int4_ef"]))
        equal_quality = int4ef_med <= int8_med * (
            1.0 + aa_noise_pct / 100.0
        )
        print(json.dumps({
            "metric": "quant_summary",
            "n_workers": n,
            "dim": dim,
            "wire_bytes_int8": b_int8,
            "wire_bytes_int4": b_int4,
            "wire_bytes_int4_ef": b_int4ef,
            "wire_reduction_int4_vs_int8": round(ratio, 4),
            "aa_noise_pct": round(aa_noise_pct, 3),
            "final_consensus_int8": int8_med,
            "final_consensus_int4_ef": int4ef_med,
            "int4_ef_no_worse_than_int8": bool(equal_quality),
        }), flush=True)

        # -- fused wire kernels: kernel-vs-composite ----------------------
        # (BLUEFOG_WIRE_KERNELS, collective/kernels.py): same combine,
        # compiled twice — composite (kernels pinned off, the
        # MEMORY_EVIDENCE before-baseline) vs fused — comparing the
        # measured XLA scratch, the step time, and bitwise equality of
        # the outputs. The headline gate: the fused path's scratch
        # lands BELOW the fp32 row (no full-width temporary exists),
        # for int8 AND int4.
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from bluefog_tpu.collective import inner
        from bluefog_tpu.collective import kernels as wire_kernels

        k_plan = plan_from_topology(topo.RingGraph(n))
        mesh = Mesh(np.array(jax.devices()[:n]), ("workers",))
        xk = jax.device_put(
            jnp.asarray(
                np.random.RandomState(7)
                .randn(n, dim).astype(np.float32) * 5.0
            ),
            NamedSharding(mesh, P("workers")),
        )

        def kernel_build(wire):
            if wire is None:
                body = lambda t: inner.neighbor_allreduce(
                    t, k_plan, "workers"
                )
            else:
                body = lambda t, w=wire: inner.weighted_combine_quantized(
                    t, k_plan, "workers", wire=w
                )
            fn = jax.jit(jax.shard_map(
                body, mesh=mesh, in_specs=P("workers"),
                out_specs=P("workers"),
            ))
            c = fn.lower(xk).compile()
            return fn, int(c.memory_analysis().temp_size_in_bytes)

        def kernel_time_us(fn, reps=30):
            jax.block_until_ready(fn(xk))  # warm
            t0 = time.perf_counter()
            for _ in range(reps):
                jax.block_until_ready(fn(xk))
            return 1e6 * (time.perf_counter() - t0) / reps

        old_wk = os.environ.get("BLUEFOG_WIRE_KERNELS")
        kernel_rows = []
        try:
            os.environ["BLUEFOG_WIRE_KERNELS"] = "0"
            _, fp32_temp = kernel_build(None)
            for wire in ("int8", "int4"):
                os.environ["BLUEFOG_WIRE_KERNELS"] = "0"
                fn_c, temp_c = kernel_build(wire)
                out_c = np.asarray(fn_c(xk))
                t_c = kernel_time_us(fn_c)
                os.environ["BLUEFOG_WIRE_KERNELS"] = "1"
                fn_f, temp_f = kernel_build(wire)
                out_f = np.asarray(fn_f(xk))
                t_f = kernel_time_us(fn_f)
                kernel_rows.append({
                    "metric": "quant_kernel",
                    "wire": wire,
                    "payload_elems": dim,
                    "kernels_native": wire_kernels.pallas_available()
                    and jax.default_backend() == "tpu",
                    "temp_bytes_composite": temp_c,
                    "temp_bytes_fused": temp_f,
                    "temp_bytes_fp32": fp32_temp,
                    "temp_bytes_analytic_fused": (
                        scaling.quantized_temporaries_bytes(
                            dim, wire, fused=True
                        )
                    ),
                    "temp_bytes_analytic_composite": (
                        scaling.quantized_temporaries_bytes(dim, wire)
                    ),
                    "fused_below_fp32_row": temp_f < fp32_temp,
                    "step_time_composite_us": round(t_c, 2),
                    "step_time_fused_us": round(t_f, 2),
                    "bitwise_equal": bool(
                        (out_c.view(np.uint32)
                         == out_f.view(np.uint32)).all()
                    ),
                })
                print(json.dumps(kernel_rows[-1]), flush=True)
        finally:
            if old_wk is None:
                os.environ.pop("BLUEFOG_WIRE_KERNELS", None)
            else:
                os.environ["BLUEFOG_WIRE_KERNELS"] = old_wk

        # push-sum mass conservation under the quantized window wire
        os.environ["BLUEFOG_WINDOW_WIRE"] = "int4"
        os.environ["BLUEFOG_METRICS"] = "0"
        bf.init(devices=jax.devices()[:n])
        bf.set_topology(topo.ExponentialTwoGraph(n))
        bf.turn_on_win_ops_with_associated_p()
        x0 = (
            np.random.RandomState(0).randn(n, dim).astype(np.float32) * 3
        )
        bf.win_create(
            bf.worker_values(lambda r: x0[r]), "quant_ps", zero_init=True
        )
        outs = bf.get_context().out_neighbor_ranks()
        dst = [
            {d: 1.0 / (len(outs[r]) + 1) for d in outs[r]}
            for r in range(n)
        ]
        sw = [1.0 / (len(outs[r]) + 1) for r in range(n)]
        total0 = x0.sum(0, dtype=np.float64)
        max_drift = 0.0
        ps_steps = int(os.environ.get("BENCH_QUANT_PS_STEPS", "25"))
        for _ in range(ps_steps):
            bf.win_accumulate(
                name="quant_ps", self_weight=sw, dst_weights=dst
            )
            bf.win_update_then_collect("quant_ps")
            v = np.asarray(bf.win_read("quant_ps"), np.float64)
            max_drift = max(
                max_drift, float(np.abs(v.sum(0) - total0).max())
            )
        p = win_mod.win_associated_p("quant_ps")
        est = np.asarray(bf.win_read("quant_ps")) / np.asarray(
            p
        )[:, None]
        # bound: f32 rounding of the running sums, NOT quantization
        # magnitude — per-element mass error accumulates as ~n_workers *
        # steps * ulp(sum) with the quantization residual absorbed
        mass_bound = float(
            ps_steps * n * float(np.abs(x0).max())
            * np.finfo(np.float32).eps * 64
        )
        mass_ok = max_drift < mass_bound
        print(json.dumps({
            "metric": "quant_window_mass",
            "wire": "int4",
            "wire_kernels_on": wire_kernels.wire_kernels_on(),
            "n_workers": n,
            "dim": dim,
            "ps_steps": ps_steps,
            "max_mass_drift": round(max_drift, 9),
            "mass_bound": round(mass_bound, 9),
            "mass_conserved": bool(mass_ok),
            "consensus_err": round(
                float(np.abs(est - x0.mean(0)).max()), 6
            ),
        }), flush=True)
        bf.shutdown()
    finally:
        for k, v in old_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert ratio >= 2.0, (
            f"int4 wire reduction vs int8 is {ratio:.3f}x, below the "
            "2x acceptance bound"
        )
        assert equal_quality, (
            f"int4_ef final consensus {int4ef_med:.3e} exceeds int8's "
            f"{int8_med:.3e} beyond the {aa_noise_pct:.2f}% A/A floor"
        )
        assert mass_ok, (
            f"push-sum mass drift {max_drift:.3e} exceeds the f32 "
            f"rounding bound {mass_bound:.3e} under the int4 window wire"
        )
        for row in kernel_rows:
            assert row["bitwise_equal"], (
                f"fused wire kernels changed the {row['wire']} combine "
                "bitwise — the same-bits contract is broken"
            )
            assert row["fused_below_fp32_row"], (
                f"fused {row['wire']} scratch "
                f"{row['temp_bytes_fused']} B is not below the fp32 "
                f"row's {row['temp_bytes_fp32']} B — the full-width "
                "temporary still materializes"
            )
    return 0


def run_shard() -> int:
    """Weight-update-sharding evidence (``BENCH_MODE=shard``, committed
    as SHARD_EVIDENCE.json). Five facts, BENCH_ASSERT-gated:

    1. *Memory*: on an 8-worker mesh, Adam state for a model whose
       REPLICATED per-rank footprint exceeds a simulated per-chip
       budget trains under ``BLUEFOG_SHARD=1`` with measured (real
       allocated arrays, not a model) per-rank state bytes at
       1/N + the disclosed 512-alignment slack.
    2. *Trajectory*: the sharded run matches the replicated run AND the
       numpy Adam oracle coordinate-for-coordinate (ulp envelope) —
       sharding is a memory layout, not an algorithm change. The ZeRO-2
       run (``BLUEFOG_SHARD_GRADS=1``, gradient leg lowered to
       reduce-scatter) sits inside the SAME envelope.
    3. *Step time*: sharded vs unsharded at the same model size stays
       within the disclosed A/A noise floor (the 1/N update saving and
       the all-gather cost trade against each other on CPU).
    4. *Off pin*: ``BLUEFOG_SHARD=0`` dispatches bitwise-identically
       with zero shard-tagged cache keys.
    5. *Gradient wire* (``shard_grad_wire``): the dispatched
       reduce-scatter delivers a measured per-rank reduced-gradient
       buffer at ~1/N of the allreduce's (pad slack disclosed);
       reduce-scatter + all-gather wire <= allreduce + all-gather; and
       the quantized scatter tiers price at the exact block-scale
       ratios (int8 = 516/2048, int4 = 258/2048 — slots are 512-grid
       multiples so the ratios are exact, not approximate).

    See docs/sharding.md."""
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(
            int(os.environ.get("BENCH_SHARD_DEVICES", "8"))
        )
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu import scaling, sharding

    devices = jax.devices()
    n = min(len(devices), int(os.environ.get("BENCH_SHARD_WORKERS", "8")))
    # odd on purpose: the 512-grid padding slack must be real, not zero
    dim = int(os.environ.get("BENCH_SHARD_DIM", "262145"))
    budget = int(os.environ.get("BENCH_SHARD_BUDGET", str(1 << 20)))
    steps = int(os.environ.get("BENCH_SHARD_STEPS", "24"))
    t_steps = int(os.environ.get("BENCH_SHARD_TIME_STEPS", "60"))
    lr = 0.02
    rng = np.random.RandomState(0)
    c = rng.randn(n, dim).astype(np.float32)
    c_mean = c.mean(axis=0)

    def session(shard, body, grads=False):
        os.environ["BLUEFOG_SHARD"] = "1" if shard else "0"
        if grads:
            os.environ["BLUEFOG_SHARD_GRADS"] = "1"
        bf.init(devices=devices[:n])
        try:
            return body()
        finally:
            bf.shutdown()
            os.environ.pop("BLUEFOG_SHARD", None)
            os.environ.pop("BLUEFOG_SHARD_GRADS", None)

    def make(shard_unused=None):
        opt = bf.DistributedGradientAllreduceOptimizer(optax.adam(lr))
        params = {"w": bf.worker_values(
            lambda r: np.zeros(dim, np.float32)
        )}
        state = opt.init(params)
        return opt, params, state

    def grads_of(params):
        return {"w": params["w"] - jnp.asarray(c)}

    import jax.numpy as jnp

    def loss_of(params):
        w = np.asarray(params["w"])
        return float(np.mean(0.5 * np.sum((w - c_mean) ** 2, -1)))

    lines = []

    # -- 1. memory + train-past-the-budget ------------------------------
    def mem_shard():
        opt, params, state = make()
        layout = opt._shard_layout
        measured = scaling.optimizer_state_bytes(state=state, world=n)
        analytic = scaling.optimizer_state_bytes(params, opt, shard=True)
        loss0 = loss_of(params)
        for _ in range(steps):
            params, state = opt.step(params, state, grads_of(params))
            # one multi-device program in flight at a time: overlapped
            # 8-participant rendezvous can starve each other on a
            # small host
            jax.block_until_ready(params)
        w = np.asarray(params["w"])
        return {
            "measured": measured, "analytic": analytic,
            "slot_elems": layout.groups[0].slot,
            "pad_ratio": round(
                layout.groups[0].padded / layout.groups[0].elems - 1.0, 6
            ),
            "gather_bytes": sharding.gather_wire_bytes(layout),
            "loss0": loss0, "loss1": loss_of({"w": w}),
            "replica_spread": float(np.abs(w - w[0]).max()),
        }

    def mem_repl():
        opt, params, state = make()
        return {
            "measured": scaling.optimizer_state_bytes(state=state,
                                                      world=n),
            "analytic": scaling.optimizer_state_bytes(params, opt,
                                                      shard=False),
        }

    sh = session(True, mem_shard)
    rp = session(False, mem_repl)
    shard_ratio = sh["measured"] / rp["measured"]
    # the 1/N claim with the alignment slack disclosed: the sharded
    # footprint is bounded by slot/dim of replicated (slot IS
    # ceil(dim/N) rounded to the 512 grid) plus scalar state overhead
    mem_bound = rp["measured"] * (sh["slot_elems"] / dim) * 1.02 + 4096
    lines.append({
        "metric": "shard_memory",
        "workers": n,
        "dim": dim,
        "optimizer": "adam",
        "budget_bytes": budget,
        "state_bytes_replicated": rp["measured"],
        "state_bytes_sharded": sh["measured"],
        "state_bytes_replicated_analytic": rp["analytic"],
        "state_bytes_sharded_analytic": sh["analytic"],
        "shard_ratio": round(shard_ratio, 6),
        "slot_elems": sh["slot_elems"],
        "pad_ratio": sh["pad_ratio"],
        "gather_bytes_per_step": sh["gather_bytes"],
        "replicated_exceeds_budget": rp["measured"] > budget,
        "sharded_fits_budget": sh["measured"] <= budget,
        "trained_steps": steps,
        "loss_start": sh["loss0"],
        "loss_end": sh["loss1"],
        "replica_spread": sh["replica_spread"],
    })

    # -- 2. trajectory: sharded == replicated == numpy Adam oracle ------
    traj_dim = int(os.environ.get("BENCH_SHARD_TRAJ_DIM", "4099"))
    ct = rng.randn(n, traj_dim).astype(np.float32)
    ct_mean = ct.mean(axis=0)

    def traj(shard):
        del shard
        opt = bf.DistributedGradientAllreduceOptimizer(optax.adam(lr))
        params = {"w": bf.worker_values(
            lambda r: np.zeros(traj_dim, np.float32)
        )}
        state = opt.init(params)
        for _ in range(8):
            params, state = opt.step(
                params, state, {"w": params["w"] - jnp.asarray(ct)}
            )
            jax.block_until_ready(params)
        return np.asarray(params["w"])[0]

    w_sh = session(True, lambda: traj(True))
    w_rp = session(False, lambda: traj(False))
    # ZeRO-2: the same trajectory with the gradient leg lowered to
    # reduce-scatter (BLUEFOG_SHARD_GRADS=1) — the scatter's fixed
    # reduction order must keep it inside the SAME pin envelope
    w_z2 = session(True, lambda: traj(True), grads=True)

    # numpy oracle: replicated gradient-allreduce Adam on the quadratic
    # (grad of 0.5||x - c_r||^2 allreduce-means to x - mean(c))
    b1, b2, eps = 0.9, 0.999, 1e-8
    x = np.zeros(traj_dim, np.float32)
    m = np.zeros(traj_dim, np.float32)
    v = np.zeros(traj_dim, np.float32)
    for t in range(1, 9):
        g = x - ct_mean
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        x = x - lr * (m / (1 - b1 ** t)) / (
            np.sqrt(v / (1 - b2 ** t)) + eps
        )
    traj_tol = 1e-5
    traj_max_dev = float(np.abs(w_sh - w_rp).max())
    oracle_dev = float(np.abs(w_sh - x).max())
    z2_max_dev = float(np.abs(w_z2 - w_rp).max())
    z2_oracle_dev = float(np.abs(w_z2 - x).max())
    lines.append({
        "metric": "shard_trajectory",
        "dim": traj_dim,
        "steps": 8,
        "traj_max_dev": traj_max_dev,
        "oracle_max_dev": oracle_dev,
        "zero2_max_dev": z2_max_dev,
        "zero2_oracle_max_dev": z2_oracle_dev,
        "tol": traj_tol,
        "sharded_matches_replicated": traj_max_dev <= traj_tol,
        "sharded_matches_numpy_oracle": oracle_dev <= 1e-4,
        "zero2_matches_replicated": z2_max_dev <= traj_tol,
        "zero2_matches_numpy_oracle": z2_oracle_dev <= 1e-4,
        "oracle": "numpy replicated-Adam replay",
    })

    # -- 3. step time within the A/A noise floor ------------------------
    def timed(shard):
        def body():
            opt, params, state = make()
            holder = {"p": params, "s": state}

            def one():
                holder["p"], holder["s"] = opt.step(
                    holder["p"], holder["s"], grads_of(holder["p"])
                )
                # synchronous per-step timing on both arms: identical
                # A/B treatment, and no overlapped rendezvous
                return jax.block_until_ready(holder["p"]["w"])

            one()  # compile
            return _timed_differenced(one, t_steps, windows=2)[0]

        return session(shard, body)

    # INTERLEAVED A/B/A/B... windows (the BENCH_MODE=gossip
    # discipline): ambient drift on a shared host lands on both
    # configs instead of biasing one; best-of-R per config, A/A floor
    # from the spread of the A windows
    reps = int(os.environ.get("BENCH_SHARD_TIME_REPS", "3"))
    t_off, t_on = [], []
    for _rep in range(reps):
        t_off.append(timed(False))
        t_on.append(timed(True))
    t_a = min(t_off)
    t_b = min(t_on)
    aa_pct = (max(t_off) - min(t_off)) / t_a * 100
    delta_pct = (t_b - t_a) / t_a * 100
    noise_bound_pct = max(3 * aa_pct, 15.0)
    lines.append({
        "metric": "shard_step_time",
        "dim": dim,
        "steps_timed": t_steps,
        "windows": reps,
        "ms_unsharded": round(t_a * 1e3, 4),
        "ms_unsharded_aa": round(max(t_off) * 1e3, 4),
        "ms_sharded": round(t_b * 1e3, 4),
        "aa_noise_pct": round(aa_pct, 3),
        "delta_pct": round(delta_pct, 3),
        "noise_bound_pct": round(noise_bound_pct, 3),
        "within_noise": abs(delta_pct) <= noise_bound_pct,
    })

    # -- 4. shard-off bitwise pin + cache-key hygiene --------------------
    def off_run():
        opt, params, state = make()
        for _ in range(4):
            params, state = opt.step(params, state, grads_of(params))
            jax.block_until_ready(params)
        keys = [
            k for k in bf.get_context().op_cache
            if isinstance(k, tuple) and "shard" in map(str, k)
        ]
        return np.asarray(params["w"]), len(keys)

    w_off1, k_off1 = session(False, off_run)
    w_off2, k_off2 = session(False, off_run)
    lines.append({
        "metric": "shard_off_pin",
        "bitwise_identical": bool(np.array_equal(w_off1, w_off2)),
        "shard_tagged_cache_keys": int(k_off1 + k_off2),
        "steps": 4,
    })

    # -- 5. ZeRO-2 gradient memory + scatter wire ------------------------
    def grad_mem():
        """MEASURED (real allocated arrays) reduced-gradient bytes:
        dispatch the actual reduce-scatter collective on the bench
        payload and read the delivered buffer's nbytes — the [slot]
        owned row is the ONLY reduced-gradient buffer the ZeRO-2
        program materializes, vs the allreduce's full [dim] output."""
        from jax.sharding import NamedSharding, PartitionSpec

        from bluefog_tpu.collective import inner as inner_mod

        opt, params, state = make()
        layout = opt._shard_layout
        assert layout is not None and layout.grads
        for _ in range(2):
            params, state = opt.step(params, state, grads_of(params))
            jax.block_until_ready(params)
        g = layout.groups[0]
        ctx = bf.get_context()
        spec = PartitionSpec("workers")
        nd = NamedSharding(ctx.mesh, spec)
        live_index = tuple(
            int(v) for v in np.asarray(layout.live_index())
        )
        xs = np.zeros((n, g.padded), np.float32)
        xs[:, :dim] = c
        rs = jax.jit(jax.shard_map(
            lambda t: inner_mod.reduce_scatter(
                t[0], "workers", live_index, g.slot
            )[None],
            mesh=ctx.mesh, in_specs=spec, out_specs=spec,
        ))
        ar = jax.jit(jax.shard_map(
            lambda t: inner_mod.allreduce(t, "workers", average=True),
            mesh=ctx.mesh, in_specs=spec, out_specs=spec,
        ))
        # one multi-device program in flight at a time: on a small host
        # two concurrent 8-participant rendezvous can starve each other
        y_scat = rs(jax.device_put(jnp.asarray(xs), nd))
        y_scat.block_until_ready()
        y_full = ar(jax.device_put(jnp.asarray(c), nd))
        y_full.block_until_ready()
        # value cross-check: the concatenated delivered slots ARE the
        # allreduce mean (the two programs compute the same reduction)
        got = np.asarray(y_scat)[layout.live, :].reshape(-1)[:dim]
        np.testing.assert_allclose(
            got, np.asarray(y_full)[0], rtol=0, atol=1e-5
        )
        return {
            "layout": layout,
            "slot": g.slot,
            "scat_bytes": int(y_scat.nbytes) // n,
            "full_bytes": int(y_full.nbytes) // n,
        }

    gm = session(True, grad_mem, grads=True)
    layout = gm["layout"]
    slot = gm["slot"]
    grad_ratio = gm["scat_bytes"] / gm["full_bytes"]
    scatter_fp32 = scaling.reduce_scatter_bytes(((slot, 4),), n)
    allreduce_fp32 = sharding.allreduce_wire_bytes(layout)
    gather_fp32 = sharding.gather_wire_bytes(layout)
    tiers = {
        "fp32": {
            "scatter_bytes_per_step": scatter_fp32,
            "ratio_vs_fp32": 1.0,
        },
    }
    for tier in ("bf16", "int8", "int4", "int8_ef", "int4_ef"):
        b = scaling.reduce_scatter_bytes(((slot, 4),), n, wire=tier)
        tiers[tier] = {
            "scatter_bytes_per_step": b,
            "ratio_vs_fp32": round(b / scatter_fp32, 6),
        }
    lines.append({
        "metric": "shard_grad_wire",
        "workers": n,
        "dim": dim,
        "slot_elems": slot,
        "grad_bytes_replicated_measured": gm["full_bytes"],
        "grad_bytes_sharded_measured": gm["scat_bytes"],
        "grad_ratio_measured": round(grad_ratio, 6),
        "grad_pad_ratio": round(slot * n / dim - 1.0, 6),
        "scatter_bytes_per_step": scatter_fp32,
        "allreduce_bytes_per_step": allreduce_fp32,
        "gather_bytes_per_step": gather_fp32,
        "scatter_plus_gather": scatter_fp32 + gather_fp32,
        "allreduce_plus_gather": allreduce_fp32 + gather_fp32,
        "wire_le_baseline": (
            scatter_fp32 + gather_fp32 <= allreduce_fp32 + gather_fp32
        ),
        "tiers": tiers,
    })

    for line in lines:
        print(json.dumps(line), flush=True)

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        memline = lines[0]
        assert memline["replicated_exceeds_budget"], (
            f"replicated state {memline['state_bytes_replicated']} does "
            f"not exceed the simulated budget {budget} — the scenario "
            "proves nothing; raise BENCH_SHARD_DIM"
        )
        assert memline["sharded_fits_budget"], memline
        assert memline["state_bytes_sharded"] <= mem_bound, (
            memline["state_bytes_sharded"], mem_bound,
        )
        assert memline["loss_end"] < 0.5 * memline["loss_start"], memline
        assert memline["replica_spread"] == 0.0, memline
        trajline = lines[1]
        assert trajline["sharded_matches_replicated"], trajline
        assert trajline["sharded_matches_numpy_oracle"], trajline
        assert trajline["zero2_matches_replicated"], trajline
        assert trajline["zero2_matches_numpy_oracle"], trajline
        timeline = lines[2]
        assert timeline["within_noise"], timeline
        offline = lines[3]
        assert offline["bitwise_identical"], offline
        assert offline["shard_tagged_cache_keys"] == 0, offline
        gw = lines[4]
        # measured reduced-gradient footprint: exactly slot/dim of the
        # replicated buffer (both are real f32 arrays, so the ratio is
        # the geometry itself — no tolerance needed beyond the slack)
        assert gw["grad_bytes_sharded_measured"] * dim == (
            gw["grad_bytes_replicated_measured"] * gw["slot_elems"]
        ), gw
        assert gw["grad_ratio_measured"] <= 1.0 / n + gw["grad_pad_ratio"] + 1e-6, gw
        assert gw["wire_le_baseline"], gw
        assert gw["scatter_bytes_per_step"] < gw["allreduce_bytes_per_step"], gw
        # block-scale tier ratios are EXACT on the 512 grid
        assert gw["tiers"]["int8"]["ratio_vs_fp32"] == round(516 / 2048, 6), gw
        assert gw["tiers"]["int4"]["ratio_vs_fp32"] == round(258 / 2048, 6), gw
        assert gw["tiers"]["int8_ef"]["ratio_vs_fp32"] == (
            gw["tiers"]["int8"]["ratio_vs_fp32"]
        ), gw
        assert gw["tiers"]["bf16"]["ratio_vs_fp32"] == 0.5, gw
    return 0


def run_memory() -> int:
    """Memory-observatory evidence (``BENCH_MODE=memory``, committed as
    MEMORY_EVIDENCE.json). Four claims, each measured the way it is
    resolvable (the metrics/health noise-floor lessons apply):

    1. **Analytic-vs-measured reconciliation** (``memory_reconcile``):
       on an 8-worker mesh the observatory's live-array census of the
       Adam state must match the analytic
       ``scaling.optimizer_state_bytes`` model within the disclosed
       tolerance for BOTH ``BLUEFOG_SHARD=0`` and ``=1``, and the
       measured sharded/replicated ratio must be consistent with
       SHARD_EVIDENCE's x0.127 at N=8 — the reconciliation loop PR 14
       shipped only half of.
    2. **Quantized-wire temporaries** (``memory_wire_temps``): at the
       PR-8 payload width, the compiled int8/int4 combines' measured
       XLA scratch (``memory_analysis().temp_size_in_bytes``) must
       contain the full-width f32 temporary (>= 4 bytes/elem) and
       EXCEED the uncompressed combine's scratch — the committed
       before-baseline the ROADMAP-2 kernel-fusion PR must beat
       (EQuARX, arxiv 2506.17615). The analytic staging model
       (``scaling.quantized_temporaries_bytes``) is disclosed next to
       the measurement.
    3. **Overhead <= 1 % at the default interval**
       (``memory_overhead``): sampled-census extra cost in an
       all-orderings off/on/off rotation, amortized over the default
       interval, A/A control disclosed; structural pin (the
       observatory compiles NOTHING — zero new cache entries of any
       kind) and bitwise on/off trajectory pin.
    4. **Pressure gate** (``memory_pressure``): under a simulated
       per-chip budget the ``memory_pressure`` advisory fires with the
       shard-recommendation hint when the optimizer state dominates
       and ``BLUEFOG_SHARD`` is off.
    """
    from bluefog_tpu.platforms import ensure_cpu_device_count

    ensure_cpu_device_count(
        int(os.environ.get("BENCH_MEMORY_DEVICES", "8"))
    )
    import itertools
    import time as time_mod

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    jax.config.update("jax_platforms", "cpu")

    import bluefog_tpu as bf
    import bluefog_tpu.topology as topo
    from bluefog_tpu import memory as bf_memory
    from bluefog_tpu import metrics as bf_metrics
    from bluefog_tpu import scaling
    from bluefog_tpu.collective import inner, plan as planlib

    devices = jax.devices()
    n = min(len(devices),
            int(os.environ.get("BENCH_MEMORY_WORKERS", "8")))
    # the SHARD_EVIDENCE model size: ratio x0.127 at N=8 reproduces
    dim_rec = int(os.environ.get("BENCH_MEMORY_RECONCILE_DIM",
                                 "262145"))
    # the PR-8 payload width (QUANT_EVIDENCE dim)
    dim_wire = int(os.environ.get("BENCH_MEMORY_WIRE_DIM", "4096"))
    dim = int(os.environ.get("BENCH_MEMORY_DIM", "256"))
    layers = int(os.environ.get("BENCH_MEMORY_LAYERS", "6"))
    batch = int(os.environ.get("BENCH_MEMORY_BATCH", "16"))
    samples = max(18, int(os.environ.get("BENCH_MEMORY_SAMPLES", "60")))
    tol = float(os.environ.get("BENCH_MEMORY_TOL", "0.02"))

    old_env = {
        k: os.environ.get(k)
        for k in ("BLUEFOG_MEMORY", "BLUEFOG_MEMORY_INTERVAL",
                  "BLUEFOG_MEMORY_BUDGET", "BLUEFOG_MEMORY_FILE",
                  "BLUEFOG_SHARD", "BLUEFOG_METRICS", "BLUEFOG_HEALTH",
                  "BLUEFOG_DOCTOR", "BLUEFOG_STALENESS")
    }
    for k in old_env:
        os.environ.pop(k, None)
    default_interval = bf_memory.memory_interval()
    rng = np.random.RandomState(0)

    # -- claim 1: analytic-vs-measured reconciliation, SHARD=0/1 --------------
    def reconcile(shard):
        os.environ["BLUEFOG_SHARD"] = "1" if shard else "0"
        bf.init(devices=devices[:n])
        try:
            obs = bf_memory.start(interval=1)
            opt = bf.DistributedGradientAllreduceOptimizer(
                optax.adam(0.02)
            )
            params = {"w": bf.worker_values(
                lambda r: np.zeros(dim_rec, np.float32)
            )}
            state = opt.init(params)
            grads = {"w": bf.worker_values(
                lambda r: rng.randn(dim_rec).astype(np.float32)
            )}
            for _ in range(3):
                params, state = opt.step(params, state, grads)
            s = obs.samples[-1]
            return {
                "measured": s["measured_state_bytes"],
                "analytic": s["analytic_state_bytes"],
                "rel_err": s["reconcile_rel_err"],
            }
        finally:
            bf_memory.stop()
            bf.shutdown()
            os.environ.pop("BLUEFOG_SHARD", None)

    rec_repl = reconcile(False)
    rec_shard = reconcile(True)
    ratio = rec_shard["measured"] / rec_repl["measured"]
    shard_ref = 0.127  # SHARD_EVIDENCE's measured ratio at N=8
    reconcile_line = {
        "metric": "memory_reconcile",
        "workers": n,
        "dim": dim_rec,
        "optimizer": "adam",
        "tolerance": tol,
        "replicated_measured_bytes": rec_repl["measured"],
        "replicated_analytic_bytes": rec_repl["analytic"],
        "replicated_rel_err": rec_repl["rel_err"],
        "sharded_measured_bytes": rec_shard["measured"],
        "sharded_analytic_bytes": rec_shard["analytic"],
        "sharded_rel_err": rec_shard["rel_err"],
        "measured_shard_ratio": round(ratio, 6),
        "shard_evidence_ratio": shard_ref,
        "ratio_consistent_with_shard_evidence": (
            abs(ratio - shard_ref) <= 0.02
        ),
        "both_within_tolerance": (
            rec_repl["rel_err"] <= tol and rec_shard["rel_err"] <= tol
        ),
    }
    print(json.dumps(reconcile_line))

    # -- claim 2: quantized-wire temporaries (the fusion baseline) ------------
    mesh = Mesh(np.array(devices[:n]), ("workers",))
    wire_plan = planlib.plan_from_topology(topo.RingGraph(n))
    x_wire = jax.device_put(
        jnp.zeros((n, dim_wire), jnp.float32),
        NamedSharding(mesh, P("workers")),
    )

    def temp_bytes(wire):
        if wire is None:
            body = lambda t: inner.neighbor_allreduce(
                t, wire_plan, "workers"
            )
        else:
            body = lambda t, w=wire: inner.weighted_combine_quantized(
                t, wire_plan, "workers", wire=w
            )
        fn = jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P("workers"),
            out_specs=P("workers"),
        ))
        ma = fn.lower(x_wire).compile().memory_analysis()
        return int(ma.temp_size_in_bytes)

    full_width = 4 * dim_wire  # the f32 temporary fusion eliminates
    temps = {}
    wire_rows = []
    # pin the fused kernels OFF: these rows are the committed COMPOSITE
    # before-baseline (the fused numbers live in QUANT_EVIDENCE's
    # quant_kernel rows); wire_kernels_on() reads the env per trace, so
    # the fresh lambdas above retrace under the pin
    old_wk = os.environ.get("BLUEFOG_WIRE_KERNELS")
    os.environ["BLUEFOG_WIRE_KERNELS"] = "0"
    try:
        for wire in (None, "int8", "int4"):
            name = wire or "fp32"
            t = temp_bytes(wire)
            temps[name] = t
            wire_rows.append({
                "metric": "memory_wire_temps",
                "wire": name,
                "payload_elems": dim_wire,
                "temp_bytes_measured": t,
                "temp_bytes_analytic": (
                    scaling.quantized_temporaries_bytes(dim_wire, wire)
                ),
                "full_width_bytes": full_width,
                "wire_bytes_per_round": scaling.wire_payload_bytes(
                    dim_wire, 4, wire
                ),
                "extra_vs_exact_bytes": t - temps["fp32"],
                "full_width_temporary_materializes": t >= full_width,
            })
            print(json.dumps(wire_rows[-1]))
    finally:
        if old_wk is None:
            os.environ.pop("BLUEFOG_WIRE_KERNELS", None)
        else:
            os.environ["BLUEFOG_WIRE_KERNELS"] = old_wk
    wire_summary = {
        "metric": "memory_wire_summary",
        "payload_elems": dim_wire,
        "quantized_scratch_exceeds_exact": (
            temps["int8"] > temps["fp32"]
            and temps["int4"] > temps["fp32"]
        ),
        "all_full_width": all(
            r["full_width_temporary_materializes"] for r in wire_rows
            if r["wire"] != "fp32"
        ),
        "note": (
            "composite quantize->pack->ppermute->unpack scratch, "
            "measured with BLUEFOG_WIRE_KERNELS=0 — the retained "
            "before-baseline for the fused wire kernels; the fused "
            "path's measurement (temp_bytes below the fp32 row) lives "
            "in QUANT_EVIDENCE's quant_kernel rows"
        ),
    }
    print(json.dumps(wire_summary))

    # -- claim 4: pressure gate + shard hint ----------------------------------
    # (measured BEFORE the overhead claim: its small model must not be
    # drowned in the overhead steppers' still-live buffers)
    bf.init(devices=devices[:n])
    ctx = bf.get_context()
    obs_p = bf_memory.start(interval=1)
    opt_p = bf.DistributedGradientAllreduceOptimizer(optax.adam(0.02))
    p_p = {"w": bf.worker_values(
        lambda r: np.zeros(1 << 16, np.float32)
    )}
    s_p = opt_p.init(p_p)
    g_p = {"w": bf.worker_values(
        lambda r: rng.randn(1 << 16).astype(np.float32)
    )}
    p_p, s_p = opt_p.step(p_p, s_p, g_p)
    # budget just under the measured footprint: the very next sample
    # must read zero headroom and fire the pressure advisory
    obs_p.budget = int(obs_p.last_bytes_per_rank() * 0.9) or 1
    for _ in range(3):
        p_p, s_p = opt_p.step(p_p, s_p, g_p)
    pressures = [
        a for a in obs_p.advisories if a.kind == "memory_pressure"
    ]
    pressure_line = {
        "metric": "memory_pressure",
        "budget_bytes": obs_p.budget,
        "bytes_per_rank": int(obs_p.last_bytes_per_rank()),
        "headroom_bytes": int(obs_p.last_headroom()),
        "advisory_fired": bool(pressures),
        "shard_hint": (
            pressures[0].detail.get("shard_hint") if pressures
            else None
        ),
        "opt_state_fraction": (
            pressures[0].detail.get("opt_state_fraction")
            if pressures else None
        ),
    }
    print(json.dumps(pressure_line))
    bf_memory.stop()
    del opt_p, p_p, s_p, g_p
    import gc

    gc.collect()

    # -- claim 3: overhead / structural / bitwise pins ------------------------
    bf.set_topology(topo.RingGraph(n))
    w0 = [
        (rng.randn(dim, dim) / np.sqrt(dim)).astype(np.float32)
        for _ in range(layers)
    ]
    xs_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )
    ys_b = bf.worker_values(
        lambda r: rng.randn(batch, dim).astype(np.float32)
    )

    def loss_fn(p, x, y):
        h = x
        for i in range(layers):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.mean((h - y) ** 2)

    def make_stepper():
        opt_s = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.01, momentum=0.9)
        )
        train_step = bf.make_train_step(opt_s, loss_fn)
        params_s = {
            f"w{i}": bf.worker_values(lambda r, i=i: w0[i])
            for i in range(layers)
        }
        carry = [(params_s, opt_s.init(params_s))]

        def _step():
            p, s = carry[0]
            p, s, loss = train_step(p, s, xs_b, ys_b)
            carry[0] = (p, s)
            return loss

        return _step, carry

    # structural pin: the observatory compiles NOTHING — enabling it
    # adds zero cache entries of any kind
    bf_memory.stop()
    stepper, _carry = make_stepper()
    stepper()
    stepper()
    keys_off = set(ctx.op_cache)
    bf_memory.start(interval=1)
    stepper()
    stepper()
    keys_on = set(ctx.op_cache)
    unsampled_shared = keys_on == keys_off
    bf_memory.stop()

    # bitwise trajectory pin
    state_bits = {}
    for variant in ("off", "on"):
        if variant == "on":
            bf_memory.start(interval=3)
        else:
            bf_memory.stop()
        _step, carry = make_stepper()
        for _ in range(12):
            _step()
        state_bits[variant] = jax.tree_util.tree_leaves(carry[0])
    bf_memory.stop()
    bitwise = all(
        bool(np.array_equal(np.asarray(a), np.asarray(b)))
        for a, b in zip(state_bits["off"], state_bits["on"])
    )

    # overhead at the default interval, all-orderings rotation + A/A
    steppers = {}
    obs_on = bf_memory.MemoryObservatory(interval=1)
    for variant in ("off", "on", "off2"):
        bf_memory.activate(obs_on if variant == "on" else None)
        steppers[variant], _ = make_stepper()
        steppers[variant]()  # compile
        _settle(steppers[variant]())
    orders = list(itertools.permutations(("off", "on", "off2")))
    times = {v: [] for v in steppers}
    for i in range(samples):
        for variant in orders[i % len(orders)]:
            bf_memory.activate(obs_on if variant == "on" else None)
            t0 = time_mod.perf_counter()
            _settle(steppers[variant]())
            times[variant].append(time_mod.perf_counter() - t0)
    bf_memory.activate(None)

    def median(v):
        v = sorted(v)
        return v[len(v) // 2] if v else 0.0

    base_s = median(times["off"])
    sample_extra_s = median(
        [on - off for off, on in zip(times["off"], times["on"])]
    )
    control_extra_s = median(
        [o2 - off for off, o2 in zip(times["off"], times["off2"])]
    )
    overhead_pct = (
        100.0 * sample_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    control_pct = (
        100.0 * control_extra_s / default_interval / base_s
        if base_s > 0 else 0.0
    )
    overhead_line = {
        "metric": "memory_overhead",
        "n_workers": n,
        "payload_mb": round(layers * dim * dim * 4 / 1e6, 2),
        "interval": default_interval,
        "ms_per_step_off": round(base_s * 1e3, 3),
        "ms_sampled_step_extra": round(sample_extra_s * 1e3, 3),
        "overhead_pct": round(overhead_pct, 3),
        "control_aa_pct": round(control_pct, 3),
        "unsampled_program_shared": unsampled_shared,
        # MEASURED: cache entries that appeared while the observatory
        # was on (the structural claim is that this is zero — it
        # compiles nothing)
        "observatory_cache_entries": len(keys_on - keys_off),
        "bitwise_identical": bitwise,
        "samples": samples,
    }
    print(json.dumps(overhead_line))
    bf.shutdown()

    bf_metrics.flush()
    for k, v in old_env.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert reconcile_line["both_within_tolerance"], (
            "analytic-vs-measured optimizer-state reconciliation "
            f"exceeded the {tol} tolerance: {reconcile_line}"
        )
        assert reconcile_line[
            "ratio_consistent_with_shard_evidence"
        ], (
            f"measured shard ratio {ratio:.4f} inconsistent with "
            f"SHARD_EVIDENCE's {shard_ref} at N={n}"
        )
        assert wire_summary["all_full_width"], (
            "a quantized combine's measured scratch lost the "
            f"full-width temporary: {wire_rows}"
        )
        assert wire_summary["quantized_scratch_exceeds_exact"], (
            "quantized scratch no longer exceeds the exact path's — "
            "either fusion landed (update this baseline) or the "
            f"accounting broke: {temps}"
        )
        assert unsampled_shared, (
            "enabling the memory observatory changed the compiled "
            "cache entries (it must compile nothing)"
        )
        assert bitwise, (
            "enabling the memory observatory changed the training "
            "state bitwise"
        )
        assert overhead_pct <= 1.0, (
            f"memory-observatory overhead {overhead_pct:.3f}% exceeds "
            f"the 1% acceptance bound at interval {default_interval}"
        )
        assert pressure_line["advisory_fired"], pressure_line
        assert pressure_line["shard_hint"] is True, (
            "memory_pressure fired without the shard hint although "
            f"the Adam state dominates and BLUEFOG_SHARD is off: "
            f"{pressure_line}"
        )
    return 0


def run_fleetscale() -> int:
    """Fleet-scale control-plane evidence (``BENCH_MODE=fleetscale``,
    committed as FLEETSCALE_EVIDENCE.json). The fleet simulator
    (``bf.fleetsim``, docs/fleetsim.md) drives the real membership
    state machine, repair-weight algebra, and plan-cache key
    discipline for hundreds-to-thousands of virtual ranks — no device
    dispatch, so every number here is pure control-plane cost. Four
    claims:

    1. **Per-membership-event cost is sublinear in N**
       (``fleetscale_event_scaling``): a 32-kill cascade at N in
       {128..1024} under the structure-preserving ``receiver`` policy
       (lazy neighborhood renormalization, O(degree^2) per kill;
       ``average`` rebuilds O(edges) per event and is excluded from
       the sublinearity claim — disclosed). The growth exponent of
       the per-event repair cost (log-log least squares over the N
       sweep, best-of-3 runs) must stay < 1. The dense baseline
       (full ``repaired_matrix`` + dense-eig verdict per event) is
       timed at small N only and extrapolated by its own fitted
       power law — the extrapolation model is disclosed in the row,
       not silently assumed.
    2. **A 10 % simultaneous rank-loss storm at N=1024 repairs with
       zero stale dispatches** (``fleetscale_storm``): audit mode ON
       — every dispatch replays its plan's compile-time edge snapshot
       against the current dead set, so one surviving stale plan
       would trip the counter. Asserts zero, plus the churn advisory
       and the exact post-storm live count.
    3. **Controller decision latency at N=1024 is bounded**
       (``fleetscale_decision``): one decision over the candidate set
       (incumbent / live ring / live Exp2) through the sparse
       spectral engine, every candidate's convergence disclosure
       carried; asserts the sparse engine actually ran and the
       decision landed under the bound.
    4. **The sparse engine agrees with the dense oracle at the
       routing boundary** (``fleetscale_agreement``): |sparse-SLEM -
       dense-SLEM| <= 1e-9 at N around ``BLUEFOG_SPECTRAL_DENSE_MAX``
       (the tier-1 property sweep pins this exhaustively; the
       evidence row keeps the claim visible next to the numbers that
       depend on it).
    """
    import numpy as np

    from bluefog_tpu import fleetsim
    from bluefog_tpu.topology import spectral as spectral_mod

    topology = "exp2"
    policy = "receiver"
    kills = 32
    best_of = 3

    # -- claim 1: per-event cost scaling ----------------------------------
    sweep_ns = (128, 256, 512, 1024)
    cells = []
    for n in sweep_ns:
        means, maxes = [], []
        for rep in range(best_of):
            plan = fleetsim.cascade_plan(n, kills, start_step=1,
                                         stride=1, seed=rep)
            vf = fleetsim.VirtualFleet(n, topology=topology,
                                       policy=policy, plan=plan,
                                       audit_edges=False, seed=rep)
            vf.run(kills + 4)
            evs = [e["event_ms"] for e in vf.events
                   if e["metric"] == "fleetsim_repair"]
            means.append(float(np.mean(evs)))
            maxes.append(float(np.max(evs)))
        cells.append({
            "n": n,
            "repairs": kills,
            # best-of-N: ambient stalls only ever inflate a window
            "event_ms_mean": round(min(means), 6),
            "event_ms_max": round(min(maxes), 6),
            "spread_ms": round(max(means) - min(means), 6),
        })
    xs = np.log([c["n"] for c in cells])
    ys = np.log([max(c["event_ms_mean"], 1e-9) for c in cells])
    exponent = float(np.polyfit(xs, ys, 1)[0])

    # dense baseline: full-matrix repair + dense-eig verdict per event,
    # timed at small N, extrapolated by its own fitted power law
    from bluefog_tpu.elastic.repair import repaired_matrix

    dense_ns = (64, 128, 256)
    dense_cells = []
    for n in dense_ns:
        edges = fleetsim.base_edges(n, topology)
        w = np.zeros((n, n))
        for (i, j), v in edges.items():
            w[i, j] = v
        rng = np.random.RandomState(0)
        dead = sorted(rng.choice(n, size=max(1, n // 32),
                                 replace=False).tolist())
        live = [r for r in range(n) if r not in dead]
        reps = []
        for _ in range(best_of):
            t0 = time.perf_counter()
            fixed = repaired_matrix(w, live, policy=policy)
            sub = fixed[np.ix_(live, live)]
            spectral_mod.dense_slem(sub)
            reps.append((time.perf_counter() - t0) * 1e3)
        dense_cells.append({"n": n, "event_ms": round(min(reps), 6)})
    dxs = np.log([c["n"] for c in dense_cells])
    dys = np.log([c["event_ms"] for c in dense_cells])
    dfit = np.polyfit(dxs, dys, 1)
    dense_exponent = float(dfit[0])
    dense_at_1024_ms = float(np.exp(dfit[1]) * 1024 ** dense_exponent)
    sparse_at_1024 = cells[-1]["event_ms_mean"]
    scaling_line = {
        "metric": "fleetscale_event_scaling",
        "topology": topology,
        "policy": policy,
        "cells": cells,
        "growth_exponent": round(exponent, 4),
        "sublinear": exponent < 1.0,
        "dense_baseline_cells": dense_cells,
        "dense_growth_exponent": round(dense_exponent, 4),
        "dense_extrapolation_model": (
            "power-law fit of the measured dense per-event cost "
            f"(log-log least squares over N={list(dense_ns)}), "
            "evaluated at N=1024 — the dense path (full repaired_matrix "
            "+ O(N^3) eig verdict) is never actually run at 1024"
        ),
        "dense_at_1024_ms_extrapolated": round(dense_at_1024_ms, 3),
        "sparse_at_1024_ms": sparse_at_1024,
        "speedup_at_1024_extrapolated": round(
            dense_at_1024_ms / max(sparse_at_1024, 1e-9), 1),
        "note": (
            "per-event cost = lazy neighborhood renormalization of the "
            "killed ranks (receiver policy); the 'average' policy "
            "rebuilds O(edges) per event and is excluded from the "
            "sublinearity claim"
        ),
    }
    print(json.dumps(scaling_line), flush=True)

    # -- claim 2: 10% storm at N=1024, zero stale dispatches ---------------
    n = 1024
    frac = 0.10
    plan = fleetsim.storm_plan(n, frac, step=5, seed=1)
    killed = len(plan.faults)
    vf = fleetsim.VirtualFleet(n, topology=topology, policy=policy,
                               plan=plan, audit_edges=True, seed=1)
    vf.run(12)
    summary = vf.summary()
    storm_line = {
        "metric": "fleetscale_storm",
        "n": n,
        "fraction": frac,
        "killed": killed,
        "steps": summary["steps"],
        "live_after": summary["live"],
        "repair_events": summary["repairs"],
        "stale_dispatches": summary["stale_dispatches"],
        "worst_event_ms": summary["worst_event_ms"],
        "cache_hits": summary["cache_hits"],
        "cache_misses": summary["cache_misses"],
        "advisories": [a["kind"] for a in summary["advisories"]],
        "audit": "every dispatch replays the plan's compile-time edge "
                 "snapshot against the current dead set",
    }
    print(json.dumps(storm_line), flush=True)

    # -- claim 3: decision latency at N=1024 -------------------------------
    decision_bound_ms = 30_000.0
    probe = vf.decision_probe()
    decision_line = {
        "metric": "fleetscale_decision",
        "n_live": probe["n_live"],
        "chosen": probe["chosen"],
        "decision_ms": probe["decision_ms"],
        "bound_ms": decision_bound_ms,
        "candidates": probe["candidates"],
    }
    print(json.dumps(decision_line), flush=True)

    # -- claim 4: sparse/dense agreement at the routing boundary -----------
    agree_rows = []
    worst = 0.0
    for kind in ("ring", "exp2"):
        for an in (48, 64):
            edges = fleetsim.base_edges(an, kind)
            em = spectral_mod.EdgeMatrix(an, edges)
            sparse_rho, _ = spectral_mod.slem_info((an, edges))
            dense_rho = spectral_mod.dense_slem(em.to_dense())
            diff = abs(sparse_rho - dense_rho)
            worst = max(worst, diff)
            agree_rows.append({
                "topology": kind, "n": an,
                "sparse": sparse_rho, "dense": dense_rho,
                "abs_diff": diff,
            })
    agreement_line = {
        "metric": "fleetscale_agreement",
        "tolerance": 1e-9,
        "worst_abs_diff": worst,
        "rows": agree_rows,
        "note": "tests/test_spectral.py sweeps every generator x N x "
                "live subset x period product at this tolerance",
    }
    print(json.dumps(agreement_line), flush=True)

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert scaling_line["sublinear"], (
            f"per-event control-plane cost grew with exponent "
            f"{exponent:.3f} >= 1 over N={list(sweep_ns)}: {cells}"
        )
        assert scaling_line["speedup_at_1024_extrapolated"] > 10.0, (
            "sparse per-event repair no longer clearly beats the "
            f"extrapolated dense baseline at N=1024: {scaling_line}"
        )
        assert storm_line["stale_dispatches"] == 0, (
            f"storm repair leaked stale dispatches: {storm_line}"
        )
        assert storm_line["live_after"] == n - killed, storm_line
        assert storm_line["repair_events"] >= 1, storm_line
        assert "fleet_churn" in storm_line["advisories"], storm_line
        assert decision_line["decision_ms"] <= decision_bound_ms, (
            f"N=1024 decision latency {decision_line['decision_ms']}ms "
            f"exceeded the {decision_bound_ms}ms bound"
        )
        for name, cand in decision_line["candidates"].items():
            assert cand["spectral"]["engine"] == "sparse", (
                f"candidate {name} was not scored by the sparse "
                f"engine at fleet scale: {cand}"
            )
        assert agreement_line["worst_abs_diff"] <= 1e-9, agreement_line
    return 0


def run_federate() -> int:
    """Hierarchical-federation evidence (``BENCH_MODE=federate``,
    committed as FEDERATE_EVIDENCE.json). A two-pod fabric
    (``bf.federation``, docs/federation.md): intra-pod gossip on ICI at
    full rate, a designated-gateway inter-pod leg on DCN every
    ``BLUEFOG_DCN_PERIOD``-th communicating step at the aggressive DCN
    wire tier. Four claims:

    1. **The chosen DCN period matches the spectral prediction**
       (``federate_period``): ``choose_dcn_period`` picks the largest
       period whose composed two-level window (scored end-to-end by the
       PR-18 sparse engine) still meets the target per-step consensus
       rate; the MEASURED rate (host gossip of a random mean-zero
       vector through the real period-T matrix window) must agree with
       the prediction within a disclosed absolute tolerance.
    2. **DCN wire bytes cut >= 8x vs flat gossip at matched measured
       consensus rate** (``federate_wire``): the flat baseline is the
       same base topology spanning both pods, gossiping every k-th
       step with k chosen so its measured per-step rate is at least as
       good as the federated fabric's — the strongest flat opponent at
       the matched rate. Cross-pod bytes per communicating step, both
       sides per-edge totals. The flat side is priced at the exact
       fp32 wire (a flat fabric has ONE tier for all edges — per-leg
       tiers are the point of federation); the all-int4 flat variant
       is disclosed unasserted, since its consensus-error cost is not
       modeled here.
    3. **Whole-pod loss is ONE repair event with zero stale
       dispatches** (``federate_podloss``): a 4x16 fleetsim fleet
       loses pod 1 entirely at one step — the batched repair
       re-elects gateways and renormalizes the inter-pod ring in the
       same event, audit mode on.
    4. **The live dispatch accounts per-leg wire bytes**
       (``federate_dispatch``): a real 8-device 2-pod optimizer run
       under ``BLUEFOG_METRICS=1`` — the
       ``bluefog.federation.{ici,dcn}_wire_bytes`` counters must
       reconcile with the DCN event count and the global mean must be
       preserved through the two-level combine.
    """
    import numpy as np

    from bluefog_tpu import federation, fleetsim

    kind = "exp2"
    n = 16
    layout = federation.parse_pods("2x8", n)

    # -- claim 1: chosen period vs measured rate ---------------------------
    target_rate = float(os.environ.get("BENCH_FED_TARGET_RATE", "0.985"))
    rate_tol = 0.02
    chosen = federation.choose_dcn_period(layout, target_rate, kind=kind)
    period = chosen["period"]
    w_ici = (n, federation.intra_edges(layout, kind))
    w_dcn = (n, federation.inter_edges(layout))
    measured_fed = federation.simulate_consensus(
        [w_ici] * period + [w_dcn], steps=max(4, 256 // period),
        comm_steps_per_cycle=period,
    )
    period_line = {
        "metric": "federate_period",
        "n": n,
        "pods": layout.n_pods,
        "kind": kind,
        "target_rate": target_rate,
        "chosen_period": period,
        "predicted_rate": round(chosen["predicted_rate"], 6),
        "measured_rate": round(measured_fed, 6),
        "abs_err": round(abs(chosen["predicted_rate"] - measured_fed), 6),
        "tolerance": rate_tol,
        "met": chosen["met"],
        "table": chosen["table"],
    }
    print(json.dumps(period_line), flush=True)

    # -- claim 2: matched-rate DCN byte cut --------------------------------
    flat_edges = (n, fleetsim.base_edges(n, kind))
    measured_flat = federation.simulate_consensus([flat_edges], steps=64)
    # flat gossiping every k-th step contracts rate_flat^(1/k) per step;
    # the largest k keeping that at least as strong as the federated
    # measured rate is the cheapest flat opponent at the matched rate
    k = max(1, int(np.floor(
        np.log(max(measured_flat, 1e-12))
        / np.log(max(measured_fed, 1e-12))
    )))
    n_elems = int(os.environ.get("BENCH_FED_ELEMS", str(1 << 20)))
    ws = federation.wire_summary(
        layout, n_elems, itemsize=4, ici_wire=None,
        dcn_wire_tier="int4", period=period, kind=kind,
    )
    flat_dcn_per_step = ws["flat_dcn_bytes_per_step"] / k
    ratio = flat_dcn_per_step / max(ws["dcn_wire_bytes_per_step"], 1e-9)
    ws_int4 = federation.wire_summary(
        layout, n_elems, itemsize=4, ici_wire="int4",
        dcn_wire_tier="int4", period=period, kind=kind,
    )
    ratio_flat_int4 = (
        ws_int4["flat_dcn_bytes_per_step"] / k
        / max(ws["dcn_wire_bytes_per_step"], 1e-9)
    )
    wire_line = {
        "metric": "federate_wire",
        "n": n,
        "n_elems": n_elems,
        "dcn_wire": ws["dcn_wire"],
        "dcn_period": period,
        "measured_rate_fed": round(measured_fed, 6),
        "measured_rate_flat_dense": round(measured_flat, 6),
        "flat_gossip_every": k,
        "measured_rate_flat_matched": round(
            measured_flat ** (1.0 / k), 6
        ),
        "fed_dcn_bytes_per_step": round(
            ws["dcn_wire_bytes_per_step"], 1
        ),
        "flat_dcn_bytes_per_step_matched": round(flat_dcn_per_step, 1),
        "flat_cross_pod_edges": ws["flat_cross_pod_edges"],
        "dcn_cut_ratio_matched": round(ratio, 2),
        "dcn_cut_ratio_flat_int4_unasserted": round(ratio_flat_int4, 2),
        "ici_wire_bytes_per_step": ws["ici_wire_bytes_per_step"],
        "note": (
            "both sides per-edge cross-pod totals per communicating "
            "step; flat opponent gossips every k-th step so its "
            "measured per-step rate is at least as strong as the "
            "federated fabric's"
        ),
    }
    print(json.dumps(wire_line), flush=True)

    # -- claim 3: whole-pod loss = one repair event ------------------------
    n2 = 64
    layout2 = federation.parse_pods("4x16", n2)
    lost = layout2.ranks(1)
    plan = fleetsim.region_plan(n2, lost.start, lost.stop, step=3)
    os.environ["BLUEFOG_PODS"] = "4x16"
    try:
        ff = federation.FederatedFleet(
            layout2, kind=kind, policy="receiver", plan=plan,
            audit_edges=True, seed=0,
        )
        ff.run(8)
        summary = ff.summary()
    finally:
        os.environ.pop("BLUEFOG_PODS", None)
    repair_events = [
        e for e in ff.fleet.events if e["metric"] == "fleetsim_repair"
    ]
    podloss_line = {
        "metric": "federate_podloss",
        "n": n2,
        "pods": layout2.n_pods,
        "pod_lost": 1,
        "ranks_lost": len(lost),
        "repair_events": summary["repairs"],
        "stale_dispatches": summary["stale_dispatches"],
        "loss_class": (
            repair_events[0].get("loss_class") if repair_events else None
        ),
        "pods_lost": (
            repair_events[0].get("pods_lost") if repair_events else None
        ),
        "gateways_after": summary["federation"]["gateways"],
        "gateway_change": (
            repair_events[0].get("gateway_change")
            if repair_events else None
        ),
        "event_ms": (
            repair_events[0].get("event_ms") if repair_events else None
        ),
        "live_after": summary["live"],
    }
    print(json.dumps(podloss_line), flush=True)

    # -- claim 4: live dispatch, per-leg counters --------------------------
    from bluefog_tpu.platforms import ensure_cpu_device_count

    ensure_cpu_device_count(8)
    os.environ["BLUEFOG_PODS"] = "2"
    os.environ["BLUEFOG_DCN_PERIOD"] = "4"
    os.environ["BLUEFOG_METRICS"] = "1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf
    from bluefog_tpu import metrics as metrics_mod

    federation.clear_fabric_cache()
    bf.init(devices=jax.devices()[:8])
    steps = 8
    dcn_events = (steps + 3) // 4
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.05))
    params = {"w": bf.worker_values(lambda r: jnp.full((256,), float(r)))}
    state = opt.init(params)
    train = bf.make_train_step(
        opt, lambda p, b: jnp.sum(p["w"] ** 2) * 0.0
    )
    for _ in range(steps):
        params, state, _loss = train(params, state, None)
    snap = metrics_mod.snapshot()
    w = np.asarray(params["w"])
    fab = federation.get_fabric(8)
    dispatch_line = {
        "metric": "federate_dispatch",
        "devices": 8,
        "pods": 2,
        "dcn_period": 4,
        "dcn_wire": fab.wire if fab else None,
        "steps": steps,
        "dcn_events": dcn_events,
        "ici_wire_bytes": snap.get(
            "bluefog.federation.ici_wire_bytes", {}
        ).get("value"),
        "dcn_wire_bytes": snap.get(
            "bluefog.federation.dcn_wire_bytes", {}
        ).get("value"),
        "total_wire_bytes": snap.get(
            "bluefog.wire_bytes", {}
        ).get("value"),
        "mean_preserved": bool(
            np.isclose(float(w.mean()), (8 - 1) / 2.0, atol=1e-4)
        ),
        "consensus_spread": round(
            float(w.mean(axis=1).max() - w.mean(axis=1).min()), 6
        ),
    }
    print(json.dumps(dispatch_line), flush=True)

    if os.environ.get("BENCH_ASSERT", "1") != "0":
        assert period_line["met"], (
            f"no DCN period meets the {target_rate} target: {period_line}"
        )
        assert period_line["abs_err"] <= rate_tol, (
            "measured federated consensus rate drifted from the "
            f"spectral prediction: {period_line}"
        )
        assert wire_line["dcn_cut_ratio_matched"] >= 8.0, (
            f"DCN byte cut fell below 8x at matched rate: {wire_line}"
        )
        assert podloss_line["repair_events"] == 1, (
            f"whole-pod loss was not ONE repair event: {podloss_line}"
        )
        assert podloss_line["stale_dispatches"] == 0, podloss_line
        assert podloss_line["loss_class"] == "pod_loss", podloss_line
        assert podloss_line["pods_lost"] == [1], podloss_line
        assert podloss_line["live_after"] == n2 - len(lost), podloss_line
        assert dispatch_line["ici_wire_bytes"], dispatch_line
        assert dispatch_line["dcn_wire_bytes"], dispatch_line
        assert dispatch_line["mean_preserved"], dispatch_line
        expected_total = (
            dispatch_line["ici_wire_bytes"]
            + dispatch_line["dcn_wire_bytes"]
        )
        assert dispatch_line["total_wire_bytes"] == expected_total, (
            "per-leg counters do not reconcile with the total: "
            f"{dispatch_line}"
        )
    return 0


def run_all() -> int:
    """The full evidence set: each family in an isolated subprocess (the
    scaling family must own backend init; a family crash must not take
    out the headline), headline last for tail-reading drivers."""
    import subprocess

    for mode in ("scaling", "plan", "overlap", "metrics", "elastic",
                 "flight", "attribution", "health", "slo",
                 "staleness", "autotune", "async", "quant", "shard",
                 "memory", "fleetscale", "federate", "gossip",
                 "flash", "transformer"):
        env = dict(os.environ, BENCH_MODE=mode)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True, timeout=2400,
            )
        except subprocess.TimeoutExpired as e:
            # isolation contract: a hung family must not take out the
            # remaining families or the headline
            print(json.dumps({
                "metric": f"bench_{mode}_failed",
                "timeout_s": 2400,
                "stdout_tail": (e.stdout or b"").decode(
                    "utf-8", "replace"
                )[-200:] if isinstance(e.stdout, bytes)
                else (e.stdout or "")[-200:],
            }), flush=True)
            continue
        for line in proc.stdout.splitlines():
            if line.startswith("{"):
                print(line, flush=True)
        if proc.returncode != 0:
            print(json.dumps({
                "metric": f"bench_{mode}_failed",
                "returncode": proc.returncode,
                "stderr_tail": proc.stderr[-400:],
            }), flush=True)
    return run_headline()


def main() -> int:
    mode = os.environ.get("BENCH_MODE", "")
    print(json.dumps(_provenance()), flush=True)
    runners = {
        "scaling": run_scaling,
        "elastic": run_elastic,
        "plan": run_plan,
        "overlap": run_overlap,
        "metrics": run_metrics,
        "flight": run_flight,
        "attribution": run_attribution,
        "health": run_health,
        "slo": run_slo,
        "staleness": run_staleness,
        "autotune": run_autotune,
        "async": run_async,
        "quant": run_quant,
        "shard": run_shard,
        "memory": run_memory,
        "fleetscale": run_fleetscale,
        "federate": run_federate,
        "gossip": run_gossip_overhead,
        "transformer": run_transformer,
        "flash": run_flash,
        "headline": run_headline,
    }
    rc = runners.get(mode, run_all)()
    # the ambient-drift anchor closes EVERY evidence artifact: measured
    # after the mode ran (the mode owns backend/platform init), memoized
    # so a headline's embedded vs_anchor is this same measurement
    try:
        print(json.dumps(_ambient_anchor()), flush=True)
    except Exception as e:  # an anchor failure must not fail the bench
        print(json.dumps({
            "metric": "ambient_anchor", "error": str(e)[:200],
        }), flush=True)
    return rc


if __name__ == "__main__":
    sys.exit(main())
