#!/usr/bin/env python
# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Headline benchmark: ResNet50 decentralized train-step throughput.

Mirrors the reference benchmark driver (``examples/pytorch_benchmark.py``:
ResNet50, bs=64 per worker, neighbor_allreduce optimizer) on one TPU chip.
Baseline: BlueFog-NCCL ResNet50 at 4310.6 img/s total on 16 V100s
(docs/performance.rst:16-24) = 269.4 img/s per accelerator; vs_baseline is
imgs/sec-per-chip against that per-accelerator number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time


def main() -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bluefog_tpu.models import ResNet50
    import bluefog_tpu.topology as topo
    from bluefog_tpu.collective import inner, plan as planlib

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    n = len(devices)

    # Per-worker batch: the BASELINE config is 64; CPU fallback stays tiny
    # so the driver always gets a line.
    batch = int(os.environ.get("BENCH_BATCH", "64" if on_tpu else "4"))
    image = int(os.environ.get("BENCH_IMAGE", "224" if on_tpu else "32"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3"))
    warmup = int(os.environ.get("BENCH_WARMUP", "5" if on_tpu else "1"))

    mesh = Mesh(np.array(devices), ("workers",))
    plan = planlib.plan_from_topology(
        topo.ExponentialTwoGraph(n) if n > 1 else topo.FullyConnectedGraph(1),
        weighted=True,
    )

    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    sample = jnp.ones((batch, image, image, 3), jnp.bfloat16)
    variables = model.init(rng, sample, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def stack(tree):
        return jax.tree_util.tree_map(
            lambda t: jnp.broadcast_to(t[None], (n,) + t.shape), tree
        )

    spec = P("workers")
    sharding = NamedSharding(mesh, spec)
    state = jax.device_put(
        (stack(params), stack(batch_stats), stack(opt_state)), sharding
    )

    def train_step(state, images, labels):
        params, batch_stats, opt_state = jax.tree_util.tree_map(
            lambda t: t[0], state
        )
        x, y = images[0], labels[0]

        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Adapt-then-combine gossip of the updated parameters (the
        # neighbor_allreduce optimizer's hot path).
        params = jax.tree_util.tree_map(
            lambda t: inner.neighbor_allreduce(t, plan, "workers"), params
        )
        expand = lambda tr: jax.tree_util.tree_map(
            lambda t: jnp.expand_dims(t, 0), tr
        )
        return expand((params, new_stats, opt_state)), loss.reshape(1)

    fn = jax.jit(
        jax.shard_map(
            train_step,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=(spec, spec),
        ),
        donate_argnums=(0,),
    )

    rng_np = np.random.RandomState(0)
    images = jax.device_put(
        rng_np.randn(n, batch, image, image, 3).astype(np.float32), sharding
    ).astype(jnp.bfloat16)
    labels = jax.device_put(
        rng_np.randint(0, 1000, size=(n, batch)).astype(np.int32), sharding
    )

    def settle(loss):
        # block_until_ready can be a no-op on remote-tunneled platforms;
        # a host readback of the loss scalar provably waits for the step.
        return float(np.asarray(loss)[0])

    for _ in range(warmup):
        state, loss = fn(state, images, labels)
    settle(loss)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = fn(state, images, labels)
    settle(loss)
    t1 = time.perf_counter()
    settle(loss)  # already materialized: measures pure readback latency
    t_read = time.perf_counter() - t1
    dt = max(t1 - t0 - t_read, 1e-9)

    imgs_per_sec = n * batch * steps / dt
    per_chip = imgs_per_sec / n
    baseline_per_accel = 4310.6 / 16.0  # docs/performance.rst:16-24
    print(
        json.dumps(
            {
                "metric": "resnet50_bs%d_imgs_per_sec_per_chip" % batch,
                "value": round(per_chip, 2),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(per_chip / baseline_per_accel, 4),
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
