#!/usr/bin/env python
# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Headline benchmark: ResNet50 decentralized train-step throughput.

Mirrors the reference benchmark driver (``examples/pytorch_benchmark.py``:
ResNet50, bs=64 per worker, neighbor_allreduce optimizer) on one TPU chip.
Baseline: BlueFog-NCCL ResNet50 at 4310.6 img/s total on 16 V100s
(docs/performance.rst:16-24) = 269.4 img/s per accelerator; vs_baseline is
imgs/sec-per-chip against that per-accelerator number.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "mfu", ...}.
``mfu`` uses the 2*MAC FLOP convention (ResNet50 fwd ~= 8.2 GFLOP/img,
fwd+bwd ~= 3x fwd) against the device's peak bf16 FLOP/s.

``BENCH_MODE=scaling`` instead emits the scaling-efficiency evidence
(reference docs/performance.rst:26-53, README.rst:51-60): static per-step
comm accounting from compiled HLO for one-peer gossip vs allreduce across
mesh sizes, plus weak-scaling step times on the available devices.
"""

import json
import os
import sys
import time

# Peak dense bf16 FLOP/s by TPU generation (public spec sheets); used only
# to report MFU. Unknown kinds fall back to 0 => mfu omitted.
_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# 2*MAC FLOPs: ResNet50 forward at 224x224 is ~4.1 GMACs = 8.2 GFLOP/img;
# backward ~= 2x forward.
_FLOPS_PER_IMG_FWD_BWD = 3 * 8.2e9


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "")
    for key, val in _PEAK_BF16.items():
        if kind.startswith(key):
            return val
    return 0.0


# Tunnel-safe sync point (a plain np.asarray readback would cache on the
# array object and break the readback-latency correction — the round-3
# ~25% under-report).
from bluefog_tpu.timing import settle as _settle  # noqa: E402


def run_headline() -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from bluefog_tpu.models import ResNet50
    import bluefog_tpu.topology as topo
    from bluefog_tpu.collective import inner, plan as planlib

    devices = jax.devices()
    on_tpu = devices[0].platform not in ("cpu",)
    n = len(devices)

    # Per-worker batch: the BASELINE config is 64; CPU fallback stays tiny
    # so the driver always gets a line.
    batch = int(os.environ.get("BENCH_BATCH", "64" if on_tpu else "4"))
    image = int(os.environ.get("BENCH_IMAGE", "224" if on_tpu else "32"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "3")))
    # >=1: the timing loop settles on the warmup's last loss
    warmup = max(
        1, int(os.environ.get("BENCH_WARMUP", "5" if on_tpu else "1"))
    )

    mesh = Mesh(np.array(devices), ("workers",))
    plan = planlib.plan_from_topology(
        topo.ExponentialTwoGraph(n) if n > 1 else topo.FullyConnectedGraph(1),
        weighted=True,
    )

    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    sample = jnp.ones((batch, image, image, 3), jnp.bfloat16)
    variables = model.init(rng, sample, train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = tx.init(params)

    def stack(tree):
        return jax.tree_util.tree_map(
            lambda t: jnp.broadcast_to(t[None], (n,) + t.shape), tree
        )

    spec = P("workers")
    sharding = NamedSharding(mesh, spec)
    state = jax.device_put(
        (stack(params), stack(batch_stats), stack(opt_state)), sharding
    )

    def train_step(state, images, labels):
        params, batch_stats, opt_state = jax.tree_util.tree_map(
            lambda t: t[0], state
        )
        x, y = images[0], labels[0]

        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y
            ).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # Adapt-then-combine gossip of the updated parameters (the
        # neighbor_allreduce optimizer's hot path).
        params = jax.tree_util.tree_map(
            lambda t: inner.neighbor_allreduce(t, plan, "workers"), params
        )
        expand = lambda tr: jax.tree_util.tree_map(
            lambda t: jnp.expand_dims(t, 0), tr
        )
        return expand((params, new_stats, opt_state)), loss.reshape(1)

    fn = jax.jit(
        jax.shard_map(
            train_step,
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=(spec, spec),
        ),
        donate_argnums=(0,),
    )

    rng_np = np.random.RandomState(0)
    images = jax.device_put(
        rng_np.randn(n, batch, image, image, 3).astype(np.float32), sharding
    ).astype(jnp.bfloat16)
    labels = jax.device_put(
        rng_np.randint(0, 1000, size=(n, batch)).astype(np.int32), sharding
    )

    for _ in range(warmup):
        state, loss = fn(state, images, labels)
    _settle(loss)
    _settle(loss)  # warm any readback-path compile cache

    # Best-of-N timed windows (default 8 on TPU; each is cheap once
    # compiled): the chip is reached
    # through a shared tunnel, so a single window can absorb unrelated
    # stalls; the best window is the reproducible hardware number (each
    # window is still steps>=20 long).
    best_dt = None
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", "8" if on_tpu else "1")))
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            state, loss = fn(state, images, labels)
        _settle(loss)
        t1 = time.perf_counter()
        _settle(loss)  # already materialized: measures pure readback latency
        t_read = time.perf_counter() - t1
        dt = max(t1 - t0 - t_read, 1e-9)
        if best_dt is None or dt < best_dt:
            best_dt = dt

    imgs_per_sec = n * batch * steps / best_dt
    per_chip = imgs_per_sec / n
    baseline_per_accel = 4310.6 / 16.0  # docs/performance.rst:16-24
    result = {
        "metric": "resnet50_bs%d_imgs_per_sec_per_chip" % batch,
        "value": round(per_chip, 2),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(per_chip / baseline_per_accel, 4),
    }
    peak = _peak_flops(devices[0])
    if peak:
        # FLOPs/img scale ~quadratically with resolution (BENCH_IMAGE knob).
        flops_img = _FLOPS_PER_IMG_FWD_BWD * (image / 224.0) ** 2
        result["mfu"] = round(per_chip * flops_img / peak, 4)
        result["device"] = devices[0].device_kind
    print(json.dumps(result))
    return 0


def run_scaling() -> int:
    """Scaling-efficiency evidence: HLO comm accounting + weak scaling.

    Defaults to an 8-device virtual CPU mesh (the ambient TPU tunnel exposes
    one chip, and plain env vars are too late — the platform plugin pins
    JAX_PLATFORMS at interpreter startup, so this must go through
    ``jax.config`` before backend init). Set BENCH_SCALING_PLATFORM=native
    to run on the real devices of a multi-chip slice.
    """
    if os.environ.get("BENCH_SCALING_PLATFORM", "cpu") != "native":
        from bluefog_tpu.platforms import ensure_cpu_device_count

        ensure_cpu_device_count(int(os.environ.get("BENCH_SCALING_DEVICES", "8")))
        import jax

        jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import bluefog_tpu.topology as topo
    from bluefog_tpu import scaling
    from bluefog_tpu.collective import plan as planlib

    n_dev = len(jax.devices())
    # Model size in ELEMENTS (ResNet50 has ~25.56M parameters); the f32 wire
    # payload is 4 bytes each.
    payload_elems = int(os.environ.get("BENCH_PAYLOAD_ELEMS", str(25_557_032)))
    payload_bytes = payload_elems * 4
    lines = []

    # Static comm accounting across mesh sizes (bounded by device count).
    ns = [n for n in (2, 4, 8, 16) if n <= n_dev]
    for n in ns:
        sched = planlib.schedule_from_dynamic(
            n,
            lambda r: topo.GetDynamicOnePeerSendRecvRanks(
                topo.ExponentialGraph(n), r
            ),
        )
        stats = scaling.gossip_comm_stats(
            sched.plans[0], payload_elems, jnp.float32
        )
        cp = stats.get("collective-permute", {"count": 0, "bytes": 0})
        ring = scaling.ring_allreduce_cost(n, payload_bytes)
        lines.append(
            {
                "metric": "one_peer_gossip_comm",
                "n_workers": n,
                "collective_permutes": cp["count"],
                "wire_bytes_per_worker": cp["bytes"],
                "ring_allreduce_wire_bytes": round(ring["wire_bytes"]),
                "ring_allreduce_hops": ring["latency_hops"],
            }
        )

    # Weak scaling: constant per-worker compute + one-peer gossip.
    def make_step(mesh):
        n = mesh.devices.size
        plan = (
            planlib.schedule_from_dynamic(
                n,
                lambda r: topo.GetDynamicOnePeerSendRecvRanks(
                    topo.ExponentialGraph(n), r
                ),
            ).plans[0]
            if n > 1
            else planlib.plan_from_topology(topo.FullyConnectedGraph(1))
        )
        spec = P("workers")

        def body(x, w):
            y = jnp.tanh(x @ w)
            return scaling.inner.neighbor_allreduce(y, plan, "workers")

        fn = jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=(spec, P()), out_specs=spec
            )
        )
        x = jax.device_put(
            np.ones((n, 64, 1024), np.float32), NamedSharding(mesh, spec)
        )
        w = jnp.ones((1024, 1024), jnp.float32)
        return fn, (x, w)

    ns_weak = [n for n in (1, 2, 4, 8) if n <= n_dev]
    for row in scaling.weak_scaling_times(make_step, ns_weak):
        lines.append(
            {
                "metric": "weak_scaling_gossip_step",
                "n_workers": row["n"],
                "ms_per_step": round(row["ms_per_step"], 3),
                "efficiency": round(row["efficiency"], 4),
            }
        )

    for line in lines:
        print(json.dumps(line))
    return 0


def run_gossip_overhead() -> int:
    """Bound the gossip step's on-chip cost with communication REALLY in
    the program: 8 virtual workers share the one chip (vmapped replicas,
    bs/8 each), and the neighbor combine is the algebraically-identical
    einsum with the Exp2 weight matrix over the replica axis. The delta
    vs the combine-free step bounds the per-step gossip arithmetic +
    memory cost; the model-size HBM roundtrip gives the per-round wire
    floor a real ppermute pays on top (ICI transfer not measurable with
    one chip). Emits one JSON line per measurement."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    import networkx as nx

    from bluefog_tpu.models import ResNet50
    import bluefog_tpu.topology as topo

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    n_virt = int(os.environ.get("BENCH_GOSSIP_WORKERS", "8"))
    batch = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "2"))
    image = int(os.environ.get("BENCH_IMAGE", "224" if on_tpu else "32"))
    steps = max(1, int(os.environ.get("BENCH_STEPS", "10" if on_tpu else "2")))
    # >=1: the timing loop settles on the warmup's last loss
    warmup = max(
        1, int(os.environ.get("BENCH_WARMUP", "3" if on_tpu else "1"))
    )

    w = jnp.asarray(
        nx.to_numpy_array(topo.ExponentialTwoGraph(n_virt)), jnp.float32
    )
    model = ResNet50(num_classes=1000)
    rng = jax.random.PRNGKey(0)
    sample = jnp.ones((batch, image, image, 3), jnp.bfloat16)
    variables = model.init(rng, sample, train=True)
    tx = optax.sgd(0.1, momentum=0.9)
    stack = lambda tree: jax.tree_util.tree_map(
        lambda t: jnp.broadcast_to(t[None], (n_virt,) + t.shape) + 0.0, tree
    )
    params = stack(variables["params"])
    batch_stats = stack(variables["batch_stats"])
    opt_state = jax.tree_util.tree_map(
        lambda t: t + 0.0, stack(tx.init(variables["params"]))
    )
    rng_np = np.random.RandomState(0)
    images = jnp.asarray(
        rng_np.randn(n_virt, batch, image, image, 3), jnp.bfloat16
    )
    labels = jnp.asarray(
        rng_np.randint(0, 1000, (n_virt, batch)), jnp.int32
    )

    def one_step(p, bs, s, x, y):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": bs}, x, train=True,
                mutable=["batch_stats"],
            )
            return (
                optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean(),
                mutated["batch_stats"],
            )

        (loss, nbs), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), nbs, s, loss

    def make(gossip):
        def step(params, batch_stats, opt_state, images, labels):
            p, nbs, s, loss = jax.vmap(one_step)(
                params, batch_stats, opt_state, images, labels
            )
            if gossip:
                # y_j = sum_i W[i, j] x_i over the replica axis — the
                # exact neighbor_allreduce combine, on-chip
                p = jax.tree_util.tree_map(
                    lambda t: jnp.einsum(
                        "ij,i...->j...", w.astype(t.dtype), t
                    ),
                    p,
                )
            return p, nbs, s, loss

        return jax.jit(step, donate_argnums=(0, 1, 2))

    def timed(fn, state):
        params, batch_stats, opt_state = state
        for _ in range(warmup):
            params, batch_stats, opt_state, loss = fn(
                params, batch_stats, opt_state, images, labels
            )
        _settle(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, batch_stats, opt_state, loss = fn(
                params, batch_stats, opt_state, images, labels
            )
        _settle(loss)
        t1 = time.perf_counter()
        _settle(loss)
        t_read = time.perf_counter() - t1
        return max(t1 - t0 - t_read, 1e-9) / steps

    copy = lambda tr: jax.tree_util.tree_map(lambda t: t + 0.0, tr)
    dt_plain = timed(make(False), (copy(params), copy(batch_stats),
                                   copy(opt_state)))
    dt_gossip = timed(make(True), (params, batch_stats, opt_state))

    # wire floor: one model-size HBM roundtrip (a ppermute's on-chip
    # cost). Sub-ms per iteration, so run many to dominate the readback
    # correction.
    flat = jnp.zeros((25_557_032,), jnp.float32)
    bump = jax.jit(lambda t: t + 1.0)
    copy_iters = 20 * steps
    for _ in range(warmup):
        flat = bump(flat)
    _settle(flat[:1])
    t0 = time.perf_counter()
    for _ in range(copy_iters):
        flat = bump(flat)
    _settle(flat[:1])
    t1 = time.perf_counter()
    _settle(flat[:1])
    dt_copy = max(t1 - t0 - (time.perf_counter() - t1), 1e-9) / copy_iters

    total = n_virt * batch
    for line in (
        {"metric": "gossip_step_no_comm", "workers_on_chip": n_virt,
         "imgs_per_sec": round(total / dt_plain, 1),
         "ms_per_step": round(dt_plain * 1e3, 2)},
        {"metric": "gossip_step_with_combine", "workers_on_chip": n_virt,
         "imgs_per_sec": round(total / dt_gossip, 1),
         "ms_per_step": round(dt_gossip * 1e3, 2),
         "gossip_overhead_pct": round(
             100.0 * (dt_gossip - dt_plain) / dt_plain, 2)},
        {"metric": "model_hbm_roundtrip", "ms": round(dt_copy * 1e3, 3)},
    ):
        print(json.dumps(line))
    return 0


def main() -> int:
    mode = os.environ.get("BENCH_MODE", "")
    if mode == "scaling":
        return run_scaling()
    if mode == "gossip":
        return run_gossip_overhead()
    return run_headline()


if __name__ == "__main__":
    sys.exit(main())
