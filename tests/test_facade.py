# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Facade tests: init / topology management / eager ops / handle model.

Parity model: reference ``test/torch_basics_test.py`` (init, rank/size,
topology set/load, neighbor queries) and the eager-op slices of
``test/torch_ops_test.py`` lifted to worker arrays.
"""

import numpy as np
import networkx as nx
import pytest

import jax.numpy as jnp

import bluefog_tpu as bf

SIZE = 8


@pytest.fixture(autouse=True)
def fresh_context():
    bf.init()
    yield
    bf.shutdown()


def rand(shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_init_sizes():
    assert bf.is_initialized()
    assert bf.size() == SIZE
    assert bf.rank() == 0
    assert bf.local_rank() == 0
    assert bf.is_homogeneous()
    # Single process, no nodes_per_machine: one flat machine.
    assert bf.local_size() == SIZE
    assert bf.machine_size() == 1


def test_default_topology_is_exponential():
    topo = bf.load_topology()
    assert bf.topology.IsTopologyEquivalent(
        topo, bf.topology.ExponentialGraph(SIZE)
    )
    assert not bf.is_topo_weighted()


def test_set_load_topology_roundtrip():
    ring = bf.topology.RingGraph(SIZE)
    assert bf.set_topology(ring, is_weighted=True)
    assert bf.topology.IsTopologyEquivalent(bf.load_topology(), ring)
    assert bf.is_topo_weighted()
    # Reset to default.
    assert bf.set_topology(None)
    assert bf.topology.IsTopologyEquivalent(
        bf.load_topology(), bf.topology.ExponentialGraph(SIZE)
    )


def test_set_topology_wrong_size_raises():
    with pytest.raises(ValueError, match="workers"):
        bf.set_topology(bf.topology.RingGraph(SIZE + 1))


def test_neighbor_ranks_queries():
    bf.set_topology(bf.topology.RingGraph(SIZE))
    for r in range(SIZE):
        assert bf.in_neighbor_ranks(r) == sorted({(r - 1) % SIZE, (r + 1) % SIZE})
        assert bf.out_neighbor_ranks(r) == sorted({(r - 1) % SIZE, (r + 1) % SIZE})
    all_ins = bf.in_neighbor_ranks()
    assert len(all_ins) == SIZE and all_ins[0] == [1, SIZE - 1]


def test_worker_values_forms():
    x = bf.worker_values(lambda r: np.full((3,), float(r)))
    np.testing.assert_allclose(np.asarray(x), np.arange(SIZE)[:, None] * np.ones(3))
    y = bf.worker_values([np.full((2,), r) for r in range(SIZE)])
    assert y.shape == (SIZE, 2)
    z = bf.worker_values(np.ones((4,)))
    assert z.shape == (SIZE, 4)


def test_neighbor_allreduce_default_uniform():
    """Default (no weights): uniform 1/(in_deg+1) combine over the default
    unweighted Exp topology (reference mpi_ops.py:500-505)."""
    x = rand((SIZE, 5), seed=1)
    got = np.asarray(bf.neighbor_allreduce(bf.worker_values(list(x))))
    adj = nx.to_numpy_array(bf.load_topology())
    expected = np.zeros_like(x)
    for j in range(SIZE):
        srcs = [i for i in range(SIZE) if adj[i, j] != 0 and i != j]
        expected[j] = (x[j] + x[srcs].sum(0)) / (len(srcs) + 1)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_neighbor_allreduce_weighted_topology():
    ring = bf.topology.RingGraph(SIZE)
    bf.set_topology(ring, is_weighted=True)
    x = rand((SIZE, 4), seed=2)
    got = np.asarray(bf.neighbor_allreduce(jnp.asarray(x)))
    expected = nx.to_numpy_array(ring).T @ x
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_neighbor_allreduce_explicit_weights():
    bf.set_topology(bf.topology.RingGraph(SIZE))
    src_w = [
        {(j - 1) % SIZE: 0.3, (j + 1) % SIZE: 0.2} for j in range(SIZE)
    ]
    x = rand((SIZE, 3), seed=3)
    got = np.asarray(
        bf.neighbor_allreduce(jnp.asarray(x), self_weight=0.5, src_weights=src_w)
    )
    expected = np.zeros_like(x)
    for j in range(SIZE):
        expected[j] = (
            0.5 * x[j] + 0.3 * x[(j - 1) % SIZE] + 0.2 * x[(j + 1) % SIZE]
        )
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_neighbor_allreduce_rejects_non_neighbors():
    bf.set_topology(bf.topology.RingGraph(SIZE))
    src_w = [{(j + 2) % SIZE: 0.5} for j in range(SIZE)]  # not an in-neighbor
    with pytest.raises(ValueError, match="not in-neighbors"):
        bf.neighbor_allreduce(
            jnp.asarray(rand((SIZE, 2))), self_weight=0.5, src_weights=src_w
        )


def test_neighbor_allreduce_rejects_flat_dict():
    with pytest.raises(ValueError, match="per-rank"):
        bf.neighbor_allreduce(
            jnp.asarray(rand((SIZE, 2))),
            self_weight=0.5,
            src_weights={1: 0.5},
        )


def test_neighbor_allreduce_dynamic_dst_weights():
    """Dynamic mode: dst list + explicit self/src weights, stepping a
    one-peer schedule eagerly (the reference README dynamic-topology loop)."""
    g = bf.topology.ExponentialTwoGraph(SIZE)
    bf.set_topology(g)
    iters = [
        bf.topology.GetDynamicOnePeerSendRecvRanks(g, r) for r in range(SIZE)
    ]
    x = rand((SIZE, 4), seed=4)
    val = jnp.asarray(x)
    for _ in range(3):
        lists = [next(it) for it in iters]
        dst_w = [send for send, _ in lists]
        src_w = [{s: 0.5 for s in recv} for _, recv in lists]
        got = np.asarray(
            bf.neighbor_allreduce(
                val, self_weight=0.5, src_weights=src_w, dst_weights=dst_w
            )
        )
        cur = np.asarray(val)
        expected = np.zeros_like(cur)
        for j, (_, recv) in enumerate(lists):
            expected[j] = 0.5 * cur[j] + sum(0.5 * cur[s] for s in recv)
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)
        val = jnp.asarray(got)


def test_dynamic_requires_self_and_src():
    with pytest.raises(ValueError, match="dynamic topology"):
        bf.neighbor_allreduce(
            jnp.asarray(rand((SIZE, 2))), dst_weights=[[1]] * SIZE
        )


def test_allreduce_allgather_broadcast():
    x = rand((SIZE, 3), seed=5)
    avg = np.asarray(bf.allreduce(jnp.asarray(x)))
    np.testing.assert_allclose(avg, np.tile(x.mean(0), (SIZE, 1)), rtol=1e-5)

    summed = np.asarray(bf.allreduce(jnp.asarray(x), average=False))
    np.testing.assert_allclose(summed, np.tile(x.sum(0), (SIZE, 1)), rtol=1e-5)

    # Per-worker value is [3]; reference concatenates along dim 0 -> [24].
    gathered = np.asarray(bf.allgather(jnp.asarray(x)))
    assert gathered.shape == (SIZE, SIZE * 3)
    np.testing.assert_allclose(gathered[5].reshape(SIZE, 3), x, rtol=1e-6)

    bc = np.asarray(bf.broadcast(jnp.asarray(x), root_rank=4))
    np.testing.assert_allclose(bc, np.tile(x[4], (SIZE, 1)), rtol=1e-6)


def test_neighbor_allgather():
    bf.set_topology(bf.topology.StarGraph(SIZE))
    x = rand((SIZE, 2), seed=6)
    per_rank = bf.neighbor_allgather(jnp.asarray(x))
    assert len(per_rank) == SIZE
    # Center (0) receives everyone else, rank-ascending.
    np.testing.assert_allclose(np.asarray(per_rank[0]), x[1:], rtol=1e-6)
    # Leaves receive only the center.
    for r in range(1, SIZE):
        np.testing.assert_allclose(np.asarray(per_rank[r]), x[:1], rtol=1e-6)


def test_pair_gossip_facade():
    x = rand((SIZE, 2), seed=7)
    got = np.asarray(bf.pair_gossip(jnp.asarray(x), [(0, 1), (2, 3)]))
    np.testing.assert_allclose(got[0], 0.5 * (x[0] + x[1]), rtol=1e-6)
    np.testing.assert_allclose(got[7], x[7], rtol=1e-6)
    # Per-rank involution form.
    targets = [1, 0, 3, 2, -1, -1, -1, -1]
    got2 = np.asarray(bf.pair_gossip(jnp.asarray(x), targets))
    np.testing.assert_allclose(got2, got, rtol=1e-6)
    with pytest.raises(ValueError, match="mutual"):
        bf.pair_gossip(jnp.asarray(x), [1, 2, 0, -1, -1, -1, -1, -1])


def test_handle_model():
    x = rand((SIZE, 3), seed=8)
    h = bf.allreduce_nonblocking(jnp.asarray(x))
    assert isinstance(h, int)
    out = bf.synchronize(h)
    np.testing.assert_allclose(np.asarray(out), np.tile(x.mean(0), (SIZE, 1)), rtol=1e-5)
    h2 = bf.neighbor_allreduce_nonblocking(jnp.asarray(x))
    _ = bf.poll(h2)  # may be True or False; must not raise
    out2 = bf.wait(h2)
    assert out2.shape == (SIZE, 3)
    bf.barrier()


def test_hierarchical_facade():
    bf.init(nodes_per_machine=4)
    assert bf.local_size() == 4 and bf.machine_size() == 2
    assert bf.machine_rank(5) == 1
    ring = bf.topology.RingGraph(2)
    bf.set_machine_topology(ring, is_weighted=True)
    assert bf.in_neighbor_machine_ranks(0) == [1]

    x = rand((SIZE, 3), seed=9)
    got = np.asarray(bf.hierarchical_neighbor_allreduce(jnp.asarray(x)))
    wm = nx.to_numpy_array(ring)
    means = x.reshape(2, 4, 3).mean(1)
    expected = np.repeat(wm.T @ means, 4, axis=0)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_hierarchical_explicit_weights():
    bf.init(nodes_per_machine=2)  # 4 machines
    mw = [{(m - 1) % 4: 0.5} for m in range(4)]
    x = rand((SIZE, 2), seed=10)
    got = np.asarray(
        bf.hierarchical_neighbor_allreduce(
            jnp.asarray(x),
            self_weight=0.5,
            neighbor_machine_weights=mw,
            send_neighbor_machines=[[(m + 1) % 4] for m in range(4)],
        )
    )
    means = x.reshape(4, 2, 2).mean(1)
    expected_m = np.zeros_like(means)
    for m in range(4):
        expected_m[m] = 0.5 * means[m] + 0.5 * means[(m - 1) % 4]
    np.testing.assert_allclose(
        got, np.repeat(expected_m, 2, axis=0), rtol=1e-5, atol=1e-6
    )


def test_plan_cache_follows_topology_changes():
    """Switching topologies must not serve a stale compiled plan."""
    x = rand((SIZE, 3), seed=11)
    bf.set_topology(bf.topology.RingGraph(SIZE), is_weighted=True)
    ring_out = np.asarray(bf.neighbor_allreduce(jnp.asarray(x)))
    bf.set_topology(bf.topology.StarGraph(SIZE), is_weighted=True)
    star_out = np.asarray(bf.neighbor_allreduce(jnp.asarray(x)))
    np.testing.assert_allclose(
        ring_out, nx.to_numpy_array(bf.topology.RingGraph(SIZE)).T @ x, rtol=1e-5
    )
    np.testing.assert_allclose(
        star_out, nx.to_numpy_array(bf.topology.StarGraph(SIZE)).T @ x, rtol=1e-5
    )


def test_nonblocking_matches_blocking_layout():
    """synchronize(nonblocking) returns exactly the blocking op's layout."""
    bf.set_topology(bf.topology.StarGraph(SIZE))
    x = rand((SIZE, 2), seed=12)
    blocking = bf.neighbor_allgather(jnp.asarray(x))
    nonblocking = bf.synchronize(bf.neighbor_allgather_nonblocking(jnp.asarray(x)))
    assert len(blocking) == len(nonblocking)
    for a, b in zip(blocking, nonblocking):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    ag_block = np.asarray(bf.allgather(jnp.asarray(x)))
    ag_nonblock = np.asarray(bf.synchronize(bf.allgather_nonblocking(jnp.asarray(x))))
    assert ag_block.shape == ag_nonblock.shape
    np.testing.assert_allclose(ag_block, ag_nonblock, rtol=1e-6)


def test_uninitialized_raises():
    bf.shutdown()
    with pytest.raises(RuntimeError, match="not initialized"):
        bf.size()
    bf.init()  # restore for the autouse fixture's shutdown


def test_resnet_family_shapes():
    """torchvision-parity model zoo: every ResNet depth builds and runs
    (the reference benchmarks arbitrary torchvision models,
    examples/pytorch_benchmark.py:60-75)."""
    import jax
    import jax.numpy as jnp
    from bluefog_tpu import models as zoo

    expected = {
        "ResNet18": 11.2e6, "ResNet34": 21.3e6, "ResNet50": 23.6e6,
        "ResNet101": 42.5e6, "ResNet152": 58.2e6,
    }
    for name, approx in expected.items():
        m = getattr(zoo, name)(num_classes=10)
        v = m.init(jax.random.PRNGKey(0),
                   jnp.ones((1, 32, 32, 3), jnp.bfloat16), train=False)
        out = m.apply(v, jnp.ones((1, 32, 32, 3), jnp.bfloat16),
                      train=False)
        assert out.shape == (1, 10)
        n = sum(p.size for p in jax.tree_util.tree_leaves(v["params"]))
        assert abs(n - approx) / approx < 0.05, (name, n)


# -- fire-and-forget handle reclamation (VERDICT Weak #6) ---------------------


def test_handle_map_bounded_under_fire_and_forget():
    """10k unsynchronized nonblocking ops must not grow the handle map
    without bound: once past the reap threshold, each new dispatch
    reclaims the oldest READY results (the abandoned ones). The bulk of
    the pressure uses host-side ready stand-ins (dispatching 10k real
    programs is pure test latency); a real-op smoke closes the loop."""
    from bluefog_tpu.collective import ops as col_ops

    class Ready:
        def is_ready(self):
            return True

    baseline = len(col_ops._handle_map)
    handles = [col_ops._new_handle(Ready()) for _ in range(10_000)]
    assert len(col_ops._handle_map) <= (
        col_ops._HANDLE_REAP_THRESHOLD + baseline + 1
    )
    # a reclaimed handle polls True (its result WAS ready) and
    # synchronize reports the reclamation instead of a bare KeyError
    assert bf.poll(handles[0])
    with pytest.raises(ValueError, match="reclaimed"):
        bf.synchronize(handles[0])
    # the newest handle survived and synchronizes normally
    assert col_ops._handle_map.pop(handles[-1], None) is not None

    # real ops: a burst of nonblocking dispatches stays bounded, and a
    # recent handle still returns the right value
    x = bf.worker_values(lambda r: np.full((4,), float(r), np.float32))
    hs = [bf.allreduce_nonblocking(x) for _ in range(40)]
    assert len(col_ops._handle_map) <= col_ops._HANDLE_REAP_THRESHOLD + 1
    out = bf.synchronize(hs[-1])
    np.testing.assert_allclose(
        np.asarray(out)[0], np.full((4,), np.mean(range(SIZE))),
        rtol=1e-6,
    )
    for h in hs[:-1]:  # drain what survived
        col_ops._handle_map.pop(h, None)


# -- per-op neighbor-list validation cache (VERDICT Weak #7) ------------------


def test_in_neighbor_sets_cached_on_topo_version():
    ctx = bf.get_context()
    first = ctx.in_neighbor_sets()
    # warm path: same object back, no recompute
    assert ctx.in_neighbor_sets() is first
    assert first[0] == frozenset(bf.in_neighbor_ranks(0))
    # a topology change invalidates exactly once
    bf.set_topology(bf.topology.RingGraph(SIZE))
    second = ctx.in_neighbor_sets()
    assert second is not first
    assert second[0] == frozenset(bf.in_neighbor_ranks(0))
    assert ctx.in_neighbor_sets() is second


def test_explicit_weights_hot_path_host_cost_pinned():
    """Pin the eager explicit-weights path's per-call host validation at
    the north-star scale (256 ranks), mirroring
    test_windows.py::test_host_weight_resolution_cost: after the first
    call builds the topo_version-keyed neighbor sets, repeated
    validation is O(keys) — the graph is never walked again."""
    import time
    import types

    from bluefog_tpu import context as ctx_mod

    size = 256
    g = bf.topology.ExponentialTwoGraph(size)
    ctx = types.SimpleNamespace(
        size=size, _topology=g, topo_version=1,
        _neighbor_sets_cache=None,
    )
    t0 = time.perf_counter()
    sets = ctx_mod.BluefogContext.in_neighbor_sets(ctx)
    cold_s = time.perf_counter() - t0
    assert len(sets) == size

    # the validation body _resolve_plan runs per call, against the
    # cached sets (one entry per rank, subset check per rank)
    per_rank = [dict.fromkeys(s, 0.1) for s in sets]

    def validate_once():
        in_sets = ctx_mod.BluefogContext.in_neighbor_sets(ctx)
        for r, entry in enumerate(per_rank):
            assert set(entry.keys()).issubset(in_sets[r])

    validate_once()
    t0 = time.perf_counter()
    for _ in range(50):
        validate_once()
    warm_s = (time.perf_counter() - t0) / 50
    # generous CI bound (measured ~0.2 ms at 256 ranks); the load-bearing
    # assertion is identity: the cache is returned, never rebuilt
    assert warm_s < 0.01, (warm_s, cold_s)
    assert ctx_mod.BluefogContext.in_neighbor_sets(ctx) is sets
