# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Fleet-health-plane tests: spectral helpers, decay-rate fitting and
mixing efficiency, the in-band push-sum lane vs its numpy oracle
(including a dead rank on a weighted digraph), the ``mixing_degraded``
advisory across all emission surfaces, the ``/healthz`` / ``/metrics``
/ ``/fleet`` endpoints (including port-conflict graceful no-op), and
``tools/fleet_report.py``.
"""

import json
import os
import socket
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import bluefog_tpu as bf
import bluefog_tpu.topology as tu
from bluefog_tpu import flight, health, metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIZE = 8


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices, monkeypatch):
    for k in ("BLUEFOG_HEALTH", "BLUEFOG_HEALTH_INTERVAL",
              "BLUEFOG_HEALTH_PORT", "BLUEFOG_HEALTH_FILE",
              "BLUEFOG_HEALTH_ROUNDS", "BLUEFOG_HEALTH_EPS"):
        monkeypatch.delenv(k, raising=False)
    metrics.reset()
    bf.init(devices=cpu_devices[:SIZE])
    yield
    health.stop()
    bf.elastic.stop()
    bf.shutdown()
    metrics.reset()


# -- spectral helpers ---------------------------------------------------------


def test_slem_known_values():
    """Ring/Exp2/fully-connected SLEMs land on their analytic values:
    the ring's 1/3 + 2/3·cos(2π/8), Exp2's 1/2, fully-connected 0."""
    ring = tu.mixing_matrix(tu.RingGraph(SIZE))
    exp2 = tu.mixing_matrix(tu.ExponentialTwoGraph(SIZE))
    full = tu.mixing_matrix(tu.FullyConnectedGraph(SIZE))
    assert tu.second_largest_eigenvalue_modulus(ring) == pytest.approx(
        1.0 / 3.0 + 2.0 / 3.0 * np.cos(2 * np.pi / SIZE), abs=1e-9
    )
    assert tu.second_largest_eigenvalue_modulus(exp2) == pytest.approx(
        0.5, abs=1e-9
    )
    assert tu.second_largest_eigenvalue_modulus(full) < 1e-9
    assert tu.spectral_gap(full) == pytest.approx(1.0, abs=1e-9)
    # Exp2 promises faster mixing than ring — the paper's premise
    assert tu.consensus_decay_rate(exp2) < tu.consensus_decay_rate(ring)


def test_slem_disconnected_graph_promises_nothing():
    """Two disconnected cliques have a repeated eigenvalue 1: SLEM 1.0
    (no contraction), and the observatory maps that to 'no
    prediction'."""
    w = np.zeros((4, 4))
    w[:2, :2] = 0.5
    w[2:, 2:] = 0.5
    assert tu.second_largest_eigenvalue_modulus(w) == pytest.approx(1.0)
    assert health.mixing_efficiency(0.5, 1.0) is None


def test_one_peer_period_product_beats_single_step():
    """The dynamic one-peer schedule's period-product rate: each single
    iteration barely mixes (one peer per rank), but the period product
    contracts — and the helper's matrices are doubly stochastic."""
    topo = tu.ExponentialTwoGraph(SIZE)
    mats = tu.one_peer_period_matrices(topo)
    assert len(mats) == 3  # out-degree log2(8) = 3 neighbor choices
    for m in mats:
        assert m.sum(axis=0) == pytest.approx(np.ones(SIZE))
        assert m.sum(axis=1) == pytest.approx(np.ones(SIZE))
    rate = tu.consensus_decay_rate(mats)
    assert 0.0 < rate < 1.0
    # the period product mixes strictly better per step than any single
    # iteration's matrix promises alone
    single = tu.consensus_decay_rate(mats[0])
    assert rate < single


# -- decay fit / efficiency / projection --------------------------------------


def test_fit_decay_rate_recovers_geometric_series():
    pts = [(i, 3.0 * 0.85 ** i) for i in range(0, 24, 3)]
    rate = health.fit_decay_rate(pts)
    assert rate == pytest.approx(0.85, abs=1e-9)
    assert health.mixing_efficiency(rate, 0.85) == pytest.approx(
        1.0, abs=1e-6
    )


def test_fit_decay_rate_refuses_thin_or_flat_input():
    assert health.fit_decay_rate([(0, 1.0), (1, 0.9)]) is None
    # noise-floor points are dropped, starving the fit
    pts = [(i, 1e-15) for i in range(10)]
    assert health.fit_decay_rate(pts) is None
    # a non-decaying series reports rate >= 1 -> efficiency 0
    pts = [(i, 1.0 + 0.01 * i) for i in range(8)]
    rate = health.fit_decay_rate(pts)
    assert rate >= 1.0
    assert health.mixing_efficiency(rate, 0.8) == 0.0


def test_time_to_consensus_projection():
    # 1.0 -> 1e-6 at rate 0.5: log(1e-6)/log(0.5) ~ 19.9 steps
    steps = health.time_to_consensus_steps(1.0, 0.5, eps=1e-6)
    assert steps == pytest.approx(19.93, abs=0.01)
    assert health.time_to_consensus_steps(1e-9, 0.5, eps=1e-6) == 0.0
    assert health.time_to_consensus_steps(1.0, 1.1, eps=1e-6) is None
    assert health.time_to_consensus_steps(None, 0.5) is None


# -- push-sum lane ------------------------------------------------------------


def test_push_matrix_conserves_sender_mass():
    w = tu.mixing_matrix(tu.ExponentialTwoGraph(SIZE))
    p = health.push_matrix(w, dead=[5])
    # every live row sums to 1 (mass conservation); dead row/col zeroed
    for i in range(SIZE):
        if i == 5:
            assert p[i].sum() == 0.0
            assert p[:, i].sum() == 0.0
        else:
            assert p[i].sum() == pytest.approx(1.0)


def test_fleet_aggregate_device_matches_numpy_oracle():
    """The acceptance oracle: the compiled lane on a WEIGHTED digraph
    with one dead rank must match the numpy replay, and both must
    deliver the live-set mean/min/max."""
    # a genuinely weighted, non-symmetric digraph: exp2 weights skewed
    g = tu.ExponentialTwoGraph(SIZE)
    w = tu.mixing_matrix(g)
    w[0, 1] *= 2.0  # break symmetry; lane normalizes per sender
    ctx = bf.get_context()
    bf.set_topology(g)
    rng = np.random.RandomState(3)
    vals = rng.randn(SIZE, len(health.FLEET_FIELDS)) * 5.0
    dead = [4]
    dev = health.fleet_aggregate(ctx, vals, rounds=12, w=w, dead=dead)
    ora = health.fleet_aggregate_np(w, vals, rounds=12, dead=dead)
    assert np.allclose(dev["mean"], ora["mean"], rtol=1e-4, atol=1e-5)
    assert np.allclose(dev["min"], ora["min"])
    assert np.allclose(dev["max"], ora["max"])
    live = [j for j in range(SIZE) if j not in dead]
    assert np.allclose(dev["min"], vals[live].min(axis=0))
    assert np.allclose(dev["max"], vals[live].max(axis=0))
    true_mean = vals[live].mean(axis=0)
    assert np.allclose(dev["mean"], true_mean, rtol=0.02, atol=0.02)
    assert dev["live"] == live
    assert dev["residual"] < 0.02


def test_streaming_lane_tracks_changing_values():
    """The sampled-step streaming form: delta injection keeps the
    push-sum mean tracking a CHANGING per-rank summary, and the min/max
    generations publish exact extrema once warmed."""
    ctx = bf.get_context()
    bf.set_topology(tu.ExponentialTwoGraph(SIZE))
    plane = health.HealthPlane(interval=1)
    rng = np.random.RandomState(0)
    vals = rng.rand(SIZE, len(health.FLEET_FIELDS))
    rep = None
    for t in range(40):
        if t == 20:
            vals = vals + 10.0  # the fleet state moves mid-run
        rep = plane._fleet_step(ctx, vals, dead=[], predicted=0.5)
    assert not rep["warming"]
    assert np.allclose(rep["mean"], vals.mean(axis=0), rtol=0.02,
                       atol=0.02)
    assert np.allclose(rep["min"], vals.min(axis=0), atol=1e-5)
    assert np.allclose(rep["max"], vals.max(axis=0), atol=1e-5)


# -- observatory + advisory ---------------------------------------------------


def _drive_consensus(plane, ctx, w, steps, start_step=0, x=None,
                     lossy=None, factor=0.05):
    """Drive the plane with an eager numpy consensus iteration;
    ``lossy=(s, d)`` replays a deterministic packet-droppy link."""
    if x is None:
        x = np.random.RandomState(1).randn(w.shape[0], 64)
    for t in range(start_step, start_step + steps):
        y = w.T @ x
        if lossy is not None:
            s, d = lossy
            y[d] += (1.0 - factor) * w[s, d] * (x[d] - x[s])
        x = y
        dist = float(np.sqrt(((x - x.mean(0)) ** 2).sum(1)).mean())
        plane.observe(ctx, step=t, consensus=dist)
    return x


def test_observatory_measures_on_contract_efficiency():
    ctx = bf.get_context()
    ring = tu.RingGraph(SIZE)
    bf.set_topology(ring)
    w = tu.mixing_matrix(ring)
    plane = health.start(interval=1)
    _drive_consensus(plane, ctx, w, steps=25)
    s = plane.samples[-1]
    pred = tu.consensus_decay_rate(w)
    assert s["predicted_rate"] == pytest.approx(pred, abs=1e-9)
    assert s["measured_rate"] == pytest.approx(pred, rel=0.05)
    assert s["mixing_efficiency"] == pytest.approx(1.0, abs=0.1)
    assert s["time_to_eps_steps"] > 0
    # gauges landed
    assert metrics.peek("bluefog.health.mixing_efficiency") is not None
    assert metrics.peek("bluefog.health.samples").value >= 25


def test_mixing_degraded_fires_and_names_injected_edge(tmp_path):
    """The chaos acceptance path: a lossy link measurably slows mixing
    below the spectral promise; the advisory fires on every surface
    (metrics counter, flight side table, health JSONL) and its suspect
    join names the injected edge."""
    os.environ["BLUEFOG_HEALTH_FILE"] = str(tmp_path / "health.jsonl")
    ctx = bf.get_context()
    ring = tu.RingGraph(SIZE)
    bf.set_topology(ring)
    w = tu.mixing_matrix(ring)
    session = bf.elastic.start(policy="average")
    session.inject("degrade", rank=2, step=0, factor=0.05, peer=3)
    plane = health.start(interval=1)
    x = _drive_consensus(plane, ctx, w, steps=30)
    assert not [a for a in plane.advisories
                if a.kind == "mixing_degraded"]
    _drive_consensus(plane, ctx, w, steps=50, start_step=30, x=x,
                     lossy=(2, 3))
    advs = [a for a in plane.advisories if a.kind == "mixing_degraded"]
    assert advs, "mixing_degraded never fired"
    assert [2, 3] in advs[0].detail["suspect_edges"]
    assert advs[0].detail["mixing_efficiency"] < (
        advs[0].detail["baseline_efficiency"]
    )
    # surfaces: metrics counter, flight advisory side table, JSONL
    c = metrics.peek("bluefog.doctor.advisory.mixing_degraded")
    assert c is not None and c.value >= 1
    flight_advs = [
        a for a in flight.events()
        if a.get("kind") == "advisory"
    ]
    lines = [
        json.loads(l) for l in
        open(tmp_path / "health.jsonl").read().splitlines()
    ]
    assert any(l.get("advisory_kind") == "mixing_degraded"
               for l in lines)
    assert any(l.get("kind") == "sample" and "mixing_efficiency" in l
               for l in lines)
    # /healthz degrades to warn while the advisory is recent
    assert health.healthz_verdict(plane)["status"] == "warn"
    del flight_advs


def test_advisory_survives_healthy_restart_of_baseline():
    """A topology swap mid-session resets the efficiency baseline: the
    new graph's different (healthy) efficiency must NOT fire the
    advisory that a stale baseline would have."""
    ctx = bf.get_context()
    ring = tu.RingGraph(SIZE)
    bf.set_topology(ring)
    plane = health.start(interval=1)
    _drive_consensus(plane, ctx, tu.mixing_matrix(ring), steps=25)
    exp2 = tu.ExponentialTwoGraph(SIZE)
    bf.set_topology(exp2)  # topo_version bumps
    _drive_consensus(plane, ctx, tu.mixing_matrix(exp2), steps=20)
    assert not [a for a in plane.advisories
                if a.kind == "mixing_degraded"]
    s = plane.samples[-1]
    assert s["predicted_rate"] == pytest.approx(0.5, abs=1e-9)


def test_healthz_recency_uses_comm_step_marks():
    """Regression: under K>1 gradient accumulation an advisory's
    ``step`` (optimizer step clock) runs K× faster than the plane's
    comm-step count; the /healthz recency window must compare the
    comm-step emit marks, or a cleared condition stays 'warn' K×
    longer than the window intends."""
    from bluefog_tpu.attribution import Advisory

    plane = health.start(interval=1)
    adv = Advisory(kind="mixing_degraded", step=400, detail={})
    plane.advisories.append(adv)
    plane.advisory_marks.append(100)  # emitted at comm step 100
    plane._count = 100 + health.VERDICT_RECENT_SAMPLES + 1
    v = health.healthz_verdict(plane)
    assert v["status"] == "ok", v  # stale despite step=400 >> floor
    plane._count = 100 + health.VERDICT_RECENT_SAMPLES - 1
    assert health.healthz_verdict(plane)["status"] == "warn"


# -- serving surface ----------------------------------------------------------


def test_healthz_fleet_metrics_endpoints():
    ctx = bf.get_context()
    bf.set_topology(tu.RingGraph(SIZE))
    plane = health.start(interval=1)
    _drive_consensus(plane, ctx, tu.mixing_matrix(bf.load_topology()),
                     steps=12)
    srv = health.serve(0)  # OS-assigned port
    assert srv is not None
    base = f"http://127.0.0.1:{srv.port}"
    v = json.loads(urllib.request.urlopen(base + "/healthz").read())
    assert v["status"] == "ok" and v["dead_ranks"] == []
    prom = urllib.request.urlopen(base + "/metrics").read().decode()
    assert "# HELP" in prom and "# TYPE" in prom
    assert "bluefog_health_samples_total" in prom
    fleet = json.loads(urllib.request.urlopen(base + "/fleet").read())
    assert fleet["kind"] == "health_dump"
    assert fleet["fleet"]["fields"] == list(health.FLEET_FIELDS)
    assert fleet["healthz"]["status"] == "ok"
    # unknown path -> 404 with the path list
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(base + "/nope")
    assert err.value.code == 404
    srv.close()


def test_healthz_critical_on_dead_rank_returns_503():
    ctx = bf.get_context()
    bf.set_topology(tu.RingGraph(SIZE))
    session = bf.elastic.start(policy="average")
    session.membership.mark_dead(5, "killed", 0)
    plane = health.start(interval=1)
    v = health.healthz_verdict(plane)
    assert v["status"] == "critical" and 5 in v["dead_ranks"]
    srv = health.serve(0)
    with pytest.raises(urllib.error.HTTPError) as err:
        urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz"
        )
    assert err.value.code == 503
    srv.close()
    del ctx


def test_port_conflict_is_graceful_noop():
    blocker = socket.socket()
    blocker.bind(("0.0.0.0", 0))
    blocker.listen(1)
    port = blocker.getsockname()[1]
    try:
        srv = health.HealthServer.maybe_start(port)
        assert srv is None  # warned, did not raise, did not serve
    finally:
        blocker.close()


def test_env_port_wires_serving_through_init(cpu_devices, monkeypatch):
    free = socket.socket()
    free.bind(("", 0))
    port = free.getsockname()[1]
    free.close()
    monkeypatch.setenv("BLUEFOG_HEALTH_PORT", str(port))
    monkeypatch.setenv("BLUEFOG_HEALTH", "1")
    bf.shutdown()
    bf.init(devices=cpu_devices[:SIZE])
    try:
        assert health.server() is not None
        assert health.active() is not None  # BLUEFOG_HEALTH=1 observatory
        v = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz"
        ).read())
        assert v["status"] in ("ok", "warn")
    finally:
        bf.shutdown()
        assert health.server() is None  # shutdown closed it


# -- optimizer integration ----------------------------------------------------


def test_optimizer_hook_feeds_plane_without_touching_programs():
    """The hook path: a real fused train step drives the plane; the
    train-step cache is untouched (lane programs live under their own
    family), and the sampled plane sees the topology's predicted
    rate."""
    import optax

    ctx = bf.get_context()
    bf.set_topology(tu.ExponentialTwoGraph(SIZE))
    rng = np.random.RandomState(0)
    w0 = (rng.randn(32, 32) / 6.0).astype(np.float32)
    xs = bf.worker_values(lambda r: rng.randn(8, 32).astype(np.float32))
    ys = bf.worker_values(lambda r: rng.randn(8, 32).astype(np.float32))

    def loss_fn(p, x, y):
        import jax.numpy as jnp

        return jnp.mean((jnp.tanh(x @ p["w"]) - y) ** 2)

    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.01))
    step = bf.make_train_step(opt, loss_fn)
    params = {"w": bf.worker_values(lambda r: w0)}
    state = opt.init(params)
    for _ in range(2):
        params, state, _ = step(params, state, xs, ys)
    train_keys = {
        k for k in ctx.op_cache
        if isinstance(k, tuple) and k and k[0] in (
            "opt_step", "opt_fused_step",
        )
    }
    plane = health.start(interval=2)
    for _ in range(6):
        params, state, _ = step(params, state, xs, ys)
    assert plane.samples, "optimizer hook never sampled"
    s = plane.samples[-1]
    assert s["predicted_rate"] == pytest.approx(0.5, abs=1e-6)
    assert s["fleet"]["live"] == list(range(SIZE))
    after = {
        k for k in ctx.op_cache
        if isinstance(k, tuple) and k and k[0] in (
            "opt_step", "opt_fused_step",
        )
    }
    assert after == train_keys  # structural pin
    assert any(
        isinstance(k, tuple) and k and k[0] == "health_pushsum"
        for k in ctx.op_cache
    )


# -- fleet_report CLI ---------------------------------------------------------


def test_fleet_report_renders_artifacts(tmp_path):
    ctx = bf.get_context()
    bf.set_topology(tu.RingGraph(SIZE))
    plane = health.start(interval=1)
    _drive_consensus(plane, ctx, tu.mixing_matrix(bf.load_topology()),
                     steps=15)
    art = tmp_path / "health_0.json"
    health.dump(str(art))
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         str(art), "--json"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["kind"] == "fleet_report"
    assert rep["overall"] == "ok"
    assert rep["processes"][0]["mixing_efficiency"] is not None
    assert rep["worst_rank"] is not None
    assert 0 <= rep["worst_rank"]["rank"] < SIZE
    # human table mode renders without crashing
    out2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         str(art)],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out2.returncode == 0, out2.stderr
    assert "worst rank" in out2.stdout
    assert "fleet aggregate" in out2.stdout


def test_fleet_report_unreadable_inputs_exit_2(tmp_path):
    bad = tmp_path / "nope.json"
    bad.write_text("{}")
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         str(bad), "--json"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out.returncode == 2


def test_doctor_triage_ingests_health_artifact(tmp_path):
    """tools/doctor.py --health: the triage report names the worst rank
    and its dominant advisory in the human-sentence section."""
    ctx = bf.get_context()
    ring = tu.RingGraph(SIZE)
    bf.set_topology(ring)
    w = tu.mixing_matrix(ring)
    session = bf.elastic.start(policy="average")
    session.inject("degrade", rank=2, step=0, factor=0.05, peer=3)
    plane = health.start(interval=1)
    x = _drive_consensus(plane, ctx, w, steps=30)
    _drive_consensus(plane, ctx, w, steps=50, start_step=30, x=x,
                     lossy=(2, 3))
    art = tmp_path / "health.json"
    health.dump(str(art))
    attr = tmp_path / "doctor.json"
    attr.write_text(json.dumps({
        "kind": "doctor_dump", "interval": 100, "samples": [],
        "advisories": [], "baselines": {}, "calibration": {},
    }))
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "doctor.py"),
         "--attribution", str(attr), "--health", str(art), "--json"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["health"]["worst_rank"] is not None
    assert rep["health"]["dominant_advisory"] == "mixing_degraded"
    joined = " ".join(rep["summary"])
    assert "worst in the fleet" in joined
    assert "mixing_degraded" in joined


# -- non-finite rendering + concurrent scrapes (staleness-PR satellites) ------


def test_endpoints_survive_non_finite_gauges():
    """Regression: a NaN gauge (e.g. a step EWMA before warmup) must
    never reach a scraper as a bare ``NaN`` token — strict JSON
    parsers reject it and a /fleet scrape turns into a parse error
    exactly while the plane warms up. JSON surfaces degrade the value
    to null; the Prometheus text surface uses the exposition format's
    own ``NaN``/``+Inf`` casings."""
    plane = health.start(interval=1)
    plane._step_ewma_ms = float("nan")
    with plane._report_lock:
        plane.samples.append({
            "kind": "sample", "step": 0,
            "step_ms_ewma": float("nan"),
            "consensus": float("inf"),
            "nested": {"v": float("-inf"), "list": [float("nan")]},
        })
    metrics.gauge("bluefog.test.nan_gauge").set(float("nan"))
    metrics.gauge("bluefog.test.inf_gauge").set(float("inf"))
    srv = health.serve(0)
    assert srv is not None
    base = f"http://127.0.0.1:{srv.port}"

    def strict_loads(raw):
        def reject(tok):
            raise ValueError(f"non-finite token {tok!r} in JSON")

        return json.loads(raw, parse_constant=reject)

    fleet = strict_loads(urllib.request.urlopen(base + "/fleet").read())
    last = fleet["samples"][-1]
    assert last["step_ms_ewma"] is None
    assert last["consensus"] is None
    assert last["nested"]["v"] is None
    assert last["nested"]["list"] == [None]
    strict_loads(urllib.request.urlopen(base + "/healthz").read())
    prom = urllib.request.urlopen(base + "/metrics").read().decode()
    for line in prom.splitlines():
        assert " nan" not in line and " inf" not in line, line
    assert "bluefog_test_nan_gauge NaN" in prom
    assert "bluefog_test_inf_gauge +Inf" in prom
    srv.close()


def test_concurrent_scrapes_while_plane_publishes():
    """Two clients hammering /metrics and /fleet while the training
    thread publishes sampled steps: every response must be a parseable
    200 (the report-lock regression surface — deque mutation during
    iteration turned scrapes into 500s exactly on sampled steps)."""
    import threading

    ctx = bf.get_context()
    bf.set_topology(tu.RingGraph(SIZE))
    plane = health.start(interval=1)
    srv = health.serve(0)
    assert srv is not None
    base = f"http://127.0.0.1:{srv.port}"
    errors = []
    stop = threading.Event()

    def scrape(path):
        while not stop.is_set():
            try:
                raw = urllib.request.urlopen(base + path, timeout=5).read()
                if path != "/metrics":
                    json.loads(raw)
            except Exception as e:  # any non-200 / parse failure
                errors.append((path, repr(e)))
                return

    threads = [
        threading.Thread(target=scrape, args=("/metrics",), daemon=True),
        threading.Thread(target=scrape, args=("/fleet",), daemon=True),
    ]
    for t in threads:
        t.start()
    w = tu.mixing_matrix(bf.load_topology())
    x = np.random.RandomState(0).randn(SIZE, 64)
    for t_step in range(30):
        x = w.T @ x
        d = float(np.sqrt(((x - x.mean(0)) ** 2).sum(1)).mean())
        metrics.gauge("bluefog.gossip.disagreement").set(d)
        plane.observe(ctx, step=t_step, consensus=d)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    srv.close()
    assert not errors, errors
