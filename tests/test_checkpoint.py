# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Checkpoint/resume: a resumed run must continue bit-compatibly.

The reference has no in-framework checkpointing (SURVEY §5); these tests
pin the TPU rebuild's guarantee: save at step k, restore into a fresh
optimizer, and the continued trajectory equals the uninterrupted one —
including window-subsystem device state (buffers, versions, the push-sum
p lane) and the step counter that drives dynamic schedules.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import checkpoint as ckpt
from bluefog_tpu import topology as tu
from bluefog_tpu.collective.plan import schedule_from_dynamic

SIZE = 8
DIM = 3


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    yield
    bf.win_free()
    bf.shutdown()


def targets(seed=0):
    return np.random.RandomState(seed).randn(SIZE, DIM).astype(np.float32)


def grads(params, c):
    return {"w": params["w"] - jnp.asarray(c)}


def test_latest_step_empty(tmp_path):
    assert ckpt.latest_step(str(tmp_path)) is None
    with pytest.raises(FileNotFoundError):
        ckpt.restore(str(tmp_path / "nothing"))


def test_gossip_optimizer_resume_matches_uninterrupted(tmp_path):
    c = targets()
    sched = schedule_from_dynamic(
        SIZE,
        lambda r: tu.GetDynamicOnePeerSendRecvRanks(
            tu.ExponentialGraph(SIZE), r
        ),
    )

    def fresh_opt():
        opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.2))
        opt.schedule = sched  # step-indexed: resume must restore the count
        return opt

    opt = fresh_opt()
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(5):
        params, state = opt.step(params, state, grads(params, c))
    ckpt.save(str(tmp_path), 5, params, state, optimizer=opt)
    # uninterrupted continuation
    p_ref, s_ref = params, state
    for _ in range(5):
        p_ref, s_ref = opt.step(p_ref, s_ref, grads(p_ref, c))

    # resumed continuation in a "new process" (fresh optimizer object)
    opt2 = fresh_opt()
    step, p2, s2 = ckpt.restore(str(tmp_path), optimizer=opt2)
    assert step == 5
    assert opt2._step_count == opt._step_count - 5  # saved mid-run count
    for _ in range(5):
        p2, s2 = opt2.step(p2, s2, grads(p2, c))
    np.testing.assert_allclose(
        np.asarray(p2["w"]), np.asarray(p_ref["w"]), rtol=1e-6, atol=1e-7
    )


def test_window_optimizer_resume_restores_device_state(tmp_path):
    c = targets(1)
    bf.set_topology(tu.RingGraph(SIZE, connect_style=1))

    def run(opt, state, steps):
        for _ in range(steps):
            est = opt.params()
            _, state = opt.step(state, {"w": est["w"] - jnp.asarray(c)})
        return state

    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.1))
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    state = run(opt, state, 4)
    ckpt.save(str(tmp_path), 4, opt.params(), state, optimizer=opt)
    ref_state = run(opt, state, 4)
    ref = np.asarray(opt.params()["w"])
    opt.free()

    opt2 = bf.DistributedPushSumOptimizer(optax.sgd(0.1))
    state2 = opt2.init(params)  # window re-created, then overwritten
    step, _p, state2 = ckpt.restore(str(tmp_path), optimizer=opt2)
    state2 = run(opt2, state2, 4)
    got = np.asarray(opt2.params()["w"])
    opt2.free()
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)


def test_restore_shape_mismatch_raises(tmp_path):
    c = targets(2)
    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1))
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    ckpt.save(str(tmp_path), 1, opt.params(), state, optimizer=opt)
    opt.free()

    opt2 = bf.DistributedWinPutOptimizer(optax.sgd(0.1))
    bigger = {"w": bf.worker_values(lambda r: np.zeros(DIM + 2, np.float32))}
    opt2.init(bigger)
    with pytest.raises(ValueError, match="shape"):
        ckpt.restore(str(tmp_path), optimizer=opt2)
    opt2.free()


def test_latest_step_picks_max(tmp_path):
    c = targets(3)
    opt = bf.DistributedAllreduceOptimizer(optax.sgd(0.1))
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for s in (1, 3, 10, 7):
        ckpt.save(str(tmp_path), s, params, state, optimizer=opt)
    assert ckpt.latest_step(str(tmp_path)) == 10
    step, _, _ = ckpt.restore(str(tmp_path))
    assert step == 10


def test_saving_freed_window_optimizer_refuses(tmp_path):
    c = targets(4)
    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1))
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    saved_params = opt.params()
    opt.free()
    with pytest.raises(ValueError, match="no live window"):
        ckpt.save(str(tmp_path), 1, saved_params, state, optimizer=opt)


def test_restore_without_saved_optimizer_state_refuses(tmp_path):
    """A checkpoint saved WITHOUT optimizer= lacks the step counter and
    window lanes; restoring it INTO an optimizer must refuse rather than
    silently resume divergently."""
    c = targets(5)
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    params, state = opt.step(params, state, grads(params, c))
    ckpt.save(str(tmp_path), 1, params, state)  # no optimizer=
    with pytest.raises(ValueError, match="step counter"):
        ckpt.restore(str(tmp_path), optimizer=opt)

    wopt = bf.DistributedWinPutOptimizer(optax.sgd(0.1))
    wstate = wopt.init(params)
    ckpt.save(str(tmp_path / "w"), 1, wopt.params(), wstate)
    wopt2 = bf.DistributedWinPutOptimizer(optax.sgd(0.1))
    wopt2.init(params)
    # window optimizers have no _step_count; the window check must fire
    with pytest.raises(ValueError, match="window state"):
        ckpt.restore(str(tmp_path / "w"), optimizer=wopt2)
    wopt.free(); wopt2.free()


def test_ef_compression_state_resumes_bit_compatibly(tmp_path):
    """int8_ef CHOCO copies survive save/restore: the resumed trajectory
    equals the uninterrupted one exactly."""
    c = targets(6)
    zero = {"w": jnp.zeros((SIZE, DIM), jnp.float32)}

    def fresh_opt():
        opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
        opt.compression = "int8_ef"
        return opt

    opt = fresh_opt()
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(5):
        params, state = opt.step(params, state, zero)
    ckpt.save(str(tmp_path), 5, params, state, optimizer=opt)
    p_ref, s_ref = params, state
    for _ in range(5):
        p_ref, s_ref = opt.step(p_ref, s_ref, zero)

    opt2 = fresh_opt()
    s2_init = opt2.init(params)  # no priming step needed: restore installs
    step, p2, s2 = ckpt.restore(str(tmp_path), optimizer=opt2)
    for _ in range(5):
        p2, s2 = opt2.step(p2, s2, zero)
    np.testing.assert_array_equal(
        np.asarray(p2["w"]), np.asarray(p_ref["w"])
    )


def test_ef_restore_from_other_topology_safely_rezeros(tmp_path):
    """EF copies saved under one edge set must NOT survive into a
    different one (stale replicas would corrupt the combine); the
    optimizer's signature check zero-rebuilds them on the next step and
    consensus still holds."""
    c = targets(7)
    zero = {"w": jnp.zeros((SIZE, DIM), jnp.float32)}
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    opt.compression = "int8_ef"
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(5):
        params, state = opt.step(params, state, zero)
    ckpt.save(str(tmp_path), 5, params, state, optimizer=opt)

    opt2 = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    opt2.compression = "int8_ef"
    # different (connected) edge set than the default Exp topology
    opt2.self_weight = 1.0 / 3.0
    opt2.src_weights = [
        {(r - 1) % SIZE: 1 / 3, (r + 1) % SIZE: 1 / 3} for r in range(SIZE)
    ]
    opt2.dst_weights = [[(r - 1) % SIZE, (r + 1) % SIZE] for r in range(SIZE)]
    state2 = opt2.init(params)
    step, p2, state2 = ckpt.restore(str(tmp_path), optimizer=opt2)
    installed = opt2._ef
    for _ in range(80):
        p2, state2 = opt2.step(p2, state2, zero)
    assert opt2._ef is not installed  # sig mismatch -> rebuilt
    w = np.asarray(p2["w"])
    np.testing.assert_allclose(w, np.tile(w.mean(0), (SIZE, 1)), atol=5e-3)


def test_num_steps_per_communication_resume_exact(tmp_path):
    """A K>1 optimizer saved MID-accumulation-cycle resumes exactly: the
    communication-phase counter and (for gradient order) the pending
    gradient sum both ride the checkpoint."""
    c = targets(9)
    nonzero = {"w": bf.worker_values(
        lambda r: np.full((DIM,), 0.5 + r, np.float32)
    )}

    def run(opt, params, state, n, path=None, save_at=None):
        for i in range(n):
            params, state = opt.step(params, state, nonzero)
            if save_at is not None and i + 1 == save_at:
                ckpt.save(str(path), i + 1, params, state, optimizer=opt)
        return params, state

    for factory in (
        lambda: bf.DistributedGradientAllreduceOptimizer(
            optax.sgd(0.1), num_steps_per_communication=3
        ),
        lambda: bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.1), num_steps_per_communication=3
        ),
    ):
        path = tmp_path / factory().__class__.__name__
        # uninterrupted: 4 + 5 steps (save lands mid-cycle: 4 % 3 != 0)
        opt = factory()
        params = {"w": bf.worker_values(lambda r: c[r])}
        state = opt.init(params)
        params, state = run(opt, params, state, 4, path, save_at=4)
        p_ref, s_ref = run(opt, params, state, 5)

        opt2 = factory()
        params2 = {"w": bf.worker_values(lambda r: c[r])}
        state2 = opt2.init(params2)
        step, p2, s2 = ckpt.restore(str(path), optimizer=opt2)
        assert step == 4
        assert opt2._step_count == 4 and opt2._comm_count == 1
        p2, s2 = run(opt2, p2, s2, 5)
        np.testing.assert_array_equal(
            np.asarray(p_ref["w"]), np.asarray(p2["w"])
        )


# -- graph-shape guard (elastic integration) ----------------------------------


def test_checkpoint_records_topology_version_and_world_size(tmp_path):
    params = {"w": bf.worker_values(lambda r: targets()[r])}
    ckpt.save(str(tmp_path), 1, params, {})
    import ast

    payload = ckpt._checkpointer().restore(
        str(tmp_path / "1")
    )
    info = ast.literal_eval(str(payload["graph_info"]))
    ctx = bf.get_context()
    assert info["world_size"] == SIZE
    assert info["topo_version"] == ctx.topo_version
    assert info["topo_digest"] == ckpt.topology_digest(ctx.load_topology())
    assert info["live_ranks"] == list(range(SIZE))


def test_restore_world_size_mismatch_raises(tmp_path, cpu_devices):
    params = {"w": bf.worker_values(lambda r: targets()[r])}
    ckpt.save(str(tmp_path), 1, params, {})
    bf.init(devices=cpu_devices[:4])
    with pytest.raises(ValueError, match="8-worker mesh.*4 workers"):
        ckpt.restore(str(tmp_path))


def test_restore_topology_mismatch_raises_clear_message(tmp_path):
    """Restoring window/plan state shaped for a different graph must be
    an explicit refusal, not a silent load."""
    params = {"w": bf.worker_values(lambda r: targets()[r])}
    ckpt.save(str(tmp_path), 1, params, {})
    bf.set_topology(tu.RingGraph(SIZE))
    with pytest.raises(ValueError, match="set_topology|elastic"):
        ckpt.restore(str(tmp_path))
    # reinstalling the matching topology unblocks the restore
    bf.set_topology(tu.ExponentialGraph(SIZE))
    step, p, s = ckpt.restore(str(tmp_path))
    assert step == 1


def test_restore_live_set_mismatch_repairs_under_elastic(tmp_path):
    """With an elastic session active, a checkpoint recorded under a
    reduced live set repairs the topology instead of refusing."""
    session = bf.elastic.start()
    session.inject("kill", rank=3, step=0)
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    session.before_dispatch(opt)  # triggers the repair to 7 survivors
    assert session.repairs
    params = {"w": bf.worker_values(lambda r: targets()[r])}
    ckpt.save(str(tmp_path), 2, params, {})
    bf.elastic.stop()

    # fresh context: full membership, pristine topology
    bf.init(devices=bf.get_context().devices)
    session2 = bf.elastic.start()
    step, p, s = ckpt.restore(str(tmp_path))
    assert step == 2
    assert session2.membership.dead_ranks() == (3,)
    assert session2.repairs  # topology repaired to the saved live set
    bf.elastic.stop()


def test_restore_pre_graph_info_checkpoint_still_loads(tmp_path):
    """Checkpoints from before the graph-info block restore untouched
    (no spurious refusal on legacy data)."""
    params = {"w": bf.worker_values(lambda r: targets()[r])}
    target = ckpt.save(str(tmp_path), 3, params, {})
    # simulate a legacy checkpoint by stripping the block (and the
    # graph-info sidecar that now also carries it)
    payload = ckpt._checkpointer().restore(target)
    payload.pop("graph_info", None)
    import os as _os
    import shutil

    shutil.rmtree(target)
    _os.remove(str(tmp_path / "3.graph.json"))
    ckpt._checkpointer().save(target, payload, force=True)
    bf.set_topology(tu.RingGraph(SIZE))  # would mismatch, if recorded
    step, p, s = ckpt.restore(str(tmp_path))
    assert step == 3


def test_restore_superset_live_set_revives_under_elastic(tmp_path):
    """A checkpoint saved while everyone was alive, restored into a
    session that has since condemned a rank: the checkpoint's membership
    is the source of truth, so the rank is revived and the topology
    repaired back — not silently skipped."""
    params = {"w": bf.worker_values(lambda r: targets()[r])}
    ckpt.save(str(tmp_path), 1, params, {})  # full 8-rank live set

    session = bf.elastic.start()
    session.inject("kill", rank=2, step=0)
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    session.before_dispatch(opt)
    assert session.membership.dead_ranks() == (2,)
    digest_dead = ckpt.topology_digest(bf.get_context().load_topology())

    step, p, s = ckpt.restore(str(tmp_path))
    assert step == 1
    assert session.membership.dead_ranks() == ()
    # the repaired-back topology matches the full live set again
    assert ckpt.topology_digest(
        bf.get_context().load_topology()
    ) != digest_dead
    bf.elastic.stop()


# -- weight-update sharding (BLUEFOG_SHARD, docs/sharding.md) ----------------


SHARD_DIM = 1100


def _shard_grad_run(monkeypatch, steps, params=None, state=None, opt=None):
    monkeypatch.setenv("BLUEFOG_SHARD", "1")
    c = np.random.RandomState(7).randn(SIZE, SHARD_DIM).astype(np.float32)
    if opt is None:
        opt = bf.DistributedGradientAllreduceOptimizer(optax.adam(0.02))
        params = {"w": bf.worker_values(
            lambda r: np.zeros(SHARD_DIM, np.float32)
        )}
        state = opt.init(params)
    for _ in range(steps):
        params, state = opt.step(
            params, state, {"w": params["w"] - jnp.asarray(c)}
        )
    return opt, params, state, c


def test_sharded_checkpoint_resume_bit_exact(tmp_path, monkeypatch):
    """Gather-on-save: the sharded state round-trips through the
    layout-independent checkpoint form and the resumed trajectory is
    bit-exact against the uninterrupted one."""
    opt, params, state, c = _shard_grad_run(monkeypatch, 3)
    ckpt.save(str(tmp_path), 3, params, state, optimizer=opt)
    # the payload's state leaves are FULL vectors, not slot rows
    p_ref, s_ref = params, state
    for _ in range(3):
        p_ref, s_ref = opt.step(
            p_ref, s_ref, {"w": p_ref["w"] - jnp.asarray(c)}
        )
    opt2 = bf.DistributedGradientAllreduceOptimizer(optax.adam(0.02))
    step, p2, s2 = ckpt.restore(str(tmp_path), optimizer=opt2)
    assert step == 3
    from bluefog_tpu import sharding

    assert isinstance(s2, sharding.ShardedOptState)
    for _ in range(3):
        p2, s2 = opt2.step(p2, s2, {"w": p2["w"] - jnp.asarray(c)})
    np.testing.assert_array_equal(
        np.asarray(p2["w"]), np.asarray(p_ref["w"])
    )


def test_sharded_checkpoint_refusals(tmp_path, monkeypatch):
    """Mismatch = refusal with the reason, never a silent re-layout:
    sharded checkpoint + sharding off, replicated checkpoint + sharding
    on, and a flipped master knob all fail with clear messages."""
    opt, params, state, _c = _shard_grad_run(monkeypatch, 1)
    ckpt.save(str(tmp_path / "sharded"), 1, params, state, optimizer=opt)
    monkeypatch.setenv("BLUEFOG_SHARD", "0")
    opt_off = bf.DistributedGradientAllreduceOptimizer(optax.adam(0.02))
    with pytest.raises(ValueError, match="BLUEFOG_SHARD=1"):
        ckpt.restore(str(tmp_path / "sharded"), optimizer=opt_off)
    # replicated checkpoint, shard-active restore
    state_off = opt_off.init(params)
    ckpt.save(str(tmp_path / "plain"), 1, params, state_off,
              optimizer=opt_off)
    monkeypatch.setenv("BLUEFOG_SHARD", "1")
    opt_on = bf.DistributedGradientAllreduceOptimizer(optax.adam(0.02))
    with pytest.raises(ValueError, match="REPLICATED"):
        ckpt.restore(str(tmp_path / "plain"), optimizer=opt_on)
    # master-knob flip
    monkeypatch.setenv("BLUEFOG_SHARD_MASTER", "1")
    opt_m = bf.DistributedGradientAllreduceOptimizer(optax.adam(0.02))
    with pytest.raises(ValueError, match="SHARD_MASTER"):
        ckpt.restore(str(tmp_path / "sharded"), optimizer=opt_m)


def test_restore_prevalidates_graph_before_allocating(tmp_path,
                                                      monkeypatch):
    """The elastic-repair ride-along bugfix: a live-set/world mismatch
    must fail on the graph-info SIDECAR — before orbax materializes a
    single state buffer — with the clear message, not a shape error
    mid-restore."""
    params = {"w": bf.worker_values(lambda r: targets()[r])}
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    state = opt.init(params)
    ckpt.save(str(tmp_path), 2, params, state, optimizer=opt)
    import os as _os

    assert _os.path.exists(str(tmp_path / "2.graph.json"))
    bf.shutdown()
    bf.init(devices=jax.devices("cpu")[:4])  # wrong world size

    def boom():
        raise AssertionError(
            "orbax restore ran before graph validation — state buffers "
            "were allocated for a mismatched graph"
        )

    monkeypatch.setattr(ckpt, "_checkpointer", boom)
    opt2 = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    with pytest.raises(ValueError, match="8-worker mesh"):
        ckpt.restore(str(tmp_path), optimizer=opt2)


def test_restore_without_sidecar_still_validates(tmp_path):
    """Checkpoints predating the sidecar keep the post-load check."""
    params = {"w": bf.worker_values(lambda r: targets()[r])}
    ckpt.save(str(tmp_path), 1, params, {})
    import os as _os

    _os.remove(str(tmp_path / "1.graph.json"))
    bf.shutdown()
    bf.init(devices=jax.devices("cpu")[:4])
    with pytest.raises(ValueError, match="8-worker mesh"):
        ckpt.restore(str(tmp_path))
