# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""XLA-fusion smoke tests: multi-leaf gossip must not emit per-leaf wires.

Analogue of the reference's fusion coverage
(``test/torch_ops_test.py:960`` ``test_neighbor_allreduce_fusion_alot``,
backed by the fusion buffer ``tensor_queue.h:75-124``): there the proof is
wire-level; here the whole step is one compiled program, so the proof is
counting ``collective-permute`` instructions in the optimized HLO. A
multi-leaf optimizer step must emit O(rounds) collectives (one payload per
round per dtype group), not O(leaves x rounds).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import scaling
from bluefog_tpu import context as ctx_mod
from bluefog_tpu import topology as tu

SIZE = 8
N_LEAVES = 6
ROUNDS = 3  # ExponentialTwoGraph(8) lowers to log2(8) ppermute rounds


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    bf.set_topology(tu.ExponentialTwoGraph(SIZE))
    yield
    bf.shutdown()


def _compiled_step_hlo(opt, params, state, grads):
    """Lower the optimizer's cached compiled step for these avals."""
    ctx = ctx_mod.get_context()
    gossip_key, gossip_fn, wops = opt._gossip_key_and_fn(ctx)
    step_idx = jnp.asarray([0], jnp.int32)
    opt.step(params, state, grads)  # populate the compiled-step cache
    fns = [
        v
        for k, v in ctx.op_cache.items()
        if isinstance(k, tuple) and k and k[0] == "opt_step"
    ]
    assert len(fns) == 1
    return (
        fns[0]
        .lower(params, state, grads, step_idx, wops, ())
        .compile()
        .as_text()
    )


def make_tree(dtype=np.float32):
    return {
        f"w{i}": bf.worker_values(
            lambda r: np.full((3,), float(r)), dtype=dtype
        )
        for i in range(N_LEAVES)
    }


def test_atc_step_emits_one_permute_per_round():
    opt = bf.DistributedAdaptThenCombineOptimizer(optax.sgd(0.1))
    params = make_tree()
    state = opt.init(params)
    txt = _compiled_step_hlo(opt, params, state, make_tree())
    stats = scaling.hlo_collective_stats(txt)
    cp = stats.get("collective-permute", {"count": 0})
    # one payload per round — NOT leaves x rounds (= 18)
    assert cp["count"] == ROUNDS, stats


def test_mixed_dtype_tree_packs_per_dtype_group():
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    params = {
        **make_tree(np.float32),
        **{
            f"b{i}": bf.worker_values(
                lambda r: np.full((2,), float(r)), dtype=jnp.bfloat16
            )
            for i in range(3)
        },
    }
    state = opt.init(params)
    txt = _compiled_step_hlo(opt, params, state, params)
    stats = scaling.hlo_collective_stats(txt)
    cp = stats.get("collective-permute", {"count": 0})
    # two dtype groups x ROUNDS; bf16 wires stay bf16 (2-byte payloads)
    assert cp["count"] == 2 * ROUNDS, stats
    assert "bf16[" in txt


def test_gradient_allreduce_packs_leaves():
    opt = bf.DistributedGradientAllreduceOptimizer(optax.sgd(0.1))
    params = make_tree()
    state = opt.init(params)
    txt = _compiled_step_hlo(opt, params, state, make_tree())
    stats = scaling.hlo_collective_stats(txt)
    ar = stats.get("all-reduce", {"count": 0})
    # one packed psum for all six gradient leaves (+none hidden elsewhere)
    assert ar["count"] == 1, stats


def test_packed_step_still_converges():
    """Packing must not change the math: same consensus fixed point."""
    c = np.random.RandomState(0).randn(SIZE, 4).astype(np.float32)
    # decaying step size: constant-lr CTA keeps a steady-state residual
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(optax.exponential_decay(0.3, 10, 0.5))
    )
    params = {
        "a": bf.worker_values(lambda r: c[r, :2]),
        "b": bf.worker_values(lambda r: c[r, 2:]),
    }
    state = opt.init(params)
    for _ in range(50):
        grads = {
            "a": params["a"] - jnp.asarray(c[:, :2]),
            "b": params["b"] - jnp.asarray(c[:, 2:]),
        }
        params, state = opt.step(params, state, grads)
    w = np.concatenate(
        [np.asarray(params["a"]), np.asarray(params["b"])], -1
    )
    np.testing.assert_allclose(w, c.mean(0)[None].repeat(SIZE, 0), atol=0.1)
