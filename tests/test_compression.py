# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""int8-quantized gossip: 4x fewer wire bytes, bounded error, converges.

Beyond-reference capability (EQuARX-style quantized collectives lifted to
the gossip setting): the wire payload of every ppermute round is int8
with a rider scale; the HLO-level byte accounting proves the 4x claim
and the optimizer tests prove training still reaches consensus.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import scaling
from bluefog_tpu import topology as tu
from bluefog_tpu.collective import inner, plan as planlib
from jax.sharding import NamedSharding, PartitionSpec as P

SIZE = 8


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    yield
    bf.shutdown()


def test_quantized_combine_close_to_exact():
    bf.set_topology(tu.RingGraph(SIZE))
    x = np.random.RandomState(0).randn(SIZE, 64).astype(np.float32)
    exact = np.asarray(bf.neighbor_allreduce(x))
    quant = np.asarray(bf.neighbor_allreduce(x, compression="int8"))
    # error bounded by the neighbor weight mass * one quantization step
    step = np.abs(x).max(axis=1, keepdims=True) / 127.0
    assert np.abs(quant - exact).max() < 1.5 * step.max()
    assert not np.array_equal(quant, exact)  # it IS quantized


def test_consensus_is_fixed_point():
    """All-equal state must be exactly preserved (self term full
    precision + identical payloads)."""
    bf.set_topology(tu.RingGraph(SIZE))
    x = np.tile(np.random.RandomState(1).randn(1, 16), (SIZE, 1)).astype(
        np.float32
    )
    out = np.asarray(bf.neighbor_allreduce(x, compression="int8"))
    np.testing.assert_allclose(out, x, rtol=1e-6, atol=1e-7)


def test_wire_bytes_are_one_quarter():
    """HLO proof of the 4x: the quantized program's collective-permute
    payloads are int8 (+ a scalar scale) vs the f32 baseline."""
    D = 4096
    plan = planlib.plan_from_topology(tu.RingGraph(SIZE), weighted=True)
    mesh = bf.get_context().mesh
    spec = P("workers")

    def lower(combine):
        fn = jax.jit(
            jax.shard_map(
                lambda t: combine(t, plan, "workers"),
                mesh=mesh, in_specs=spec, out_specs=spec,
            )
        )
        x = jax.device_put(
            jnp.zeros((SIZE, D), jnp.float32), NamedSharding(mesh, spec)
        )
        return scaling.hlo_collective_stats(fn.lower(x).compile().as_text())

    base = lower(inner.weighted_combine)["collective-permute"]
    quant = lower(inner.weighted_combine_quantized)["collective-permute"]
    assert base["bytes"] == 2 * D * 4  # 2 ring rounds, f32
    # int8 payload + per-512-chunk f32 scales (~0.8% of payload) per round
    assert quant["bytes"] <= int(base["bytes"] // 4 * 1.05), (base, quant)


def test_optimizer_with_compression_converges():
    c = np.random.RandomState(2).randn(SIZE, 4).astype(np.float32)
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(optax.exponential_decay(0.3, 10, 0.5))
    )
    opt.compression = "int8"
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(60):
        grads = {"w": params["w"] - jnp.asarray(c)}
        params, state = opt.step(params, state, grads)
    w = np.asarray(params["w"])
    target = c.mean(0)
    start_spread = np.abs(c - target).max()
    assert np.abs(w - target).max() < 0.15 * start_spread
    assert np.abs(w - w.mean(0)).max() < 0.1


def test_bad_compression_rejected():
    x = bf.worker_values(lambda r: np.ones(4, np.float32))
    with pytest.raises(ValueError, match="int8"):
        bf.neighbor_allreduce(x, compression="fp4")
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    opt.compression = "fp4"
    params = {"w": x}
    state = opt.init(params)
    with pytest.raises(ValueError, match="int8"):
        opt.step(params, state, params)


def test_fp16_all_zero_no_nan():
    """The f32 scale floor: an all-zero fp16 tensor must combine to
    zeros, not NaN (fp16 would flush a tiny f32 literal to 0)."""
    bf.set_topology(tu.RingGraph(SIZE))
    x = bf.worker_values(lambda r: np.zeros(8, np.float16))
    out = np.asarray(bf.neighbor_allreduce(x, compression="int8"),
                     np.float32)
    assert np.isfinite(out).all() and (out == 0).all()


def test_non_normalized_weights_refused():
    """Push-sum-style column-stochastic weights break the difference
    form's algebra (silent O(x) error); they must be refused."""
    sw = 0.8
    srcs = [{(r - 1) % SIZE: 0.8} for r in range(SIZE)]  # sums to 1.6
    x = bf.worker_values(lambda r: np.ones(4, np.float32))
    with pytest.raises(ValueError, match="normalized"):
        bf.neighbor_allreduce(x, self_weight=sw, src_weights=srcs,
                              compression="int8")


def test_compression_refused_off_static_path():
    """opt.compression must raise, not silently no-op, on paths that do
    not support it (schedules / allreduce)."""
    from bluefog_tpu.collective.plan import schedule_from_dynamic

    x = bf.worker_values(lambda r: np.ones(4, np.float32))
    params = {"w": x}

    opt = bf.DistributedAllreduceOptimizer(optax.sgd(0.1))
    opt.compression = "int8"
    state = opt.init(params)
    with pytest.raises(ValueError, match="static-plan"):
        opt.step(params, state, params)

    opt2 = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    opt2.compression = "int8"
    opt2.schedule = schedule_from_dynamic(
        SIZE,
        lambda r: tu.GetDynamicOnePeerSendRecvRanks(
            tu.ExponentialGraph(SIZE), r
        ),
    )
    state2 = opt2.init(params)
    with pytest.raises(ValueError, match="static-plan"):
        opt2.step(params, state2, params)


def test_compressed_varying_weights_single_program():
    """Per-step weight changes with compression reuse ONE compiled
    program (operand-keyed, same guarantee as the exact path)."""
    ctx = bf.get_context()
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    opt.compression = "int8"
    c = np.random.RandomState(3).randn(SIZE, 4).astype(np.float32)
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    before = None
    for i in range(6):
        wv = 0.4 + 0.02 * i  # same ring EDGES, different weight VALUES
        opt.self_weight = 1.0 - wv
        opt.src_weights = [{(r - 1) % SIZE: wv} for r in range(SIZE)]
        opt.dst_weights = [[(r + 1) % SIZE] for r in range(SIZE)]
        params, state = opt.step(params, state,
                                 {"w": params["w"] - jnp.asarray(c)})
        if i == 0:
            before = len(ctx.op_cache)
    assert len(ctx.op_cache) == before  # no recompiles across weights


def test_hierarchical_compression_converges(cpu_devices):
    """int8 on the machine-level (DCN) leg: intra-host psum exact,
    cross-host gossip quantized; training still reaches consensus."""
    bf.shutdown()
    bf.init(devices=cpu_devices[:SIZE], nodes_per_machine=4)
    bf.set_machine_topology(tu.RingGraph(2))
    c = np.random.RandomState(4).randn(SIZE, 4).astype(np.float32)
    opt = bf.DistributedHierarchicalNeighborAllreduceOptimizer(
        optax.sgd(optax.exponential_decay(0.3, 10, 0.5))
    )
    opt.compression = "int8"
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(60):
        params, state = opt.step(params, state,
                                 {"w": params["w"] - jnp.asarray(c)})
    w = np.asarray(params["w"])
    target = c.mean(0)
    assert np.abs(w - target).max() < 0.15 * np.abs(c - target).max()
    assert np.abs(w - w.mean(0)).max() < 0.1


def test_bf16_wire_close_and_half_bytes():
    """compression='bf16': near-lossless, half the wire bytes."""
    bf.set_topology(tu.RingGraph(SIZE))
    x = np.random.RandomState(5).randn(SIZE, 64).astype(np.float32)
    exact = np.asarray(bf.neighbor_allreduce(x))
    half = np.asarray(bf.neighbor_allreduce(x, compression="bf16"))
    assert np.abs(half - exact).max() < 0.02  # bf16 mantissa error
    # consensus fixed point holds for bf16 too
    c = np.tile(x[:1], (SIZE, 1))
    out = np.asarray(bf.neighbor_allreduce(c, compression="bf16"))
    np.testing.assert_allclose(out, c, rtol=1e-6, atol=1e-7)

    D = 4096
    plan = planlib.plan_from_topology(tu.RingGraph(SIZE), weighted=True)
    mesh = bf.get_context().mesh
    spec = P("workers")
    fn = jax.jit(
        jax.shard_map(
            lambda t: inner.weighted_combine_quantized_operands(
                t, plan.perms,
                jnp.asarray(plan.weight_operands()[1]), "workers",
                wire="bf16",
            ),
            mesh=mesh, in_specs=spec, out_specs=spec,
        )
    )
    xd = jax.device_put(jnp.zeros((SIZE, D), jnp.float32),
                        NamedSharding(mesh, spec))
    # the EMITTED program carries bf16 on the wire (the CPU backend then
    # legalizes bf16 collectives by widening to f32 — visible only in its
    # optimized HLO; TPU moves bf16 natively). Bind the assertion to the
    # collective op's own operand/result types in the lowering.
    import re

    lowered = fn.lower(xd).as_text()
    cp_lines = [l for l in lowered.splitlines()
                if "collective_permute" in l]
    assert cp_lines, lowered[:2000]
    for line in cp_lines:
        assert re.search(r"tensor<1x4096xbf16>\)?\s*->", line), line


def test_bf16_optimizer_converges():
    c = np.random.RandomState(6).randn(SIZE, 4).astype(np.float32)
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(optax.exponential_decay(0.3, 10, 0.5))
    )
    opt.compression = "bf16"
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(60):
        params, state = opt.step(params, state,
                                 {"w": params["w"] - jnp.asarray(c)})
    w = np.asarray(params["w"])
    assert np.abs(w - c.mean(0)).max() < 0.1 * np.abs(c - c.mean(0)).max()


def test_bf16_wire_fp16_extremes_finite():
    """fp16 values near the fp16 max must survive the bf16 wire: the
    difference arithmetic runs in f32 (bf16 rounds 65504 to 65536, which
    is inf in fp16)."""
    bf.set_topology(tu.RingGraph(SIZE))
    x = bf.worker_values(lambda r: np.full(8, 65504.0, np.float16))
    out = np.asarray(bf.neighbor_allreduce(x, compression="bf16"),
                     np.float32)
    assert np.isfinite(out).all()
    np.testing.assert_allclose(out, 65504.0, rtol=1e-3)


def test_unknown_wire_raises_in_inner():
    with pytest.raises(ValueError, match="wire"):
        inner.weighted_combine_quantized_operands(
            jnp.ones((4,)), (), jnp.zeros((0, SIZE)), "workers",
            wire="fp4",
        )


def test_error_feedback_removes_constant_lr_noise_floor():
    """Plain int8 gossip stalls at a quantization noise floor; error
    feedback (int8_ef) keeps shrinking the consensus residual — the
    reason the EF variant exists. Pure consensus (zero gradients)
    isolates the floor from the CTA constant-lr bias."""
    c = np.random.RandomState(7).randn(SIZE, 64).astype(np.float32) * 5.0
    zero = {"w": jnp.zeros((SIZE, 64), jnp.float32)}

    def run(compression):
        opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
        opt.compression = compression
        params = {"w": bf.worker_values(lambda r: c[r])}
        state = opt.init(params)
        for _ in range(150):
            params, state = opt.step(params, state, zero)
        w = np.asarray(params["w"])
        return np.abs(w - w.mean(0)).max()

    spread_plain = run("int8")
    spread_ef = run("int8_ef")
    assert spread_ef < 0.1 * spread_plain, (spread_plain, spread_ef)
    assert spread_ef < 1e-3


def test_error_feedback_single_program():
    ctx = bf.get_context()
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    opt.compression = "int8_ef"
    c = np.random.RandomState(8).randn(SIZE, 6).astype(np.float32)
    params = {"a": bf.worker_values(lambda r: c[r, :3]),
              "b": bf.worker_values(lambda r: c[r, 3:])}
    state = opt.init(params)
    before = None
    for i in range(5):
        params, state = opt.step(params, state,
                                 {"a": params["a"], "b": params["b"]})
        if i == 0:
            before = len(ctx.op_cache)
    assert len(ctx.op_cache) == before


def test_error_feedback_restricted_paths():
    opt = bf.DistributedAllreduceOptimizer(optax.sgd(0.1))
    opt.compression = "int8_ef"
    params = {"w": bf.worker_values(lambda r: np.ones(4, np.float32))}
    state = opt.init(params)
    with pytest.raises(ValueError, match="int8_ef"):
        opt.step(params, state, params)


def test_ef_state_resets_on_topology_change():
    """Dynamic weight reassignment changes the per-round sources; stale
    CHOCO copies would break the bit-identical-replica invariant, so the
    EF state must be rebuilt (and training stays correct through the
    change)."""
    c = np.random.RandomState(9).randn(SIZE, 16).astype(np.float32)
    zero = {"w": jnp.zeros((SIZE, 16), jnp.float32)}
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    opt.compression = "int8_ef"
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(10):
        params, state = opt.step(params, state, zero)
    ef_before = opt._ef
    # move to a ring (different edge set, 2 rounds)
    opt.self_weight = 1.0 / 3.0
    opt.src_weights = [
        {(r - 1) % SIZE: 1 / 3, (r + 1) % SIZE: 1 / 3} for r in range(SIZE)
    ]
    opt.dst_weights = [[(r - 1) % SIZE, (r + 1) % SIZE] for r in range(SIZE)]
    for _ in range(60):
        params, state = opt.step(params, state, zero)
    assert opt._ef is not ef_before  # rebuilt for the new structure
    w = np.asarray(params["w"])
    np.testing.assert_allclose(w, np.tile(c.mean(0), (SIZE, 1)), atol=5e-3)


def test_hierarchical_rejects_int8_ef(cpu_devices):
    bf.shutdown()
    bf.init(devices=cpu_devices[:SIZE], nodes_per_machine=4)
    bf.set_machine_topology(tu.RingGraph(2))
    opt = bf.DistributedHierarchicalNeighborAllreduceOptimizer(
        optax.sgd(0.1)
    )
    opt.compression = "int8_ef"
    params = {"w": bf.worker_values(lambda r: np.ones(4, np.float32))}
    state = opt.init(params)
    with pytest.raises(ValueError, match="int8_ef"):
        opt.step(params, state, params)


# -- the int4 tier (block-scaled nibble-packed, bf16 scales) ------------------


@pytest.mark.parametrize("n", [1, 511, 512, 513])
def test_int4_pack_unpack_roundtrip_oracle(n):
    """Numpy oracle for the nibble wire at every 512-block remainder
    width: quantize -> pack -> unpack -> dequantize on device must equal
    the host replica bit for bit, and pack/unpack must round-trip every
    int4 value exactly."""
    from bluefog_tpu import metrics

    rng = np.random.RandomState(n)
    x = (rng.randn(n) * 3).astype(np.float32)

    dev_q, dev_s, dev_xhat = jax.jit(inner._chunk_quantize4)(
        jnp.asarray(x)
    )
    # host replica reconstructs through the packed wire format
    np.testing.assert_array_equal(
        np.asarray(dev_xhat), metrics._np_chunk_quantize4(x)
    )
    # receivers reconstruct from the PACKED bits: bitwise the sender's
    # own xhat (the property the difference form and EF copies rely on)
    recon = jax.jit(lambda q, s: inner._dequant4(q, s, n))(dev_q, dev_s)
    np.testing.assert_array_equal(np.asarray(recon), np.asarray(dev_xhat))
    # pack/unpack is exact for every representable nibble value
    n_chunks = -(-n // 512)
    q_all = rng.randint(-7, 8, size=(n_chunks, 512)).astype(np.int8)
    rt = np.asarray(
        inner._unpack_nibbles(inner._pack_nibbles(jnp.asarray(q_all)))
    )
    np.testing.assert_array_equal(rt, q_all)
    hostrt = metrics._np_unpack_nibbles(metrics._np_pack_nibbles(q_all))
    np.testing.assert_array_equal(hostrt, q_all)


def test_int4_combine_close_and_fixed_point():
    bf.set_topology(tu.RingGraph(SIZE))
    x = np.random.RandomState(20).randn(SIZE, 700).astype(np.float32)
    exact = np.asarray(bf.neighbor_allreduce(x))
    quant = np.asarray(bf.neighbor_allreduce(x, compression="int4"))
    step = np.abs(x).max(axis=1, keepdims=True) / 7.0
    assert np.abs(quant - exact).max() < 1.5 * step.max()
    assert not np.array_equal(quant, exact)
    # consensus is an exact fixed point (difference form)
    c = np.tile(x[:1], (SIZE, 1))
    out = np.asarray(bf.neighbor_allreduce(c, compression="int4"))
    np.testing.assert_allclose(out, c, rtol=1e-6, atol=1e-7)


def test_int4_wire_bytes_are_one_eighth_and_2x_vs_int8():
    """HLO proof of the 8x-vs-f32 / 2x-vs-int8 claims: packed nibbles +
    bf16 block scales. The byte accounting (scale sidecar included) is
    exactly 2x at every payload width; the CPU backend's optimized HLO
    widens the bf16 scale sidecar to f32 (its collective legalization,
    same as the bf16 wire — TPU ships it natively), so the HLO-counted
    ratio is bounded slightly under 2."""
    D = 4096
    plan = planlib.plan_from_topology(tu.RingGraph(SIZE), weighted=True)
    mesh = bf.get_context().mesh
    spec = P("workers")

    def lower(wire):
        import functools

        combine = (
            inner.weighted_combine if wire is None
            else functools.partial(
                inner.weighted_combine_quantized, wire=wire
            )
        )
        fn = jax.jit(
            jax.shard_map(
                lambda t: combine(t, plan, "workers"),
                mesh=mesh, in_specs=spec, out_specs=spec,
            )
        )
        x = jax.device_put(
            jnp.zeros((SIZE, D), jnp.float32), NamedSharding(mesh, spec)
        )
        return scaling.hlo_collective_stats(
            fn.lower(x).compile().as_text()
        )["collective-permute"]

    base, q8, q4 = lower(None), lower("int8"), lower("int4")
    assert q4["bytes"] <= int(base["bytes"] // 8 * 1.05), (base, q4)
    assert q8["bytes"] / q4["bytes"] > 1.9, (q8, q4)
    # the accounting (what the chooser and the evidence price) is exact
    for n in (1, 511, 512, 513, D):
        assert scaling.wire_payload_bytes(n, 4, "int8") == (
            2 * scaling.wire_payload_bytes(n, 4, "int4")
        ), n


def test_int4_scales_ride_bf16_on_the_wire():
    """The lowering ships the block scales as bf16 (the sidecar that
    preserves the full 2x vs int8); bind to the emitted collective's
    own types like the bf16-wire test."""
    import re

    D = 4096
    plan = planlib.plan_from_topology(tu.RingGraph(SIZE), weighted=True)
    mesh = bf.get_context().mesh
    spec = P("workers")
    fn = jax.jit(
        jax.shard_map(
            lambda t: inner.weighted_combine_quantized(
                t, plan, "workers", wire="int4"
            ),
            mesh=mesh, in_specs=spec, out_specs=spec,
        )
    )
    xd = jax.device_put(jnp.zeros((SIZE, D), jnp.float32),
                        NamedSharding(mesh, spec))
    lowered = fn.lower(xd).as_text()
    cp_types = re.findall(
        r"collective_permute.*?->\s*tensor<([^>]+)>", lowered
    )
    assert any("i8" in t and "256" in t for t in cp_types), cp_types
    assert any("bf16" in t for t in cp_types), cp_types


def test_int4_optimizer_converges():
    c = np.random.RandomState(21).randn(SIZE, 4).astype(np.float32)
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(optax.exponential_decay(0.3, 10, 0.5))
    )
    opt.compression = "int4"
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(60):
        params, state = opt.step(params, state,
                                 {"w": params["w"] - jnp.asarray(c)})
    w = np.asarray(params["w"])
    target = c.mean(0)
    assert np.abs(w - target).max() < 0.2 * np.abs(c - target).max()


def test_int4_ef_removes_int4_noise_floor():
    """Plain int4's quantization floor is far coarser than int8's; the
    CHOCO error-feedback tier erases it the same way int8_ef erases
    int8's — the fact that makes a 4-bit wire trajectory-safe."""
    c = np.random.RandomState(22).randn(SIZE, 640).astype(np.float32) * 5.0
    zero = {"w": jnp.zeros((SIZE, 640), jnp.float32)}

    def run(compression):
        opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
        opt.compression = compression
        params = {"w": bf.worker_values(lambda r: c[r])}
        state = opt.init(params)
        for _ in range(150):
            params, state = opt.step(params, state, zero)
        w = np.asarray(params["w"])
        return np.abs(w - w.mean(0)).max()

    spread_plain = run("int4")
    spread_ef = run("int4_ef")
    assert spread_ef < 0.01 * spread_plain, (spread_plain, spread_ef)
    assert spread_ef < 1e-3


def test_int4_ef_restricted_paths():
    opt = bf.DistributedAllreduceOptimizer(optax.sgd(0.1))
    opt.compression = "int4_ef"
    params = {"w": bf.worker_values(lambda r: np.ones(4, np.float32))}
    state = opt.init(params)
    with pytest.raises(ValueError, match="int4_ef"):
        opt.step(params, state, params)

    opt2 = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    opt2.compression = "int4_ef"
    state2 = opt2.init(params)
    train_step = opt2.make_train_step(
        lambda p, t: jnp.sum(p["w"] * t), delayed=True
    )
    with pytest.raises(ValueError, match="int4_ef"):
        train_step(params, state2, params["w"])


def test_hierarchical_int4_converges(cpu_devices):
    """int4 on the machine-level (DCN) leg: the 8x-compressed cross-host
    gossip still reaches consensus."""
    bf.shutdown()
    bf.init(devices=cpu_devices[:SIZE], nodes_per_machine=4)
    bf.set_machine_topology(tu.RingGraph(2))
    c = np.random.RandomState(23).randn(SIZE, 4).astype(np.float32)
    opt = bf.DistributedHierarchicalNeighborAllreduceOptimizer(
        optax.sgd(optax.exponential_decay(0.3, 10, 0.5))
    )
    opt.compression = "int4"
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    for _ in range(60):
        params, state = opt.step(params, state,
                                 {"w": params["w"] - jnp.asarray(c)})
    w = np.asarray(params["w"])
    target = c.mean(0)
    assert np.abs(w - target).max() < 0.2 * np.abs(c - target).max()
    assert np.abs(w - w.mean(0)).max() < 0.15


def test_quantized_allgather_all_wires():
    """Compressed neighbor_allgather: every wire returns a bounded
    approximation of the exact gather (bf16 near-lossless, int8/int4 at
    their block-scaled steps), same neighbor order and shapes."""
    bf.set_topology(tu.RingGraph(SIZE))
    x = np.random.RandomState(24).randn(SIZE, 600).astype(np.float32)
    exact = bf.neighbor_allgather(x)
    steps = {"bf16": 0.02 * np.abs(x).max(),
             "int8": np.abs(x).max() / 127.0 * 1.5,
             "int4": np.abs(x).max() / 7.0 * 1.5}
    for wire, bound in steps.items():
        got = bf.neighbor_allgather(x, compression=wire)
        assert len(got) == len(exact)
        for e, g in zip(exact, got):
            assert np.asarray(g).shape == np.asarray(e).shape
            assert np.abs(np.asarray(g) - np.asarray(e)).max() < bound, (
                wire
            )
    with pytest.raises(ValueError, match="int4"):
        bf.neighbor_allgather(x, compression="fp4")
    with pytest.raises(ValueError, match="float"):
        bf.neighbor_allgather(
            bf.worker_values(lambda r: np.ones(8, np.int32)),
            compression="int8",
        )
