# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Comm-plan compiler coverage: minimal round packing, preserved fast
path, and semantic equivalence.

Three layers of proof, mirroring the repo's HLO-verification style
(test_fusion.py):

- *structural*: the edge-coloring pass hits the König bound
  ``max(max_in_degree, max_out_degree)`` on fuzzed digraphs, every round
  is a partial permutation, and circulant topologies keep their
  byte-identical offset-grouped lowering;
- *compiled*: the optimized HLO for star / mesh2d / sparse random
  digraphs contains exactly the bound's number of ``collective-permute``
  instructions (the naive lowering emits up to N-1);
- *semantic*: ``weighted_combine`` over the optimized plan is EXACTLY the
  naive plan's result. Round re-packing permutes the order of per-receiver
  additions, so genuine float inputs could differ in the last ulp without
  meaning anything; the equality tests therefore use dyadic-rational
  weights and integer-valued inputs, for which every product and partial
  sum is exactly representable — bitwise equality then PROVES semantic
  equivalence rather than sampling it.
"""

import functools
import json
import os
import subprocess
import sys

import numpy as np
import networkx as nx
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import bluefog_tpu.topology as topo
from bluefog_tpu import scaling
from bluefog_tpu.collective import compiler, inner, plan as planlib

SIZE = 8
AXIS = "workers"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_spmd(fn, *arrays, out_specs=P(AXIS)):
    m = jax.make_mesh((SIZE,), (AXIS,))
    wrapped = jax.jit(
        jax.shard_map(
            fn, mesh=m, in_specs=tuple(P(AXIS) for _ in arrays),
            out_specs=out_specs,
        )
    )
    return wrapped(*arrays)


def random_edges(rng, size):
    all_edges = [
        (i, j) for i in range(size) for j in range(size) if i != j
    ]
    k = rng.randint(0, len(all_edges) + 1)
    idx = rng.choice(len(all_edges), size=k, replace=False)
    return [all_edges[i] for i in idx]


# -- structural --------------------------------------------------------------


def test_coloring_meets_koenig_bound_fuzzed():
    rng = np.random.RandomState(0)
    for _ in range(100):
        size = rng.randint(2, 17)
        edges = random_edges(rng, size)
        perms = compiler.coloring_perms(edges, size)
        assert len(perms) == compiler.min_rounds(edges, size)
        # partition + partial-permutation invariants (also asserted
        # inside the pass; re-checked here from the public result)
        flat = [e for p in perms for e in p]
        assert sorted(flat) == sorted(set(map(tuple, edges)))
        for p in perms:
            assert len({s for s, _ in p}) == len(p)
            assert len({d for _, d in p}) == len(p)


def test_auto_never_worse_than_offset_and_reaches_bound():
    rng = np.random.RandomState(1)
    for _ in range(50):
        size = rng.randint(2, 17)
        edges = random_edges(rng, size)
        res = compiler.compile_edges(edges, size)
        assert res.lower_bound <= res.rounds <= res.offset_rounds
        # auto must always land ON the bound: either offsets already
        # meet it or the coloring is taken
        assert res.rounds == res.lower_bound or not edges


def test_circulant_topologies_keep_offset_fast_path():
    for g, rounds in (
        (topo.ExponentialTwoGraph(SIZE), 3),
        (topo.RingGraph(SIZE), 2),  # offsets {+1, -1}; the self loop is no round
        (topo.FullyConnectedGraph(SIZE), 7),
    ):
        plan = planlib.plan_from_topology(g, weighted=True)
        assert plan.compile_info.method == "offset"
        assert len(plan.rounds) == rounds
        # circulant rounds are FULL permutations riding ICI
        assert all(len(r.perm) == SIZE for r in plan.rounds)
        naive = planlib.plan_from_topology(g, weighted=True, method="offset")
        assert plan.perms == naive.perms


def test_compile_cache_dedupes_repeated_lowerings():
    edges = [(0, 1), (2, 1), (3, 1), (1, 5), (4, 5)]
    a = compiler.compile_edges(edges, SIZE)
    b = compiler.compile_edges(list(reversed(edges)), SIZE)
    assert a is b  # canonical edge set -> one host-side compile


def test_forced_methods_and_cost_model():
    edges = [(0, 1), (2, 1), (3, 1), (1, 5), (4, 5), (6, 2), (7, 3)]
    auto = compiler.compile_edges(edges, SIZE)
    off = compiler.compile_edges(edges, SIZE, method="offset")
    col = compiler.compile_edges(edges, SIZE, method="coloring")
    assert off.method == "offset" and off.rounds == off.offset_rounds
    assert col.rounds == col.lower_bound
    assert auto.method == "coloring" and auto.perms == col.perms
    # cost model: strictly fewer rounds -> strictly cheaper plan
    assert auto.predicted_cost_s < auto.offset_cost_s
    payload = 1024
    assert scaling.plan_cost_s(2, payload) == pytest.approx(
        2 * (scaling.ROUND_ALPHA_S + payload / scaling.ICI_LINK_BYTES_PER_S)
    )


@pytest.mark.parametrize("degree", [2, 6, 7])
def test_random_regular_digraph_properties(degree):
    """Sparse degrees come from rejection sampling; dense degrees (the
    rejection-hostile regime, up to the complete digraph) from the
    coloring-based completion — both must produce exact regularity."""
    g = topo.RandomRegularDigraph(SIZE, degree, seed=3)
    w = nx.to_numpy_array(g)
    off_diag = (w != 0) & ~np.eye(SIZE, dtype=bool)
    assert (off_diag.sum(1) == degree).all()
    assert (off_diag.sum(0) == degree).all()
    np.testing.assert_allclose(w.sum(0), 1.0, atol=1e-12)
    np.testing.assert_allclose(w.sum(1), 1.0, atol=1e-12)


# -- compiled (HLO round-count regression) -----------------------------------


@pytest.mark.parametrize(
    "name,make",
    [
        ("star", lambda: topo.StarGraph(SIZE)),
        ("mesh2d", lambda: topo.MeshGrid2DGraph(SIZE)),
        ("random_d2", lambda: topo.RandomRegularDigraph(SIZE, 2, seed=3)),
    ],
)
def test_optimized_hlo_emits_bound_many_permutes(name, make):
    """The compiled program for an optimized plan contains exactly the
    König bound's number of collective-permutes."""
    plan = planlib.plan_from_topology(make(), weighted=True)
    info = plan.compile_info
    stats = scaling.gossip_comm_stats(plan, 256)
    cp = stats.get("collective-permute", {"count": 0})
    assert cp["count"] == info.lower_bound, (name, stats, info)
    assert cp["count"] <= info.offset_rounds


def test_random_digraph_hlo_beats_naive_lowering():
    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    optimized = planlib.plan_from_topology(g, weighted=True)
    naive = planlib.plan_from_topology(g, weighted=True, method="offset")
    assert optimized.compile_info.method == "coloring"
    assert len(optimized.rounds) == 2 < len(naive.rounds)
    n_stats = scaling.gossip_comm_stats(naive, 256)
    o_stats = scaling.gossip_comm_stats(optimized, 256)
    assert o_stats["collective-permute"]["count"] == 2
    assert (
        n_stats["collective-permute"]["count"] == len(naive.rounds) > 2
    )


def test_gossip_comm_stats_plan_summary():
    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    plan = planlib.plan_from_topology(g, weighted=True)
    stats = scaling.gossip_comm_stats(plan, 256, include_plan=True)
    summary = stats["plan"]
    assert summary["rounds"] == 2
    assert summary["decomposition"] == "coloring"
    assert summary["naive_rounds"] > 2 and summary["lower_bound"] == 2
    assert summary["predicted_cost_us"] < summary["naive_cost_us"]
    # default shape untouched: every non-plan entry is {count, bytes}
    plain = scaling.gossip_comm_stats(plan, 256)
    assert "plan" not in plain


# -- semantic equivalence ----------------------------------------------------


def dyadic_matrix(rng, size):
    """Random combine matrix with dyadic-rational entries (k/64) so the
    f32 combine arithmetic is exact regardless of summation order."""
    w = rng.randint(-64, 65, size=(size, size)).astype(np.float64) / 64.0
    mask = rng.rand(size, size) < 0.5
    np.fill_diagonal(mask, True)
    return np.where(mask, w, 0.0)


def combine(plan, x):
    got = run_spmd(
        functools.partial(
            inner.weighted_combine, plan=plan, axis_name=AXIS
        ),
        x,
    )
    return np.asarray(got)


def test_optimized_combine_bitwise_equals_naive():
    """Exact (same dtype path) equality on randomized weight matrices,
    including zero-weighted declared edges."""
    rng = np.random.RandomState(7)
    for trial in range(10):
        w = dyadic_matrix(rng, SIZE)
        # declare EVERY off-diagonal position an edge, including the
        # zero-weighted ones — pattern membership must not depend on the
        # weight value
        edges = [
            (i, j) for i in range(SIZE) for j in range(SIZE) if i != j
        ]
        naive = planlib.plan_from_matrix(w, edges=edges, method="offset")
        opt = planlib.plan_from_matrix(w, edges=edges, method="coloring")
        np.testing.assert_array_equal(
            naive.weight_matrix(), opt.weight_matrix()
        )
        x = rng.randint(-8, 9, size=(SIZE, 16)).astype(np.float32)
        got_naive, got_opt = combine(naive, x), combine(opt, x)
        assert got_naive.dtype == got_opt.dtype == np.float32
        np.testing.assert_array_equal(got_naive, got_opt), trial


def test_optimized_combine_sparse_auto_wins_and_matches():
    rng = np.random.RandomState(11)
    for trial in range(10):
        g = topo.RandomRegularDigraph(SIZE, 2, seed=100 + trial)
        adj = (nx.to_numpy_array(g) != 0) & ~np.eye(SIZE, dtype=bool)
        w = np.where(adj, dyadic_matrix(rng, SIZE), 0.0)
        np.fill_diagonal(w, rng.randint(-64, 65, SIZE) / 64.0)
        edges = [tuple(e) for e in zip(*np.nonzero(adj))]
        naive = planlib.plan_from_matrix(w, edges=edges, method="offset")
        auto = planlib.plan_from_matrix(w, edges=edges)
        assert len(auto.rounds) <= len(naive.rounds)
        x = rng.randint(-8, 9, size=(SIZE, 4)).astype(np.float32)
        np.testing.assert_array_equal(combine(naive, x), combine(auto, x))


def test_dynamic_schedule_offset_vs_coloring_identical():
    """Dynamic schedules lower per-step through the same compiler; the
    mass-conserving one-peer schedule has purely dyadic weights (0.5 /
    1.0), so offset and coloring plans must agree bitwise step by step."""
    g = topo.ExponentialTwoGraph(SIZE)
    mk = lambda method: planlib.schedule_from_dynamic(
        SIZE,
        lambda r: topo.GetDynamicOnePeerSendRecvRanks(g, r),
        self_weight=0.5,
        uniform=False,
        method=method,
    )
    s_off, s_col = mk("offset"), mk("coloring")
    assert s_off.period == s_col.period
    rng = np.random.RandomState(13)
    x = rng.randint(-8, 9, size=(SIZE, 4)).astype(np.float32)
    for p_off, p_col in zip(s_off.plans, s_col.plans):
        np.testing.assert_array_equal(
            p_off.weight_matrix(), p_col.weight_matrix()
        )
        np.testing.assert_array_equal(combine(p_off, x), combine(p_col, x))


def test_dynamic_schedule_uniform_close():
    """Uniform one-peer weights (1/(deg+1)) are not dyadic, so the
    guarantee is weight-matrix identity plus tight numeric agreement."""
    g = topo.ExponentialTwoGraph(SIZE)
    mk = lambda method: planlib.schedule_from_dynamic(
        SIZE,
        lambda r: topo.GetDynamicOnePeerSendRecvRanks(g, r),
        method=method,
    )
    s_off, s_col = mk("offset"), mk("coloring")
    x = np.random.RandomState(17).randn(SIZE, 4).astype(np.float32)
    for p_off, p_col in zip(s_off.plans, s_col.plans):
        np.testing.assert_array_equal(
            p_off.weight_matrix(), p_col.weight_matrix()
        )
        np.testing.assert_allclose(
            combine(p_off, x), combine(p_col, x), rtol=1e-6, atol=1e-6
        )


def test_windows_on_irregular_topology_use_packed_rounds():
    """The window subsystem lowers its put/get patterns through the same
    compiler; semantics (buffer contents) must be decomposition-blind."""
    import bluefog_tpu as bf

    bf.init(devices=jax.devices("cpu")[:SIZE])
    try:
        g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
        bf.set_topology(g)
        x = bf.worker_values(lambda r: np.full((3,), float(r), np.float32))
        assert bf.win_create(x, "plan_test")
        bf.win_put(name="plan_test")
        adj = (nx.to_numpy_array(g) != 0) & ~np.eye(SIZE, dtype=bool)
        expected = np.zeros((SIZE, 3))
        for j in range(SIZE):
            srcs = sorted(np.nonzero(adj[:, j])[0])
            deg = len(srcs)
            # default win_update: uniform 1/(deg+1) over self + buffers,
            # each buffer holding dst_weight(=1.0) * src value
            expected[j] = (j + sum(srcs)) / (deg + 1.0)
        got = np.asarray(bf.win_update(name="plan_test"))
        np.testing.assert_allclose(got, expected, rtol=1e-6)
    finally:
        bf.win_free()
        bf.shutdown()


# -- acceptance: 16-rank sparse digraph via BENCH_MODE=plan ------------------


def test_bench_plan_mode_16_rank_bound():
    """End-to-end acceptance: `BENCH_MODE=plan` on a 16-device virtual
    mesh reports star / mesh2d / random lines; the degree-3 random
    digraph lowers to exactly 3 rounds, verified from compiled HLO."""
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["BENCH_MODE"] = "plan"
    env["BENCH_STEPS"] = "2"
    env["BENCH_WINDOWS"] = "1"
    env["BENCH_PLAN_PAYLOAD_ELEMS"] = "1024"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = {
        l["topology"]: l
        for l in map(json.loads, out.stdout.splitlines())
        if l.get("metric") == "plan_compiler"
    }
    assert {"star", "mesh2d", "random_d3"} <= set(lines)
    for l in lines.values():
        assert l["optimized_rounds"] <= l["naive_rounds"], l
        assert l["hlo_collective_permutes"] == l["optimized_rounds"], l
        assert l["optimized_ms_per_step"] > 0, l
    rand = lines["random_d3"]
    assert rand["n_workers"] == 16
    assert rand["optimized_rounds"] == 3 == rand["lower_bound"], rand
    assert rand["naive_rounds"] > 3, rand
    assert rand["decomposition"] == "coloring", rand
    # circulant fast path: exp2 keeps its offset rounds
    assert lines["exp2"]["decomposition"] == "offset"
    assert lines["exp2"]["optimized_rounds"] == lines["exp2"]["naive_rounds"]
