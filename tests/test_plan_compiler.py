# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Comm-plan compiler coverage: minimal round packing, preserved fast
path, and semantic equivalence.

Three layers of proof, mirroring the repo's HLO-verification style
(test_fusion.py):

- *structural*: the edge-coloring pass hits the König bound
  ``max(max_in_degree, max_out_degree)`` on fuzzed digraphs, every round
  is a partial permutation, and circulant topologies keep their
  byte-identical offset-grouped lowering;
- *compiled*: the optimized HLO for star / mesh2d / sparse random
  digraphs contains exactly the bound's number of ``collective-permute``
  instructions (the naive lowering emits up to N-1);
- *semantic*: ``weighted_combine`` over the optimized plan is EXACTLY the
  naive plan's result. Round re-packing permutes the order of per-receiver
  additions, so genuine float inputs could differ in the last ulp without
  meaning anything; the equality tests therefore use dyadic-rational
  weights and integer-valued inputs, for which every product and partial
  sum is exactly representable — bitwise equality then PROVES semantic
  equivalence rather than sampling it.
"""

import functools
import json
import os
import subprocess
import sys

import numpy as np
import networkx as nx
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import bluefog_tpu.topology as topo
from bluefog_tpu import scaling
from bluefog_tpu.collective import compiler, inner, plan as planlib

SIZE = 8
AXIS = "workers"

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_spmd(fn, *arrays, out_specs=P(AXIS)):
    m = jax.make_mesh((SIZE,), (AXIS,))
    wrapped = jax.jit(
        jax.shard_map(
            fn, mesh=m, in_specs=tuple(P(AXIS) for _ in arrays),
            out_specs=out_specs,
        )
    )
    return wrapped(*arrays)


def random_edges(rng, size):
    all_edges = [
        (i, j) for i in range(size) for j in range(size) if i != j
    ]
    k = rng.randint(0, len(all_edges) + 1)
    idx = rng.choice(len(all_edges), size=k, replace=False)
    return [all_edges[i] for i in idx]


# -- structural --------------------------------------------------------------


def test_coloring_meets_koenig_bound_fuzzed():
    rng = np.random.RandomState(0)
    for _ in range(100):
        size = rng.randint(2, 17)
        edges = random_edges(rng, size)
        perms = compiler.coloring_perms(edges, size)
        assert len(perms) == compiler.min_rounds(edges, size)
        # partition + partial-permutation invariants (also asserted
        # inside the pass; re-checked here from the public result)
        flat = [e for p in perms for e in p]
        assert sorted(flat) == sorted(set(map(tuple, edges)))
        for p in perms:
            assert len({s for s, _ in p}) == len(p)
            assert len({d for _, d in p}) == len(p)


def test_auto_never_worse_than_offset_and_reaches_bound():
    rng = np.random.RandomState(1)
    for _ in range(50):
        size = rng.randint(2, 17)
        edges = random_edges(rng, size)
        res = compiler.compile_edges(edges, size)
        assert res.lower_bound <= res.rounds <= res.offset_rounds
        # auto must always land ON the bound: either offsets already
        # meet it or the coloring is taken
        assert res.rounds == res.lower_bound or not edges


def test_circulant_topologies_keep_offset_fast_path():
    for g, rounds in (
        (topo.ExponentialTwoGraph(SIZE), 3),
        (topo.RingGraph(SIZE), 2),  # offsets {+1, -1}; the self loop is no round
        (topo.FullyConnectedGraph(SIZE), 7),
    ):
        plan = planlib.plan_from_topology(g, weighted=True)
        assert plan.compile_info.method == "offset"
        assert len(plan.rounds) == rounds
        # circulant rounds are FULL permutations riding ICI
        assert all(len(r.perm) == SIZE for r in plan.rounds)
        naive = planlib.plan_from_topology(g, weighted=True, method="offset")
        assert plan.perms == naive.perms


def test_compile_cache_dedupes_repeated_lowerings():
    edges = [(0, 1), (2, 1), (3, 1), (1, 5), (4, 5)]
    a = compiler.compile_edges(edges, SIZE)
    b = compiler.compile_edges(list(reversed(edges)), SIZE)
    assert a is b  # canonical edge set -> one host-side compile


def test_forced_methods_and_cost_model():
    edges = [(0, 1), (2, 1), (3, 1), (1, 5), (4, 5), (6, 2), (7, 3)]
    auto = compiler.compile_edges(edges, SIZE)
    off = compiler.compile_edges(edges, SIZE, method="offset")
    col = compiler.compile_edges(edges, SIZE, method="coloring")
    assert off.method == "offset" and off.rounds == off.offset_rounds
    assert col.rounds == col.lower_bound
    assert auto.method == "coloring" and auto.perms == col.perms
    # cost model: strictly fewer rounds -> strictly cheaper plan
    assert auto.predicted_cost_s < auto.offset_cost_s
    payload = 1024
    assert scaling.plan_cost_s(2, payload) == pytest.approx(
        2 * (scaling.ROUND_ALPHA_S + payload / scaling.ICI_LINK_BYTES_PER_S)
    )


@pytest.mark.parametrize("degree", [2, 6, 7])
def test_random_regular_digraph_properties(degree):
    """Sparse degrees come from rejection sampling; dense degrees (the
    rejection-hostile regime, up to the complete digraph) from the
    coloring-based completion — both must produce exact regularity."""
    g = topo.RandomRegularDigraph(SIZE, degree, seed=3)
    w = nx.to_numpy_array(g)
    off_diag = (w != 0) & ~np.eye(SIZE, dtype=bool)
    assert (off_diag.sum(1) == degree).all()
    assert (off_diag.sum(0) == degree).all()
    np.testing.assert_allclose(w.sum(0), 1.0, atol=1e-12)
    np.testing.assert_allclose(w.sum(1), 1.0, atol=1e-12)


# -- compiled (HLO round-count regression) -----------------------------------


@pytest.mark.parametrize(
    "name,make",
    [
        ("star", lambda: topo.StarGraph(SIZE)),
        ("mesh2d", lambda: topo.MeshGrid2DGraph(SIZE)),
        ("random_d2", lambda: topo.RandomRegularDigraph(SIZE, 2, seed=3)),
    ],
)
def test_optimized_hlo_emits_bound_many_permutes(name, make):
    """The compiled program for an optimized plan contains exactly the
    König bound's number of collective-permutes."""
    plan = planlib.plan_from_topology(make(), weighted=True)
    info = plan.compile_info
    stats = scaling.gossip_comm_stats(plan, 256)
    cp = stats.get("collective-permute", {"count": 0})
    assert cp["count"] == info.lower_bound, (name, stats, info)
    assert cp["count"] <= info.offset_rounds


def test_random_digraph_hlo_beats_naive_lowering():
    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    optimized = planlib.plan_from_topology(g, weighted=True)
    naive = planlib.plan_from_topology(g, weighted=True, method="offset")
    assert optimized.compile_info.method == "coloring"
    assert len(optimized.rounds) == 2 < len(naive.rounds)
    n_stats = scaling.gossip_comm_stats(naive, 256)
    o_stats = scaling.gossip_comm_stats(optimized, 256)
    assert o_stats["collective-permute"]["count"] == 2
    assert (
        n_stats["collective-permute"]["count"] == len(naive.rounds) > 2
    )


def test_gossip_comm_stats_plan_summary():
    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    plan = planlib.plan_from_topology(g, weighted=True)
    stats = scaling.gossip_comm_stats(plan, 256, include_plan=True)
    summary = stats["plan"]
    assert summary["rounds"] == 2
    assert summary["decomposition"] == "coloring"
    assert summary["naive_rounds"] > 2 and summary["lower_bound"] == 2
    assert summary["predicted_cost_us"] < summary["naive_cost_us"]
    # default shape untouched: every non-plan entry is {count, bytes}
    plain = scaling.gossip_comm_stats(plan, 256)
    assert "plan" not in plain


# -- semantic equivalence ----------------------------------------------------


def dyadic_matrix(rng, size):
    """Random combine matrix with dyadic-rational entries (k/64) so the
    f32 combine arithmetic is exact regardless of summation order."""
    w = rng.randint(-64, 65, size=(size, size)).astype(np.float64) / 64.0
    mask = rng.rand(size, size) < 0.5
    np.fill_diagonal(mask, True)
    return np.where(mask, w, 0.0)


def combine(plan, x):
    got = run_spmd(
        functools.partial(
            inner.weighted_combine, plan=plan, axis_name=AXIS
        ),
        x,
    )
    return np.asarray(got)


def test_optimized_combine_bitwise_equals_naive():
    """Exact (same dtype path) equality on randomized weight matrices,
    including zero-weighted declared edges."""
    rng = np.random.RandomState(7)
    for trial in range(10):
        w = dyadic_matrix(rng, SIZE)
        # declare EVERY off-diagonal position an edge, including the
        # zero-weighted ones — pattern membership must not depend on the
        # weight value
        edges = [
            (i, j) for i in range(SIZE) for j in range(SIZE) if i != j
        ]
        naive = planlib.plan_from_matrix(w, edges=edges, method="offset")
        opt = planlib.plan_from_matrix(w, edges=edges, method="coloring")
        np.testing.assert_array_equal(
            naive.weight_matrix(), opt.weight_matrix()
        )
        x = rng.randint(-8, 9, size=(SIZE, 16)).astype(np.float32)
        got_naive, got_opt = combine(naive, x), combine(opt, x)
        assert got_naive.dtype == got_opt.dtype == np.float32
        np.testing.assert_array_equal(got_naive, got_opt), trial


def test_optimized_combine_sparse_auto_wins_and_matches():
    rng = np.random.RandomState(11)
    for trial in range(10):
        g = topo.RandomRegularDigraph(SIZE, 2, seed=100 + trial)
        adj = (nx.to_numpy_array(g) != 0) & ~np.eye(SIZE, dtype=bool)
        w = np.where(adj, dyadic_matrix(rng, SIZE), 0.0)
        np.fill_diagonal(w, rng.randint(-64, 65, SIZE) / 64.0)
        edges = [tuple(e) for e in zip(*np.nonzero(adj))]
        naive = planlib.plan_from_matrix(w, edges=edges, method="offset")
        auto = planlib.plan_from_matrix(w, edges=edges)
        assert len(auto.rounds) <= len(naive.rounds)
        x = rng.randint(-8, 9, size=(SIZE, 4)).astype(np.float32)
        np.testing.assert_array_equal(combine(naive, x), combine(auto, x))


def test_dynamic_schedule_offset_vs_coloring_identical():
    """Dynamic schedules lower per-step through the same compiler; the
    mass-conserving one-peer schedule has purely dyadic weights (0.5 /
    1.0), so offset and coloring plans must agree bitwise step by step."""
    g = topo.ExponentialTwoGraph(SIZE)
    mk = lambda method: planlib.schedule_from_dynamic(
        SIZE,
        lambda r: topo.GetDynamicOnePeerSendRecvRanks(g, r),
        self_weight=0.5,
        uniform=False,
        method=method,
    )
    s_off, s_col = mk("offset"), mk("coloring")
    assert s_off.period == s_col.period
    rng = np.random.RandomState(13)
    x = rng.randint(-8, 9, size=(SIZE, 4)).astype(np.float32)
    for p_off, p_col in zip(s_off.plans, s_col.plans):
        np.testing.assert_array_equal(
            p_off.weight_matrix(), p_col.weight_matrix()
        )
        np.testing.assert_array_equal(combine(p_off, x), combine(p_col, x))


def test_dynamic_schedule_uniform_close():
    """Uniform one-peer weights (1/(deg+1)) are not dyadic, so the
    guarantee is weight-matrix identity plus tight numeric agreement."""
    g = topo.ExponentialTwoGraph(SIZE)
    mk = lambda method: planlib.schedule_from_dynamic(
        SIZE,
        lambda r: topo.GetDynamicOnePeerSendRecvRanks(g, r),
        method=method,
    )
    s_off, s_col = mk("offset"), mk("coloring")
    x = np.random.RandomState(17).randn(SIZE, 4).astype(np.float32)
    for p_off, p_col in zip(s_off.plans, s_col.plans):
        np.testing.assert_array_equal(
            p_off.weight_matrix(), p_col.weight_matrix()
        )
        np.testing.assert_allclose(
            combine(p_off, x), combine(p_col, x), rtol=1e-6, atol=1e-6
        )


def test_windows_on_irregular_topology_use_packed_rounds():
    """The window subsystem lowers its put/get patterns through the same
    compiler; semantics (buffer contents) must be decomposition-blind."""
    import bluefog_tpu as bf

    bf.init(devices=jax.devices("cpu")[:SIZE])
    try:
        g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
        bf.set_topology(g)
        x = bf.worker_values(lambda r: np.full((3,), float(r), np.float32))
        assert bf.win_create(x, "plan_test")
        bf.win_put(name="plan_test")
        adj = (nx.to_numpy_array(g) != 0) & ~np.eye(SIZE, dtype=bool)
        expected = np.zeros((SIZE, 3))
        for j in range(SIZE):
            srcs = sorted(np.nonzero(adj[:, j])[0])
            deg = len(srcs)
            # default win_update: uniform 1/(deg+1) over self + buffers,
            # each buffer holding dst_weight(=1.0) * src value
            expected[j] = (j + sum(srcs)) / (deg + 1.0)
        got = np.asarray(bf.win_update(name="plan_test"))
        np.testing.assert_allclose(got, expected, rtol=1e-6)
    finally:
        bf.win_free()
        bf.shutdown()


# -- bandwidth family: chunked / short-cut / Pareto chooser ------------------


@pytest.fixture
def clean_cost_model():
    """Calibration is process-global; tests that install one must not
    leak it into the class-constant assertions elsewhere."""
    compiler.clear_calibration()
    yield
    compiler.clear_calibration()


def test_chunk_bounds_512_aligned_and_covering():
    for n, k in ((4096, 4), (4097, 4), (513, 8), (1 << 20, 64), (511, 3)):
        bounds = inner.chunk_bounds(n, k)
        assert bounds[0][0] == 0 and bounds[-1][1] == n
        for (a0, b0), (a1, b1) in zip(bounds, bounds[1:]):
            assert b0 == a1
        for a, b in bounds[:-1]:
            assert (b - a) % 512 == 0, (n, k, bounds)
        assert len(bounds) <= max(1, k) + 1
    assert inner.chunk_bounds(256, 8) == [(0, 256)]  # sub-grid payload
    assert inner.chunk_bounds(4096, 1) == [(0, 4096)]


def test_shortcut_perms_structure_fuzzed():
    """Relay schedules on fuzzed digraphs: every round is a partial
    permutation of UNIT hops (ring-adjacent under the default fabric),
    chains occupy consecutive rounds, and the compiler's built-in relay
    simulation (delivery correctness) passes — shortcut_perms raises
    otherwise."""
    rng = np.random.RandomState(5)
    for _ in range(25):
        size = rng.randint(3, 12)
        edges = random_edges(rng, size)
        if not edges:
            continue
        perms, inject, delivery = compiler.shortcut_perms(edges, size)
        assert sorted(e for e, _ in delivery) == sorted(set(edges))
        for perm in perms:
            for s, d in perm:
                assert (d - s) % size in (1, size - 1), (s, d, size)
        for r, inj in enumerate(inject):
            assert set(inj) <= {s for s, _ in perms[r]}


def test_shortcut_combine_bitwise_dyadic():
    """Short-cut relay lowering == offset lowering to the bit under
    dyadic weights / integer inputs (the repo's exactness scheme for
    cross-decomposition equivalence)."""
    rng = np.random.RandomState(23)
    edges = [(i, j) for i in range(SIZE) for j in range(SIZE) if i != j]
    for _ in range(5):
        w = dyadic_matrix(rng, SIZE)
        naive = planlib.plan_from_matrix(w, edges=edges, method="offset")
        sc = planlib.plan_from_matrix(w, edges=edges, method="shortcut")
        assert sc.compile_info.route == "shortcut"
        assert sc.compile_info.inject is not None
        np.testing.assert_array_equal(
            naive.weight_matrix(), sc.weight_matrix()
        )
        x = rng.randint(-8, 9, size=(SIZE, 16)).astype(np.float32)
        np.testing.assert_array_equal(combine(naive, x), combine(sc, x))


def test_shortcut_neighbor_relations_and_allgather():
    """in/out-neighbors and gather slots of a short-cut plan come from
    the DELIVERY table (relay pairs are transport, not neighbors), so
    neighbor_allgather returns exactly the direct plan's output."""
    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    direct = planlib.plan_from_topology(g, weighted=True)
    sc = planlib.plan_from_topology(g, weighted=True, method="shortcut")
    assert sc.in_neighbors == direct.in_neighbors
    assert sc.out_neighbors == direct.out_neighbors
    x = np.random.RandomState(3).randn(SIZE, 64).astype(np.float32)
    ga = run_spmd(
        lambda t: inner.neighbor_allgather(t, sc, AXIS), x,
        out_specs=(P(AXIS), P(AXIS)),
    )
    gb = run_spmd(
        lambda t: inner.neighbor_allgather(t, direct, AXIS), x,
        out_specs=(P(AXIS), P(AXIS)),
    )
    np.testing.assert_array_equal(np.asarray(ga[0]), np.asarray(gb[0]))
    np.testing.assert_array_equal(np.asarray(ga[1]), np.asarray(gb[1]))


@pytest.mark.parametrize("elems", [4096, 8192 + 1536])
def test_chunked_combine_bitwise_all_wires(elems):
    """Chunked == monolithic to the BIT for arbitrary float inputs, for
    the exact combine and both memoryless quantized wires (chunk bounds
    snap to the 512-element scale grid; the exact path concatenates
    received chunks back to full width before the accumulate so the
    arithmetic graph is shape-identical)."""
    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    plan = planlib.plan_from_topology(g, weighted=True)
    x = np.random.RandomState(11).randn(SIZE, elems).astype(np.float32)

    base = combine(plan, x)
    for k in (2, 4, 8):
        got = np.asarray(run_spmd(
            functools.partial(
                inner.weighted_combine, plan=plan, axis_name=AXIS, chunks=k
            ), x,
        ))
        np.testing.assert_array_equal(base, got), k
    for wire in ("int8", "bf16", "int4"):
        qbase = np.asarray(run_spmd(
            functools.partial(
                inner.weighted_combine_quantized, plan=plan,
                axis_name=AXIS, wire=wire,
            ), x,
        ))
        for k in (2, 4):
            got = np.asarray(run_spmd(
                functools.partial(
                    inner.weighted_combine_quantized, plan=plan,
                    axis_name=AXIS, wire=wire, chunks=k,
                ), x,
            ))
            np.testing.assert_array_equal(qbase, got), (wire, k)


@pytest.mark.parametrize("wire", ["int8", "int4"])
def test_chunked_ef_bitwise_output_and_state(wire):
    """int8_ef / int4_ef chunked == monolithic for output AND both CHOCO
    copies: the state is positional over the flat payload and slices
    with it (int4 additionally pins that per-chunk nibble-pack slices
    are whole scale groups)."""
    import jax.numpy as jnp

    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    plan = planlib.plan_from_topology(g, weighted=True)
    perms = plan.perms
    _sw, recv_w = plan.weight_operands()
    elems = 4096
    x = np.random.RandomState(13).randn(SIZE, elems).astype(np.float32)
    e_self = np.random.RandomState(14).randn(SIZE, elems).astype(
        np.float32
    ) * 0.01
    e_recv = np.zeros((SIZE, len(perms), elems), np.float32)

    def run(chunks):
        def body(t, es, er):
            y, (es2, er2) = inner.weighted_combine_quantized_ef_operands(
                t, (es[0], er[0]), perms, jnp.asarray(recv_w), AXIS,
                chunks=chunks, wire=wire,
            )
            return y, jnp.expand_dims(es2, 0), jnp.expand_dims(er2, 0)
        out = run_spmd(
            body, x, e_self, e_recv, out_specs=(P(AXIS), P(AXIS), P(AXIS))
        )
        return [np.asarray(o) for o in out]

    y1, s1, r1 = run(1)
    for k in (2, 4):
        yk, sk, rk = run(k)
        np.testing.assert_array_equal(y1, yk)
        np.testing.assert_array_equal(s1, sk)
        np.testing.assert_array_equal(r1, rk)


def test_choose_chunks_env_override_and_forced_methods(
    clean_cost_model, monkeypatch
):
    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    compiled = planlib.plan_from_topology(g, weighted=True).compile_info
    big = 100 * 1024 * 1024
    monkeypatch.setenv("BLUEFOG_PLAN_CHUNKS", "4")
    assert compiler.choose_chunks(compiled, big, n_elems=big // 4) == 4
    # the override is capped so every chunk keeps a 512-elem scale group
    assert compiler.choose_chunks(compiled, 4096, n_elems=1024) == 2
    monkeypatch.setenv("BLUEFOG_PLAN_CHUNKS", "zero")
    with pytest.raises(ValueError):
        compiler.choose_chunks(compiled, big)
    monkeypatch.delenv("BLUEFOG_PLAN_CHUNKS")
    # forced structure methods pin k=1 (A/B isolation)
    for m in ("offset", "coloring", "shortcut"):
        assert compiler.choose_chunks(
            compiled, big, n_elems=big // 4, method=m
        ) == 1


def test_choose_chunks_pareto_crossover(clean_cost_model):
    """Under the (class-constant) cost model: small payloads stay at the
    latency-optimal k=1, large payloads pipeline, and a calibration
    that measured NO pipelining (pipeline_eff=0) never chunks — the
    chooser can only pick what the fabric delivered."""
    g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
    compiled = planlib.plan_from_topology(g, weighted=True).compile_info
    assert compiled.rounds >= 2
    small, big = 64 * 1024, 100 * 1024 * 1024
    assert compiler.choose_chunks(compiled, small, n_elems=small // 4) == 1
    k_big = compiler.choose_chunks(compiled, big, n_elems=big // 4)
    assert k_big > 1
    # chunked cost at the chosen k beats the serial plan cost
    cong = compiled.congestion
    assert compiler.pipelined_cost_s(big, k_big, cong) < (
        compiler.pipelined_cost_s(big, 1, cong)
    )
    compiler.set_calibration(
        1e-3, 1e9, pipeline_eff=0.0, source="test"
    )
    assert compiler.choose_chunks(compiled, big, n_elems=big // 4) == 1


def test_calibration_roundtrip(clean_cost_model):
    base = compiler.round_cost_s(1024)
    compiler.set_calibration(0.5, 1024.0, pipeline_eff=0.5, source="test")
    cal = compiler.calibration()
    assert cal["source"] == "test" and cal["alpha_s"] == 0.5
    assert compiler.round_cost_s(1024) == pytest.approx(0.5 + 1.0)
    compiler.clear_calibration()
    assert compiler.calibration()["source"] == "class-constants"
    assert compiler.round_cost_s(1024) == pytest.approx(base)


def test_compile_cache_distinguishes_method_and_fabric(monkeypatch):
    edges = [(0, 3), (3, 6), (6, 1), (1, 0)]
    a = compiler.compile_edges(edges, SIZE, method="coloring")
    b = compiler.compile_edges(edges, SIZE, method="shortcut")
    assert a is not b and b.route == "shortcut"
    monkeypatch.setenv("BLUEFOG_TORUS_DIMS", "2,4")
    c = compiler.compile_edges(edges, SIZE, method="shortcut")
    assert c is not b  # declared fabric joins the compile-cache key
    monkeypatch.delenv("BLUEFOG_TORUS_DIMS")


def test_torus_routes_and_congestion():
    from bluefog_tpu.topology import placement

    # declared 4x4 torus: serpentine neighbors are unit hops; a pair far
    # apart in ring order can be few torus hops
    dims = (4, 4)
    for i in range(15):
        assert placement.hop_distance(i, i + 1, 16, dims) == 1
    assert placement.hop_distance(0, 15, 16, dims) <= 2
    route = placement.route_ranks(0, 15, 16, dims)
    assert route[0] == 0 and route[-1] == 15
    # ring model: an offset-2 full permutation loads every link twice
    perm = tuple((i, (i + 2) % SIZE) for i in range(SIZE))
    assert placement.perm_congestion(perm, SIZE) == 2
    assert placement.perm_congestion(
        tuple((i, (i + 1) % SIZE) for i in range(SIZE)), SIZE
    ) == 1
    # BLUEFOG_TORUS_DIMS validation: wrong product is ignored
    assert placement.declared_torus_dims(16) is None


def test_eager_cache_keys_unique_per_chunk_and_route(monkeypatch):
    """ops-level: a chunk-count or route change dispatches its own
    compiled program (cache-key uniqueness), with identical results."""
    import bluefog_tpu as bf
    from bluefog_tpu import context as ctx_mod

    bf.init(devices=jax.devices("cpu")[:SIZE])
    try:
        g = topo.RandomRegularDigraph(SIZE, 2, seed=3)
        bf.set_topology(g)
        x = bf.worker_values(
            lambda r: np.random.RandomState(r).randn(2048).astype(
                np.float32
            )
        )
        ctx = ctx_mod.get_context()

        def na_keys():
            return {
                k for k in ctx.op_cache if k[0] == "neighbor_allreduce"
            }

        monkeypatch.setenv("BLUEFOG_PLAN_CHUNKS", "1")
        a = np.asarray(bf.neighbor_allreduce(x))
        monkeypatch.setenv("BLUEFOG_PLAN_CHUNKS", "2")
        b = np.asarray(bf.neighbor_allreduce(x))
        assert len(na_keys()) == 2, na_keys()
        np.testing.assert_array_equal(a, b)
        monkeypatch.delenv("BLUEFOG_PLAN_CHUNKS")
        monkeypatch.setenv("BLUEFOG_PLAN_METHOD", "shortcut")
        c = np.asarray(bf.neighbor_allreduce(x))
        assert len(na_keys()) == 3, na_keys()
        np.testing.assert_allclose(a, c, rtol=1e-5, atol=1e-5)
    finally:
        bf.shutdown()


@pytest.mark.parametrize("order", ["atc", "cta"])
@pytest.mark.parametrize(
    "wire", [None, "int8", "int8_ef", "int4", "int4_ef"]
)
def test_optimizer_chunked_trajectory_bitwise(order, wire, monkeypatch):
    """The acceptance pin: BLUEFOG_PLAN_CHUNKS=4 vs =1 optimizer
    trajectories are bitwise-identical for ATC/CTA x
    fp32/int8/int8_ef/int4/int4_ef (PR-2 buckets are the chunking
    grain; chunking is a schedule change, never a numerics change)."""
    import bluefog_tpu as bf
    import optax

    def run(chunks):
        monkeypatch.setenv("BLUEFOG_PLAN_CHUNKS", str(chunks))
        bf.init(devices=jax.devices("cpu")[:SIZE])
        try:
            bf.set_topology(topo.ExponentialTwoGraph(SIZE))
            factory = (
                bf.DistributedAdaptThenCombineOptimizer if order == "atc"
                else bf.DistributedAdaptWithCombineOptimizer
            )
            opt = factory(
                optax.sgd(0.1, momentum=0.9),
                bf.CommunicationType.neighbor_allreduce,
            )
            if wire is not None:
                opt.compression = wire
            rng = np.random.RandomState(0)
            params = {
                "w": bf.worker_values(
                    lambda r: rng.randn(2048).astype(np.float32)
                    + np.float32(r)
                )
            }
            state = opt.init(params)
            traj = []
            for step in range(3):
                grads = {
                    "w": params["w"] * np.float32(0.01 * (step + 1))
                }
                params, state = opt.step(params, state, grads)
                traj.append(np.asarray(params["w"]).copy())
            return traj
        finally:
            bf.shutdown()

    t1, t4 = run(1), run(4)
    for a, b in zip(t1, t4):
        np.testing.assert_array_equal(a, b)


# -- acceptance: 16-rank sparse digraph via BENCH_MODE=plan ------------------


def test_bench_plan_mode_16_rank_bound():
    """End-to-end acceptance: `BENCH_MODE=plan` on a 16-device virtual
    mesh reports star / mesh2d / random lines; the degree-3 random
    digraph lowers to exactly 3 rounds, verified from compiled HLO."""
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["BENCH_MODE"] = "plan"
    env["BENCH_STEPS"] = "2"
    env["BENCH_WINDOWS"] = "1"
    env["BENCH_PLAN_PAYLOAD_ELEMS"] = "1024"
    # keep the smoke fast: tiny payload sweep (the full 64KiB-100MiB
    # sweep is the committed PLAN_SWEEP_EVIDENCE.json run)
    env["BENCH_PLAN_SWEEP_BYTES"] = "65536,262144"
    env["BENCH_PLAN_SWEEP_STEPS"] = "2"
    env["BENCH_PLAN_SWEEP_WINDOWS"] = "1"
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    lines = {
        l["topology"]: l
        for l in map(json.loads, out.stdout.splitlines())
        if l.get("metric") == "plan_compiler"
    }
    assert {"star", "mesh2d", "random_d3"} <= set(lines)
    for l in lines.values():
        assert l["optimized_rounds"] <= l["naive_rounds"], l
        assert l["hlo_collective_permutes"] == l["optimized_rounds"], l
        assert l["optimized_ms_per_step"] > 0, l
    rand = lines["random_d3"]
    assert rand["n_workers"] == 16
    assert rand["optimized_rounds"] == 3 == rand["lower_bound"], rand
    assert rand["naive_rounds"] > 3, rand
    assert rand["decomposition"] == "coloring", rand
    # circulant fast path: exp2 keeps its offset rounds
    assert lines["exp2"]["decomposition"] == "offset"
    assert lines["exp2"]["optimized_rounds"] == lines["exp2"]["naive_rounds"]
