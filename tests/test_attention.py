# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Sequence-parallel attention vs dense reference (numpy-oracle grade).

Capability beyond the reference (it is DP-only, alg_spectrum.rst:11-23):
ring attention and all-to-all (Ulysses) sequence parallelism must produce
the exact softmax attention of the logically-concatenated sequence, with
exact adjoints, at any mesh size that divides the sequence.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import bluefog_tpu as bf
from bluefog_tpu.ops import (
    reference_attention,
    ring_attention,
    ring_attention_block,
    ulysses_attention,
    ulysses_attention_block,
)

SIZE = 8
B, T, H, D = 2, 4, 8, 16  # per-worker block length T; full seq = SIZE * T


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    yield
    bf.shutdown()


def qkv(seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    full = [
        rng.randn(B, SIZE * T, H, D).astype(dtype) for _ in range(3)
    ]
    stacked = [
        np.stack(np.split(a, SIZE, axis=1)) for a in full
    ]  # [size, B, T, H, D]
    return full, stacked


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_matches_dense_reference(fn, causal):
    (qf, kf, vf), (qs, ks, vs) = qkv()
    expected = np.asarray(
        reference_attention(
            jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf), causal=causal
        )
    )
    got = np.asarray(fn(qs, ks, vs, causal=causal))
    got_full = got.transpose(1, 0, 2, 3, 4).reshape(B, SIZE * T, H, D)
    np.testing.assert_allclose(got_full, expected, rtol=2e-5, atol=2e-5)


def test_ring_attention_bf16():
    (qf, kf, vf), (qs, ks, vs) = qkv(1)
    to16 = lambda a: jnp.asarray(a, jnp.bfloat16)
    out = ring_attention(to16(np.asarray(qs)), to16(np.asarray(ks)),
                         to16(np.asarray(vs)), causal=True)
    assert out.dtype == jnp.bfloat16
    expected = reference_attention(
        to16(np.asarray(qf)), to16(np.asarray(kf)), to16(np.asarray(vf)),
        causal=True,
    )
    got_full = np.asarray(out, np.float32).transpose(1, 0, 2, 3, 4).reshape(
        B, SIZE * T, H, D
    )
    np.testing.assert_allclose(
        got_full, np.asarray(expected, np.float32), rtol=5e-2, atol=5e-2
    )


@pytest.mark.parametrize("block_fn",
                         [ring_attention_block, ulysses_attention_block])
def test_gradients_match_dense(block_fn):
    """The sequence-parallel adjoint equals the dense adjoint."""
    (qf, kf, vf), (qs, ks, vs) = qkv(2)
    mesh = bf.get_context().mesh
    spec = P("workers")

    def sp_loss(qs, ks, vs):
        out = jax.shard_map(
            lambda q, k, v: block_fn(
                q[0], k[0], v[0], "workers", causal=True
            )[None],
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        )(qs, ks, vs)
        return (out * jnp.sin(out)).sum()

    def dense_loss(qf, kf, vf):
        out = reference_attention(qf, kf, vf, causal=True)
        return (out * jnp.sin(out)).sum()

    g_sp = jax.jit(jax.grad(sp_loss, argnums=(0, 1, 2)))(
        jnp.asarray(np.asarray(qs)), jnp.asarray(np.asarray(ks)),
        jnp.asarray(np.asarray(vs)),
    )
    g_dense = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf)
    )
    for sp, dn in zip(g_sp, g_dense):
        sp_full = np.asarray(sp).transpose(1, 0, 2, 3, 4).reshape(
            B, SIZE * T, H, D
        )
        np.testing.assert_allclose(
            sp_full, np.asarray(dn), rtol=5e-4, atol=5e-5
        )


def test_ring_attention_comm_volume_one_block_per_round():
    """The compiled ring step moves exactly one K and one V block per
    round (2N ppermutes total over the N-round loop, payload = one
    block), independent of total sequence length — the long-context
    analogue of the O(1) gossip cost."""
    from bluefog_tpu import scaling

    _, (qs, ks, vs) = qkv(3)
    mesh = jax.make_mesh((SIZE,), ("workers",))
    spec = P("workers")
    fn = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring_attention_block(
                q[0], k[0], v[0], "workers"
            )[None],
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        )
    )
    args = [
        jax.device_put(jnp.asarray(np.asarray(a)),
                       NamedSharding(mesh, spec))
        for a in (qs, ks, vs)
    ]
    txt = fn.lower(*args).compile().as_text()
    stats = scaling.hlo_collective_stats(txt)
    cp = stats.get("collective-permute", {"count": 0, "bytes": 0})
    # n-1 rotations (the final round attends without rotating — a last
    # permute would be dead traffic); XLA may unroll or keep the loop
    assert cp["count"] in (2, 2 * (SIZE - 1)), stats
    block_bytes = B * T * H * D * 4
    assert cp["bytes"] in (2 * block_bytes, 2 * (SIZE - 1) * block_bytes), stats


def test_ulysses_requires_divisible_heads():
    mesh = jax.make_mesh((SIZE,), ("workers",))
    spec = P("workers")
    bad_h = SIZE - 1  # not divisible
    q = jnp.zeros((SIZE, B, T, bad_h, D))
    with pytest.raises(ValueError, match="divisible"):
        jax.shard_map(
            lambda q, k, v: ulysses_attention_block(
                q[0], k[0], v[0], "workers"
            )[None],
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        )(q, q, q)


def test_facade_validates_all_operands():
    _, (qs, ks, vs) = qkv(4)
    bad_k = np.asarray(ks)[: SIZE - 1]  # wrong leading axis
    with pytest.raises(ValueError, match="worker array"):
        ring_attention(np.asarray(qs), bad_k, np.asarray(vs))


def test_gqa_ring_matches_dense():
    """Grouped-query attention: 8 query heads over 2 KV heads; the ring
    rotates the compact KV (wire bytes / 4) and must still equal dense
    GQA, which itself must equal repeated-head MHA."""
    rng = np.random.RandomState(6)
    h_kv = 2
    qf = rng.randn(B, SIZE * T, H, D).astype(np.float32)
    kf = rng.randn(B, SIZE * T, h_kv, D).astype(np.float32)
    vf = rng.randn(B, SIZE * T, h_kv, D).astype(np.float32)
    dense = reference_attention(
        jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf), causal=True
    )
    # oracle: GQA == MHA with explicitly repeated KV heads
    rep = lambda a: np.repeat(a, H // h_kv, axis=2)
    mha = reference_attention(
        jnp.asarray(qf), jnp.asarray(rep(kf)), jnp.asarray(rep(vf)),
        causal=True,
    )
    np.testing.assert_allclose(np.asarray(dense), np.asarray(mha),
                               rtol=1e-6, atol=1e-6)

    stack = lambda a: np.stack(np.split(a, SIZE, axis=1))
    got = np.asarray(
        ring_attention(stack(qf), stack(kf), stack(vf), causal=True)
    )
    got_full = got.transpose(1, 0, 2, 3, 4).reshape(B, SIZE * T, H, D)
    np.testing.assert_allclose(got_full, np.asarray(dense), rtol=2e-5,
                               atol=2e-5)


def test_gqa_ring_wire_bytes_are_compact():
    """The rotated payload is the COMPACT KV: wire bytes divide by the
    group factor vs MHA."""
    from bluefog_tpu import scaling

    h_kv = 2
    mesh = jax.make_mesh((SIZE,), ("workers",))
    spec = P("workers")

    def lower(h_kv_heads):
        fn = jax.jit(
            jax.shard_map(
                lambda q, k, v: ring_attention_block(
                    q[0], k[0], v[0], "workers"
                )[None],
                mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
            )
        )
        q = jnp.zeros((SIZE, B, T, H, D))
        kv = jnp.zeros((SIZE, B, T, h_kv_heads, D))
        args = [
            jax.device_put(a, NamedSharding(mesh, spec))
            for a in (q, kv, kv)
        ]
        stats = scaling.hlo_collective_stats(
            fn.lower(*args).compile().as_text()
        )
        return stats["collective-permute"]["bytes"]

    assert lower(h_kv) * (H // h_kv) == lower(H)


def test_gqa_rejects_indivisible_heads():
    q = jnp.zeros((1, 8, 6, 16))
    kv = jnp.zeros((1, 8, 4, 16))
    with pytest.raises(ValueError, match="multiple"):
        reference_attention(q, kv, kv)


def test_gqa_ulysses_matches_dense():
    """h_kv=2 < mesh size exercises the expand-first path; the compact
    reshard path (h_kv divisible by mesh) is covered separately, and
    h_kv == H is plain MHA already covered elsewhere."""
    rng = np.random.RandomState(7)
    for h_kv in (2,):
        qf = rng.randn(B, SIZE * T, H, D).astype(np.float32)
        kf = rng.randn(B, SIZE * T, h_kv, D).astype(np.float32)
        vf = rng.randn(B, SIZE * T, h_kv, D).astype(np.float32)
        dense = reference_attention(
            jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf), causal=True
        )
        stack = lambda a: np.stack(np.split(a, SIZE, axis=1))
        got = np.asarray(
            ulysses_attention(stack(qf), stack(kf), stack(vf), causal=True)
        )
        got_full = got.transpose(1, 0, 2, 3, 4).reshape(B, SIZE * T, H, D)
        np.testing.assert_allclose(got_full, np.asarray(dense), rtol=2e-5,
                                   atol=2e-5, err_msg=f"h_kv={h_kv}")


def test_gqa_ulysses_compact_reshard_path():
    """16 query heads over 8 KV heads on an 8-mesh: the KV reshard stays
    COMPACT (h_kv % n == 0) and group alignment must hold."""
    rng = np.random.RandomState(8)
    H2, h_kv = 16, 8
    qf = rng.randn(B, SIZE * T, H2, D).astype(np.float32)
    kf = rng.randn(B, SIZE * T, h_kv, D).astype(np.float32)
    vf = rng.randn(B, SIZE * T, h_kv, D).astype(np.float32)
    dense = reference_attention(
        jnp.asarray(qf), jnp.asarray(kf), jnp.asarray(vf), causal=True
    )
    stack = lambda a: np.stack(np.split(a, SIZE, axis=1))
    got = np.asarray(
        ulysses_attention(stack(qf), stack(kf), stack(vf), causal=True)
    )
    got_full = got.transpose(1, 0, 2, 3, 4).reshape(B, SIZE * T, H2, D)
    np.testing.assert_allclose(got_full, np.asarray(dense), rtol=2e-5,
                               atol=2e-5)


def test_gqa_ulysses_invalid_group_raises_at_entry():
    """h divisible by mesh but not by h_kv must fail with GLOBAL head
    counts at entry, not mid-trace with per-shard counts."""
    mesh = jax.make_mesh((2,), ("workers",))
    spec = P("workers")
    q = jnp.zeros((2, 1, 8, 8, 16))
    kv = jnp.zeros((2, 1, 8, 6, 16))
    with pytest.raises(ValueError, match=r"\(8\).*\(6\)"):
        jax.shard_map(
            lambda q, k, v: ulysses_attention_block(
                q[0], k[0], v[0], "workers"
            )[None],
            mesh=mesh, in_specs=(spec,) * 3, out_specs=spec,
        )(q, kv, kv)


def test_facade_caches_compiled_program():
    """Repeated ring/Ulysses facade calls with the same avals reuse ONE
    compiled program (the op_cache contract every eager op follows)."""
    ctx = bf.get_context()
    _, (qs, ks, vs) = qkv(9)
    args = [jnp.asarray(np.asarray(a)) for a in (qs, ks, vs)]
    ring_attention(*args, causal=True)
    before = len(ctx.op_cache)
    for _ in range(3):
        ring_attention(*args, causal=True)
    assert len(ctx.op_cache) == before
    ulysses_attention(*args, causal=True)
    after_u = len(ctx.op_cache)
    ulysses_attention(*args, causal=True)
    assert len(ctx.op_cache) == after_u
