"""Topology toolkit tests.

Mirrors the topology coverage of reference test/torch_basics_test.py (graph
generators, equivalence, recv/send weights, infer helpers) plus schedule
properties the compiled path relies on.
"""

import collections

import numpy as np
import networkx as nx
import pytest

from bluefog_tpu import topology as tu


ALL_SIZES = [1, 2, 3, 4, 7, 8, 12, 16]


def _w(topo):
    return nx.to_numpy_array(topo)


@pytest.mark.parametrize("size", ALL_SIZES)
@pytest.mark.parametrize(
    "gen",
    [
        tu.ExponentialTwoGraph,
        tu.ExponentialGraph,
        lambda n: tu.SymmetricExponentialGraph(n, 4),
        tu.MeshGrid2DGraph,
        tu.StarGraph,
        tu.RingGraph,
        tu.FullyConnectedGraph,
    ],
)
def test_generators_row_stochastic(gen, size):
    w = _w(gen(size))
    assert w.shape == (size, size)
    np.testing.assert_allclose(w.sum(axis=1), np.ones(size), atol=1e-12)
    assert (w >= 0).all()
    # every rank keeps a self loop
    assert (np.diag(w) > 0).all()


def test_exponential_two_structure():
    w = _w(tu.ExponentialTwoGraph(12))
    # rank 0 sends to offsets {1, 2, 4, 8} and itself, uniformly
    nz = np.nonzero(w[0])[0]
    np.testing.assert_array_equal(nz, [0, 1, 2, 4, 8])
    np.testing.assert_allclose(w[0, nz], 0.2)
    # circulant: every row is a roll of row 0
    for i in range(12):
        np.testing.assert_allclose(w[i], np.roll(w[0], i))


def test_exponential_graph_base3():
    w = _w(tu.ExponentialGraph(28, base=3))
    nz = set(np.nonzero(w[0])[0])
    assert nz == {0, 1, 3, 9, 27}


def test_meshgrid_doubly_stochastic():
    for size, shape in [(6, None), (16, None), (6, (2, 3)), (12, (3, 4))]:
        w = _w(tu.MeshGrid2DGraph(size, shape=shape))
        np.testing.assert_allclose(w.sum(axis=0), np.ones(size), atol=1e-12)
        np.testing.assert_allclose(w.sum(axis=1), np.ones(size), atol=1e-12)
        np.testing.assert_allclose(w, w.T)


def test_ring_styles():
    w0 = _w(tu.RingGraph(8, connect_style=0))
    assert set(np.nonzero(w0[0])[0]) == {0, 1, 7}
    w1 = _w(tu.RingGraph(8, connect_style=1))
    assert set(np.nonzero(w1[0])[0]) == {0, 7}
    w2 = _w(tu.RingGraph(8, connect_style=2))
    assert set(np.nonzero(w2[0])[0]) == {0, 1}


def test_star_structure():
    w = _w(tu.StarGraph(8, center_rank=2))
    for i in range(8):
        assert w[i, 2] > 0 and w[2, i] > 0


def test_is_topology_equivalent():
    assert tu.IsTopologyEquivalent(tu.RingGraph(8), tu.RingGraph(8))
    assert not tu.IsTopologyEquivalent(tu.RingGraph(8), tu.StarGraph(8))
    assert not tu.IsTopologyEquivalent(tu.RingGraph(8), tu.RingGraph(9))
    assert not tu.IsTopologyEquivalent(None, tu.RingGraph(8))


def test_is_regular():
    assert tu.IsRegularGraph(tu.RingGraph(8))
    assert tu.IsRegularGraph(tu.ExponentialTwoGraph(8))
    assert not tu.IsRegularGraph(tu.StarGraph(8))


def test_recv_send_weights():
    topo = tu.ExponentialTwoGraph(8)
    self_w, recv = tu.GetRecvWeights(topo, 3)
    assert self_w == pytest.approx(0.25)
    assert set(recv) == {2, 1, 7, 3 - 4 + 8}  # offsets -1,-2,-4 mod 8 => 2,1,7
    assert all(v == pytest.approx(0.25) for v in recv.values())
    self_w2, send = tu.GetSendWeights(topo, 3)
    assert self_w2 == pytest.approx(0.25)
    assert set(send) == {4, 5, 7}
    # recv weights of rank j are the column j of W
    w = _w(topo)
    for src, val in recv.items():
        assert w[src, 3] == pytest.approx(val)


def test_power_of():
    assert tu.isPowerOf(1, 2) and tu.isPowerOf(8, 2) and tu.isPowerOf(27, 3)
    assert not tu.isPowerOf(6, 2)
    # large power exactness (float log would fail around here)
    assert tu.isPowerOf(3**30, 3)


# ---------------------------------------------------------------------------
# dynamic schedules
# ---------------------------------------------------------------------------


def _collect_round(gens, t):
    sends = {}
    recvs = {}
    for r, g in enumerate(gens):
        s, rv = next(g)
        sends[r] = s
        recvs[r] = rv
    return sends, recvs


@pytest.mark.parametrize("size", [4, 8, 12])
def test_dynamic_one_peer_consistency(size):
    topo = tu.ExponentialTwoGraph(size)
    gens = [tu.GetDynamicOnePeerSendRecvRanks(topo, r) for r in range(size)]
    for t in range(12):
        sends, recvs = _collect_round(gens, t)
        # every send must appear in the destination's recv list, and vice versa
        for r in range(size):
            assert len(sends[r]) == 1
            dst = sends[r][0]
            assert r in recvs[dst]
            for src in recvs[r]:
                assert sends[src] == [r]
        # edges must come from the base topology
        for r in range(size):
            assert sends[r][0] in [v for v in topo.successors(r) if v != r]


def test_dynamic_one_peer_exp2_uniform_offset():
    """For Exp-2 every rank picks the same offset each round (this is what
    lets the compiled path use a single ppermute per step)."""
    size = 8
    topo = tu.ExponentialTwoGraph(size)
    gens = [tu.GetDynamicOnePeerSendRecvRanks(topo, r) for r in range(size)]
    for t in range(6):
        sends, _ = _collect_round(gens, t)
        offsets = {(sends[r][0] - r) % size for r in range(size)}
        assert len(offsets) == 1
        assert offsets.pop() == 2 ** (t % 3)


@pytest.mark.parametrize("world,local", [(16, 4), (24, 4)])
def test_inner_outer_ring_is_permutation(world, local):
    gens = [
        tu.GetInnerOuterRingDynamicSendRecvRanks(world, local, r)
        for r in range(world)
    ]
    for t in range(10):
        sends, recvs = _collect_round(gens, t)
        all_dsts = [sends[r][0] for r in range(world)]
        assert sorted(all_dsts) == list(range(world))  # a permutation
        for r in range(world):
            assert len(recvs[r]) == 1
            assert all_dsts[recvs[r][0]] == r  # my declared source sends to me


@pytest.mark.parametrize("world,local", [(16, 4), (32, 8)])
def test_inner_outer_expo2_is_permutation(world, local):
    gens = [
        tu.GetInnerOuterExpo2DynamicSendRecvRanks(world, local, r)
        for r in range(world)
    ]
    for t in range(12):
        sends, recvs = _collect_round(gens, t)
        all_dsts = [sends[r][0] for r in range(world)]
        assert sorted(all_dsts) == list(range(world))
        for r in range(world):
            src = recvs[r][0]
            assert sends[src] == [r]


def test_exp2_machine_schedule():
    world, local = 16, 4
    machines = world // local
    gens = {
        r: tu.GetExp2DynamicSendRecvMachineRanks(world, local, r, r % local)
        for r in range(world)
    }
    s, rv = next(gens[0])
    assert s == [1] and rv == [3]  # machine 0 -> 1, recv from 3 (4 machines)
    s, rv = next(gens[0])
    assert s == [2] and rv == [2]
    # Full period, all ranks: the machine-level pattern must be a consistent
    # permutation — every machine sends to machine+2^t and receives from
    # machine-2^t, and ranks on the same machine agree.
    gens = {
        r: tu.GetExp2DynamicSendRecvMachineRanks(world, local, r, r % local)
        for r in range(world)
    }
    period = int(np.log2(machines - 1)) + 1
    for t in range(2 * period):
        dist = 2 ** (t % period)
        for r in range(world):
            s, rv = next(gens[r])
            m = r // local
            assert s == [(m + dist) % machines]
            assert rv == [(m - dist) % machines]


# ---------------------------------------------------------------------------
# infer helpers
# ---------------------------------------------------------------------------


def test_infer_source_from_destination():
    dst = [[1], [2], [3], [0]]  # directed ring on 4 ranks
    src = tu.InferSourceFromDestinationRanks(dst)
    assert src == [[3], [0], [1], [2]]
    src3, w = tu.InferSourceFromDestinationRanks(
        dst, construct_adjacency_matrix=True, rank=3
    )
    assert src3 == [2]
    assert w.shape == (4, 4)


def test_infer_destination_from_source():
    src = [[1, 2], [0], [0], []]
    dst = tu.InferDestinationFromSourceRanks(src)
    assert dst == [[1, 2], [0], [0], []]


def test_infer_validation():
    with pytest.raises(AssertionError):
        tu.InferSourceFromDestinationRanks([[0], [0], [0], [0]])  # self rank
    with pytest.raises(ValueError):
        tu.InferSourceFromDestinationRanks([1, 2, 3])  # flat list


def test_serpentine_order_passthrough():
    class FakeDev:
        pass

    devs = [FakeDev() for _ in range(4)]
    assert tu.serpentine_device_order(devs) == devs


def test_serpentine_order_torus():
    class FakeDev:
        def __init__(self, coords):
            self.coords = coords

        def __repr__(self):
            return f"D{self.coords}"

    devs = [FakeDev((x, y, 0)) for y in range(2) for x in range(4)]
    ordered = tu.worker_device_order(devs)
    coords = [d.coords for d in ordered]
    # serpentine: consecutive coords differ by one hop
    for a, b in zip(coords, coords[1:]):
        assert sum(abs(i - j) for i, j in zip(a, b)) == 1


class _Dev:
    def __init__(self, coords):
        self.coords = coords

    def __repr__(self):
        return f"D{self.coords}"


def _grid_devs(dims):
    """Fake devices covering a full (x, y[, z]) grid of the given dims."""
    import itertools

    return [
        _Dev(c[::-1])
        for c in itertools.product(*(range(n) for n in reversed(dims)))
    ]


def _torus_hops(a, b, dims):
    """ICI hop count between coords on a torus with wrap links."""
    return sum(min(abs(i - j), n - abs(i - j)) for i, j, n in zip(a, b, dims))


@pytest.mark.parametrize(
    "dims", [(4, 2), (4, 8), (4, 2, 2), (2, 2, 4), (4, 4, 4)]
)
def test_boustrophedon_single_hop(dims):
    """Every consecutive pair in the walk is ONE physical hop — including the
    3-D z-plane seam the round-1 implementation got wrong (ADVICE r1)."""
    devs = _grid_devs(dims)
    ordered = tu.serpentine_device_order(devs)
    assert len(ordered) == len(devs)
    assert {d.coords for d in ordered} == {d.coords for d in devs}
    coords = [d.coords for d in ordered]
    for a, b in zip(coords, coords[1:]):
        assert _torus_hops(a, b, dims) == 1, (a, b)
    # closing ring edge rides torus wrap links (even dims): short, not O(N)
    assert _torus_hops(coords[-1], coords[0], dims) <= 2


@pytest.mark.parametrize("dims", [(4, 8), (8, 8), (4, 4, 4)])
def test_exp2_placement_hop_counts(dims):
    """Hop-count evidence for the placement claims (measured, not asserted
    from prose): under the boustrophedon order every ring step is exactly one
    ICI hop (row-major has 2-3-hop seams), and across the Exp-2 offsets the
    boustrophedon's *worst* per-offset average never exceeds row-major's,
    while its total stays within 5% (row-major's power-of-two offsets map to
    pure-axis moves on a wrap-linked torus, so it wins the total slightly)."""
    devs = _grid_devs(dims)
    n = len(devs)
    naive = [d.coords for d in devs]  # row-major, x fastest
    ordered = [d.coords for d in tu.serpentine_device_order(devs)]
    offsets = [2**k for k in range(int(np.log2(n - 1)) + 1)]

    def per_offset_avg(order):
        return {
            off: sum(
                _torus_hops(order[r], order[(r + off) % n], dims)
                for r in range(n)
            )
            / n
            for off in offsets
        }

    h_ord, h_naive = per_offset_avg(ordered), per_offset_avg(naive)
    # wrap edge excluded: it is covered (<= 2 hops) by the single-hop test
    assert all(
        _torus_hops(ordered[r], ordered[r + 1], dims) == 1
        for r in range(n - 1)
    )
    assert max(_torus_hops(naive[r], naive[r + 1], dims) for r in range(n - 1)) > 1
    assert max(h_ord.values()) <= max(h_naive.values())
    assert sum(h_ord.values()) <= 1.05 * sum(h_naive.values())
