# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""TransformerLM model-level pins (the sequence-parallel equivalences
live in tests/test_attention.py; the training e2e in the long_context
example)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu.models.transformer import TransformerLM


def _model(**kw):
    return TransformerLM(vocab=64, dim=32, heads=4, layers=2, max_len=128,
                         **kw)


def test_remat_is_numerically_invisible():
    """remat=True must change memory behavior only: same params, same
    logits, same gradients as the plain model."""
    tokens = jnp.asarray(
        np.random.RandomState(0).randint(0, 64, (2, 96)), jnp.int32
    )
    plain, remat = _model(), _model(remat=True)
    params = plain.init(jax.random.PRNGKey(0), tokens)["params"]
    # identical parameter structure: remat wraps the module, not the math
    params_r = remat.init(jax.random.PRNGKey(0), tokens)["params"]
    assert jax.tree_util.tree_structure(params) == (
        jax.tree_util.tree_structure(params_r)
    )

    def loss(model, p):
        return (
            model.apply({"params": p}, tokens).astype(jnp.float32) ** 2
        ).mean()

    l1, g1 = jax.value_and_grad(lambda p: loss(plain, p))(params)
    l2, g2 = jax.value_and_grad(lambda p: loss(remat, p))(params)
    assert float(l1) == pytest.approx(float(l2), rel=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(g1), jax.tree_util.tree_leaves(g2)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_static_pos_offset_overflow_refused():
    tokens = jnp.zeros((1, 100), jnp.int32)
    model = _model()
    with pytest.raises(ValueError, match="max_len"):
        model.init(jax.random.PRNGKey(0), tokens, pos_offset=64)
