# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Memory-observatory tests: the live-buffer census ownership
classification, analytic-vs-measured reconciliation with the
``memory_drift`` gate, the budgeted ``memory_pressure`` advisory with
its shard-recommendation hint, phase watermarks, the shared
``env_int``/``env_float`` knob parsing, OOM forensics (crash-hook
detection, the ``oom`` chaos fault producing a flight dump whose
ranked census names the planted owner category), the health-plane
fleet fields + ``/fleet`` block, the autotune decision flag, and
``tools/memory_report.py`` postmortem reconstruction from committed
artifacts alone.
"""

import json
import os
import subprocess
import sys

import numpy as np
import optax
import pytest

import bluefog_tpu as bf
import bluefog_tpu.topology as tu
from bluefog_tpu import autotune, flight, health
from bluefog_tpu import memory as bf_memory
from bluefog_tpu import metrics, scaling
from bluefog_tpu.logging_util import env_float, env_int

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIZE = 8


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices, monkeypatch):
    for k in ("BLUEFOG_MEMORY", "BLUEFOG_MEMORY_INTERVAL",
              "BLUEFOG_MEMORY_BUDGET", "BLUEFOG_MEMORY_FILE",
              "BLUEFOG_MEMORY_DRIFT_TOL", "BLUEFOG_SHARD",
              "BLUEFOG_METRICS", "BLUEFOG_HEALTH", "BLUEFOG_FLIGHT_DIR"):
        monkeypatch.delenv(k, raising=False)
    metrics.reset()
    bf.init(devices=cpu_devices[:SIZE])
    yield
    bf_memory.stop()
    health.stop()
    bf.elastic.stop()
    bf.shutdown()
    metrics.reset()


def _adam_problem(dim=4096, order="grad"):
    cls = (
        bf.DistributedGradientAllreduceOptimizer if order == "grad"
        else bf.DistributedNeighborAllreduceOptimizer
    )
    opt = cls(optax.adam(0.01))
    rng = np.random.RandomState(0)
    params = {"w": bf.worker_values(
        lambda r: rng.randn(dim).astype(np.float32)
    )}
    state = opt.init(params)
    grads = {"w": bf.worker_values(
        lambda r: np.zeros(dim, np.float32)
    )}
    return opt, params, state, grads


# -- env knob parsing (logging_util.env_int/env_float) ------------------------


def test_env_int_malformed_falls_back_with_one_warning(monkeypatch):
    from bluefog_tpu import logging_util

    monkeypatch.setenv("BLUEFOG_MEMORY_INTERVAL", "twenty")
    key = "env_int:BLUEFOG_MEMORY_INTERVAL:twenty"
    logging_util._warned_once.discard(key)
    assert bf_memory.memory_interval() == 20
    assert key in logging_util._warned_once
    n = len(logging_util._warned_once)
    assert bf_memory.memory_interval() == 20  # second read: silent
    assert len(logging_util._warned_once) == n


def test_env_int_and_float_parse_valid_values(monkeypatch):
    monkeypatch.setenv("X_INT", "42")
    monkeypatch.setenv("X_FLOAT", "2.5")
    assert env_int("X_INT", 7) == 42
    assert env_float("X_FLOAT", 1.0) == 2.5
    assert env_int("X_ABSENT", 7) == 7
    assert env_float("X_ABSENT", 1.5) == 1.5


def test_malformed_knobs_do_not_raise_across_modules(monkeypatch):
    """The audit's point: a typo'd interval/capacity/byte knob must
    never raise ValueError out of a dispatch path."""
    from bluefog_tpu import async_gossip, attribution, staleness
    from bluefog_tpu.collective import inner

    for k in ("BLUEFOG_METRICS_INTERVAL", "BLUEFOG_HEALTH_INTERVAL",
              "BLUEFOG_HEALTH_PORT", "BLUEFOG_DOCTOR_INTERVAL",
              "BLUEFOG_STALENESS_INTERVAL", "BLUEFOG_STALENESS_BOUND",
              "BLUEFOG_AUTOTUNE_INTERVAL", "BLUEFOG_FLIGHT_CAPACITY",
              "BLUEFOG_BUCKET_BYTES", "BLUEFOG_ASYNC_MAX_AGE",
              "BLUEFOG_MEMORY_BUDGET"):
        monkeypatch.setenv(k, "not-a-number")
    assert metrics.metrics_interval() == 10
    assert health.health_interval() == 20
    assert health.health_port() == 0
    assert attribution.doctor_interval() == 100
    assert staleness.staleness_interval() == 20
    assert staleness.staleness_bound() == 4
    assert autotune.autotune_interval() == 50
    assert flight.capacity() == 8192
    assert inner.bucket_bytes_cap() == 4 << 20
    assert async_gossip.async_max_age() == 8
    assert bf_memory.memory_budget() == 0


def test_quantized_temporaries_bytes_model():
    """The ROADMAP-2 fusion baseline's analytic staging model: f32
    dequant (4 B/elem) + int8 quantize staging (1 B/elem) + the packed
    nibble copy for the int4 tiers (0.5 B/elem), all over the payload
    padded UP to the 512-element scale grid; fp32 ships verbatim."""
    f = scaling.quantized_temporaries_bytes
    assert f(4096, None) == 0
    assert f(0, "int8") == 0
    assert f(4096, "bf16") == 4 * 4096
    assert f(4096, "int8") == 4 * 4096 + 4096
    assert f(4096, "int8_ef") == f(4096, "int8")
    assert f(4096, "int4") == 4 * 4096 + 4096 + 4096 // 2
    assert f(4096, "int4_ef") == f(4096, "int4")
    # padding: 100 elems stage a whole 512-block
    assert f(100, "int8") == 4 * 512 + 512
    assert f(100, "int4") == 4 * 512 + 512 + 256
    # int4 stages MORE than int8 (the extra packed copy) even though
    # it ships fewer wire bytes — exactly the fusion motivation
    assert f(4096, "int4") > f(4096, "int8")
    assert scaling.wire_payload_bytes(4096, 4, "int4") < \
        scaling.wire_payload_bytes(4096, 4, "int8")


def test_quantized_temporaries_bytes_fused_arm():
    """``fused=True`` prices the kernel wire: 2x (packed buffer +
    scale sidecar) — the encode output plus the one in-flight received
    copy — and never a full-width reconstruction. It must land below
    both the composite model AND the raw fp32 payload (4 B/elem), the
    BENCH_ASSERT gate the evidence run enforces on measured bytes."""
    f = scaling.quantized_temporaries_bytes
    # int8: packed = padded int8 lanes, sidecar = f32 scale per block
    assert f(4096, "int8", fused=True) == 2 * (4096 + (4096 // 512) * 4)
    # int4: half-width lanes, bf16 scale per block
    assert f(4096, "int4", fused=True) == 2 * (2048 + (4096 // 512) * 2)
    for wire in ("int8", "int4"):
        assert f(4096, wire + "_ef", fused=True) == f(4096, wire, fused=True)
        assert f(4096, wire, fused=True) < f(4096, wire)
        assert f(4096, wire, fused=True) < 4 * 4096  # under the fp32 payload
    # padding still rounds up to the 512-element scale grid
    assert f(100, "int8", fused=True) == 2 * (512 + 4)
    assert f(100, "int4", fused=True) == 2 * (256 + 2)
    # no fused path for bf16/fp32 — priced identically
    assert f(4096, "bf16", fused=True) == f(4096, "bf16")
    assert f(4096, None, fused=True) == 0
    assert f(0, "int4", fused=True) == 0


# -- census + reconciliation --------------------------------------------------


def test_census_classifies_owner_categories():
    opt, params, state, grads = _adam_problem()
    params, state = opt.step(params, state, grads)
    c = bf_memory.census({"params": params, "opt_state": state})
    assert set(bf_memory.CATEGORIES) <= set(c)
    assert c["params"]["bytes"] == SIZE * 4096 * 4
    # Adam: mu + nu (+ scalar count) — at least 2x the param bytes
    assert c["opt_state"]["bytes"] >= 2 * c["params"]["bytes"]
    assert c["other"]["bytes"] > 0  # grads etc. are unattributed
    ranked = bf_memory.ranked_census(c)
    assert ranked[0]["bytes"] >= ranked[-1]["bytes"]


def test_census_grads_owner_category():
    """The ``grads`` owner class (the ZeRO-2 memory axis): a gradient
    tree handed to the census is attributed to ``grads``, not
    ``other`` — what the ×1/N reduced-gradient claim is measured
    against (docs/sharding.md)."""
    opt, params, state, grads = _adam_problem()
    params, state = opt.step(params, state, grads)
    assert "grads" in bf_memory.CATEGORIES
    c0 = bf_memory.census({"params": params, "opt_state": state})
    c1 = bf_memory.census(
        {"params": params, "opt_state": state, "grads": grads}
    )
    assert c0["grads"]["bytes"] == 0
    assert c1["grads"]["bytes"] == SIZE * 4096 * 4
    assert c1["other"]["bytes"] <= c0["other"]["bytes"]


def test_reconciliation_is_exact_for_replicated_adam():
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem()
    for _ in range(3):
        params, state = opt.step(params, state, grads)
    s = obs.samples[-1]
    assert s["measured_state_bytes"] == s["analytic_state_bytes"]
    assert s["reconcile_rel_err"] == 0.0
    assert not [a for a in obs.advisories if a.kind == "memory_drift"]


def test_reconciliation_is_exact_for_sharded_adam(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SHARD", "1")
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem(dim=1 << 15)
    for _ in range(3):
        params, state = opt.step(params, state, grads)
    s = obs.samples[-1]
    assert s["analytic_state_bytes"] < scaling.optimizer_state_bytes(
        params, opt, shard=False
    ), "sharded analytic model must price the 1/N slot"
    assert s["reconcile_rel_err"] == 0.0


def test_memory_drift_fires_on_planted_leak():
    """A state tree carrying an unaccounted buffer (a leak, a stale
    generation) must trip the persistent-residual gate and name the
    advisory across the emission surfaces."""
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem()
    leak = bf.worker_values(
        lambda r: np.zeros(4096, np.float32)
    )
    ctx = bf.get_context()
    for step in range(bf_memory.DRIFT_STREAK + 1):
        # feed the observatory directly: same params/opt, but the
        # opt_state tree is padded with the planted leak
        obs.observe(ctx, step=step, optimizer=opt,
                    params=params, opt_state=(state, leak, leak))
    drifts = [a for a in obs.advisories if a.kind == "memory_drift"]
    assert drifts, "planted leak did not fire memory_drift"
    d = drifts[0].detail
    assert d["measured_state_bytes"] > d["analytic_state_bytes"]
    assert d["rel_err"] > obs.drift_tol
    # the advisory reached the doctor counter and the flight side table
    ctr = metrics.peek("bluefog.doctor.advisory.memory_drift")
    assert ctr is not None and ctr.value >= 1
    assert any(
        a.get("kind") == "memory_drift" for a in flight._advisories
    )


def test_clean_run_never_fires_drift_or_pressure():
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem()
    for _ in range(6):
        params, state = opt.step(params, state, grads)
    assert obs.advisories == []
    assert obs.samples, "sampling must have happened"


# -- pressure gate + shard hint -----------------------------------------------


def test_memory_pressure_fires_under_budget_with_shard_hint():
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem(dim=1 << 15)
    params, state = opt.step(params, state, grads)
    obs.budget = max(int(obs.last_bytes_per_rank() * 0.9), 1)
    for _ in range(3):
        params, state = opt.step(params, state, grads)
    pressures = [
        a for a in obs.advisories if a.kind == "memory_pressure"
    ]
    assert pressures, "budget breach did not fire memory_pressure"
    d = pressures[0].detail
    assert d["headroom_bytes"] < 0
    assert d["shard_enabled"] is False
    assert d["shard_hint"] is True, d
    assert d["census"], "advisory must carry the ranked census"
    assert obs.last_headroom() < 0


def test_memory_pressure_respects_cooldown():
    obs = bf_memory.start(interval=1)
    obs.budget = 1  # everything breaches
    opt, params, state, grads = _adam_problem()
    for _ in range(bf_memory.ADVISORY_COOLDOWN):
        params, state = opt.step(params, state, grads)
    pressures = [
        a for a in obs.advisories if a.kind == "memory_pressure"
    ]
    assert len(pressures) == 1, (
        "persistent pressure must re-fire once per cooldown, got "
        f"{len(pressures)}"
    )


def test_cooldown_expires_on_the_sample_clock():
    """The mute ticks per SAMPLE, not per gate check: a pressure
    episode that ends, followed by a quiet stretch longer than the
    cooldown, must not swallow the NEXT episode's first advisory."""
    obs = bf_memory.start(interval=1)
    obs.budget = 1
    opt, params, state, grads = _adam_problem()
    params, state = opt.step(params, state, grads)  # episode 1 fires
    assert len(obs.advisories) == 1
    obs.budget = 1 << 40  # pressure relieved
    for _ in range(bf_memory.ADVISORY_COOLDOWN + 1):
        params, state = opt.step(params, state, grads)
    assert not obs.pressure_active(), "mute must expire while quiet"
    obs.budget = 1  # episode 2
    params, state = opt.step(params, state, grads)
    pressures = [
        a for a in obs.advisories if a.kind == "memory_pressure"
    ]
    assert len(pressures) == 2, (
        "a new episode after an expired cooldown must fire immediately"
    )


def test_autotune_decision_records_carry_memory_pressure():
    """The decision flag is 'un-cooled-down advisory RIGHT NOW': true
    inside the re-fire window, false again once it expires."""
    obs = bf_memory.start(interval=1)
    assert autotune._memory_pressure() is False
    obs.budget = 1
    opt, params, state, grads = _adam_problem()
    params, state = opt.step(params, state, grads)
    assert autotune._memory_pressure() is True
    obs.budget = 1 << 40  # relieved; let the cooldown run out
    for _ in range(bf_memory.ADVISORY_COOLDOWN + 1):
        params, state = opt.step(params, state, grads)
    assert autotune._memory_pressure() is False


# -- phase watermarks ---------------------------------------------------------


def test_phase_scopes_record_watermarks():
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem()
    params, state = opt.step(params, state, grads)
    assert "dispatch" in obs.phase_peaks
    assert obs.phase_peaks["dispatch"]["count"] >= 1
    assert obs.phase_peaks["dispatch"]["peak_rss_bytes"] > 0
    assert "compile" in obs.phase_peaks  # first step built the program
    g = metrics.peek("bluefog.memory.phase_peak_bytes.dispatch")
    assert g is not None and g.value > 0


def test_phase_scope_noop_without_session():
    bf_memory.stop()
    with bf_memory.phase_scope("dispatch"):
        pass  # must not raise, must not create state
    assert bf_memory.active() is None


def test_checkpoint_save_records_phase(tmp_path):
    from bluefog_tpu import checkpoint

    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem(dim=512)
    params, state = opt.step(params, state, grads)
    checkpoint.save(str(tmp_path / "ckpt"), 1, params, state, opt)
    assert "checkpoint_save" in obs.phase_peaks


# -- structural / bitwise neutrality ------------------------------------------


def test_observatory_compiles_nothing_and_stays_bitwise():
    ctx = bf.get_context()
    opt, params, state, grads = _adam_problem(order="na")
    params, state = opt.step(params, state, grads)
    keys_off = set(ctx.op_cache)
    bf_memory.start(interval=1)
    params_on, state_on = opt.step(params, state, grads)
    assert set(ctx.op_cache) == keys_off, (
        "the memory observatory must not add cache entries"
    )
    bf_memory.stop()
    params_off, state_off = opt.step(params, state, grads)
    # same inputs, observatory on vs off: identical bits
    assert np.array_equal(
        np.asarray(params_on["w"]), np.asarray(params_off["w"])
    )


# -- OOM forensics ------------------------------------------------------------


def test_oom_fault_grammar_validation():
    from bluefog_tpu.elastic.faults import Fault, parse_fault_plan

    plan = parse_fault_plan("oom:rank=3,step=12")
    assert plan.faults[0].kind == "oom"
    with pytest.raises(ValueError, match="peer="):
        Fault(kind="oom", rank=1, step=0, peer=2)
    with pytest.raises(ValueError, match="seconds=/factor="):
        Fault(kind="oom", rank=1, step=0, seconds=5.0)
    with pytest.raises(ValueError, match="seconds=/factor="):
        Fault(kind="oom", rank=1, step=0, factor=0.5)
    with pytest.raises(ValueError, match="steps="):
        Fault(kind="oom", rank=1, step=0, hold_steps=3)


def test_is_oom_detects_both_shapes():
    assert bf_memory._is_oom(MemoryError, MemoryError("boom"))
    assert bf_memory._is_oom(
        RuntimeError,
        RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating"),
    )
    assert not bf_memory._is_oom(ValueError, ValueError("nope"))
    assert bf_memory._is_oom(
        bf_memory.SimulatedResourceExhausted,
        bf_memory.SimulatedResourceExhausted("x"),
    )


def test_oom_chaos_dump_names_planted_owner_category(
    tmp_path, monkeypatch
):
    """The acceptance criterion: a simulated RESOURCE_EXHAUSTED (the
    ``oom`` fault kind) produces a flight dump whose RANKED buffer
    census names the planted owner category — and
    ``tools/memory_report.py`` reconstructs the postmortem from the
    committed artifact alone."""
    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    flight.reconfigure()
    obs = bf_memory.start(interval=1)
    # plant the owner: a window buffer far bigger than everything else
    big = bf.worker_values(
        lambda r: np.zeros((1 << 20,), np.float32)  # 4 MiB per rank
    )
    bf.win_create(big, "planted")
    opt, params, state, grads = _adam_problem(dim=1024)
    session = bf.elastic.start(policy="average")
    session.inject("oom", rank=2, step=2)
    guard = bf.elastic.guard(opt)
    with pytest.raises(MemoryError, match="RESOURCE_EXHAUSTED"):
        for _ in range(4):
            params, state = guard.step(params, state, grads)
    # the forensics path ran: counter, ring event, side table, dump
    ctr = metrics.peek("bluefog.memory.oom_events")
    assert ctr is not None and ctr.value >= 1
    dump_path = tmp_path / "flight_0.json"
    assert dump_path.exists(), "oom must trigger an automatic dump"
    d = json.loads(dump_path.read_text())
    assert any(h.startswith("oom:chaos") for h in d["dump_history"])
    ooms = [a for a in d["advisories"] if a.get("kind") == "oom"]
    assert ooms, "ranked census must ride the advisory side table"
    assert ooms[-1]["top_category"] == "windows", ooms[-1]
    assert ooms[-1]["ranked_census"][0]["category"] == "windows"
    assert obs.oom_events >= 1

    # postmortem reconstruction from the committed artifact ALONE
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memory_report.py"),
         "--flight", str(dump_path), "--json"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    report = json.loads(out.stdout)
    assert report["postmortems"], report
    pm = report["postmortems"][0]
    assert pm["top_category"] == "windows"
    assert pm["ranked_census"][0]["category"] == "windows"
    # human mode names the category in a sentence
    out2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memory_report.py"),
         "--flight", str(dump_path)],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out2.returncode == 0, out2.stderr
    assert "windows" in out2.stdout
    assert "OOM postmortem" in out2.stdout


def test_real_memoryerror_excepthook_path(tmp_path, monkeypatch):
    """An uncaught MemoryError through the installed excepthook must
    run the forensics path (hook chain preserved and restored)."""
    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    flight.reconfigure()
    bf_memory.start(interval=1)
    orig_hook = sys.excepthook
    bf_memory._install_oom_hooks()
    try:
        prev_calls = []
        bf_memory._prev_excepthook = (
            lambda *a: prev_calls.append(a)
        )
        exc = MemoryError("RESOURCE_EXHAUSTED: oom")
        sys.excepthook(MemoryError, exc, None)
        assert prev_calls, "previous hook must still be chained"
        assert (tmp_path / "flight_0.json").exists()
        d = json.loads((tmp_path / "flight_0.json").read_text())
        assert any(
            a.get("kind") == "oom" for a in d["advisories"]
        )
    finally:
        bf_memory._uninstall_oom_hooks()
        sys.excepthook = orig_hook
    assert sys.excepthook is not bf_memory._excepthook


def test_injected_oom_counts_once_through_excepthook(
    tmp_path, monkeypatch
):
    """The chaos fault runs forensics at the raise site and marks the
    exception; an UNCAUGHT propagation through the installed
    excepthook must not run them a second time (one injected failure
    = one oom event, like a real single-hook OOM)."""
    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    flight.reconfigure()
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem(dim=1024)
    session = bf.elastic.start(policy="average")
    session.inject("oom", rank=1, step=0)
    guard = bf.elastic.guard(opt)
    caught = None
    try:
        guard.step(params, state, grads)
    except MemoryError as e:
        caught = e
    assert caught is not None
    assert obs.oom_events == 1
    # replay the uncaught path: the hook must skip marked exceptions
    orig_hook = sys.excepthook
    bf_memory._install_oom_hooks()
    try:
        bf_memory._prev_excepthook = lambda *a: None
        sys.excepthook(type(caught), caught, None)
        assert obs.oom_events == 1, "forensics must not run twice"
        # an UNmarked oom still runs them (the real-OOM path)
        sys.excepthook(MemoryError, MemoryError("RESOURCE_EXHAUSTED"),
                       None)
        assert obs.oom_events == 2
    finally:
        bf_memory._uninstall_oom_hooks()
        sys.excepthook = orig_hook


# -- fleet plumbing -----------------------------------------------------------


def test_fleet_fields_carry_memory_slots():
    assert "mem_bytes_per_rank" in health.FLEET_FIELDS
    assert "mem_headroom" in health.FLEET_FIELDS
    obs = bf_memory.start(interval=1)
    obs.budget = 1 << 30
    opt, params, state, grads = _adam_problem()
    params, state = opt.step(params, state, grads)
    plane = health.HealthPlane(interval=1)
    vec = plane._local_vector(bf.get_context(), None, list(range(SIZE)))
    i_bytes = health.FLEET_FIELDS.index("mem_bytes_per_rank")
    i_head = health.FLEET_FIELDS.index("mem_headroom")
    assert vec[0, i_bytes] > 0
    assert vec[0, i_head] > 0
    assert vec[0, i_head] == pytest.approx(
        (1 << 30) - vec[0, i_bytes]
    )


def test_serving_report_carries_memory_block():
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem()
    params, state = opt.step(params, state, grads)
    plane = health.HealthPlane(interval=1)
    rep = plane.report()
    assert "memory" in rep
    blk = rep["memory"]
    assert blk["bytes_per_rank"] > 0
    assert blk["ranked_census"], blk
    assert blk["oom_events"] == 0


def test_fleet_report_renders_memory_columns(tmp_path):
    """tools/fleet_report.py: memory columns render when the block is
    present and degrade to absent when it is not (pre-memory
    artifacts)."""
    with_mem = {
        "kind": "health_dump", "comm_steps": 10,
        "last_sample": {"step_ms_ewma": 1.0},
        "healthz": {"status": "ok"},
        "memory": {"bytes_per_rank": 123456, "headroom_bytes": 1000,
                   "budget_bytes": 124456, "peak_bytes_per_rank": 130000,
                   "oom_events": 0, "ranked_census": []},
    }
    without = {
        "kind": "health_dump", "comm_steps": 10,
        "last_sample": {"step_ms_ewma": 1.0},
        "healthz": {"status": "ok"},
    }
    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(with_mem))
    b.write_text(json.dumps(without))
    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         str(a), str(b), "--json"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    rows = rep["processes"]
    assert rows[0]["memory"] == "active"
    assert rows[0]["mem_bytes_per_rank"] == 123456
    assert rows[0]["mem_headroom_bytes"] == 1000
    assert rows[1]["memory"] == "absent"
    assert rows[1]["mem_bytes_per_rank"] is None


# -- artifacts + CLI ----------------------------------------------------------


def test_dump_and_memory_report_cli(tmp_path, monkeypatch):
    jsonl = tmp_path / "memory.jsonl"
    monkeypatch.setenv("BLUEFOG_MEMORY_FILE", str(jsonl))
    obs = bf_memory.start(interval=1)
    opt, params, state, grads = _adam_problem()
    for _ in range(3):
        params, state = opt.step(params, state, grads)
    dump = tmp_path / "memory_dump.json"
    assert bf_memory.dump(str(dump)) == str(dump)
    d = json.loads(dump.read_text())
    assert d["kind"] == "memory_dump"
    assert d["samples"] and d["last_census_ranked"]
    assert jsonl.exists()

    env = dict(os.environ, PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memory_report.py"),
         str(dump), "--jsonl", str(jsonl), "--json"],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["kind"] == "memory_report"
    assert rep["samples"] >= 3
    assert rep["last_census"]
    # human mode renders without crashing
    out2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "memory_report.py"),
         str(dump)],
        capture_output=True, text=True, timeout=60, env=env, cwd=REPO,
    )
    assert out2.returncode == 0, out2.stderr
    assert "last census" in out2.stdout


def test_init_respects_enable_env(monkeypatch, cpu_devices):
    monkeypatch.setenv("BLUEFOG_MEMORY", "1")
    bf.init(devices=cpu_devices[:SIZE])
    assert bf_memory.active() is not None
    monkeypatch.delenv("BLUEFOG_MEMORY")
    bf.init(devices=cpu_devices[:SIZE])
    assert bf_memory.active() is None
