# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Push-sum iterate oracle: numpy model of the reference recursion.

The reference push-sum optimizer (``torch/optimizers.py:1026-1177``) runs,
per iteration, with sender-stochastic weights W (W[i,j] = the share of
rank i's mass sent to j; rows sum to 1; diagonal = self_weight):

    zu_i(t)  = z_i(t) - lr * grad_i(z_i(t))          (inner SGD on iterate)
    x_j(t+1) = sum_i W[i,j] * zu_i(t)                (win_accumulate+collect)
    w_j(t+1) = sum_i W[i,j] * 1                      (ps-weight lane, RESET
    z_j(t+1) = x_j(t+1) / w_j(t+1)                    to 1 every iteration)

The TPU window-optimizer (``optimizers._WindowOptimizer`` mode='push_sum')
keeps the textbook accumulated-p recursion instead:

    u_i(t)   = x_i(t) - lr * grad_i  (grads evaluated at z = x/p by caller)
    x_j(t+1) = sum_i W[i,j] * u_i(t)
    p_j(t+1) = sum_i W[i,j] * p_i(t)                 (NEVER reset)
    z_j(t+1) = x_j(t+1) / p_j(t+1)

**Exact divergence point** (pinned below): on weight-balanced topologies
(every column of W sums to 1 — all regular digraphs with uniform weights,
e.g. a directed ring or Exp2) the two recursions are IDENTICAL: w stays 1,
x stays the corrected iterate, so the reset is invisible. On non-balanced
digraphs (e.g. a star) they agree at t=1 and diverge from t=2 on — and it
is the reference's reset variant that loses push-sum's mass-conservation
guarantee (its consensus limit is a skewed average on such graphs), while
the accumulated-p recursion converges to the exact mean. The numpy models
here are the committed oracle for both claims.
"""

import numpy as np
import pytest

import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import topology as tu

SIZE = 8
DIM = 3


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    yield
    bf.win_free()
    bf.shutdown()


def sender_stochastic_matrix(graph, size):
    """W[i, j]: uniform split of rank i's mass over self + out-neighbors
    (the reference's default dst_weights/self_weight, optimizers.py:1032)."""
    w = np.zeros((size, size))
    for i in range(size):
        outs = [j for j in graph.successors(i) if j != i]
        share = 1.0 / (len(outs) + 1)
        w[i, i] = share
        for j in outs:
            w[i, j] = share
    return w


def reference_pushsum(z0, c, lr, steps, w):
    """The reference recursion (corrected iterate, ps-weight reset)."""
    z = z0.copy()
    for _ in range(steps):
        zu = z - lr * (z - c)
        x = w.T @ zu
        wsum = w.T @ np.ones(len(z0))
        z = x / wsum[:, None]
    return z


def accumulated_pushsum(z0, c, lr, steps, w):
    """The TPU window-optimizer recursion (raw x, accumulated p)."""
    x = z0.copy()
    p = np.ones(len(z0))
    z = x / p[:, None]
    out = []
    for _ in range(steps):
        u = x - lr * (z - c)  # grads evaluated at the corrected estimate
        x = w.T @ u
        p = w.T @ p
        z = x / p[:, None]
        out.append(z.copy())
    return np.asarray(out)


def run_window_optimizer(graph, z0, c, lr, steps):
    bf.set_topology(graph)
    opt = bf.DistributedPushSumOptimizer(optax.sgd(lr))
    params = {"w": bf.worker_values(lambda r: z0[r])}
    state = opt.init(params)
    seq = []
    for _ in range(steps):
        est = opt.params()
        grads = {"w": est["w"] - jnp.asarray(c)}
        _, state = opt.step(state, grads)
        seq.append(np.asarray(opt.params()["w"]))
    opt.free()
    return np.asarray(seq)


def problem(seed=0):
    rng = np.random.RandomState(seed)
    z0 = rng.randn(SIZE, DIM).astype(np.float32)
    c = z0.copy()  # pure-local optimum: only communication creates motion
    return z0, c


def test_ring_iterate_sequence_matches_reference_oracle():
    """On a directed ring (weight-balanced) the window optimizer's iterate
    sequence equals the reference recursion step for step."""
    z0, c = problem()
    graph = tu.RingGraph(SIZE, connect_style=1)  # directed one-way ring
    w = sender_stochastic_matrix(graph, SIZE)
    assert np.allclose(w.sum(1), 1.0) and np.allclose(w.sum(0), 1.0)
    got = run_window_optimizer(graph, z0, c, lr=0.2, steps=12)
    z = z0.copy()
    for t in range(12):
        z = reference_pushsum(z, c, 0.2, 1, w)
        np.testing.assert_allclose(got[t], z, rtol=1e-4, atol=1e-5,
                                   err_msg=f"diverged at step {t}")


def test_ring_consensus_reaches_exact_mean():
    z0, c = problem()
    graph = tu.RingGraph(SIZE, connect_style=1)
    got = run_window_optimizer(graph, z0, c, lr=0.0, steps=200)
    np.testing.assert_allclose(
        got[-1], np.tile(z0.mean(0), (SIZE, 1)), atol=1e-3
    )


def test_star_divergence_point_is_step_two():
    """Non-balanced digraph: the recursions agree at t=1, split at t=2
    (the reference resets w to 1 after its first collect; the accumulated-p
    lane keeps mass). This is the documented iterate-bookkeeping departure
    (optimizers.py DistributedPushSumOptimizer docstring)."""
    z0, c = problem(1)
    graph = tu.StarGraph(SIZE)
    w = sender_stochastic_matrix(graph, SIZE)
    assert not np.allclose(w.sum(0), 1.0)  # star is not weight-balanced
    got = run_window_optimizer(graph, z0, c, lr=0.0, steps=2)
    oracle_acc = accumulated_pushsum(z0, c, 0.0, 2, w)
    # our implementation IS the accumulated-p oracle on any graph
    np.testing.assert_allclose(got, oracle_acc, rtol=1e-4, atol=1e-5)
    # vs the reference recursion: equal at t=1 ...
    ref1 = reference_pushsum(z0, c, 0.0, 1, w)
    np.testing.assert_allclose(got[0], ref1, rtol=1e-4, atol=1e-5)
    # ... diverged at t=2
    ref2 = reference_pushsum(z0, c, 0.0, 2, w)
    assert np.abs(got[1] - ref2).max() > 1e-3


def window_mass(win):
    """Total x mass in flight: window values + pending buffers."""
    return float(np.sum(np.asarray(win.value), dtype=np.float64)) + \
        float(np.sum(np.asarray(win.buffers), dtype=np.float64))


def window_p_mass(win):
    return float(np.sum(np.asarray(win.p), dtype=np.float64)) + \
        float(np.sum(np.asarray(win.p_buffers), dtype=np.float64))


@pytest.mark.parametrize("wire", [None, "int8_ef", "int4_ef"])
def test_async_mass_conservation_random_cadences(wire):
    """The asynchronous engine's push-sum mass-conservation property:
    random per-rank cadences x wire tier, lr = 0 — total x mass
    (window values + pending buffers) and total p mass are invariant
    per tick to f32 rounding, NOT quantization precision (the sender
    absorbs its shipped quantization residual; the _ef spellings ride
    that exact absorption as their error feedback)."""
    from bluefog_tpu import windows as win_mod

    rng = np.random.RandomState(7)
    graph = tu.RingGraph(SIZE, connect_style=1)
    bf.set_topology(graph)
    z0 = rng.randn(SIZE, 1024).astype(np.float32) * 2
    periods = {r: int(p) for r, p in enumerate(rng.randint(1, 5, SIZE))}
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    params = {"w": jnp.asarray(z0)}
    state = opt.init(params)

    def loss_fn(p, target):
        return 0.5 * jnp.sum((p["w"] - target) ** 2)

    step = bf.make_async_train_step(
        opt, loss_fn, cadence=periods, wire=wire, max_age=10 ** 6
    )
    batch = jnp.asarray(z0)
    mass0 = float(np.sum(z0, dtype=np.float64))
    scale = max(abs(mass0), float(np.abs(z0).sum()))
    for t in range(20):
        params, state, _ = step(params, state, batch)
        win = win_mod._get_win(bf.get_context(), step.engine._name)
        drift = abs(window_mass(win) - mass0)
        assert drift < 1e-5 * scale, (
            f"tick {t}: wire={wire} mass drift {drift} (scale {scale})"
        )
        assert abs(window_p_mass(win) - SIZE) < 1e-5


@pytest.mark.parametrize("window_wire", [None, "int8", "int4"])
def test_interleaved_accumulate_update_conserves_mass(
    window_wire, monkeypatch,
):
    """Raw window-op form of the async property: a random interleave
    of per-rank-participation ``win_accumulate`` (column-stochastic
    shares, sitting-out ranks as ``None`` spec entries) and
    per-rank-participation collecting ``win_update`` conserves total
    mass under every window wire tier."""
    if window_wire is not None:
        monkeypatch.setenv("BLUEFOG_WINDOW_WIRE", window_wire)
    rng = np.random.RandomState(11)
    graph = tu.RingGraph(SIZE, connect_style=1)
    bf.set_topology(graph)
    z0 = rng.randn(SIZE, 1024).astype(np.float32)
    x = bf.worker_values(lambda r: z0[r])
    bf.win_create(x, "async_prop", zero_init=True)
    bf.turn_on_win_ops_with_associated_p()
    ctx = bf.get_context()
    win = ctx.windows["async_prop"]
    outs = ctx.out_neighbor_ranks()
    mass0 = float(np.sum(z0, dtype=np.float64))
    scale = float(np.abs(z0).sum())
    for t in range(12):
        if rng.rand() < 0.6:  # a partial-participation accumulate
            part = rng.rand(SIZE) < 0.7
            dst = [
                {d: 1.0 / (len(outs[r]) + 1) for d in outs[r]}
                if part[r] else None
                for r in range(SIZE)
            ]
            sw = {
                r: 1.0 / (len(outs[r]) + 1)
                for r in range(SIZE) if part[r]
            }
            bf.win_accumulate(
                name="async_prop", self_weight=sw, dst_weights=dst
            )
        else:  # a partial-participation collect
            part = rng.rand(SIZE) < 0.7
            nw = [
                {s: 1.0 for s in win.in_neighbors[r]}
                if part[r] else None
                for r in range(SIZE)
            ]
            bf.win_update(
                name="async_prop", self_weight=1.0,
                neighbor_weights=nw, reset=True,
            )
        total = float(
            np.sum(np.asarray(win.value), dtype=np.float64)
        ) + float(np.sum(np.asarray(win.buffers), dtype=np.float64))
        assert abs(total - mass0) < 1e-5 * max(scale, 1.0), (
            f"op {t}: wire={window_wire} drift {abs(total - mass0)}"
        )
    bf.turn_off_win_ops_with_associated_p()


def test_get_win_age_oracle_decoupled_cadences():
    """Host-oracle pin of the window age lane under the async engine's
    decoupled cadences: after T ticks, the slot fed by sender s (period
    P_s) must report age T - last_write_clock, where sender s last
    wrote at tick floor((T-1)/P_s)*P_s (stamped at clock tick+1)."""
    rng = np.random.RandomState(13)
    graph = tu.RingGraph(SIZE, connect_style=1)
    bf.set_topology(graph)
    z0 = rng.randn(SIZE, DIM).astype(np.float32)
    periods = {r: int(p) for r, p in enumerate(rng.randint(1, 6, SIZE))}
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    params = {"w": jnp.asarray(z0)}
    state = opt.init(params)

    def loss_fn(p, target):
        return 0.5 * jnp.sum((p["w"] - target) ** 2)

    step = bf.make_async_train_step(
        opt, loss_fn, cadence=periods, max_age=10 ** 6
    )
    batch = jnp.asarray(z0)
    for ticks in (1, 3, 7, 12):
        while step.engine._tick < ticks:
            params, state, _ = step(params, state, batch)
        ages = bf.get_win_age(step.engine._name)
        for r in range(SIZE):
            for s, age in ages[r].items():
                last_tick = ((ticks - 1) // periods[s]) * periods[s]
                expected = ticks - (last_tick + 1)
                assert age == expected, (
                    f"T={ticks} edge {s}->{r}: age {age} != {expected} "
                    f"(period {periods[s]})"
                )


def test_star_accumulated_p_preserves_exact_mean():
    """What the departure buys: on the star the accumulated-p recursion
    still converges to the exact average; the reference's reset recursion
    settles on a skewed consensus (center over-weighted)."""
    z0, c = problem(2)
    graph = tu.StarGraph(SIZE)
    w = sender_stochastic_matrix(graph, SIZE)
    got = run_window_optimizer(graph, z0, c, lr=0.0, steps=120)
    np.testing.assert_allclose(
        got[-1], np.tile(z0.mean(0), (SIZE, 1)), atol=1e-3
    )
    ref = z0.copy()
    for _ in range(120):
        ref = reference_pushsum(ref, c, 0.0, 1, w)
    # reference limit is a consensus, but NOT the mean
    assert np.abs(ref - ref.mean(0)).max() < 1e-3
    assert np.abs(ref.mean(0) - z0.mean(0)).max() > 1e-2
