# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Push-sum iterate oracle: numpy model of the reference recursion.

The reference push-sum optimizer (``torch/optimizers.py:1026-1177``) runs,
per iteration, with sender-stochastic weights W (W[i,j] = the share of
rank i's mass sent to j; rows sum to 1; diagonal = self_weight):

    zu_i(t)  = z_i(t) - lr * grad_i(z_i(t))          (inner SGD on iterate)
    x_j(t+1) = sum_i W[i,j] * zu_i(t)                (win_accumulate+collect)
    w_j(t+1) = sum_i W[i,j] * 1                      (ps-weight lane, RESET
    z_j(t+1) = x_j(t+1) / w_j(t+1)                    to 1 every iteration)

The TPU window-optimizer (``optimizers._WindowOptimizer`` mode='push_sum')
keeps the textbook accumulated-p recursion instead:

    u_i(t)   = x_i(t) - lr * grad_i  (grads evaluated at z = x/p by caller)
    x_j(t+1) = sum_i W[i,j] * u_i(t)
    p_j(t+1) = sum_i W[i,j] * p_i(t)                 (NEVER reset)
    z_j(t+1) = x_j(t+1) / p_j(t+1)

**Exact divergence point** (pinned below): on weight-balanced topologies
(every column of W sums to 1 — all regular digraphs with uniform weights,
e.g. a directed ring or Exp2) the two recursions are IDENTICAL: w stays 1,
x stays the corrected iterate, so the reset is invisible. On non-balanced
digraphs (e.g. a star) they agree at t=1 and diverge from t=2 on — and it
is the reference's reset variant that loses push-sum's mass-conservation
guarantee (its consensus limit is a skewed average on such graphs), while
the accumulated-p recursion converges to the exact mean. The numpy models
here are the committed oracle for both claims.
"""

import numpy as np
import pytest

import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import topology as tu

SIZE = 8
DIM = 3


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    yield
    bf.win_free()
    bf.shutdown()


def sender_stochastic_matrix(graph, size):
    """W[i, j]: uniform split of rank i's mass over self + out-neighbors
    (the reference's default dst_weights/self_weight, optimizers.py:1032)."""
    w = np.zeros((size, size))
    for i in range(size):
        outs = [j for j in graph.successors(i) if j != i]
        share = 1.0 / (len(outs) + 1)
        w[i, i] = share
        for j in outs:
            w[i, j] = share
    return w


def reference_pushsum(z0, c, lr, steps, w):
    """The reference recursion (corrected iterate, ps-weight reset)."""
    z = z0.copy()
    for _ in range(steps):
        zu = z - lr * (z - c)
        x = w.T @ zu
        wsum = w.T @ np.ones(len(z0))
        z = x / wsum[:, None]
    return z


def accumulated_pushsum(z0, c, lr, steps, w):
    """The TPU window-optimizer recursion (raw x, accumulated p)."""
    x = z0.copy()
    p = np.ones(len(z0))
    z = x / p[:, None]
    out = []
    for _ in range(steps):
        u = x - lr * (z - c)  # grads evaluated at the corrected estimate
        x = w.T @ u
        p = w.T @ p
        z = x / p[:, None]
        out.append(z.copy())
    return np.asarray(out)


def run_window_optimizer(graph, z0, c, lr, steps):
    bf.set_topology(graph)
    opt = bf.DistributedPushSumOptimizer(optax.sgd(lr))
    params = {"w": bf.worker_values(lambda r: z0[r])}
    state = opt.init(params)
    seq = []
    for _ in range(steps):
        est = opt.params()
        grads = {"w": est["w"] - jnp.asarray(c)}
        _, state = opt.step(state, grads)
        seq.append(np.asarray(opt.params()["w"]))
    opt.free()
    return np.asarray(seq)


def problem(seed=0):
    rng = np.random.RandomState(seed)
    z0 = rng.randn(SIZE, DIM).astype(np.float32)
    c = z0.copy()  # pure-local optimum: only communication creates motion
    return z0, c


def test_ring_iterate_sequence_matches_reference_oracle():
    """On a directed ring (weight-balanced) the window optimizer's iterate
    sequence equals the reference recursion step for step."""
    z0, c = problem()
    graph = tu.RingGraph(SIZE, connect_style=1)  # directed one-way ring
    w = sender_stochastic_matrix(graph, SIZE)
    assert np.allclose(w.sum(1), 1.0) and np.allclose(w.sum(0), 1.0)
    got = run_window_optimizer(graph, z0, c, lr=0.2, steps=12)
    z = z0.copy()
    for t in range(12):
        z = reference_pushsum(z, c, 0.2, 1, w)
        np.testing.assert_allclose(got[t], z, rtol=1e-4, atol=1e-5,
                                   err_msg=f"diverged at step {t}")


def test_ring_consensus_reaches_exact_mean():
    z0, c = problem()
    graph = tu.RingGraph(SIZE, connect_style=1)
    got = run_window_optimizer(graph, z0, c, lr=0.0, steps=200)
    np.testing.assert_allclose(
        got[-1], np.tile(z0.mean(0), (SIZE, 1)), atol=1e-3
    )


def test_star_divergence_point_is_step_two():
    """Non-balanced digraph: the recursions agree at t=1, split at t=2
    (the reference resets w to 1 after its first collect; the accumulated-p
    lane keeps mass). This is the documented iterate-bookkeeping departure
    (optimizers.py DistributedPushSumOptimizer docstring)."""
    z0, c = problem(1)
    graph = tu.StarGraph(SIZE)
    w = sender_stochastic_matrix(graph, SIZE)
    assert not np.allclose(w.sum(0), 1.0)  # star is not weight-balanced
    got = run_window_optimizer(graph, z0, c, lr=0.0, steps=2)
    oracle_acc = accumulated_pushsum(z0, c, 0.0, 2, w)
    # our implementation IS the accumulated-p oracle on any graph
    np.testing.assert_allclose(got, oracle_acc, rtol=1e-4, atol=1e-5)
    # vs the reference recursion: equal at t=1 ...
    ref1 = reference_pushsum(z0, c, 0.0, 1, w)
    np.testing.assert_allclose(got[0], ref1, rtol=1e-4, atol=1e-5)
    # ... diverged at t=2
    ref2 = reference_pushsum(z0, c, 0.0, 2, w)
    assert np.abs(got[1] - ref2).max() > 1e-3


def test_star_accumulated_p_preserves_exact_mean():
    """What the departure buys: on the star the accumulated-p recursion
    still converges to the exact average; the reference's reset recursion
    settles on a skewed consensus (center over-weighted)."""
    z0, c = problem(2)
    graph = tu.StarGraph(SIZE)
    w = sender_stochastic_matrix(graph, SIZE)
    got = run_window_optimizer(graph, z0, c, lr=0.0, steps=120)
    np.testing.assert_allclose(
        got[-1], np.tile(z0.mean(0), (SIZE, 1)), atol=1e-3
    )
    ref = z0.copy()
    for _ in range(120):
        ref = reference_pushsum(ref, c, 0.0, 1, w)
    # reference limit is a consensus, but NOT the mean
    assert np.abs(ref - ref.mean(0)).max() < 1e-3
    assert np.abs(ref.mean(0) - z0.mean(0)).max() > 1e-2
