# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Launcher layer: arg surface, env contract, host bring-up, multi-process
context branches (reference run/run.py:58-203 parity)."""

import os
import subprocess
import sys

import pytest

from bluefog_tpu.run import network_util
from bluefog_tpu.run.run import (
    build_child_env,
    build_host_commands,
    parse_args,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- network_util --------------------------------------------------------------


def test_parse_hosts():
    hosts = network_util.parse_hosts("host1:2,host2:4,host3")
    assert hosts == [("host1", 2), ("host2", 4), ("host3", 1)]


def test_parse_hosts_empty_raises():
    with pytest.raises(ValueError):
        network_util.parse_hosts(" , ")


def test_parse_hostfile(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text(
        "# pod hosts\nhost1 slots=4\n\nhost2 slots = 4  # trailing\nhost3\n"
    )
    assert network_util.parse_hostfile(str(hf)) == [
        ("host1", 4),
        ("host2", 4),
        ("host3", 1),
    ]


def test_parse_hostfile_malformed(tmp_path):
    hf = tmp_path / "hosts"
    hf.write_text("host1 slots=two\n")
    with pytest.raises(ValueError):
        network_util.parse_hostfile(str(hf))


def test_filter_local_addresses():
    remote = network_util.filter_local_addresses(
        ["localhost", "127.0.0.1", "farawayhost"]
    )
    assert remote == ["farawayhost"]


# -- arg surface (reference run/run.py:58-118) ---------------------------------


def test_parse_args_requires_np():
    with pytest.raises(SystemExit):
        parse_args(["train.py"])


def test_parse_args_surface():
    args = parse_args(
        [
            "-np", "8", "--platform", "cpu", "--timeline-filename", "/tmp/tl",
            "--extra-env", "FOO=1", "--verbose", "train.py", "--lr", "0.1",
        ]
    )
    assert args.np == 8
    assert args.platform == "cpu"
    assert args.command == ["train.py", "--lr", "0.1"]


def test_parse_args_coordinator_pair_required():
    with pytest.raises(SystemExit):
        parse_args(["-np", "8", "--coordinator", "h:1", "x.py"])


# -- env contract --------------------------------------------------------------


def test_child_env_cpu_mode():
    args = parse_args(["-np", "4", "--platform", "cpu", "x.py"])
    env = build_child_env(args, base_env={})
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["BLUEFOG_NUM_WORKERS"] == "4"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]


def test_child_env_auto_keeps_platform_and_ambient_env_intact():
    args = parse_args(["-np", "4", "x.py"])
    before = os.environ.get("XLA_FLAGS")
    env = build_child_env(args, base_env={"PATH": "/bin"})
    assert "JAX_PLATFORMS" not in env
    assert env["PATH"] == "/bin"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert os.environ.get("XLA_FLAGS") == before  # launcher env untouched


def test_child_env_timeline_and_extra():
    args = parse_args(
        ["-np", "2", "--timeline-filename", "/tmp/tl_", "--extra-env",
         "A=b", "x.py"]
    )
    env = build_child_env(args, base_env={})
    assert env["BLUEFOG_TIMELINE"] == "/tmp/tl_"
    assert env["A"] == "b"


def test_child_env_coordinator():
    args = parse_args(
        ["-np", "8", "--coordinator", "h0:9781", "--num-processes", "2",
         "--process-id", "1", "x.py"]
    )
    env = build_child_env(args, base_env={})
    assert env["BLUEFOG_COORDINATOR"] == "h0:9781"
    assert env["BLUEFOG_NUM_PROCESSES"] == "2"
    assert env["BLUEFOG_PROCESS_ID"] == "1"


# -- multi-host bring-up -------------------------------------------------------


def test_host_commands_slots_mismatch():
    args = parse_args(["-np", "4", "-H", "h1:4,h2:4", "x.py"])
    hosts = network_util.parse_hosts(args.hosts)
    with pytest.raises(ValueError):
        build_host_commands(args, hosts)


def test_host_commands_shape():
    args = parse_args(["-np", "8", "-H", "localhost:4,far1:4", "x.py"])
    hosts = network_util.parse_hosts(args.hosts)
    cmds = build_host_commands(args, hosts)
    assert len(cmds) == 2
    # process 0 on the local host: plain env-wrapped python
    host0, argv0 = cmds[0]
    assert argv0[0] == "env"
    joined0 = " ".join(argv0)
    assert "BLUEFOG_PROCESS_ID=0" in joined0
    assert "BLUEFOG_NUM_PROCESSES=2" in joined0
    # 'localhost' would resolve to the remote machine itself; the
    # coordinator must be advertised under a routable name.
    assert "BLUEFOG_COORDINATOR=localhost:" not in joined0
    assert (
        f"BLUEFOG_COORDINATOR={network_util.reachable_local_name()}:"
        in joined0
    )
    # each controller exposes only its own host's worker devices
    assert "--xla_force_host_platform_device_count=4" in joined0
    assert sys.executable in argv0  # .py command runs under the interpreter
    # process 1 remote: ssh wrapper
    host1, argv1 = cmds[1]
    assert argv1[0] == "ssh" and "far1" in argv1
    assert "BLUEFOG_PROCESS_ID=1" in argv1[-1]


def test_host_commands_forward_ambient_xla_flags(monkeypatch):
    monkeypatch.setenv("XLA_FLAGS", "--xla_dump_to=/tmp/d")
    args = parse_args(["-np", "2", "-H", "far1:1,far2:1", "x.py"])
    cmds = build_host_commands(args, network_util.parse_hosts(args.hosts))
    for _h, argv in cmds:
        joined = argv[-1]  # remote: quoted command string
        assert "--xla_dump_to=/tmp/d" in joined
        assert "--xla_force_host_platform_device_count=1" in joined


def test_host_commands_ssh_port():
    args = parse_args(["-np", "2", "-H", "far1:1,far2:1", "-p", "2222", "x.py"])
    cmds = build_host_commands(args, network_util.parse_hosts(args.hosts))
    assert all("-p" in argv and "2222" in argv for _h, argv in cmds)


# -- multi-process context branches (mocked process topology) ------------------


class FakeDev:
    def __init__(self, process_index, ident):
        self.process_index = process_index
        self.ident = ident

    def __repr__(self):
        return f"d{self.ident}@p{self.process_index}"


def test_order_devices_for_mesh_groups_by_process():
    from bluefog_tpu.context import order_devices_for_mesh

    devs = [FakeDev(pi, i) for i, pi in enumerate([1, 0, 1, 0])]
    ordered = order_devices_for_mesh(devs, multi_process=True)
    assert [d.process_index for d in ordered] == [0, 0, 1, 1]
    # stable within each process group
    assert [d.ident for d in ordered] == [1, 3, 0, 2]


def test_default_nodes_per_machine():
    from bluefog_tpu.context import default_nodes_per_machine

    devs = [FakeDev(pi, i) for i, pi in enumerate([0, 0, 0, 1, 1, 1])]
    assert default_nodes_per_machine(devs, process_count=2) == 3
    assert default_nodes_per_machine(devs, process_count=1) is None


def test_maybe_init_distributed(monkeypatch):
    """Argument-contract check only (env -> initialize kwargs); the real
    two-process bring-up is proven end-to-end in test_multiprocess.py."""
    import jax

    from bluefog_tpu import context as ctx

    calls = {}

    def fake_initialize(coordinator_address, num_processes, process_id):
        calls.update(
            addr=coordinator_address, n=num_processes, pid=process_id
        )

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(ctx, "_distributed_initialized", False)
    monkeypatch.setenv("BLUEFOG_COORDINATOR", "h0:9781")
    monkeypatch.setenv("BLUEFOG_NUM_PROCESSES", "4")
    monkeypatch.setenv("BLUEFOG_PROCESS_ID", "3")
    assert ctx.maybe_init_distributed() is True
    assert calls == {"addr": "h0:9781", "n": 4, "pid": 3}
    # second call is a no-op
    assert ctx.maybe_init_distributed() is False


def test_maybe_init_distributed_without_env(monkeypatch):
    from bluefog_tpu import context as ctx

    monkeypatch.delenv("BLUEFOG_COORDINATOR", raising=False)
    monkeypatch.setattr(ctx, "_distributed_initialized", False)
    assert ctx.maybe_init_distributed() is False


# -- end-to-end: bfrun-tpu launches a real program -----------------------------


E2E_SCRIPT = """
import bluefog_tpu as bf
import jax, numpy as np
bf.init()
assert bf.size() == 4, bf.size()
x = np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
y = bf.neighbor_allreduce(jax.device_put(x, jax.sharding.NamedSharding(
    bf.get_context().mesh, jax.sharding.PartitionSpec("workers"))))
assert np.asarray(y).shape == (4, 3)
print("E2E_OK")
"""


def test_bfrun_end_to_end(tmp_path):
    script = tmp_path / "prog.py"
    script.write_text(E2E_SCRIPT)
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_NUM_WORKERS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [
            sys.executable, "-m", "bluefog_tpu.run.run", "-np", "4",
            "--platform", "cpu", str(script),
        ],
        capture_output=True,
        text=True,
        timeout=240,
        env=env,
        cwd=str(tmp_path),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "E2E_OK" in out.stdout


def test_bfrun_version():
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run.run", "--version"],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "PYTHONPATH": REPO},
    )
    assert out.returncode == 0
    assert out.stdout.strip()


def test_ibfrun_start_executes_env_contract(tmp_path):
    """``ibfrun-tpu start -np 4 <cmd>`` must exec the child with the
    launcher env contract applied (worker count, dev platform) and the
    stall watchdog defaulted OFF for interactive think time."""
    import subprocess
    import sys

    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os\n"
        "assert os.environ['BLUEFOG_NUM_WORKERS'] == '4', os.environ\n"
        "assert os.environ['BLUEFOG_STALL_TIMEOUT'] == '0', os.environ\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import bluefog_tpu as bf\n"
        "bf.init()\n"
        "assert bf.size() == 4, bf.size()\n"
        "import numpy as np\n"
        "x = bf.worker_values(lambda r: np.full((2,), float(r), np.float32))\n"
        "for _ in range(20):\n"
        "    x = bf.neighbor_allreduce(x)\n"
        "mse = float(np.mean((np.asarray(x) - 1.5) ** 2))\n"
        "assert mse < 1e-6, mse\n"
        "bf.suspend(); bf.resume(); bf.shutdown()\n"
        "print('IBFRUN_OK')\n"
    )
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run.interactive_run",
         "start", "-np", "4", "--platform", "cpu",
         sys.executable, str(probe)],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "IBFRUN_OK" in out.stdout


def test_interactive_notebook_cells_execute(tmp_path):
    """The committed notebook example (reference
    examples/interactive_bluefog_helloworld.ipynb analogue) must stay
    runnable: execute its code cells in order in a child interpreter
    under the ibfrun env contract."""
    import json
    import subprocess
    import sys

    nb_path = os.path.join(REPO, "examples", "interactive_helloworld.ipynb")
    with open(nb_path) as f:
        nb = json.load(f)
    cells = [
        "".join(c["source"]) for c in nb["cells"]
        if c["cell_type"] == "code"
    ]
    script = tmp_path / "nb.py"
    script.write_text("\n\n".join(cells))
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.run.interactive_run",
         "start", "-np", "8", "--platform", "cpu",
         sys.executable, str(script)],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "done" in out.stdout


# -- restart policy (--max-restarts / BLUEFOG_MAX_RESTARTS) --------------------


def test_resolve_max_restarts_precedence():
    from bluefog_tpu.run.run import resolve_max_restarts

    flag = parse_args(["-np", "2", "--max-restarts", "3", "x.py"])
    assert resolve_max_restarts(flag, env={"BLUEFOG_MAX_RESTARTS": "9"}) == 3
    noflag = parse_args(["-np", "2", "x.py"])
    assert resolve_max_restarts(noflag, env={"BLUEFOG_MAX_RESTARTS": "5"}) == 5
    assert resolve_max_restarts(noflag, env={}) == 0
    with pytest.raises(ValueError):
        resolve_max_restarts(noflag, env={"BLUEFOG_MAX_RESTARTS": "many"})
    with pytest.raises(ValueError):
        resolve_max_restarts(
            parse_args(["-np", "2", "--max-restarts", "-1", "x.py"]), env={}
        )


def test_backoff_is_exponential_and_capped():
    from bluefog_tpu.run.run import backoff_seconds

    assert [backoff_seconds(a, base=1.0, cap=30.0) for a in range(6)] == [
        1.0, 2.0, 4.0, 8.0, 16.0, 30.0
    ]
    assert backoff_seconds(50, base=1.0, cap=30.0) == 30.0


def test_run_with_restarts_retries_then_succeeds():
    from bluefog_tpu.run.run import run_with_restarts

    codes = iter([1, 1, 0])
    sleeps, logs = [], []
    rc = run_with_restarts(
        lambda: next(codes), max_restarts=5, sleep=sleeps.append,
        log=logs.append,
    )
    assert rc == 0
    assert sleeps == [1.0, 2.0]  # exponential backoff between attempts
    assert len(logs) == 2 and "restart 1/5" in logs[0]


def test_run_with_restarts_exhausts_budget():
    from bluefog_tpu.run.run import run_with_restarts

    calls = []
    rc = run_with_restarts(
        lambda: calls.append(1) or 7, max_restarts=2,
        sleep=lambda s: None,
    )
    assert rc == 7
    assert len(calls) == 3  # initial + 2 restarts


def test_run_with_restarts_zero_budget_fails_fast():
    from bluefog_tpu.run.run import run_with_restarts

    calls = []
    rc = run_with_restarts(
        lambda: calls.append(1) or 3, max_restarts=0,
        sleep=lambda s: (_ for _ in ()).throw(AssertionError("no sleep")),
    )
    assert rc == 3 and len(calls) == 1
