# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Torch-frontend tests: the second frontend over the one runtime.

Mirrors the coverage of the reference's second-frontend test file
(``test/tensorflow_ops_test.py``, 12 cases): op semantics against numpy
oracles, registered gradients, dtype fidelity, and the optimizer wrappers.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import bluefog_tpu as bf
import bluefog_tpu.torch as bft
from bluefog_tpu import topology as tu

SIZE = 8
DIM = 4


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    yield
    bf.shutdown()


def stacked(fill=None, shape=(DIM,), dtype=torch.float32):
    if fill is None:
        return torch.stack(
            [torch.full(shape, float(r)) for r in range(SIZE)]
        ).to(dtype)
    return torch.as_tensor(fill, dtype=dtype)


# -- op semantics --------------------------------------------------------------


def test_allreduce_mean():
    out = bft.allreduce(stacked())
    assert isinstance(out, torch.Tensor)
    torch.testing.assert_close(
        out, torch.full((SIZE, DIM), (SIZE - 1) / 2.0)
    )


def test_allreduce_sum():
    out = bft.allreduce(stacked(), average=False)
    torch.testing.assert_close(
        out, torch.full((SIZE, DIM), float(SIZE * (SIZE - 1) // 2))
    )


def test_broadcast():
    out = bft.broadcast(stacked(), root_rank=5)
    torch.testing.assert_close(out, torch.full((SIZE, DIM), 5.0))


def test_allgather():
    out = bft.allgather(stacked(shape=(2,)))
    assert out.shape == (SIZE, SIZE * 2)
    expected = torch.repeat_interleave(
        torch.arange(SIZE, dtype=torch.float32), 2
    )
    torch.testing.assert_close(out[3], expected)


def test_neighbor_allreduce_matches_numpy_oracle():
    bf.set_topology(tu.RingGraph(SIZE))
    x = np.random.RandomState(0).randn(SIZE, DIM).astype(np.float32)
    out = bft.neighbor_allreduce(torch.from_numpy(x.copy()))
    w = np.zeros((SIZE, SIZE))
    for j in range(SIZE):
        for i in (j - 1, j, j + 1):
            w[i % SIZE, j] = 1.0 / 3.0
    np.testing.assert_allclose(out.numpy(), w.T @ x, rtol=1e-5, atol=1e-6)


def test_neighbor_allreduce_explicit_weights():
    sw = 0.5
    srcs = [{(r - 1) % SIZE: 0.5} for r in range(SIZE)]
    x = stacked()
    out = bft.neighbor_allreduce(x, self_weight=sw, src_weights=srcs)
    expected = 0.5 * x + 0.5 * torch.roll(x, 1, dims=0)
    torch.testing.assert_close(out, expected)


def test_neighbor_allgather():
    bf.set_topology(tu.RingGraph(SIZE))
    outs = bft.neighbor_allgather(stacked(shape=(2,)))
    assert len(outs) == SIZE
    # ring in-neighbors of rank 3 are {2, 4}, rank-ascending
    torch.testing.assert_close(
        outs[3], torch.tensor([[2.0, 2.0], [4.0, 4.0]])
    )


# -- registered gradients ------------------------------------------------------


def test_allreduce_gradient():
    x = stacked().requires_grad_(True)
    y = bft.allreduce(x)
    v = torch.randn(SIZE, DIM)
    (y * v).sum().backward()
    torch.testing.assert_close(x.grad, v.mean(0, keepdim=True).expand_as(v))


def test_broadcast_gradient():
    x = stacked().requires_grad_(True)
    bft.broadcast(x, root_rank=2).sum().backward()
    expected = torch.zeros(SIZE, DIM)
    expected[2] = SIZE
    torch.testing.assert_close(x.grad, expected)


def test_neighbor_allreduce_gradient_is_transposed_combine():
    bf.set_topology(tu.ExponentialTwoGraph(SIZE))
    xnp = np.random.RandomState(1).randn(SIZE, DIM).astype(np.float32)
    vnp = np.random.RandomState(2).randn(SIZE, DIM).astype(np.float32)
    x = torch.from_numpy(xnp.copy()).requires_grad_(True)
    y = bft.neighbor_allreduce(x)
    (y * torch.from_numpy(vnp)).sum().backward()
    from bluefog_tpu.collective.plan import plan_from_topology

    w = plan_from_topology(
        tu.ExponentialTwoGraph(SIZE), weighted=False
    ).weight_matrix()
    np.testing.assert_allclose(x.grad.numpy(), w @ vnp, rtol=1e-5,
                               atol=1e-6)


def test_full_jacobian_equals_weight_matrix():
    """Column-by-column Jacobian extraction: d y_j / d x_i == W[i, j]
    exactly (float64 gradcheck is unavailable — the mesh computes in f32
    unless jax_enable_x64, which is process-global; exact f32 equality on
    the linear op is the equivalent proof)."""
    bf.set_topology(tu.RingGraph(SIZE))
    from bluefog_tpu.collective.plan import plan_from_topology

    w = plan_from_topology(
        tu.RingGraph(SIZE), weighted=False
    ).weight_matrix()
    jac = np.zeros((SIZE, SIZE), np.float32)
    for j in range(SIZE):
        x = torch.zeros(SIZE, 1, requires_grad=True)
        y = bft.neighbor_allreduce(x)
        g = torch.zeros_like(y)
        g[j, 0] = 1.0
        (gx,) = torch.autograd.grad(y, x, g)
        jac[:, j] = gx.numpy()[:, 0]
    np.testing.assert_allclose(jac, w, rtol=1e-6, atol=1e-7)


# -- dtype fidelity ------------------------------------------------------------


def test_bfloat16_roundtrip_bit_exact():
    x = stacked(dtype=torch.bfloat16)
    back = bft.from_numpy(bft.to_numpy(x))
    assert back.dtype == torch.bfloat16
    assert torch.equal(back.view(torch.uint16), x.view(torch.uint16))


def test_bfloat16_gossip_stays_bfloat16():
    out = bft.neighbor_allreduce(stacked(dtype=torch.bfloat16))
    assert out.dtype == torch.bfloat16


# -- optimizer wrappers --------------------------------------------------------


def quad_problem(seed=0):
    c = np.random.RandomState(seed).randn(SIZE, DIM).astype(np.float32)
    p = torch.nn.Parameter(torch.from_numpy(c.copy()))
    return c, p


def test_gradient_allreduce_optimizer_matches_centralized_sgd():
    c, p = quad_problem()
    opt = bft.DistributedGradientAllreduceOptimizer(
        torch.optim.SGD([p], lr=0.5)
    )
    ref = torch.from_numpy(c.copy())
    for _ in range(10):
        opt.zero_grad()
        loss = 0.5 * ((p - torch.from_numpy(c)) ** 2).sum()
        loss.backward()
        opt.step()
        # centralized oracle: every worker follows the mean gradient
        ref = ref - 0.5 * (ref - torch.from_numpy(c)).mean(0, keepdim=True)
    torch.testing.assert_close(p.data, ref, rtol=1e-4, atol=1e-5)


def test_neighbor_allreduce_optimizer_reaches_consensus():
    c, p = quad_problem(3)
    opt = bft.DistributedNeighborAllreduceOptimizer(
        torch.optim.SGD([p], lr=0.1)
    )
    for _ in range(60):
        opt.zero_grad()
        (0.5 * ((p - torch.from_numpy(c)) ** 2).sum()).backward()
        opt.step()
        # decay: constant-lr CTA keeps a steady-state consensus residual
        opt.param_groups[0]["lr"] *= 0.95
    w = p.data.numpy()
    target = c.mean(0)
    assert np.abs(w - target).max() < 0.25 * np.abs(c - target).max()
    assert np.abs(w - w.mean(0)).max() < 0.2


def test_broadcast_parameters_and_validation():
    params = {
        "a": torch.randn(SIZE, DIM),
        "b": torch.randn(SIZE),
    }
    ref = params["a"][1].clone()
    bft.broadcast_parameters(params, root_rank=1)
    for r in range(SIZE):
        torch.testing.assert_close(params["a"][r], ref)
    with pytest.raises(ValueError, match="root_rank"):
        bft.broadcast_parameters(params, root_rank=SIZE)
    with pytest.raises(ValueError, match="worker-stacked"):
        bft.broadcast_parameters({"x": torch.randn(SIZE + 1, 2)})


def test_wrapper_rejects_unstacked_parameters():
    p = torch.nn.Parameter(torch.randn(SIZE + 1, DIM))
    with pytest.raises(ValueError, match="worker-stacked"):
        bft.DistributedGradientAllreduceOptimizer(
            torch.optim.SGD([p], lr=0.1)
        )


def test_wrapper_is_real_torch_optimizer_with_scheduler():
    """The factories specialize the instance in place, so schedulers,
    state_dict round-trips, and add_param_group all see a genuine
    torch.optim.Optimizer."""
    c, p = quad_problem(5)
    opt = bft.DistributedGradientAllreduceOptimizer(
        torch.optim.SGD([p], lr=0.4)
    )
    assert isinstance(opt, torch.optim.Optimizer)
    sched = torch.optim.lr_scheduler.StepLR(opt, step_size=2, gamma=0.5)
    for _ in range(4):
        opt.zero_grad()
        (0.5 * ((p - torch.from_numpy(c)) ** 2).sum()).backward()
        opt.step()
        sched.step()
    assert opt.param_groups[0]["lr"] == pytest.approx(0.1)
    sd = opt.state_dict()
    opt.load_state_dict(sd)
    # late param groups are validated too
    with pytest.raises(ValueError, match="worker-stacked"):
        opt.add_param_group({"params": [torch.nn.Parameter(torch.ones(3))]})


def test_broadcast_parameters_skips_non_tensor_dict_values():
    params = {
        "w": torch.randn(SIZE, DIM),
        "meta": {"nested": "state"},
        "lst": [1, 2, 3],
    }
    ref = params["w"][0].clone()
    bft.broadcast_parameters(params, root_rank=0)
    torch.testing.assert_close(params["w"][3], ref)
    assert params["meta"] == {"nested": "state"}


def test_64bit_dtypes_rejected_not_truncated():
    """Out-of-range int64 and all float64 would be silently corrupted by
    the 32-bit mesh; the boundary must refuse instead (in-range int64
    narrows losslessly — see test_int64_in_range_narrows_losslessly)."""
    big = torch.full((SIZE, 2), 2**40, dtype=torch.int64)
    with pytest.raises(TypeError, match="int32 range"):
        bft.allreduce(big)
    with pytest.raises(TypeError, match="int32 range"):
        bft.broadcast_parameters([big])
    assert big[0, 0].item() == 2**40  # untouched
    with pytest.raises(TypeError, match="precision"):
        bft.allreduce(torch.randn(SIZE, 2, dtype=torch.float64))


def test_add_param_group_failure_leaves_optimizer_clean():
    c, p = quad_problem(7)
    opt = bft.DistributedGradientAllreduceOptimizer(
        torch.optim.SGD([p], lr=0.1)
    )
    with pytest.raises(ValueError, match="worker-stacked"):
        opt.add_param_group({"params": [torch.nn.Parameter(torch.ones(3))]})
    assert len(opt.param_groups) == 1  # invalid group NOT installed


def test_int64_in_range_narrows_losslessly():
    """Small-valued int64 state (e.g. BatchNorm num_batches_tracked) must
    broadcast fine; only out-of-int32-range values are refused."""
    t = torch.full((SIZE, 2), 7, dtype=torch.int64)
    bft.broadcast_parameters([t], root_rank=3)
    assert t.dtype == torch.int64 and t[0, 0].item() == 7
    with pytest.raises(TypeError, match="int32 range"):
        bft.allreduce(torch.full((SIZE, 2), 2**40, dtype=torch.int64))


def test_add_param_group_accepts_generator():
    c, p = quad_problem(9)
    opt = bft.DistributedGradientAllreduceOptimizer(
        torch.optim.SGD([p], lr=0.1)
    )
    extra = torch.nn.Parameter(torch.randn(SIZE, 2))
    opt.add_param_group({"params": (q for q in [extra])})  # generator
    assert len(opt.param_groups) == 2
    assert len(opt.param_groups[1]["params"]) == 1  # NOT silently empty


def test_int64_results_keep_dtype_and_sum_overflow_refused():
    """Bit-moving ops restore int64; a sum that would wrap int32 refuses."""
    t = torch.full((SIZE, 2), 7, dtype=torch.int64)
    assert bft.broadcast(t, 0).dtype == torch.int64
    assert bft.allgather(t).dtype == torch.int64
    assert bft.allreduce(t, average=False).dtype == torch.int64
    assert bft.allreduce(t, average=False)[0, 0].item() == 7 * SIZE
    near = torch.full((SIZE, 2), 2**28, dtype=torch.int64)  # fits int32,
    with pytest.raises(TypeError, match="overflow"):       # sum does not
        bft.allreduce(near, average=False)


def test_int64_average_inexact_refused():
    """average=True runs through float32, exact only up to |sum| <= 2**24;
    the guard is symmetric with the sum path's overflow refusal."""
    small = torch.full((SIZE, 2), 1000, dtype=torch.int64)
    assert bft.allreduce(small, average=True)[0, 0].item() == 1000.0
    big = torch.full((SIZE, 2), 2**24, dtype=torch.int64)  # in int32 range
    with pytest.raises(TypeError, match="float32"):
        bft.allreduce(big, average=True)


def test_neighbor_optimizer_dynamic_topology_idiom():
    """The reference's per-iteration weight-reassignment idiom
    (README.rst:108-123) through the torch wrapper: assign self/src/dst
    between steps; peers move with no error and consensus still forms."""
    c, p = quad_problem(11)
    opt = bft.DistributedNeighborAllreduceOptimizer(
        torch.optim.SGD([p], lr=0.2)
    )
    for i in range(40):
        shift = 1 + (i % 2)  # alternate one-peer ring distance 1 / 2
        opt.self_weight = 0.5
        opt.src_weights = [{(r - shift) % SIZE: 0.5} for r in range(SIZE)]
        opt.dst_weights = [[(r + shift) % SIZE] for r in range(SIZE)]
        opt.zero_grad()
        (0.5 * ((p - torch.from_numpy(c)) ** 2).sum()).backward()
        opt.step()
        opt.param_groups[0]["lr"] *= 0.95
    w = p.data.numpy()
    assert np.abs(w - w.mean(0)).max() < 0.25
    assert np.abs(w.mean(0) - c.mean(0)).max() < 0.1


def test_neighbor_allreduce_compression():
    """The torch frontend exposes the compressed gossip wire; adjoints
    stay full precision."""
    bf.set_topology(tu.RingGraph(SIZE))
    x = torch.randn(SIZE, 64)
    exact = bft.neighbor_allreduce(x)
    for comp, tol in (("bf16", 0.02), ("int8", 0.05)):
        out = bft.neighbor_allreduce(x, compression=comp)
        assert (out - exact).abs().max().item() < tol, comp
    xg = x.clone().requires_grad_(True)
    bft.neighbor_allreduce(xg, compression="int8").sum().backward()
    assert torch.isfinite(xg.grad).all()

    c, p = quad_problem(13)
    opt = bft.DistributedNeighborAllreduceOptimizer(
        torch.optim.SGD([p], lr=0.1)
    )
    opt.compression = "int8"
    for _ in range(40):
        opt.zero_grad()
        (0.5 * ((p - torch.from_numpy(c)) ** 2).sum()).backward()
        opt.step()
        opt.param_groups[0]["lr"] *= 0.95
    w = p.data.numpy()
    assert np.abs(w - w.mean(0)).max() < 0.25


def test_compression_validated_at_torch_boundary():
    x = torch.randn(SIZE, 4)
    with pytest.raises(ValueError, match="compression must be"):
        bft.neighbor_allreduce(x, compression="fp16")
