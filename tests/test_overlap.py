# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Overlap-layer tests: the fused train step, bucketed gossip, the
delayed (one-step-stale) combine, and the static HLO overlap scan.

The load-bearing guarantee is bitwise equivalence: ``make_train_step``
fuses forward/backward/update/gossip into one program for SCHEDULING
reasons only — the math must be byte-for-byte the legacy two-program
path (grad program + ``opt.step``), with and without wire bucketing.
Fusing or bucketing that changed a single ULP would silently break the
bit-identical-replica invariant the compression paths rely on.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import context as ctx_mod
from bluefog_tpu import topology as tu
from bluefog_tpu.collective import inner, ops as col_ops
from bluefog_tpu.collective.plan import schedule_from_dynamic
from jax.sharding import PartitionSpec as P

from tools.hlo_overlap_scan import scan_overlap

SIZE = 8


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    bf.set_topology(tu.ExponentialTwoGraph(SIZE))
    yield
    bf.shutdown()


# -- a small transformer workload --------------------------------------------


def make_transformer():
    from bluefog_tpu.models.transformer import TransformerLM

    return TransformerLM(
        vocab=64, dim=32, heads=2, layers=2, max_len=16
    )


def transformer_setup(seed=0):
    model = make_transformer()
    rng = np.random.RandomState(seed)
    tokens_np = rng.randint(0, 64, (SIZE, 2, 16)).astype(np.int32)
    p0 = model.init(
        jax.random.PRNGKey(0), jnp.asarray(tokens_np[0])
    )["params"]
    params = jax.tree_util.tree_map(
        lambda t: bf.worker_values(np.asarray(t)), p0
    )
    tokens = bf.worker_values(lambda r: tokens_np[r])

    def loss_fn(p, toks):
        logits = model.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1], toks[:, 1:]
        ).mean()

    return params, tokens, loss_fn


def legacy_grad_fn(loss_fn, example_params):
    ctx = ctx_mod.get_context()
    spec = P(ctx_mod.WORKER_AXIS)

    def body(p_b, t_b):
        p = jax.tree_util.tree_map(lambda t: t[0], p_b)
        g = jax.grad(loss_fn)(p, t_b[0])
        return jax.tree_util.tree_map(lambda t: jnp.expand_dims(t, 0), g)

    return jax.jit(
        jax.shard_map(
            body, mesh=ctx.mesh, in_specs=(spec, spec), out_specs=spec
        )
    )


def assert_trees_bitwise(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


FACTORIES = {
    "cta": bf.DistributedNeighborAllreduceOptimizer,
    "atc": lambda tx: bf.DistributedAdaptThenCombineOptimizer(
        tx, bf.CommunicationType.neighbor_allreduce
    ),
}


@pytest.mark.parametrize("order", ["cta", "atc"])
@pytest.mark.parametrize("schedule", ["static", "dynamic"])
@pytest.mark.parametrize("bucketed", [False, True])
def test_fused_bitwise_matches_two_program(order, schedule, bucketed,
                                           monkeypatch):
    """make_train_step == grad-program + opt.step, to the bit, on a small
    transformer — for ATC and CTA, static and dynamic schedules, with
    and without wire bucketing (the fusion is a scheduling change, never
    a numerics change)."""
    monkeypatch.setenv(
        "BLUEFOG_BUCKET_BYTES", "2048" if bucketed else "0"
    )
    params, tokens, loss_fn = transformer_setup()

    def configure(opt):
        if schedule == "dynamic":
            opt.schedule = schedule_from_dynamic(
                SIZE,
                lambda r: tu.GetDynamicOnePeerSendRecvRanks(
                    tu.ExponentialGraph(SIZE), r
                ),
            )

    opt1 = FACTORIES[order](optax.sgd(0.1, momentum=0.9))
    configure(opt1)
    p1 = params
    s1 = opt1.init(p1)
    grad_fn = legacy_grad_fn(loss_fn, params)

    opt2 = FACTORIES[order](optax.sgd(0.1, momentum=0.9))
    configure(opt2)
    p2 = params
    s2 = opt2.init(p2)
    train_step = opt2.make_train_step(loss_fn)

    for _ in range(3):
        g = grad_fn(p1, tokens)
        p1, s1 = opt1.step(p1, s1, g)
        p2, s2, loss = train_step(p2, s2, tokens)
    assert_trees_bitwise(p1, p2)
    assert_trees_bitwise(s1, s2)
    assert np.isfinite(np.asarray(loss)).all()


def test_bucketed_gossip_bitwise_matches_monolithic(monkeypatch):
    """Bucketing is pure payload slicing: same bits out, whatever the
    cap (the combine is elementwise; concat/split never reorders leaf
    math)."""
    params, tokens, loss_fn = transformer_setup()
    results = {}
    for cap in ("0", "2048"):
        monkeypatch.setenv("BLUEFOG_BUCKET_BYTES", cap)
        opt = bf.DistributedNeighborAllreduceOptimizer(
            optax.sgd(0.1, momentum=0.9)
        )
        p = params
        s = opt.init(p)
        train_step = opt.make_train_step(loss_fn)
        for _ in range(2):
            p, s, _loss = train_step(p, s, tokens)
        results[cap] = (p, s)
    assert_trees_bitwise(results["0"][0], results["2048"][0])
    assert_trees_bitwise(results["0"][1], results["2048"][1])


@pytest.mark.parametrize("wire", ["int8_ef", "int4_ef"])
def test_bucketed_ef_bitwise_matches_monolithic(wire, monkeypatch):
    """Error-feedback compression under bucketing: the residual state is
    sliced with the payload and bucket bounds snap to the quantization
    chunk, so bucketed int8_ef / int4_ef is bitwise the monolithic
    wire — state included (int4_ef additionally exercises the packed
    nibble wire across bucket boundaries)."""
    n = 2048
    rng = np.random.RandomState(3)
    c = rng.randn(SIZE, n).astype(np.float32)
    results = {}
    for cap in ("0", "4096"):  # 1024-elem buckets, 512-aligned
        monkeypatch.setenv("BLUEFOG_BUCKET_BYTES", cap)
        opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
        opt.compression = wire
        params = {"w": bf.worker_values(lambda r: c[r])}
        s = opt.init(params)
        p = params
        for _ in range(3):
            p, s = opt.step(p, s, {"w": p["w"] - jnp.asarray(c)})
        results[cap] = (p, opt._ef)
    assert_trees_bitwise(results["0"][0], results["4096"][0])
    assert_trees_bitwise(results["0"][1], results["4096"][1])


def test_fused_int4_bitwise_matches_two_program(monkeypatch):
    """The fused train step with the int4 wire == grad-program +
    opt.step, to the bit, bucketed — the new tier rides the shared
    _combine_update core like every other wire."""
    monkeypatch.setenv("BLUEFOG_BUCKET_BYTES", "4096")
    n = 2048
    rng = np.random.RandomState(9)
    c = rng.randn(SIZE, n).astype(np.float32)
    cvals = bf.worker_values(lambda r: c[r])

    def loss_fn(p, cv):
        return 0.5 * jnp.sum((p["w"] - cv) ** 2)

    opt1 = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    opt1.compression = "int4"
    params = {"w": bf.worker_values(lambda r: c[r] + 1.0)}
    p1, s1 = params, opt1.init(params)
    grad_fn = legacy_grad_fn(loss_fn, params)
    opt2 = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    opt2.compression = "int4"
    p2, s2 = params, opt2.init(params)
    train_step = opt2.make_train_step(loss_fn)
    for _ in range(3):
        g = grad_fn(p1, cvals)
        p1, s1 = opt1.step(p1, s1, g)
        p2, s2, _loss = train_step(p2, s2, cvals)
    assert_trees_bitwise(p1, p2)
    assert_trees_bitwise(s1, s2)


def test_fused_gradient_allreduce_matches_two_program():
    """order='grad' fused path: gradient averaging inside the fused
    program tracks the legacy two-program path bitwise."""
    params, tokens, loss_fn = transformer_setup()
    opt1 = bf.DistributedGradientAllreduceOptimizer(optax.sgd(0.1))
    p1, s1 = params, opt1.init(params)
    grad_fn = legacy_grad_fn(loss_fn, params)
    opt2 = bf.DistributedGradientAllreduceOptimizer(optax.sgd(0.1))
    p2, s2 = params, opt2.init(params)
    train_step = opt2.make_train_step(loss_fn)
    for _ in range(2):
        g = grad_fn(p1, tokens)
        p1, s1 = opt1.step(p1, s1, g)
        p2, s2, _loss = train_step(p2, s2, tokens)
    assert_trees_bitwise(p1, p2)
    assert_trees_bitwise(s1, s2)


def test_fused_num_steps_per_communication_matches_legacy():
    """K=2 through the fused builder: local call then communicating
    call, identical to the legacy path's own K=2 sequence."""
    params, tokens, loss_fn = transformer_setup()
    tx = optax.sgd(0.1)
    opt1 = bf.DistributedNeighborAllreduceOptimizer(
        tx, num_steps_per_communication=2
    )
    p1, s1 = params, opt1.init(params)
    grad_fn = legacy_grad_fn(loss_fn, params)
    opt2 = bf.DistributedNeighborAllreduceOptimizer(
        tx, num_steps_per_communication=2
    )
    p2, s2 = params, opt2.init(params)
    train_step = opt2.make_train_step(loss_fn)
    for _ in range(4):
        g = grad_fn(p1, tokens)
        p1, s1 = opt1.step(p1, s1, g)
        p2, s2, _loss = train_step(p2, s2, tokens)
    assert opt2._step_count == 4 and opt2._comm_count == 2
    assert_trees_bitwise(p1, p2)


# -- delayed (one-step-stale) gossip ------------------------------------------


def quad_setup():
    rng = np.random.RandomState(0)
    c = rng.randn(SIZE, 4).astype(np.float32)
    params = {"w": bf.worker_values(lambda r: c[r])}
    cvals = bf.worker_values(lambda r: c[r])

    def loss_fn(p, cv):
        return 0.5 * jnp.sum((p["w"] - cv) ** 2)

    return c, params, cvals, loss_fn


def test_delayed_matches_stale_mix_oracle():
    """Pin the delayed-CTA semantics against a numpy oracle of the
    self-fresh/neighbors-stale recursion:

        mix_k = s * p_k + N @ p_{k-1}        (N = W minus its diagonal)
        p_{k+1} = mix_k - lr * (p_k - c)     (grads at the ENTERING p_k)

    with the buffer seeded at p_0 (so step 0 mixes fresh). One-step
    staleness is the whole point — a fresh-mix implementation would
    diverge from this oracle at step 1."""
    c, params, cvals, loss_fn = quad_setup()
    ctx = ctx_mod.get_context()
    plan = col_ops._resolve_plan(ctx, None, None, None, True)
    w = plan.weight_matrix()  # combine: y_j = sum_i W[i, j] x_i
    s_diag = np.diag(w).copy()
    n_part = w - np.diag(s_diag)
    lr = 0.2

    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(lr))
    p = params
    st = opt.init(p)
    train_step = opt.make_train_step(loss_fn, delayed=True)

    x = np.asarray(params["w"]).copy()  # [size, dim]
    buf = x.copy()
    for _ in range(5):
        p, st, _loss = train_step(p, st, cvals)
        mix = s_diag[:, None] * x + n_part.T @ buf
        buf, x = x, mix - lr * (x - c)
    np.testing.assert_allclose(
        np.asarray(p["w"]), x, rtol=1e-5, atol=1e-6
    )


@pytest.mark.parametrize("order", ["cta", "atc"])
def test_delayed_convergence_smoke(order):
    """Delayed gossip is a known-convergent staleness variant: on the
    gossip oracle problem (decentralized quadratic, same harness as
    test_optimizers/test_pushsum_oracle) the global loss decreases and
    the consensus distance shrinks."""
    c, params, cvals, loss_fn = quad_setup()
    opt = FACTORIES[order](
        optax.sgd(optax.exponential_decay(0.3, 10, 0.5))
    )
    p = params
    s = opt.init(p)
    train_step = opt.make_train_step(loss_fn, delayed=True)

    def global_loss(p):
        w = np.asarray(p["w"])
        return float(np.mean(0.5 * np.sum((w - c.mean(0)) ** 2, -1)))

    def disagreement(p):
        w = np.asarray(p["w"])
        return float(np.max(np.abs(w - w.mean(0))))

    start_loss, start_dis = global_loss(p), disagreement(p)
    for _ in range(80):
        p, s, _loss = train_step(p, s, cvals)
    assert global_loss(p) < 0.05 * start_loss
    assert disagreement(p) < 0.1 and disagreement(p) < start_dis


def test_delayed_refuses_int8_ef():
    """Error feedback cannot ride a one-step-stale payload (the CHOCO
    copies would desynchronize); the refusal must be loud, not a silent
    wrong answer."""
    c, params, cvals, loss_fn = quad_setup()
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    opt.compression = "int8_ef"
    s = opt.init(params)
    train_step = opt.make_train_step(loss_fn, delayed=True)
    with pytest.raises(ValueError, match="int8_ef"):
        train_step(params, s, cvals)


def test_delayed_refuses_hierarchical(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE], nodes_per_machine=4)
    bf.set_machine_topology(tu.RingGraph(2))
    c, params, cvals, loss_fn = quad_setup()
    opt = bf.DistributedHierarchicalNeighborAllreduceOptimizer(
        optax.sgd(0.1)
    )
    s = opt.init(params)
    train_step = opt.make_train_step(loss_fn, delayed=True)
    with pytest.raises(ValueError, match="hierarchical"):
        train_step(params, s, cvals)


def test_delayed_refuses_grad_order():
    opt = bf.DistributedGradientAllreduceOptimizer(optax.sgd(0.1))
    with pytest.raises(ValueError, match="delayed"):
        opt.make_train_step(lambda p: 0.0, delayed=True)


def test_delayed_int8_quantized_converges():
    """The delayed mix composes with the quantized wire (payloads are
    stale AND int8): still converges on the oracle problem."""
    c, params, cvals, loss_fn = quad_setup()
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(optax.exponential_decay(0.3, 10, 0.5))
    )
    opt.compression = "int8"
    p = params
    s = opt.init(p)
    train_step = opt.make_train_step(loss_fn, delayed=True)

    def global_loss(p):
        w = np.asarray(p["w"])
        return float(np.mean(0.5 * np.sum((w - c.mean(0)) ** 2, -1)))

    start = global_loss(p)
    for _ in range(80):
        p, s, _loss = train_step(p, s, cvals)
    assert global_loss(p) < 0.05 * start


# -- compiled-program structure ----------------------------------------------


def _fused_hlo(opt, p, s, *batch):
    return opt.lower_last_fused_hlo(p, s, *batch)


def test_fused_is_one_cached_program():
    """Repeated fused calls reuse ONE compiled program (no cache growth,
    no per-call retrace)."""
    c, params, cvals, loss_fn = quad_setup()
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    p, s = params, opt.init(params)
    train_step = opt.make_train_step(loss_fn)
    p, s, _ = train_step(p, s, cvals)
    cache = ctx_mod.get_context().op_cache
    n = len(cache)
    for _ in range(4):
        p, s, _ = train_step(p, s, cvals)
    assert len(cache) == n
    assert sum(1 for k in cache if k[0] == "opt_fused_step") == 1


def test_fused_program_buckets_permutes(monkeypatch):
    """With a small cap the fused program's permute count is
    n_buckets x rounds (each bucket issues its own plan rounds), and
    every permute is over a capped payload."""
    monkeypatch.setenv("BLUEFOG_BUCKET_BYTES", "2048")  # 512 f32 elems
    n_elems = 3000
    rng = np.random.RandomState(0)
    c = rng.randn(SIZE, n_elems).astype(np.float32)
    params = {"w": bf.worker_values(lambda r: c[r])}
    cvals = bf.worker_values(lambda r: c[r])

    def loss_fn(p, cv):
        return 0.5 * jnp.sum((p["w"] - cv) ** 2)

    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    p, s = params, opt.init(params)
    train_step = opt.make_train_step(loss_fn)
    p, s, _ = train_step(p, s, cvals)
    txt = _fused_hlo(opt, p, s, cvals)
    scan = scan_overlap(txt)
    rounds = 3  # ExponentialTwoGraph(8) -> log2(8) rounds
    n_buckets = len(inner.bucket_bounds(n_elems, 4, 2048))
    assert n_buckets == 6
    total = scan["async_pairs"] + scan["sync_collective_permutes"]
    assert total == rounds * n_buckets, scan
    assert all(
        pm["payload_bytes"] <= 2048 for pm in scan["permutes"]
    ), scan["permutes"]


def test_delayed_program_permutes_independent_of_compute():
    """The delayed program's permutes consume only the carried buffer:
    the def-use scan must find compute they are independent of (what
    makes them schedulable under the whole forward/backward)."""
    c, params, cvals, loss_fn = quad_setup()
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    p, s = params, opt.init(params)
    train_step = opt.make_train_step(loss_fn, delayed=True)
    p, s, _ = train_step(p, s, cvals)
    txt = _fused_hlo(opt, p, s, cvals)
    scan = scan_overlap(txt)
    total = scan["async_pairs"] + scan["sync_collective_permutes"]
    assert total >= 1
    assert scan["overlappable_permutes"] == total, scan


# -- the scan tool itself -----------------------------------------------------


SYNTHETIC_ASYNC_HLO = """\
HloModule test

ENTRY %main (p0: f32[1024]) -> f32[1024] {
  %p0 = f32[1024]{0} parameter(0)
  %cps = (f32[1024]{0}, f32[1024]{0}) collective-permute-start(f32[1024]{0} %p0), channel_id=1, source_target_pairs={{0,1},{1,0}}
  %fusion.1 = f32[1024]{0} fusion(f32[1024]{0} %p0), kind=kLoop, calls=%fused_x
  %dot.1 = f32[1024]{0} dot(f32[1024]{0} %fusion.1, f32[1024]{0} %fusion.1)
  %cpd = f32[1024]{0} collective-permute-done((f32[1024]{0}, f32[1024]{0}) %cps)
  ROOT %add = f32[1024]{0} add(f32[1024]{0} %cpd, f32[1024]{0} %dot.1)
}
"""

SYNTHETIC_SERIAL_HLO = """\
HloModule test

ENTRY %main (p0: f32[256]) -> f32[256] {
  %p0 = f32[256]{0} parameter(0)
  %fusion.1 = f32[256]{0} fusion(f32[256]{0} %p0), kind=kLoop, calls=%f
  %cp = f32[256]{0} collective-permute(f32[256]{0} %fusion.1), channel_id=1, source_target_pairs={{0,1}}
  ROOT %fusion.2 = f32[256]{0} fusion(f32[256]{0} %cp), kind=kLoop, calls=%g
}
"""


def test_scan_counts_async_pairs():
    scan = scan_overlap(SYNTHETIC_ASYNC_HLO)
    assert scan["async_pairs"] == 1
    assert scan["overlapped_async_pairs"] == 1  # fusion+dot between
    assert scan["sync_collective_permutes"] == 0
    (pm,) = scan["permutes"]
    assert pm["compute_between"] == 2
    assert pm["payload_bytes"] == 4096 * 2  # start's tuple shape
    assert pm["independent_compute_ops"] == 2


def test_scan_serial_permute_has_no_independence():
    """A permute whose producers and consumers span all compute is NOT
    overlappable; the scan must not report false capability."""
    scan = scan_overlap(SYNTHETIC_SERIAL_HLO)
    assert scan["async_pairs"] == 0
    assert scan["sync_collective_permutes"] == 1
    (pm,) = scan["permutes"]
    assert pm["independent_compute_ops"] == 0
    assert scan["overlappable_permutes"] == 0


# -- facade -------------------------------------------------------------------


def test_facade_make_train_step():
    c, params, cvals, loss_fn = quad_setup()
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.2))
    s = opt.init(params)
    train_step = bf.make_train_step(opt, loss_fn)
    p, s, loss = train_step(params, s, cvals)
    assert np.asarray(loss).shape == (SIZE,)
    assert "make_train_step" in bf.__all__
