"""Timeline, logging, and watchdog tests.

Mirrors reference test/timeline_test.py: activate via env/API, run ops,
and parse the emitted Chrome-trace JSON.
"""

import json
import logging
import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

import bluefog_tpu as bf
from bluefog_tpu import timeline as tl
from bluefog_tpu import watchdog

SIZE = 8


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    yield
    if bf.timeline_enabled():
        bf.timeline_shutdown()
    bf.shutdown()


def test_native_writer_builds():
    """The C++ writer must compile and load (the Python fallback exists but
    the native path is the designed one)."""
    assert tl.using_native_writer()


def test_timeline_records_ops(tmp_path):
    path = str(tmp_path / "trace.json")
    assert bf.timeline_init(path)
    assert bf.timeline_enabled()

    x = bf.worker_values(lambda r: np.float32(r))
    with bf.timeline_context("consensus", "USER_SPAN"):
        for _ in range(3):
            x = bf.neighbor_allreduce(x)
    h = bf.neighbor_allreduce_nonblocking(x)
    bf.synchronize(h)
    assert bf.timeline_shutdown()
    assert not bf.timeline_enabled()

    events = json.load(open(path))
    assert isinstance(events, list) and events
    cats = {e.get("cat") for e in events}
    assert "ENQUEUE" in cats        # op dispatch spans
    assert "SYNCHRONIZE" in cats    # blocking waits
    assert "USER_SPAN" in cats      # explicit activity context
    spans = [e for e in events if e.get("cat") == "USER_SPAN"]
    assert {e["ph"] for e in spans} == {"B", "E"}
    # chrome requires monotonically sensible ts
    assert all(isinstance(e["ts"], int) for e in events)


def test_timeline_env_activation(tmp_path, monkeypatch, cpu_devices):
    prefix = str(tmp_path / "envtrace_")
    monkeypatch.setenv("BLUEFOG_TIMELINE", prefix)
    bf.init(devices=cpu_devices[:SIZE])
    assert bf.timeline_enabled()
    bf.allreduce(bf.worker_values(np.float32(1)))
    bf.timeline_shutdown()
    events = json.load(open(prefix + "0.json"))
    assert any(e.get("cat") == "ENQUEUE" for e in events)


def test_timeline_counter_events_valid_chrome_trace(tmp_path, monkeypatch):
    """Counter (ph=C) and instant (ph=i) records — the metrics exporter's
    timeline tier — interleave with op spans and the file still loads as
    a valid Chrome trace."""
    from bluefog_tpu import metrics

    path = str(tmp_path / "counters.json")
    assert bf.timeline_init(path)
    x = bf.worker_values(lambda r: np.float32(r))
    # drive a real device-tier drain so counters flow through the
    # registry exporter, not just the raw record call
    monkeypatch.setenv("BLUEFOG_METRICS", "1")
    monkeypatch.setenv("BLUEFOG_METRICS_INTERVAL", "1")
    import optax

    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    params = {"w": x}
    state = opt.init(params)
    opt.step(params, state, {"w": jnp.zeros_like(x)})
    bf.metrics_export()  # flush the deferred drain onto the timeline
    bf.timeline_record_counter("bluefog.custom", 1.25)
    bf.timeline_record_instant("marker")
    assert bf.timeline_shutdown()

    events = json.load(open(path))  # valid JSON array == valid trace
    assert isinstance(events, list)
    counters = [e for e in events if e.get("ph") == "C"]
    assert counters, events[:5]
    for e in counters:
        # chrome requires counter values under args
        assert "value" in e["args"], e
        assert isinstance(e["ts"], int)
    names = {e["name"] for e in counters}
    assert "bluefog.custom" in names
    assert "bluefog.gossip.disagreement" in names, names
    instants = [e for e in events if e.get("ph") == "i"]
    assert instants and instants[0]["s"] == "p"
    # spans and counters coexist in one file
    assert any(e.get("cat") == "ENQUEUE" for e in events)


def test_double_init_rejected(tmp_path):
    path = str(tmp_path / "t.json")
    assert bf.timeline_init(path)
    assert not bf.timeline_init(path)
    bf.timeline_shutdown()


def test_log_level_env():
    bf.set_log_level("debug")
    assert bf.logger.level == logging.DEBUG
    bf.set_log_level("warn")
    with pytest.raises(ValueError):
        bf.set_log_level("chatty")


def test_watchdog_reports_stall(caplog):
    watchdog.set_stall_timeout(0.1)
    bf.logger.propagate = True  # caplog captures via the root logger
    try:
        with caplog.at_level("ERROR", logger="bluefog_tpu"):
            with watchdog.watch("test-op"):
                time.sleep(0.4)
        assert any("Stall detected" in r.message for r in caplog.records)
    finally:
        bf.logger.propagate = False
        watchdog.set_stall_timeout(60)


def test_record_complete_returns_bool(tmp_path):
    """timeline_record_complete reports success like every sibling
    record function (it used to return None)."""
    assert tl.timeline_record_complete("x", "CAT", 0, 1) is False
    assert bf.timeline_init(str(tmp_path / "rc.json"))
    assert tl.timeline_record_complete("x", "CAT", 0, 1) is True
    assert bf.timeline_shutdown()


def test_pywriter_concurrent_records_stay_valid_json(tmp_path):
    """The pure-Python fallback writer is hit concurrently by the
    watchdog thread (stall instants, counters) and the main thread
    (spans); its separator handshake is locked so the stream stays
    parseable. Hammer it from 4 threads and parse the result."""
    import threading

    from bluefog_tpu.timeline import _PyWriter

    w = _PyWriter()
    path = tmp_path / "py.json"
    assert w.bf_timeline_start(str(path).encode())

    def spam(tid):
        for _ in range(200):
            w.bf_timeline_record(b"span", b"CAT", b"B", 0, tid)
            w.bf_timeline_record_counter(b"ctr", b"CAT", 0, tid, 1.5)
            w.bf_timeline_record(b"span", b"CAT", b"E", 0, tid)

    threads = [
        threading.Thread(target=spam, args=(t,)) for t in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    w.bf_timeline_stop()
    events = json.load(open(path))  # corruption -> JSONDecodeError
    assert len(events) == 4 * 200 * 3


def test_counter_nonfinite_guard_regression(tmp_path):
    """Non-finite counter values must be DROPPED (returning False), not
    serialized: %g would emit bare nan/inf tokens and invalidate the
    whole trace as JSON — exactly when training diverges and the trace
    matters most."""
    path = str(tmp_path / "nonfinite.json")
    assert bf.timeline_init(path)
    assert bf.timeline_record_counter("ok", 1.0) is True
    assert bf.timeline_record_counter("bad", float("nan")) is False
    assert bf.timeline_record_counter("bad", float("inf")) is False
    assert bf.timeline_record_counter("bad", float("-inf")) is False
    assert bf.timeline_shutdown()
    events = json.load(open(path))  # the file must still parse
    counters = [e for e in events if e.get("ph") == "C"]
    assert {e["name"] for e in counters} == {"ok"}


def test_env_activation_uses_process_index(tmp_path, monkeypatch,
                                           cpu_devices):
    """Multi-host runs must not clobber each other's trace file:
    BLUEFOG_TIMELINE=<prefix> writes <prefix><process_index>.json, with
    the index from BLUEFOG_PROCESS_ID (the launcher contract)."""
    assert tl.process_file_index() == 0  # single-controller default
    monkeypatch.setenv("BLUEFOG_PROCESS_ID", "3")
    assert tl.process_file_index() == 3
    prefix = str(tmp_path / "proc_")
    monkeypatch.setenv("BLUEFOG_TIMELINE", prefix)
    assert tl.maybe_init_from_env()
    bf.allreduce(bf.worker_values(np.float32(1)))
    bf.timeline_shutdown()
    assert not os.path.exists(prefix + "0.json")
    events = json.load(open(prefix + "3.json"))
    assert isinstance(events, list)


def test_watchdog_suspend_resume_clock_restart(caplog):
    """A suspended interval must NOT count toward a stall: resume()
    restarts every pending wait's clock (the notebook-pause contract of
    the reference bf.suspend)."""
    watchdog.set_stall_timeout(0.3)
    bf.logger.propagate = True
    try:
        with caplog.at_level("ERROR", logger="bluefog_tpu"):
            with watchdog.watch("suspended-op"):
                watchdog.suspend()
                time.sleep(0.6)  # past the limit, but suspended
                watchdog.resume()  # clock restarts here
                time.sleep(0.1)  # under the limit since resume
            assert not any(
                "Stall detected" in r.message for r in caplog.records
            ), "suspended interval was counted toward the stall"
            # the SAME deadline still fires once the post-resume wait
            # genuinely exceeds it (resume must re-arm, not disable)
            with watchdog.watch("post-resume-op"):
                time.sleep(0.7)
        assert any(
            "post-resume-op" in r.message for r in caplog.records
        )
    finally:
        bf.logger.propagate = False
        watchdog.resume()
        watchdog.set_stall_timeout(60)


def test_stall_handler_exception_isolated(caplog):
    """A raising stall handler must neither kill the monitor thread nor
    skip the handlers after it."""
    calls = []

    def bad(name, waited):
        raise RuntimeError("handler boom")

    def good(name, waited):
        calls.append(name)

    watchdog.add_stall_handler(bad)
    watchdog.add_stall_handler(good)  # registered AFTER the raiser
    watchdog.set_stall_timeout(0.1)
    bf.logger.propagate = True
    try:
        with caplog.at_level("ERROR", logger="bluefog_tpu"):
            with watchdog.watch("iso-op"):
                time.sleep(0.5)
            assert "iso-op" in calls, (
                "handler after the raiser was skipped"
            )
            assert any(
                "stall handler" in r.message for r in caplog.records
            )
            # monitor thread survived: a later stall still reports
            calls.clear()
            with watchdog.watch("iso-op-2"):
                time.sleep(0.5)
        assert "iso-op-2" in calls, "monitor thread died"
    finally:
        bf.logger.propagate = False
        watchdog.remove_stall_handler(bad)
        watchdog.remove_stall_handler(good)
        watchdog.set_stall_timeout(60)


def test_watchdog_quiet_when_fast(caplog):
    watchdog.set_stall_timeout(5)
    bf.logger.propagate = True
    try:
        with caplog.at_level("ERROR", logger="bluefog_tpu"):
            for _ in range(3):
                with watchdog.watch("fast-op"):
                    pass
        assert not caplog.records
    finally:
        bf.logger.propagate = False


def test_optimizer_steps_record_spans(tmp_path, cpu_devices):
    """Optimizer dispatches appear in the trace — the analogue of the
    reference's optimizer timeline hooks (torch/optimizers.py:112-165)."""
    import optax

    path = str(tmp_path / "opt_trace.json")
    assert bf.timeline_init(path)
    try:
        c = np.random.RandomState(0).randn(SIZE, 3).astype(np.float32)
        opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
        params = {"w": bf.worker_values(lambda r: c[r])}
        state = opt.init(params)
        params, state = opt.step(
            params, state, {"w": params["w"] - jnp.asarray(c)}
        )
        wopt = bf.DistributedWinPutOptimizer(optax.sgd(0.1))
        wstate = wopt.init(params)
        wopt.step(wstate, {"w": params["w"] - jnp.asarray(c)})
        wopt.free()
    finally:
        assert bf.timeline_shutdown()
    names = {e.get("name") for e in json.load(open(path))}
    assert "optimizer_step" in names, names
    assert "window_optimizer_step" in names, names


def test_profiler_tier(tmp_path, cpu_devices):
    """timeline_init(profiler=True) brackets the session with
    jax.profiler.start_trace: device-side traces land next to the host
    JSON (the reference has no device tier; its C++ phases were the
    device story)."""
    path = str(tmp_path / "trace.json")
    assert bf.timeline_init(path, profiler=True)
    bf.allreduce(bf.worker_values(np.float32(1)))
    assert bf.timeline_shutdown()
    prof_dir = path + ".xplane"
    assert os.path.isdir(prof_dir), os.listdir(str(tmp_path))
    # jax writes <dir>/plugins/profile/<ts>/*.xplane.pb
    found = [
        f for _root, _dirs, files in os.walk(prof_dir) for f in files
    ]
    assert any(f.endswith(".xplane.pb") for f in found), found
