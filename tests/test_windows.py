"""Window-op subsystem tests.

Mirrors the semantics coverage of reference test/torch_win_ops_test.py on
the 8-device virtual CPU mesh: lifecycle, update with default/given
weights, update_then_collect, put/get/accumulate (full and partial
destinations), version counters, mutex no-op, and the associated-p lane.
"""

import numpy as np
import pytest

import jax

import bluefog_tpu as bf
from bluefog_tpu import topology as tu

SIZE = 8


@pytest.fixture(autouse=True)
def fresh_context(cpu_devices):
    bf.init(devices=cpu_devices[:SIZE])
    yield
    bf.win_free()
    bf.shutdown()


def ranks_tensor(shape=(5,)):
    return bf.worker_values(lambda r: np.full(shape, float(r), np.float32))


def exp2_in_neighbors(rank, size=SIZE):
    indegree = int(np.ceil(np.log2(size)))
    return [(rank - 2**i) % size for i in range(indegree)]


def test_win_create_update_free():
    x = ranks_tensor()
    assert bf.win_create(x, "w")
    assert not bf.win_create(x, "w")  # duplicate name
    out = np.asarray(bf.win_update("w"))
    # buffers hold copies of my own value -> update is the identity
    for r in range(SIZE):
        np.testing.assert_allclose(out[r], r, atol=1e-5)
    assert bf.get_current_created_window_names() == ["w"]
    assert bf.win_free("w")
    assert not bf.win_free("w")
    assert bf.get_current_created_window_names() == []


def test_win_free_all():
    x = ranks_tensor()
    bf.win_create(x, "a")
    bf.win_create(x, "b")
    assert bf.get_current_created_window_names() == ["a", "b"]
    assert bf.win_free()
    assert bf.get_current_created_window_names() == []


def test_win_update_with_given_weights():
    x = ranks_tensor()
    bf.win_create(x, "w")
    ins = bf.in_neighbor_ranks()
    weights = [
        {s: 1.0 / (len(ins[r]) + 1) for s in ins[r]} for r in range(SIZE)
    ]
    self_w = [1.0 / (len(ins[r]) + 1) for r in range(SIZE)]
    out = np.asarray(bf.win_update("w", self_weight=self_w, neighbor_weights=weights))
    for r in range(SIZE):
        np.testing.assert_allclose(out[r], r, atol=1e-5)


def test_win_update_then_collect_twice():
    """Collect sums self + buffers then zeroes buffers, so the second
    collect returns the same value (reference torch_win_ops_test.py:214)."""
    x = ranks_tensor()
    bf.win_create(x, "w")
    indegree = int(np.ceil(np.log2(SIZE)))
    # First collect: self (rank) + indegree buffers holding create-time
    # copies (rank each). Second: value is rank*(indeg+1), buffers zeroed.
    for _ in range(2):
        out = np.asarray(bf.win_update_then_collect("w"))
        for r in range(SIZE):
            np.testing.assert_allclose(out[r], r * (indegree + 1), atol=1e-4)


def test_win_put_default():
    x = ranks_tensor()
    bf.win_create(x, "w")
    bf.win_put(x, "w")
    out = np.asarray(bf.win_update("w"))
    for r in range(SIZE):
        ns = exp2_in_neighbors(r)
        expect = (r + sum(ns)) / (len(ns) + 1)
        np.testing.assert_allclose(out[r], expect, atol=1e-4)


def test_win_put_given_destination():
    """Each rank puts 1.23x its value to rank+1 only; other buffers keep the
    create-time copy (reference torch_win_ops_test.py:385-424)."""
    x = ranks_tensor()
    bf.win_create(x, "w")
    dst = [{(r + 1) % SIZE: 1.23} for r in range(SIZE)]
    bf.win_put(x, "w", dst_weights=dst)
    out = np.asarray(bf.win_update("w"))
    for r in range(SIZE):
        ns = exp2_in_neighbors(r)
        indeg = len(ns)
        expect = (r * indeg + 1.23 * ((r - 1) % SIZE)) / (indeg + 1)
        np.testing.assert_allclose(out[r], expect, atol=1e-4)


def test_win_accumulate_default():
    x = ranks_tensor()
    bf.win_create(x, "w")
    bf.win_accumulate(x, "w")
    out = np.asarray(bf.win_update("w"))
    for r in range(SIZE):
        ns = exp2_in_neighbors(r)
        outdeg = len(ns)
        expect = r + sum(ns) / (outdeg + 1)
        np.testing.assert_allclose(out[r], expect, atol=1e-4)


def test_win_accumulate_given_destination():
    x = ranks_tensor()
    bf.win_create(x, "w")
    dst = [{(r + 1) % SIZE: 1.23} for r in range(SIZE)]
    bf.win_accumulate(x, "w", dst_weights=dst)
    nw = [{(r - 1) % SIZE: 0.5} for r in range(SIZE)]
    out = np.asarray(
        bf.win_update("w", self_weight=0.5, neighbor_weights=nw)
    )
    for r in range(SIZE):
        expect = 0.5 * r + 0.5 * (r + 1.23 * ((r - 1) % SIZE))
        np.testing.assert_allclose(out[r], expect, atol=1e-4)


def test_win_get_default():
    x = ranks_tensor()
    bf.win_create(x, "w")
    bf.win_get("w")
    out = np.asarray(bf.win_update("w"))
    for r in range(SIZE):
        ns = exp2_in_neighbors(r)
        expect = (r + sum(ns)) / (len(ns) + 1)
        np.testing.assert_allclose(out[r], expect, atol=1e-4)


def test_win_get_given_sources():
    x = ranks_tensor()
    bf.win_create(x, "w")
    src = [{(r - 1) % SIZE: 2.0} for r in range(SIZE)]
    bf.win_get("w", src_weights=src)
    out = np.asarray(bf.win_update("w"))
    for r in range(SIZE):
        ns = exp2_in_neighbors(r)
        indeg = len(ns)
        # the (r-1) buffer now holds 2*(r-1); the rest keep the copy of r
        expect = (r + 2.0 * ((r - 1) % SIZE) + (indeg - 1) * r) / (indeg + 1)
        np.testing.assert_allclose(out[r], expect, atol=1e-4)


def test_win_version_counters():
    x = ranks_tensor()
    bf.win_create(x, "w")
    before = bf.get_win_version("w")
    for r in range(SIZE):
        assert set(before[r]) == set(exp2_in_neighbors(r))
        assert all(v == 0 for v in before[r].values())
    bf.win_put(x, "w")
    after = bf.get_win_version("w")
    for r in range(SIZE):
        assert all(v == 1 for v in after[r].values())
    bf.win_put(x, "w")
    assert all(v == 2 for v in bf.get_win_version("w", rank=0).values())
    bf.win_update("w")
    cleared = bf.get_win_version("w")
    for r in range(SIZE):
        assert all(v == 0 for v in cleared[r].values())


def test_win_partial_write_versions():
    x = ranks_tensor()
    bf.win_create(x, "w")
    dst = [{(r + 1) % SIZE: 1.0} for r in range(SIZE)]
    bf.win_put(x, "w", dst_weights=dst)
    vers = bf.get_win_version("w")
    for r in range(SIZE):
        for s, v in vers[r].items():
            assert v == (1 if s == (r - 1) % SIZE else 0)


def test_win_put_to_non_neighbor_raises():
    x = ranks_tensor()
    bf.win_create(x, "w")
    # rank 0 -> rank 3 is not an Exp2(8) edge (offsets are 1, 2, 4)
    dst = [None] * SIZE
    dst[0] = {3: 1.0}
    with pytest.raises(ValueError, match="not an in-neighbor"):
        bf.win_put(x, "w", dst_weights=dst)


def test_win_update_invalid_source_raises():
    x = ranks_tensor()
    bf.win_create(x, "w")
    nw = [{s: 0.5 for s in exp2_in_neighbors(r)} for r in range(SIZE)]
    nw[0] = {3: 1.0}  # 3 is not an Exp2(8) in-neighbor of 0
    with pytest.raises(ValueError, match="no buffer slot"):
        bf.win_update("w", self_weight=0.5, neighbor_weights=nw)
    # changing topology without re-creating the window must also raise
    bf.set_topology(tu.MeshGrid2DGraph(SIZE), is_weighted=True)
    with pytest.raises(ValueError, match="no buffer slot"):
        bf.win_update("w")


def test_win_update_participation():
    """A rank whose neighbor_weights entry is None sits the update out:
    value, p, and buffers stay untouched."""
    x = ranks_tensor()
    bf.win_create(x, "w")
    nw = [
        None if r == 0 else {s: 0.0 for s in exp2_in_neighbors(r)}
        for r in range(SIZE)
    ]
    out = np.asarray(bf.win_update("w", self_weight=0.5, neighbor_weights=nw))
    np.testing.assert_allclose(out[0], 0.0, atol=1e-6)  # value was rank 0 = 0
    # rank 0 kept its value scale: re-check with a nonzero rank sitting out
    bf.win_free("w")
    bf.win_create(x, "w")
    nw[0], nw[3] = {s: 0.0 for s in exp2_in_neighbors(0)}, None
    out = np.asarray(bf.win_update("w", self_weight=0.5, neighbor_weights=nw))
    np.testing.assert_allclose(out[3], 3.0, atol=1e-6)  # untouched
    np.testing.assert_allclose(out[1], 0.5, atol=1e-6)  # halved


def test_win_update_sitout_keeps_buffers_and_versions():
    """A None entry keeps that rank's buffers, versions, value, and p."""
    x = ranks_tensor()
    bf.win_create(x, "w")
    bf.win_put(x, "w")
    nw = [
        None if r == 0 else {s: 0.1 for s in exp2_in_neighbors(r)}
        for r in range(SIZE)
    ]
    bf.win_update("w", self_weight=0.5, neighbor_weights=nw, reset=True)
    vers = bf.get_win_version("w")
    assert all(v == 1 for v in vers[0].values())  # rank 0 untouched
    assert all(v == 0 for v in vers[1].values())  # others cleared
    # rank 0's pending writes survive to the next full update
    out = np.asarray(bf.win_update("w"))
    ns = exp2_in_neighbors(0)
    np.testing.assert_allclose(out[0], sum(ns) / (len(ns) + 1), atol=1e-4)


def test_self_weight_dict_form():
    x = ranks_tensor()
    bf.win_create(x, "w")
    bf.turn_on_win_ops_with_associated_p()
    try:
        bf.win_accumulate(x, "w", self_weight={r: 0.5 for r in range(SIZE)})
        np.testing.assert_allclose(bf.win_associated_p("w"), 0.5)
    finally:
        bf.turn_off_win_ops_with_associated_p()
    with pytest.raises(ValueError, match="one entry per rank"):
        bf.win_accumulate(x, "w", self_weight=[0.5, 0.5])


def test_associated_p_off_stays_one():
    x = ranks_tensor()
    bf.win_create(x, "w")
    bf.win_accumulate(x, "w", self_weight=0.5)
    bf.win_update_then_collect("w")
    np.testing.assert_allclose(bf.win_associated_p("w"), 1.0)


def test_win_mutex_noop():
    x = ranks_tensor()
    bf.win_create(x, "w")
    with bf.win_mutex("w"):
        bf.win_put(x, "w")
    with pytest.raises(ValueError):
        with bf.win_mutex("nope"):
            pass


def test_associated_p_ring_accumulate():
    """Parity with reference torch_win_ops_test.py:823-862: one sender
    accumulates with self_weight=0.5 split over its two ring neighbors."""
    bf.set_topology(tu.RingGraph(SIZE))
    bf.turn_on_win_ops_with_associated_p()
    try:
        for send_rank in range(SIZE):
            name = f"p_{send_rank}"
            x = ranks_tensor(shape=(1,))
            bf.win_create(x, name)
            left, right = (send_rank - 1) % SIZE, (send_rank + 1) % SIZE
            dst = [None] * SIZE
            dst[send_rank] = {left: 0.5, right: 0.5}
            bf.win_accumulate(x, name, self_weight=0.5, dst_weights=dst)
            bf.win_update_then_collect(name)
            p = bf.win_associated_p(name)
            for r in range(SIZE):
                if r == send_rank:
                    assert p[r] == pytest.approx(0.5)
                elif r in (left, right):
                    assert p[r] == pytest.approx(1.5)
                else:
                    assert p[r] == pytest.approx(1.0)
            bf.win_free(name)
    finally:
        bf.turn_off_win_ops_with_associated_p()


def test_associated_p_tracks_value():
    """The p lane undergoes the same linear ops as the window value: with a
    1-filled tensor and zero_init, p equals the value after any op mix
    (reference torch_win_ops_test.py:864-904)."""
    rng = np.random.RandomState(7)
    x = bf.worker_values(np.ones((3,), np.float32))
    bf.win_create(x, "w", zero_init=True)
    bf.turn_on_win_ops_with_associated_p()
    outs = bf.out_neighbor_ranks()
    for _ in range(5):
        dst, sw = [], []
        for r in range(SIZE):
            w = rng.rand(len(outs[r]) + 1)
            w /= w.sum()
            sw.append(float(w[-1]))
            dst.append({d: float(w[i]) for i, d in enumerate(outs[r])})
        bf.win_put(None, "w", self_weight=sw, dst_weights=dst)
        bf.win_update("w")
        bf.win_accumulate(None, "w", self_weight=sw, dst_weights=dst)
        bf.win_update_then_collect("w")
    val = np.asarray(bf.win_update_then_collect("w"))
    p = bf.win_associated_p("w")
    bf.turn_off_win_ops_with_associated_p()
    np.testing.assert_allclose(p, val[:, 0], atol=1e-5)


def test_push_sum_consensus():
    """Push-sum over a directed ring converges to the true average: the
    algorithmic contract the window subsystem exists for (reference
    optimizers.py:1026-1177 semantics distilled)."""
    bf.set_topology(tu.RingGraph(SIZE, connect_style=1))  # directed ring
    bf.turn_on_win_ops_with_associated_p()
    vals = np.arange(SIZE, dtype=np.float32)
    x = bf.worker_values(lambda r: np.array([vals[r]], np.float32))
    bf.win_create(x, "ps", zero_init=True)
    outs = bf.out_neighbor_ranks()
    for _ in range(150):  # directed-ring mixing rate is cos(pi/8) ~ 0.92
        dst = [
            {d: 1.0 / (len(outs[r]) + 1) for d in outs[r]} for r in range(SIZE)
        ]
        sw = [1.0 / (len(outs[r]) + 1) for r in range(SIZE)]
        bf.win_accumulate(None, "ps", self_weight=sw, dst_weights=dst)
        out = bf.win_update_then_collect("ps")
        out.block_until_ready()
    p = bf.win_associated_p("ps")
    bf.turn_off_win_ops_with_associated_p()
    # pure accumulate sequences conserve push-sum mass
    assert float(np.sum(p)) == pytest.approx(SIZE, abs=1e-3)
    corrected = np.asarray(out)[:, 0] / p
    np.testing.assert_allclose(corrected, vals.mean(), atol=1e-3)


def test_host_weight_resolution_cost():
    """Pin the window optimizer's per-step host-side weight resolution at
    the BASELINE north-star scale (v5e-256): the structure-keyed caches
    must make the warm path well under the device step time. Bound is
    generous (10 ms vs ~0.6 ms measured) to ride out CI noise; the real
    assertion is that repeated calls add NO new cache entries (all
    O(size^2) lowering work happened once)."""
    import time
    import types

    from bluefog_tpu import topology as topo_mod
    from bluefog_tpu import windows as win_mod

    size = 256
    g = topo_mod.ExponentialTwoGraph(size)
    in_nbrs = tuple(
        tuple(sorted(int(s) for s in g.predecessors(r) if s != r))
        for r in range(size)
    )
    out_nbrs = tuple(
        tuple(sorted(int(d) for d in g.successors(r) if d != r))
        for r in range(size)
    )
    max_deg = max(len(s) for s in in_nbrs)
    ctx = types.SimpleNamespace(size=size, op_cache={})
    win = types.SimpleNamespace(
        in_neighbors=in_nbrs, max_deg=max_deg, name="pin", shape=(4,)
    )

    def resolve_once():
        w, part = win_mod._per_rank_edges(ctx, None, out_nbrs, "dst_weights")
        win_mod._self_weight_vec(ctx, None, part)
        perms, _slots = win_mod._lowered_exchange(ctx, win, w)
        win_mod._round_weights(perms, w)
        win_mod._slot_weights(win, w.T, size)

    resolve_once()  # cold: builds the structure caches
    n_keys = len(ctx.op_cache)
    t0 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        resolve_once()
    per_step = (time.perf_counter() - t0) / reps
    assert len(ctx.op_cache) == n_keys, "warm calls must not re-lower"
    assert per_step < 0.010, f"host weight resolution {per_step*1e3:.2f} ms"


# -- quantized window wire (BLUEFOG_WINDOW_WIRE) ------------------------------


def test_window_wire_env_validation(monkeypatch):
    from bluefog_tpu import windows as win_mod

    for v, want in (("", None), ("off", None), ("fp32", None),
                    ("bf16", "bf16"), ("INT8", "int8"), ("int4", "int4")):
        monkeypatch.setenv("BLUEFOG_WINDOW_WIRE", v)
        assert win_mod.window_wire() == want, v
    monkeypatch.setenv("BLUEFOG_WINDOW_WIRE", "fp4")
    with pytest.raises(ValueError, match="BLUEFOG_WINDOW_WIRE"):
        win_mod.window_wire()


@pytest.mark.parametrize("wire", ["bf16", "int8", "int4"])
def test_quantized_win_put_matches_numpy_oracle(wire, monkeypatch):
    """win_put under a quantized wire: each destination's buffer holds
    ``w * dequant(Q(x))`` with the SAME reconstruction the host replica
    computes — the oracle is the numpy quantizer, not a tolerance."""
    import ml_dtypes

    from bluefog_tpu import metrics as bf_metrics

    monkeypatch.setenv("BLUEFOG_WINDOW_WIRE", wire)
    rng = np.random.RandomState(31)
    vals = rng.randn(SIZE, 600).astype(np.float32) * 3
    x = bf.worker_values(lambda r: vals[r])
    bf.win_create(x, "qw")
    bf.win_put(name="qw", self_weight=1.0,
               dst_weights=[{(r + 1) % SIZE: 0.5} for r in range(SIZE)])
    from bluefog_tpu import windows as win_mod

    win = win_mod._get_win(bf.get_context(), "qw")
    bufs = np.asarray(win.buffers)
    for r in range(SIZE):
        src = (r - 1) % SIZE
        slot = win.in_neighbors[r].index(src)
        v = vals[src]
        if wire == "bf16":
            hat = v.astype(ml_dtypes.bfloat16).astype(np.float32)
        elif wire == "int8":
            hat = bf_metrics._np_chunk_quantize(v)
        else:
            hat = bf_metrics._np_chunk_quantize4(v)
        np.testing.assert_array_equal(bufs[r, slot], 0.5 * hat)


@pytest.mark.parametrize("wire", ["bf16", "int8", "int4"])
def test_push_sum_mass_conserved_under_quantized_wire(wire, monkeypatch):
    """THE quantized-windows acceptance oracle: under any wire tier the
    push-sum accumulate conserves sender mass EXACTLY (to f32 rounding
    of the running sums) — the sender absorbs the quantization residual
    of the mass it ships — and the p lane (never quantized) stays an
    exact column-stochastic recursion. The x/p estimate still reaches
    the true average to within the wire's noise floor."""
    monkeypatch.setenv("BLUEFOG_WINDOW_WIRE", wire)
    from bluefog_tpu import windows as win_mod

    bf.set_topology(tu.ExponentialTwoGraph(SIZE))
    bf.turn_on_win_ops_with_associated_p()
    x0 = np.random.RandomState(32).randn(SIZE, 600).astype(np.float32) * 3
    bf.win_create(bf.worker_values(lambda r: x0[r]), "psq", zero_init=True)
    outs = bf.get_context().out_neighbor_ranks()
    dst = [
        {d: 1.0 / (len(outs[r]) + 1) for d in outs[r]} for r in range(SIZE)
    ]
    sw = [1.0 / (len(outs[r]) + 1) for r in range(SIZE)]
    total0 = x0.sum(0, dtype=np.float64)
    for _ in range(15):
        bf.win_accumulate(name="psq", self_weight=sw, dst_weights=dst)
        bf.win_update_then_collect("psq")
        v = np.asarray(bf.win_read("psq"), np.float64)
        # f32 rounding of the running sums only — NOT quantization
        # magnitude (plain quantized shipping without the residual
        # absorption drifts ~1e-1 on this problem)
        assert np.abs(v.sum(0) - total0).max() < 5e-4
    p = win_mod.win_associated_p("psq")
    np.testing.assert_allclose(p.sum(), SIZE, rtol=1e-6)
    est = np.asarray(bf.win_read("psq")) / p[:, None].astype(np.float32)
    noise = {"bf16": 0.05, "int8": 0.1, "int4": 0.6}[wire]
    assert np.abs(est - x0.mean(0)).max() < noise


def test_quantized_window_rejects_integer_window(monkeypatch):
    monkeypatch.setenv("BLUEFOG_WINDOW_WIRE", "int8")
    x = bf.worker_values(lambda r: np.ones(4, np.float32))
    bf.win_create(x, "f_ok")
    bf.win_put(name="f_ok")  # float window: fine
    monkeypatch.delenv("BLUEFOG_WINDOW_WIRE")
    xi = bf.worker_values(lambda r: np.ones(4, np.int32))
    bf.win_create(xi, "i_win")
    monkeypatch.setenv("BLUEFOG_WINDOW_WIRE", "int8")
    with pytest.raises(ValueError, match="float window"):
        bf.win_put(name="i_win")


def test_window_optimizer_push_sum_quantized_wire(monkeypatch):
    """The fused window-optimizer step honors BLUEFOG_WINDOW_WIRE: the
    push-sum optimizer still converges to the survivor average under
    the int4 wire (mass conservation holds through the fused exchange
    too), and the wire tier keys its own compiled program."""
    import optax

    monkeypatch.setenv("BLUEFOG_WINDOW_WIRE", "int4")
    bf.set_topology(tu.ExponentialTwoGraph(SIZE))
    c = np.random.RandomState(33).randn(SIZE, 16).astype(np.float32)
    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.0))
    params = {"w": bf.worker_values(lambda r: c[r])}
    state = opt.init(params)
    zero = {"w": np.zeros((SIZE, 16), np.float32)}
    for _ in range(40):
        params, state = opt.step(state, zero)
    w = np.asarray(opt.params()["w"])
    assert np.abs(w - c.mean(0)).max() < 0.25 * np.abs(
        c - c.mean(0)
    ).max()
    opt.free()


# -- age lane (staleness observatory, docs/staleness.md) ----------------------


def test_get_win_version_age_semantics_oracle():
    """Oracle for the age lane across win_put -> win_update cycles: the
    version counter resets at every update, but the AGE (local window
    steps since the slot's last write) keeps counting from the write —
    the question ``get_win_version(ages=True)`` exists to answer."""
    x = ranks_tensor()
    bf.win_create(x, "agew")
    in_nbrs = bf.get_context().in_neighbor_ranks()

    # fresh window: buffers are copies of the creating value, age 0
    for r in range(SIZE):
        assert bf.get_win_age("agew", rank=r) == {
            s: 0 for s in in_nbrs[r]
        }

    # numpy oracle replayed against the same op sequence: clock
    # advances per op; a put stamps every written slot
    expected_age = {r: {s: 0 for s in in_nbrs[r]} for r in range(SIZE)}

    def tick(written: bool):
        for r in range(SIZE):
            for s in expected_age[r]:
                expected_age[r][s] = (
                    0 if written else expected_age[r][s] + 1
                )

    for cycle in range(3):
        bf.win_put(name="agew")
        tick(written=True)
        assert bf.get_win_version("agew", ages=True) == [
            expected_age[r] for r in range(SIZE)
        ]
        # two updates in a row: version resets to 0 both times, the
        # age keeps growing — the two lanes answer different questions
        for _ in range(2):
            bf.win_update(name="agew")
            tick(written=False)
            vers = bf.get_win_version("agew")
            assert all(
                v == 0 for row in vers for v in row.values()
            )
            assert bf.get_win_age("agew") == [
                expected_age[r] for r in range(SIZE)
            ]
    bf.win_free("agew")


def test_win_age_mass_lane_tracks_oldest_pending_accumulate():
    """Push-sum mass age: the oldest uncollected win_accumulate mass
    per slot, cleared by the collecting (resetting) update — mass
    conservation and mass staleness jointly visible."""
    bf.turn_on_win_ops_with_associated_p()
    x = ranks_tensor()
    bf.win_create(x, "massw", zero_init=True)
    in_nbrs = bf.get_context().in_neighbor_ranks()

    # nothing pending before any accumulate
    for r in range(SIZE):
        assert all(
            v is None
            for v in bf.get_win_age("massw", rank=r, mass=True).values()
        )
    bf.win_accumulate(name="massw")
    for r in range(SIZE):
        assert bf.get_win_age("massw", rank=r, mass=True) == {
            s: 0 for s in in_nbrs[r]
        }
    # a second accumulate does NOT refresh the mass birth: the slot
    # holds mass from BOTH, and its age is the oldest contribution's
    bf.win_accumulate(name="massw")
    for r in range(SIZE):
        assert bf.get_win_age("massw", rank=r, mass=True) == {
            s: 1 for s in in_nbrs[r]
        }
    # the collect consumes the mass: nothing pending again
    bf.win_update_then_collect("massw")
    for r in range(SIZE):
        assert all(
            v is None
            for v in bf.get_win_age("massw", rank=r, mass=True).values()
        )
    bf.win_free("massw")
    bf.turn_off_win_ops_with_associated_p()
