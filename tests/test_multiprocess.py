# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Real multi-controller bring-up: two OS processes, one global mesh.

The reference launcher exists to start N communicating processes
(``run/run.py:180-203``); the TPU analogue is ``jax.distributed.initialize``
joined from each controller (``context.maybe_init_distributed``). The
mocked launcher test (test_launcher.py) checks only the argument contract —
THIS test actually spawns two controller processes over the env contract
the launcher emits (BLUEFOG_COORDINATOR/NUM_PROCESSES/PROCESS_ID), forms a
4-device global mesh (2 local CPU devices per process, Gloo collectives),
runs a decentralized neighbor_allreduce training loop to consensus, and
exits cleanly.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=2"
).strip()
import jax
# The ambient platform plugin pins JAX_PLATFORMS at interpreter startup;
# config.update is the reliable pre-backend-init override (see
# tests/conftest.py).
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
import optax
import bluefog_tpu as bf
from jax.sharding import NamedSharding, PartitionSpec as P

bf.init()  # BLUEFOG_COORDINATOR env => jax.distributed.initialize runs HERE
assert jax.process_count() == 2, jax.process_count()
ctx = bf.get_context()
assert bf.size() == 4, bf.size()
# one "machine" per controller process by default
assert ctx.machine_size == 2 and ctx.local_size == 2, (
    ctx.machine_size, ctx.local_size)

SIZE, DIM = 4, 3
c = np.random.RandomState(0).randn(SIZE, DIM).astype(np.float32)
opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.4))
params = {"w": jnp.asarray(c)}  # same value on both controllers
state = opt.init(params)

grad_fn = jax.jit(lambda w, tgt: w - tgt)
mesh = ctx.mesh
loss_fn = jax.jit(
    lambda w, m: 0.5 * jnp.mean(jnp.sum((w - m) ** 2, -1)),
    out_shardings=NamedSharding(mesh, P()),
)
start = float(np.asarray(loss_fn(params["w"], c.mean(0))))
for _ in range(50):
    grads = {"w": grad_fn(params["w"], c)}
    params, state = opt.step(params, state, grads)
    jax.block_until_ready(params["w"])  # CPU Gloo rendezvous: don't queue deep
final = float(np.asarray(loss_fn(params["w"], c.mean(0))))
# CTA gossip with a constant step size keeps a steady-state consensus
# residual; 5x loss reduction proves communication is really averaging
# across the two OS processes (local-only SGD would stay at `start`).
assert final < 0.2 * start, (start, final)

# hierarchical across REAL machine boundaries: machine = controller
# process, intra-machine psum on each host's devices, machine-level
# gossip across the process boundary
import bluefog_tpu.topology as tu
bf.set_machine_topology(tu.RingGraph(2))
hopt = bf.DistributedHierarchicalNeighborAllreduceOptimizer(optax.sgd(0.4))
hparams = {"w": jnp.asarray(c)}
hstate = hopt.init(hparams)
for _ in range(40):
    hgrads = {"w": grad_fn(hparams["w"], c)}
    hparams, hstate = hopt.step(hparams, hstate, hgrads)
    jax.block_until_ready(hparams["w"])
hfinal = float(np.asarray(loss_fn(hparams["w"], c.mean(0))))
assert hfinal < 0.2 * start, (start, hfinal)

# window family across REAL controller processes: push-sum diffusion on a
# directed ring over the global mesh. The window's value/buffer/p lanes
# are worker-stacked arrays sharded across devices owned by BOTH
# processes, so every buffered ppermute exchange crosses the process
# boundary — the one surface the gossip legs above don't touch.
bf.set_topology(tu.RingGraph(SIZE, connect_style=1), is_weighted=True)
wopt = bf.DistributedPushSumOptimizer(
    optax.sgd(optax.exponential_decay(0.4, 20, 0.5))
)
wparams = {"w": jnp.asarray(c)}
wstate = wopt.init(wparams)
cur = wparams
for _ in range(60):
    cur, wstate = wopt.step(wstate, {"w": grad_fn(cur["w"], c)})
    jax.block_until_ready(cur["w"])
wfinal = float(np.asarray(loss_fn(cur["w"], c.mean(0))))
assert wfinal < 0.2 * start, (start, wfinal)
wopt.free()
bf.turn_off_win_ops_with_associated_p()

bf.shutdown()
print("MP_OK", jax.process_index(), start, final, hfinal, wfinal, flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.example
def test_two_controller_processes_end_to_end(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()
    base = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_NUM_WORKERS")
    }
    base["PYTHONPATH"] = REPO + os.pathsep + base.get("PYTHONPATH", "")
    procs = []
    for pid in range(2):
        env = dict(
            base,
            BLUEFOG_COORDINATOR=f"localhost:{port}",
            BLUEFOG_NUM_PROCESSES="2",
            BLUEFOG_PROCESS_ID=str(pid),
            BLUEFOG_NUM_WORKERS="4",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script)],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                env=env,
                cwd=str(tmp_path),
            )
        )
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out, err))
    if any(
        "Multiprocess computations aren't implemented on the CPU backend"
        in err
        for _rc, _out, err in outs
    ):
        # Known environment gap, not a framework regression: this jaxlib
        # build ships no cross-process CPU collective backend (Gloo), so
        # the two-controller global mesh cannot execute any computation.
        # The launcher/env-contract surface is still covered by
        # test_launcher.py; this end-to-end tier needs a jaxlib with CPU
        # collectives (or a real multi-host slice). Tracked in
        # CHANGES.md (PR 3 triage note).
        pytest.skip(
            "jaxlib lacks multiprocess CPU collectives "
            "(XlaRuntimeError: 'Multiprocess computations aren't "
            "implemented on the CPU backend') — environment gap, see "
            "PR 3 triage note in CHANGES.md"
        )
    for rc, out, err in outs:
        assert rc == 0, err[-3000:]
        assert "MP_OK" in out, (out, err[-2000:])
    # Both controllers converged to the same consensus losses (gossip,
    # hierarchical, AND push-sum window legs — the last three tokens).
    finals = {
        tuple(o.split()[-3:])
        for _rc, o, _e in outs
        for o in [o.strip().splitlines()[-1]]
    }
    assert len(finals) == 1, outs
