# Copyright 2026. Licensed under the Apache License, Version 2.0.
"""Elastic gossip: fault injection, liveness, repair, recovery.

The chaos suite runs entirely on the 8-device virtual CPU mesh — every
failure mode is a deterministic replay (:mod:`bluefog_tpu.elastic.faults`),
so rank death is a tier-1 unit test, not a multi-host fire drill.

Oracle notes. The fp32 end-to-end tests pin the device trajectory
BITWISE against a numpy replay: the combine accumulates left-to-right in
round order (verified), and the only backend latitude observed is whether
the SGD apply ``p + (-lr)*g`` is contracted to a single-rounding FMA —
both are legal IEEE evaluations, so the oracle computes both (FMA
emulated exactly via float64) and asserts the device matches one of them
for the WHOLE trajectory. The int8 wire's accumulation is vectorized
with mixed FMA lanes (no single associativity reproduces it), so the
int8 tests pin the quantization math bitwise at the payload level and
the trajectory/consensus to a few-ulp tolerance instead.
"""

import numpy as np
import networkx as nx
import pytest

import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import metrics
from bluefog_tpu import watchdog
from bluefog_tpu import windows as win_mod
from bluefog_tpu.collective import ops as col_ops
from bluefog_tpu.collective.plan import (
    plan_from_topology,
    schedule_from_dynamic,
)
from bluefog_tpu.elastic import (
    Fault,
    FaultPlan,
    Membership,
    RankState,
    parse_fault_plan,
    repair_schedule,
    repaired_matrix,
    survivor_consensus,
)
from bluefog_tpu.elastic import repair as repair_mod
from bluefog_tpu.elastic.recovery import consensus_restore

SIZE = 8


@pytest.fixture(autouse=True)
def _fresh_state():
    metrics.reset()
    yield
    bf.elastic.stop()
    metrics.reset()


def _init(n=SIZE):
    import jax

    bf.init(devices=jax.devices("cpu")[:n])


# -- fault-plan grammar -------------------------------------------------------


def test_fault_plan_grammar_roundtrip():
    plan = parse_fault_plan(
        "kill:rank=3,step=5; stall:rank=2,step=10,seconds=120 ;"
        "degrade:rank=1,step=4,factor=0.25;"
    )
    assert [f.kind for f in plan.faults] == ["degrade", "kill", "stall"]
    kill = plan.due(5)[0]
    assert (kill.rank, kill.step) == (3, 5)
    stall = plan.due(10)[0]
    assert stall.seconds == 120.0
    deg = plan.due(4)[0]
    assert deg.factor == 0.25
    assert parse_fault_plan("") .faults == ()
    assert parse_fault_plan(None).faults == ()


@pytest.mark.parametrize("bad", [
    "explode:rank=1,step=2",        # unknown kind
    "kill:rank=1",                  # missing step
    "kill:step=1",                  # missing rank
    "kill:rank=1,step=2,blast=3",   # unknown field
    "kill:rank=1 step=2",           # not key=value
    "degrade:rank=1,step=2,factor=0",   # factor out of range
    "degrade:rank=1,step=2,factor=1.5",
    "stall:rank=1,step=2,seconds=-1",
    "kill:rank=1,step=-3",
])
def test_fault_plan_grammar_rejects(bad):
    with pytest.raises(ValueError):
        parse_fault_plan(bad)


def test_slow_fault_grammar_roundtrip():
    """The rank-scoped compute-dilation kind: factor >= 1, optional
    steps= duration, no peer/seconds form."""
    plan = parse_fault_plan(
        "slow:rank=5,step=0,factor=10; slow:rank=2,step=4,factor=3,steps=6"
    )
    assert [f.kind for f in plan.faults] == ["slow", "slow"]
    f = plan.due(0)[0]
    assert (f.rank, f.factor, f.hold_steps) == (5, 10.0, 0)
    bounded = plan.due(4)[0]
    assert (bounded.rank, bounded.factor, bounded.hold_steps) == (2, 3.0, 6)
    plan.validate(SIZE)
    # factor defaults to 1.0 — a no-op dilation is legal
    parse_fault_plan("slow:rank=1,step=0")
    with pytest.raises(ValueError, match="9"):
        plan2 = parse_fault_plan("slow:rank=9,step=0,factor=2")
        plan2.validate(SIZE)


@pytest.mark.parametrize("bad", [
    "slow:rank=1,step=0,factor=0.5",       # a slowdown must dilate
    "slow:rank=1,step=0,factor=2,peer=3",  # rank-scoped by definition
    "slow:rank=1,step=0,factor=2,seconds=5",
])
def test_slow_fault_grammar_rejects(bad):
    with pytest.raises(ValueError):
        parse_fault_plan(bad)


def test_simulated_compute_dilation_window():
    """inject() parity + the step-clock activation window: a slow
    fault dilates from its step, expires after steps=, and never
    triggers repair or a death verdict."""
    _init()
    session = bf.elastic.start()
    session.inject("slow", rank=3, step=2, factor=10)
    session.inject("slow", rank=1, step=4, factor=4, steps=3)
    dilations = []
    for step in range(10):
        # the dilation map a dispatch at `step` would see
        dilations.append(dict(session.simulated_compute_dilation()))
        session.before_dispatch(None)  # replay faults, advance clock
    assert dilations[0] == {} and dilations[1] == {}
    assert dilations[2] == {3: 10.0}
    assert dilations[4] == {3: 10.0, 1: 4.0}
    assert dilations[6] == {3: 10.0, 1: 4.0}  # last active step for 1
    assert dilations[7] == {3: 10.0}          # steps=3 expired
    assert session.repairs == []              # never a repair trigger
    assert session.membership.live_ranks() == tuple(range(SIZE))
    assert metrics.snapshot()["bluefog.elastic.slow_faults"]["value"] == 2


def test_fault_plan_env_and_validate(monkeypatch):
    monkeypatch.setenv("BLUEFOG_FAULT_PLAN", "kill:rank=9,step=0")
    plan = FaultPlan.from_env()
    assert len(plan) == 1
    with pytest.raises(ValueError):
        plan.validate(world_size=8)
    plan.validate(world_size=16)


# -- membership ---------------------------------------------------------------


def test_membership_transitions_and_epoch():
    m = Membership(4)
    assert m.live_ranks() == (0, 1, 2, 3)
    e0 = m.epoch
    assert m.mark_suspect(2, "deadline", step=7)
    assert m.state(2) is RankState.SUSPECT
    assert m.is_live(2)  # suspicion does not leave the wire
    assert m.mark_dead(2, "killed", step=8)
    assert not m.mark_dead(2)  # idempotent
    assert m.live_ranks() == (0, 1, 3)
    assert m.dead_ranks() == (2,)
    assert not m.mark_suspect(2)  # dead stays dead
    assert m.revive(2, step=20)
    assert m.live_ranks() == (0, 1, 2, 3)
    assert m.epoch > e0
    # token changes with every transition (cache-key requirement)
    t0 = m.token()
    m.mark_dead(0)
    assert m.token() != t0
    with pytest.raises(ValueError):
        m.mark_dead(17)
    with pytest.raises(ValueError):
        m.mark_degraded(1, 0.0)
    assert m.mark_degraded(1, 0.5)
    assert m.degraded() == {1: 0.5}


# -- repair weight correctness (numpy oracles) --------------------------------

GENERATORS = {
    "ring": lambda n: bf.topology.RingGraph(n),
    "exp2": lambda n: bf.topology.ExponentialTwoGraph(n),
    "mesh": lambda n: bf.topology.MeshGrid2DGraph(n),
    "star": lambda n: bf.topology.StarGraph(n),
}


@pytest.mark.parametrize("name", sorted(GENERATORS))
def test_repair_stays_stochastic_for_every_single_rank_loss(name):
    """Every static generator, every single dead rank, every policy: the
    repaired matrix keeps the stochasticity its family needs (receiver
    sums = row-stochastic in the standard x' = W^T x convention)."""
    w = nx.to_numpy_array(GENERATORS[name](SIZE))
    for dead in range(SIZE):
        live = [r for r in range(SIZE) if r != dead]
        for policy in repair_mod.POLICIES:
            w2 = repaired_matrix(w, live, policy=policy)
            # dead slot frozen: self weight 1, no edges either direction
            assert w2[dead, dead] == 1.0
            assert np.count_nonzero(w2[dead]) == 1
            assert np.count_nonzero(w2[:, dead]) == 1
            if policy in ("average", "receiver"):
                np.testing.assert_allclose(
                    repair_mod.receiver_sums(w2, live), 1.0, atol=1e-12,
                    err_msg=f"{name} dead={dead} {policy}",
                )
            if policy in ("average", "push_sum"):
                np.testing.assert_allclose(
                    repair_mod.sender_sums(w2, live), 1.0, atol=1e-12,
                    err_msg=f"{name} dead={dead} {policy}",
                )
            if policy == "average":
                np.testing.assert_allclose(w2, w2.T, atol=1e-12)


@pytest.mark.parametrize("name", sorted(GENERATORS))
def test_average_repair_fixed_point_is_survivor_mean(name):
    """The doubly-stochastic repair's gossip iteration converges to the
    uniform survivor average — the consensus-preservation oracle."""
    w = nx.to_numpy_array(GENERATORS[name](SIZE))
    rng = np.random.RandomState(0)
    x = rng.randn(SIZE, 3)
    for dead in (0, 3, SIZE - 1):
        live = [r for r in range(SIZE) if r != dead]
        w2 = repaired_matrix(w, live, policy="average")
        y = x.copy()
        for _ in range(300):
            y = w2.T @ y
        target = survivor_consensus(x, live)
        for r in live:
            np.testing.assert_allclose(y[r], target, atol=1e-9)
        # the dead slot never mixes
        np.testing.assert_allclose(y[dead], x[dead])


def test_star_center_death_falls_back_to_connected_graph():
    """Killing the star's center disconnects every survivor; the repair
    engine unions in the survivor ring so gossip still mixes."""
    w = nx.to_numpy_array(bf.topology.StarGraph(SIZE, center_rank=0))
    live = list(range(1, SIZE))
    w2 = repaired_matrix(w, live, policy="average")
    g = nx.from_numpy_array(w2[np.ix_(live, live)])
    assert nx.is_connected(g)
    np.testing.assert_allclose(repair_mod.receiver_sums(w2, live), 1.0)
    np.testing.assert_allclose(repair_mod.sender_sums(w2, live), 1.0)


def test_degrade_scales_edges_and_keeps_stochasticity():
    w = nx.to_numpy_array(bf.topology.RingGraph(SIZE))
    live = list(range(SIZE))
    healthy = repaired_matrix(w, live, policy="average")
    degraded = repaired_matrix(
        w, live, policy="average", degraded={2: 0.25}
    )
    # the slow rank's cross edges shrank by exactly the factor
    for j in (1, 3):  # ring neighbors of 2
        assert degraded[2, j] == pytest.approx(healthy[2, j] * 0.25)
        assert degraded[j, 2] == pytest.approx(healthy[j, 2] * 0.25)
    np.testing.assert_allclose(repair_mod.receiver_sums(degraded, live), 1.0)
    np.testing.assert_allclose(repair_mod.sender_sums(degraded, live), 1.0)
    np.testing.assert_allclose(degraded, degraded.T)


def test_repair_rejects_bad_inputs():
    w = nx.to_numpy_array(bf.topology.RingGraph(4))
    with pytest.raises(ValueError):
        repaired_matrix(w, [], policy="average")
    with pytest.raises(ValueError):
        repaired_matrix(w, [0, 9], policy="average")
    with pytest.raises(ValueError):
        repaired_matrix(w, [0, 1], policy="nonsense")
    # lone survivor: identity on its slot
    w2 = repaired_matrix(w, [2], policy="average")
    assert w2[2, 2] == 1.0


# -- dynamic one-peer schedules skip dead peers -------------------------------


def test_dynamic_schedule_repair_preserves_period_and_skips_dead():
    topo = bf.topology.ExponentialTwoGraph(SIZE)
    sched = schedule_from_dynamic(
        SIZE,
        lambda r: bf.topology.GetDynamicOnePeerSendRecvRanks(topo, r),
    )
    assert sched.period == 3  # log2(8) one-peer rounds
    dead = 5
    live = [r for r in range(SIZE) if r != dead]
    rep = repair_schedule(sched, live, policy="receiver")
    # the period is preserved — skipping a dead peer must not break the
    # period detection the compiled lax.switch relies on
    assert rep.period == sched.period
    for p in rep.plans:
        edges = [(s, d) for rnd in p.rounds for (s, d) in rnd.perm]
        assert all(dead not in e for e in edges), edges
        np.testing.assert_allclose(
            repair_mod.receiver_sums(p.weight_matrix(), live), 1.0,
            atol=1e-12,
        )
    # ranks whose peer-of-the-round died now gossip with themselves that
    # round (weight 1 on self), other rounds unchanged in structure
    for p_old, p_new in zip(sched.plans, rep.plans):
        old_edges = {
            (s, d)
            for rnd in p_old.rounds for (s, d) in rnd.perm
            if dead not in (s, d)
        }
        new_edges = {
            (s, d) for rnd in p_new.rounds for (s, d) in rnd.perm
        }
        assert new_edges == old_edges


# -- live-set-aware plan cache ------------------------------------------------


def test_static_plan_cache_key_includes_live_set():
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    ctx = bf.get_context()
    assert ctx.live_token() is None  # no session: everyone lives
    p1 = col_ops._static_plan(ctx)
    assert col_ops._static_plan(ctx) is p1  # cached
    session = bf.elastic.start()
    tok = ctx.live_token()
    assert tok == (0, tuple(range(SIZE)))
    p2 = col_ops._static_plan(ctx)
    assert p2 is not p1  # token changed None -> epoch 0
    # a membership transition ALONE (no set_topology) must invalidate
    session.membership.mark_dead(3, "test")
    assert ctx.live_token() != tok
    p3 = col_ops._static_plan(ctx)
    assert p3 is not p2
    bf.elastic.stop()


# -- session mechanics --------------------------------------------------------


def test_session_exclusive_and_inject_validation():
    _init()
    session = bf.elastic.start()
    with pytest.raises(RuntimeError):
        bf.elastic.start()
    with pytest.raises(ValueError):
        session.inject("kill", rank=99, step=0)
    with pytest.raises(ValueError):
        bf.elastic.inject("explode", rank=0, step=0)
    bf.elastic.stop()
    bf.elastic.stop()  # idempotent
    with pytest.raises(RuntimeError):
        bf.elastic.inject("kill", rank=0, step=0)
    with pytest.raises(RuntimeError):
        bf.elastic.guard(object())


def test_transient_stall_does_not_repair_but_deadline_stall_does():
    _init()
    bf.set_topology(bf.topology.RingGraph(SIZE))
    session = bf.elastic.start(liveness_timeout_s=60.0)
    session.inject("stall", rank=1, step=0, seconds=5)  # transient
    session.inject("stall", rank=2, step=2, seconds=60)  # past deadline
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    session.before_dispatch(opt)
    assert session.repairs == [] and session.membership.dead_ranks() == ()
    assert metrics.snapshot()["bluefog.elastic.stalls"]["value"] == 1
    session.before_dispatch(opt)
    session.before_dispatch(opt)  # step 2: condemned + repaired
    assert session.membership.dead_ranks() == (2,)
    assert len(session.repairs) == 1
    reason = session.membership.reason(2)[0]
    assert "stalled" in reason and "deadline" in reason


def test_watchdog_stall_files_suspects():
    """A real blocking wait past the liveness deadline files SUSPECT
    verdicts for the ranks of the last dispatched plan — the
    watchdog-integrated detection path."""
    import time

    _init()
    session = bf.elastic.start(liveness_timeout_s=0.3)
    old = watchdog.stall_timeout()
    watchdog.set_stall_timeout(0.3)
    try:
        with watchdog.watch("combine dispatch (test)"):
            time.sleep(1.2)  # monitor polls every ~75 ms at this limit
    finally:
        watchdog.set_stall_timeout(old)
    assert all(
        session.membership.state(r) is RankState.SUSPECT
        for r in range(SIZE)
    )
    assert metrics.snapshot()["bluefog.elastic.suspects"]["value"] == SIZE
    # suspicion never removes a rank from the wire by itself
    assert session.membership.live_ranks() == tuple(range(SIZE))


# -- end-to-end chaos: kill mid-training, bitwise fp32 oracle -----------------


def _np_combine(v, plan):
    """Numpy replay of weighted_combine_operands: left-to-right in round
    order (bitwise on the CPU backend, verified)."""
    self_w, recv_w = plan.weight_operands()
    y = v * self_w[:, None]
    for r, rnd in enumerate(plan.rounds):
        recv = np.zeros_like(v)
        for s, d in rnd.perm:
            recv[d] = v[s]
        y = y + recv * recv_w[r][:, None]
    return y


def _np_fma(a, b, c):
    """Exact float32 FMA via float64 (f32 products are exact in f64)."""
    return np.float32(np.float64(a) * np.float64(b) + np.float64(c))


def _np_sgd_apply(p, g, lr, fma):
    return _np_fma(g, -lr, p) if fma else p + (-lr) * g


def _chaos_run(order, kill_rank=3, kill_step=5, steps=24, lr=0.05,
               compression=None):
    """Run the 8-worker chaos scenario on device; return everything the
    oracles need."""
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    ctx = bf.get_context()
    base_plan = col_ops._static_plan(ctx)

    session = bf.elastic.start(policy="average")
    session.inject("kill", rank=kill_rank, step=kill_step)
    factory = (
        bf.DistributedAdaptThenCombineOptimizer if order == "atc"
        else bf.DistributedAdaptWithCombineOptimizer
    )
    opt = factory(optax.sgd(lr))
    if compression:
        opt.compression = compression
    guard = bf.elastic.guard(opt)

    rng = np.random.RandomState(42)
    x0 = rng.randn(SIZE, 1536).astype(np.float32)
    grads = [
        rng.randn(SIZE, 1536).astype(np.float32) for _ in range(steps)
    ]
    params = {"w": bf.worker_values(lambda r: x0[r])}
    state = opt.init(params)
    trajectory = []
    for t in range(steps):
        params, state = guard.step(
            params, state, {"w": bf.worker_values(lambda r: grads[t][r])}
        )
        trajectory.append(np.asarray(params["w"]))

    live = session.membership.live_ranks()
    repaired_plan = col_ops._static_plan(ctx)
    assert repaired_plan is not base_plan
    result = dict(
        session=session, x0=x0, grads=grads, trajectory=trajectory,
        live=live, base_plan=base_plan, repaired_plan=repaired_plan,
        lr=np.float32(lr), kill_step=kill_step, kill_rank=kill_rank,
    )
    return result


def _np_replay(run, order, fma):
    """Full-trajectory numpy replay, switching plans at the repair step
    exactly where the guard did."""
    x = run["x0"].copy()
    out = []
    for t, g in enumerate(run["grads"]):
        plan = (
            run["base_plan"] if t < run["kill_step"]
            else run["repaired_plan"]
        )
        if order == "atc":
            x = _np_combine(_np_sgd_apply(x, g, run["lr"], fma), plan)
        else:  # cta
            x = _np_sgd_apply(_np_combine(x, plan), g, run["lr"], fma)
        out.append(x.copy())
    return out


@pytest.mark.chaos
@pytest.mark.parametrize("order", ["atc", "cta"])
def test_chaos_kill_fp32_bitwise_survivor_oracle(order):
    """8-worker mesh, rank killed mid-training: detected at its first
    would-be dispatch, repaired before it. Oracle pins, strongest that
    each phase admits:

    - pre-repair trajectory BITWISE vs the numpy replay (the 3-round
      Exp2 combine is a serial chain XLA evaluates left-to-right; the
      SGD apply's legal FMA contraction is calibrated, both variants
      computed);
    - the whole run — kill, detection, repair — BITWISE reproducible
      across two independent sessions (fresh context, fresh compiles):
      the determinism contract the chaos harness exists for;
    - post-repair trajectory within a few-ulp envelope of the replay
      (the repaired 5-round combine is reassociated by XLA's
      vectorizer, so per-element order is not replayable), and the
      survivors' consensus matches the numpy survivor oracle."""
    run = _chaos_run(order)
    session = run["session"]
    assert [r.detected for r in session.repairs] == [(run["kill_rank"],)]
    rec = session.repairs[0]
    assert rec.step == run["kill_step"]
    assert rec.steps_to_detect == {run["kill_rank"]: 0}
    assert rec.steps_to_repair == 0
    assert session.stale_dispatches == 0
    assert run["live"] == tuple(
        r for r in range(SIZE) if r != run["kill_rank"]
    )

    # 1. pre-repair phase: bitwise vs numpy (FMA-calibrated apply)
    matched = None
    for fma in (True, False):
        oracle = _np_replay(run, order, fma)
        if all(
            np.array_equal(d, o)
            for d, o in zip(
                run["trajectory"][: run["kill_step"]],
                oracle[: run["kill_step"]],
            )
        ):
            matched = fma
            break
    assert matched is not None, (
        "pre-repair device trajectory matches neither FMA nor "
        "plain-apply numpy oracle bitwise"
    )

    # 2. full-run trajectory stays in a tight envelope of the oracle
    # (reassociation of the repaired combine costs ~1 ulp per step and
    # gossip is non-expanding, so the envelope stays ulp-scale)
    oracle = _np_replay(run, order, matched)
    for t, (d, o) in enumerate(zip(run["trajectory"], oracle)):
        np.testing.assert_allclose(
            d, o, atol=1e-5, rtol=0,
            err_msg=f"step {t} left the oracle envelope",
        )

    # 3. survivor consensus: mean matches the oracle's survivor mean
    final = run["trajectory"][-1]
    live = list(run["live"])
    np.testing.assert_allclose(
        final[live].mean(axis=0),
        survivor_consensus(oracle[-1], live),
        atol=1e-5,
    )
    # the dead slot froze out of the mixing at the repair: from there it
    # only took local sgd steps (its combine is self-weight 1 plus
    # zero-weighted rounds), which ARE bitwise-replayable
    dead = run["kill_rank"]
    x = run["trajectory"][run["kill_step"] - 1][dead]
    for t in range(run["kill_step"], len(run["grads"])):
        x = _np_sgd_apply(x, run["grads"][t][dead], run["lr"], matched)
    np.testing.assert_array_equal(final[dead], x)

    # metrics wiring (before the rerun adds its own repair)
    snap = metrics.snapshot()
    assert snap["bluefog.elastic.repairs"]["value"] == 1
    assert snap["bluefog.elastic.dead_ranks"]["value"] == 1

    # 4. the whole chaos run is bitwise reproducible end to end
    rerun = _chaos_run(order)
    assert all(
        np.array_equal(a, b)
        for a, b in zip(run["trajectory"], rerun["trajectory"])
    ), "chaos replay is not deterministic"
    assert rerun["session"].repairs[0].detected == rec.detected


@pytest.mark.chaos
@pytest.mark.parametrize("order", ["atc", "cta"])
def test_chaos_kill_int8_converges_to_survivor_consensus(order):
    """Same scenario over the int8 difference-form wire. The int8
    accumulation is vectorized with mixed FMA lanes (no single numpy
    associativity is bitwise — see module docstring), so this pins the
    trajectory to a few-ulp envelope of the fp32 oracle plus the
    convergence contract: after the gradient phase ends the survivors
    contract to a consensus within quantization noise of the survivor
    average."""
    kill_step, grad_steps, steps = 5, 10, 80
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    session = bf.elastic.start(policy="average")
    session.inject("kill", rank=3, step=kill_step)
    factory = (
        bf.DistributedAdaptThenCombineOptimizer if order == "atc"
        else bf.DistributedAdaptWithCombineOptimizer
    )
    opt = factory(optax.sgd(0.05))
    opt.compression = "int8"
    guard = bf.elastic.guard(opt)

    rng = np.random.RandomState(7)
    x0 = rng.randn(SIZE, 1536).astype(np.float32)
    zeros = np.zeros((SIZE, 1536), np.float32)
    params = {"w": bf.worker_values(lambda r: x0[r])}
    state = opt.init(params)
    at_repair = None
    for t in range(steps):
        g = (
            rng.randn(SIZE, 1536).astype(np.float32) * 0.1
            if t < grad_steps else zeros
        )
        if t == kill_step:
            at_repair = np.asarray(params["w"])
        params, state = guard.step(
            params, state, {"w": bf.worker_values(lambda r: g[r])}
        )

    assert session.stale_dispatches == 0
    assert len(session.repairs) == 1
    live = list(session.membership.live_ranks())
    assert 3 not in live

    final = np.asarray(params["w"])
    at_repair_spread = np.abs(
        at_repair[live] - at_repair[live].mean(axis=0)
    ).max()
    spread = np.abs(final[live] - final[live].mean(axis=0)).max()
    # plain int8 (no error feedback) has a quantization noise floor: the
    # wire payload is the raw iterate, so chunk scales stay ~max|x|/127
    # ≈ 0.03 and the spread stalls there instead of contracting to zero
    # (inner.py's CHOCO docstring). Pin: hard contraction from the
    # at-repair spread down to the floor.
    assert spread < 0.05, spread
    assert spread < at_repair_spread / 5, (spread, at_repair_spread)
    # consensus value: the survivor mean is invariant under the doubly
    # stochastic combine (symmetric weights make the difference-form
    # cross terms cancel in the mean), so the target is the survivor
    # mean at repair plus the post-repair gradient drift — replay that
    # one-line recursion exactly
    mean = survivor_consensus(at_repair, live)
    rng2 = np.random.RandomState(7)
    _ = rng2.randn(SIZE, 1536)  # x0 draw
    g_seq = [
        rng2.randn(SIZE, 1536).astype(np.float32) * 0.1
        for _ in range(grad_steps)
    ]
    for t in range(kill_step, grad_steps):
        mean = mean - 0.05 * g_seq[t][live].mean(axis=0)
    np.testing.assert_allclose(
        final[live].mean(axis=0), mean, atol=2e-2
    )
    snap = metrics.snapshot()
    assert snap["bluefog.elastic.repairs"]["value"] == 1


# -- push-sum mass correction -------------------------------------------------


@pytest.mark.chaos
@pytest.mark.parametrize("order", ["atc"])
def test_chaos_kill_chunked_plan_repairs_zero_stale(order, monkeypatch):
    """Elastic repair of a CHUNKED plan: with BLUEFOG_PLAN_CHUNKS set,
    the kill -> detect -> repair path recompiles the chunked lowering
    under the live-token cache key with zero stale dispatches, and the
    whole trajectory (through the repair) is bitwise the unchunked
    run's — chunking is a schedule change even across a membership
    change."""
    def run(chunks):
        monkeypatch.setenv("BLUEFOG_PLAN_CHUNKS", str(chunks))
        try:
            _init()
            bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
            session = bf.elastic.start(policy="average")
            session.inject("kill", rank=3, step=4)
            opt = bf.DistributedAdaptThenCombineOptimizer(
                optax.sgd(0.05)
            )
            guard = bf.elastic.guard(opt)
            rng = np.random.RandomState(7)
            x0 = rng.randn(SIZE, 1536).astype(np.float32)
            params = {"w": bf.worker_values(lambda r: x0[r])}
            state = opt.init(params)
            traj = []
            for t in range(10):
                g = rng.randn(SIZE, 1536).astype(np.float32) * 0.1
                params, state = guard.step(
                    params, state, {"w": bf.worker_values(lambda r: g[r])}
                )
                traj.append(np.asarray(params["w"]).copy())
            assert session.stale_dispatches == 0
            assert len(session.repairs) == 1
            assert 3 not in session.membership.live_ranks()
            # the repaired static plan sits under a live-token key
            ctx = bf.context.get_context()
            live_keyed = [
                k for k in ctx.op_cache
                if k and k[0] == "static_plan" and k[-1] is not None
            ]
            assert live_keyed, "repaired plan not keyed by live token"
            return traj
        finally:
            bf.elastic.stop()
            bf.shutdown()

    t2 = run(2)
    t1 = run(1)
    for a, b in zip(t1, t2):
        np.testing.assert_array_equal(a, b)


@pytest.mark.chaos
def test_chaos_pushsum_mass_corrected_consensus():
    """Push-sum family: kill a rank mid-run; the repaired split is
    mass-conserving over survivors, so x-lane and p-lane totals are
    invariant from the repair on, and every survivor's corrected iterate
    x/p converges to sum(x_live)/sum(p_live) at repair — the push-sum
    mass-corrected survivor consensus."""
    kill_step, steps = 4, 60
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    session = bf.elastic.start()
    session.inject("kill", rank=2, step=kill_step)
    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.0))
    guard = bf.elastic.guard(opt)

    rng = np.random.RandomState(3)
    x0 = rng.randn(SIZE, 64).astype(np.float32)
    params = {"w": bf.worker_values(lambda r: x0[r])}
    state = opt.init(params)
    grads = {"w": jnp.zeros_like(params["w"])}
    ctx = bf.get_context()
    totals = []
    at_repair = None
    for t in range(steps):
        if t == kill_step:
            win = win_mod._get_win(ctx, opt._name)
            at_repair = (
                np.asarray(win.value).copy(), np.asarray(win.p).copy()
            )
        _, state = guard.step(state, grads)
        if t >= kill_step:
            win = win_mod._get_win(ctx, opt._name)
            live = list(session.membership.live_ranks())
            totals.append((
                np.asarray(win.value)[live].sum(axis=0),
                np.asarray(win.p)[live].sum(),
            ))

    assert session.repairs and session.repairs[0].policy == "push_sum"
    assert session.stale_dispatches == 0
    live = list(session.membership.live_ranks())
    assert live == [r for r in range(SIZE) if r != 2]

    # mass conservation from the repair on (x-lane and p-lane totals)
    x_tot0, p_tot0 = totals[0]
    for x_tot, p_tot in totals[1:]:
        np.testing.assert_allclose(x_tot, x_tot0, rtol=1e-5, atol=1e-4)
        np.testing.assert_allclose(p_tot, p_tot0, rtol=1e-6)

    # corrected iterates converge to the mass-corrected consensus
    x_live, p_live = at_repair[0][live], at_repair[1][live]
    target = x_live.sum(axis=0) / p_live.sum()
    est = np.asarray(guard.optimizer.params()["w"])
    for r in live:
        np.testing.assert_allclose(est[r], target, atol=1e-4)


# -- fused train step under the guard ----------------------------------------


@pytest.mark.chaos
def test_chaos_fused_train_step_repairs():
    """The overlap-layer fused train step runs the same liveness + repair
    path: kill mid-training, repair, survivors keep converging."""
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    session = bf.elastic.start()
    session.inject("kill", rank=6, step=3)
    opt = bf.DistributedAdaptThenCombineOptimizer(optax.sgd(0.1))
    guard = bf.elastic.guard(opt)

    rng = np.random.RandomState(11)
    x0 = rng.randn(SIZE, 32).astype(np.float32)
    target = rng.randn(32).astype(np.float32)
    params = {"w": bf.worker_values(lambda r: x0[r])}
    state = opt.init(params)
    batch = bf.worker_values(np.broadcast_to(target, (SIZE, 32)))

    def loss_fn(p, y):
        return jnp.sum((p["w"] - y) ** 2)

    train_step = guard.make_train_step(loss_fn)
    losses = []
    for _ in range(30):
        params, state, loss = train_step(params, state, batch)
        losses.append(np.asarray(loss))

    assert len(session.repairs) == 1
    assert session.repairs[0].detected == (6,)
    assert session.stale_dispatches == 0
    live = list(session.membership.live_ranks())
    final = np.asarray(params["w"])
    # the quadratic pulls every survivor to the shared target
    np.testing.assert_allclose(
        final[live], np.tile(target, (len(live), 1)), atol=1e-2
    )


# -- rejoin + consensus restore ----------------------------------------------


def test_consensus_restore_pure():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    tree = {"w": jnp.asarray(x)}
    out = consensus_restore(tree, rank=1, live=(0, 2, 3))
    got = np.asarray(out["w"])
    np.testing.assert_allclose(got[1], x[[0, 2, 3]].mean(axis=0))
    np.testing.assert_array_equal(got[[0, 2, 3]], x[[0, 2, 3]])
    with pytest.raises(ValueError):
        consensus_restore(tree, rank=1, live=(1,))


@pytest.mark.chaos
def test_rejoin_restores_edges_and_consensus():
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    ctx = bf.get_context()
    session = bf.elastic.start()
    session.inject("kill", rank=4, step=2)
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    guard = bf.elastic.guard(opt)
    rng = np.random.RandomState(5)
    x0 = rng.randn(SIZE, 16).astype(np.float32)
    params = {"w": bf.worker_values(lambda r: x0[r])}
    state = opt.init(params)
    grads = {"w": jnp.zeros_like(params["w"])}
    for _ in range(6):
        params, state = guard.step(params, state, grads)
    assert session.membership.dead_ranks() == (4,)

    params = session.rejoin(4, params=params, optimizer=opt)
    assert session.membership.dead_ranks() == ()
    # topology references the rejoined rank again
    topo = ctx.load_topology()
    assert any(4 in e for e in topo.edges() if e[0] != e[1])
    # its slot was restored to the survivors' consensus
    got = np.asarray(params["w"])
    survivors = [r for r in range(SIZE) if r != 4]
    np.testing.assert_allclose(
        got[4],
        np.mean(got[survivors].astype(np.float32), axis=0),
        atol=1e-6,
    )
    # and training proceeds with everyone back on the wire
    for _ in range(3):
        params, state = guard.step(params, state, grads)
    assert session.stale_dispatches == 0
    snap = metrics.snapshot()
    assert snap["bluefog.elastic.rejoins"]["value"] == 1


@pytest.mark.chaos
def test_pushsum_rejoin_reinstalls_sender_weights():
    """Rejoin must re-point the push-sum sender mass split at the full
    live set — stale pruned dst_weights would silently keep the rejoined
    rank off the wire forever."""
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    session = bf.elastic.start()
    session.inject("kill", rank=2, step=1)
    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.0))
    guard = bf.elastic.guard(opt)
    params = {"w": bf.worker_values(
        lambda r: np.full((8,), float(r), np.float32)
    )}
    state = opt.init(params)
    grads = {"w": jnp.zeros((SIZE, 8), jnp.float32)}
    for _ in range(3):
        _, state = guard.step(state, grads)
    # post-repair: no sender routes mass to the dead rank
    assert all(2 not in d for d in opt.dst_weights)

    session.rejoin(2, optimizer=opt)
    # rank 2's in-edges are back in the installed sender split
    assert any(2 in d for d in opt.dst_weights), opt.dst_weights
    for _ in range(3):
        _, state = guard.step(state, grads)
    assert session.stale_dispatches == 0
    # mass flows again: rank 2's p-lane departs from its frozen value
    est = np.asarray(opt.params()["w"])
    live = list(session.membership.live_ranks())
    assert len(live) == SIZE


@pytest.mark.chaos
def test_winput_repair_prunes_put_wire():
    """The put diffusion family: repair must prune the EXCHANGE wire
    (dst_weights default to create-time out-neighbors and would keep
    shipping to the dead rank) and use the receiver policy (no added
    edges — window buffers only exist for create-time neighbors)."""
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    session = bf.elastic.start()
    session.inject("kill", rank=3, step=2)
    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.0))
    guard = bf.elastic.guard(opt)
    x0 = np.random.RandomState(1).randn(SIZE, 8).astype(np.float32)
    params = {"w": bf.worker_values(lambda r: x0[r])}
    state = opt.init(params)
    grads = {"w": jnp.zeros((SIZE, 8), jnp.float32)}
    for _ in range(6):
        _, state = guard.step(state, grads)
    assert session.repairs and session.repairs[0].policy == "receiver"
    assert session.stale_dispatches == 0
    # no sender pushes to the dead rank anymore
    assert opt.dst_weights is not None
    assert all(3 not in d for d in opt.dst_weights), opt.dst_weights
    opt.free()


@pytest.mark.chaos
def test_user_set_topology_mid_session_becomes_repair_base():
    """A user-installed topology after bf.elastic.start() must become
    the base later repairs restrict — not be silently reverted to the
    session-start graph."""
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    session = bf.elastic.start()
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    guard = bf.elastic.guard(opt)
    params = {"w": bf.worker_values(
        lambda r: np.full(4, float(r), np.float32)
    )}
    state = opt.init(params)
    grads = {"w": jnp.zeros((SIZE, 4), jnp.float32)}
    _, state = guard.step(params, state, grads)

    bf.set_topology(bf.topology.RingGraph(SIZE))  # the user's new base
    session.inject("kill", rank=4, step=session.step)
    for _ in range(2):
        params, state = guard.step(params, state, grads)
    # the repaired graph derives from the RING: Exp2-only offset-2 jumps
    # like (0, 2) and (1, 3) must not reappear
    topo = bf.get_context().load_topology()
    live_edges = {
        tuple(sorted(e)) for e in topo.edges() if e[0] != e[1]
    }
    assert not (live_edges & {(0, 2), (1, 3)}), live_edges


@pytest.mark.chaos
def test_simultaneous_kills_all_detected_in_one_repair():
    """Two ranks killed at the same step: the repair prunes both and
    records BOTH detections — neither is stranded unrepaired after its
    edges are gone."""
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    session = bf.elastic.start()
    session.inject("kill", rank=2, step=3)
    session.inject("kill", rank=6, step=3)
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.0))
    guard = bf.elastic.guard(opt)
    params = {"w": bf.worker_values(
        lambda r: np.full(4, float(r), np.float32)
    )}
    state = opt.init(params)
    grads = {"w": jnp.zeros((SIZE, 4), jnp.float32)}
    for _ in range(6):
        params, state = guard.step(params, state, grads)
    assert len(session.repairs) == 1
    assert session.repairs[0].detected == (2, 6)
    assert session.repairs[0].steps_to_detect == {2: 0, 6: 0}
    assert session._unrepaired == {}
    assert session.stale_dispatches == 0


@pytest.mark.chaos
@pytest.mark.parametrize("wire", ["int8_ef", "int4_ef"])
def test_chaos_kill_ef_residuals_self_invalidate(wire):
    """Repair under an active error-feedback session (int8_ef AND the
    int4_ef tier): the CHOCO copies integrate a fixed per-round source,
    so the membership change must zero-rebuild them — stale copies
    integrated under the pre-failure edge set would desynchronize the
    bit-identical sender/receiver replicas. After the rebuild the EF
    recursion re-converges: survivors reach a consensus far below the
    memoryless tier's quantization floor."""
    _init()
    bf.set_topology(bf.topology.ExponentialTwoGraph(SIZE))
    session = bf.elastic.start(policy="average")
    session.inject("kill", rank=3, step=5)
    opt = bf.DistributedAdaptWithCombineOptimizer(optax.sgd(0.0))
    opt.compression = wire
    guard = bf.elastic.guard(opt)
    rng = np.random.RandomState(17)
    x0 = rng.randn(SIZE, 1024).astype(np.float32) * 4.0
    params = {"w": bf.worker_values(lambda r: x0[r])}
    state = opt.init(params)
    zero = {"w": bf.worker_values(lambda r: np.zeros(1024, np.float32))}
    ef_sig_pre = ef_pre = None
    for t in range(60):
        params, state = guard.step(params, state, zero)
        if t == 4:  # one step before the kill lands
            ef_sig_pre = opt._ef_sig
            ef_pre = opt._ef
    assert ef_sig_pre is not None
    # the repaired plan's perms differ -> the EF signature changed and
    # the copies were rebuilt (zeroed), not carried across the repair
    assert opt._ef_sig != ef_sig_pre
    assert opt._ef is not ef_pre
    live = sorted(session.membership.live_ranks())
    assert 3 not in live
    w = np.asarray(params["w"])[live]
    assert np.abs(w - w.mean(0)).max() < 1e-2, wire
    bf.elastic.stop()
